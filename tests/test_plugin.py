"""Override/tagging framework + differential tests through the DataFrame API.

Reference analog: the CPU-vs-GPU suites (HashAggregatesSuite,
StringFallbackSuite, explain-report behavior) of SURVEY.md §4 tier 3.
"""
import math

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import schema_of
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.expressions import col, lit
from spark_rapids_tpu.sql import TpuSession

from harness import assert_fallback, assert_tpu_and_cpu_equal

SCHEMA = schema_of(k=T.INT, a=T.LONG, b=T.DOUBLE, s=T.STRING)

# floating-point aggregation is CPU-only by default (reference parity);
# differential tests opt in and compare approximately
FLOAT_AGG_CONF = {"spark.rapids.tpu.sql.variableFloatAgg.enabled": True}


def _data(n=500):
    return {
        "k": [i % 5 if i % 13 else None for i in range(n)],
        "a": [i * 3 - n for i in range(n)],
        "b": [
            None if i % 17 == 0 else (float("nan") if i % 19 == 0 else i / 7.0)
            for i in range(n)
        ],
        "s": [None if i % 23 == 0 else f"s{i % 11}" for i in range(n)],
    }


def make_df(sess, n=500, parts=2):
    return sess.create_dataframe(_data(n), SCHEMA, num_partitions=parts)


class TestDifferential:
    def test_project_arithmetic(self):
        assert_tpu_and_cpu_equal(
            lambda s: make_df(s).select(
                col("k"),
                E.Alias(E.Add(col("a"), lit(7)), "a7"),
                E.Alias(E.Multiply(col("a"), col("k")), "ak"),
                E.Alias(E.Divide(col("b"), lit(2.0)), "b2"),
            )
        )

    def test_filter_predicates(self):
        assert_tpu_and_cpu_equal(
            lambda s: make_df(s).where(
                E.And(
                    E.GreaterThan(col("a"), lit(0)),
                    E.Or(E.IsNull(col("b")), E.LessThan(col("b"), lit(30.0))),
                )
            )
        )

    def test_grouped_aggregate(self):
        assert_tpu_and_cpu_equal(
            lambda s: make_df(s).group_by("k").agg(
                A.agg(A.Sum(col("a")), "sa"),
                A.agg(A.Count(col("b")), "cb"),
                A.agg(A.Count(), "n"),
                A.agg(A.Min(col("a")), "mn"),
                A.agg(A.Max(col("b")), "mx"),
            ),
            approx_float=True,
        )

    def test_grand_aggregate(self):
        assert_tpu_and_cpu_equal(
            lambda s: make_df(s).agg(
                A.agg(A.Average(col("b")), "avg"),
                A.agg(A.Count(), "n"),
            ),
            conf=FLOAT_AGG_CONF,
            approx_float=True,
        )

    def test_case_when_cast(self):
        assert_tpu_and_cpu_equal(
            lambda s: make_df(s).select(
                E.Alias(
                    E.CaseWhen(
                        (
                            (E.LessThan(col("a"), lit(0)), lit(-1)),
                            (E.GreaterThan(col("a"), lit(100)), lit(1)),
                        ),
                        lit(0),
                    ),
                    "sign_bucket",
                ),
                E.Alias(E.Cast(col("a"), T.INT), "a_int"),
                E.Alias(E.Cast(col("b"), T.LONG), "b_long"),
            )
        )

    def test_filter_project_aggregate_pipeline(self):
        def build(s):
            return (
                make_df(s, n=997, parts=3)
                .where(E.IsNotNull(col("k")))
                .select(col("k"), E.Alias(E.Multiply(col("a"), lit(2)), "a2"), col("b"))
                .group_by("k")
                .agg(A.agg(A.Sum(col("a2")), "s"), A.agg(A.Average(col("b")), "m"))
            )

        assert_tpu_and_cpu_equal(build, conf=FLOAT_AGG_CONF, approx_float=True)

    def test_filter_string_key_aggregate_pipeline(self):
        # regression: the sort-groupby path (string keys) mislabeled row
        # liveness when the fused filter produced a non-prefix mask,
        # dropping a row and emitting a phantom null-key group
        def build(s):
            return (
                make_df(s, n=503, parts=2)
                .where(E.IsNotNull(col("k")))
                .group_by("s")
                .agg(A.agg(A.Count(None), "n"), A.agg(A.Sum(col("a")), "sa"))
            )

        assert_tpu_and_cpu_equal(build)

    def test_union_limit(self):
        def build(s):
            d = make_df(s, n=50, parts=1)
            return d.union(d).limit(60)

        # limit over union: per-partition limits differ between engines in
        # which rows survive, so only check count via ordered-insensitive
        # compare on a deterministic subset: use where to make it exact
        assert_tpu_and_cpu_equal(
            lambda s: make_df(s, 50, 1).union(make_df(s, 50, 1)))

    def test_range(self):
        assert_tpu_and_cpu_equal(lambda s: s.range(1000, num_slices=3))

    def test_distinct(self):
        assert_tpu_and_cpu_equal(
            lambda s: make_df(s).select(col("k")).distinct())

    def test_nan_grouping_keys(self):
        sch = schema_of(f=T.DOUBLE, v=T.INT)
        data = {
            "f": [1.0, float("nan"), float("nan"), None, -0.0, 0.0],
            "v": [1, 2, 3, 4, 5, 6],
        }

        def build(s):
            return s.create_dataframe(data, sch).group_by("f").agg(
                A.agg(A.Sum(col("v")), "sv"))

        assert_tpu_and_cpu_equal(build)

    def test_in_and_coalesce(self):
        assert_tpu_and_cpu_equal(
            lambda s: make_df(s).select(
                E.Alias(E.In(col("k"), (1, 3, None)), "k_in"),
                E.Alias(E.Coalesce((col("b"), E.Cast(col("a"), T.DOUBLE))), "c"),
            )
        )


class TestFallback:
    def test_float_agg_falls_back_by_default(self):
        # reference parity: float sum/avg stay on CPU unless variableFloatAgg
        assert_fallback(
            lambda s: make_df(s).group_by("k").agg(A.agg(A.Sum(col("b")), "sb")),
            "CpuHashAggregateExec",
        )

    def test_left_join_with_condition_falls_back(self):
        def build(s):
            left = make_df(s, 40, 1).select(col("k"), col("a"))
            right = make_df(s, 30, 1).select(
                E.Alias(col("k"), "k2"), E.Alias(col("b"), "b2"))
            return left.join(
                right, on=[("k", "k2")], how="left",
                condition=E.GreaterThan(col("b2"), lit(1.0)))

        assert_fallback(build, "CpuJoinExec")

    def test_string_agg_input_falls_back(self):
        # min/max over strings now run on TPU (rank-based kernels); the
        # remaining string-input aggregates (first/last) still fall back
        assert_fallback(
            lambda s: make_df(s).group_by("k").agg(A.agg(A.First(col("s")), "fs")),
            "CpuHashAggregateExec",
        )

    def test_string_minmax_agg_runs_on_tpu(self):
        # VERDICT #4: TPC-DS min/max over char columns — lexicographic
        # min/max lowers via the rank kernels, diffed vs the CPU oracle
        assert_tpu_and_cpu_equal(
            lambda s: make_df(s).group_by("k").agg(
                A.agg(A.Min(col("s")), "mn"),
                A.agg(A.Max(col("s")), "mx"),
                A.agg(A.Count(), "n"),
            )
        )

    def test_test_mode_raises_on_fallback(self):
        sess = TpuSession({
            "spark.rapids.tpu.sql.enabled": True,
            "spark.rapids.tpu.sql.test.enabled": True,
        })
        df = make_df(sess).group_by("k").agg(A.agg(A.First(col("s")), "fs"))
        with pytest.raises(AssertionError, match="not columnar"):
            df.collect()

    def test_plugin_disabled_runs_cpu(self):
        sess = TpuSession({"spark.rapids.tpu.sql.enabled": False})
        df = make_df(sess, 20, 1).select(col("a"))
        assert len(df.collect()) == 20
        from spark_rapids_tpu.cpu.plan import CpuExec

        assert isinstance(sess.last_executed_plan, CpuExec)


class TestExplain:
    def test_explain_marks_tpu_and_cpu(self):
        sess = TpuSession()
        df = make_df(sess).where(E.IsNotNull(col("k"))).group_by("k").agg(
            A.agg(A.First(col("s")), "fs"))
        report = df.explain()
        assert "!Exec <HashAggregateExec> cannot run on TPU" in report
        assert "*Exec <FilterExec> will run on TPU" in report

    def test_explain_names_rule_param_and_type(self):
        """Every fallback reason names the rule, parameter, and offending
        type, and the exec line carries a nested !Expression annotation
        (the willNotWorkOnTpu contract of the static matrix)."""
        sess = TpuSession()
        df = make_df(sess).group_by("k").agg(A.agg(A.First(col("s")), "fs"))
        report = df.explain()
        assert "First: input string is not supported" in report
        assert "aggregation context" in report
        assert "!Expression <First>" in report

    def test_explain_conf_capture(self):
        sess = TpuSession({"spark.rapids.tpu.sql.explain": "ALL"})
        make_df(sess).select(col("a")).collect()
        assert "will run on TPU" in sess.last_explain

    def test_explain_not_on_tpu_only(self):
        sess = TpuSession({"spark.rapids.tpu.sql.explain": "NOT_ON_TPU"})
        make_df(sess).group_by("k").agg(A.agg(A.First(col("s")), "fs")).collect()
        assert "cannot run on TPU" in sess.last_explain
        assert "will run on TPU" not in sess.last_explain


class TestMixedPlan:
    def test_tpu_below_cpu_agg(self):
        """Filter/project run on TPU, string agg falls back, transitions
        inserted at the boundary."""
        sess = TpuSession()
        df = (
            make_df(sess, 100, 2)
            .where(E.GreaterThan(col("a"), lit(-50)))
            .select(col("k"), col("s"))
            .group_by("k")
            .agg(A.agg(A.First(col("s")), "fs"))
        )
        rows = df.collect()
        assert len(rows) > 0
        plan_str = sess.last_executed_plan.tree_string()
        assert "ColumnarToRowExec" in plan_str
        assert "TpuFilterExec" in plan_str

    def test_sort_now_runs_on_tpu(self):
        sess = TpuSession()
        df = make_df(sess, 100, 2).select(col("a")).order_by("a")
        rows = df.collect()
        assert [r[0] for r in rows] == sorted(r[0] for r in rows)
        # multi-device sessions lower global sort to the mesh stage
        plan = sess.last_executed_plan.tree_string()
        assert "TpuSortExec" in plan or "TpuMeshSortExec" in plan


class TestDocGen:
    def test_generated_docs_cover_registries(self):
        """configs.md / supported_ops.md generate from the live registries
        (reference: RapidsConf.help + TypeChecks.help doc artifacts)."""
        from spark_rapids_tpu.conf import _REGISTRY
        from spark_rapids_tpu.plugin.docgen import configs_md, supported_ops_md
        from spark_rapids_tpu.plugin.overrides import (
            EXEC_RULES,
            EXPRESSION_RULES,
        )

        cfg = configs_md()
        assert "spark.rapids.tpu.sql.enabled" in cfg
        public = [k for k, e in _REGISTRY.items() if not e.internal]
        assert all(k in cfg for k in public)

        ops = supported_ops_md()
        for r in EXPRESSION_RULES.values():
            assert f"| {r.name} |" in ops
        for r in EXEC_RULES.values():
            assert f"| {r.name} |" in ops
        # a few known matrix facts
        assert "| Upper | uppercase conversion |" in ops
        assert "CollectLimitExec" in ops
