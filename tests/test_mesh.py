"""Mesh exchange-stage tests: planner-selected shard_map programs over the
8-device virtual mesh, differentially checked against the CPU oracle and
the single-host exchange path.

This is the coverage VERDICT r2 item #4 asked for: a TpuSession query with
N partitions executing on the mesh via collectives.
"""
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.expressions import col, lit
from spark_rapids_tpu.sql import TpuSession

from harness import assert_tpu_and_cpu_equal, compare_rows

# broadcast-threshold off: these tests exercise the exchange paths
ICI = {"spark.rapids.tpu.shuffle.mode": "ici",
       "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": -1}
HOST = {"spark.rapids.tpu.shuffle.mode": "host",
        "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": -1}

SCHEMA = T.StructType([
    T.StructField("k", T.INT),
    T.StructField("g", T.LONG),
    T.StructField("v", T.LONG),
    T.StructField("d", T.DOUBLE),
])


def _data(n=700):
    return {
        "k": [i % 9 if i % 13 else None for i in range(n)],
        "g": [(i * 7) % 4 for i in range(n)],
        "v": [None if i % 17 == 0 else i * 3 - n for i in range(n)],
        "d": [None if i % 19 == 0 else i / 7.0 for i in range(n)],
    }


def make_df(sess, n=700, parts=4):
    return sess.create_dataframe(_data(n), SCHEMA, num_partitions=parts)


def _plan(sess):
    return sess.last_executed_plan.tree_string()


def test_mesh_aggregate_differential():
    def build(s):
        return make_df(s).group_by("k").agg(
            A.agg(A.Count(None), "n"),
            A.agg(A.Sum(col("v")), "sv"),
            A.agg(A.Min(col("v")), "mn"),
            A.agg(A.Max(col("g")), "mx"),
        )

    assert_tpu_and_cpu_equal(build, conf=ICI)


def test_mesh_aggregate_average_and_multi_key():
    def build(s):
        return make_df(s).group_by("k", "g").agg(
            A.agg(A.Average(col("v")), "av"),
            A.agg(A.Count(col("v")), "cv"),
        )

    assert_tpu_and_cpu_equal(build, conf=ICI, approx_float=True)


def test_mesh_plan_selected():
    sess = TpuSession(ICI)
    make_df(sess).group_by("k").agg(A.agg(A.Count(None), "n")).collect()
    assert "TpuMeshAggregateExec" in _plan(sess)
    make_df(sess).order_by(col("v")).collect()
    assert "TpuMeshSortExec" in _plan(sess)


def test_mesh_sort_differential():
    def build(s):
        return make_df(s).order_by(col("v"), col("k"))

    # global ordering must hold exactly (not just set equality)
    cpu = TpuSession({**ICI, "spark.rapids.tpu.sql.enabled": False})
    tpu = TpuSession({**ICI, "spark.rapids.tpu.sql.test.enabled": True})
    crows = build(cpu).collect()
    trows = build(tpu).collect()
    assert "TpuMeshSortExec" in _plan(tpu)
    compare_rows(crows, trows, ignore_order=True, approx_float=False)

    # the (v, k) key sequence must match the CPU engine's global order
    # exactly (ties may permute non-key columns)
    def keyseq(rows):
        return [(r[2] is None, r[2] or 0, r[0] is None, r[0] or 0)
                for r in rows]

    assert keyseq(trows) == keyseq(crows)


def test_mesh_sort_desc_nulls():
    def build(s):
        return make_df(s).order_by(col("d"), ascending=False)

    assert_tpu_and_cpu_equal(build, conf=ICI)


def test_mesh_join_differential():
    def build(s):
        left = make_df(s, n=400, parts=3)
        right = s.create_dataframe(
            {"k2": [i % 9 for i in range(60)],
             "w": [i * 10 for i in range(60)]},
            T.StructType([T.StructField("k2", T.INT),
                          T.StructField("w", T.LONG)]),
            num_partitions=2)
        return left.join(right, on=[("k", "k2")])

    assert_tpu_and_cpu_equal(build, conf=ICI)


def test_mesh_join_plan_selected():
    sess = TpuSession(ICI)
    left = make_df(sess, n=100, parts=2)
    right = sess.create_dataframe(
        {"k2": [1, 2, 3], "w": [10, 20, 30]},
        T.StructType([T.StructField("k2", T.INT), T.StructField("w", T.LONG)]),
        num_partitions=2)
    left.join(right, on=[("k", "k2")]).collect()
    assert "TpuMeshHashJoinExec" in _plan(sess)


def test_mesh_matches_host_exchange():
    """ici and host modes must agree bit-for-bit (two shuffle architectures,
    one semantics — the reference's transport-agnostic contract)."""
    def build(s):
        return make_df(s).group_by("g").agg(
            A.agg(A.Sum(col("v")), "sv"), A.agg(A.Count(None), "n"))

    a = TpuSession({**ICI, "spark.rapids.tpu.sql.test.enabled": True})
    b = TpuSession({**HOST, "spark.rapids.tpu.sql.test.enabled": True})
    ra = build(a).collect()
    rb = build(b).collect()
    assert "TpuMeshAggregateExec" in _plan(a)
    assert "TpuShuffleExchangeExec" in _plan(b)
    compare_rows(ra, rb, ignore_order=True, approx_float=False)


STR_SCHEMA = T.StructType([
    T.StructField("s", T.STRING),
    T.StructField("v", T.LONG),
    T.StructField("p", T.STRING),
])


def _str_data(n=600):
    pool = ["alpha", "beta-longer-key", "", "gamma", None, "déjà"]
    return {
        "s": [pool[i % len(pool)] for i in range(n)],
        "v": [None if i % 23 == 0 else i * 3 - n for i in range(n)],
        "p": [f"payload-{i % 11}-{'x' * (i % 5)}" for i in range(n)],
    }


def make_str_df(s, n=600, parts=4):
    return s.create_dataframe(_str_data(n), STR_SCHEMA, num_partitions=parts)


def test_mesh_string_key_aggregate_differential():
    """String group keys cross the mesh via the collective's byte plane
    (reference bar: the UCX shuffle is type-agnostic,
    RapidsShuffleClient.scala:35-98)."""
    def build(s):
        return make_str_df(s).group_by("s").agg(
            A.agg(A.Count(None), "n"), A.agg(A.Sum(col("v")), "sv"))

    assert_tpu_and_cpu_equal(build, conf=ICI)
    sess = TpuSession({**ICI, "spark.rapids.tpu.sql.test.enabled": True})
    make_str_df(sess).group_by("s").agg(A.agg(A.Count(None), "n")).collect()
    assert "TpuMeshAggregateExec" in _plan(sess)


def test_mesh_string_key_join_differential():
    def build(s):
        left = make_str_df(s, n=300, parts=3)
        right = s.create_dataframe(
            {"s2": ["alpha", "beta-longer-key", "", "zeta"],
             "w": ["W-alpha", "W-beta", "W-empty", "W-zeta"]},
            T.StructType([T.StructField("s2", T.STRING),
                          T.StructField("w", T.STRING)]),
            num_partitions=2)
        return left.join(right, on=[("s", "s2")])

    assert_tpu_and_cpu_equal(build, conf=ICI)
    sess = TpuSession({**ICI, "spark.rapids.tpu.sql.test.enabled": True})
    left = make_str_df(sess, n=120, parts=2)
    right = sess.create_dataframe(
        {"s2": ["alpha"], "w": ["W"]},
        T.StructType([T.StructField("s2", T.STRING),
                      T.StructField("w", T.STRING)]), num_partitions=2)
    left.join(right, on=[("s", "s2")]).collect()
    assert "TpuMeshHashJoinExec" in _plan(sess)


def test_mesh_string_sort_differential():
    def build(s):
        return make_str_df(s).order_by(col("s"))

    assert_tpu_and_cpu_equal(build, conf=ICI)


def test_mesh_string_matches_host_exchange():
    def build(s):
        return make_str_df(s).group_by("s").agg(
            A.agg(A.Sum(col("v")), "sv"), A.agg(A.Count(None), "n"))

    a = TpuSession({**ICI, "spark.rapids.tpu.sql.test.enabled": True})
    b = TpuSession({**HOST, "spark.rapids.tpu.sql.test.enabled": True})
    ra = build(a).collect()
    rb = build(b).collect()
    assert "TpuMeshAggregateExec" in _plan(a)
    assert "TpuShuffleExchangeExec" in _plan(b)
    compare_rows(ra, rb, ignore_order=True, approx_float=False)


def test_computed_string_key_falls_back_to_host_exchange():
    """COMPUTED string keys have no staged byte bound; the planner must
    pick the single-host exchange, not fail."""
    sess = TpuSession({**ICI, "spark.rapids.tpu.sql.test.enabled": True})
    df = make_str_df(sess, n=200, parts=3)
    rows = df.group_by(E.Alias(E.Upper(col("s")), "u")).agg(
        A.agg(A.Sum(col("v")), "sv")).collect()
    plan = _plan(sess)
    assert "TpuMeshAggregateExec" not in plan
    assert "TpuShuffleExchangeExec" in plan
    # pool: ALPHA, BETA-LONGER-KEY, "", GAMMA, None, DÉJÀ
    assert len(rows) == 6


def test_mesh_empty_and_skewed_partitions():
    sess = TpuSession({**ICI, "spark.rapids.tpu.sql.test.enabled": True})
    # every row in one partition; more shards than rows in others
    df = sess.create_dataframe(
        {"k": [1] * 50 + [2], "v": list(range(51))}, T.StructType([
            T.StructField("k", T.INT), T.StructField("v", T.LONG)]),
        num_partitions=6)
    rows = sorted(df.group_by("k").agg(A.agg(A.Count(None), "n")).collect())
    assert rows == [(1, 50), (2, 1)]
