import pytest

from spark_rapids_tpu import conf as C
from spark_rapids_tpu.conf import RapidsConf


def test_defaults():
    rc = RapidsConf()
    assert rc.is_sql_enabled
    assert rc.explain == "NONE"
    assert rc.concurrent_tpu_tasks == 1


def test_typed_parsing():
    rc = RapidsConf({
        "spark.rapids.tpu.sql.enabled": "false",
        "spark.rapids.tpu.sql.concurrentTpuTasks": "4",
        "spark.rapids.tpu.memory.hbm.allocFraction": "0.5",
    })
    assert rc.is_sql_enabled is False
    assert rc.concurrent_tpu_tasks == 4
    assert rc.get(C.HBM_POOL_FRACTION) == 0.5


def test_unknown_rapids_key_rejected():
    with pytest.raises(ValueError):
        RapidsConf({"spark.rapids.tpu.sql.doesNotExist": "1"})


def test_foreign_keys_ignored():
    rc = RapidsConf({"spark.executor.cores": "8"})
    assert rc.is_sql_enabled


def test_validation():
    with pytest.raises(ValueError):
        RapidsConf({"spark.rapids.tpu.sql.explain": "SOMETIMES"})
    with pytest.raises(ValueError):
        RapidsConf({"spark.rapids.tpu.sql.concurrentTpuTasks": "0"})
    with pytest.raises(ValueError):
        RapidsConf({"spark.rapids.tpu.memory.hbm.allocFraction": "1.5"})


def test_help_generates_docs():
    doc = RapidsConf.help()
    assert "spark.rapids.tpu.sql.enabled" in doc
    assert doc.startswith("# TPU RAPIDS Configuration")
    # internal test keys hidden by default
    assert "test.allowedNonTpu" not in doc
    assert "test.allowedNonTpu" in RapidsConf.help(include_internal=True)


def test_arm_idiom():
    from spark_rapids_tpu.utils import close_on_except, safe_close, with_resource

    class R:
        def __init__(self):
            self.closed = False

        def close(self):
            self.closed = True

    r = R()
    with with_resource(r):
        pass
    assert r.closed

    r2 = R()
    try:
        with close_on_except(r2):
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    assert r2.closed

    r3 = R()
    with close_on_except(r3):
        pass
    assert not r3.closed
    safe_close([r3, None, R()])
    assert r3.closed
