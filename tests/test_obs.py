"""Live observability plane: registry, /metrics + /status, watchdog.

Reference analog: SQLMetrics streaming into the live Spark UI (the
online half of observability; tests/test_events.py covers the offline
half). Pins the PR's acceptance contracts:
  1. the metric catalog is the single source of truth and every
     events.EVENT_TYPES entry has a live twin (the planes cannot drift);
  2. during a live query /metrics serves Prometheus-format gauges for
     the HBM watermark, compile misses, shuffle bytes, and scan-cache
     hit rate, and /status shows per-query per-op progress whose
     denominators come from the plan analyzer's row/batch forecasts;
  3. a deliberately stalled op, a tiny hbm budget, and a compile-miss
     burst each raise their typed watchdog alert in BOTH the event log
     and /status — one alert per episode;
  4. with the plane off (the default) NOTHING is touched: no registry
     method runs, no exporter/watchdog thread exists (the PR-5
     zero-overhead contract, mirrored);
  5. N concurrent emitter threads lose no increments, take no lock
     inversion against the BufferCatalog, and /status stays parseable
     mid-run.
"""
import importlib.util
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from spark_rapids_tpu import events as EV
from spark_rapids_tpu import obs
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.expressions import col, lit
from spark_rapids_tpu.memory.catalog import BufferCatalog
from spark_rapids_tpu.obs.registry import METRICS, MetricsRegistry
from spark_rapids_tpu.obs.server import build_status
from spark_rapids_tpu.obs.watchdog import WatchdogRules, replay_alerts
from spark_rapids_tpu.sql import TpuSession

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


tpu_top = _load_tool("tpu_top")
tpu_profile = _load_tool("tpu_profile")


@pytest.fixture(autouse=True)
def clean_plane():
    """Every test starts and ends with the plane down and no logger."""
    obs.shutdown()
    EV.uninstall()
    yield
    obs.shutdown()
    EV.uninstall()


def _run_query(sess):
    df = (sess.range(0, 2048)
          .where(E.GreaterThanOrEqual(col("id"), lit(100)))
          .select(col("id"), E.Alias(E.Multiply(col("id"), lit(2)), "v"))
          .agg(A.agg(A.Sum(col("v")), "s"), A.agg(A.Count(None), "c")))
    return df.collect()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.read().decode()


# ---------------------------------------------------------------------------
# 1. catalog + registry semantics
# ---------------------------------------------------------------------------
def test_every_event_type_has_a_live_twin():
    for etype in EV.EVENT_TYPES:
        fam = obs.EVENT_BACKED_METRICS.get(etype)
        assert fam is not None, f"{etype} has no live metric twin"
        assert fam in METRICS, f"{etype} -> {fam} is not declared"


def test_registry_counters_gauges_and_labels():
    reg = MetricsRegistry()
    reg.inc("tpu_compile_misses", 1, site="sort")
    reg.inc("tpu_compile_misses", 2, site="sort")
    reg.inc("tpu_compile_misses", 1, site="project")
    assert reg.value("tpu_compile_misses", site="sort") == 3
    assert reg.value("tpu_compile_misses", site="project") == 1
    reg.set_gauge("tpu_hbm_device_bytes", 4096)
    reg.set_gauge("tpu_hbm_device_bytes", 1024)  # gauge: last write wins
    assert reg.value("tpu_hbm_device_bytes") == 1024
    with pytest.raises(ValueError, match="undeclared label"):
        reg.inc("tpu_compile_misses", 1, nope="x")  # typo fails loudly


def test_prometheus_exposition_shape():
    import re

    reg = MetricsRegistry()
    reg.inc("tpu_op_rows", 128, op="TpuProjectExec")
    reg.observe("tpu_op_batch_seconds", 0.005, op="TpuProjectExec")
    text = reg.render_prometheus()
    # every declared family shows HELP/TYPE even with zero samples
    for name, (kind, _, _) in METRICS.items():
        ename = name + ("_total" if kind == "counter" else "")
        assert f"# TYPE {ename} {kind}" in text, ename
    # value class includes '-' INSIDE so negative exponents (2e-05) pass
    sample = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [-+0-9.eE]+$")
    for line in text.strip().splitlines():
        if not line.startswith("#"):
            assert sample.match(line), line
    assert 'tpu_op_rows_total{op="TpuProjectExec"} 128' in text
    # histogram renders cumulative buckets + sum/count
    assert 'tpu_op_batch_seconds_bucket{op="TpuProjectExec",le="0.01"} 1' \
        in text
    assert 'tpu_op_batch_seconds_count{op="TpuProjectExec"} 1' in text


def test_open_span_table():
    reg = MetricsRegistry()
    t = reg.span_open("TpuSortExec", "", start_ns=100)
    assert reg.open_spans() == [("TpuSortExec", "", 100)]
    reg.span_close(t)
    assert reg.open_spans() == []


# ---------------------------------------------------------------------------
# 2. the acceptance path: live query -> /metrics + /status
# ---------------------------------------------------------------------------
def test_live_query_serves_metrics_and_progress(tmp_path):
    sess = TpuSession({
        "spark.rapids.tpu.metrics.http.enabled": True,
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
    })
    assert sess.obs_address is not None
    rows = _run_query(sess)
    assert rows[0][1] == 1948
    # a warm process-wide pipeline cache may legitimately compile
    # nothing for this query; drive one miss through the shared counter
    # path so the labeled sample is deterministic
    from spark_rapids_tpu.exec.base import note_compile_miss

    note_compile_miss("obs_probe_site")

    text = _get(sess.obs_address + "/metrics")
    # the four acceptance families, by exact exposition name
    assert "tpu_hbm_device_bytes" in text            # HBM watermark gauge
    assert 'tpu_compile_misses_total{site="obs_probe_site"} 1' in text
    assert "# TYPE tpu_shuffle_bytes_total counter" in text
    assert "# TYPE tpu_scan_cache_hit_ratio gauge" in text
    assert 'tpu_queries_total{state="finished"} 1' in text
    # per-op lane: the range source recorded its rows
    assert 'tpu_op_rows_total{op="TpuRangeExec"} 2048' in text

    st = json.loads(_get(sess.obs_address + "/status"))
    q = st["queries"][0]
    assert q["state"] == "finished" and q["rows_out"] == 1
    ops = {o["op"]: o for o in q["ops"]}
    # forecast-derived denominators: the analyzer's rows_by_op feeds the
    # denominator, record_batch the numerator
    rng = ops["TpuRangeExec"]
    assert rng["rows"] == 2048 and rng["rows_forecast"] == 2048
    assert rng["progress"] == 1.0
    agg = ops["TpuHashAggregateExec"]
    assert agg["rows_forecast"] == 1 and agg["batches_forecast"] == 1
    assert agg["progress"] == 1.0  # lazy row count -> batch denominator
    # the same forecasts rode into the event log for offline tools
    with open(sess.events.path) as f:
        recs = [json.loads(line) for line in f]
    pa = next(r for r in recs if r["event"] == "plan_analysis")
    assert pa["rows_by_op"]["TpuRangeExec"] == 2048
    assert pa["batches_by_op"]["TpuHashAggregateExec"] == 1


def test_shuffle_and_scan_cache_counters_feed_registry():
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.io.scan_cache import DeviceScanCache

    sess = TpuSession({
        "spark.rapids.tpu.metrics.live.enabled": True,
        "spark.rapids.tpu.shuffle.transport.class": "host",
        "spark.rapids.tpu.shuffle.mode": "host",
    })
    reg = obs.active()
    schema = T.StructType((T.StructField("k", T.IntegerType()),
                           T.StructField("v", T.LongType())))
    data = {"k": [i % 4 for i in range(64)], "v": list(range(64))}
    df = (sess.create_dataframe(data, schema, num_partitions=3)
          .group_by("k").agg(A.agg(A.Sum(col("v")), "s")))
    assert len(df.collect()) == 4
    written = reg.value("tpu_shuffle_bytes", direction="write",
                        codec="none")
    fetched = reg.value("tpu_shuffle_bytes", direction="fetch",
                        codec="none")
    assert written > 0 and fetched == written
    assert reg.value("tpu_shuffle_codec_seconds", op="encode") > 0

    cache = DeviceScanCache(max_bytes=1 << 20)
    cache.get(("nope",))               # miss
    cache.put(("k",), "v", 100)
    cache.get(("k",))                  # hit
    assert reg.value("tpu_scan_cache_ops", op="miss") == 1
    assert reg.value("tpu_scan_cache_ops", op="hit") == 1
    assert reg.value("tpu_scan_cache_hit_ratio") == 0.5

    # the h2d half of the transfer event (packed uploads) counts too
    import numpy as np

    from spark_rapids_tpu.io.arrow_convert import packed_upload

    packed_upload([np.arange(16, dtype=np.int64)])
    assert reg.value("tpu_transfer_bytes", direction="h2d") >= 128


def test_mesh_staging_reports_per_chip():
    pytest.importorskip("jax")
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device mesh")
    from spark_rapids_tpu import types as T

    sess = TpuSession({
        "spark.rapids.tpu.metrics.live.enabled": True,
        "spark.rapids.tpu.shuffle.mode": "ici",
    })
    schema = T.StructType((T.StructField("k", T.IntegerType()),
                           T.StructField("v", T.LongType())))
    data = {"k": [i % 8 for i in range(256)], "v": list(range(256))}
    df = (sess.create_dataframe(data, schema, num_partitions=4)
          .group_by("k").agg(A.agg(A.Sum(col("v")), "s")))
    assert len(df.collect()) == 8
    reg = obs.active()
    staged = {d: reg.value("tpu_mesh_staged_rows", device=str(d))
              for d in range(len(jax.devices()))}
    assert sum(staged.values()) == 256  # every row attributed to a chip
    assert sum(1 for v in staged.values() if v) >= 2  # truly per-device


# ---------------------------------------------------------------------------
# 3. watchdog alerts: log + /status
# ---------------------------------------------------------------------------
def _watchdog_session(tmp_path, extra=None):
    conf = {
        "spark.rapids.tpu.metrics.http.enabled": True,
        "spark.rapids.tpu.watchdog.enabled": True,
        # huge interval: tests drive check_now() deterministically
        "spark.rapids.tpu.watchdog.intervalMs": 3_600_000,
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
    }
    conf.update(extra or {})
    sess = TpuSession(conf)
    EV.install(sess.events)
    return sess, obs.plane()


def test_stalled_op_raises_watchdog_alert(tmp_path):
    from spark_rapids_tpu.exec.base import TpuExec

    sess, plane = _watchdog_session(tmp_path, {
        "spark.rapids.tpu.watchdog.stallThresholdMs": 1})

    class Dummy(TpuExec):
        @property
        def output_schema(self):
            raise NotImplementedError

    d = Dummy(RapidsConf({}))
    cm = d.op_timed("decode")
    cm.__enter__()  # span stays OPEN: the deliberately stalled op
    try:
        time.sleep(0.01)
        new = plane.watchdog.check_now()
        assert [a.kind for a in new] == ["stall"]
        assert new[0].detail == "Dummy.decode"
        # one alert per episode: the same open span does not re-alert
        assert plane.watchdog.check_now() == []
    finally:
        cm.__exit__(None, None, None)
    # surfaced in the event log...
    alerts = [r for r in sess.events.records() if r["event"] == "alert"]
    assert alerts and alerts[0]["kind"] == "stall"
    # ...and in /status
    st = json.loads(_get(sess.obs_address + "/status"))
    assert any(a["kind"] == "stall" for a in st["alerts"])
    # cleared condition can fire again as a fresh episode
    cm2 = d.op_timed("decode")
    cm2.__enter__()
    try:
        time.sleep(0.01)
        assert [a.kind for a in plane.watchdog.check_now()] == ["stall"]
    finally:
        cm2.__exit__(None, None, None)


def test_tiny_hbm_budget_raises_pressure_alert(tmp_path):
    import jax.numpy as jnp

    from spark_rapids_tpu.expr.values import ColV
    from spark_rapids_tpu.memory import SpillableVals

    sess, plane = _watchdog_session(tmp_path, {
        "spark.rapids.tpu.watchdog.hbmPressureFraction": 0.5})
    BufferCatalog.reset(RapidsConf(
        {"spark.rapids.tpu.memory.hbm.budgetBytes": 100_000}))
    try:
        sv = SpillableVals([ColV(jnp.zeros(8192, jnp.int64),
                                 jnp.ones(8192, jnp.bool_))])  # ~72KB
        assert BufferCatalog.get().device_bytes > 50_000
        new = plane.watchdog.check_now()
        assert [a.kind for a in new] == ["hbm_pressure"]
        assert new[0].value > new[0].threshold / 2
        st = json.loads(_get(sess.obs_address + "/status"))
        assert any(a["kind"] == "hbm_pressure" for a in st["alerts"])
        assert st["hbm"]["pressure"] > 0.5
        kinds = [r["kind"] for r in sess.events.records()
                 if r["event"] == "alert"]
        assert "hbm_pressure" in kinds
        sv.close()
    finally:
        BufferCatalog.reset()


def test_pressure_alert_without_catalog_budget(tmp_path):
    """The pressure rule must also fire when the session conf carries
    the budget but the catalog was lazily created under a DEFAULT conf
    (no budget -> the spiller never caps, which is exactly when an
    operator needs the alert): the watchdog falls back to its own
    conf-derived budget."""
    import jax.numpy as jnp

    from spark_rapids_tpu.expr.values import ColV
    from spark_rapids_tpu.memory import SpillableVals

    BufferCatalog.reset()  # default conf: cat.budget is None on CPU
    sess, plane = _watchdog_session(tmp_path, {
        "spark.rapids.tpu.watchdog.hbmPressureFraction": 0.5,
        "spark.rapids.tpu.memory.hbm.budgetBytes": 100_000,
    })
    try:
        assert BufferCatalog.get().budget is None
        sv = SpillableVals([ColV(jnp.zeros(8192, jnp.int64),
                                 jnp.ones(8192, jnp.bool_))])  # ~72KB
        new = plane.watchdog.check_now()
        assert [a.kind for a in new] == ["hbm_pressure"]
        sv.close()
    finally:
        BufferCatalog.reset()


def test_storm_threshold_has_one_home():
    """The 'one storm definition engine-wide' promise: the conf entry's
    default, the bare WatchdogRules() default, and tpu_profile's CLI
    default must all agree (a drifted copy fails here)."""
    from spark_rapids_tpu.conf import ANALYSIS_STORM_THRESHOLD

    assert WatchdogRules().storm_threshold \
        == ANALYSIS_STORM_THRESHOLD.default \
        == tpu_profile.DEFAULT_STORM_THRESHOLD


def test_compile_miss_burst_raises_storm_alert(tmp_path):
    from spark_rapids_tpu.exec.base import note_compile_miss

    sess, plane = _watchdog_session(tmp_path, {
        "spark.rapids.tpu.sql.analysis.recompileStorm.threshold": 5})
    for _ in range(5):
        note_compile_miss("test_site")
    new = plane.watchdog.check_now()
    assert [a.kind for a in new] == ["recompile_storm"]
    assert new[0].detail == "test_site" and new[0].value == 5
    st = json.loads(_get(sess.obs_address + "/status"))
    assert any(a["kind"] == "recompile_storm" for a in st["alerts"])
    assert any(r["event"] == "alert" for r in sess.events.records())


# ---------------------------------------------------------------------------
# 4. zero overhead when off (the PR-5 contract, mirrored)
# ---------------------------------------------------------------------------
def test_disabled_plane_touches_nothing(monkeypatch):
    calls = []
    for name in ("inc", "set_gauge", "observe", "span_open",
                 "note_compile_miss"):
        orig = getattr(MetricsRegistry, name)

        def spy(self, *a, __n=name, __o=orig, **k):
            calls.append(__n)
            return __o(self, *a, **k)

        monkeypatch.setattr(MetricsRegistry, name, spy)
    sess = TpuSession({})  # defaults: the plane is OFF
    assert sess.obs_address is None and obs.plane() is None
    _run_query(sess)
    assert obs.enabled() is False
    assert calls == []            # no registry method ran at all
    assert obs.tracker().status() == []  # progress untouched
    live = [t.name for t in threading.enumerate()
            if t.name in ("srtpu-metrics-http", "srtpu-watchdog")]
    assert live == []             # no exporter/watchdog thread


def test_op_timed_returns_plain_context_when_off():
    """With the plane off op_timed must hand back the unwrapped timed()
    context — no span registration, no per-batch obs wrapper."""
    from spark_rapids_tpu.exec.base import TpuExec

    class Dummy(TpuExec):
        @property
        def output_schema(self):
            raise NotImplementedError

    d = Dummy(RapidsConf({}))
    assert type(d.op_timed()).__name__ == "_GeneratorContextManager"
    reg = MetricsRegistry()
    obs.install(reg)
    try:
        with d.op_timed():
            assert len(reg.open_spans()) == 1  # wrapper registered it
        assert reg.open_spans() == []
        assert reg.value("tpu_op_time_seconds", op="Dummy",
                         lane="host") > 0
    finally:
        obs.uninstall()


def test_direct_execute_consumer_leaks_no_live_query():
    """ml/columnar_rdd and bench drive _execute() + execute_columnar()
    directly, with no _run_collect finally: progress registration is
    deferred to the drain paths, so a direct consumer must leave no
    forever-'running' query behind and the start/finish counters must
    balance."""
    sess = TpuSession({"spark.rapids.tpu.metrics.live.enabled": True})
    df = sess.range(0, 256).agg(A.agg(A.Sum(col("id")), "s"))
    final = sess._execute(df.node)  # the direct-consumer path
    assert [b.num_rows for b in final.tpu_child.execute_columnar()] == [1]
    assert obs.tracker().live_count() == 0
    assert obs.tracker().status() == []  # nothing phantom, live or recent
    reg = obs.active()
    assert reg.value("tpu_queries", state="started") == 0
    # the collect path still counts one started + one finished
    _run_query(sess)
    assert reg.value("tpu_queries", state="started") == 1
    assert reg.value("tpu_queries", state="finished") == 1
    assert obs.tracker().live_count() == 0


def test_writer_progress_survives_intervening_query(tmp_path):
    """The writer claims its progress registration EAGERLY at plan
    time: a query collected between _batches() and the sink drain must
    not steal/overwrite the shared pending slot (the same race the
    event log fixes by capturing qid eagerly)."""
    from spark_rapids_tpu.sql.session import DataFrameWriter

    sess = TpuSession({"spark.rapids.tpu.metrics.live.enabled": True})
    df = sess.range(0, 256).agg(A.agg(A.Sum(col("id")), "s"))
    gen, _schema = DataFrameWriter(df)._batches()  # plan, don't drain
    assert _run_query(sess)[0][1] == 1948          # intervening query
    assert len(list(gen)) >= 1                     # now drain the sink
    reg = obs.active()
    assert reg.value("tpu_queries", state="started") == 2
    assert reg.value("tpu_queries", state="finished") == 2
    assert obs.tracker().live_count() == 0


# ---------------------------------------------------------------------------
# 5. concurrency: no lost increments, no inversion, parseable /status
# ---------------------------------------------------------------------------
def test_concurrent_emitters_and_status_reads(tmp_path):
    sess = TpuSession({
        "spark.rapids.tpu.metrics.http.enabled": True,
    })
    reg = obs.active()
    url = sess.obs_address
    errors = []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            try:
                st = json.loads(_get(url + "/status"))
                assert isinstance(st["queries"], list)
                _get(url + "/metrics")
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    def query_thread(i):
        try:
            s = TpuSession({"spark.rapids.tpu.metrics.live.enabled": True})
            for _ in range(3):
                assert _run_query(s)[0][1] == 1948
            for _ in range(1000):
                reg.inc("tpu_op_rows", 1, op=f"T{i}")
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def catalog_thread():
        # spill-pressure traffic interleaving with registry emits: the
        # catalog holds ITS lock while calling the (leaf) registry lock
        import jax.numpy as jnp

        from spark_rapids_tpu.expr.values import ColV
        from spark_rapids_tpu.memory import SpillableVals

        try:
            BufferCatalog.reset(RapidsConf(
                {"spark.rapids.tpu.memory.hbm.budgetBytes": 150_000}))
            for _ in range(8):
                vals = [SpillableVals([ColV(
                    jnp.zeros(4096, jnp.int64),
                    jnp.ones(4096, jnp.bool_))]) for _ in range(4)]
                for v in vals:
                    v.get_vals()
                    v.close()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    rt = threading.Thread(target=reader)
    rt.start()
    threads = [threading.Thread(target=query_thread, args=(i,))
               for i in range(4)] + [threading.Thread(target=catalog_thread)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "emitter deadlocked"
    stop.set()
    rt.join(timeout=30)
    assert not errors, errors
    # no lost increments: exact totals per thread-private label and
    # across the shared query counters
    for i in range(4):
        assert reg.value("tpu_op_rows", op=f"T{i}") == 1000
    assert reg.value("tpu_queries", state="started") == 12
    assert reg.value("tpu_queries", state="finished") == 12
    assert obs.tracker().live_count() == 0
    BufferCatalog.reset()


# ---------------------------------------------------------------------------
# 6. offline alert replay + the terminal view
# ---------------------------------------------------------------------------
def _replay_events():
    t = 1_000_000
    evs = [
        {"ts": t, "event": "query_start", "query_id": 1,
         "plan_digest": "abc", "sql_hash": "d"},
        {"ts": t + 1, "event": "plan_analysis", "query_id": 1,
         "bounded": True, "site_forecast": {}, "bytes_by_op": {},
         "rows_by_op": {}, "batches_by_op": {}, "peak_hbm": 1000,
         "budget": 100_000, "warnings": []},
        # a 2s span: the stall — plus its deviceSync device-lane twin,
        # which must NOT replay as a second alert for the same episode
        {"ts": t + 10, "event": "op_span", "op": "TpuSortExec",
         "section": "", "start": t + 5, "dur": 2_000_000_000,
         "lane": "host"},
        {"ts": t + 11, "event": "op_span", "op": "TpuSortExec",
         "section": "device_wait", "start": t + 6,
         "dur": 2_000_000_000, "lane": "device"},
        # watermark at 90% of the logged budget: pressure
        {"ts": t + 20, "event": "spill", "kind": "device_to_host",
         "bytes": 1, "device_bytes": 90_000},
    ]
    # 6 misses on one site within 1ms: a storm at threshold 5
    evs += [{"ts": t + 30 + i, "event": "compile_miss", "site": "sort",
             "total": i + 1} for i in range(6)]
    evs.append({"ts": t + 99, "event": "query_end", "query_id": 1,
                "dur": 90, "rows": 1})
    return evs


def test_replay_alerts_finds_all_three_kinds():
    rules = WatchdogRules(stall_ns=1_000_000_000, pressure_fraction=0.85,
                          storm_threshold=5,
                          storm_window_ns=10_000_000_000)
    alerts = replay_alerts(_replay_events(), rules)
    kinds = [a.kind for a in alerts]
    assert kinds == ["stall", "hbm_pressure", "recompile_storm"]
    # storm alerts once per episode, not once per extra miss
    assert kinds.count("recompile_storm") == 1
    # higher thresholds silence it — the tuning workflow
    quiet = WatchdogRules(stall_ns=10_000_000_000, pressure_fraction=0.99,
                          storm_threshold=50,
                          storm_window_ns=10_000_000_000)
    assert replay_alerts(_replay_events(), quiet) == []


def test_tpu_profile_alerts_mode(tmp_path, capsys):
    p = str(tmp_path / "log.jsonl")
    with open(p, "w") as f:
        for r in _replay_events():
            f.write(json.dumps(r) + "\n")
    rc = tpu_profile.main([p, "--alerts", "--stall-ms", "1000",
                           "--storm-threshold", "5"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "== watchdog alert replay ==" in out
    assert "stall: TpuSortExec" in out
    assert "hbm_pressure" in out and "recompile_storm" in out
    assert "3 alert(s)" in out


def test_tpu_top_renders_status():
    status = {
        "queries_live": 1,
        "queries": [{
            "query_id": 7, "plan_digest": "abc", "state": "running",
            "elapsed_ms": 1234.5, "rows_out": None,
            "ops": [
                {"op": "TpuRangeExec", "rows": 1024, "rows_forecast": 2048,
                 "batches": 1, "batches_forecast": 2, "bytes": 9216,
                 "progress": 0.5},
                {"op": "TpuShuffledHashJoinExec", "rows": 10,
                 "rows_forecast": None, "batches": 1,
                 "batches_forecast": None, "bytes": 80, "progress": None},
            ],
        }],
        "hbm": {"device_bytes": 50_000_000, "peak_device_bytes": 60_000_000,
                "spilled_bytes": 0, "budget_bytes": 100_000_000,
                "pressure": 0.5},
        "alerts": [{"kind": "stall", "detail": "TpuSortExec",
                    "value": 2e9, "threshold": 1e9, "ts": 0}],
        "metrics": {"tpu_compile_misses": {"site=sort": 3},
                    "tpu_scan_cache_ops": {"op=hit": 3, "op=miss": 1}},
    }
    text = tpu_top.render_status(status, clock="12:00:00")
    assert "query 7 [running]" in text
    assert "rows 1024/2048" in text and " 50.0%" in text
    assert "(unbounded)" in text           # no fake percentage
    assert "ALERT [stall] TpuSortExec" in text
    assert "HBM" in text and "50.0MB" in text
    assert "75% hit" in text and "compile misses: 3" in text


def test_build_status_is_json_serializable():
    reg = MetricsRegistry()
    obs.install(reg)
    try:
        reg.inc("tpu_op_rows", 5, op="X")
        reg.observe("tpu_op_batch_seconds", 0.2, op="X")
        st = build_status(reg, obs.tracker(), None)
        json.dumps(st)  # must never smuggle a non-JSON type
        assert st["metrics"]["tpu_op_rows"] == {"op=X": 5}
    finally:
        obs.uninstall()


# ---------------------------------------------------------------------------
# 7. event-log durability at teardown (satellite)
# ---------------------------------------------------------------------------
def test_dying_interpreter_leaves_parseable_log(tmp_path):
    """A session killed mid-query (SystemExit between query_start and
    query_end, no close()) must still leave a fully parseable JSONL log
    — the atexit flush plus line buffering guarantee no truncated final
    line."""
    script = f"""
import sys
sys.path.insert(0, {str(REPO)!r})
from spark_rapids_tpu.sql import TpuSession
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr.expressions import col

sess = TpuSession({{"spark.rapids.tpu.eventLog.dir": {str(tmp_path)!r}}})
df = sess.range(0, 512).agg(A.agg(A.Sum(col("id")), "s"))
final = sess._execute(df.node)   # emits query_start + plan events
it = final.tpu_child.execute_columnar()
next(it)                          # mid-query: first batch materialized
raise SystemExit(3)               # die WITHOUT close(); no query_end
"""
    r = subprocess.run([sys.executable, "-c", script],
                       capture_output=True, text=True,
                       env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 3, r.stderr
    logs = [f for f in os.listdir(tmp_path) if f.endswith(".jsonl")]
    assert len(logs) == 1
    with open(tmp_path / logs[0]) as f:
        recs = [json.loads(line) for line in f]  # every line parses
    kinds = [rec["event"] for rec in recs]
    assert "query_start" in kinds and "query_end" not in kinds
    # the offline profiler copes with the open window
    text, violations = tpu_profile.build_report(recs)
    assert "query 1" in text


def test_dropped_logger_not_pinned_by_atexit(tmp_path):
    """The atexit durability hook registers through a weakref: a
    short-lived session's logger that nobody close()s must still be
    collectable (no fd/ring-buffer accumulation until process exit)."""
    import gc
    import weakref

    logger = EV.EventLogger(RapidsConf(
        {"spark.rapids.tpu.eventLog.dir": str(tmp_path)}))
    logger.emit("compile_miss", site="x", total=1)
    ref = weakref.ref(logger)
    del logger
    gc.collect()
    assert ref() is None, "atexit hook pins the dropped logger"


def test_session_close_flushes_and_detaches(tmp_path):
    sess = TpuSession({"spark.rapids.tpu.eventLog.dir": str(tmp_path)})
    _run_query(sess)
    assert EV._ACTIVE is sess.events
    sess.close()
    assert EV.enabled() is False and sess.events._fh is None
    with open(sess.events.path) as f:
        for line in f:
            json.loads(line)


def test_flight_recorder_mode_ring_only_until_dumped(tmp_path):
    """eventLog.flightRecorder.enabled + eventLog.dir: events land ONLY
    in the ring (no streaming JSONL sink opened), and dump_flight_record
    writes the ring snapshot as one tpu-flightrec-<pid>-<episode>.jsonl;
    a streaming logger's dump is a no-op (already durable)."""
    logger = EV.EventLogger(RapidsConf({
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.eventLog.flightRecorder.enabled": True}))
    assert logger.enabled and logger.path is None and logger._fh is None
    assert logger.flight_dir == str(tmp_path)
    logger.emit("compile_miss", site="x", total=1)
    assert os.listdir(tmp_path) == [], "flight recorder opened a sink"
    path = logger.dump_flight_record(1)
    assert os.path.basename(path) == f"tpu-flightrec-{os.getpid()}-1.jsonl"
    with open(path) as f:
        recs = [json.loads(line) for line in f]
    assert [r["event"] for r in recs] == ["compile_miss"]
    # a streaming logger has nowhere (and no need) to dump
    streaming = EV.EventLogger(RapidsConf(
        {"spark.rapids.tpu.eventLog.dir": str(tmp_path)}))
    assert streaming.dump_flight_record(1) is None
    streaming.close()


def test_watchdog_alert_dumps_flight_ring(tmp_path):
    """Each NEW watchdog alert episode dumps the ring — including the
    alert events just raised — one file per episode."""
    from spark_rapids_tpu.exec.base import TpuExec

    sess, plane = _watchdog_session(tmp_path, {
        "spark.rapids.tpu.eventLog.flightRecorder.enabled": True,
        "spark.rapids.tpu.watchdog.stallThresholdMs": 1})
    assert sess.events.flight_dir == str(tmp_path)

    class Dummy(TpuExec):
        @property
        def output_schema(self):
            raise NotImplementedError

    d = Dummy(RapidsConf({}))
    cm = d.op_timed("decode")
    cm.__enter__()
    try:
        time.sleep(0.01)
        assert [a.kind for a in plane.watchdog.check_now()] == ["stall"]
        dumps = sorted(f for f in os.listdir(tmp_path)
                       if f.startswith("tpu-flightrec-"))
        assert len(dumps) == 1
        with open(tmp_path / dumps[0]) as f:
            recs = [json.loads(line) for line in f]
        assert any(r["event"] == "alert" and r["kind"] == "stall"
                   for r in recs), "dump lost the triggering alert"
        # the same open episode does not dump again
        assert plane.watchdog.check_now() == []
        assert len([f for f in os.listdir(tmp_path)
                    if f.startswith("tpu-flightrec-")]) == 1
    finally:
        cm.__exit__(None, None, None)
    # a fresh episode gets its own numbered file
    cm2 = d.op_timed("decode")
    cm2.__enter__()
    try:
        time.sleep(0.01)
        assert plane.watchdog.check_now()
    finally:
        cm2.__exit__(None, None, None)
    assert len([f for f in os.listdir(tmp_path)
                if f.startswith("tpu-flightrec-")]) == 2


def test_flight_record_survives_dying_interpreter(tmp_path):
    """The satellite's acceptance path: ring-buffer mode (no streaming
    log), a watchdog alert fires MID-QUERY, the interpreter SystemExits
    without close() — and post-hoc diagnosis still works from the
    alert-triggered dump alone."""
    script = f"""
import sys, time
sys.path.insert(0, {str(REPO)!r})
from spark_rapids_tpu import obs
from spark_rapids_tpu.sql import TpuSession
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr.expressions import col

sess = TpuSession({{
    "spark.rapids.tpu.eventLog.dir": {str(tmp_path)!r},
    "spark.rapids.tpu.eventLog.flightRecorder.enabled": True,
    "spark.rapids.tpu.watchdog.enabled": True,
    "spark.rapids.tpu.watchdog.intervalMs": 3600000,
    "spark.rapids.tpu.watchdog.stallThresholdMs": 1,
}})
df = sess.range(0, 512).agg(A.agg(A.Sum(col("id")), "s"))
final = sess._execute(df.node)    # emits query_start into the ring
it = final.tpu_child.execute_columnar()
next(it)                          # mid-query: first batch materialized
cm = final.tpu_child.op_timed("wedged")
cm.__enter__()                    # a span that will never close
time.sleep(0.01)
alerts = obs.plane().watchdog.check_now()
assert alerts, "stall rule did not fire"
raise SystemExit(3)               # die WITHOUT close(); no query_end
"""
    r = subprocess.run([sys.executable, "-c", script],
                       capture_output=True, text=True,
                       env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 3, r.stderr
    # NO streaming log exists — the dump is the only artifact
    names = os.listdir(tmp_path)
    assert not any(n.startswith("tpu-events-") for n in names), names
    dumps = [n for n in names if n.startswith("tpu-flightrec-")]
    assert len(dumps) == 1, names
    with open(tmp_path / dumps[0]) as f:
        recs = [json.loads(line) for line in f]  # every line parses
    kinds = [rec["event"] for rec in recs]
    assert "query_start" in kinds and "query_end" not in kinds
    assert any(rec["event"] == "alert" and rec["kind"] == "stall"
               for rec in recs), kinds
    # the offline profiler reads the dump like any log
    text, _ = tpu_profile.build_report(recs)
    assert "query 1" in text


# ---------------------------------------------------------------------------
# 8. bench satellite: per-shape memory-pressure fields
# ---------------------------------------------------------------------------
def test_bench_mem_stats_fields():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    from spark_rapids_tpu.io.scan_cache import DeviceScanCache

    DeviceScanCache.reset()
    before = bench._mem_snapshot()
    cache = DeviceScanCache(1 << 20)
    DeviceScanCache._instance = cache
    try:
        cache.get(("a",))          # miss
        cache.put(("a",), 1, 10)
        cache.get(("a",))          # hit
        stats = bench._mem_stats(before)
        assert stats["scan_cache_hit_rate"] == 0.5
        assert stats["peak_device_bytes"] >= 0
        assert stats["scan_cache_bytes"] == 10
    finally:
        DeviceScanCache.reset()
