"""Multi-device SPMD tests: collectives on the 8-device virtual CPU mesh.

The distributed kernels (parallel/collective.py, parallel/distributed.py)
run under shard_map with real all_to_all / all_gather / psum collectives and
are checked differentially against a plain-python oracle — the same
correctness contract the single-chip differential harness enforces.

``shard_map`` comes from parallel/mesh.py (the ONE home of the jax version
shim — importing it from jax directly is exactly the collection error that
kept this suite red from the seed through round 5).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.eval import ColV, StrV
from spark_rapids_tpu.parallel import (
    all_to_all_exchange,
    dist_groupby,
    dist_hash_join,
    dist_sort,
)
from spark_rapids_tpu.parallel.mesh import shard_map

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices("cpu")
    if len(devs) < N_DEV:
        pytest.skip(f"need {N_DEV} virtual devices, have {len(devs)}")
    return Mesh(np.array(devs[:N_DEV]), ("dp",))


def _shard_put(mesh, arr):
    return jax.device_put(arr, NamedSharding(mesh, P("dp")))


def test_all_to_all_exchange_routes_rows(mesh):
    local = 64
    cap = local * N_DEV
    rng = np.random.default_rng(0)
    data = rng.integers(-1000, 1000, cap).astype(np.int64)
    valid = rng.random(cap) > 0.1
    target = rng.integers(0, N_DEV, cap).astype(np.int32)

    def step(d, v, t):
        cols, n, ok = all_to_all_exchange(
            [ColV(d, v)], t, local, "dp", N_DEV)
        # returned per-shard: fixed capacity, count varies
        return cols[0].data, cols[0].validity, jnp.reshape(n, (1,)), ok

    fn = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P("dp"), P("dp"), P("dp")),
        out_specs=(P("dp"), P("dp"), P("dp"), P()),
        check_vma=False,
    ))
    out_d, out_v, counts, ok = fn(
        _shard_put(mesh, data), _shard_put(mesh, valid),
        _shard_put(mesh, target))
    assert bool(ok)
    counts = np.asarray(counts)
    out_d = np.asarray(out_d).reshape(N_DEV, cap)
    out_v = np.asarray(out_v).reshape(N_DEV, cap)
    # oracle: rows grouped by target shard
    for s in range(N_DEV):
        n_s = int(counts[s])
        want = sorted(
            (int(d), bool(v))
            for d, v, t in zip(data, valid, target) if t == s
        )
        got_rows = []
        for i in range(n_s):
            got_rows.append(
                (int(out_d[s, i]) if out_v[s, i] else 0, bool(out_v[s, i])))
        # null rows carry data=0 by construction; compare multisets
        want = sorted((d if v else 0, v) for d, v in want)
        assert sorted(got_rows) == want
        assert not out_v[s, n_s:].any()


def _run_exchange(mesh, data, live_counts, target, bucket_cap=0):
    """Drive all_to_all_exchange with per-shard live row counts; returns
    (per-shard data rows, counts, ok)."""
    local = data.shape[0] // N_DEV
    cap = data.shape[0]

    def step(d, n, t):
        ones = jnp.ones(local, jnp.bool_)
        cols, rn, ok = all_to_all_exchange(
            [ColV(d, ones & (jnp.arange(local) < n[0]))], t, n[0],
            "dp", N_DEV, bucket_cap=bucket_cap)
        return cols[0].data, cols[0].validity, jnp.reshape(rn, (1,)), ok

    fn = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P("dp"), P("dp"), P("dp")),
        out_specs=(P("dp"), P("dp"), P("dp"), P()),
        check_vma=False,
    ))
    d, v, counts, ok = fn(
        _shard_put(mesh, data),
        _shard_put(mesh, np.asarray(live_counts, np.int32)),
        _shard_put(mesh, target))
    recv_cap = np.asarray(d).shape[0] // N_DEV
    return (np.asarray(d).reshape(N_DEV, recv_cap),
            np.asarray(v).reshape(N_DEV, recv_cap),
            np.asarray(counts), bool(np.asarray(ok)))


def test_exchange_empty_shard(mesh):
    """A shard with ZERO live rows sends nothing and still receives its
    share — the empty-partition edge of the data-parallel scan."""
    local = 32
    cap = local * N_DEV
    data = np.arange(cap, dtype=np.int64)
    live = [local] * N_DEV
    live[3] = 0  # shard 3 stages an empty partition
    target = (np.arange(cap, dtype=np.int32) % N_DEV)
    d, v, counts, ok = _run_exchange(mesh, data, live, target)
    assert ok
    want_total = sum(live)
    assert int(counts.sum()) == want_total
    # shard 3 sent nothing: no row of its range [3*local, 4*local) arrives
    got = sorted(int(x) for s in range(N_DEV)
                 for x in d[s, :counts[s]][v[s, :counts[s]]])
    want = sorted(int(x) for s in range(N_DEV) if live[s]
                  for x in data[s * local:(s + 1) * local])
    assert got == want


def test_exchange_all_rows_one_target(mesh):
    """Every live row targets shard 5: the receive side must hold
    n_shards x local rows (full-capacity granule always fits)."""
    local = 16
    cap = local * N_DEV
    data = np.arange(cap, dtype=np.int64)
    target = np.full(cap, 5, np.int32)
    d, v, counts, ok = _run_exchange(mesh, data, [local] * N_DEV, target)
    assert ok
    assert int(counts[5]) == cap
    assert all(int(counts[s]) == 0 for s in range(N_DEV) if s != 5)
    assert sorted(int(x) for x in d[5][v[5]]) == list(range(cap))


def test_exchange_overflow_reports_not_ok(mesh):
    local = 32

    def step(d):
        ones = jnp.ones(local, jnp.bool_)
        # every row targets shard 0 with a tiny bucket: must overflow
        cols, n, ok = all_to_all_exchange(
            [ColV(d, ones)], jnp.zeros(local, jnp.int32), local,
            "dp", N_DEV, bucket_cap=4)
        return jnp.reshape(n, (1,)), ok

    fn = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P("dp"),),
        out_specs=(P("dp"), P()), check_vma=False,
    ))
    cap = local * N_DEV
    _, ok = fn(_shard_put(mesh, np.arange(cap, dtype=np.int64)))
    assert not bool(ok)


def test_exchange_string_zero_length_chars(mesh):
    """String byte plane with zero-length values: empty strings cross the
    collective as 0-byte rows (offsets flat, validity TRUE) and shards
    whose whole payload is empty strings move no bytes at all."""
    local = 8
    cap = local * N_DEV
    # shard s sends strings; even shards send ONLY empty strings
    per_row = []
    for s in range(N_DEV):
        for i in range(local):
            per_row.append(b"" if s % 2 == 0 else b"x%d" % i)
    lens = np.array([len(b) for b in per_row], np.int64)
    # per-shard Arrow layout planes: offsets restart at 0 per shard
    o_in = np.zeros(N_DEV * (local + 1), np.int32)
    chars_parts = []
    for s in range(N_DEV):
        lo, hi = s * local, (s + 1) * local
        o_in[s * (local + 1) + 1: (s + 1) * (local + 1)] = np.cumsum(
            lens[lo:hi])
        chars_parts.append(b"".join(per_row[lo:hi]))
    # per-shard chars plane: equal static size per shard (pad with zeros)
    ccap = max(1, max(len(c) for c in chars_parts))
    chars = np.zeros(N_DEV * ccap, np.uint8)
    for s, c in enumerate(chars_parts):
        if c:
            chars[s * ccap: s * ccap + len(c)] = np.frombuffer(c, np.uint8)
    target = np.tile(np.arange(N_DEV, dtype=np.int32), local)[:cap]

    def step(o, ch, t):
        ones = jnp.ones(local, jnp.bool_)
        cols, n, ok = all_to_all_exchange(
            [StrV(o, ch, ones)], t, local, "dp", N_DEV)
        sv = cols[0]
        return (sv.offsets, sv.chars, sv.validity,
                jnp.reshape(n, (1,)), ok)

    fn = jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(P("dp"), P("dp"), P("dp")),
        out_specs=(P("dp"), P("dp"), P("dp"), P("dp"), P()),
        check_vma=False,
    ))
    oo, cc, vv, counts, ok = fn(
        _shard_put(mesh, o_in), _shard_put(mesh, chars),
        _shard_put(mesh, target))
    assert bool(ok)
    counts = np.asarray(counts)
    assert int(counts.sum()) == cap
    oo = np.asarray(oo)
    cc = np.asarray(cc)
    vv = np.asarray(vv)
    ocap = oo.shape[0] // N_DEV
    chcap = cc.shape[0] // N_DEV
    vcap = vv.shape[0] // N_DEV
    # oracle: shard s receives the rows whose target == s, as a multiset
    for s in range(N_DEV):
        so = oo[s * ocap: (s + 1) * ocap]
        sch = cc[s * chcap: (s + 1) * chcap]
        svv = vv[s * vcap: (s + 1) * vcap]
        n_s = int(counts[s])
        got = []
        for i in range(n_s):
            assert svv[i]
            b = bytes(sch[so[i]: so[i + 1]])
            got.append(b)
        want = [per_row[r] for r in range(cap) if target[r] == s]
        assert sorted(got) == sorted(want)


def test_dist_groupby_matches_oracle(mesh):
    local = 128
    cap = local * N_DEV
    rng = np.random.default_rng(1)
    keys = rng.integers(0, 40, cap).astype(np.int32)
    knull = rng.random(cap) < 0.05
    vals = rng.integers(-50, 50, cap).astype(np.int64)
    vnull = rng.random(cap) < 0.1

    def step(kd, kv, vd, vv):
        ks, aggs, n, ok = dist_groupby(
            [ColV(kd, kv)], [T.INT], [ColV(vd, vv), ColV(vd, vv)],
            ["sum", "count"], ["sum", "sum"], local, "dp", N_DEV)
        return (ks[0].data, ks[0].validity, aggs[0].data, aggs[0].validity,
                aggs[1].data, jnp.reshape(n, (1,)), ok)

    fn = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P("dp"),) * 4,
        out_specs=(P("dp"),) * 6 + (P(),),
        check_vma=False,
    ))
    kd, kv, sd, sv, cd, ns, ok = fn(
        _shard_put(mesh, keys), _shard_put(mesh, ~knull),
        _shard_put(mesh, vals), _shard_put(mesh, ~vnull))
    assert bool(ok)
    # gather per-shard outputs
    got = {}
    kd = np.asarray(kd).reshape(N_DEV, -1)
    kv = np.asarray(kv).reshape(N_DEV, -1)
    sd = np.asarray(sd).reshape(N_DEV, -1)
    sv = np.asarray(sv).reshape(N_DEV, -1)
    cd = np.asarray(cd).reshape(N_DEV, -1)
    ns = np.asarray(ns)
    for s in range(N_DEV):
        for i in range(int(ns[s])):
            k = int(kd[s, i]) if kv[s, i] else None
            assert k not in got, f"group {k} appears on two shards"
            got[k] = (
                int(sd[s, i]) if sv[s, i] else None, int(cd[s, i]))
    # oracle
    want = {}
    for k, kn, v, vn in zip(keys, knull, vals, vnull):
        kk = None if kn else int(k)
        s, c = want.get(kk, (None, 0))
        if not vn:
            s = int(v) if s is None else s + int(v)
            c += 1
        want[kk] = (s, c)
    assert got == want


@pytest.mark.parametrize("group_cap", [64, 128])
def test_dist_groupby_group_cap_slices_exchange(mesh, group_cap):
    """The capacity-sliced post-PARTIAL exchange (the round-6 bandwidth
    fix) is bit-equal to the full-capacity exchange when every shard's
    group count fits the cap."""
    local = 256
    cap = local * N_DEV
    rng = np.random.default_rng(5)
    keys = rng.integers(0, 40, cap).astype(np.int32)  # <= 40 groups/shard
    vals = rng.integers(-50, 50, cap).astype(np.int64)

    def step(kd, vd):
        ones = jnp.ones(local, jnp.bool_)
        ks, aggs, n, ok = dist_groupby(
            [ColV(kd, ones)], [T.INT], [ColV(vd, ones), ColV(vd, ones)],
            ["sum", "count"], ["sum", "sum"], local, "dp", N_DEV,
            group_cap=group_cap)
        return ks[0].data, aggs[0].data, aggs[1].data, jnp.reshape(
            n, (1,)), ok

    fn = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P("dp"),) * 2,
        out_specs=(P("dp"),) * 4 + (P(),), check_vma=False,
    ))
    kd, sd, cd, ns, ok = fn(_shard_put(mesh, keys), _shard_put(mesh, vals))
    assert bool(ok)
    got = {}
    ns = np.asarray(ns)
    # output capacity after a sliced exchange derives from the exchanged
    # surface, so reshape by the actual plane size
    kd = np.asarray(kd).reshape(N_DEV, -1)
    sd = np.asarray(sd).reshape(N_DEV, -1)
    cd = np.asarray(cd).reshape(N_DEV, -1)
    for s in range(N_DEV):
        for i in range(int(ns[s])):
            got[int(kd[s, i])] = (int(sd[s, i]), int(cd[s, i]))
    want = {}
    for k, v in zip(keys, vals):
        s, c = want.get(int(k), (0, 0))
        want[int(k)] = (s + int(v), c + 1)
    assert got == want


def test_dist_groupby_group_cap_overflow_not_ok(mesh):
    """More groups per shard than the exchange cap: ok must be False (the
    mesh aggregate's signal to retry with a doubled cap)."""
    local = 64

    def step(kd, vd):
        ones = jnp.ones(local, jnp.bool_)
        ks, aggs, n, ok = dist_groupby(
            [ColV(kd, ones)], [T.INT], [ColV(vd, ones)], ["sum"], ["sum"],
            local, "dp", N_DEV, group_cap=8)
        return jnp.reshape(n, (1,)), ok

    fn = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P("dp"),) * 2,
        out_specs=(P("dp"), P()), check_vma=False,
    ))
    cap = local * N_DEV
    # every row its own group: 64 groups/shard > cap of 8
    keys = np.arange(cap, dtype=np.int32)
    vals = np.ones(cap, np.int64)
    _, ok = fn(_shard_put(mesh, keys), _shard_put(mesh, vals))
    assert not bool(ok)


def test_dist_sort_global_order(mesh):
    local = 100
    cap = local * N_DEV
    rng = np.random.default_rng(2)
    keys = rng.integers(-500, 500, cap).astype(np.int64)
    knull = rng.random(cap) < 0.07
    payload = np.arange(cap, dtype=np.int64)

    from spark_rapids_tpu.ops.sort import SortOrder

    asc = SortOrder(True, None)

    def step(kd, kv, pd):
        cols, n, ok = dist_sort(
            [ColV(kd, kv), ColV(pd, jnp.ones_like(kv))],
            [0], [T.LONG], [asc], local, "dp", N_DEV)
        return (cols[0].data, cols[0].validity, cols[1].data,
                jnp.reshape(n, (1,)), ok)
    fn = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P("dp"), P("dp"), P("dp")),
        out_specs=(P("dp"), P("dp"), P("dp"), P("dp"), P()),
        check_vma=False,
    ))
    kd, kv, pd, ns, ok = fn(
        _shard_put(mesh, keys), _shard_put(mesh, ~knull),
        _shard_put(mesh, payload))
    assert bool(ok)
    kd = np.asarray(kd).reshape(N_DEV, -1)
    kv = np.asarray(kv).reshape(N_DEV, -1)
    ns = np.asarray(ns)
    flat = []
    for s in range(N_DEV):
        for i in range(int(ns[s])):
            flat.append(None if not kv[s, i] else int(kd[s, i]))
    assert len(flat) == cap
    # Spark ASC NULLS FIRST order, globally across shard boundaries
    want = sorted(
        (None if n else int(k) for k, n in zip(keys, knull)),
        key=lambda x: (x is not None, x if x is not None else 0),
    )
    assert flat == list(want)


def test_dist_sort_bucketed_granule(mesh):
    """The ~2x-fair-share exchange granule returns the same global order
    as the always-fits granule on an even key distribution, and reports
    ok=False instead of corrupting rows on a pathological skew."""
    local = 128
    cap = local * N_DEV
    rng = np.random.default_rng(9)
    keys = rng.integers(-10**6, 10**6, cap).astype(np.int64)

    from spark_rapids_tpu.ops.sort import SortOrder

    asc = SortOrder(True, None)

    def run(bucket_cap, kvals):
        def step(kd):
            ones = jnp.ones(local, jnp.bool_)
            cols, n, ok = dist_sort(
                [ColV(kd, ones)], [0], [T.LONG], [asc], local, "dp",
                N_DEV, bucket_cap=bucket_cap)
            return cols[0].data, jnp.reshape(n, (1,)), ok

        fn = jax.jit(shard_map(
            step, mesh=mesh, in_specs=(P("dp"),),
            out_specs=(P("dp"), P("dp"), P()), check_vma=False,
        ))
        d, ns, ok = fn(_shard_put(mesh, kvals))
        d = np.asarray(d)
        ns = np.asarray(ns)
        out = []
        per = d.shape[0] // N_DEV
        for s in range(N_DEV):
            out.extend(int(x) for x in d[s * per: s * per + int(ns[s])])
        return out, bool(np.asarray(ok))

    got, ok = run(2 * local // N_DEV * 2, keys)  # ~2x fair share
    assert ok
    assert got == sorted(int(k) for k in keys)
    # all-equal keys: every row lands in one range -> granule overflows
    _, ok = run(32, np.zeros(cap, np.int64))
    assert not ok


def test_dist_hash_join_inner(mesh):
    local = 64
    cap = local * N_DEV
    rng = np.random.default_rng(3)
    lk = rng.integers(0, 60, cap).astype(np.int32)
    lv = np.arange(cap, dtype=np.int64)
    rk = rng.integers(0, 60, cap).astype(np.int32)
    rnull = rng.random(cap) < 0.05
    rv = np.arange(cap, dtype=np.int64) * 10
    out_cap = 4096

    def step(lkd, lvd, rkd, rkv, rvd):
        ones = jnp.ones(local, jnp.bool_)
        cols, n, ok = dist_hash_join(
            [ColV(lkd, ones), ColV(lvd, ones)], [0],
            [ColV(rkd, rkv), ColV(rvd, ones)], [0],
            [T.INT], local, local, "dp", N_DEV, out_cap)
        return cols[0].data, cols[1].data, cols[3].data, jnp.reshape(n, (1,)), ok

    fn = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P("dp"),) * 5,
        out_specs=(P("dp"),) * 3 + (P("dp"), P()),
        check_vma=False,
    ))
    jk, jl, jr, ns, ok = fn(
        _shard_put(mesh, lk), _shard_put(mesh, lv),
        _shard_put(mesh, rk), _shard_put(mesh, ~rnull),
        _shard_put(mesh, rv))
    assert bool(ok)
    jk = np.asarray(jk).reshape(N_DEV, -1)
    jl = np.asarray(jl).reshape(N_DEV, -1)
    jr = np.asarray(jr).reshape(N_DEV, -1)
    ns = np.asarray(ns)
    got = []
    for s in range(N_DEV):
        for i in range(int(ns[s])):
            got.append((int(jk[s, i]), int(jl[s, i]), int(jr[s, i])))
    want = []
    right_by_key = {}
    for k, nn, v in zip(rk, rnull, rv):
        if not nn:
            right_by_key.setdefault(int(k), []).append(int(v))
    for k, v in zip(lk, lv):
        for rvv in right_by_key.get(int(k), ()):
            want.append((int(k), int(v), rvv))
    assert sorted(got) == sorted(want)
