"""Multi-device SPMD tests: collectives on the 8-device virtual CPU mesh.

The distributed kernels (parallel/collective.py, parallel/distributed.py)
run under shard_map with real all_to_all / all_gather / psum collectives and
are checked differentially against a plain-python oracle — the same
correctness contract the single-chip differential harness enforces.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.eval import ColV
from spark_rapids_tpu.parallel import (
    all_to_all_exchange,
    dist_groupby,
    dist_hash_join,
    dist_sort,
)

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices("cpu")
    if len(devs) < N_DEV:
        pytest.skip(f"need {N_DEV} virtual devices, have {len(devs)}")
    return Mesh(np.array(devs[:N_DEV]), ("dp",))


def _shard_put(mesh, arr):
    return jax.device_put(arr, NamedSharding(mesh, P("dp")))


def test_all_to_all_exchange_routes_rows(mesh):
    local = 64
    cap = local * N_DEV
    rng = np.random.default_rng(0)
    data = rng.integers(-1000, 1000, cap).astype(np.int64)
    valid = rng.random(cap) > 0.1
    target = rng.integers(0, N_DEV, cap).astype(np.int32)

    def step(d, v, t):
        cols, n, ok = all_to_all_exchange(
            [ColV(d, v)], t, local, "dp", N_DEV)
        # returned per-shard: fixed capacity, count varies
        return cols[0].data, cols[0].validity, jnp.reshape(n, (1,)), ok

    fn = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P("dp"), P("dp"), P("dp")),
        out_specs=(P("dp"), P("dp"), P("dp"), P()),
        check_vma=False,
    ))
    out_d, out_v, counts, ok = fn(
        _shard_put(mesh, data), _shard_put(mesh, valid),
        _shard_put(mesh, target))
    assert bool(ok)
    counts = np.asarray(counts)
    out_d = np.asarray(out_d).reshape(N_DEV, cap)
    out_v = np.asarray(out_v).reshape(N_DEV, cap)
    # oracle: rows grouped by target shard
    for s in range(N_DEV):
        n_s = int(counts[s])
        want = sorted(
            (int(d), bool(v))
            for d, v, t in zip(data, valid, target) if t == s
        )
        got_rows = []
        for i in range(n_s):
            got_rows.append(
                (int(out_d[s, i]) if out_v[s, i] else 0, bool(out_v[s, i])))
        # null rows carry data=0 by construction; compare multisets
        want = sorted((d if v else 0, v) for d, v in want)
        assert sorted(got_rows) == want
        assert not out_v[s, n_s:].any()


def test_dist_groupby_matches_oracle(mesh):
    local = 128
    cap = local * N_DEV
    rng = np.random.default_rng(1)
    keys = rng.integers(0, 40, cap).astype(np.int32)
    knull = rng.random(cap) < 0.05
    vals = rng.integers(-50, 50, cap).astype(np.int64)
    vnull = rng.random(cap) < 0.1

    def step(kd, kv, vd, vv):
        ks, aggs, n = dist_groupby(
            [ColV(kd, kv)], [T.INT], [ColV(vd, vv), ColV(vd, vv)],
            ["sum", "count"], ["sum", "sum"], local, "dp", N_DEV)
        return (ks[0].data, ks[0].validity, aggs[0].data, aggs[0].validity,
                aggs[1].data, jnp.reshape(n, (1,)))

    fn = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P("dp"),) * 4,
        out_specs=(P("dp"),) * 5 + (P("dp"),),
        check_vma=False,
    ))
    kd, kv, sd, sv, cd, ns = fn(
        _shard_put(mesh, keys), _shard_put(mesh, ~knull),
        _shard_put(mesh, vals), _shard_put(mesh, ~vnull))
    # gather per-shard outputs
    got = {}
    kd = np.asarray(kd).reshape(N_DEV, -1)
    kv = np.asarray(kv).reshape(N_DEV, -1)
    sd = np.asarray(sd).reshape(N_DEV, -1)
    sv = np.asarray(sv).reshape(N_DEV, -1)
    cd = np.asarray(cd).reshape(N_DEV, -1)
    ns = np.asarray(ns)
    for s in range(N_DEV):
        for i in range(int(ns[s])):
            k = int(kd[s, i]) if kv[s, i] else None
            assert k not in got, f"group {k} appears on two shards"
            got[k] = (
                int(sd[s, i]) if sv[s, i] else None, int(cd[s, i]))
    # oracle
    want = {}
    for k, kn, v, vn in zip(keys, knull, vals, vnull):
        kk = None if kn else int(k)
        s, c = want.get(kk, (None, 0))
        if not vn:
            s = int(v) if s is None else s + int(v)
            c += 1
        want[kk] = (s, c)
    assert got == want


def test_dist_sort_global_order(mesh):
    local = 100
    cap = local * N_DEV
    rng = np.random.default_rng(2)
    keys = rng.integers(-500, 500, cap).astype(np.int64)
    knull = rng.random(cap) < 0.07
    payload = np.arange(cap, dtype=np.int64)

    from spark_rapids_tpu.ops.sort import SortOrder

    asc = SortOrder(True, None)

    def step(kd, kv, pd):
        cols, n = dist_sort(
            [ColV(kd, kv), ColV(pd, jnp.ones_like(kv))],
            [0], [T.LONG], [asc], local, "dp", N_DEV)
        return cols[0].data, cols[0].validity, cols[1].data, jnp.reshape(n, (1,))
    fn = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P("dp"), P("dp"), P("dp")),
        out_specs=(P("dp"), P("dp"), P("dp"), P("dp")),
        check_vma=False,
    ))
    kd, kv, pd, ns = fn(
        _shard_put(mesh, keys), _shard_put(mesh, ~knull),
        _shard_put(mesh, payload))
    kd = np.asarray(kd).reshape(N_DEV, -1)
    kv = np.asarray(kv).reshape(N_DEV, -1)
    ns = np.asarray(ns)
    flat = []
    for s in range(N_DEV):
        for i in range(int(ns[s])):
            flat.append(None if not kv[s, i] else int(kd[s, i]))
    assert len(flat) == cap
    # Spark ASC NULLS FIRST order, globally across shard boundaries
    want = sorted(
        (None if n else int(k) for k, n in zip(keys, knull)),
        key=lambda x: (x is not None, x if x is not None else 0),
    )
    assert flat == list(want)


def test_dist_hash_join_inner(mesh):
    local = 64
    cap = local * N_DEV
    rng = np.random.default_rng(3)
    lk = rng.integers(0, 60, cap).astype(np.int32)
    lv = np.arange(cap, dtype=np.int64)
    rk = rng.integers(0, 60, cap).astype(np.int32)
    rnull = rng.random(cap) < 0.05
    rv = np.arange(cap, dtype=np.int64) * 10
    out_cap = 4096

    def step(lkd, lvd, rkd, rkv, rvd):
        ones = jnp.ones(local, jnp.bool_)
        cols, n, ok = dist_hash_join(
            [ColV(lkd, ones), ColV(lvd, ones)], [0],
            [ColV(rkd, rkv), ColV(rvd, ones)], [0],
            [T.INT], local, local, "dp", N_DEV, out_cap)
        return cols[0].data, cols[1].data, cols[3].data, jnp.reshape(n, (1,)), ok

    fn = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P("dp"),) * 5,
        out_specs=(P("dp"),) * 3 + (P("dp"), P()),
        check_vma=False,
    ))
    jk, jl, jr, ns, ok = fn(
        _shard_put(mesh, lk), _shard_put(mesh, lv),
        _shard_put(mesh, rk), _shard_put(mesh, ~rnull),
        _shard_put(mesh, rv))
    assert bool(ok)
    jk = np.asarray(jk).reshape(N_DEV, -1)
    jl = np.asarray(jl).reshape(N_DEV, -1)
    jr = np.asarray(jr).reshape(N_DEV, -1)
    ns = np.asarray(ns)
    got = []
    for s in range(N_DEV):
        for i in range(int(ns[s])):
            got.append((int(jk[s, i]), int(jl[s, i]), int(jr[s, i])))
    want = []
    right_by_key = {}
    for k, nn, v in zip(rk, rnull, rv):
        if not nn:
            right_by_key.setdefault(int(k), []).append(int(v))
    for k, v in zip(lk, lv):
        for rvv in right_by_key.get(int(k), ()):
            want.append((int(k), int(v), rvv))
    assert sorted(got) == sorted(want)


def test_exchange_overflow_reports_not_ok(mesh):
    local = 32

    def step(d):
        ones = jnp.ones(local, jnp.bool_)
        # every row targets shard 0 with a tiny bucket: must overflow
        cols, n, ok = all_to_all_exchange(
            [ColV(d, ones)], jnp.zeros(local, jnp.int32), local,
            "dp", N_DEV, bucket_cap=4)
        return jnp.reshape(n, (1,)), ok

    fn = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P("dp"),),
        out_specs=(P("dp"), P()), check_vma=False,
    ))
    cap = local * N_DEV
    _, ok = fn(_shard_put(mesh, np.arange(cap, dtype=np.int64)))
    assert not bool(ok)
