"""AQE-lite: adaptive exchange reads from materialized partition stats.

Reference analog: GpuCustomShuffleReaderExec.scala + ShuffledBatchRDD's
coalesced/skew partition specs (:31-157) and OptimizeSkewedJoin. Differential
contract: the adaptive plan returns exactly what the static plan (and the
CPU oracle) returns, while the specs show coalescing/splitting happened.
"""
import random

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import ColumnarBatch, schema_of
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.exec import InMemoryScanExec, TpuHashAggregateExec
from spark_rapids_tpu.exec.exchange import (
    TpuShuffleExchangeExec,
    plan_aqe_coalesce,
    plan_aqe_join_pair,
)
from spark_rapids_tpu.exec.join import TpuShuffledHashJoinExec
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr.expressions import col
from spark_rapids_tpu.shuffle.partition import HashPartitioning

pytestmark = pytest.mark.cpu_only


def _conf(**extra):
    base = {"spark.rapids.tpu.shuffle.mode": "host",
            "spark.rapids.tpu.sql.adaptive.targetPartitionRows": 64}
    base.update({k: v for k, v in extra.items()})
    return RapidsConf(base)


def _skewed_batch(n=2000, nkeys=50, skew_key=7, skew_frac=0.8, seed=3):
    rng = random.Random(seed)
    ks, vs = [], []
    for i in range(n):
        if rng.random() < skew_frac:
            ks.append(skew_key)
        else:
            ks.append(rng.randrange(nkeys))
        vs.append(rng.randrange(-100, 100))
    schema = schema_of(k=T.INT, v=T.LONG)
    return ColumnarBatch.from_pydict({"k": ks, "v": vs}, schema), ks, vs, schema


def test_coalesce_small_partitions():
    conf = _conf()
    batch, ks, vs, schema = _skewed_batch(n=300, skew_frac=0.0)
    scan = InMemoryScanExec(conf, [[batch]], schema)
    ex = TpuShuffleExchangeExec(conf, scan, HashPartitioning([0], 16))
    read = plan_aqe_coalesce(conf, ex)
    # 300 rows over 16 partitions at target 64 -> far fewer read tasks
    assert read.num_partitions < 16
    rows = []
    for p in range(read.num_partitions):
        for b in read.execute_partition(p):
            rows.extend(b.to_rows())
    assert sorted(rows) == sorted(zip(ks, vs))


def test_skewed_join_splits_probe():
    conf = _conf()
    fact, ks, vs, schema = _skewed_batch(n=2000, skew_frac=0.8)
    dschema = schema_of(dk=T.INT, dv=T.LONG)
    dim = ColumnarBatch.from_pydict(
        {"dk": list(range(50)), "dv": [i * 10 for i in range(50)]}, dschema)

    P = 8
    lex = TpuShuffleExchangeExec(
        conf, InMemoryScanExec(conf, [[fact]], schema),
        HashPartitioning([0], P))
    rex = TpuShuffleExchangeExec(
        conf, InMemoryScanExec(conf, [[dim]], dschema),
        HashPartitioning([0], P))
    lread, rread = plan_aqe_join_pair(conf, lex, rex, probe_left=True)
    # the skewed probe partition must have been split into slices
    assert any(s[0] == "slice" for s in lread.specs), lread.specs
    assert lread.num_partitions == rread.num_partitions

    join = TpuShuffledHashJoinExec(
        conf, lread, rread, [col("k")], [col("dk")], "inner",
        partitioned=True)
    rows = []
    for p in range(join.num_partitions):
        for b in join.execute_partition(p):
            rows.extend(b.to_rows())
    dv = {i: i * 10 for i in range(50)}
    exp = sorted((k, v, k, dv[k]) for k, v in zip(ks, vs))
    assert sorted(rows) == exp


@pytest.mark.parametrize("jt", ["left", "semi", "anti"])
def test_skewed_join_types(jt):
    conf = _conf()
    fact, ks, vs, schema = _skewed_batch(n=800, skew_frac=0.7, seed=11)
    dschema = schema_of(dk=T.INT, dv=T.LONG)
    # dim covers only even keys: exercises unmatched probe rows
    dkeys = [i for i in range(50) if i % 2 == 0]
    dim = ColumnarBatch.from_pydict(
        {"dk": dkeys, "dv": [i * 10 for i in dkeys]}, dschema)
    P = 4
    lex = TpuShuffleExchangeExec(
        conf, InMemoryScanExec(conf, [[fact]], schema),
        HashPartitioning([0], P))
    rex = TpuShuffleExchangeExec(
        conf, InMemoryScanExec(conf, [[dim]], dschema),
        HashPartitioning([0], P))
    lread, rread = plan_aqe_join_pair(conf, lex, rex, probe_left=True)
    join = TpuShuffledHashJoinExec(
        conf, lread, rread, [col("k")], [col("dk")], jt, partitioned=True)
    rows = []
    for p in range(join.num_partitions):
        for b in join.execute_partition(p):
            rows.extend(b.to_rows())
    dv = {k: k * 10 for k in dkeys}
    if jt == "left":
        exp = sorted(
            (k, v, k if k in dv else None, dv.get(k))
            for k, v in zip(ks, vs))
    elif jt == "semi":
        exp = sorted((k, v) for k, v in zip(ks, vs) if k in dv)
    else:
        exp = sorted((k, v) for k, v in zip(ks, vs) if k not in dv)
    assert sorted(rows) == exp


def test_planner_inserts_aqe_for_aggregate():
    """Through the session/planner path: the adaptive read appears in the
    plan and the result matches the static plan."""
    from spark_rapids_tpu.sql import TpuSession

    rng = random.Random(21)
    rows = [(rng.randrange(20), rng.randrange(1000)) for _ in range(500)]
    schema = schema_of(k=T.INT, v=T.LONG)

    def run(aqe: bool):
        sess = TpuSession({
            "spark.rapids.tpu.shuffle.mode": "host",
            "spark.rapids.tpu.sql.adaptive.enabled": aqe,
            "spark.rapids.tpu.sql.shuffle.partitions": 8,
        })
        df = sess.create_dataframe(
            {"k": [r[0] for r in rows], "v": [r[1] for r in rows]}, schema,
            num_partitions=4)
        out = (df.group_by("k")
               .agg(A.agg(A.Sum(col("v")), "s"), A.agg(A.Count(None), "c"))
               .collect())
        return sess, sorted(out)

    s1, with_aqe = run(True)
    s2, without = run(False)
    assert with_aqe == without
    plan = s1.last_executed_plan
    assert plan is not None and "AQE" in plan.tree_string()
    assert "AQE" not in s2.last_executed_plan.tree_string()
