"""Static plan analyzer (plugin/plananalysis.py) unit + behavior tests.

The harness-wide cross-check (harness.assert_tpu_and_cpu_equal runs with
sql.analysis.crossCheck.enabled for EVERY differential test) covers the
three forecast-vs-reality invariants across the whole tier-1 suite; this
file pins the analyzer's own semantics: the nullability lattice, the
validity-elision differential, the OOM-warning path, recompile-storm
detection, and the zero-column-batch capacity regression.
"""
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(__file__))

from harness import assert_tpu_and_cpu_equal, compare_rows  # noqa: E402

from spark_rapids_tpu import types as T  # noqa: E402
from spark_rapids_tpu.columnar.batch import (  # noqa: E402
    ColumnarBatch,
    batch_from_rows,
    schema_of,
)
from spark_rapids_tpu.expr import aggregates as A  # noqa: E402
from spark_rapids_tpu.expr import expressions as E  # noqa: E402
from spark_rapids_tpu.plugin import plananalysis as PA  # noqa: E402
from spark_rapids_tpu.sql import TpuSession  # noqa: E402
from spark_rapids_tpu.types import StructField, StructType  # noqa: E402


def _analyze(df):
    from spark_rapids_tpu.sql.session import _lower

    return PA.analyze_plan(_lower(df.node, df.session.conf),
                           df.session.conf)


# ---------------------------------------------------------------------------
# Nullability lattice units
# ---------------------------------------------------------------------------
class TestNullabilityLattice:
    def _ref(self, i, dt=T.LONG, nullable=True):
        return E.BoundReference(i, dt, nullable)

    def test_literals(self):
        assert PA.expr_nullability(E.lit(5), []) == PA.NON_NULL
        assert PA.expr_nullability(
            E.Literal(None, T.LONG), []) == PA.ALL_NULL

    def test_bound_reference_reads_input_state(self):
        r = self._ref(0)
        assert PA.expr_nullability(r, [PA.NON_NULL]) == PA.NON_NULL
        assert PA.expr_nullability(r, [PA.MAYBE_NULL]) == PA.MAYBE_NULL
        assert PA.expr_nullability(r, [PA.ALL_NULL]) == PA.ALL_NULL

    def test_isnull_isnotnull_always_non_null(self):
        r = self._ref(0)
        for cls in (E.IsNull, E.IsNotNull):
            assert PA.expr_nullability(
                cls(r), [PA.ALL_NULL]) == PA.NON_NULL

    def test_coalesce_narrowing(self):
        r = self._ref(0)
        # a non-null fallback makes the whole coalesce NON_NULL
        c = E.Coalesce((r, E.lit(0)))
        assert PA.expr_nullability(c, [PA.MAYBE_NULL]) == PA.NON_NULL
        # all-nullable branches stay maybe
        c2 = E.Coalesce((r, self._ref(1)))
        assert PA.expr_nullability(
            c2, [PA.MAYBE_NULL, PA.MAYBE_NULL]) == PA.MAYBE_NULL
        # every branch a null literal: provably ALL_NULL
        c3 = E.Coalesce((E.Literal(None, T.LONG), E.Literal(None, T.LONG)))
        assert PA.expr_nullability(c3, []) == PA.ALL_NULL

    def test_arithmetic_meet(self):
        a, b = self._ref(0), self._ref(1)
        add = E.Add(a, b)
        assert PA.expr_nullability(
            add, [PA.NON_NULL, PA.NON_NULL]) == PA.NON_NULL
        assert PA.expr_nullability(
            add, [PA.NON_NULL, PA.MAYBE_NULL]) == PA.MAYBE_NULL
        assert PA.expr_nullability(
            add, [PA.ALL_NULL, PA.NON_NULL]) == PA.ALL_NULL

    def test_divide_nulls_on_zero_divisor(self):
        a, b = self._ref(0), self._ref(1)
        assert PA.expr_nullability(
            E.Divide(a, b), [PA.NON_NULL, PA.NON_NULL]) == PA.MAYBE_NULL
        # literal non-zero divisor cannot introduce a null
        assert PA.expr_nullability(
            E.Divide(a, E.lit(2)), [PA.NON_NULL]) == PA.NON_NULL

    def test_filter_isnull_narrowing(self):
        cond = E.And(E.IsNotNull(self._ref(0)),
                     E.GreaterThan(self._ref(1), E.lit(5)))
        out = PA.narrow_by_predicate(
            [PA.MAYBE_NULL, PA.MAYBE_NULL, PA.MAYBE_NULL], cond)
        # IsNotNull narrows col 0; the comparison's 3VL NULL verdict (a
        # filtered row) narrows col 1; col 2 untouched
        assert out == [PA.NON_NULL, PA.NON_NULL, PA.MAYBE_NULL]

    def test_outer_join_reintroduces_maybe_null(self):
        sess = TpuSession({})
        left = sess.create_dataframe(
            {"k": [1, 2], "lv": [10, 20]}, schema_of(k=T.LONG, lv=T.LONG))
        right = sess.create_dataframe(
            {"k": [1, 3], "rv": [100, 300]}, schema_of(k=T.LONG, rv=T.LONG))
        joined = left.join(right, "k", how="left")
        analysis = _analyze(joined)

        def find(rep, name):
            if rep.name == name:
                return rep
            for c in rep.children:
                r = find(c, name)
                if r is not None:
                    return r
            return None

        jr = find(analysis.root, "CpuJoinExec")
        assert jr is not None
        by_name = {c.name: c.null for c in jr.layout}
        # right-side columns are MAYBE_NULL after a left join even though
        # the inputs carry values everywhere
        assert by_name["rv"] == PA.MAYBE_NULL

    def test_aggregate_nullability(self):
        cnt = A.Count()
        assert PA.agg_nullability(cnt, PA.MAYBE_NULL, grouped=True) \
            == PA.NON_NULL
        s = A.Sum(E.col("x"))
        assert PA.agg_nullability(s, PA.NON_NULL, grouped=True) \
            == PA.NON_NULL
        # a grand aggregate can see an empty input -> NULL sum
        assert PA.agg_nullability(s, PA.NON_NULL, grouped=False) \
            == PA.MAYBE_NULL
        assert PA.agg_nullability(s, PA.MAYBE_NULL, grouped=True) \
            == PA.MAYBE_NULL


# ---------------------------------------------------------------------------
# Analyzer end-to-end: bounded plans, forecasts, warnings
# ---------------------------------------------------------------------------
class TestAnalyzerReports:
    def test_bounded_scan_filter_agg(self):
        sess = TpuSession(
            {"spark.rapids.tpu.sql.analysis.crossCheck.enabled": True})
        df = sess.create_dataframe(
            {"k": [1, 2, 1], "v": [10, 20, 30]}, schema_of(k=T.INT, v=T.LONG))
        q = df.where(E.GreaterThan(E.col("v"), E.lit(5))) \
            .group_by("k").agg(A.agg(A.Sum(E.col("v")), "s"))
        q.collect()
        an = sess.last_analysis
        assert an is not None and an.bounded
        assert sum(an.site_forecast.values()) >= 1
        assert an.peak_hbm is not None and an.peak_hbm > 0
        # the report names layouts and renders without error
        text = an.render()
        assert "TpuHashAggregateExec" in text
        assert "InMemoryScanExec" in text

    def test_explain_includes_analysis(self):
        sess = TpuSession({})
        df = sess.range(100)
        out = df.select(E.Alias(E.Add(E.col("id"), E.lit(1)), "x")).explain()
        assert "Static Plan Analysis" in out
        assert "forecast compile signatures" in out
        assert "NON_NULL" in out  # range ids are provably non-null

    def test_oom_warning_fires_without_device_allocation(self):
        """Acceptance: an over-budget plan warns at explain() time with
        zero device allocations (the in-memory rows stay host-side)."""
        sess = TpuSession(
            {"spark.rapids.tpu.memory.hbm.budgetBytes": 1024})
        n = 4096
        df = sess.create_dataframe(
            {"a": list(range(n)), "b": [float(i) for i in range(n)]},
            schema_of(a=T.LONG, b=T.DOUBLE))
        out = df.select("a", "b").explain()
        assert "exceeds the device budget" in out
        assert "spill/OOM at capacity 4096" in out

    def test_recompile_storm_named_before_execution(self):
        """Acceptance: a deliberately shape-polymorphic plan (a union of
        many distinct capacity buckets under one projection) is flagged
        with the site and the expected signature count at explain()."""
        sess = TpuSession(
            {"spark.rapids.tpu.sql.analysis.recompileStorm.threshold": 4})
        schema = schema_of(x=T.LONG)
        sizes = [100, 200, 400, 800, 1600]  # 5 distinct capacity buckets
        dfs = [
            sess.create_dataframe({"x": list(range(s))}, schema)
            for s in sizes
        ]
        u = dfs[0]
        for d in dfs[1:]:
            u = u.union(d)
        out = u.select(E.Alias(E.Add(E.col("x"), E.lit(1)), "y")).explain()
        assert "recompile storm: site fused_chain expects 5" in out

    def test_forecast_matches_actual_for_polymorphic_plan(self):
        """The storm forecast is REAL: executing the polymorphic plan
        compiles exactly as many fused_chain programs as forecast."""
        from spark_rapids_tpu.exec.base import COMPILE_COUNTER

        sess = TpuSession(
            {"spark.rapids.tpu.sql.analysis.crossCheck.enabled": True})
        schema = schema_of(x=T.LONG)
        sizes = [129, 257, 513]
        dfs = [
            sess.create_dataframe({"x": list(range(s))}, schema)
            for s in sizes
        ]
        u = dfs[0]
        for d in dfs[1:]:
            u = u.union(d)
        q = u.select(E.Alias(E.Add(E.col("x"), E.lit(1)), "y"))
        before = dict(COMPILE_COUNTER.by_site)
        rows = q.collect()
        assert len(rows) == sum(sizes)
        an = sess.last_analysis
        assert an.bounded
        assert an.site_forecast.get("fused_chain") == 3
        actual = (COMPILE_COUNTER.by_site.get("fused_chain", 0)
                  - before.get("fused_chain", 0))
        assert actual <= 3

    def test_unbounded_plans_say_so(self):
        sess = TpuSession({})
        left = sess.create_dataframe(
            {"k": [1, 2], "lv": [10, 20]}, schema_of(k=T.LONG, lv=T.LONG))
        right = sess.create_dataframe(
            {"k": [1, 2], "rv": [7, 8]}, schema_of(k=T.LONG, rv=T.LONG))
        an = _analyze(left.join(right, "k"))
        assert not an.bounded
        assert "not statically bounded" in an.render()


# ---------------------------------------------------------------------------
# Nullability elision: differential identity + actual engagement
# ---------------------------------------------------------------------------
class TestNullElision:
    def _run(self, elide: bool):
        sess = TpuSession({
            "spark.rapids.tpu.sql.analysis.nullElision.enabled": elide,
        })
        df = sess.range(0, 1000)
        q = df.select(
            E.Alias(E.Multiply(E.col("id"), E.lit(3)), "x"),
            E.Alias(E.Cast(E.col("id"), T.DOUBLE), "f"),
        ).where(E.GreaterThan(E.col("x"), E.lit(100))) \
            .agg(A.agg(A.Sum(E.col("x")), "sx"),
                 A.agg(A.Average(E.col("f")), "af"))
        return q.collect()

    def test_elided_identical_to_mask_carrying(self):
        on = self._run(True)
        off = self._run(False)
        compare_rows(on, off, ignore_order=False)

    def test_entry_flags_respect_conf_and_schema(self):
        from spark_rapids_tpu.conf import RapidsConf

        schema = StructType((
            StructField("a", T.LONG, False),
            StructField("b", T.LONG, True),
        ))
        on = PA.entry_nonnull_flags(schema, RapidsConf({}))
        assert on == (True, False)
        off = PA.entry_nonnull_flags(schema, RapidsConf({
            "spark.rapids.tpu.sql.analysis.nullElision.enabled": False}))
        assert off == ()
        all_nullable = StructType((StructField("b", T.LONG, True),))
        assert PA.entry_nonnull_flags(all_nullable, RapidsConf({})) == ()

    def test_evaluate_projection_elided_path(self):
        """expr/eval.py's consumption of the lattice: the elided compiled
        path returns exactly what the mask-carrying path returns."""
        from spark_rapids_tpu.expr.eval import evaluate_projection

        schema = StructType((
            StructField("a", T.LONG, False),
            StructField("b", T.DOUBLE, True),
        ))
        batch = ColumnarBatch.from_pydict(
            {"a": [1, 2, 3], "b": [1.5, None, 2.5]}, schema)
        bound = [
            E.bind_references(E.Add(E.col("a"), E.lit(1)), schema),
            E.bind_references(E.Multiply(E.col("b"), E.col("a")), schema),
        ]
        from spark_rapids_tpu.conf import RapidsConf

        # no flags/conf -> mask-carrying path; a conf derives the flags
        # through entry_nonnull_flags and takes the elided path — and
        # disabling the conf forces the mask-carrying path back on
        plain = [c.to_pylist()
                 for c in evaluate_projection(bound, batch)]
        elided = [c.to_pylist()
                  for c in evaluate_projection(bound, batch,
                                               conf=RapidsConf({}))]
        off = [c.to_pylist()
               for c in evaluate_projection(bound, batch, conf=RapidsConf({
                   "spark.rapids.tpu.sql.analysis.nullElision.enabled":
                       False}))]
        explicit = [c.to_pylist()
                    for c in evaluate_projection(bound, batch,
                                                 nonnull=(True, False))]
        assert plain == elided == off == explicit \
            == [[2, 3, 4], [1.5, None, 7.5]]

    def test_harness_cross_check_runs_differential(self):
        """End-to-end through the harness: a range-sourced plan elides
        (range ids are declared non-null) and stays oracle-identical."""
        assert_tpu_and_cpu_equal(
            lambda s: s.range(0, 500).select(
                E.Alias(E.Add(E.col("id"), E.lit(7)), "y"))
            .where(E.LessThan(E.col("y"), E.lit(100))))


# ---------------------------------------------------------------------------
# Satellite: zero-column batch capacity regression (count(*) over a
# fully-pruned scan)
# ---------------------------------------------------------------------------
class TestZeroColumnCapacity:
    def test_batch_carries_capacity_without_columns(self):
        schema = StructType(())
        b = ColumnarBatch([], schema, 200)
        assert b.num_rows == 200
        assert b.capacity >= 200  # was 0 before the fix

    def test_batch_from_rows_keeps_rows_for_empty_schema(self):
        schema = StructType(())
        b = batch_from_rows([() for _ in range(200)], schema)
        assert b.num_rows == 200
        assert b.capacity >= 200

    def test_count_star_over_pruned_scan(self):
        n = 300  # > the 128 minimum bucket: a lost capacity truncates
        sess = TpuSession({"spark.rapids.tpu.sql.test.enabled": True})
        df = sess.from_rows([() for _ in range(n)], StructType(()))
        assert df.count() == n
        out = df.agg(A.agg(A.Count(), "c")).collect()
        assert out == [(n,)]

    def test_context_project_over_pruned_source(self):
        """A context-expression projection (monotonically_increasing_id)
        over a zero-column source must run at the source's REAL capacity,
        not the 128 fallback — 300 rows would otherwise alias."""
        n = 300
        sess = TpuSession({"spark.rapids.tpu.sql.test.enabled": True})
        df = sess.from_rows([() for _ in range(n)], StructType(()))
        rows = df.select(
            E.Alias(E.MonotonicallyIncreasingID(), "id")).collect()
        ids = [r[0] for r in rows]
        assert len(ids) == n and len(set(ids)) == n

    def test_count_star_after_column_pruning_projection(self):
        assert_tpu_and_cpu_equal(
            lambda s: s.create_dataframe(
                {"a": list(range(300)), "b": list(range(300))},
                schema_of(a=T.LONG, b=T.LONG),
            ).select().agg(A.agg(A.Count(), "c")))


# ---------------------------------------------------------------------------
# Satellite: from_host error context + choose_capacity routing
# ---------------------------------------------------------------------------
class TestChooseCapacity:
    def test_from_host_error_names_the_column(self):
        from spark_rapids_tpu.columnar.column import HostColumn

        h = HostColumn.from_pylist([1, 2, 3, 4, 5], T.LONG)
        with pytest.raises(ValueError, match=r"column 'payload'.*capacity 2"):
            h.to_device(capacity=2, name="payload")
        with pytest.raises(ValueError, match="choose_capacity"):
            h.to_device(capacity=2)

    def test_choose_capacity_matches_bucket_rules(self):
        from spark_rapids_tpu.columnar.column import choose_capacity
        from spark_rapids_tpu.utils.bucketing import bucket_rows

        for n in (0, 1, 127, 128, 129, 1000, 4096):
            assert choose_capacity(n) == bucket_rows(n)
        assert choose_capacity(3, 4) == bucket_rows(3, 4)
