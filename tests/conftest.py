"""Test bootstrap: force an 8-device virtual CPU mesh BEFORE jax initializes.

Multi-chip hardware is not available in CI; sharding/collective paths are
validated on a virtual device mesh exactly as the driver's dryrun does.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # override axon: the shell presets it
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# sitecustomize.py (axon TPU tunnel) imports jax at interpreter startup,
# before this conftest runs — the env var alone is too late. The config
# update below still wins as long as no backend has been initialized.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
