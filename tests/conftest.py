"""Test bootstrap: force an 8-device virtual CPU mesh BEFORE jax initializes.

Multi-chip hardware is not available in CI; sharding/collective paths are
validated on a virtual device mesh exactly as the driver's dryrun does.

ON-TPU MODE (reference: the GPU differential suites run on the real
device, SURVEY §4 tier 2/3): setting SRTPU_TEST_TPU=1 keeps the real
backend so the differential suites validate Spark-exactness ON the chip
(f32 accumulation, x64 emulation, axon fusion quirks) instead of only
against the CPU backend. Usage:
    SRTPU_TEST_TPU=1 python -m pytest tests/ -q -m "not cpu_only"
"""
import os
import sys

import pytest

ON_TPU = os.environ.get("SRTPU_TEST_TPU", "") == "1"

if not ON_TPU:
    os.environ["JAX_PLATFORMS"] = "cpu"  # override axon: the shell presets it
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

# sitecustomize.py (axon TPU tunnel) imports jax at interpreter startup,
# before this conftest runs — the env var alone is too late. The config
# update below still wins as long as no backend has been initialized.
import jax  # noqa: E402

if not ON_TPU:
    jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite is compile-dominated (~500 XLA
# programs); caching compiled executables across runs cuts the full-suite
# wall time (SURVEY §4 test-strategy analog of the reference's reuse of
# warmed Spark sessions across its pytest modules).
_cache_dir = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    ".jax_compile_cache")
try:
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
except Exception:
    pass  # older jax without these flags

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "cpu_only: needs the multi-device virtual CPU mesh; "
        "skipped when SRTPU_TEST_TPU=1 runs the suite on the real chip")
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 budgeted run "
        "(-m 'not slow'); dedicated CI jobs run these files unfiltered")


@pytest.fixture(autouse=True)
def _hbm_leak_guard():
    """Harness teardown twin of the HBM ledger's leak sentinel: any test
    whose queries left sentinel-flagged buffers live fails HERE, by
    name, instead of poisoning a later test's catalog state. Peeks only
    (no catalog is conjured for tests that never touched memory); a test
    that DELIBERATELY leaks must reset the BufferCatalog itself."""
    yield
    from spark_rapids_tpu.memory.catalog import BufferCatalog

    cat = BufferCatalog._instance
    if cat is None:
        return
    leaked = cat.ledger.stats()["leaked_live"]
    if leaked:
        leaks = cat.ledger.live_leaks()
        BufferCatalog.reset()  # don't cascade into the next test
        raise AssertionError(
            f"HBM leak sentinel: {leaked} buffer(s) outlived their "
            "owning query: " + ", ".join(
                f"{r.get('op') or '(unattributed)'} {r['bytes']}B "
                f"from {r['site']} (query {r.get('query_id')})"
                for r in leaks[:5]))


def pytest_collection_modifyitems(config, items):
    if not ON_TPU:
        return
    skip = pytest.mark.skip(reason="needs 8-device CPU mesh (on-TPU run)")
    for item in items:
        if "cpu_only" in item.keywords or item.fspath.basename in (
            "test_mesh.py", "test_multichip.py", "test_shuffle.py",
        ):
            item.add_marker(skip)
