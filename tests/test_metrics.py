"""Profiler + fused aggregate hot-path tests.

Covers the round-6 tentpole:
  * differential tests diffing the FUSED multi-column bucket reduce
    against the per-column baseline (FORCE_PER_COLUMN) on BOTH lowerings
    (scatter on CPU, FORCE_MATMUL for the MXU limb path) — int64
    wraparound, all-null columns, the float hi/lo split, and mixed
    sum/count/min/max plans;
  * device-sync timing + bytes-touched accounting via
    TpuSession.explain_metrics() for aggregate and project execs;
  * the recompile-regression guard: a multi-batch fused aggregate plan
    compiles ONCE (compile cache-miss counter == expected) and re-running
    the same plan shape compiles nothing.
"""
import numpy as np
import pytest

import spark_rapids_tpu  # noqa: F401  (x64 enable)
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, schema_of
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.exec import (
    InMemoryScanExec,
    TpuFilterExec,
    TpuHashAggregateExec,
    TpuProjectExec,
)
from spark_rapids_tpu.exec import base as exec_base
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.expressions import col, lit
from spark_rapids_tpu.ops import bucket_reduce as BR
from spark_rapids_tpu.sql import TpuSession


# ---------------------------------------------------------------------------
# fused vs per-column bucket reduce (all three lowerings)
# ---------------------------------------------------------------------------
def _strategy_of(lowering):
    """The explicit strategy to pass for a fixture param (sort is selected
    via the strategy argument — the round-7 lowering; matmul still rides
    the FORCE_MATMUL hook, which outranks any passed strategy)."""
    return "SORT" if lowering == "sort" else None


@pytest.fixture(params=["scatter", "matmul", "sort"])
def lowering(request):
    """Run the differential against ALL THREE lowerings: the CPU scatter
    family, the forced MXU limb-matmul path, and the sort+prefix-diff
    bandwidth path (round-7 sql.agg.strategy=SORT)."""
    prev = BR.FORCE_MATMUL
    BR.FORCE_MATMUL = request.param == "matmul"
    try:
        yield request.param
    finally:
        BR.FORCE_MATMUL = prev


def _diff_bucket_reduce(seg, B, int_cols, count_cols, float_cols,
                        strategy=None):
    fused = BR.bucket_reduce(seg, B, int_cols, count_cols, float_cols,
                             strategy=strategy)
    prev = BR.FORCE_PER_COLUMN
    BR.FORCE_PER_COLUMN = True
    try:
        percol = BR.bucket_reduce(seg, B, int_cols, count_cols, float_cols,
                                  strategy=strategy)
    finally:
        BR.FORCE_PER_COLUMN = prev
    for fi, pi in zip(fused[0], percol[0]):
        np.testing.assert_array_equal(np.asarray(fi), np.asarray(pi))
    for fc, pc in zip(fused[1], percol[1]):
        np.testing.assert_array_equal(np.asarray(fc), np.asarray(pc))
    for ff, pf in zip(fused[2], percol[2]):
        np.testing.assert_allclose(
            np.asarray(ff), np.asarray(pf), rtol=1e-12, atol=0.0)
    return fused


def test_fused_reduce_int64_wraparound(lowering):
    """Java-wraparound int64 sums must survive the multi-column fusion
    bit-exactly (limb accumulation wraps mod 2^64 like native adds)."""
    n = 512
    rng = np.random.default_rng(3)
    seg = jnp.asarray((rng.integers(0, 7, n)).astype(np.int32))
    big = np.full(n, (1 << 62) + 12345, np.int64)
    mixed = rng.integers(-(2**62), 2**62, n).astype(np.int64)
    valid = jnp.ones(n, jnp.bool_)
    out = _diff_bucket_reduce(
        seg, 8,
        [(jnp.asarray(big), valid), (jnp.asarray(mixed), valid)],
        [valid], [], strategy=_strategy_of(lowering))
    # cross-check column 0 against numpy's wrapping sum per bucket
    segs = np.asarray(seg)
    for b in range(7):
        want = np.int64(0)
        with np.errstate(over="ignore"):
            for v in big[segs == b]:
                want = np.int64(want + v)  # wraps
        assert int(np.asarray(out[0][0])[b]) == int(want)


def test_fused_reduce_all_null_columns(lowering):
    n = 256
    seg = jnp.asarray(np.arange(n, dtype=np.int32) % 5)
    none_valid = jnp.zeros(n, jnp.bool_)
    some_valid = jnp.asarray(np.arange(n) % 3 == 0)
    data_i = jnp.asarray(np.arange(n, dtype=np.int64) * 7 - 100)
    data_f = jnp.asarray(np.linspace(-4.0, 9.0, n))
    out = _diff_bucket_reduce(
        seg, 8,
        [(data_i, none_valid), (data_i, some_valid)],
        [none_valid, some_valid],
        [(data_f, none_valid), (data_f, some_valid)],
        strategy=_strategy_of(lowering))
    assert np.all(np.asarray(out[0][0]) == 0)  # all-null sums to 0
    assert np.all(np.asarray(out[1][0]) == 0)  # all-null counts to 0
    assert np.all(np.asarray(out[2][0]) == 0.0)


def test_fused_reduce_float_hilo_split(lowering):
    """Doubles whose mantissa exceeds f32 need the hi/lo split; values
    beyond f32 range take the overflow correction. Both must be identical
    fused vs per-column."""
    n = 384
    rng = np.random.default_rng(11)
    seg = jnp.asarray((rng.integers(0, 4, n)).astype(np.int32))
    precise = rng.normal(size=n) * 1e9 + rng.normal(size=n) * 1e-9
    huge = np.where(np.arange(n) % 97 == 0, 1e300, precise)
    valid = jnp.asarray(rng.random(n) < 0.9)
    _diff_bucket_reduce(
        seg, 4, [], [],
        [(jnp.asarray(precise), valid), (jnp.asarray(huge), valid)],
        strategy=_strategy_of(lowering))


def test_fused_minmax_family_matches_per_column(lowering):
    n = 300
    rng = np.random.default_rng(23)
    seg = jnp.asarray((rng.integers(0, 6, n)).astype(np.int32))
    cols = [jnp.asarray(rng.integers(-1000, 1000, n).astype(np.int64))
            for _ in range(3)]
    for op in ("min", "max"):
        fused = BR.bucket_min_max(seg, 6, op, cols)
        prev = BR.FORCE_PER_COLUMN
        BR.FORCE_PER_COLUMN = True
        try:
            percol = BR.bucket_min_max(seg, 6, op, cols)
        finally:
            BR.FORCE_PER_COLUMN = prev
        for f, p in zip(fused, percol):
            np.testing.assert_array_equal(np.asarray(f), np.asarray(p))


def _mixed_plan_exec(conf, batches, schema):
    scan = InMemoryScanExec(conf, [batches], schema)
    filt = TpuFilterExec(conf, E.GreaterThanOrEqual(col("a"), lit(-80)), scan)
    proj = TpuProjectExec(
        conf, [col("k"), E.Alias(E.Multiply(col("a"), lit(3)), "a3"),
               col("b")], filt)
    return TpuHashAggregateExec(
        conf, [col("k")],
        [A.agg(A.Sum(col("a3")), "s"), A.agg(A.Count(col("b")), "c"),
         A.agg(A.Min(col("a3")), "mn"), A.agg(A.Max(col("a3")), "mx"),
         A.agg(A.Min(col("b")), "fmn"), A.agg(A.Max(col("b")), "fmx"),
         A.agg(A.Count(None), "cs")], proj)


def _mk_batches(schema, nb=3, n=50):
    rng = np.random.default_rng(7)
    out = []
    for i in range(nb):
        out.append(ColumnarBatch.from_pydict({
            "k": [int(x) for x in rng.integers(0, 6, n)],
            "a": [int(x) for x in rng.integers(-100, 100, n)],
            "b": [None if rng.random() < 0.15 else float(rng.normal())
                  for _ in range(n)],
        }, schema))
    return out


def _cmp_rows(lhs, rhs):
    assert len(lhs) == len(rhs)
    for a, b in zip(sorted(lhs), sorted(rhs)):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            if isinstance(x, float) and x == x and y == y:
                assert abs(x - y) < 1e-9, (a, b)
            else:
                assert x == y or (x != x and y != y), (a, b)


def test_mixed_plan_fused_vs_per_column(lowering):
    """Exec-level differential for a mixed sum/count/min/max plan: the
    fused multi-column kernel vs the per-column baseline, same results on
    all three lowerings (and fused single-program plan vs per-batch
    paths). The sort lowering is selected the way users select it — the
    sql.agg.strategy conf."""
    schema = schema_of(k=T.INT, a=T.LONG, b=T.DOUBLE)
    batches = _mk_batches(schema)
    strategy = "SORT" if lowering == "sort" else "AUTO"
    on = RapidsConf({"spark.rapids.tpu.sql.agg.fusedPlan": "ON",
                     "spark.rapids.tpu.sql.agg.strategy": strategy})
    off = RapidsConf({"spark.rapids.tpu.sql.agg.fusedPlan": "OFF",
                      "spark.rapids.tpu.sql.agg.strategy": strategy})
    fused_rows = _mixed_plan_exec(on, batches, schema).collect()
    prev = BR.FORCE_PER_COLUMN
    BR.FORCE_PER_COLUMN = True
    try:
        percol_rows = _mixed_plan_exec(off, batches, schema).collect()
    finally:
        BR.FORCE_PER_COLUMN = prev
    _cmp_rows(fused_rows, percol_rows)


def test_sort_lowering_dead_and_out_of_range_rows(lowering):
    """Out-of-range segment ids — padding rows at id B, dead rows past it,
    and NEGATIVE ids — must drop out of every reduction under all three
    lowerings (the sort lowering's boundary search must exclude both
    tails)."""
    n = 257  # off the block/tile sizes on purpose
    rng = np.random.default_rng(31)
    seg_np = rng.integers(-3, 12, n).astype(np.int32)  # B=8: both tails
    seg = jnp.asarray(seg_np)
    data = rng.integers(-(2**62), 2**62, n).astype(np.int64)
    valid = jnp.asarray(rng.random(n) < 0.7)
    out = _diff_bucket_reduce(
        seg, 8, [(jnp.asarray(data), valid)], [valid], [],
        strategy=_strategy_of(lowering))
    v = np.asarray(valid)
    for b in range(8):
        m = (seg_np == b) & v
        want = np.int64(0)
        with np.errstate(over="ignore"):
            for x in data[m]:
                want = np.int64(want + x)
        assert int(np.asarray(out[0][0])[b]) == int(want)
        assert int(np.asarray(out[1][0])[b]) == int(m.sum())


def test_three_lowerings_bit_identical_int_sums():
    """Acceptance pin: MATMUL, SCATTER and SORT produce BIT-identical
    integer sums and counts over the same inputs (incl. wraparound)."""
    n = 600
    rng = np.random.default_rng(43)
    seg = jnp.asarray(rng.integers(0, 16, n).astype(np.int32))
    cols = [(jnp.asarray(rng.integers(-(2**62), 2**62, n).astype(np.int64)),
             jnp.asarray(rng.random(n) < 0.8)) for _ in range(3)]
    cnts = [v for _, v in cols]
    outs = {}
    for strat in ("SCATTER", "SORT"):
        outs[strat] = BR.bucket_reduce(seg, 16, cols, cnts, [],
                                       strategy=strat)
    prev = BR.FORCE_MATMUL
    BR.FORCE_MATMUL = True
    try:
        outs["MATMUL"] = BR.bucket_reduce(seg, 16, cols, cnts, [])
    finally:
        BR.FORCE_MATMUL = prev
    for strat in ("SORT", "MATMUL"):
        for i in range(3):
            np.testing.assert_array_equal(
                np.asarray(outs["SCATTER"][0][i]),
                np.asarray(outs[strat][0][i]))
            np.testing.assert_array_equal(
                np.asarray(outs["SCATTER"][1][i]),
                np.asarray(outs[strat][1][i]))


# ---------------------------------------------------------------------------
# strategy chooser: conf plumbing, visibility, cost-model branches
# ---------------------------------------------------------------------------
def test_strategy_chooser_forced_and_auto_branches():
    from spark_rapids_tpu.exec.aggregate import choose_agg_strategy

    ops = ("sum", "count", "count_star")
    exprs = (E.BoundReference(1, T.LONG, True),
             E.BoundReference(1, T.LONG, True), None)
    keys = (T.INT,)
    forced = RapidsConf({"spark.rapids.tpu.sql.agg.strategy": "SORT"})
    s, why = choose_agg_strategy(forced, 1 << 20, ops, exprs, keys)
    assert s == "SORT" and "forced" in why
    auto = RapidsConf({})
    s, why = choose_agg_strategy(auto, 1 << 20, ops, exprs, keys,
                                 backend="cpu")
    assert s == "SCATTER" and "CPU backend" in why
    # on an accelerator backend AUTO compares the derated-peak models;
    # a wide aggregate (many limb columns) pushes the matmul cost up
    # until the bandwidth-sized tiled radix lowering wins
    wide_ops = tuple(["sum"] * 40)
    wide_exprs = tuple(E.BoundReference(i, T.LONG, True) for i in range(40))
    s_wide, why_wide = choose_agg_strategy(
        auto, 1 << 24, wide_ops, wide_exprs, keys, backend="tpu")
    s_narrow, _ = choose_agg_strategy(
        auto, 1 << 24, ("count_star",), (None,), keys, backend="tpu")
    assert s_wide == "RADIX", why_wide
    assert s_narrow == "MATMUL"
    assert "est matmul" in why_wide and "radix" in why_wide
    # exact float sums (variableFloatAgg off) keep RADIX out of AUTO:
    # the bandwidth pick degrades to SORT, whose float sums stay on the
    # order-preserving scatter path
    fwide_ops = tuple(["sum"] * 40)
    fwide_exprs = tuple(E.BoundReference(i, T.DOUBLE, True)
                        for i in range(40))
    s_f, why_f = choose_agg_strategy(
        auto, 1 << 24, fwide_ops, fwide_exprs, keys, backend="tpu")
    assert s_f == "SORT", why_f
    # CPU AUTO flips to RADIX at the byte-amplification capacity
    # threshold (the merge gate is XLA bytes, not shared-box wall clock)
    s_big, why_big = choose_agg_strategy(
        auto, 1 << 24, ops, exprs, keys, backend="cpu")
    assert s_big == "RADIX" and "amplif" in why_big
    # the chooser reads the conf-declared roofline peaks (one peak
    # source with the roofline report): a huge declared MXU peak makes
    # the matmul model win the same wide shape RADIX just won
    fast_mxu = RapidsConf(
        {"spark.rapids.tpu.roofline.peakTflops": 197000.0})
    s_conf, why_conf = choose_agg_strategy(
        fast_mxu, 1 << 24, wide_ops, wide_exprs, keys, backend="tpu")
    assert s_conf == "MATMUL", why_conf
    assert "197000TF" in why_conf


def test_strategy_visible_in_events_and_explain_metrics():
    sess = TpuSession({"spark.rapids.tpu.eventLog.enabled": True,
                       "spark.rapids.tpu.sql.agg.strategy": "SORT"})
    n = 64
    data = {"k": [i % 4 for i in range(n)], "v": list(range(n))}
    schema = schema_of(k=T.INT, v=T.LONG)
    rows = sess.create_dataframe(data, schema).group_by("k").agg(
        A.agg(A.Sum(col("v")), "s")).collect()
    assert sorted(rows) == sorted(
        (k, sum(v for i, v in enumerate(range(n)) if i % 4 == k))
        for k in range(4))
    evs = [r for r in sess.events.records()
           if r["event"] == "agg_strategy"]
    assert evs and evs[0]["strategy"] == "SORT"
    assert "forced" in evs[0]["reason"]
    assert "strategy=SORT" in sess.explain_metrics()
    # the analyzer's forecast note names the same strategy (explain)
    df = sess.create_dataframe(data, schema).group_by("k").agg(
        A.agg(A.Sum(col("v")), "s"))
    assert "agg strategy: SORT" in df.explain()
    sess.close()


def test_auto_strategy_resolution_does_not_double_compile():
    """Recompile guard for the chooser: AUTO resolves to ONE fixed
    strategy per plan shape, so the fused aggregate still compiles
    exactly once across batches and a rerun compiles nothing — the
    strategy is memoized per capacity, part of the cache key, and never
    data-dependent."""
    schema = schema_of(k=T.INT, a=T.LONG, b=T.DOUBLE)
    # a capacity bucket (256) no other test's plan uses: the guard below
    # must observe ITS OWN compile, not another test's warm cache
    batches = _mk_batches(schema, nb=4, n=200)
    conf = RapidsConf({"spark.rapids.tpu.sql.agg.fusedPlan": "ON",
                       "spark.rapids.tpu.sql.agg.strategy": "AUTO"})
    agg = _mixed_plan_exec(conf, batches, schema)
    before = exec_base.compile_miss_count()
    rows1 = agg.collect()
    assert exec_base.compile_miss_count() - before == 1
    again = _mixed_plan_exec(conf, batches, schema)
    before2 = exec_base.compile_miss_count()
    rows2 = again.collect()
    assert exec_base.compile_miss_count() == before2
    _cmp_rows(rows1, rows2)
    # and a SORT-forced plan is a DIFFERENT program (one fresh compile),
    # not a silent reuse of the scatter executable
    forced = RapidsConf({"spark.rapids.tpu.sql.agg.fusedPlan": "ON",
                         "spark.rapids.tpu.sql.agg.strategy": "SORT"})
    sorted_agg = _mixed_plan_exec(forced, batches, schema)
    before3 = exec_base.compile_miss_count()
    rows3 = sorted_agg.collect()
    assert exec_base.compile_miss_count() - before3 == 1
    _cmp_rows(rows1, rows3)


# ---------------------------------------------------------------------------
# explain_metrics: device-sync timing + bytes accounting
# ---------------------------------------------------------------------------
def _find_exec(plan, cls):
    if isinstance(plan, cls):
        return plan
    for c in getattr(plan, "children", ()):
        r = _find_exec(c, cls)
        if r is not None:
            return r
    return None


def test_explain_metrics_device_sync_and_bytes():
    sess = TpuSession({
        "spark.rapids.tpu.metrics.deviceSync.enabled": True,
    })
    n = 64
    data = {"k": [i % 4 for i in range(n)], "v": list(range(n))}
    schema = schema_of(k=T.INT, v=T.LONG)

    # a project-topped plan: the project exec runs (and records) itself
    sess.create_dataframe(data, schema).select(
        col("k"), E.Alias(E.Multiply(col("v"), lit(2)), "v2")).collect()
    proj = _find_exec(sess.last_executed_plan.tpu_child, TpuProjectExec)
    assert proj is not None

    # an aggregate-topped plan (a project below would fuse INTO the agg
    # program and record nothing of its own — by design)
    sess.create_dataframe(data, schema).group_by("k").agg(
        A.agg(A.Sum(col("v")), "s")).collect()
    agg = _find_exec(sess.last_executed_plan.tpu_child,
                     TpuHashAggregateExec)
    assert agg is not None

    for node in (agg, proj):
        m = node.metrics
        # device-accurate timing recorded (fence ran and waited)
        assert exec_base.OP_TIME_DEVICE in m, node
        assert m[exec_base.OP_TIME_DEVICE].kind == "ns"
        assert m[exec_base.OP_TIME_DEVICE].value > 0
        assert m[exec_base.BYTES_TOUCHED].value > 0
    # bytes accounting is rows x row-bytes of the OUTPUT batch:
    # project emits n rows of (int32 k + int64 v2) + 2 validity bytes
    assert proj.metrics[exec_base.BYTES_TOUCHED].value == n * (4 + 1 + 8 + 1)
    # aggregate emits 4 groups of (int32 k + int64 s) + 2 validity bytes
    assert agg.metrics[exec_base.BYTES_TOUCHED].value == 4 * (4 + 1 + 8 + 1)
    report = sess.explain_metrics()
    assert "opTimeDevice" in report
    assert "bytesTouched" in report
    assert "compile cache misses" in report
    # the footer is PER-RUN: re-running the (cache-warm) query reports 0
    sess.create_dataframe(data, schema).group_by("k").agg(
        A.agg(A.Sum(col("v")), "s")).collect()
    assert "compile cache misses: 0" in sess.explain_metrics()


def test_explain_metrics_without_sync_has_no_device_time():
    sess = TpuSession()
    df = sess.create_dataframe(
        {"k": [1, 2], "v": [3, 4]}, schema_of(k=T.INT, v=T.LONG))
    df.select(col("k"), col("v")).collect()
    report = sess.explain_metrics()
    assert "opTimeDevice" not in report
    assert "bytesTouched" in report


# ---------------------------------------------------------------------------
# recompile-regression guard: the fused aggregate compiles once per plan
# ---------------------------------------------------------------------------
def test_fused_agg_compiles_once_across_batches():
    schema = schema_of(k=T.INT, a=T.LONG, b=T.DOUBLE)
    batches = _mk_batches(schema, nb=4, n=40)  # same shape bucket
    conf = RapidsConf({"spark.rapids.tpu.sql.agg.fusedPlan": "ON"})
    agg = _mixed_plan_exec(conf, batches, schema)
    before = exec_base.compile_miss_count()
    site_before = dict(exec_base.COMPILE_COUNTER.by_site)
    rows1 = agg.collect()
    added = exec_base.compile_miss_count() - before
    # ONE program for the whole update+merge+eval across 4 batches (the
    # child chain fuses into it; nothing else may compile)
    assert exec_base.COMPILE_COUNTER.by_site.get("agg_plan", 0) \
        == site_before.get("agg_plan", 0) + 1
    assert added == 1, exec_base.COMPILE_COUNTER.by_site
    # an identical plan over the same batch shapes recompiles NOTHING
    again = _mixed_plan_exec(conf, batches, schema)
    before2 = exec_base.compile_miss_count()
    rows2 = again.collect()
    assert exec_base.compile_miss_count() == before2
    _cmp_rows(rows1, rows2)
