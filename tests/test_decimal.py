"""DECIMAL64 differential tests: TPU int64-unscaled kernels vs the CPU
python-Decimal oracle.

Reference analog: the DECIMAL64 rows of GpuCast.scala /
decimalExpressions.scala with the precision-18 cap (GpuOverrides.scala:562,
TypeChecks.scala:453). Covers arithmetic rescaling, overflow-to-null edges,
casts, comparisons, and sum/avg aggregates.
"""
import decimal
import random
from decimal import Decimal

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import ColumnarBatch, schema_of
from spark_rapids_tpu.cpu import eval_expression_rows
from spark_rapids_tpu.expr import bind_references, col, evaluate_projection, lit
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.eval import tpu_supports

N = 128


def gen_decimals(n, rng, p, s, null_prob=0.15, edge_prob=0.2):
    lim = 10 ** p - 1
    edges = [0, lim, -lim, 10 ** (p - 1), -(10 ** (p - 1)), 1, -1,
             lim - 1, -(lim - 1)]
    out = []
    for _ in range(n):
        r = rng.random()
        if r < null_prob:
            out.append(None)
            continue
        unscaled = (
            rng.choice(edges) if r < null_prob + edge_prob
            else rng.randint(-lim, lim)
        )
        out.append(Decimal(unscaled).scaleb(-s))
    return out


def make_batch(pa, sa, pb, sb, seed, null_prob=0.15):
    rng = random.Random(seed)
    schema = schema_of(a=T.DecimalType(pa, sa), b=T.DecimalType(pb, sb))
    data = {
        "a": gen_decimals(N, rng, pa, sa, null_prob),
        "b": gen_decimals(N, rng, pb, sb, null_prob),
    }
    return ColumnarBatch.from_pydict(data, schema), data, schema


def check(expr, pa=7, sa=2, pb=7, sb=2, seed=0):
    from data_gen import ON_TPU, approx_equal

    batch, data, schema = make_batch(pa, sa, pb, sb, seed)
    bound = bind_references(expr, schema)
    [tpu_col] = evaluate_projection([bound], batch)
    tpu_vals = tpu_col.to_pylist()
    rows = list(zip(data["a"], data["b"]))
    cpu_vals = eval_expression_rows(bound, rows)
    for i, (tv, cv) in enumerate(zip(tpu_vals, cpu_vals)):
        if ON_TPU and isinstance(cv, float):
            # decimal->float rides the chip's emulated f64 divide: a few
            # ulps off the correctly-rounded quotient (documented incompat)
            assert approx_equal(tv, cv, 1e-9), (
                f"row {i}: tpu={tv!r} cpu={cv!r} expr={expr}")
            continue
        assert tv == cv, (
            f"row {i}: tpu={tv!r} cpu={cv!r} expr={expr} in={rows[i]!r}")


# ---------------------------------------------------------------------------
# arithmetic
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("op", [E.Add, E.Subtract])
def test_add_sub_same_scale(op):
    check(op(col("a"), col("b")), seed=1)


@pytest.mark.parametrize("op", [E.Add, E.Subtract])
def test_add_sub_mixed_scale(op):
    check(op(col("a"), col("b")), pa=9, sa=4, pb=6, sb=1, seed=2)


def test_add_overflow_edges():
    # (18,0) + (18,0) would need precision 19 -> plan-time fallback
    ok, why = tpu_supports(
        E.Add(col("a"), col("b")),
        schema_of(a=T.DecimalType(18, 0), b=T.DecimalType(18, 0)))
    assert not ok and "DECIMAL64" in why


def test_multiply():
    check(E.Multiply(col("a"), col("b")), pa=7, sa=2, pb=8, sb=3, seed=3)


def test_multiply_precision_cap_falls_back():
    ok, _ = tpu_supports(
        E.Multiply(col("a"), col("b")),
        schema_of(a=T.DecimalType(10, 2), b=T.DecimalType(10, 2)))
    assert not ok


def test_divide():
    check(E.Divide(col("a"), col("b")), pa=5, sa=2, pb=4, sb=1, seed=4)


def test_divide_by_zero_is_null():
    schema = schema_of(a=T.DecimalType(5, 2), b=T.DecimalType(4, 1))
    batch = ColumnarBatch.from_pydict(
        {"a": [Decimal("1.25"), Decimal("-3.50")],
         "b": [Decimal("0.0"), Decimal("0.0")]}, schema)
    bound = bind_references(E.Divide(col("a"), col("b")), schema)
    [c] = evaluate_projection([bound], batch)
    assert c.to_pylist() == [None, None]


def test_decimal_int_mixed():
    schema = schema_of(a=T.DecimalType(7, 2), b=T.INT)
    rng = random.Random(5)
    data = {
        "a": gen_decimals(N, rng, 7, 2),
        "b": [None if rng.random() < 0.1 else rng.randint(-1000, 1000)
              for _ in range(N)],
    }
    batch = ColumnarBatch.from_pydict(data, schema)
    bound = bind_references(E.Add(col("a"), col("b")), schema)
    [c] = evaluate_projection([bound], batch)
    cpu = eval_expression_rows(bound, list(zip(data["a"], data["b"])))
    assert c.to_pylist() == cpu


def test_unary_minus_abs():
    check(E.UnaryMinus(col("a")), seed=6)
    check(E.Abs(col("a")), seed=7)


# ---------------------------------------------------------------------------
# comparisons
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("op", [
    E.EqualTo, E.LessThan, E.GreaterThan, E.LessThanOrEqual,
    E.GreaterThanOrEqual,
])
def test_comparisons_mixed_scale(op):
    check(op(col("a"), col("b")), pa=9, sa=4, pb=7, sb=1, seed=8)


def test_compare_with_literal():
    check(E.GreaterThan(col("a"), lit(Decimal("12.34"))), seed=9)


# ---------------------------------------------------------------------------
# casts
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("to", [
    T.DecimalType(9, 4), T.DecimalType(7, 2), T.DecimalType(5, 0),
    T.DecimalType(4, 2),
])
def test_cast_decimal_to_decimal(to):
    check(E.Cast(col("a"), to), pa=7, sa=2, seed=10)


@pytest.mark.parametrize("to", [T.INT, T.LONG, T.DOUBLE, T.FLOAT, T.BOOLEAN])
def test_cast_decimal_to_numeric(to):
    check(E.Cast(col("a"), to), pa=9, sa=3, seed=11)


def test_cast_int_to_decimal():
    schema = schema_of(a=T.INT, b=T.INT)
    rng = random.Random(12)
    data = {
        "a": [None if rng.random() < 0.1
              else rng.choice([0, 1, -1, 2**31 - 1, -(2**31), 4242])
              for _ in range(N)],
        "b": [0] * N,
    }
    batch = ColumnarBatch.from_pydict(data, schema)
    bound = bind_references(E.Cast(col("a"), T.DecimalType(12, 2)), schema)
    [c] = evaluate_projection([bound], batch)
    cpu = eval_expression_rows(bound, list(zip(data["a"], data["b"])))
    assert c.to_pylist() == cpu


def test_cast_int_to_small_decimal_overflows_null():
    schema = schema_of(a=T.INT, b=T.INT)
    batch = ColumnarBatch.from_pydict(
        {"a": [12345, 12, -99999], "b": [0, 0, 0]}, schema)
    bound = bind_references(E.Cast(col("a"), T.DecimalType(4, 2)), schema)
    [c] = evaluate_projection([bound], batch)
    assert c.to_pylist() == [None, Decimal("12.00"), None]


def test_float_to_decimal_falls_back():
    ok, why = tpu_supports(
        E.Cast(col("a"), T.DecimalType(9, 2)), schema_of(a=T.DOUBLE, b=T.INT))
    assert not ok


# ---------------------------------------------------------------------------
# aggregates (through the exec layer: TPU vs CPU plan)
# ---------------------------------------------------------------------------
def _agg_both(data, schema, keys, aggs):
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.exec import InMemoryScanExec, TpuHashAggregateExec
    from spark_rapids_tpu.cpu.plan import (
        CpuHashAggregateExec,
        CpuScanExec,
    )

    conf = RapidsConf({})
    batch = ColumnarBatch.from_pydict(data, schema)
    tpu = TpuHashAggregateExec(
        conf, keys, aggs, InMemoryScanExec(conf, [[batch]], schema))
    trows = []
    for b in tpu.execute_columnar():
        trows.extend(b.to_rows())
    rows = list(zip(*[data[f.name] for f in schema.fields]))
    cpu = CpuHashAggregateExec(
        conf, keys, aggs, CpuScanExec(conf, [rows], schema))
    crows = cpu.collect()
    return sorted(trows, key=repr), sorted(crows, key=repr)


def test_sum_avg_group_by():
    from spark_rapids_tpu.expr import aggregates as A

    rng = random.Random(13)
    schema = schema_of(k=T.INT, d=T.DecimalType(7, 2))
    data = {
        "k": [rng.randint(0, 5) for _ in range(N)],
        "d": gen_decimals(N, rng, 7, 2),
    }
    t, c = _agg_both(
        data, schema, [col("k")],
        [A.agg(A.Sum(col("d")), "s"), A.agg(A.Average(col("d")), "m"),
         A.agg(A.Min(col("d")), "lo"), A.agg(A.Max(col("d")), "hi")])
    assert t == c


def test_sum_beyond_decimal64_falls_back():
    from spark_rapids_tpu.expr import aggregates as A

    # Spark types sum(decimal(p,s)) as decimal(p+10,s): beyond the
    # DECIMAL64 cap the aggregate must REJECT (int64 accumulation could
    # wrap into a wrong non-null answer) — review regression
    with pytest.raises(TypeError, match="DECIMAL64"):
        _ = A.Sum(E.BoundReference(0, T.DecimalType(18, 0), True)).dtype
    # p <= 8 stays on device
    assert isinstance(
        A.Sum(E.BoundReference(0, T.DecimalType(8, 2), True)).dtype,
        T.DecimalType)


def test_avg_precision_cap_falls_back():
    from spark_rapids_tpu.expr import aggregates as A

    with pytest.raises(TypeError):
        A.Average(col("x")).__class__(
            E.BoundReference(0, T.DecimalType(17, 2), True)).dtype


# ---------------------------------------------------------------------------
# round-trip
# ---------------------------------------------------------------------------
def test_roundtrip_pydict():
    schema = schema_of(a=T.DecimalType(6, 3), b=T.INT)
    vals = [Decimal("1.234"), None, Decimal("-999.999"), Decimal("0.000")]
    batch = ColumnarBatch.from_pydict(
        {"a": vals, "b": [1, 2, 3, 4]}, schema)
    assert [r[0] for r in batch.to_rows()] == vals
