"""Native runtime library tests: the C++ LZ4 block codec behind the
shuffle serializer SPI (reference: NvcompLZ4CompressionCodec behind
TableCompressionCodec; SURVEY §2.12 item 4)."""
import os
import random

import pytest

from spark_rapids_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable")


def test_lz4_round_trip_patterns():
    cases = [
        b"",
        b"a",
        b"hello world " * 1000,
        bytes(range(256)) * 64,
        b"\x00" * 100_000,
        os.urandom(50_000),  # incompressible
        b"abcabcabcabc" + os.urandom(17) + b"zzzzzzzzzzzzzzzzzzzzz",
    ]
    for raw in cases:
        comp = native.lz4_compress(raw)
        back = native.lz4_decompress(comp, len(raw))
        assert back == raw, f"round trip failed for {raw[:20]!r}..."


def test_lz4_compresses_redundant_data():
    raw = (b"spark-rapids-tpu " * 5000)
    comp = native.lz4_compress(raw)
    assert len(comp) < len(raw) // 10


def test_lz4_fuzz_round_trip():
    rng = random.Random(7)
    for _ in range(40):
        n = rng.randint(0, 20000)
        # mixed compressibility: runs + random
        raw = b"".join(
            bytes([rng.randint(0, 255)]) * rng.randint(1, 50)
            if rng.random() < 0.5 else os.urandom(rng.randint(1, 50))
            for _ in range(n // 25 + 1)
        )[:n]
        comp = native.lz4_compress(raw)
        assert native.lz4_decompress(comp, len(raw)) == raw


def test_lz4_rejects_corrupt_payload():
    comp = native.lz4_compress(b"hello world, hello world, hello world")
    with pytest.raises((ValueError, RuntimeError)):
        native.lz4_decompress(comp[:-3] + b"\xff\xff\xff", 37 + 50)


def test_serializer_lz4_round_trip():
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar import ColumnarBatch
    from spark_rapids_tpu.columnar.batch import schema_of
    from spark_rapids_tpu.shuffle.serializer import (
        deserialize_batch,
        serialize_batch,
    )

    schema = schema_of(a=T.LONG, s=T.STRING, b=T.DOUBLE)
    batch = ColumnarBatch.from_pydict(
        {"a": [1, None, 3] * 50, "s": ["xy", None, "zzz"] * 50,
         "b": [1.5, 2.5, None] * 50}, schema)
    wire = serialize_batch(batch, codec="lz4")
    back = deserialize_batch(wire)
    assert back.to_rows() == batch.to_rows()


def test_exchange_with_lz4_codec():
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.expr import aggregates as A
    from spark_rapids_tpu.expr.expressions import col
    from spark_rapids_tpu.sql import TpuSession

    sess = TpuSession({
        "spark.rapids.tpu.shuffle.mode": "host",
        "spark.rapids.tpu.shuffle.transport.class": "host",
        "spark.rapids.tpu.shuffle.compression.codec": "lz4",
    })
    schema = T.StructType([T.StructField("k", T.INT),
                           T.StructField("v", T.LONG)])
    df = sess.create_dataframe(
        {"k": [i % 5 for i in range(500)], "v": list(range(500))},
        schema, num_partitions=3)
    rows = sorted(df.group_by("k").agg(A.agg(A.Sum(col("v")), "sv")).collect())
    expect = {}
    for i in range(500):
        expect[i % 5] = expect.get(i % 5, 0) + i
    assert rows == sorted(expect.items())
