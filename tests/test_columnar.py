"""Columnar core unit tests (reference tier-1 analog: GpuBatchUtilsSuite etc.)."""
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import (
    ColumnarBatch,
    DeviceColumn,
    HostColumn,
    batch_from_rows,
    column_from_pylist,
    schema_of,
)
from spark_rapids_tpu.utils import bucket_rows, round_up_pow2


def test_bucketing():
    assert round_up_pow2(1) == 1
    assert round_up_pow2(2) == 2
    assert round_up_pow2(3) == 4
    assert round_up_pow2(1000) == 1024
    assert bucket_rows(5) == 128
    assert bucket_rows(300) == 512


@pytest.mark.parametrize(
    "dtype,values",
    [
        (T.INT, [1, None, 3, -7]),
        (T.LONG, [2**40, None, -(2**40)]),
        (T.DOUBLE, [1.5, None, float("inf"), -0.0]),
        (T.FLOAT, [1.25, None, 3.5]),
        (T.BOOLEAN, [True, False, None]),
        (T.BYTE, [1, -128, None]),
        (T.SHORT, [300, None, -300]),
        (T.DATE, [18000, None]),
        (T.TIMESTAMP, [1_600_000_000_000_000, None]),
    ],
)
def test_fixed_width_roundtrip(dtype, values):
    col = column_from_pylist(values, dtype)
    assert col.to_pylist() == values
    assert col.capacity >= len(values)
    assert col.null_count() == sum(1 for v in values if v is None)


def test_string_roundtrip():
    values = ["hello", None, "", "wörld", "a" * 300]
    col = column_from_pylist(values, T.STRING)
    assert col.to_pylist() == values
    assert col.is_string
    assert col.null_count() == 1


def test_binary_roundtrip():
    values = [b"\x00\x01", None, b""]
    col = column_from_pylist(values, T.BINARY)
    assert col.to_pylist() == values


def test_decimal_storage():
    dt = T.DecimalType(10, 2)
    col = column_from_pylist([12345, None, -99], dt)  # unscaled int64 values
    assert col.to_pylist() == [12345, None, -99]
    assert col.data.dtype == np.int64


def test_batch_pydict_roundtrip():
    schema = schema_of(a=T.INT, b=T.STRING, c=T.DOUBLE)
    data = {"a": [1, 2, None], "b": ["x", None, "z"], "c": [0.5, 1.5, None]}
    batch = ColumnarBatch.from_pydict(data, schema)
    assert batch.num_rows == 3
    assert batch.num_columns == 3
    assert batch.to_pydict() == data
    assert batch.to_rows() == [(1, "x", 0.5), (2, None, 1.5), (None, "z", None)]


def test_batch_from_rows():
    schema = schema_of(x=T.LONG, y=T.STRING)
    rows = [(1, "a"), (None, "b"), (3, None)]
    batch = batch_from_rows(rows, schema)
    assert batch.to_rows() == rows


def test_select():
    schema = schema_of(a=T.INT, b=T.INT, c=T.INT)
    batch = ColumnarBatch.from_pydict({"a": [1], "b": [2], "c": [3]}, schema)
    sel = batch.select([2, 0])
    assert sel.schema.names == ["c", "a"]
    assert sel.to_rows() == [(3, 1)]


def test_memory_size_accounting():
    col = column_from_pylist(list(range(100)), T.INT)
    assert col.device_memory_size() >= 100 * 4


def test_padding_is_invalid_and_zero():
    col = column_from_pylist([1, 2, 3], T.INT)
    full_validity = np.asarray(col.validity)
    assert not full_validity[3:].any()
    full_data = np.asarray(col.data)
    assert (full_data[3:] == 0).all()
