"""Differential tests for TPU sort, join, and window execs.

Reference analog: SortExecSuite, BroadcastHashJoinSuite/HashJoin tests,
WindowFunctionSuite (SURVEY.md §4 tier 3).
"""
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import schema_of
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr import windows as W
from spark_rapids_tpu.expr.expressions import col, lit
from spark_rapids_tpu.sql import TpuSession

from harness import assert_fallback, assert_tpu_and_cpu_equal, compare_rows

LEFT = schema_of(k=T.INT, a=T.LONG, s=T.STRING)
RIGHT = schema_of(k2=T.INT, b=T.DOUBLE)


def left_df(sess, n=120, parts=2):
    data = {
        "k": [i % 9 if i % 11 else None for i in range(n)],
        "a": [(i * 7) % 50 - 25 for i in range(n)],
        "s": [None if i % 13 == 0 else f"v{i % 5}" for i in range(n)],
    }
    return sess.create_dataframe(data, LEFT, num_partitions=parts)


def right_df(sess, n=40):
    data = {
        "k2": [i % 12 if i % 7 else None for i in range(n)],
        "b": [i / 3.0 for i in range(n)],
    }
    return sess.create_dataframe(data, RIGHT)


class TestSort:
    def test_sort_int_asc(self):
        assert_tpu_and_cpu_equal(
            lambda s: left_df(s).order_by("a"), ignore_order=False)

    def test_sort_desc_nulls(self):
        assert_tpu_and_cpu_equal(
            lambda s: left_df(s).select(col("k"), col("a")).order_by(
                "k", ascending=False),
            ignore_order=False,
        )

    def test_sort_multi_key_mixed(self):
        assert_tpu_and_cpu_equal(
            lambda s: left_df(s).order_by(
                "k", "a", ascending=[True, False]),
            ignore_order=False,
        )

    def test_sort_strings(self):
        assert_tpu_and_cpu_equal(
            lambda s: left_df(s).order_by("s", "a"), ignore_order=False)

    def test_sort_doubles_nan(self):
        sch = schema_of(x=T.DOUBLE)
        data = {"x": [1.5, None, float("nan"), -0.0, 0.0, float("inf"), -3.0]}
        assert_tpu_and_cpu_equal(
            lambda s: s.create_dataframe(data, sch).order_by("x"),
            ignore_order=False,
        )


class TestJoin:
    @pytest.mark.parametrize("how", ["inner", "left", "right", "full", "semi", "anti"])
    def test_join_types(self, how):
        assert_tpu_and_cpu_equal(
            lambda s: left_df(s).join(right_df(s), on=[("k", "k2")], how=how),
            approx_float=True,
        )

    def test_join_duplicate_build_keys(self):
        # several build rows per key -> expansion > 1
        sch_r = schema_of(k2=T.INT, b=T.LONG)
        data_r = {"k2": [1, 1, 1, 2, 2, None], "b": [10, 20, 30, 40, 50, 60]}

        def build(s):
            r = s.create_dataframe(data_r, sch_r)
            return left_df(s, 30, 1).join(r, on=[("k", "k2")], how="inner")

        assert_tpu_and_cpu_equal(build)

    def test_join_multi_key(self):
        sch_l = schema_of(x=T.INT, y=T.LONG, v=T.INT)
        sch_r = schema_of(x2=T.INT, y2=T.LONG, w=T.INT)
        dl = {"x": [1, 1, 2, None, 3], "y": [1, 2, 1, 1, None], "v": [1, 2, 3, 4, 5]}
        dr = {"x2": [1, 1, 2, 3], "y2": [2, 1, 1, 3], "w": [10, 20, 30, 40]}

        def build(s):
            return s.create_dataframe(dl, sch_l).join(
                s.create_dataframe(dr, sch_r), on=[("x", "x2"), ("y", "y2")],
                how="left")

        assert_tpu_and_cpu_equal(build)

    def test_join_string_keys(self):
        sch_r = schema_of(s2=T.STRING, w=T.INT)
        dr = {"s2": ["v0", "v2", "v4", None], "w": [1, 2, 3, 4]}

        def build(s):
            return left_df(s, 40, 1).join(
                s.create_dataframe(dr, sch_r), on=[("s", "s2")], how="inner")

        assert_tpu_and_cpu_equal(build)

    def test_inner_join_with_condition(self):
        def build(s):
            return left_df(s, 40, 1).join(
                right_df(s), on=[("k", "k2")], how="inner",
                condition=E.GreaterThan(col("b"), E.Cast(col("a"), T.DOUBLE)),
            )

        assert_tpu_and_cpu_equal(build, approx_float=True)

    def test_cross_join_with_condition(self):
        def build(s):
            l = left_df(s, 12, 1).select(col("k"), col("a"))
            r = right_df(s, 8).select(col("k2"))
            return l.join(r, on=[], how="inner",
                          condition=E.LessThan(col("k2"), col("k")))

        assert_tpu_and_cpu_equal(build)

    def test_left_join_with_condition_falls_back(self):
        def build(s):
            return left_df(s, 20, 1).join(
                right_df(s), on=[("k", "k2")], how="left",
                condition=E.GreaterThan(col("b"), lit(1.0)),
            )

        assert_fallback(build, "CpuJoinExec")

    def test_join_nan_keys_match(self):
        sch_l = schema_of(f=T.DOUBLE, v=T.INT)
        sch_r = schema_of(f2=T.DOUBLE, w=T.INT)
        dl = {"f": [float("nan"), 1.0, -0.0, None], "v": [1, 2, 3, 4]}
        dr = {"f2": [float("nan"), 0.0, 2.0], "w": [10, 20, 30]}

        def build(s):
            return s.create_dataframe(dl, sch_l).join(
                s.create_dataframe(dr, sch_r), on=[("f", "f2")], how="inner")

        assert_tpu_and_cpu_equal(build)


class TestWindow:
    def _spec(self, order=True):
        return W.WindowSpec(
            partition_by=(col("k"),),
            order_by=(col("a"),) if order else (),
            orders=((True, None),) if order else (),
        )

    def test_row_number_rank(self):
        def build(s):
            return left_df(s).select(col("k"), col("a")).with_windows(
                W.WindowExpression(W.RowNumber(), self._spec(), "rn"),
                W.WindowExpression(W.Rank(), self._spec(), "rk"),
                W.WindowExpression(W.DenseRank(), self._spec(), "dr"),
            )

        assert_tpu_and_cpu_equal(build)

    def test_lead_lag(self):
        def build(s):
            return left_df(s).select(col("k"), col("a")).with_windows(
                W.WindowExpression(W.Lead(col("a"), 1), self._spec(), "ld"),
                W.WindowExpression(W.Lag(col("a"), 2), self._spec(), "lg"),
            )

        assert_tpu_and_cpu_equal(build)

    def test_running_aggregates(self):
        def build(s):
            return left_df(s).select(col("k"), col("a")).with_windows(
                W.WindowExpression(A.Sum(col("a")), self._spec(), "rs"),
                W.WindowExpression(A.Count(col("a")), self._spec(), "rc"),
                W.WindowExpression(A.Min(col("a")), self._spec(), "rmn"),
                W.WindowExpression(A.Max(col("a")), self._spec(), "rmx"),
            )

        assert_tpu_and_cpu_equal(build)

    def test_whole_partition_agg(self):
        def build(s):
            return left_df(s).select(col("k"), col("a")).with_windows(
                W.WindowExpression(A.Sum(col("a")), self._spec(order=False), "ps"),
                W.WindowExpression(A.Count(), self._spec(order=False), "pc"),
            )

        assert_tpu_and_cpu_equal(build)

    def test_avg_over_window(self):
        def build(s):
            return left_df(s).select(col("k"), col("a")).with_windows(
                W.WindowExpression(A.Average(col("a")), self._spec(), "ra"),
            )

        assert_tpu_and_cpu_equal(build, approx_float=True)

    def test_range_frame_peers_share_value(self):
        # duplicate order keys: RANGE frame must include the whole peer group
        sch = schema_of(g=T.INT, o=T.INT, v=T.INT)
        data = {"g": [1, 1, 1, 1], "o": [1, 1, 2, 2], "v": [1, 2, 3, 4]}

        def build(s):
            spec = W.WindowSpec((col("g"),), (col("o"),), ((True, None),))
            return s.create_dataframe(data, sch).with_windows(
                W.WindowExpression(A.Sum(col("v")), spec, "rs"))

        rows = assert_tpu_and_cpu_equal(build)
        by = sorted(rows)
        # peers (o=1): both rows see 1+2=3; (o=2): both see 10
        assert [r[-1] for r in by] == [3, 3, 10, 10]


class TestBoundedRangeFrames:
    """Literal RANGE frames over the ORDER BY key VALUE (VERDICT r4 #5;
    reference: RangeFrame in GpuWindowExpression.scala:88,168)."""

    def _df(self, s, n=180):
        data = {
            "g": [i % 4 for i in range(n)],
            "o": [None if i % 19 == 0 else (i * 7) % 50 for i in range(n)],
            "v": [None if i % 13 == 0 else i - n // 2 for i in range(n)],
        }
        return s.create_dataframe(
            data, schema_of(g=T.INT, o=T.INT, v=T.LONG))

    def _win(self, s, frame, asc=True, nulls_first=None):
        spec = W.WindowSpec(
            (col("g"),), (col("o"),), ((asc, nulls_first),), frame=frame)
        return self._df(s).with_windows(
            W.WindowExpression(A.Sum(col("v")), spec, "rs"),
            W.WindowExpression(A.Count(col("v")), spec, "rc"),
            W.WindowExpression(A.Average(col("v")), spec, "ra"),
        )

    def test_range_preceding_current(self):
        frame = W.WindowFrame(W.RANGE, -10, W.CURRENT_ROW)
        assert_tpu_and_cpu_equal(
            lambda s: self._win(s, frame), approx_float=True)

    def test_range_preceding_following(self):
        frame = W.WindowFrame(W.RANGE, -5, 7)
        assert_tpu_and_cpu_equal(
            lambda s: self._win(s, frame), approx_float=True)

    def test_range_unbounded_to_following(self):
        frame = W.WindowFrame(W.RANGE, W.UNBOUNDED_PRECEDING, 3)
        assert_tpu_and_cpu_equal(
            lambda s: self._win(s, frame), approx_float=True)

    def test_range_current_to_unbounded(self):
        frame = W.WindowFrame(W.RANGE, W.CURRENT_ROW, W.UNBOUNDED_FOLLOWING)
        assert_tpu_and_cpu_equal(
            lambda s: self._win(s, frame), approx_float=True)

    def test_range_descending_order(self):
        frame = W.WindowFrame(W.RANGE, -8, 2)
        assert_tpu_and_cpu_equal(
            lambda s: self._win(s, frame, asc=False), approx_float=True)

    def test_range_nulls_last(self):
        frame = W.WindowFrame(W.RANGE, -10, W.CURRENT_ROW)
        assert_tpu_and_cpu_equal(
            lambda s: self._win(s, frame, nulls_first=False),
            approx_float=True)

    def test_range_ties_share_frames(self):
        # explicit tie rows: CURRENT ROW in RANGE means the peer boundary
        sch = schema_of(g=T.INT, o=T.INT, v=T.INT)
        data = {"g": [1] * 6, "o": [1, 1, 3, 3, 8, 9],
                "v": [1, 2, 4, 8, 16, 32]}
        frame = W.WindowFrame(W.RANGE, -2, W.CURRENT_ROW)

        def build(s):
            spec = W.WindowSpec(
                (col("g"),), (col("o"),), ((True, None),), frame=frame)
            return s.create_dataframe(data, sch).with_windows(
                W.WindowExpression(A.Sum(col("v")), spec, "rs"))

        rows = assert_tpu_and_cpu_equal(build)
        got = [r[-1] for r in sorted(rows, key=lambda r: (r[1], r[2]))]
        # o=1 rows: keys in [-1,1] -> {1,2}=3 (both peers); o=3: [1,3] ->
        # 1+2+4+8=15; o=8: [6,8] -> 16; o=9: [7,9] -> 16+32=48
        assert got == [3, 3, 15, 15, 16, 48]

    def test_range_min_max_falls_back(self):
        frame = W.WindowFrame(W.RANGE, -5, 5)

        def build(s):
            spec = W.WindowSpec(
                (col("g"),), (col("o"),), ((True, None),), frame=frame)
            return self._df(s).with_windows(
                W.WindowExpression(A.Min(col("v")), spec, "mn"))

        assert_fallback(build, "WindowExec")

    def test_default_order_by_spelling_runs_on_tpu(self):
        """sum() over (order by o) — Spark's default RANGE frame — must
        plan on TPU, not fall back (VERDICT r4 weak #5)."""
        from spark_rapids_tpu.sql import TpuSession

        s = TpuSession({"spark.rapids.tpu.sql.test.enabled": True})
        spec = W.WindowSpec((), (col("o"),), ((True, None),))
        df = self._df(s).with_windows(
            W.WindowExpression(A.Sum(col("v")), spec, "rs"))
        rows = df.collect()
        assert "TpuWindowExec" in s.last_executed_plan.tree_string()
        assert len(rows) == 180


class TestRangeFrameNullKeyCollision:
    """Null order keys park at the dtype extreme for the frame search; a
    saturating range bound near the dtype edge used to collide with the
    park value and pull the null-key peer block into non-null frames
    (confirmed repro: key=int64.min+1, RANGE 5 PRECEDING, nulls_first).
    The searched frame is now clamped to the partition's non-null span."""

    def _build(self, data, nulls_first, frame):
        sch = schema_of(g=T.INT, o=T.LONG, v=T.INT)

        def build(s):
            spec = W.WindowSpec(
                (col("g"),), (col("o"),), ((True, nulls_first),),
                frame=frame)
            return s.create_dataframe(data, sch).with_windows(
                W.WindowExpression(A.Sum(col("v")), spec, "rs"))

        return build

    def test_nulls_first_min_edge(self):
        imin = -(2 ** 63)
        data = {"g": [1, 1, 1, 1],
                "o": [None, imin + 1, imin + 3, 10],
                "v": [100, 1, 2, 4]}
        frame = W.WindowFrame(W.RANGE, -5, W.CURRENT_ROW)
        rows = assert_tpu_and_cpu_equal(
            self._build(data, True, frame))
        by_o = {r[1]: r[-1] for r in rows}
        assert by_o[imin + 1] == 1  # NOT 101: the null row stays out
        assert by_o[imin + 3] == 3  # {imin+1, imin+3}
        assert by_o[10] == 4
        assert by_o[None] == 100  # null peer block only

    def test_nulls_last_max_edge(self):
        imax = 2 ** 63 - 1
        data = {"g": [1, 1, 1, 1],
                "o": [5, imax - 3, imax - 1, None],
                "v": [8, 2, 1, 100]}
        frame = W.WindowFrame(W.RANGE, W.CURRENT_ROW, 5)
        rows = assert_tpu_and_cpu_equal(
            self._build(data, False, frame))
        by_o = {r[1]: r[-1] for r in rows}
        assert by_o[imax - 1] == 1  # NOT 101: saturated upper, nulls out
        assert by_o[imax - 3] == 3  # {imax-3, imax-1}
        assert by_o[5] == 8
        assert by_o[None] == 100
