"""Matrix-conformance suite for the static type-support subsystem.

Reference analog: the TypeChecks-driven doc/tagging invariants of the
reference plugin — every supported cell must actually lower and match
the CPU oracle, every unsupported cell must fall back cleanly with a
reason naming the rule, and docs/supported_ops.md must be byte-identical
to what the matrix generates.

Layers:
  * coverage: every registered expression rule declares a matrix
  * safety sweep: NO cell where the matrix says ON_TPU but the legacy
    lowering probe says the trace fails (that direction = runtime crash)
  * execution sweep: supported project-context cells lower a one-op
    plan and diff against the row-interpreter CPU oracle
  * aggregation cells: supported cells run a full differential plan;
    unsupported cells produce a reasoned, named fallback in explain()
  * string min/max (VERDICT #4): grouped/grand/multi-partition/dict
    differential tests for the new rank-based kernels
  * docgen --check and the tracing-hazard lint
"""
import decimal
import os
import subprocess
import sys

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import ColumnarBatch, schema_of
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.cpu import eval_expression_rows
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import bind_references, evaluate_projection
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.expressions import col, lit
from spark_rapids_tpu.plugin import typechecks as TC
from spark_rapids_tpu.plugin.overrides import (
    EXPRESSION_RULES,
    _probe_check_expression,
    check_aggregate,
    check_expression,
)
from spark_rapids_tpu.sql import TpuSession

from harness import assert_tpu_and_cpu_equal, compare_rows

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# decimal(7,2): Multiply/Divide results fit DECIMAL64, so the decimal
# cells exercise DECLARED support (the PR-1 drift: the old doc probed
# decimal(10,2), whose products overflow, and published the resulting
# fallback as "unsupported")
DEC = T.DecimalType(7, 2)

PROBE_TYPES = (
    T.BOOLEAN, T.BYTE, T.SHORT, T.INT, T.LONG, T.FLOAT, T.DOUBLE,
    DEC, T.STRING, T.DATE, T.TIMESTAMP,
)
#: one representative per kernel family for the (compile-heavy)
#: execution sweep; the verdict sweeps above it stay exhaustive
EXEC_TYPES = (T.BOOLEAN, T.INT, T.LONG, T.DOUBLE, DEC, T.STRING,
              T.DATE, T.TIMESTAMP)

# moderate magnitudes on purpose: the conformance sweep verifies CELLS
# (does the op lower and agree for this type), not numeric edge
# semantics — the dedicated suites (test_expressions/test_decimal/...)
# own overflow/NaN/saturation torture
_DATA = {
    "boolean": [True, False, None, True, False, True, None, False],
    "tinyint": [1, -3, None, 7, 0, 20, -20, 5],
    "smallint": [1, -3, None, 7, 0, 20, -20, 5],
    "int": [1, -3, None, 7, 0, 20, -20, 5],
    "bigint": [1, -3, None, 7, 0, 20, -20, 5],
    "float": [1.5, -2.25, None, 0.0, 3.75, -0.5, 20.25, 7.0],
    "double": [1.5, -2.25, None, 0.0, 3.75, -0.5, 20.25, 7.0],
    "decimal": [decimal.Decimal("12.34"), decimal.Decimal("-0.05"), None,
                decimal.Decimal("31.99"), decimal.Decimal("0.00"),
                decimal.Decimal("-23.45"), decimal.Decimal("1.00"),
                decimal.Decimal("7.77")],
    "string": ["a", "bb", None, "ccc", "", "zz", "a", "mn"],
    "date": [18321, 0, None, -365, 19000, 1, 7300, 18321],
    "timestamp": [1_600_000_000_000_000, 0, None, -86_400_000_000,
                  1_700_000_000_123_456, 1, 777, 42],
}

_SKIP_INSTANCE = {
    E.Literal, E.UnresolvedAttribute, E.BoundReference, E.Alias,
    E.NativeUDF, A.AggregateExpression,
}
_AGG_CLASSES = (A.Count, A.Sum, A.Min, A.Max, A.Average, A.First, A.Last)


def _schema_of(dt):
    return T.StructType((T.StructField("c", dt, True),))


def _instance(cls, dt):
    """Best-effort single-column instance of an expression rule (the old
    docgen probe builder, now test-side only)."""
    import dataclasses

    from spark_rapids_tpu.expr import windows as W

    c = col("c")
    if cls in _SKIP_INSTANCE or issubclass(cls, (W.WindowFunction,)) \
            or cls is W.WindowExpression:
        return None
    if issubclass(cls, A.AggregateFunction):
        return cls(c)
    if cls is E.TimeAdd:  # days/microseconds are plain ints, not exprs
        return E.TimeAdd(c, 1, 500_000)
    lit1 = E.Literal(1, T.INT)
    lits = E.Literal("a", T.STRING)
    try:
        args = []
        for f in dataclasses.fields(cls):
            if f.name in ("child", "left", "right", "column", "str",
                          "start_date", "end_date", "sec", "start", "date",
                          "predicate", "true_value", "false_value"):
                args.append(c)
            elif f.name in ("pattern", "substr", "search", "replacement",
                            "pad", "delim", "format", "fmt"):
                args.append(lits)
            elif f.name in ("pos", "len", "days", "count", "index"):
                args.append(lit1)
            elif f.name in ("exprs", "children_"):
                args.append((c,))
            elif f.name == "values":
                args.append((1, 2))
            elif f.name == "branches":
                args.append(((E.IsNotNull(c), c),))
            elif f.name == "to":
                args.append(T.LONG)
            elif f.default is not dataclasses.MISSING or \
                    f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
                break
            else:
                args.append(c)
        else:
            return cls(*args)
        return cls(*args)
    except Exception:
        return None


def _cells():
    for cls in sorted(EXPRESSION_RULES, key=lambda c: c.__name__):
        if issubclass(cls, A.AggregateFunction):
            continue
        for dt in PROBE_TYPES:
            node = _instance(cls, dt)
            if node is None:
                continue
            yield cls, dt, node


class TestMatrixCoverage:
    def test_every_rule_declares_a_matrix(self):
        missing = [
            r.name for cls, r in EXPRESSION_RULES.items()
            if cls not in TC.CHECKS
        ]
        assert not missing, f"rules without a type matrix: {missing}"

    def test_unsupported_reasons_name_rule_param_and_type(self):
        conf = RapidsConf({})
        schema = _schema_of(T.STRING)
        reasons = check_expression(E.Sqrt(col("c")), schema, conf)
        assert reasons and "Sqrt" in reasons[0] and "string" in reasons[0]
        reasons = check_aggregate(
            A.agg(A.First(col("c")), "f"), schema, conf)
        assert reasons == [
            "First: input string is not supported in the aggregation context"
        ]


class TestVerdictSafety:
    """The direction that would crash at runtime must be empty: no cell
    where the matrix tags ON_TPU but the abstract lowering trace fails.
    (The matrix being NARROWER than the lenient trace is fine — that is
    a clean documented fallback, e.g. sin() over a timestamp column.)"""

    def test_matrix_supported_implies_probe_supported(self):
        conf = RapidsConf({})
        bad = []
        for cls, dt, node in _cells():
            schema = _schema_of(dt)
            if check_expression(node, schema, conf, allow_context=True):
                continue  # matrix says fallback — safe by construction
            probe = _probe_check_expression(
                node, schema, conf, allow_context=True)
            if probe:
                bad.append((cls.__name__, dt.simpleString, probe[0][:90]))
        assert not bad, (
            "matrix claims ON_TPU where the lowering trace fails "
            f"({len(bad)} cells):\n" + "\n".join(map(str, bad)))


class TestProjectCellExecution:
    """Every supported project-context cell lowers a ONE-OP plan and
    matches the row-interpreter CPU oracle; every unsupported cell
    produces a reason (never a crash)."""

    @pytest.mark.parametrize("dt", EXEC_TYPES,
                             ids=lambda d: d.simpleString)
    def test_supported_cells_match_cpu_oracle(self, dt):
        conf = RapidsConf({})
        schema = _schema_of(dt)
        tag = TC.tag_of(dt)
        data = {"c": _DATA[tag]}
        batch = ColumnarBatch.from_pydict(data, schema)
        rows = [(v,) for v in data["c"]]
        ran = 0
        for cls, cdt, node in _cells():
            if cdt != dt:
                continue
            if check_expression(node, schema, conf, allow_context=True):
                continue
            if E.has_context_expr(node):
                continue  # partition-context values differ by design
            bound = bind_references(node, schema)
            [out] = evaluate_projection([bound], batch)
            cpu = eval_expression_rows(bound, rows)
            compare_rows([tuple([v]) for v in cpu],
                         [tuple([v]) for v in out.to_pylist()],
                         ignore_order=False, approx_float=True)
            ran += 1
        assert ran > 0

    def test_unsupported_cells_fall_back_with_reason(self):
        conf = RapidsConf({})
        for cls, dt, node in _cells():
            schema = _schema_of(dt)
            reasons = check_expression(node, schema, conf,
                                       allow_context=True)
            for r in reasons:
                assert isinstance(r, str) and r, (cls, dt)


class TestAggregationCells:
    """Aggregate matrix cells: supported -> full differential plan;
    unsupported -> a clean, named fallback reason in explain()."""

    @pytest.mark.parametrize("func_cls", _AGG_CLASSES,
                             ids=lambda c: c.__name__)
    def test_agg_cells(self, func_cls):
        for dt in EXEC_TYPES:
            tag = TC.tag_of(dt)
            schema = schema_of(k=T.INT, c=dt)
            data = {"k": [1, 1, 2, 2, 1, 2, None, 1],
                    "c": _DATA[tag]}
            conf = RapidsConf({})
            ae = A.agg(func_cls(col("c")), "a")
            reasons = check_aggregate(ae, schema, conf)
            if not reasons:
                assert_tpu_and_cpu_equal(
                    lambda s: s.create_dataframe(data, schema)
                    .group_by("k").agg(A.agg(func_cls(col("c")), "a")),
                    approx_float=True,
                )
            else:
                assert any(func_cls.__name__ in r for r in reasons), (
                    func_cls, dt, reasons)
                sess = TpuSession()
                report = (
                    sess.create_dataframe(data, schema)
                    .group_by("k")
                    .agg(A.agg(func_cls(col("c")), "a"))
                    .explain()
                )
                assert "cannot run on TPU" in report
                assert func_cls.__name__ in report

    def test_float_agg_conf_flips_the_cell(self):
        schema = schema_of(k=T.INT, c=T.DOUBLE)
        off = RapidsConf({})
        on = RapidsConf(
            {"spark.rapids.tpu.sql.variableFloatAgg.enabled": True})
        ae = A.agg(A.Sum(col("c")), "s")
        assert check_aggregate(ae, schema, off)
        assert not check_aggregate(ae, schema, on)

    def test_window_string_minmax_stays_off_with_reason(self):
        from spark_rapids_tpu.expr import windows as W

        schema = schema_of(k=T.INT, s=T.STRING)
        conf = RapidsConf({})
        bound = bind_references(A.Min(col("s")), schema)
        reasons = TC.check_node(bound, conf, TC.WINDOW)
        assert reasons == [
            "Min: input string is not supported in the window context"
        ]
        assert not TC.check_node(bound, conf, TC.AGGREGATION)


class TestProbeCrossCheckConf:
    def test_cross_check_logs_nothing_on_clean_plans(self):
        TC.clear_cross_check_log()
        sess = TpuSession({
            "spark.rapids.tpu.sql.matrix.probeCrossCheck.enabled": True})
        schema = schema_of(a=T.LONG, s=T.STRING)
        df = sess.create_dataframe(
            {"a": [1, 2, None], "s": ["x", None, "z"]}, schema)
        df.where(E.IsNotNull(col("a"))).select(
            E.Alias(E.Add(col("a"), lit(1)), "a1"),
            E.Alias(E.Upper(col("s")), "u"),
        ).collect()
        assert TC.cross_check_log() == []


# ---------------------------------------------------------------------------
# String min/max aggregates (VERDICT #4) — CPU-oracle differentials
# ---------------------------------------------------------------------------
STR_POOL = ["apple", "Banana", "", "cherry", "apple", "kiwi", "zz",
            "éclair", None]


def _str_df(s, n=200, parts=1):
    schema = schema_of(k=T.INT, s=T.STRING, t=T.STRING)
    data = {
        "k": [i % 7 if i % 11 else None for i in range(n)],
        "s": [STR_POOL[i % len(STR_POOL)] for i in range(n)],
        "t": [None if i % 3 == 0 else STR_POOL[(i * 5) % len(STR_POOL)]
              for i in range(n)],
    }
    return s.create_dataframe(data, schema, num_partitions=parts)


class TestStringMinMax:
    def test_grouped(self):
        assert_tpu_and_cpu_equal(
            lambda s: _str_df(s).group_by("k").agg(
                A.agg(A.Min(col("s")), "mn"), A.agg(A.Max(col("s")), "mx"),
                A.agg(A.Min(col("t")), "mnt"), A.agg(A.Max(col("t")), "mxt"),
                A.agg(A.Count(), "n"),
            ))

    def test_grand(self):
        assert_tpu_and_cpu_equal(
            lambda s: _str_df(s).agg(
                A.agg(A.Min(col("s")), "mn"), A.agg(A.Max(col("s")), "mx")))

    def test_multi_partition_partial_final(self):
        # string buffer columns cross the exchange between PARTIAL/FINAL
        assert_tpu_and_cpu_equal(
            lambda s: _str_df(s, n=503, parts=3).group_by("k").agg(
                A.agg(A.Min(col("s")), "mn"), A.agg(A.Max(col("s")), "mx")))

    def test_mixed_with_numeric_aggs(self):
        schema = schema_of(k=T.INT, s=T.STRING, v=T.LONG)
        data = {
            "k": [i % 4 for i in range(100)],
            "s": [STR_POOL[i % len(STR_POOL)] for i in range(100)],
            "v": [i * 3 - 50 if i % 9 else None for i in range(100)],
        }
        assert_tpu_and_cpu_equal(
            lambda s: s.create_dataframe(data, schema).group_by("k").agg(
                A.agg(A.Sum(col("v")), "sv"), A.agg(A.Min(col("s")), "mn"),
                A.agg(A.Max(col("v")), "mxv"), A.agg(A.Max(col("s")), "mx"),
            ))

    def test_all_null_group(self):
        schema = schema_of(k=T.INT, s=T.STRING)
        data = {"k": [1, 1, 2, 2], "s": [None, None, "b", "a"]}
        assert_tpu_and_cpu_equal(
            lambda s: s.create_dataframe(data, schema).group_by("k").agg(
                A.agg(A.Min(col("s")), "mn"), A.agg(A.Max(col("s")), "mx")))

    def test_dict_encoded_sorted_code_order(self, monkeypatch):
        """The dictionary path (sorted-code order) vs forced
        materialization vs the groupby oracle — same answers on both
        lowerings, and the dict path keeps its output dict-encoded."""
        import jax

        from spark_rapids_tpu import columnar as COL
        from spark_rapids_tpu.columnar.column import (
            DeviceColumn,
            dict_column_from_pylist,
        )
        from spark_rapids_tpu.expr.eval import ColV
        from spark_rapids_tpu.expr.values import as_plain_str
        from spark_rapids_tpu.ops import groupby as G
        import jax.numpy as jnp

        strs = [STR_POOL[i % len(STR_POOL)] for i in range(64)]
        keys = [i % 5 for i in range(64)]
        dc = dict_column_from_pylist(strs, T.STRING)
        assert dc.is_dict
        cap = dc.dictv.codes.shape[0]
        kd = jnp.zeros(cap, jnp.int32).at[:64].set(
            jnp.array(keys, jnp.int32))
        kv = jnp.zeros(cap, bool).at[:64].set(True)

        def run(v):
            ks, ags, n = G.groupby_agg(
                [ColV(kd, kv)], [T.INT], [v, v], ["min", "max"], 64)
            n = int(n)
            out = {}
            kvals = jax.device_get(ks[0].data)[:n]
            for ai, a in enumerate(ags):
                s = as_plain_str(a)
                offs, chars, val = jax.device_get(
                    (s.offsets, s.chars, s.validity))
                out[ai] = {
                    int(kvals[g]): (
                        bytes(chars[offs[g]:offs[g + 1]]).decode()
                        if val[g] else None)
                    for g in range(n)
                }
            return out, ags

        dict_out, dict_ags = run(dc.dictv)
        from spark_rapids_tpu.expr.values import DictV

        assert all(isinstance(a, DictV) for a in dict_ags), (
            "dict path must keep min/max output dict-encoded")
        plain_out, _ = run(
            __import__(
                "spark_rapids_tpu.expr.values", fromlist=["x"]
            ).materialize_dict(dc.dictv))
        oracle = {0: {}, 1: {}}
        for k, s in zip(keys, strs):
            if s is None:
                continue
            cur = oracle[0].get(k)
            oracle[0][k] = s if cur is None else min(cur, s)
            cur = oracle[1].get(k)
            oracle[1][k] = s if cur is None else max(cur, s)
        for ai in (0, 1):
            want = {k: oracle[ai].get(k) for k in set(keys)}
            assert dict_out[ai] == want
            assert plain_out[ai] == want

    def test_dict_encoded_through_aggregate_exec(self):
        """Dict columns through the REAL exec: BoundReference values
        arrive as DictV, the byte bound comes from static metadata (no
        host sync), and the buffer batch carries dict-encoded output."""
        import jax.numpy as jnp

        from spark_rapids_tpu.columnar import ColumnarBatch
        from spark_rapids_tpu.columnar.column import (
            DeviceColumn,
            dict_column_from_pylist,
        )
        from spark_rapids_tpu.conf import RapidsConf as RC
        from spark_rapids_tpu.exec import aggregate as XA
        from spark_rapids_tpu.exec import basic as XB

        n = 48
        strs = [STR_POOL[i % len(STR_POOL)] for i in range(n)]
        keys = [i % 3 for i in range(n)]
        dc = dict_column_from_pylist(strs, T.STRING)
        cap = dc.dictv.codes.shape[0]
        kd = jnp.zeros(cap, jnp.int32).at[:n].set(jnp.array(keys, jnp.int32))
        kv = jnp.zeros(cap, bool).at[:n].set(True)
        schema = schema_of(k=T.INT, s=T.STRING)
        batch = ColumnarBatch(
            [DeviceColumn(T.INT, n, kd, kv), dc], schema, n)
        conf = RC({})
        scan = XB.InMemoryScanExec(conf, [[batch]], schema)
        agg = XA.TpuHashAggregateExec(
            conf, [col("k")],
            [A.agg(A.Min(col("s")), "mn"), A.agg(A.Max(col("s")), "mx")],
            scan)
        got = {r[0]: (r[1], r[2]) for r in agg.collect()}
        want = {}
        for k, s in zip(keys, strs):
            if s is None:
                continue
            mn, mx = want.get(k, (s, s))
            want[k] = (min(mn, s), max(mx, s))
        assert got == want

    def test_first_last_string_still_fall_back(self):
        sess = TpuSession()
        report = _str_df(sess).group_by("k").agg(
            A.agg(A.Last(col("s")), "l")).explain()
        assert "Last: input string is not supported" in report

    def test_projected_computed_string_minmax_is_exact(self):
        """Review regression: a concat PROJECTED below the aggregate is a
        direct column ref at the agg — the plan stays on TPU, so the exec
        must NOT fuse the projection into the update program (the fused
        bound is measured on the source batch, under-bounding the
        computed string and truncating the rank comparison). All values
        here tie on the first 4 bytes and differ at byte 4."""
        schema = schema_of(k=T.INT, p=T.STRING, s=T.STRING)
        data = {"k": [1, 1, 2, 2], "p": ["aaaa"] * 4,
                "s": ["z", "b", "m", "q"]}
        rows = assert_tpu_and_cpu_equal(
            lambda sess: sess.create_dataframe(data, schema)
            .select(col("k"), E.Alias(E.Concat((col("p"), col("s"))), "t"))
            .group_by("k")
            .agg(A.agg(A.Min(col("t")), "mn"), A.agg(A.Max(col("t")), "mx")))
        assert sorted(rows) == [(1, "aaaab", "aaaaz"),
                                (2, "aaaam", "aaaaq")]

    def test_minmax_same_column_shares_one_rank_sort(self):
        """min(s)+max(s) over one column must reuse a single rank sort
        (both lower to the SAME traced value, keyed by identity)."""
        import jax.numpy as jnp

        from spark_rapids_tpu.ops import groupby as G
        from spark_rapids_tpu.expr.eval import StrV
        from spark_rapids_tpu.ops import sort as sort_mod

        offs = jnp.array([0, 1, 2, 3, 4], jnp.int32)
        chars = jnp.array(list(b"dbca"), jnp.uint8)
        v = StrV(offs, chars, jnp.ones(4, bool))
        calls = []
        orig = sort_mod.sort_with_radix_keys

        def counting(*a, **k):
            calls.append(1)
            return orig(*a, **k)

        G.sort_with_radix_keys, saved = counting, G.sort_with_radix_keys
        try:
            cols = [v, v]
            G.string_minmax_ranks(cols, ["min", "max"], 4, (4, 4))
        finally:
            G.sort_with_radix_keys = saved
        assert len(calls) == 1

    def test_computed_string_minmax_falls_back(self):
        """Review regression: min(concat(s, t)) must NOT run on TPU — a
        computed string has no static byte bound, so the rank sort would
        compare only a source-bounded prefix and silently pick the wrong
        winner. The matrix tags it off with a named reason and results
        stay correct on the CPU path."""
        from harness import assert_fallback

        schema = schema_of(k=T.INT, s=T.STRING, t=T.STRING)
        data = {"k": [1, 1], "s": ["abcd", "abcd"],
                "t": ["XXXXXXXXXXXXzz", "XXXXXXXXXXXXaa"]}

        def build(sess):
            return sess.create_dataframe(data, schema).group_by("k").agg(
                A.agg(A.Min(E.Concat((col("s"), col("t")))), "m"))

        assert_fallback(build, "CpuHashAggregateExec")
        sess = TpuSession()
        report = build(sess).explain()
        assert "direct column references" in report
        # aliased direct refs stay ON (the alias is transparent)
        ok = check_aggregate(
            A.agg(A.Min(E.Alias(col("s"), "x")), "m"), schema,
            RapidsConf({}))
        assert ok == []


# ---------------------------------------------------------------------------
# docgen --check and the tracing-hazard lint
# ---------------------------------------------------------------------------
class TestGeneratedDocs:
    def test_docs_in_sync(self):
        from spark_rapids_tpu.plugin.docgen import check_docs

        assert check_docs(os.path.join(REPO, "docs")) == []

    def test_check_detects_drift(self, tmp_path):
        from spark_rapids_tpu.plugin.docgen import check_docs, write_docs

        d = str(tmp_path)
        write_docs(d)
        assert check_docs(d) == []
        p = os.path.join(d, "supported_ops.md")
        with open(p) as f:
            txt = f.read()
        with open(p, "w") as f:
            f.write(txt.replace("| Abs |", "| AbsEdited |", 1))
        assert check_docs(d) == ["supported_ops.md"]

    def test_doc_reflects_declared_decimal_support(self):
        """The PR-1 drift: Multiply/Divide/Pmod/Remainder/Bitwise* decimal
        cells must state DECLARED support, not the probe environment —
        Multiply decimal = PS (fits-DECIMAL64 note), modulo/bitwise
        decimal = unsupported, and the probeCrossCheck conf is listed."""
        with open(os.path.join(REPO, "docs", "supported_ops.md")) as f:
            ops = f.read()
        mul_lhs = next(l for l in ops.splitlines()
                       if l.startswith("| Multiply |"))
        assert "| S | S | S | S | S | S | S |" in mul_lhs
        for line in ops.splitlines():
            if line.startswith("| Pmod |") or line.startswith("| Remainder |"):
                cells = [c.strip() for c in line.split("|")]
                assert "PS" not in cells
            if line.startswith("| BitwiseAnd |"):
                # integral only: float/double/decimal cells blank
                assert "| S | S | S | S |  |  |  |" in line
        with open(os.path.join(REPO, "docs", "configs.md")) as f:
            cfg = f.read()
        assert "spark.rapids.tpu.sql.matrix.probeCrossCheck.enabled" in cfg
        assert "spark.rapids.tpu.tools.lint.allowlistPath" in cfg


class TestTpuLint:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "tpu_lint.py"),
             *args],
            capture_output=True, text=True)

    def test_repo_is_clean(self):
        r = self._run("--strict-allowlist")
        assert r.returncode == 0, r.stdout + r.stderr

    def test_catches_seeded_hazards(self, tmp_path):
        bad = tmp_path / "spark_rapids_tpu" / "exec"
        bad.mkdir(parents=True)
        (bad / "bad.py").write_text(
            "import jax\nimport numpy as np\n\n\n"
            "def hot(batch):\n"
            "    n = batch.num_rows.item()\n"
            "    return jax.device_get(batch.data), n\n\n\n"
            "def build(cap):\n"
            "    def run(cols, num_rows):\n"
            "        if num_rows > 0:\n"
            "            return cols\n"
            "        return np.asarray(cols), float(num_rows)\n\n"
            "    return jax.jit(run), jax.jit(lambda c: c + 1)\n"
        )
        r = self._run(str(tmp_path / "spark_rapids_tpu"))
        assert r.returncode == 1
        for rule in ("TPU001", "TPU002", "TPU003"):
            assert rule in r.stdout, (rule, r.stdout)
        assert ".item()" in r.stdout
        assert "lambda" in r.stdout
        assert "if/while on a traced value" in r.stdout
