"""I/O layer tests: parquet/CSV/ORC scans, pruning, partition values,
reader strategies, writer round trips — differential vs the CPU oracle.

Reference analog: parquet_test.py / orc_test.py / csv_test.py in
integration_tests, ParquetWriterSuite.
"""
import datetime
import decimal
import os
import random

import pyarrow as pa
import pyarrow.orc as paorc
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.expressions import col, lit
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.sql.session import TpuSession, _SCANNER_CACHE

from harness import assert_tpu_and_cpu_equal, compare_rows


@pytest.fixture
def tmpd(tmp_path):
    return str(tmp_path)


def _mixed_table(n=2000, seed=0):
    rnd = random.Random(seed)
    return pa.table({
        "k": pa.array(
            [rnd.randint(0, 50) if rnd.random() > 0.05 else None
             for _ in range(n)], pa.int32()),
        "v": pa.array(
            [rnd.random() * 100 if rnd.random() > 0.05 else None
             for _ in range(n)], pa.float64()),
        "s": pa.array(
            [rnd.choice(["a", "bb", None, "ccc", "ddd€", ""])
             for _ in range(n)], pa.string()),
        "l": pa.array(
            [rnd.randint(-2**40, 2**40) for _ in range(n)], pa.int64()),
    })


def test_parquet_scan_differential(tmpd):
    t = _mixed_table()
    pq.write_table(t, f"{tmpd}/a.parquet", row_group_size=500)
    pq.write_table(t.slice(0, 700), f"{tmpd}/b.parquet", row_group_size=250)
    assert_tpu_and_cpu_equal(lambda s: s.read.parquet(tmpd))


def test_parquet_all_types_round_trip(tmpd):
    t = pa.table({
        "i8": pa.array([1, None, -128], pa.int8()),
        "i16": pa.array([300, None, -2], pa.int16()),
        "b": pa.array([True, None, False], pa.bool_()),
        "f": pa.array([1.5, None, float("nan")], pa.float32()),
        "dt": pa.array(
            [datetime.date(2020, 2, 29), None, datetime.date(1969, 12, 31)],
            pa.date32()),
        "ts": pa.array(
            [datetime.datetime(2021, 5, 1, 12, 30), None,
             datetime.datetime(1970, 1, 1)], pa.timestamp("us")),
        "dec": pa.array(
            [decimal.Decimal("12.34"), None, decimal.Decimal("-0.01")],
            pa.decimal128(9, 2)),
        "bin": pa.array([b"\x00\xff", None, b""], pa.binary()),
    })
    pq.write_table(t, f"{tmpd}/typed.parquet")
    assert_tpu_and_cpu_equal(
        lambda s: s.read.parquet(f"{tmpd}/typed.parquet"),
        conf={"spark.rapids.tpu.sql.decimalType.enabled": True},
    )


def test_parquet_column_pruning(tmpd):
    pq.write_table(_mixed_table(), f"{tmpd}/a.parquet")
    got = assert_tpu_and_cpu_equal(
        lambda s: s.read.parquet(f"{tmpd}/a.parquet", columns=["s", "k"]))
    assert len(got[0]) == 2


def test_parquet_row_group_pruning_correct_and_effective(tmpd):
    t = pa.table({"k": pa.array(range(10000), pa.int64())})
    pq.write_table(t, f"{tmpd}/a.parquet", row_group_size=1000)
    _SCANNER_CACHE.clear()
    got = assert_tpu_and_cpu_equal(
        lambda s: s.read.parquet(f"{tmpd}/a.parquet")
        .where(E.GreaterThanOrEqual(col("k"), lit(9500))))
    assert len(got) == 500
    pruned = [
        sc for key, sc in _SCANNER_CACHE.items() if key[3]
    ]
    assert pruned, "no pruned scanner was created"
    assert all(
        sum(len(sp.row_groups) for sp in sc.splits()) == 1 for sc in pruned
    ), "pushdown did not prune to a single row group"


def test_parquet_hive_partition_values(tmpd):
    os.makedirs(f"{tmpd}/t/k=a")
    os.makedirs(f"{tmpd}/t/k=b/j=1")
    pq.write_table(pa.table({"v": [1, 2]}), f"{tmpd}/t/k=a/f.parquet")
    pq.write_table(pa.table({"v": [3]}), f"{tmpd}/t/k=b/j=1/f.parquet")
    # note: ragged partition depth keeps only the common first-level key
    got = assert_tpu_and_cpu_equal(
        lambda s: s.read.parquet(f"{tmpd}/t"))
    assert sorted(got)[0][0] == 1


@pytest.mark.parametrize("rt", ["PERFILE", "COALESCING", "MULTITHREADED"])
def test_parquet_reader_strategies_agree(tmpd, rt):
    t = _mixed_table(1500, seed=3)
    for i in range(3):
        pq.write_table(t.slice(i * 500, 500), f"{tmpd}/p{i}.parquet",
                       row_group_size=100)
    assert_tpu_and_cpu_equal(
        lambda s: s.read.parquet(tmpd)
        .group_by("k").agg(A.agg(A.Count(E.col("l")), "c")),
        conf={"spark.rapids.tpu.sql.format.parquet.reader.type": rt},
    )


def test_parquet_write_query_read_round_trip(tmpd):
    pq.write_table(_mixed_table(seed=5), f"{tmpd}/in.parquet")
    s = TpuSession()
    stats = (
        s.read.parquet(f"{tmpd}/in.parquet")
        .where(E.IsNotNull(col("k")))
        .write.parquet(f"{tmpd}/out.parquet")
    )
    assert stats["numRows"] > 0
    assert os.path.exists(f"{tmpd}/out.parquet")
    assert not os.path.exists(f"{tmpd}/out.parquet._temporary")
    assert_tpu_and_cpu_equal(
        lambda s2: s2.read.parquet(f"{tmpd}/out.parquet"))


def test_parquet_write_empty_result(tmpd):
    pq.write_table(pa.table({"k": pa.array([1, 2], pa.int64())}),
                   f"{tmpd}/in.parquet")
    s = TpuSession()
    stats = (
        s.read.parquet(f"{tmpd}/in.parquet")
        .where(E.GreaterThan(col("k"), lit(100)))
        .write.parquet(f"{tmpd}/empty.parquet")
    )
    assert stats["numRows"] == 0
    back = TpuSession().read.parquet(f"{tmpd}/empty.parquet").collect()
    assert back == []


def test_parquet_disabled_falls_back(tmpd):
    from harness import assert_fallback

    pq.write_table(pa.table({"k": pa.array([1, 2, 3], pa.int64())}),
                   f"{tmpd}/a.parquet")
    assert_fallback(
        lambda s: s.read.parquet(f"{tmpd}/a.parquet"),
        "FileSourceScanExec",
        conf={"spark.rapids.tpu.sql.format.parquet.enabled": False},
    )


def test_csv_scan_with_inferred_and_explicit_schema(tmpd):
    with open(f"{tmpd}/x.csv", "w") as f:
        f.write("a,b,c\n1,foo,1.5\n2,bar,\n,baz,2.5\n")
    assert_tpu_and_cpu_equal(lambda s: s.read.csv(f"{tmpd}/x.csv"))
    schema = T.StructType([
        T.StructField("a", T.LONG),
        T.StructField("b", T.STRING),
        T.StructField("c", T.DOUBLE),
    ])
    got = assert_tpu_and_cpu_equal(
        lambda s: s.read.csv(f"{tmpd}/x.csv", schema=schema))
    assert got[0][2] in (1.5, 2.5, None)


def test_orc_scan_differential(tmpd):
    t = _mixed_table(800, seed=9)
    paorc.write_table(t, f"{tmpd}/x.orc")
    assert_tpu_and_cpu_equal(
        lambda s: s.read.orc(f"{tmpd}/x.orc")
        .group_by("k").agg(A.agg(A.Count(E.col("v")), "c")))


def test_scan_feeds_partitioned_aggregate_through_exchange(tmpd):
    # multi-file scan -> multiple partitions -> exchange plan end to end
    t = _mixed_table(1200, seed=12)
    for i in range(4):
        pq.write_table(t.slice(i * 300, 300), f"{tmpd}/p{i}.parquet")
    # shuffle.mode=host pins the single-host exchange path under test
    # (string-bearing schemas are otherwise mesh-eligible now)
    s = TpuSession({
        "spark.rapids.tpu.sql.format.parquet.reader.type": "PERFILE",
        "spark.rapids.tpu.shuffle.mode": "host"})
    df = s.read.parquet(tmpd).group_by("k").agg(
        A.agg(A.Sum(E.col("l")), "sl"))
    out = df.collect()
    assert "ShuffleExchange" in s.last_executed_plan.tree_string()
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": False})
    want = cpu.read.parquet(tmpd).group_by("k").agg(
        A.agg(A.Sum(E.col("l")), "sl")).collect()
    compare_rows(want, out)


# ---------------------------------------------------------------------------
# round 3: ORC/CSV writers, ORC pushdown, MULTITHREADED prefetch, decimals
# ---------------------------------------------------------------------------
def test_orc_write_query_read_round_trip(tmpd):
    paorc.write_table(_mixed_table(seed=21), f"{tmpd}/in.orc")
    s = TpuSession()
    stats = (
        s.read.orc(f"{tmpd}/in.orc")
        .where(E.GreaterThan(col("k"), lit(10)))
        .write.orc(f"{tmpd}/out.orc")
    )
    assert stats["rows"] > 0
    assert_tpu_and_cpu_equal(lambda se: se.read.orc(f"{tmpd}/out.orc"))


def test_csv_writer_round_trip(tmpd):
    t = _mixed_table(300, seed=22)
    pq.write_table(t, f"{tmpd}/in.parquet")
    s = TpuSession()
    stats = s.read.parquet(f"{tmpd}/in.parquet").write.csv(f"{tmpd}/out.csv")
    assert stats["rows"] == 300
    import pyarrow.csv as pacsv

    back = pacsv.read_csv(f"{tmpd}/out.csv")
    assert back.num_rows == 300


def test_orc_filter_pushdown_differential(tmpd):
    paorc.write_table(_mixed_table(2000, seed=23), f"{tmpd}/a.orc")
    assert_tpu_and_cpu_equal(
        lambda s: s.read.orc(tmpd).where(
            E.And(E.GreaterThanOrEqual(col("k"), lit(20)),
                  E.IsNotNull(col("s")))))


def test_multithreaded_reader_prefetches(tmpd):
    t = _mixed_table(1200, seed=24)
    for i in range(4):
        pq.write_table(t.slice(i * 300, 300), f"{tmpd}/m{i}.parquet")
    assert_tpu_and_cpu_equal(
        lambda s: s.read.parquet(tmpd).group_by("k").agg(
            A.agg(A.Sum(col("l")), "sl")),
        conf={"spark.rapids.tpu.sql.format.parquet.reader.type":
              "MULTITHREADED"},
    )


def test_decimal_write_round_trip(tmpd):
    import decimal as D

    t = pa.table({
        "d": pa.array([D.Decimal("12.34"), None, D.Decimal("-0.05"),
                       D.Decimal("99999.99")], pa.decimal128(10, 2)),
        "v": pa.array([1, 2, 3, 4], pa.int64()),
    })
    pq.write_table(t, f"{tmpd}/dec.parquet")
    s = TpuSession()
    s.read.parquet(f"{tmpd}/dec.parquet").write.parquet(f"{tmpd}/dec_out.parquet")
    back = pq.read_table(f"{tmpd}/dec_out.parquet")
    assert back.column("d").to_pylist() == [
        D.Decimal("12.34"), None, D.Decimal("-0.05"), D.Decimal("99999.99")]
