"""Python/ML integration tests (SURVEY §2.10): zero-copy device-batch
export (ColumnarRdd analog, BASELINE config #5) and mapInPandas/mapInArrow.
"""
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.expressions import col, lit
from spark_rapids_tpu.ml import (
    columnar_rdd,
    map_in_arrow,
    map_in_pandas,
    to_dlpack_batches,
    to_numpy_batches,
)
from spark_rapids_tpu.sql import TpuSession

ON = {"spark.rapids.tpu.sql.exportColumnarRdd": True}

SCHEMA = T.StructType([
    T.StructField("x", T.DOUBLE), T.StructField("y", T.LONG)])


def _df(sess, n=500, parts=2):
    return sess.create_dataframe(
        {"x": [i / 3.0 if i % 7 else None for i in range(n)],
         "y": [i for i in range(n)]},
        SCHEMA, num_partitions=parts)


def test_columnar_rdd_requires_opt_in():
    sess = TpuSession()
    with pytest.raises(ValueError, match="exportColumnarRdd"):
        next(iter(columnar_rdd(_df(sess))))


def test_columnar_rdd_exports_device_batches():
    import jax

    sess = TpuSession(ON)
    total = 0
    for batch in columnar_rdd(_df(sess).where(E.GreaterThan(col("y"), lit(9)))):
        assert isinstance(batch.columns[0].data, jax.Array)  # still on device
        total += batch.num_rows
    assert total == 490


def test_dlpack_and_numpy_export():
    sess = TpuSession(ON)
    df = _df(sess, 100, 1)
    [cols] = list(to_dlpack_batches(df))
    assert hasattr(cols[0], "__dlpack__")
    [mats] = list(to_numpy_batches(df))
    x = mats[0]
    assert np.isnan(x[0])  # null -> NaN (DMatrix convention)
    assert x[1] == pytest.approx(1 / 3.0)


def test_columnar_rdd_rejects_fallback_plans():
    sess = TpuSession(ON)
    # string first() aggregate falls back to CPU -> no device batches
    # (min/max over strings now run on TPU via the rank kernels)
    schema = T.StructType([T.StructField("s", T.STRING)])
    df = sess.create_dataframe({"s": ["a", "b"]}, schema)
    from spark_rapids_tpu.expr import aggregates as A

    bad = df.agg(A.agg(A.First(col("s")), "m"))
    with pytest.raises(ValueError, match="CPU fallback"):
        next(iter(columnar_rdd(bad)))


def test_map_in_pandas():
    sess = TpuSession()
    out_schema = T.StructType([T.StructField("z", T.DOUBLE)])

    def f(pdf):
        import pandas as pd

        return pd.DataFrame({"z": pdf["x"].fillna(0.0) * 2 + pdf["y"]})

    out = map_in_pandas(_df(sess, 50, 2), f, out_schema)
    rows = out.collect()
    assert len(rows) == 50
    assert rows[1][0] == pytest.approx(2 / 3.0 + 1)


def test_map_in_arrow_then_tpu_ops():
    sess = TpuSession()
    out_schema = T.StructType([T.StructField("y2", T.LONG)])

    def f(t):
        import pyarrow as pa
        import pyarrow.compute as pc

        return pa.table({"y2": pc.multiply(t.column("y"), 3)})

    out = map_in_arrow(_df(sess, 40, 1), f, out_schema)
    # the result is a first-class DataFrame: TPU ops continue on it
    rows = out.where(E.GreaterThanOrEqual(col("y2"), lit(60))).collect()
    assert len(rows) == 20


def test_xgboost_style_dmatrix_build():
    """BASELINE config #5 shape: device batches -> DMatrix-ready matrix."""
    sess = TpuSession(ON)
    df = _df(sess, 200, 2).where(E.IsNotNull(col("x")))
    mats = [np.column_stack(m) for m in to_numpy_batches(df)]
    X = np.vstack(mats)
    assert X.shape[1] == 2 and not np.isnan(X).any()
    try:
        import xgboost as xgb

        d = xgb.DMatrix(X[:, :1], label=X[:, 1])
        assert d.num_row() == X.shape[0]
    except ImportError:
        pass  # xgboost not in the image: the export path is still proven
