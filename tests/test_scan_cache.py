"""Device scan cache: hot-file reuse + rewrite invalidation.

Reference analog: the cached-batch serializer keeps columnar data resident
(ParquetCachedBatchSerializer.scala); here the pool is keyed by file
identity (path, mtime, size) so a rewritten file can never serve stale
columns.
"""
import os
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.io.scan_cache import DeviceScanCache
from spark_rapids_tpu.sql import TpuSession


@pytest.fixture(autouse=True)
def fresh_cache():
    DeviceScanCache.reset()
    yield
    DeviceScanCache.reset()


def _write(path, vals):
    pq.write_table(
        pa.table({"k": pa.array(np.array(vals) % 8, type=pa.int32()),
                  "v": pa.array(np.array(vals, dtype=np.int64))}),
        path)


def _query(sess, d):
    from spark_rapids_tpu.expr import aggregates as A
    from spark_rapids_tpu.expr.expressions import col

    df = sess.read.parquet(d)
    rows = df.group_by("k").agg(A.agg(A.Sum(col("v")), "s")).collect()
    return sorted(rows)


def test_cache_hit_and_rewrite_invalidation(tmp_path):
    d = str(tmp_path)
    p = os.path.join(d, "t.parquet")
    _write(p, list(range(64)))
    sess = TpuSession({})
    first = _query(sess, d)
    cache = DeviceScanCache.get_instance(RapidsConf({}))
    assert cache is not None
    misses0 = cache.misses
    again = _query(sess, d)
    assert again == first
    assert cache.misses == misses0  # second read served from the pool
    assert cache.hits > 0
    # the stats() API mirrors the raw counters (cache effectiveness was
    # previously unobservable outside the attributes)
    st = cache.stats()
    assert st["hits"] == cache.hits and st["misses"] == cache.misses
    assert st["evictions"] == 0 and st["entries"] >= 1 and st["bytes"] > 0

    # rewrite the file: mtime/size key must miss and recompute
    time.sleep(0.01)  # ensure mtime_ns moves even on coarse filesystems
    _write(p, [10] * 64)
    changed = _query(sess, d)
    assert changed != first
    total = sum(s for _, s in changed)
    assert total == 10 * 64


def test_cache_disabled_by_conf(tmp_path):
    d = str(tmp_path)
    _write(os.path.join(d, "t.parquet"), list(range(32)))
    sess = TpuSession({"spark.rapids.tpu.scan.deviceCache.enabled": False})
    _query(sess, d)
    assert DeviceScanCache._instance is None


def test_cache_lru_eviction():
    c = DeviceScanCache(100)
    c.put(("a", 0, 0, 0, (), None), "A", 60)
    c.put(("b", 0, 0, 0, (), None), "B", 60)  # evicts A
    assert c.evictions == 1
    assert c.get(("a", 0, 0, 0, (), None)) is None
    assert c.get(("b", 0, 0, 0, (), None)) == "B"
    # oversized entries never enter the pool (and are not "evictions")
    c.put(("c", 0, 0, 0, (), None), "C", 1000)
    assert c.get(("c", 0, 0, 0, (), None)) is None
    assert c.stats() == {"hits": 1, "misses": 2, "evictions": 1,
                         "entries": 1, "bytes": 60, "max_bytes": 100}


def test_cache_events_emitted():
    # hit/miss/evict activity lands in the structured event log
    from spark_rapids_tpu import events as EV

    logger = EV.EventLogger(RapidsConf(
        {"spark.rapids.tpu.eventLog.enabled": True}))
    EV.install(logger)
    try:
        c = DeviceScanCache(100)
        c.get(("a", 0, 0, 0, (), None))          # miss
        c.put(("a", 0, 0, 0, (), None), "A", 60)
        c.get(("a", 0, 0, 0, (), None))          # hit
        c.put(("b", 0, 0, 0, (), None), "B", 60)  # evicts a
        ops = [r["op"] for r in logger.records()
               if r["event"] == "scan_cache"]
        assert ops == ["miss", "put", "hit", "put", "evict"]
    finally:
        EV.uninstall()


def test_budget_resize_on_get_instance():
    # a later session's maxBytes governs: the singleton resizes (evicting
    # LRU if shrunk) instead of silently pinning the first session's value
    key = "spark.rapids.tpu.scan.deviceCache.maxBytes"
    inst = DeviceScanCache.get_instance(RapidsConf({key: 200}))
    inst.put(("a", 0, 0, 0, (), None), "A", 80)
    inst.put(("b", 0, 0, 0, (), None), "B", 80)
    grown = DeviceScanCache.get_instance(RapidsConf({key: 500}))
    assert grown is inst and inst.max_bytes == 500
    assert inst.get(("a", 0, 0, 0, (), None)) == "A"
    shrunk = DeviceScanCache.get_instance(RapidsConf({key: 100}))
    assert shrunk is inst and inst.max_bytes == 100
    # LRU eviction down to the new budget: only the most recent survives
    assert inst.get(("b", 0, 0, 0, (), None)) is None
    assert inst.get(("a", 0, 0, 0, (), None)) == "A"


def test_file_key_and_invalidate_normalize_symlinks(tmp_path):
    from spark_rapids_tpu.io.scan_cache import file_key

    real = tmp_path / "real.parquet"
    _write(str(real), list(range(8)))
    link = tmp_path / "link.parquet"
    os.symlink(str(real), str(link))
    k_real = file_key(str(real), 0, ("k",), "batch")
    k_link = file_key(str(link), 0, ("k",), "batch")
    assert k_real == k_link  # one entry per physical file
    c = DeviceScanCache(1000)
    c.put(k_real, "V", 10)
    c.invalidate_path(str(link))  # commit through the symlink still hits
    assert c.get(k_real) is None


@pytest.mark.parametrize("fusion", ["ON", "OFF"])
def test_stage_fusion_modes_agree(tmp_path, fusion):
    # AUTO skips scan->agg fusion on the CPU backend; force both lowerings
    # through the same session query and diff them
    d = str(tmp_path)
    _write(os.path.join(d, "t.parquet"), list(range(256)))
    sess = TpuSession({
        "spark.rapids.tpu.sql.stageFusion": fusion,
        "spark.rapids.tpu.sql.variableFloatAgg.enabled": True,
    })
    rows = _query(sess, d)
    assert rows == sorted(
        (k, sum(v for v in range(256) if v % 8 == k)) for k in range(8))
