"""UDF compiler tests: CPython bytecode -> expression trees.

Reference analog: the udf-compiler test suites (OpcodeSuite) — compile a
lambda, verify it runs on the accelerator, and diff against the raw python
execution (the CPU fallback path runs the ACTUAL function, so differential
equality proves compilation fidelity)."""
import math

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.expressions import col, lit
from spark_rapids_tpu.sql import TpuSession
from spark_rapids_tpu.udf import compile_udf, udf
from spark_rapids_tpu.columnar import ColumnarBatch, schema_of
from spark_rapids_tpu.cpu import eval_expression_rows
from spark_rapids_tpu.expr import bind_references, evaluate_projection

from harness import assert_tpu_and_cpu_equal, compare_rows

ON = {"spark.rapids.tpu.sql.udfCompiler.enabled": True}


def _session_pair(n=200):
    schema = T.StructType([
        T.StructField("a", T.LONG), T.StructField("b", T.DOUBLE),
        T.StructField("s", T.STRING),
    ])
    data = {
        "a": [i * 3 - 100 if i % 7 else None for i in range(n)],
        "b": [i / 3.0 if i % 5 else None for i in range(n)],
        "s": [f"w{i % 9}x" if i % 11 else None for i in range(n)],
    }

    def make(conf):
        s = TpuSession(conf)
        return s, s.create_dataframe(data, schema, num_partitions=1)

    return make


# ---------------------------------------------------------------------------
# compile_udf unit coverage
# ---------------------------------------------------------------------------
def test_compiles_arithmetic():
    f = lambda x, y: (x + y) * 2 - x  # noqa: E731
    e = compile_udf(f, (col("a"), col("b")))
    assert e is not None
    assert isinstance(e, E.Subtract)


def test_compiles_conditional():
    def f(x):
        return x * 2 if x > 0 else -x

    e = compile_udf(f, (col("a"),))
    assert isinstance(e, E.If)


def test_compiles_math_calls():
    def f(x, y):
        return math.sqrt(x * x + y * y)

    e = compile_udf(f, (col("a"), col("b")))
    assert isinstance(e, E.Sqrt)


def test_compiles_string_methods():
    def f(s):
        return s.upper().strip()

    e = compile_udf(f, (col("s"),))
    assert isinstance(e, E.StringTrim)


def test_rejects_loops_and_unknown_calls():
    def loopy(x):
        t = 0
        for i in range(3):
            t += x
        return t

    assert compile_udf(loopy, (col("a"),)) is None

    def weird(x):
        return open("f")  # noqa: SIM115

    assert compile_udf(weird, (col("a"),)) is None


def test_rejects_varargs():
    assert compile_udf(lambda *a: a[0], (col("a"),)) is None


# ---------------------------------------------------------------------------
# end-to-end: compiled (TPU) vs raw python execution (CPU fallback)
# ---------------------------------------------------------------------------
def _diff(fn, args_builder, approx=False, extra_conf=None, guard=None):
    """Diff compiled (TPU) vs raw-python (CPU) execution. ``guard`` filters
    rows the raw function can't take (None args crash python, while the
    compiled tree null-propagates — same contract as Scala UDF NPEs)."""
    make = _session_pair()
    cpu_s, cpu_df = make({"spark.rapids.tpu.sql.enabled": False})
    tpu_s, tpu_df = make({**ON, **(extra_conf or {}),
                          "spark.rapids.tpu.sql.test.enabled": True})
    if guard is not None:
        cpu_df = cpu_df.where(guard())
        tpu_df = tpu_df.where(guard())
    u = udf(fn)
    cpu_rows = cpu_df.select(E.Alias(u(*args_builder()), "r")).collect()
    tpu_rows = tpu_df.select(E.Alias(u(*args_builder()), "r")).collect()
    compare_rows(cpu_rows, tpu_rows, ignore_order=False, approx_float=approx)
    # the TPU plan must be fully replaced (the UDF really compiled)
    assert "CpuProjectExec" not in tpu_s.last_executed_plan.tree_string()


def _ab_guard():
    return E.And(E.IsNotNull(col("a")), E.IsNotNull(col("b")))


def test_e2e_hypot_udf():
    def hypot(x: float, y: float) -> float:
        return math.sqrt(x * x + y * y)

    _diff(hypot, lambda: (col("a"), col("b")), approx=True, guard=_ab_guard)


def test_e2e_cosine_sim_style_udf():
    """BASELINE.md config #4: the cosine-similarity-style arithmetic lambda
    compiles through the bytecode compiler and fuses into the projection."""
    def cos_sim(dot: float, na: float, nb: float) -> float:
        d = math.sqrt(na) * math.sqrt(nb)
        return dot / d if d != 0 else 0.0

    _diff(cos_sim, lambda: (col("b"), E.Abs(col("a")), E.Abs(col("b"))),
          approx=True, guard=_ab_guard)


def test_e2e_conditional_int_udf():
    def bucket(x: int) -> int:
        if x is None:
            return -1
        return x // 10 if x >= 0 else -(-x // 10)

    # `is None` maps to IsNull; int semantics differential
    _diff(bucket, lambda: (col("a"),))


def test_e2e_string_udf():
    def tag(s: str) -> str:
        return ("BIG_" + s.upper()) if len(s) > 3 else s.lower()

    make = _session_pair()
    cpu_s, cpu_df = make({"spark.rapids.tpu.sql.enabled": False})
    tpu_s, tpu_df = make(ON)
    u = udf(tag)
    # guard nulls out (raw python would crash on None)
    cond = E.IsNotNull(col("s"))
    cpu_rows = cpu_df.where(cond).select(E.Alias(u(col("s")), "r")).collect()
    tpu_rows = tpu_df.where(cond).select(E.Alias(u(col("s")), "r")).collect()
    compare_rows(cpu_rows, tpu_rows, ignore_order=False, approx_float=False)


def test_uncompilable_udf_falls_back_to_cpu():
    table = {0: 1}

    def lookup(x: int) -> int:
        return table.get(x, 0)  # closure + dict.get: not compilable

    make = _session_pair()
    sess, df = make(ON)
    u = udf(lookup)
    rows = df.where(E.IsNotNull(col("a"))).select(
        E.Alias(u(col("a")), "r")).collect()
    assert all(r[0] in (0, 1) for r in rows)
    plan = sess.last_executed_plan.tree_string()
    assert "CpuProjectExec" in plan  # fell back, didn't fail


def test_disabled_key_keeps_udf_on_cpu():
    make = _session_pair()
    sess, df = make({})  # compiler off (default, reference parity)
    u = udf(lambda x: x + 1)
    df.where(E.IsNotNull(col("a"))).select(E.Alias(u(col("a")), "r")).collect()
    assert "CpuProjectExec" in sess.last_executed_plan.tree_string()


# ---------------------------------------------------------------------------
# native (JAX/Pallas) UDFs — reference: RapidsUDF.java:22 + the in-tree
# CUDA example (string_word_count.cu)
# ---------------------------------------------------------------------------
def test_native_udf_numeric():
    import jax.numpy as jnp

    from spark_rapids_tpu.expr.eval import ColV
    from spark_rapids_tpu.udf.native import tpu_udf

    def columnar(cap, a, b):
        return ColV(a.data * 2 + b.data, a.validity & b.validity)

    def row(a, b):
        if a is None or b is None:
            return None
        return a * 2 + b

    f = tpu_udf(columnar, row, T.LONG)
    schema = schema_of(a=T.LONG, b=T.LONG)
    batch = ColumnarBatch.from_pydict(
        {"a": [1, None, 3, -5], "b": [10, 20, None, 40]}, schema)
    bound = bind_references(f(col("a"), col("b")), schema)
    [r] = evaluate_projection([bound], batch)
    cpu = eval_expression_rows(bound, [(1, 10), (None, 20), (3, None), (-5, 40)])
    assert r.to_pylist() == cpu == [12, None, None, 30]


def test_native_udf_fuses_with_projection():
    """The native UDF lowers INSIDE the fused projection (no special exec)."""
    import jax.numpy as jnp

    from spark_rapids_tpu.expr.eval import ColV, tpu_supports
    from spark_rapids_tpu.udf.native import tpu_udf

    f = tpu_udf(lambda cap, a: ColV(a.data + 1, a.validity),
                lambda a: None if a is None else a + 1, T.LONG)
    schema = schema_of(a=T.LONG, b=T.LONG)
    expr = E.Multiply(f(col("a")), lit(3))
    ok, why = tpu_supports(expr, schema)
    assert ok, why
    batch = ColumnarBatch.from_pydict({"a": [1, 2], "b": [0, 0]}, schema)
    [r] = evaluate_projection([bind_references(expr, schema)], batch)
    assert r.to_pylist() == [6, 9]


def test_native_udf_bad_columnar_falls_back():
    from spark_rapids_tpu.expr.eval import tpu_supports
    from spark_rapids_tpu.udf.native import tpu_udf

    def broken(cap, a):
        raise RuntimeError("no kernel for this dtype")

    f = tpu_udf(broken, lambda a: a, T.LONG)
    ok, why = tpu_supports(f(col("a")), schema_of(a=T.LONG, b=T.LONG))
    assert not ok


def test_string_word_count_pallas():
    """The in-tree Pallas example vs the row oracle (reference:
    string_word_count.cu differential tests)."""
    from spark_rapids_tpu.udf.native import string_word_count

    vals = ["hello world", "", None, "  leading", "trailing  ", "a",
            "tabs\tand\nnewlines\there", "   ", "ünï códe wörds",
            "x " * 200, "one-token", " a b c d e f g "]
    schema = schema_of(s=T.STRING, t=T.STRING)
    batch = ColumnarBatch.from_pydict(
        {"s": vals, "t": [""] * len(vals)}, schema)
    bound = bind_references(string_word_count(col("s")), schema)
    [r] = evaluate_projection([bound], batch)
    cpu = eval_expression_rows(bound, [(v, "") for v in vals])
    assert r.to_pylist() == cpu
    assert cpu[0] == 2 and cpu[1] == 0 and cpu[2] is None and cpu[8] == 3
