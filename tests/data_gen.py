"""Randomized typed data generators with special-value injection.

Analog of the reference's integration_tests data_gen.py:27-304 (seeded RNG,
null injection, special values like NaN/inf/min/max woven into every column).
"""
from __future__ import annotations

import math
import random
from typing import Any, List, Optional

from spark_rapids_tpu import types as T

_SPECIALS = {
    "tinyint": [0, 1, -1, 127, -128],
    "smallint": [0, 1, -1, 32767, -32768],
    "int": [0, 1, -1, 2**31 - 1, -(2**31)],
    "bigint": [0, 1, -1, 2**63 - 1, -(2**63)],
    "float": [0.0, -0.0, 1.0, -1.0, float("nan"), float("inf"), float("-inf")],
    "double": [0.0, -0.0, 1.0, -1.0, float("nan"), float("inf"), float("-inf")],
    "boolean": [True, False],
    "string": ["", "a", "tpu", "NULL", "ñ→", "x" * 50],
}

_RANGES = {
    "tinyint": (-128, 127),
    "smallint": (-32768, 32767),
    "int": (-(2**31), 2**31 - 1),
    "bigint": (-(2**63), 2**63 - 1),
}


def gen_column(
    dtype: T.DataType,
    n: int,
    rng: random.Random,
    null_prob: float = 0.15,
    special_prob: float = 0.2,
) -> List[Any]:
    name = dtype.name if not isinstance(dtype, T.DecimalType) else "bigint"
    out: List[Any] = []
    for _ in range(n):
        if null_prob and rng.random() < null_prob:
            out.append(None)
            continue
        if name in _SPECIALS and rng.random() < special_prob:
            out.append(rng.choice(_SPECIALS[name]))
            continue
        if name in _RANGES:
            lo, hi = _RANGES[name]
            # mix of small and full-range values
            if rng.random() < 0.7:
                out.append(rng.randint(-100, 100))
            else:
                out.append(rng.randint(lo, hi))
        elif name in ("float", "double"):
            v = rng.uniform(-1e6, 1e6)
            if name == "float":
                import struct

                v = struct.unpack("f", struct.pack("f", v))[0]
            out.append(v)
        elif name == "boolean":
            out.append(rng.random() < 0.5)
        elif name == "string":
            k = rng.randint(0, 12)
            out.append("".join(rng.choice("abcdefg \t0123ü") for _ in range(k)))
        elif name == "date":
            out.append(rng.randint(-30000, 30000))
        elif name == "timestamp":
            out.append(rng.randint(-(2**50), 2**50))
        else:
            raise NotImplementedError(name)
    return out


import os as _os

#: On the real chip, f64 math is EMULATED (no f64 ALU): divisions and
#: transcendentals land within a few ulps-to-f32-level of libm. The
#: reference documents the same class of GPU-vs-JVM drift and its pytest
#: harness compares approximately (approximate_float mark, marks.py:17).
ON_TPU = _os.environ.get("SRTPU_TEST_TPU", "") == "1"


def tpu_rel(exact: float = 1e-12, on_tpu: float = 5e-6) -> float:
    """Comparison tolerance: tight on the bit-exact CPU backend, loosened
    to the chip's emulated-f64 accuracy for float-valued math on TPU."""
    return on_tpu if ON_TPU else exact


def approx_equal(a: Any, b: Any, rel: float = 1e-12) -> bool:
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, float) or isinstance(b, float):
        fa, fb = float(a), float(b)
        if math.isnan(fa) or math.isnan(fb):
            return math.isnan(fa) and math.isnan(fb)
        if ON_TPU:
            # f32-RANGE SATURATION EQUIVALENCE: the chip emulates f64 as
            # f32 pairs, so magnitudes beyond ~3.4e38 overflow to inf and
            # below ~1.2e-38 flush to zero. A saturated result is the
            # correct answer of that number system (documented incompat).
            for x, y in ((fa, fb), (fb, fa)):
                if math.isinf(x) and not math.isinf(y) and abs(y) > 3.0e38 \
                        and (x > 0) == (y > 0):
                    return True
                if x == 0.0 and 0.0 < abs(y) < 1.2e-37:
                    return True
        if math.isinf(fa) or math.isinf(fb):
            return fa == fb
        if fa == fb:
            return True
        return abs(fa - fb) <= rel * max(abs(fa), abs(fb), 1e-300)
    if isinstance(a, bool) or isinstance(b, bool):
        return bool(a) == bool(b)
    return a == b
