"""Differential date/time expression tests: TPU civil-calendar math vs the
python-datetime CPU oracle.

Mirrors the reference's date_time_test.py coverage (datetimeExpressions.scala)
including leap years, epoch boundaries, and pre-epoch floor semantics.
"""
import datetime
import random

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import ColumnarBatch, schema_of
from spark_rapids_tpu.cpu import eval_expression_rows
from spark_rapids_tpu.expr import bind_references, col, evaluate_projection, lit
from spark_rapids_tpu.expr import expressions as E

from data_gen import approx_equal

N = 96
_EPOCH = datetime.date(1970, 1, 1).toordinal()

# oracle uses python datetime: years 1..9999 -> days in [-719162, 2932896]
_DAY_LO, _DAY_HI = -719162, 2932896
_US_LO = _DAY_LO * 86_400_000_000
_US_HI = (_DAY_HI + 1) * 86_400_000_000 - 1

_EDGE_DAYS = [0, -1, 1, -719162, 2932896,
              datetime.date(2000, 2, 29).toordinal() - _EPOCH,
              datetime.date(1900, 2, 28).toordinal() - _EPOCH,
              datetime.date(2100, 3, 1).toordinal() - _EPOCH,
              datetime.date(1969, 12, 31).toordinal() - _EPOCH]
_EDGE_US = [0, -1, 1, 86_400_000_000, -86_400_000_001, 1_000_000,
            -999_999, 946684800123456, -12345678901234]


def gen_dates(n, rng, null_prob=0.15):
    out = []
    for _ in range(n):
        r = rng.random()
        if r < null_prob:
            out.append(None)
        elif r < null_prob + 0.25:
            out.append(rng.choice(_EDGE_DAYS))
        else:
            out.append(rng.randint(-100_000, 100_000))
    return out


def gen_ts(n, rng, null_prob=0.15):
    out = []
    for _ in range(n):
        r = rng.random()
        if r < null_prob:
            out.append(None)
        elif r < null_prob + 0.25:
            out.append(rng.choice(_EDGE_US))
        else:
            out.append(rng.randint(-5_000_000_000_000_000, 5_000_000_000_000_000))
    return out


SCHEMA = schema_of(dt=T.DATE, ts=T.TIMESTAMP, n=T.INT)


def make_batch(seed, null_prob=0.15):
    rng = random.Random(seed)
    data = {
        "dt": gen_dates(N, rng, null_prob),
        "ts": gen_ts(N, rng, null_prob),
        "n": [None if rng.random() < 0.1 else rng.randint(-1000, 1000)
              for _ in range(N)],
    }
    return ColumnarBatch.from_pydict(data, SCHEMA), data


def check(expr, seed=0):
    batch, data = make_batch(seed)
    bound = bind_references(expr, SCHEMA)
    [tpu_col] = evaluate_projection([bound], batch)
    tpu_vals = tpu_col.to_pylist()
    rows = list(zip(data["dt"], data["ts"], data["n"]))
    cpu_vals = eval_expression_rows(bound, rows)
    for i, (tv, cv) in enumerate(zip(tpu_vals, cpu_vals)):
        assert approx_equal(tv, cv), (
            f"row {i}: tpu={tv!r} cpu={cv!r} expr={expr} inputs={rows[i]!r}")


@pytest.mark.parametrize("op", [
    E.Year, E.Quarter, E.Month, E.DayOfMonth, E.DayOfYear, E.DayOfWeek,
    E.WeekDay,
])
def test_date_fields(op):
    check(op(col("dt")), seed=hash(op.__name__) & 0xFFF)
    check(op(col("ts")), seed=(hash(op.__name__) + 1) & 0xFFF)


@pytest.mark.parametrize("op", [E.Hour, E.Minute, E.Second])
def test_time_fields(op):
    check(op(col("ts")), seed=hash(op.__name__) & 0xFFF)


def test_date_arith():
    check(E.DateAdd(col("dt"), col("n")), seed=301)
    check(E.DateSub(col("dt"), col("n")), seed=302)
    check(E.DateAdd(col("dt"), lit(365)), seed=303)
    check(E.DateDiff(col("dt"), lit(0)), seed=304)
    check(E.DateDiff(E.Literal(18321, T.DATE), col("dt")), seed=305)
    check(E.LastDay(col("dt")), seed=306)


def test_unix_roundtrip():
    check(E.UnixTimestamp(col("ts")), seed=310)
    check(E.UnixTimestamp(col("dt")), seed=311)
    check(E.ToUnixTimestamp(col("ts")), seed=312)
    check(E.FromUnixTime(E.UnixTimestamp(col("ts")),
                         lit("yyyy-MM-dd HH:mm:ss")), seed=313)


def test_time_add():
    check(E.TimeAdd(col("ts"), 3, 5_500_000), seed=320)
    check(E.TimeAdd(col("ts"), -1, -1), seed=321)


@pytest.mark.parametrize("fmt", ["year", "YY", "month", "MON", "quarter",
                                 "week", "bogus"])
def test_trunc(fmt):
    check(E.TruncDate(col("dt"), lit(fmt)), seed=hash(fmt) & 0xFFF)


# ---------------------------------------------------------------------------
# casts
# ---------------------------------------------------------------------------
def test_cast_date_timestamp():
    check(E.Cast(col("dt"), T.TIMESTAMP), seed=330)
    check(E.Cast(col("ts"), T.DATE), seed=331)
    check(E.Cast(col("ts"), T.LONG), seed=332)
    check(E.Cast(col("ts"), T.DOUBLE), seed=333)
    check(E.Cast(col("n"), T.TIMESTAMP), seed=334)


def test_cast_datetime_to_string():
    check(E.Cast(col("dt"), T.STRING), seed=340)
    check(E.Cast(col("ts"), T.STRING), seed=341)


def _check_cast_strings(values, to):
    schema = schema_of(s=T.STRING)
    batch = ColumnarBatch.from_pydict({"s": values}, schema)
    bound = bind_references(E.Cast(col("s"), to), schema)
    [r] = evaluate_projection([bound], batch)
    cpu = eval_expression_rows(bound, [(v,) for v in values])
    for i, (tv, cv) in enumerate(zip(r.to_pylist(), cpu)):
        assert approx_equal(tv, cv), (
            f"cast {values[i]!r}: tpu={tv!r} cpu={cv!r}")


def test_cast_string_to_date():
    _check_cast_strings(
        ["2020-02-29", "2019-02-29", "2020-1-5", "2020-13-01", "2020-00-10",
         "1999-12-31", "2020", "2020-06", " 2020-06-15 ", "garbage",
         "20-01-01", "2020-01-00", "2020-01-32", "0001-01-01", "9999-12-31",
         "", None, "2020-01-01-05", "2020--01"], T.DATE)


def test_cast_string_to_timestamp():
    _check_cast_strings(
        ["2020-02-29 13:14:15", "2020-02-29T13:14:15", "2020-02-29",
         "2020-02-29 13:14:15.5", "2020-02-29 13:14:15.123456",
         "2020-02-29 25:00:00", "2020-02-29 13:60:00", "1969-12-31 23:59:59",
         "2020", "2020-06", "bad", "", None, "2020-02-29 1:2:3",
         "2020-01 10:20:30", "2020 1:2:3"],  # time needs a FULL date
        T.TIMESTAMP)


def test_cast_bool_to_timestamp_micros():
    """Spark maps true -> 1 MICROsecond (pinned constant: the oracle shares
    the implementation risk, so a differential test can't catch this)."""
    schema = schema_of(p=T.BOOLEAN)
    batch = ColumnarBatch.from_pydict({"p": [True, False, None]}, schema)
    bound = bind_references(E.Cast(col("p"), T.TIMESTAMP), schema)
    [r] = evaluate_projection([bound], batch)
    assert r.to_pylist() == [1, 0, None]
    assert eval_expression_rows(bound, [(True,), (False,), (None,)]) == \
        [1, 0, None]


def test_cast_edge_pairs():
    """Review regressions: ts->bool uses micros, float->ts nulls
    non-finite and saturates."""
    import data_gen

    schema = schema_of(ts=T.TIMESTAMP, d=T.DOUBLE)
    # the chip's f32-pair f64 emulation overflows past ~1e38: the
    # saturation edge still exercises at 2.5e30 there (the cast itself is
    # conf-gated off by default, like the reference's castFloatToTimestamp)
    big = -2.5e30 if data_gen.ON_TPU else -2.5e200
    vals = {"ts": [500_000, 0, -1, None],
            "d": [float("nan"), float("inf"), 1.5, big]}
    batch = ColumnarBatch.from_pydict(vals, schema)
    rows = list(zip(vals["ts"], vals["d"]))
    for e in (E.Cast(col("ts"), T.BOOLEAN), E.Cast(col("d"), T.TIMESTAMP)):
        bound = bind_references(e, schema)
        [r] = evaluate_projection([bound], batch)
        cpu = eval_expression_rows(bound, rows)
        assert r.to_pylist() == cpu, (e, r.to_pylist(), cpu)


def test_cast_string_date_round_trip():
    batch, data = make_batch(350)
    e = E.Cast(E.Cast(col("dt"), T.STRING), T.DATE)
    bound = bind_references(e, SCHEMA)
    [r] = evaluate_projection([bound], batch)
    for got, want in zip(r.to_pylist(), data["dt"]):
        if want is not None and -719162 <= want <= 2932896:
            assert got == want


def test_datetime_in_predicates():
    """Date expressions fuse with comparisons/filters (q5-style predicate)."""
    check(E.And(E.GreaterThanOrEqual(E.Year(col("dt")), lit(2000)),
                E.LessThan(E.Month(col("dt")), lit(7))), seed=360)
    check(E.If(E.EqualTo(E.Quarter(col("dt")), lit(1)),
               E.DateAdd(col("dt"), lit(90)), col("dt")), seed=361)


def test_q5_like_date_query_from_parquet(tmp_path):
    """TPC-DS q5-style: parquet scan -> date-range filter -> aggregate, the
    end-to-end shape from SURVEY.md §7 step 4, now with real date
    predicates."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    from harness import assert_tpu_and_cpu_equal
    from spark_rapids_tpu.expr import aggregates as A

    rng = random.Random(7)
    n = 3000
    base = datetime.date(1998, 1, 1).toordinal() - _EPOCH
    t = pa.table({
        "sold_date": pa.array(
            [base + rng.randint(0, 1500) if rng.random() > 0.03 else None
             for _ in range(n)], pa.date32()),
        "store": pa.array([rng.randint(1, 12) for _ in range(n)], pa.int32()),
        "profit": pa.array([rng.randint(-500, 2000) for _ in range(n)],
                           pa.int64()),
    })
    pq.write_table(t, str(tmp_path / "sales.parquet"), row_group_size=512)

    lo = E.Literal(base + 200, T.DATE)

    def build(s):
        df = s.read.parquet(str(tmp_path))
        return (
            df.where(E.And(
                E.GreaterThanOrEqual(col("sold_date"), lo),
                E.LessThanOrEqual(
                    col("sold_date"), E.DateAdd(lo, lit(30)))))
            .with_column("yr", E.Year(col("sold_date")))
            .group_by("store")
            .agg(A.agg(A.Count(None), "cnt"),
                 A.agg(A.Sum(col("profit")), "total"))
        )

    assert_tpu_and_cpu_equal(build)


def test_planner_gates_string_to_timestamp():
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.plugin.overrides import check_expression

    schema = schema_of(s=T.STRING)
    conf = RapidsConf({})
    r = check_expression(E.Cast(col("s"), T.TIMESTAMP), schema, conf)
    assert r and "castStringToTimestamp" in r[0]
    on = RapidsConf({"spark.rapids.tpu.sql.castStringToTimestamp.enabled": True})
    assert check_expression(E.Cast(col("s"), T.TIMESTAMP), schema, on) == []
    # string->date is NOT gated (always-on in the reference)
    assert check_expression(E.Cast(col("s"), T.DATE), schema, conf) == []
