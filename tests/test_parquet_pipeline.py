"""Pipelined parquet decode→upload reader (round-7 tentpole b).

Contract: io/parquet_device.read_row_groups_pipelined must produce EXACTLY
what the serial round-6 reader produced — same values, nulls, strings,
per-column host fallback — at every maxInFlight setting, while emitting
the pq_pipeline decode/upload/unpack events the offline profiler and the
live obs plane consume. The differential oracle is the host arrow decode
(deviceDecode.enabled=false), the same contract test_parquet_device.py
pins for the single-row-group path.
"""
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_rapids_tpu  # noqa: F401  (x64 enable)
from spark_rapids_tpu import events as EV
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.exec.scan import TpuFileSourceScanExec
from spark_rapids_tpu.io.parquet import ParquetScanner
from spark_rapids_tpu.io.scan_cache import DeviceScanCache


MIF = "spark.rapids.tpu.sql.format.parquet.pipeline.maxInFlight"
NO_CACHE = {"spark.rapids.tpu.scan.deviceCache.enabled": False}


def _table(n=40_000, with_nulls=True, seed=3):
    rng = np.random.default_rng(seed)
    price = np.round(rng.uniform(1.0, 100.0, 500), 2)
    v = rng.integers(-(10**6), 10**6, n)
    vmask = (rng.random(n) < 0.1) if with_nulls else np.zeros(n, bool)
    return pa.table({
        "k": pa.array(rng.integers(0, 64, n).astype(np.int32)),
        "v": pa.array(np.where(vmask, 0, v), mask=vmask),
        "w": pa.array(price[rng.integers(0, 500, n)]),
        "s": pa.array([f"tag-{i % 97}" for i in range(n)]),
    })


def _collect(path, conf_dict):
    conf = RapidsConf(conf_dict)
    sc = ParquetScanner(path, conf)
    ex = TpuFileSourceScanExec(conf, sc, "parquet")
    rows = []
    for p in range(ex.num_partitions):
        for b in ex.execute_partition(p):
            rows.extend(b.to_rows())
    return rows


@pytest.mark.parametrize("mif", [1, 2, 5])
def test_pipelined_read_matches_host_decode(tmp_path, mif):
    """Many row groups, nulls, dict strings: every window size produces
    the host oracle's rows (maxInFlight=1 is the serial round-6 order)."""
    path = os.path.join(str(tmp_path), "t.parquet")
    pq.write_table(_table(), path, row_group_size=4096)  # ~10 row groups
    DeviceScanCache.reset()
    dev = _collect(path, {**NO_CACHE, MIF: mif})
    host = _collect(path, {
        "spark.rapids.tpu.sql.format.parquet.deviceDecode.enabled": False})
    assert dev == host


def test_pipelined_read_per_column_fallback(tmp_path):
    """A PLAIN-encoded double column (no device path) host-decodes per
    column inside the pipeline; the other columns still device-decode."""
    n = 20_000
    rng = np.random.default_rng(9)
    t = pa.table({
        "a": pa.array(rng.integers(0, 100, n).astype(np.int32)),
        # dictionary encoding off => PLAIN DOUBLE => per-column fallback
        "d": pa.array(rng.normal(size=n)),
    })
    path = os.path.join(str(tmp_path), "f.parquet")
    pq.write_table(t, path, row_group_size=4096,
                   use_dictionary=["a"])
    DeviceScanCache.reset()
    dev = _collect(path, {**NO_CACHE, MIF: 3})
    host = _collect(path, {
        "spark.rapids.tpu.sql.format.parquet.deviceDecode.enabled": False})
    assert dev == host


def test_pipeline_events_emitted(tmp_path):
    """decode/upload/unpack events per row group, with durations, through
    the installed logger — and the double-buffered staging really splits
    a multi-column row group into two uploads."""
    path = os.path.join(str(tmp_path), "e.parquet")
    pq.write_table(_table(n=16_000), path, row_group_size=4096)
    logger = EV.EventLogger(RapidsConf({}), ring_size=4096,
                            path=os.path.join(str(tmp_path), "ev.jsonl"))
    EV.install(logger)
    try:
        DeviceScanCache.reset()
        _collect(path, {**NO_CACHE, MIF: 3})
    finally:
        EV.uninstall()
        logger.close()
    evs = [r for r in logger.records() if r["event"] == "pq_pipeline"]
    stages = {}
    for r in evs:
        stages.setdefault(r["stage"], []).append(r)
        assert r["dur"] >= 0 and r["bytes"] >= 0
    nrg = 4  # 16k rows / 4k per group
    assert len(stages["decode"]) == nrg * 4          # one per column chunk
    assert len(stages["unpack"]) == nrg
    # double-buffered staging: up to two packed transfers per row group
    # (one when every chunk finished inside a single wait round — the
    # split is opportunistic, never a third transfer)
    assert nrg <= len(stages["upload"]) <= nrg * 2
    # every event type used here is in the declared schema
    for r in evs:
        for field in EV.EVENT_TYPES["pq_pipeline"]:
            assert field in r


def test_pipeline_respects_scan_cache(tmp_path):
    """A second read of the same file is served from the device scan
    cache — the pipeline only runs for cache-missing row groups."""
    path = os.path.join(str(tmp_path), "c.parquet")
    pq.write_table(_table(n=12_000), path, row_group_size=4096)
    DeviceScanCache.reset()
    conf_dict = {MIF: 2}
    first = _collect(path, conf_dict)
    cache = DeviceScanCache._instance
    assert cache is not None and cache.misses > 0
    misses_before = cache.misses
    second = _collect(path, conf_dict)
    assert second == first
    assert cache.misses == misses_before  # all row groups hit
    DeviceScanCache.reset()


def test_file_scan_hbm_forecast_budget_flip(tmp_path):
    """Satellite: the analyzer models the pipelined decode's staging
    windows — a parquet plan now HAS a peak-HBM forecast, and shrinking
    hbm.budgetBytes flips the plan-time spill warning."""
    from spark_rapids_tpu.expr import aggregates as A
    from spark_rapids_tpu.expr.expressions import col
    from spark_rapids_tpu.sql import TpuSession

    path = os.path.join(str(tmp_path), "b.parquet")
    pq.write_table(_table(n=20_000), path, row_group_size=4096)

    def explain(settings):
        sess = TpuSession(settings)
        df = sess.read.parquet(path).group_by("k").agg(
            A.agg(A.Sum(col("v")), "sv"))
        return df.explain()

    roomy = explain({})
    assert "pipelined device decode" in roomy
    assert "predicted peak HBM" in roomy
    assert "will spill" not in roomy
    tight = explain({"spark.rapids.tpu.memory.hbm.budgetBytes": 4096})
    assert "will spill" in tight
