"""Structured event log, Perfetto export, and the offline profiler.

Reference analog: the Spark event log + rapids-4-spark profiling tool
(SURVEY: tools layer). Pins four contracts:
  1. every event type round-trips through the JSONL sink with its full
     declared schema (events.EVENT_TYPES is the single source of truth);
  2. export_trace() emits valid Chrome trace-event JSON with
     monotonically ordered, non-negative spans;
  3. tools/tpu_profile.py parses a log into the report (golden sections,
     forecast-vs-actual with zero violations on a healthy run, VIOLATION
     + nonzero exit on a poisoned one) and --diff flags regressions;
  4. with event logging off (the default) NOTHING is emitted — no ring
     entries, no sink writes, no EventLogger.emit calls at all.
"""
import importlib.util
import json
import os
import sys

import pytest

from spark_rapids_tpu import events as EV
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.expressions import col, lit
from spark_rapids_tpu.sql import TpuSession

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "tpu_profile", os.path.join(REPO, "tools", "tpu_profile.py"))
tpu_profile = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(tpu_profile)


@pytest.fixture(autouse=True)
def clean_logger():
    """Every test leaves the process-global logger uninstalled."""
    EV.uninstall()
    yield
    EV.uninstall()


def _dummy_value(field):
    """A JSON-typed placeholder per schema field (shape matters, not
    semantics: lists for list fields, strings for names, ints otherwise)."""
    if field in ("fallbacks", "warnings"):
        return [{"op": "X", "reasons": ["r"]}] if field == "fallbacks" else ["w"]
    if field in ("site_forecast", "bytes_by_op"):
        return {"site": 1}
    if field in ("plan_digest", "sql_hash", "op", "section", "lane", "site",
                 "direction", "kind", "codec"):
        return "x"
    if field in ("on_tpu", "bounded"):
        return True
    return 7


def _run_query(sess):
    df = (sess.range(0, 2048)
          .where(E.GreaterThanOrEqual(col("id"), lit(100)))
          .select(col("id"), E.Alias(E.Multiply(col("id"), lit(2)), "v"))
          .agg(A.agg(A.Sum(col("v")), "s"), A.agg(A.Count(None), "c")))
    return df.collect()


# ---------------------------------------------------------------------------
# 1. schema round-trip
# ---------------------------------------------------------------------------
def test_every_event_type_roundtrips_through_jsonl(tmp_path):
    logger = EV.EventLogger(
        RapidsConf({"spark.rapids.tpu.eventLog.dir": str(tmp_path)}))
    emitted = {}
    for etype, fields in EV.EVENT_TYPES.items():
        payload = {f: _dummy_value(f) for f in fields}
        logger.emit(etype, **payload)
        emitted[etype] = payload
    logger.close()
    with open(logger.path) as f:
        recs = [json.loads(line) for line in f]
    assert [r["event"] for r in recs] == list(EV.EVENT_TYPES)
    last_ts = 0
    for r in recs:
        assert isinstance(r["ts"], int) and r["ts"] >= last_ts
        last_ts = r["ts"]
        for field in EV.EVENT_TYPES[r["event"]]:
            assert r[field] == emitted[r["event"]][field], (
                f"{r['event']}.{field} did not round-trip")


def test_ring_buffer_fallback_without_dir():
    # no dir: enabled via eventLog.enabled, events land ONLY in the ring
    logger = EV.EventLogger(RapidsConf({
        "spark.rapids.tpu.eventLog.enabled": True,
        "spark.rapids.tpu.eventLog.ringBuffer.size": 4}))
    assert logger.enabled and logger.path is None
    for i in range(10):
        logger.emit("compile_miss", site=f"s{i}", total=i)
    recs = logger.records()
    assert len(recs) == 4  # ring bound holds
    assert [r["site"] for r in recs] == ["s6", "s7", "s8", "s9"]


# ---------------------------------------------------------------------------
# 2. query lifecycle through a real session
# ---------------------------------------------------------------------------
def test_query_lifecycle_lands_in_jsonl(tmp_path):
    sess = TpuSession({"spark.rapids.tpu.eventLog.dir": str(tmp_path)})
    rows = _run_query(sess)
    assert rows[0][1] == 1948  # count(id >= 100) over range(2048)
    with open(sess.events.path) as f:
        recs = [json.loads(line) for line in f]
    kinds = [r["event"] for r in recs]
    for expected in ("query_start", "plan_tagged", "plan_analysis",
                     "op_span", "op_batch", "query_end"):
        assert expected in kinds, f"missing {expected} in {sorted(set(kinds))}"
    qs = next(r for r in recs if r["event"] == "query_start")
    qe = next(r for r in recs if r["event"] == "query_end")
    assert qe["query_id"] == qs["query_id"] and qe["rows"] == 1
    assert qe["dur"] > 0
    # single-threaded session: the log is time-ordered as written
    ts = [r["ts"] for r in recs]
    assert ts == sorted(ts)
    # the analyzer's forecast rode along for the offline cross-check
    pa = next(r for r in recs if r["event"] == "plan_analysis")
    assert pa["bounded"] is True and isinstance(pa["site_forecast"], dict)


def test_device_lane_spans_with_device_sync(tmp_path):
    sess = TpuSession({
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.metrics.deviceSync.enabled": True})
    _run_query(sess)
    with open(sess.events.path) as f:
        recs = [json.loads(line) for line in f]
    lanes = {r["lane"] for r in recs if r["event"] == "op_span"}
    assert lanes == {"host", "device"}  # the two timeline lanes
    dev = [r for r in recs
           if r["event"] == "op_span" and r["lane"] == "device"]
    assert all(r["section"] == "device_wait" and r["dur"] >= 0 for r in dev)


# ---------------------------------------------------------------------------
# 3. Perfetto export
# ---------------------------------------------------------------------------
def test_export_trace_is_valid_chrome_trace(tmp_path):
    # ring-buffer-only session (no dir): export still works
    sess = TpuSession({
        "spark.rapids.tpu.eventLog.enabled": True,
        "spark.rapids.tpu.metrics.deviceSync.enabled": True})
    _run_query(sess)
    out = str(tmp_path / "trace.json")
    sess.export_trace(out)
    with open(out) as f:
        trace = json.load(f)
    evs = trace["traceEvents"]
    assert evs, "empty trace"
    spans = [e for e in evs if e.get("ph") == "X"]
    assert spans, "no spans in trace"
    for e in spans:
        assert e["ts"] >= 0 and e["dur"] >= 0
    # after the thread-name metadata, events are monotonically ordered
    body = [e for e in evs if e.get("ph") != "M"]
    ts = [e["ts"] for e in body]
    assert ts == sorted(ts)
    names = {e["args"]["name"] for e in evs if e.get("ph") == "M"}
    assert any("[device]" in n for n in names)  # separate device track
    # the compile-miss counter track appears iff the run compiled (a warm
    # process-wide pipeline cache legitimately misses nothing)
    misses = [r for r in sess.events.records()
              if r["event"] == "compile_miss"]
    counters = {e["name"] for e in evs if e.get("ph") == "C"}
    assert ("compile_misses" in counters) == bool(misses)
    # a query span wraps the op spans
    assert any(e["name"].startswith("query ") for e in spans)


def test_export_trace_raises_when_disabled():
    sess = TpuSession({})
    with pytest.raises(RuntimeError, match="event logging is off"):
        sess.export_trace("/tmp/never-written.json")


# ---------------------------------------------------------------------------
# 4. the offline profiler
# ---------------------------------------------------------------------------
def _canned_events(byte_bound=1000, measured_bytes=512):
    """A minimal healthy log: one bounded query, two ops, one compile
    miss, a spill, shuffle traffic."""
    t = 1_000_000
    return [
        {"ts": t, "event": "query_start", "query_id": 1,
         "plan_digest": "abc", "sql_hash": "def"},
        {"ts": t + 1, "event": "plan_tagged", "query_id": 1, "on_tpu": True,
         "fallbacks": []},
        {"ts": t + 2, "event": "plan_analysis", "query_id": 1,
         "bounded": True, "site_forecast": {"project": 1},
         "bytes_by_op": {"TpuProjectExec": byte_bound,
                         "TpuRangeExec": 4096},
         "peak_hbm": 8192, "budget": None, "warnings": []},
        {"ts": t + 10, "event": "compile_miss", "site": "project",
         "total": 1},
        {"ts": t + 20, "event": "op_span", "op": "TpuRangeExec",
         "section": "", "start": t + 15, "dur": 3_000_000, "lane": "host"},
        {"ts": t + 30, "event": "op_span", "op": "TpuProjectExec",
         "section": "", "start": t + 25, "dur": 8_000_000, "lane": "host"},
        {"ts": t + 31, "event": "op_span", "op": "TpuProjectExec",
         "section": "device_wait", "start": t + 30, "dur": 5_000_000,
         "lane": "device"},
        {"ts": t + 40, "event": "op_batch", "op": "TpuRangeExec",
         "rows": 64, "bytes": 2048},
        {"ts": t + 41, "event": "op_batch", "op": "TpuProjectExec",
         "rows": 64, "bytes": measured_bytes},
        {"ts": t + 50, "event": "spill", "kind": "device_to_host",
         "bytes": 4096, "device_bytes": 1024},
        {"ts": t + 60, "event": "shuffle_write", "shuffle_id": 1,
         "map_id": 0, "reduce_id": 0, "rows": 64, "bytes": 800,
         "codec": "none"},
        {"ts": t + 61, "event": "shuffle_fetch", "shuffle_id": 1,
         "reduce_id": 0, "pieces": 1, "rows": 64, "bytes": 800,
         "codec": "none"},
        {"ts": t + 99, "event": "query_end", "query_id": 1,
         "dur": 90_000_000, "rows": 64},
    ]


def _write_log(tmp_path, events, name="log.jsonl"):
    p = str(tmp_path / name)
    with open(p, "w") as f:
        for r in events:
            f.write(json.dumps(r) + "\n")
    return p


def test_profiler_report_golden(tmp_path):
    p = _write_log(tmp_path, _canned_events())
    text, violations = tpu_profile.build_report(
        tpu_profile.load_events([p]))
    assert violations == 0
    # section headers
    for section in ("== queries ==", "== top ops by device time ==",
                    "== compile cache misses ==", "== shuffle ==",
                    "== spill timeline ==", "== forecast vs actual =="):
        assert section in text, text
    # the device-ranked top op is the one with a device lane
    top_line = text.split("== top ops by device time ==\n")[1].splitlines()[0]
    assert "TpuProjectExec" in top_line and "device=5.0ms" in top_line
    assert "query 1 plan=abc dur=90.0ms rows=64" in text
    assert "device_to_host" in text and "peak device watermark" in text
    assert "shuffle_write[none]: 1 piece(s)" in text
    assert "compile[project]: actual 1 <= forecast 1" in text
    assert "0 violation(s)" in text


def test_profiler_flags_forecast_violation(tmp_path):
    # measured bytes above the analyzer bound: VIOLATION + exit code 1
    p = _write_log(tmp_path, _canned_events(byte_bound=100,
                                            measured_bytes=512))
    text, violations = tpu_profile.build_report(
        tpu_profile.load_events([p]))
    assert violations == 1
    assert "VIOLATION" in text and "bytes[TpuProjectExec]" in text
    assert tpu_profile.main([p]) == 1


def test_profiler_flags_compile_storm(tmp_path):
    evs = _canned_events()
    evs += [{"ts": 2_000_000 + i, "event": "compile_miss", "site": "sort",
             "total": 2 + i} for i in range(9)]
    p = _write_log(tmp_path, sorted(evs, key=lambda r: r["ts"]))
    text, _ = tpu_profile.build_report(tpu_profile.load_events([p]))
    assert "sort: 9 <-- COMPILE STORM" in text


def test_diff_event_log_against_itself_is_clean(tmp_path):
    p = _write_log(tmp_path, _canned_events())
    text, n = tpu_profile.run_diff(p, p, threshold=0.2)
    assert n == 0 and "0 regression(s)" in text
    assert tpu_profile.main(["--diff", p, p]) == 0


def test_diff_flags_event_log_regression(tmp_path):
    a = _write_log(tmp_path, _canned_events(), "a.jsonl")
    slow = _canned_events()
    for r in slow:
        if r["event"] == "op_span" and r["op"] == "TpuProjectExec":
            r["dur"] *= 3  # 3x slower than the old log
    b = _write_log(tmp_path, slow, "b.jsonl")
    text, n = tpu_profile.run_diff(a, b, threshold=0.2)
    assert n >= 1 and "REGRESSION" in text and "TpuProjectExec" in text


def test_diff_bench_jsons(tmp_path):
    old = {"per_shape": {"agg": {"tpu_ms": 100.0, "device_ms": 50.0},
                         "sort": {"tpu_ms": 10.0, "device_ms": None}}}
    new = {"per_shape": {"agg": {"tpu_ms": 250.0, "device_ms": 51.0},
                         "sort": {"tpu_ms": 10.5, "device_ms": None}}}
    pa = str(tmp_path / "BENCH_a.json")
    pb = str(tmp_path / "BENCH_b.json")
    for p, d in ((pa, old), (pb, new)):
        with open(p, "w") as f:
            json.dump(d, f)
    text, n = tpu_profile.run_diff(pa, pb, threshold=0.2)
    assert n == 1  # only agg.tpu_ms regressed beyond 20%
    assert "agg.tpu_ms: REGRESSION" in text
    # self-diff is clean
    _, n2 = tpu_profile.run_diff(pa, pa, threshold=0.2)
    assert n2 == 0


# ---------------------------------------------------------------------------
# 5. instrumented subsystems through real runs
# ---------------------------------------------------------------------------
def test_shuffle_metrics_and_events(tmp_path):
    from spark_rapids_tpu import types as T

    sess = TpuSession({
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.shuffle.transport.class": "host",
        "spark.rapids.tpu.shuffle.mode": "host",
    })
    schema = T.StructType((T.StructField("k", T.IntegerType()),
                           T.StructField("v", T.LongType())))
    data = {"k": [i % 4 for i in range(64)], "v": list(range(64))}
    df = (sess.create_dataframe(data, schema, num_partitions=3)
          .group_by("k").agg(A.agg(A.Sum(col("v")), "s")))
    rows = sorted(df.collect())
    assert rows == sorted(
        (k, sum(v for v in range(64) if v % 4 == k)) for k in range(4))
    report = sess.explain_metrics()
    assert "shuffleBytesWritten=" in report
    assert "shuffleBytesFetched=" in report
    with open(sess.events.path) as f:
        recs = [json.loads(line) for line in f]
    writes = [r for r in recs if r["event"] == "shuffle_write"]
    fetches = [r for r in recs if r["event"] == "shuffle_fetch"]
    assert writes and all(r["bytes"] > 0 and r["codec"] == "none"
                          for r in writes)
    # the exchange shuffles PARTIAL aggregate outputs (keys x map
    # partitions), and every written row is fetched exactly once
    assert fetches and sum(r["rows"] for r in fetches) == sum(
        r["rows"] for r in writes) > 0


def test_spill_events_watermark_and_memory_footer(tmp_path):
    import numpy as np

    from spark_rapids_tpu.memory import SpillableVals
    from spark_rapids_tpu.memory.catalog import BufferCatalog
    from spark_rapids_tpu.expr.values import ColV

    logger = EV.EventLogger(RapidsConf(
        {"spark.rapids.tpu.eventLog.enabled": True}))
    EV.install(logger)
    try:
        import jax.numpy as jnp

        BufferCatalog.reset(RapidsConf(
            {"spark.rapids.tpu.memory.hbm.budgetBytes": 100_000}))
        cat = BufferCatalog.get()

        def val():
            return ColV(jnp.zeros(8192, jnp.int64),
                        jnp.ones(8192, jnp.bool_))

        a = SpillableVals([val()])   # ~72KB
        b = SpillableVals([val()])   # pushes over budget -> a spills
        assert cat.metrics.device_to_host >= 1
        assert cat.metrics.peak_device_bytes > 100_000
        a.get_vals()                  # unspill
        assert cat.metrics.unspills >= 1
        kinds = [r["kind"] for r in logger.records()
                 if r["event"] == "spill"]
        assert "device_to_host" in kinds and "unspill" in kinds
        watermarks = [r["device_bytes"] for r in logger.records()
                      if r["event"] == "spill"]
        assert all(isinstance(w, int) for w in watermarks)
        a.close()
        b.close()
    finally:
        EV.uninstall()
        BufferCatalog.reset()
    # the explain_metrics footer surfaces the catalog counters
    sess = TpuSession({})
    _run_query(sess)
    assert "memory: device" in sess.explain_metrics()


# ---------------------------------------------------------------------------
# 6. zero overhead when off
# ---------------------------------------------------------------------------
def test_disabled_event_log_emits_nothing(tmp_path, monkeypatch):
    calls = []
    real_emit = EV.EventLogger.emit

    def spy(self, etype, **fields):
        calls.append(etype)
        return real_emit(self, etype, **fields)

    monkeypatch.setattr(EV.EventLogger, "emit", spy)
    sess = TpuSession({})  # defaults: event log OFF
    assert sess.events.enabled is False and sess.events.path is None
    _run_query(sess)
    assert EV.enabled() is False
    assert calls == []                 # no EventLogger.emit calls at all
    assert sess.events.records() == []  # ring untouched
    assert list(tmp_path.iterdir()) == []  # no sink files anywhere


def test_op_timed_fast_path_unchanged_when_disabled():
    """With logging off, op_timed must not attach event plumbing: the
    context manager is the plain timed() with event_op=None (no per-batch
    dict build, no emit)."""
    from spark_rapids_tpu.exec.base import TpuExec, timed

    class Dummy(TpuExec):
        @property
        def output_schema(self):
            raise NotImplementedError

    d = Dummy(RapidsConf({}))
    seen = {}
    import spark_rapids_tpu.exec.base as base_mod

    orig = base_mod.timed

    def probe(metric, trace_name="", trace=False, event_op=None,
              event_section=""):
        seen["event_op"] = event_op
        return orig(metric, trace_name, trace, event_op, event_section)

    base_mod.timed = probe
    try:
        with d.op_timed():
            pass
    finally:
        base_mod.timed = orig
    assert seen["event_op"] is None
