"""Unit tests for the ops kernel layer against numpy/pure-python oracles.

Mirrors the reference's pure unit-test tier (SURVEY.md §4 tier 1/2):
kernels validated independently of the exec layer.
"""
import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import column_from_pylist
from spark_rapids_tpu.expr.eval import ColV, StrV
from spark_rapids_tpu.ops import filter_gather, groupby, hashing, sort

import jax.numpy as jnp


def colv_of(values, dtype):
    c = column_from_pylist(values, dtype)
    if c.is_string:
        return StrV(c.offsets, c.chars, c.validity), c
    return ColV(c.data, c.validity), c


def read_fixed(v: ColV, n):
    data = np.asarray(v.data)[:n]
    valid = np.asarray(v.validity)[:n]
    return [data[i].item() if valid[i] else None for i in range(n)]


def read_str(v: StrV, n):
    off = np.asarray(v.offsets)
    chars = np.asarray(v.chars).tobytes()
    valid = np.asarray(v.validity)[:n]
    return [
        chars[off[i]: off[i + 1]].decode() if valid[i] else None
        for i in range(n)
    ]


# ---------------------------------------------------------------------------
# filter / gather
# ---------------------------------------------------------------------------
class TestFilterGather:
    def test_filter_compacts_front(self):
        vals = [1, None, 3, 4, None, 6]
        v, col = colv_of(vals, T.INT)
        cap = col.capacity
        mask = np.zeros(cap, dtype=bool)
        mask[:6] = [True, False, True, False, False, True]
        out, count = filter_gather.filter_cols([v], jnp.asarray(mask), 6)
        assert int(count) == 3
        assert read_fixed(out[0], 3) == [1, 3, 6]

    def test_filter_keeps_nulls_when_selected(self):
        vals = [1, None, 3]
        v, col = colv_of(vals, T.INT)
        mask = np.zeros(col.capacity, dtype=bool)
        mask[:3] = [True, True, False]
        out, count = filter_gather.filter_cols([v], jnp.asarray(mask), 3)
        assert int(count) == 2
        assert read_fixed(out[0], 2) == [1, None]

    def test_string_gather(self):
        vals = ["hello", None, "spark", "", "tpu!"]
        v, col = colv_of(vals, T.STRING)
        idx = jnp.asarray(np.array([4, 2, 0, 1], dtype=np.int32))
        valid_slot = jnp.asarray(np.array([True, True, True, True]))
        out = filter_gather.gather_string(v, idx, valid_slot, int(v.chars.shape[0]))
        assert read_str(out, 4) == ["tpu!", "spark", "hello", None]

    def test_slice(self):
        vals = list(range(10))
        v, col = colv_of(vals, T.LONG)
        out, count = filter_gather.slice_cols([v], 3, 4, jnp.asarray(10))
        assert int(count) == 4
        assert read_fixed(out[0], 4) == [3, 4, 5, 6]

    def test_slice_past_end(self):
        vals = list(range(5))
        v, col = colv_of(vals, T.INT)
        out, count = filter_gather.slice_cols([v], 3, 4, jnp.asarray(5))
        assert int(count) == 2
        assert read_fixed(out[0], 2) == [3, 4]


# ---------------------------------------------------------------------------
# sort
# ---------------------------------------------------------------------------
class TestSort:
    def _sort(self, values, dtype, ascending=True, nulls_first=None, n=None):
        v, col = colv_of(values, dtype)
        n = n or len(values)
        out = sort.sort_cols(
            [v], [0], [dtype], [sort.SortOrder(ascending, nulls_first)], n,
            str_max_lens=[64],
        )
        if isinstance(out[0], StrV):
            return read_str(out[0], n)
        return read_fixed(out[0], n)

    def test_int_asc_nulls_first(self):
        got = self._sort([5, None, 3, -7, None, 0], T.INT)
        assert got == [None, None, -7, 0, 3, 5]

    def test_int_desc_nulls_last(self):
        got = self._sort([5, None, 3, -7, None, 0], T.INT, ascending=False)
        assert got == [5, 3, 0, -7, None, None]

    def test_float_nan_sorts_largest(self):
        got = self._sort([1.5, float("nan"), -2.0, float("inf"), None], T.DOUBLE)
        assert got[0] is None
        assert got[1] == -2.0 and got[2] == 1.5 and got[3] == float("inf")
        assert np.isnan(got[4])

    def test_negative_zero_equals_zero_stable(self):
        # -0.0 and 0.0 compare equal; stable sort keeps input order
        got = self._sort([0.0, -0.0, 1.0, -1.0], T.DOUBLE)
        assert got == [-1.0, 0.0, -0.0, 1.0] or got == [-1.0, 0.0, 0.0, 1.0]

    def test_string_binary_order(self):
        vals = ["pear", "Pear", "apple", None, "app", "", "applesauce"]
        got = self._sort(vals, T.STRING)
        assert got == [None, "", "Pear", "app", "apple", "applesauce", "pear"]

    def test_string_desc(self):
        vals = ["b", "a", None, "c"]
        got = self._sort(vals, T.STRING, ascending=False)
        assert got == ["c", "b", "a", None]

    def test_multi_key(self):
        a_vals = [1, 1, 2, 2, 1]
        b_vals = [9.0, 1.0, 5.0, None, 4.0]
        va, _ = colv_of(a_vals, T.INT)
        vb, _ = colv_of(b_vals, T.DOUBLE)
        out = sort.sort_cols(
            [va, vb], [0, 1], [T.INT, T.DOUBLE],
            [sort.SortOrder(True), sort.SortOrder(False)], 5,
        )
        assert read_fixed(out[0], 5) == [1, 1, 1, 2, 2]
        assert read_fixed(out[1], 5) == [9.0, 4.0, 1.0, 5.0, None]

    def test_int64_extremes(self):
        vals = [2**62, -(2**62), 0, None, -1]
        got = self._sort(vals, T.LONG)
        assert got == [None, -(2**62), -1, 0, 2**62]


# ---------------------------------------------------------------------------
# groupby
# ---------------------------------------------------------------------------
class TestGroupBy:
    def test_sum_count_by_int_key(self):
        keys = [1, 2, 1, None, 2, 1, None]
        vals = [10, 20, 30, 40, None, 50, 60]
        kv, _ = colv_of(keys, T.INT)
        vv, _ = colv_of(vals, T.LONG)
        out_keys, out_aggs, n = groupby.sort_groupby(
            [kv], [T.INT], [vv, vv, None], ["sum", "count", "count_star"], 7
        )
        ng = int(n)
        assert ng == 3
        k = read_fixed(out_keys[0], ng)
        s = read_fixed(out_aggs[0], ng)
        c = read_fixed(out_aggs[1], ng)
        cs = read_fixed(out_aggs[2], ng)
        by_key = dict(zip(k, zip(s, c, cs)))
        assert by_key[None] == (100, 2, 2)
        assert by_key[1] == (90, 3, 3)
        assert by_key[2] == (20, 1, 2)

    def test_min_max_with_nan(self):
        keys = [1, 1, 1, 2, 2]
        vals = [float("nan"), 3.0, 1.0, float("nan"), None]
        kv, _ = colv_of(keys, T.INT)
        vv, _ = colv_of(vals, T.DOUBLE)
        out_keys, out_aggs, n = groupby.sort_groupby(
            [kv], [T.INT], [vv, vv], ["min", "max"], 5
        )
        ng = int(n)
        k = read_fixed(out_keys[0], ng)
        mn = read_fixed(out_aggs[0], ng)
        mx = read_fixed(out_aggs[1], ng)
        d = dict(zip(k, zip(mn, mx)))
        # group 1: min skips NaN -> 1.0, max -> NaN (NaN is largest)
        assert d[1][0] == 1.0 and np.isnan(d[1][1])
        # group 2: only NaN (null skipped) -> min = max = NaN
        assert np.isnan(d[2][0]) and np.isnan(d[2][1])

    def test_all_null_group_sum_is_null(self):
        keys = [1, 1, 2]
        vals = [None, None, 5]
        kv, _ = colv_of(keys, T.INT)
        vv, _ = colv_of(vals, T.INT)
        out_keys, out_aggs, n = groupby.sort_groupby(
            [kv], [T.INT], [vv], ["sum"], 3
        )
        ng = int(n)
        d = dict(zip(read_fixed(out_keys[0], ng), read_fixed(out_aggs[0], ng)))
        assert d[1] is None and d[2] == 5

    def test_string_keys(self):
        keys = ["a", "b", "a", None, "b", "ab"]
        vals = [1, 2, 3, 4, 5, 6]
        kv, _ = colv_of(keys, T.STRING)
        vv, _ = colv_of(vals, T.LONG)
        out_keys, out_aggs, n = groupby.sort_groupby(
            [kv], [T.STRING], [vv], ["sum"], 6, str_max_lens=[8]
        )
        ng = int(n)
        assert ng == 4
        d = dict(zip(read_str(out_keys[0], ng), read_fixed(out_aggs[0], ng)))
        assert d == {None: 4, "a": 4, "b": 7, "ab": 6}

    def test_first_last(self):
        keys = [1, 1, 1, 2]
        vals = [None, 7, 8, 9]
        kv, _ = colv_of(keys, T.INT)
        vv, _ = colv_of(vals, T.INT)
        out_keys, out_aggs, n = groupby.sort_groupby(
            [kv], [T.INT],
            [vv, vv, vv, vv],
            ["first", "last", "first_ignorenulls", "last_ignorenulls"], 4
        )
        ng = int(n)
        k = read_fixed(out_keys[0], ng)
        rows = {
            k[i]: tuple(read_fixed(a, ng)[i] for a in out_aggs)
            for i in range(ng)
        }
        assert rows[1] == (None, 8, 7, 8)
        assert rows[2] == (9, 9, 9, 9)

    def test_reduce_no_keys(self):
        vals = [1.0, None, 3.0]
        vv, _ = colv_of(vals, T.DOUBLE)
        outs = groupby.reduce_no_keys([vv, vv, None], ["sum", "count", "count_star"], 3)
        assert read_fixed(outs[0], 1) == [4.0]
        assert read_fixed(outs[1], 1) == [2]
        assert read_fixed(outs[2], 1) == [3]


# ---------------------------------------------------------------------------
# murmur3 — oracle is a straight transcription of Spark's Murmur3_x86_32
# ---------------------------------------------------------------------------
M32 = 0xFFFFFFFF


def _rotl(x, r):
    return ((x << r) | (x >> (32 - r))) & M32


def _mixk1(k1):
    k1 = (k1 * 0xCC9E2D51) & M32
    k1 = _rotl(k1, 15)
    return (k1 * 0x1B873593) & M32


def _mixh1(h1, k1):
    h1 = (h1 ^ k1) & M32
    h1 = _rotl(h1, 13)
    return (h1 * 5 + 0xE6546B64) & M32


def _fmix(h1, length):
    h1 = (h1 ^ length) & M32
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & M32
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & M32
    h1 ^= h1 >> 16
    return h1


def oracle_hash_int(x, seed):
    return _fmix(_mixh1(seed, _mixk1(x & M32)), 4)


def oracle_hash_long(x, seed):
    x &= 0xFFFFFFFFFFFFFFFF
    h1 = _mixh1(seed, _mixk1(x & M32))
    h1 = _mixh1(h1, _mixk1((x >> 32) & M32))
    return _fmix(h1, 8)


def oracle_hash_bytes(b, seed):
    h1 = seed
    n = len(b) - len(b) % 4
    for i in range(0, n, 4):
        word = int.from_bytes(b[i: i + 4], "little")
        h1 = _mixh1(h1, _mixk1(word))
    for i in range(n, len(b)):
        sbyte = b[i] - 256 if b[i] >= 128 else b[i]
        h1 = _mixh1(h1, _mixk1(sbyte & M32))
    return _fmix(h1, len(b))


def as_i32(u):
    return u - (1 << 32) if u >= (1 << 31) else u


class TestMurmur3:
    def test_int_column(self):
        vals = [0, 1, -1, 2**31 - 1, -(2**31), 42, None]
        v, _ = colv_of(vals, T.INT)
        got = np.asarray(hashing.murmur3([v], [T.INT]))[:7]
        for i, x in enumerate(vals):
            exp = 42 if x is None else as_i32(oracle_hash_int(x, 42))
            assert got[i] == exp, (i, x)

    def test_long_column(self):
        vals = [0, 1, -1, 2**63 - 1, -(2**63), 123456789012345]
        v, _ = colv_of(vals, T.LONG)
        got = np.asarray(hashing.murmur3([v], [T.LONG]))[:6]
        for i, x in enumerate(vals):
            assert got[i] == as_i32(oracle_hash_long(x, 42)), (i, x)

    def test_double_column(self):
        import struct
        vals = [0.0, -0.0, 1.5, -2.25, float("nan")]
        v, _ = colv_of(vals, T.DOUBLE)
        got = np.asarray(hashing.murmur3([v], [T.DOUBLE]))[:5]
        for i, x in enumerate(vals):
            if x == 0.0:
                x = 0.0  # -0.0 normalized
            bits = struct.unpack("<q", struct.pack("<d", x))[0]
            assert got[i] == as_i32(oracle_hash_long(bits, 42)), (i, x)

    def test_string_column(self):
        vals = ["", "a", "ab", "abc", "abcd", "abcde", "hello world!", None]
        v, _ = colv_of(vals, T.STRING)
        got = np.asarray(hashing.murmur3([v], [T.STRING], str_max_lens=[16]))[:8]
        for i, x in enumerate(vals):
            exp = 42 if x is None else as_i32(oracle_hash_bytes(x.encode(), 42))
            assert got[i] == exp, (i, x)

    def test_multi_column_seed_chain(self):
        a, _ = colv_of([1, None], T.INT)
        b, _ = colv_of([5, 6], T.LONG)
        got = np.asarray(hashing.murmur3([a, b], [T.INT, T.LONG]))[:2]
        e0 = oracle_hash_long(5, oracle_hash_int(1, 42))
        e1 = oracle_hash_long(6, 42)  # null int leaves seed untouched
        assert got[0] == as_i32(e0)
        assert got[1] == as_i32(e1)

    def test_partition_ids_nonnegative(self):
        v, _ = colv_of(list(range(100)), T.INT)
        h = hashing.murmur3([v], [T.INT])
        p = np.asarray(hashing.partition_ids(h, 7))
        assert p.min() >= 0 and p.max() < 7


class TestBucketReduceLowerings:
    """The bucket reduction has two lowerings — MXU limb matmuls (TPU)
    and native-dtype segment sums (CPU, where the one-hot can't fuse).
    They must agree exactly on integers/counts and to f64 rounding on
    floats, including int64 wraparound and dropped out-of-range ids."""

    def _inputs(self):
        import numpy as np

        rng = np.random.default_rng(7)
        n, B = 4096, 64
        seg = rng.integers(0, B, n).astype(np.int32)
        seg[:17] = B  # dead rows: must drop from every reduction
        ival = rng.integers(-(2 ** 62), 2 ** 62, n)  # wraparound territory
        fval = rng.uniform(-1e6, 1e6, n)
        valid = rng.random(n) > 0.1
        return seg, B, ival, fval, valid

    def test_scatter_vs_matmul_paths(self):
        import jax.numpy as jnp

        from spark_rapids_tpu.ops import bucket_reduce as BR

        seg, B, ival, fval, valid = self._inputs()
        args = (jnp.asarray(seg), B,
                [(jnp.asarray(ival), jnp.asarray(valid))],
                [jnp.asarray(valid)],
                [(jnp.asarray(fval), jnp.asarray(valid))])
        fast = BR.bucket_reduce(*args)
        old = BR.FORCE_MATMUL
        BR.FORCE_MATMUL = True
        try:
            exact = BR.bucket_reduce(*args)
        finally:
            BR.FORCE_MATMUL = old
        assert (fast[0][0] == exact[0][0]).all()  # int64, incl. wraparound
        assert (fast[1][0] == exact[1][0]).all()  # counts
        import numpy as np

        # the scatter path is a straight f64 sum (exact vs a numpy oracle);
        # the matmul's f32 hi/lo split loses bits under cancellation —
        # that's the approx-float-agg contract, so compare at its tolerance
        f1, f2 = np.asarray(fast[2][0]), np.asarray(exact[2][0])
        assert np.allclose(f1, f2, rtol=1e-4, atol=1e-6)

    def test_lookup_vs_matmul_paths(self):
        import numpy as np

        import jax.numpy as jnp

        from spark_rapids_tpu.ops import bucket_reduce as BR

        rng = np.random.default_rng(9)
        n, B = 512, 32
        seg = rng.integers(0, B + 1, n).astype(np.int32)  # incl. dead id B
        table = rng.integers(0, 2 ** 32, B, dtype=np.uint64).astype(np.uint32)
        a = BR.bucket_lookup_u32(jnp.asarray(seg), B, jnp.asarray(table))
        old = BR.FORCE_MATMUL
        BR.FORCE_MATMUL = True
        try:
            b = BR.bucket_lookup_u32(jnp.asarray(seg), B, jnp.asarray(table))
        finally:
            BR.FORCE_MATMUL = old
        assert (np.asarray(a[0]) == np.asarray(b[0])).all()
        assert (np.asarray(a[1]) == np.asarray(b[1])).all()
