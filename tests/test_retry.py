"""OOM retry + split-and-retry plane (memory/retry.py) and the
deterministic fault injector (faults.py).

Coverage, per the round-13 issue:
  * injector spec grammar (@N / %K / >C / ?K seeded) + determinism;
  * the OOM classifier over backend message patterns;
  * batch-split differential suite: depths 1-3 over the torture set
    (dict strings, all-null columns, zero-column count(*) batches,
    non-pow2 row counts) diffed row-exact against the unsplit batch,
    with the capacity-bucket/validity-padding invariants asserted;
  * the five-strategy aggregation matrix under forced splits, row-exact
    vs the CPU oracle;
  * retry -> success, split -> success, exhaustion -> typed
    TpuSplitAndRetryOOM (never a raw RESOURCE_EXHAUSTED escape);
  * named TpuOutOfDeviceMemory wrapping outside the harness;
  * serve integration: reservation released on OOM, ONE requeue with the
    forecast inflated, typed error on double failure;
  * reservation/semaphore leak audit across 8 failing queries;
  * shuffle fetch retry counters + capped exponential backoff;
  * the zero-overhead-off spy (no injector consulted, no harness
    machinery touched, with the confs at defaults);
  * watchdog retry-storm rule (live tick + offline replay) and the
    tpu_profile '== resilience ==' section.
"""
import threading

import numpy as np
import pytest

import spark_rapids_tpu  # noqa: F401
from spark_rapids_tpu import events as EV
from spark_rapids_tpu import faults
from spark_rapids_tpu import obs
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import ColumnarBatch, schema_of, split_batch
from spark_rapids_tpu.columnar.column import (
    choose_capacity,
    dict_column_from_pylist,
)
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.memory import (
    BufferCatalog,
    TpuOutOfDeviceMemory,
    TpuRetryOOM,
    TpuSemaphore,
    TpuSplitAndRetryOOM,
    is_device_oom,
    named_oom,
    with_oom_retry,
    with_oom_retry_nosplit,
)
from spark_rapids_tpu.memory.retry import concat_batches
from spark_rapids_tpu.serve import QueryScheduler, SharedPlanCache
from spark_rapids_tpu.sql import TpuSession
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.expressions import col, lit

from harness import compare_rows


@pytest.fixture(autouse=True)
def _clean_world():
    faults.uninstall()
    EV.uninstall()
    QueryScheduler.reset()
    SharedPlanCache.reset()
    BufferCatalog.reset()
    TpuSemaphore.reset()
    yield
    faults.uninstall()
    EV.uninstall()
    QueryScheduler.reset()
    SharedPlanCache.reset()
    BufferCatalog.reset()
    TpuSemaphore.reset()


NO_BACKOFF = {"spark.rapids.tpu.memory.oomRetry.backoffMs": 0}


def _q(sess):
    return (sess.range(0, 1024)
            .where(E.GreaterThanOrEqual(col("id"), lit(100)))
            .select(col("id"), E.Alias(E.Multiply(col("id"), lit(2)), "v"))
            .agg(A.agg(A.Sum(col("v")), "s"), A.agg(A.Count(None), "c")))


def _oracle():
    return _q(TpuSession({"spark.rapids.tpu.sql.enabled": False})).collect()


# ---------------------------------------------------------------------------
# 1. injector spec grammar + determinism
# ---------------------------------------------------------------------------
def test_fault_spec_nth_every_and_always():
    inj = faults.FaultInjector(RapidsConf(
        {"spark.rapids.tpu.test.faults.oom": "siteA@2,siteB%3,siteC"}))
    inj.check("oom", "siteA")  # arrival 1: no fire
    with pytest.raises(faults.InjectedOOM):
        inj.check("oom", "siteA")  # arrival 2
    inj.check("oom", "siteA")  # arrival 3: @2 fired once only
    for arrival in range(1, 7):
        if arrival % 3 == 0:
            with pytest.raises(faults.InjectedOOM):
                inj.check("oom", "siteB")
        else:
            inj.check("oom", "siteB")
    with pytest.raises(faults.InjectedOOM):
        inj.check("oom", "siteC")  # always


def test_fault_spec_cap_threshold_and_wildcard():
    inj = faults.FaultInjector(RapidsConf(
        {"spark.rapids.tpu.test.faults.oom": "Tpu*>512"}))
    inj.check("oom", "TpuSortExec", cap=512)  # not above
    with pytest.raises(faults.InjectedOOM):
        inj.check("oom", "TpuSortExec", cap=1024)
    inj.check("oom", "Other", cap=4096)  # pattern mismatch


def test_fault_spec_validation_rejects_bad_entries():
    for bad in ("site%0", "site@0", "site?0", "site@x", "site>-1"):
        with pytest.raises(ValueError):
            faults.FaultInjector(RapidsConf(
                {"spark.rapids.tpu.test.faults.oom": bad}))
    # fnmatch '?' inside a pattern survives when a real separator follows
    inj = faults.FaultInjector(RapidsConf(
        {"spark.rapids.tpu.test.faults.oom": "Tpu?ortExec@1"}))
    with pytest.raises(faults.InjectedOOM):
        inj.check("oom", "TpuSortExec")


def test_fault_spec_seeded_is_deterministic():
    def fires_at(seed):
        inj = faults.FaultInjector(RapidsConf({
            "spark.rapids.tpu.test.faults.oom": "s?8",
            "spark.rapids.tpu.test.faults.seed": seed}))
        for arrival in range(1, 9):
            try:
                inj.check("oom", "s")
            except faults.InjectedOOM:
                return arrival
        return None

    a = fires_at(7)
    assert a is not None and a == fires_at(7)
    # a different seed may pick a different arrival; same seed replays
    assert fires_at(13) == fires_at(13)


def test_injected_oom_classifies_as_device_oom():
    assert is_device_oom(faults.InjectedOOM("RESOURCE_EXHAUSTED: x"))
    assert is_device_oom(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory allocating 1073741824 bytes"))
    assert is_device_oom(RuntimeError("Failed to allocate request"))
    assert not is_device_oom(RuntimeError("shape mismatch"))
    assert not is_device_oom(TpuSplitAndRetryOOM("final"))
    # the named raw-site wrapper stays retryable by a surrounding harness
    assert is_device_oom(TpuOutOfDeviceMemory("raw"))


# ---------------------------------------------------------------------------
# 2. batch-split differential suite (torture set, depths 1-3)
# ---------------------------------------------------------------------------
def _torture_batch(n: int) -> ColumnarBatch:
    schema = schema_of(i=T.INT, d=T.DOUBLE, s=T.STRING, nul=T.LONG)
    data = {
        "i": [None if k % 7 == 0 else (k * 3) % 251 - 100 for k in range(n)],
        "d": [None if k % 11 == 0 else k / 3.0 - 5.0 for k in range(n)],
        "s": [None if k % 5 == 0 else ("x" * (k % 4)) + str(k)
              for k in range(n)],
        "nul": [None] * n,
    }
    batch = ColumnarBatch.from_pydict(data, schema)
    # ride a dict-encoded column alongside (aux planes must survive)
    dc = dict_column_from_pylist(
        [None if k % 3 == 0 else f"d{k % 6}" for k in range(n)])
    cols = list(batch.columns) + [dc]
    full = T.StructType(tuple(
        list(schema.fields) + [T.StructField("dict", T.STRING)]))
    return ColumnarBatch(cols, full, n)


def _split_rec(batch, depth):
    if depth == 0 or batch.num_rows < 2:
        return [batch]
    lo, hi = split_batch(batch)
    return _split_rec(lo, depth - 1) + _split_rec(hi, depth - 1)


@pytest.mark.parametrize("n", [5, 7, 1000])
@pytest.mark.parametrize("depth", [1, 2, 3])
def test_split_depths_row_exact_vs_unsplit_oracle(n, depth):
    batch = _torture_batch(n)
    want = batch.to_pydict()
    pieces = _split_rec(batch, depth)
    assert sum(p.num_rows for p in pieces) == n
    got = {k: [] for k in want}
    for p in pieces:
        # capacity-bucket invariant: every piece repacked to its own
        # sanctioned bucket, validity padding all-False beyond the rows
        for c in p.columns:
            assert c.capacity == choose_capacity(max(1, p.num_rows))
            v = np.asarray(c.validity)
            assert not v[p.num_rows:].any()
        for k, vs in p.to_pydict().items():
            got[k].extend(vs)
    assert got == want
    # and the pieces re-join row-exact through the standard concat path
    rejoined = concat_batches(RapidsConf({}), pieces)
    assert rejoined.to_pydict() == want


def test_split_zero_column_batch_keeps_capacity_bucket():
    schema = T.StructType(())
    b = ColumnarBatch([], schema, 1000, capacity=choose_capacity(1000))
    lo, hi = split_batch(b)
    assert (lo.num_rows, hi.num_rows) == (500, 500)
    assert lo.capacity == choose_capacity(500)
    assert hi.capacity == choose_capacity(500)


def test_split_floor_raises():
    b = ColumnarBatch.from_pydict({"a": [1]}, schema_of(a=T.INT))
    with pytest.raises(ValueError):
        split_batch(b)


# ---------------------------------------------------------------------------
# 3. five-strategy aggregation matrix under forced splits
# ---------------------------------------------------------------------------
@pytest.mark.parametrize(
    "strategy", ["MATMUL", "SCATTER", "SORT", "RADIX", "PALLAS"])
def test_agg_strategies_row_exact_under_forced_splits(strategy):
    n = 1000  # non-pow2; capacity bucket 1024 > the >256 fault threshold
    data = {
        "k": [i % 7 if i % 11 else None for i in range(n)],
        "a": [(i * 13) % 400 - 200 for i in range(n)],
        "b": [None if i % 9 == 0 else i * 5 for i in range(n)],
    }
    schema = schema_of(k=T.INT, a=T.LONG, b=T.LONG)

    def build(s):
        return (s.create_dataframe(data, schema).group_by("k")
                .agg(A.agg(A.Sum(col("a")), "sa"),
                     A.agg(A.Min(col("a")), "mn"),
                     A.agg(A.Max(col("b")), "mx"),
                     A.agg(A.Count(col("b")), "cb"),
                     A.agg(A.Count(None), "cs")))

    cpu = build(TpuSession({"spark.rapids.tpu.sql.enabled": False})).collect()
    sess = TpuSession({
        "spark.rapids.tpu.sql.agg.strategy": strategy,
        "spark.rapids.tpu.test.faults.oom": "TpuHashAggregateExec>256",
        **NO_BACKOFF})
    got = build(sess).collect()
    compare_rows(cpu, got)
    inj = faults.active()
    assert inj is not None and inj.fired(), \
        "fault never fired — the split path was not exercised"


# ---------------------------------------------------------------------------
# 4. retry / split / exhaustion through the engine
# ---------------------------------------------------------------------------
def test_retry_once_then_success_with_events():
    oracle = _oracle()
    sess = TpuSession({
        "spark.rapids.tpu.test.faults.oom": "TpuHashAggregateExec@1",
        "spark.rapids.tpu.eventLog.enabled": True, **NO_BACKOFF})
    assert _q(sess).collect() == oracle
    evs = [r for r in sess.events.records() if r["event"] == "oom_retry"]
    assert evs, "no oom_retry events recorded"
    assert all(r["op"] == "TpuHashAggregateExec" for r in evs)


def test_split_paths_for_sort_join_project():
    n = 1000
    data = {"k": [i % 13 for i in range(n)],
            "v": [None if i % 17 == 0 else (i * 7) % 500 for i in range(n)]}
    schema = schema_of(k=T.INT, v=T.LONG)
    rdata = {"k": [i for i in range(13)],
             "w": [i * 100 for i in range(13)]}
    rschema = schema_of(k=T.INT, w=T.LONG)

    def builds(s):
        left = s.create_dataframe(data, schema)
        right = s.create_dataframe(rdata, rschema)
        return {
            "TpuProjectExec": left.select(
                col("k"), E.Alias(E.Add(col("v"), lit(1)), "v1")),
            "TpuSortExec": left.order_by("v", "k"),
            "TpuShuffledHashJoinExec": left.join(right, "k"),
        }

    cpu = {name: df.collect() for name, df in builds(
        TpuSession({"spark.rapids.tpu.sql.enabled": False})).items()}
    for name, want in cpu.items():
        sess = TpuSession({
            "spark.rapids.tpu.test.faults.oom": f"{name}*>512",
            **NO_BACKOFF})
        got = builds(sess)[name].collect()
        ignore_order = name != "TpuSortExec"
        compare_rows(want, got, ignore_order=ignore_order)
        inj = faults.active()
        assert inj is not None and inj.fired(), name
        faults.uninstall()


def test_exhaustion_raises_typed_error_not_raw():
    sess = TpuSession({
        "spark.rapids.tpu.test.faults.oom": "TpuHashAggregateExec",
        "spark.rapids.tpu.memory.oomRetry.maxSplitDepth": 2, **NO_BACKOFF})
    with pytest.raises(TpuSplitAndRetryOOM) as ei:
        _q(sess).collect()
    e = ei.value
    assert e.op == "TpuHashAggregateExec"
    assert e.attempts >= 2 and e.split_depth == 2
    assert "RESOURCE_EXHAUSTED" in str(e)  # cause named, type is ours


def test_retry_disabled_propagates_raw():
    sess = TpuSession({
        "spark.rapids.tpu.test.faults.oom": "TpuHashAggregateExec",
        "spark.rapids.tpu.memory.oomRetry.enabled": False})
    with pytest.raises(faults.InjectedOOM):
        _q(sess).collect()


def test_nosplit_harness_raises_typed_retry_oom():
    conf = RapidsConf(NO_BACKOFF)

    def boom():
        raise RuntimeError("RESOURCE_EXHAUSTED: no memory")

    with pytest.raises(TpuRetryOOM) as ei:
        with_oom_retry_nosplit("mergesite", boom, conf)
    assert ei.value.op == "mergesite" and ei.value.attempts == 2


def test_named_oom_wraps_raw_failures():
    with pytest.raises(TpuOutOfDeviceMemory) as ei:
        with named_oom("scan.decode"):
            raise RuntimeError("RESOURCE_EXHAUSTED: upload failed")
    assert ei.value.op == "scan.decode"
    assert "largest spillable" in str(ei.value)
    # non-OOM failures pass through untouched
    with pytest.raises(ValueError):
        with named_oom("scan.decode"):
            raise ValueError("not an oom")


def test_ensure_headroom_respects_host_cap_without_budget():
    import jax.numpy as jnp

    from spark_rapids_tpu.memory import SpillableHandle, TIER_DISK

    # NO device budget (backend reports nothing) but a tiny host cap:
    # the emergency spill must still push the host overage to disk —
    # recovering from device exhaustion must not manufacture host
    # exhaustion
    cat = BufferCatalog.reset(RapidsConf({
        "spark.rapids.tpu.memory.host.spillStorageSize": 1}))
    assert cat.budget is None
    h = SpillableHandle({"x": jnp.zeros(4096, jnp.int32)}, catalog=cat)
    freed = cat.ensure_headroom()
    assert freed == h.size
    assert h.tier == TIER_DISK, "host overage not drained to disk"
    assert cat.metrics.host_to_disk == 1
    h.close()


def test_harness_releases_pressure_by_spilling():
    import jax.numpy as jnp

    from spark_rapids_tpu.memory import SpillableHandle, TIER_HOST

    cat = BufferCatalog.reset(RapidsConf({}))
    h = SpillableHandle({"x": jnp.zeros(1024, jnp.int32)}, catalog=cat)
    conf = RapidsConf(NO_BACKOFF)
    calls = [0]

    def attempt(b):
        calls[0] += 1
        if calls[0] == 1:
            raise RuntimeError("RESOURCE_EXHAUSTED: injected")
        return b

    b = ColumnarBatch.from_pydict({"a": [1, 2, 3]}, schema_of(a=T.INT))
    out = with_oom_retry("op", attempt, b, conf)
    assert out is b
    # the retry's pressure release spilled the catalog buffer to host
    assert h.tier == TIER_HOST
    assert cat.metrics.device_to_host == 1
    h.close()


# ---------------------------------------------------------------------------
# 5. serve integration: requeue once, reservation hygiene
# ---------------------------------------------------------------------------
def _serve_settings(extra=None):
    s = {"spark.rapids.tpu.serve.enabled": True, **NO_BACKOFF}
    s.update(extra or {})
    return s


def test_serve_requeues_once_with_inflated_forecast():
    settings = _serve_settings({
        # first submit: fused-plan probe (@1) then the streaming harness
        # (@2, maxAttempts=1, depth 0) -> typed OOM -> requeue; the
        # requeued run's fused-plan probe (arrival 3) passes
        "spark.rapids.tpu.test.faults.oom":
            "TpuHashAggregateExec@1,TpuHashAggregateExec@2",
        "spark.rapids.tpu.memory.oomRetry.maxAttempts": 1,
        "spark.rapids.tpu.memory.oomRetry.maxSplitDepth": 0})
    QueryScheduler.reset(RapidsConf(settings))
    oracle = _oracle()
    sess = TpuSession(settings)
    assert _q(sess).collect() == oracle
    st = QueryScheduler.instance().stats()
    assert st["oom_requeues"] == 1, st
    assert st["active"] == 0 and st["waiting"] == 0, st
    assert BufferCatalog.get().reserved_bytes == 0


def test_serve_double_oom_raises_typed_after_one_requeue():
    settings = _serve_settings({
        "spark.rapids.tpu.test.faults.oom": "TpuHashAggregateExec",
        "spark.rapids.tpu.memory.oomRetry.maxAttempts": 1,
        "spark.rapids.tpu.memory.oomRetry.maxSplitDepth": 0})
    QueryScheduler.reset(RapidsConf(settings))
    sess = TpuSession(settings)
    with pytest.raises(TpuSplitAndRetryOOM):
        _q(sess).collect()
    st = QueryScheduler.instance().stats()
    assert st["oom_requeues"] == 1, st
    assert st["active"] == 0 and st["waiting"] == 0, st
    assert BufferCatalog.get().reserved_bytes == 0


def test_leak_audit_eight_failing_queries():
    settings = _serve_settings({
        "spark.rapids.tpu.test.faults.oom": "*",
        "spark.rapids.tpu.memory.oomRetry.maxAttempts": 1,
        "spark.rapids.tpu.memory.oomRetry.maxSplitDepth": 0})
    QueryScheduler.reset(RapidsConf(settings))
    sess = TpuSession(settings)
    failures = 0
    for _ in range(8):
        try:
            _q(sess).collect()
        except (TpuSplitAndRetryOOM, TpuRetryOOM, TpuOutOfDeviceMemory):
            failures += 1
    assert failures == 8
    cat = BufferCatalog.get()
    assert cat.reserved_bytes == 0, "leaked admission reservations"
    assert TpuSemaphore.get().holder_names() == [], "leaked semaphore"
    st = QueryScheduler.instance().stats()
    assert st["active"] == 0 and st["waiting"] == 0, st
    with cat._lock:
        pinned = [h for h in cat._buffers.values() if h.pinned]
    assert not pinned, "leaked pinned buffers"


# ---------------------------------------------------------------------------
# 6. shuffle fetch: capped exponential backoff + retry counters
# ---------------------------------------------------------------------------
def test_fetch_backoff_is_capped_exponential():
    from spark_rapids_tpu.shuffle.network import ShuffleClient

    c = ShuffleClient(("127.0.0.1", 1), retry_wait_s=0.2,
                      retry_wait_cap_s=0.5)
    for attempt in range(8):
        span = min(0.5, 0.2 * (1 << attempt))
        for _ in range(16):
            d = c._backoff(attempt)
            assert span * 0.5 <= d <= span


def test_network_fetch_retries_counted_and_surfaced():
    from spark_rapids_tpu.shuffle.network import (
        NetworkShuffleTransport,
        ShuffleClient,
        ShuffleServer,
    )

    server = ShuffleServer()
    try:
        faults.install(RapidsConf(
            {"spark.rapids.tpu.test.faults.fetch": "network_fetch@1"}))
        client = ShuffleClient(server.address, retry_wait_s=0.01)
        t = NetworkShuffleTransport(server=None, remotes=(),
                                    owns_server=False)
        t._clients = [client]
        assert client.fetch_serialized(1, 0) == []
        assert client.retry_count == 1 and client.failure_count == 0
        st = t.stats()
        assert st["fetch_retries"] == 1 and st["fetch_failures"] == 0
    finally:
        server.close()


def test_network_fetch_exhaustion_counts_failure():
    from spark_rapids_tpu.shuffle.network import (
        FetchFailedError,
        ShuffleClient,
    )

    c = ShuffleClient(("127.0.0.1", 9), retries=2, retry_wait_s=0.01)
    with pytest.raises(FetchFailedError):
        c.fetch_serialized(1, 0)
    assert c.failure_count == 1 and c.retry_count == 1


# ---------------------------------------------------------------------------
# 7. zero-overhead-off spy
# ---------------------------------------------------------------------------
def test_zero_overhead_when_confs_off(monkeypatch):
    from spark_rapids_tpu.memory import retry as retry_mod

    consulted = []
    orig_check = faults.FaultInjector.check

    def spy_check(self, *a, **k):
        consulted.append("check")
        return orig_check(self, *a, **k)

    monkeypatch.setattr(faults.FaultInjector, "check", spy_check)
    recovered = []
    monkeypatch.setattr(
        retry_mod, "_release_pressure",
        lambda *a, **k: recovered.append(1) or 0)
    sess = TpuSession({})  # defaults: injector off, retry on but idle
    rows = _q(sess).collect()
    assert rows == _oracle()
    assert faults.enabled() is False and faults.active() is None
    assert consulted == [], "injector consulted with confs off"
    assert recovered == [], "recovery machinery ran on a clean query"


# ---------------------------------------------------------------------------
# 8. watchdog retry-storm + profiler resilience section
# ---------------------------------------------------------------------------
def test_watchdog_retry_storm_alerts_once_per_episode():
    from spark_rapids_tpu.obs.registry import MetricsRegistry
    from spark_rapids_tpu.obs.watchdog import (
        RETRY_STORM,
        Watchdog,
        WatchdogRules,
    )

    reg = MetricsRegistry()
    dog = Watchdog(reg, WatchdogRules(retry_storm_threshold=4), budget=0)
    for _ in range(4):
        reg.note_oom_retry("TpuSortExec")
    new = dog.check_now()
    assert [a.kind for a in new] == [RETRY_STORM]
    assert new[0].detail == "TpuSortExec" and new[0].value == 4
    assert dog.check_now() == []  # still storming: one alert per episode


def test_replay_alerts_flags_retry_storm():
    from spark_rapids_tpu.obs.watchdog import (
        RETRY_STORM,
        WatchdogRules,
        replay_alerts,
    )

    base = 1_000_000
    events = [
        {"ts": base + i * 1_000_000, "event": "oom_retry",
         "op": "TpuHashAggregateExec", "kind": "retry", "attempt": 1,
         "depth": 0, "watermark": 0, "budget": None}
        for i in range(5)
    ]
    alerts = replay_alerts(
        events, WatchdogRules(retry_storm_threshold=5))
    assert [a.kind for a in alerts] == [RETRY_STORM]


def test_profiler_resilience_section(tmp_path):
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "tpu_profile", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "tpu_profile.py"))
    tp = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tp)

    sess = TpuSession({
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.test.faults.oom": "TpuHashAggregateExec>256",
        **NO_BACKOFF})
    _q(sess).collect()
    sess.close()
    events = tp.load_events([str(tmp_path)])
    report, violations = tp.build_report(events)
    assert violations == 0, report
    assert "== resilience ==" in report
    body = report.split("== resilience ==", 1)[1].split("==", 1)[0]
    assert "TpuHashAggregateExec" in body
    assert "batch split" in body
    # and the events render on the Perfetto resilience track
    trace = EV.chrome_trace(events)
    tracks = {e["args"]["name"] for e in trace["traceEvents"]
              if e.get("ph") == "M"}
    assert "resilience" in tracks


def test_obs_twins_count_retries_and_splits():
    from spark_rapids_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry()
    obs.install(reg)
    try:
        sess = TpuSession({
            "spark.rapids.tpu.test.faults.oom": "TpuHashAggregateExec>256",
            **NO_BACKOFF})
        _q(sess).collect()
        retries = sum(
            v for _, v in reg._vals["tpu_oom_retries"].items())
        splits = sum(
            v for _, v in reg._vals["tpu_batch_splits"].items())
        assert retries >= 1 and splits >= 1
    finally:
        obs.uninstall()


# ---------------------------------------------------------------------------
# 9. chaos matrix: injected faults at every covered site — row-exact
#    completion or a typed error, never a raw escape, never a leak
# ---------------------------------------------------------------------------
TYPED = (TpuSplitAndRetryOOM, TpuRetryOOM, TpuOutOfDeviceMemory,
         faults.InjectedFault)


@pytest.mark.parametrize("channel,spec", [
    ("oom", "*>512"),
    ("oom", "*@1"),
    ("oom", "*?3"),
    ("compile", "*@2"),
])
def test_chaos_every_site(channel, spec):
    n = 1000
    data = {"k": [i % 13 for i in range(n)],
            "v": [None if i % 17 == 0 else (i * 7) % 500
                  for i in range(n)]}
    schema = schema_of(k=T.INT, v=T.LONG)

    rdata = {"k": list(range(13)), "w": [i * 100 for i in range(13)]}
    rschema = schema_of(k=T.INT, w=T.LONG)

    def builds(s):
        df = s.create_dataframe(data, schema)
        right = s.create_dataframe(rdata, rschema)
        return [
            df.select(col("k"), E.Alias(E.Add(col("v"), lit(1)), "v1")),
            df.order_by("v", "k"),
            df.group_by("k").agg(A.agg(A.Sum(col("v")), "sv"),
                                 A.agg(A.Count(None), "c")),
            df.join(right, "k"),
        ]

    cpu = [d.collect() for d in builds(
        TpuSession({"spark.rapids.tpu.sql.enabled": False}))]
    for i, want in enumerate(cpu):
        faults.uninstall()
        sess = TpuSession({
            f"spark.rapids.tpu.test.faults.{channel}": spec,
            **NO_BACKOFF})
        try:
            got = builds(sess)[i].collect()
            compare_rows(want, got, ignore_order=(i != 1))
        except TYPED:
            pass  # typed, named failure is an accepted chaos outcome
        assert BufferCatalog.get().reserved_bytes == 0
        assert TpuSemaphore.get().holder_names() == []
