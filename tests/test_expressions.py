"""Differential expression tests: TPU lowering vs independent CPU interpreter.

The reference's core correctness idea (SparkQueryCompareTestSuite:
testSparkResultsAreEqual, asserts.assert_gpu_and_cpu_are_equal_collect)
applied at the expression layer: evaluate the same bound tree via the fused
XLA path and the row interpreter, diff per row.
"""
import random

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import ColumnarBatch, schema_of
from spark_rapids_tpu.cpu import eval_expression_rows
from spark_rapids_tpu.expr import bind_references, col, evaluate_projection, lit
from spark_rapids_tpu.expr import expressions as E

from data_gen import approx_equal, gen_column, tpu_rel

N = 64


def make_batch(schema, seed=0, null_prob=0.15):
    rng = random.Random(seed)
    data = {
        f.name: gen_column(f.dataType, N, rng, null_prob=null_prob)
        for f in schema.fields
    }
    return ColumnarBatch.from_pydict(data, schema), data


def check(expr, schema, seed=0, rel=1e-12, null_prob=0.15):
    batch, data = make_batch(schema, seed, null_prob)
    bound = bind_references(expr, schema)
    [tpu_col] = evaluate_projection([bound], batch)
    tpu_vals = tpu_col.to_pylist()
    rows = list(zip(*(data[f.name] for f in schema.fields)))
    cpu_vals = eval_expression_rows(bound, rows)
    assert len(tpu_vals) == len(cpu_vals)
    for i, (tv, cv) in enumerate(zip(tpu_vals, cpu_vals)):
        assert approx_equal(tv, cv, rel), (
            f"row {i}: tpu={tv!r} cpu={cv!r} expr={expr} inputs={rows[i]}"
        )


NUM_SCHEMA = schema_of(a=T.INT, b=T.INT, c=T.LONG, d=T.DOUBLE, e=T.DOUBLE, f=T.FLOAT)
BOOL_SCHEMA = schema_of(p=T.BOOLEAN, q=T.BOOLEAN, x=T.INT)


@pytest.mark.parametrize("op", [E.Add, E.Subtract, E.Multiply])
@pytest.mark.parametrize("pair", [("a", "b"), ("a", "c"), ("d", "e"), ("a", "d"), ("f", "f")])
def test_arithmetic(op, pair):
    check(op(col(pair[0]), col(pair[1])), NUM_SCHEMA, seed=hash((op.__name__, pair)) & 0xFFFF)


def test_divide_null_on_zero():
    schema = schema_of(a=T.INT, b=T.INT)
    check(E.Divide(col("a"), col("b")), schema, seed=3)
    # force zeros in denominator
    batch = ColumnarBatch.from_pydict({"a": [1, 2, None, 5], "b": [0, 2, 2, 0]}, schema)
    bound = bind_references(E.Divide(col("a"), col("b")), schema)
    [r] = evaluate_projection([bound], batch)
    assert r.to_pylist() == [None, 1.0, None, None]


def test_integral_divide_and_remainder():
    schema = schema_of(a=T.LONG, b=T.LONG)
    check(E.IntegralDivide(col("a"), col("b")), schema, seed=5)
    check(E.Remainder(col("a"), col("b")), schema, seed=6)
    check(E.Pmod(col("a"), col("b")), schema, seed=7)
    batch = ColumnarBatch.from_pydict({"a": [7, -7, 7, -7], "b": [2, 2, -2, -2]}, schema)
    bound = bind_references(E.Remainder(col("a"), col("b")), schema)
    [r] = evaluate_projection([bound], batch)
    assert r.to_pylist() == [1, -1, 1, -1]  # Java: sign follows dividend
    bound = bind_references(E.IntegralDivide(col("a"), col("b")), schema)
    [r] = evaluate_projection([bound], batch)
    assert r.to_pylist() == [3, -3, -3, 3]  # truncation toward zero


@pytest.mark.parametrize(
    "op", [E.EqualTo, E.LessThan, E.LessThanOrEqual, E.GreaterThan, E.GreaterThanOrEqual, E.EqualNullSafe]
)
def test_comparisons(op):
    check(op(col("a"), col("b")), NUM_SCHEMA, seed=11)
    check(op(col("d"), col("e")), NUM_SCHEMA, seed=12)
    check(op(col("a"), col("c")), NUM_SCHEMA, seed=13)


def test_three_valued_logic():
    check(E.And(col("p"), col("q")), BOOL_SCHEMA, seed=21, null_prob=0.4)
    check(E.Or(col("p"), col("q")), BOOL_SCHEMA, seed=22, null_prob=0.4)
    check(E.Not(col("p")), BOOL_SCHEMA, seed=23, null_prob=0.4)
    # exhaustive truth table
    schema = schema_of(p=T.BOOLEAN, q=T.BOOLEAN)
    vals = [True, False, None]
    rows = [(x, y) for x in vals for y in vals]
    batch = ColumnarBatch.from_pydict(
        {"p": [r[0] for r in rows], "q": [r[1] for r in rows]}, schema
    )
    for op, expect in [
        (E.And, [True, False, None, False, False, False, None, False, None]),
        (E.Or, [True, True, True, True, False, None, True, None, None]),
    ]:
        bound = bind_references(op(col("p"), col("q")), schema)
        [r] = evaluate_projection([bound], batch)
        assert r.to_pylist() == expect, op.__name__


def test_null_ops():
    check(E.IsNull(col("a")), NUM_SCHEMA, seed=31, null_prob=0.5)
    check(E.IsNotNull(col("d")), NUM_SCHEMA, seed=32, null_prob=0.5)
    check(E.IsNan(col("d")), NUM_SCHEMA, seed=33)
    check(E.Coalesce((col("a"), col("b"), lit(42))), NUM_SCHEMA, seed=34, null_prob=0.6)
    check(E.NaNvl(col("d"), col("e")), NUM_SCHEMA, seed=35)


def test_conditionals():
    pred = E.GreaterThan(col("a"), lit(0))
    check(E.If(pred, col("b"), col("a")), NUM_SCHEMA, seed=41)
    case = E.CaseWhen(
        branches=(
            (E.GreaterThan(col("a"), lit(50)), lit(1)),
            (E.GreaterThan(col("a"), lit(0)), lit(2)),
        ),
        else_value=lit(3),
    )
    check(case, NUM_SCHEMA, seed=42)
    case_no_else = E.CaseWhen(branches=((E.LessThan(col("a"), lit(0)), col("b")),))
    check(case_no_else, NUM_SCHEMA, seed=43)


@pytest.mark.parametrize(
    "to",
    [T.BYTE, T.SHORT, T.INT, T.LONG, T.FLOAT, T.DOUBLE, T.BOOLEAN],
)
@pytest.mark.parametrize("frm", ["a", "c", "d", "f"])
def test_casts(to, frm):
    # float32 intermediate rounding differs; compare loosely for FLOAT target
    rel = 1e-6 if to == T.FLOAT or frm == "f" else 1e-12
    check(E.Cast(col(frm), to), NUM_SCHEMA, seed=51, rel=rel)


def test_cast_saturation():
    schema = schema_of(d=T.DOUBLE)
    batch = ColumnarBatch.from_pydict(
        {"d": [1e20, -1e20, float("nan"), 1.9, -1.9]}, schema
    )
    bound = bind_references(E.Cast(col("d"), T.INT), schema)
    [r] = evaluate_projection([bound], batch)
    assert r.to_pylist() == [2**31 - 1, -(2**31), 0, 1, -1]


@pytest.mark.parametrize(
    "op",
    [E.Sqrt, E.Exp, E.Log, E.Log10, E.Log2, E.Log1p, E.Sin, E.Cos, E.Tan,
     E.Asin, E.Acos, E.Atan, E.Sinh, E.Cosh, E.Tanh, E.Cbrt, E.Expm1,
     E.ToDegrees, E.ToRadians],
)
def test_unary_math(op):
    import data_gen

    if data_gen.ON_TPU and op in (E.Sin, E.Cos, E.Tan):
        # large-argument trig needs exact argument reduction, which the
        # chip's emulated f64 lacks — restrict the domain on-chip
        # (documented incompat) and keep the full domain on CPU
        schema = schema_of(d=T.DOUBLE)
        import random as _r

        rng = _r.Random(61)
        vals = [None if rng.random() < 0.1
                else rng.uniform(-100.0, 100.0) for _ in range(96)]
        batch = ColumnarBatch.from_pydict({"d": vals}, schema)
        bound = bind_references(op(col("d")), schema)
        [r] = evaluate_projection([bound], batch)
        cpu = eval_expression_rows(bound, [(v,) for v in vals])
        for i, (tv, cv) in enumerate(zip(r.to_pylist(), cpu)):
            assert approx_equal(tv, cv, tpu_rel(1e-9)), (i, tv, cv, vals[i])
        return
    # chip: transcendental f64 is emulated at ~f32 accuracy (documented
    # incompat, like the reference's GPU-vs-StrictMath drift)
    check(op(col("d")), NUM_SCHEMA, seed=61, rel=tpu_rel(1e-9))
    check(op(col("a")), NUM_SCHEMA, seed=62, rel=tpu_rel(1e-9))


def test_floor_ceil_round():
    check(E.Floor(col("d")), NUM_SCHEMA, seed=71)
    check(E.Ceil(col("d")), NUM_SCHEMA, seed=72)
    check(E.Floor(col("a")), NUM_SCHEMA, seed=73)
    check(E.Round(col("d"), 2), NUM_SCHEMA, seed=74, rel=tpu_rel(1e-9))
    check(E.Round(col("a"), -1), NUM_SCHEMA, seed=75)
    check(E.Signum(col("d")), NUM_SCHEMA, seed=76)
    check(E.Rint(col("d")), NUM_SCHEMA, seed=77)
    schema = schema_of(d=T.DOUBLE)
    batch = ColumnarBatch.from_pydict({"d": [2.5, -2.5, 3.5, 0.5]}, schema)
    bound = bind_references(E.Round(col("d"), 0), schema)
    [r] = evaluate_projection([bound], batch)
    assert r.to_pylist() == [3.0, -3.0, 4.0, 1.0]  # HALF_UP, away from zero


def test_pow_atan2():
    check(E.Pow(col("a"), lit(2)), NUM_SCHEMA, seed=81, rel=1e-9)
    check(E.Atan2(col("d"), col("e")), NUM_SCHEMA, seed=82, rel=1e-9)


@pytest.mark.parametrize("op", [E.BitwiseAnd, E.BitwiseOr, E.BitwiseXor])
def test_bitwise(op):
    check(op(col("a"), col("b")), NUM_SCHEMA, seed=91)
    check(op(col("c"), col("c")), NUM_SCHEMA, seed=92)


def test_bitwise_not_and_shifts():
    check(E.BitwiseNot(col("a")), NUM_SCHEMA, seed=93)
    schema = schema_of(a=T.INT, s=T.INT)
    rng_vals = {"a": [1, -1, 2**31 - 1, -(2**31), 255, None], "s": [1, 31, 33, 0, 4, 2]}
    batch = ColumnarBatch.from_pydict(rng_vals, schema)
    rows = list(zip(rng_vals["a"], rng_vals["s"]))
    for op in (E.ShiftLeft, E.ShiftRight, E.ShiftRightUnsigned):
        bound = bind_references(op(col("a"), col("s")), schema)
        [r] = evaluate_projection([bound], batch)
        assert r.to_pylist() == eval_expression_rows(bound, rows), op.__name__


def test_in():
    check(E.In(col("a"), (1, 2, 50)), NUM_SCHEMA, seed=95)
    check(E.In(col("a"), (1, None, 50)), NUM_SCHEMA, seed=96)


def test_string_passthrough_and_length():
    schema = schema_of(s=T.STRING)
    vals = ["héllo", "", None, "abc", "日本語"]
    batch = ColumnarBatch.from_pydict({"s": vals}, schema)
    bound = bind_references(col("s"), schema)
    [r] = evaluate_projection([bound], batch)
    assert r.to_pylist() == vals
    bound = bind_references(E.Length(col("s")), schema)
    [r] = evaluate_projection([bound], batch)
    assert r.to_pylist() == [5, 0, None, 3, 3]  # character count, not bytes


def test_string_literal():
    schema = schema_of(a=T.INT)
    batch = ColumnarBatch.from_pydict({"a": [1, 2, 3]}, schema)
    bound = bind_references(lit("xy"), schema)
    [r] = evaluate_projection([bound], batch)
    assert r.to_pylist() == ["xy", "xy", "xy"]


def test_nested_tree_fuses():
    # (a + b) * 2 > c AND NOT isnull(d) — one fused executable
    expr = E.And(
        E.GreaterThan(E.Multiply(E.Add(col("a"), col("b")), lit(2)), col("c")),
        E.Not(E.IsNull(col("d"))),
    )
    check(expr, NUM_SCHEMA, seed=99)


def test_compile_cache_hit():
    from spark_rapids_tpu.expr.eval import _compiled

    _compiled.cache_clear()
    schema = schema_of(a=T.INT)
    b1 = ColumnarBatch.from_pydict({"a": list(range(10))}, schema)
    b2 = ColumnarBatch.from_pydict({"a": list(range(90))}, schema)  # same bucket (128)
    bound = bind_references(E.Add(col("a"), lit(1)), schema)
    evaluate_projection([bound], b1)
    evaluate_projection([bound], b2)
    info = _compiled.cache_info()
    assert info.misses == 1 and info.hits == 1


def test_tpu_supports_probe():
    from spark_rapids_tpu.expr import tpu_supports

    schema = schema_of(a=T.INT, s=T.STRING)
    ok, _ = tpu_supports(E.Add(col("a"), lit(1)), schema)
    assert ok
    ok, _ = tpu_supports(E.EqualTo(col("s"), lit("x")), schema)
    assert ok  # string comparisons lower since round 3
    ok, reason = tpu_supports(E.EqualTo(col("s"), col("a")), schema)
    assert not ok and "string" in reason


def test_float_remainder_specials():
    schema = schema_of(d=T.DOUBLE, e=T.DOUBLE)
    check(E.Remainder(col("d"), col("e")), schema, seed=101, rel=tpu_rel())
    check(E.Pmod(col("d"), col("e")), schema, seed=102, rel=tpu_rel())
    inf = float("inf")
    batch = ColumnarBatch.from_pydict(
        {"d": [1.0, inf, 5.5, 7.0], "e": [0.0, 2.0, inf, 2.5]}, schema
    )
    bound = bind_references(E.Remainder(col("d"), col("e")), schema)
    [r] = evaluate_projection([bound], batch)
    vals = r.to_pylist()
    import math as m

    assert m.isnan(vals[0]) and m.isnan(vals[1])  # x%0, inf%y -> NaN
    assert vals[2] == 5.5 and vals[3] == 2.0  # x%inf == x


def test_in_literal_coercion():
    schema = schema_of(a=T.INT)
    batch = ColumnarBatch.from_pydict({"a": [1, 2, None]}, schema)
    # out-of-int32-range literal widens instead of crashing
    bound = bind_references(E.In(col("a"), (1, 2**32 + 1)), schema)
    [r] = evaluate_projection([bound], batch)
    assert r.to_pylist() == [True, False, None]
    # beyond-int64 literal can never match
    bound = bind_references(E.In(col("a"), (2**70,)), schema)
    [r] = evaluate_projection([bound], batch)
    assert r.to_pylist() == [False, False, None]
    # float literal compares exactly, no truncation
    bound = bind_references(E.In(col("a"), (1.5,)), schema)
    [r] = evaluate_projection([bound], batch)
    assert r.to_pylist() == [False, False, None]
    bound = bind_references(E.In(col("a"), (2.0,)), schema)
    [r] = evaluate_projection([bound], batch)
    assert r.to_pylist() == [False, True, None]


def test_nan_comparison_semantics():
    nan = float("nan")
    schema = schema_of(d=T.DOUBLE, e=T.DOUBLE)
    batch = ColumnarBatch.from_pydict(
        {"d": [nan, nan, 1.0, nan], "e": [nan, 1.0, nan, None]}, schema
    )
    cases = {
        E.EqualTo: [True, False, False, None],
        E.EqualNullSafe: [True, False, False, False],
        E.LessThan: [False, False, True, None],
        E.LessThanOrEqual: [True, False, True, None],
        E.GreaterThan: [False, True, False, None],
        E.GreaterThanOrEqual: [True, True, False, None],
    }
    for op, expect in cases.items():
        bound = bind_references(op(col("d"), col("e")), schema)
        [r] = evaluate_projection([bound], batch)
        assert r.to_pylist() == expect, op.__name__
        rows = list(zip(batch.to_pydict()["d"], batch.to_pydict()["e"]))
        assert eval_expression_rows(bound, rows) == expect, f"cpu {op.__name__}"
