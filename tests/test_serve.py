"""Concurrent multi-query serving: admission control, fair scheduling,
pipelined session execution (serve/scheduler.py + plan_cache.py), the
catalog reservation API, the semaphore acquire timeout, and the
thread-safety regressions for the process-shared compile caches.

The headline stress test is the ISSUE 9 acceptance path: N threads x M
queries against a deliberately tiny hbm.budgetBytes — zero OOMs, every
query completes, results match the single-threaded oracle, admission/
queue events balance, and the summed admitted forecasts never exceed the
budget (zero admission-forecast violations)."""
import importlib.util
import json
import os
import threading
import time

import pytest

from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu import events as EV
from spark_rapids_tpu import obs
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.expressions import col, lit
from spark_rapids_tpu.memory import TpuSemaphore, TpuSemaphoreTimeout
from spark_rapids_tpu.memory.catalog import BufferCatalog
from spark_rapids_tpu.serve import (
    QueryScheduler,
    ServeAdmissionRejected,
    ServeQueueTimeout,
    SharedPlanCache,
)
from spark_rapids_tpu.sql import TpuSession

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "tpu_profile", os.path.join(REPO, "tools", "tpu_profile.py"))
tpu_profile = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(tpu_profile)


@pytest.fixture(autouse=True)
def clean_serving_state():
    """Every test starts/ends with fresh process-global serving state."""
    QueryScheduler.reset()
    SharedPlanCache.reset()
    BufferCatalog.reset()
    TpuSemaphore.reset()
    EV.uninstall()
    obs.shutdown()
    yield
    QueryScheduler.reset()
    SharedPlanCache.reset()
    BufferCatalog.reset()
    TpuSemaphore.reset()
    EV.uninstall()
    obs.shutdown()


def _query_df(sess, mult: int, n: int = 2048):
    """A statically-bounded plan (in-memory range -> filter -> project ->
    COMPLETE aggregate) whose result depends on ``mult``."""
    return (sess.range(0, n)
            .where(E.GreaterThanOrEqual(col("id"), lit(100)))
            .select(col("id"),
                    E.Alias(E.Multiply(col("id"), lit(mult)), "v"))
            .agg(A.agg(A.Sum(col("v")), "s"), A.agg(A.Count(None), "c")))


def _forecast_of(settings=None) -> int:
    """The analyzer's peak-HBM forecast for _query_df's shape."""
    sess = TpuSession(dict(settings or {},
                           **{"spark.rapids.tpu.serve.enabled": True}))
    _query_df(sess, 2).collect()
    an = sess.last_analysis
    assert an is not None and an.bounded and an.peak_hbm
    return an.peak_hbm


# ---------------------------------------------------------------------------
# 1. semaphore acquire timeout (satellite)
# ---------------------------------------------------------------------------
def test_semaphore_timeout_names_holder_and_duration():
    sem = TpuSemaphore.reset(RapidsConf({
        "spark.rapids.tpu.sql.concurrentTpuTasks": 1,
        "spark.rapids.tpu.sql.semaphore.acquireTimeoutMs": 150,
    }))
    held = threading.Event()
    release = threading.Event()

    def holder():
        sem.acquire_if_necessary()
        held.set()
        release.wait(10)
        sem.release_if_necessary()

    t = threading.Thread(target=holder, name="wedged-holder")
    t.start()
    assert held.wait(5)
    with pytest.raises(TpuSemaphoreTimeout) as ei:
        sem.acquire_if_necessary()
    msg = str(ei.value)
    assert "wedged-holder" in msg          # the culprit is named
    assert "acquireTimeoutMs" in msg       # and the escape-hatch conf
    release.set()
    t.join(5)
    # after the holder releases, acquisition succeeds within the timeout
    sem.acquire_if_necessary()
    sem.release_if_necessary()


def test_semaphore_default_still_blocks_forever_config():
    sem = TpuSemaphore.reset(RapidsConf({}))
    assert sem.timeout_ms == 0  # the reference behavior is the default


# ---------------------------------------------------------------------------
# 2. admission verdicts
# ---------------------------------------------------------------------------
def test_admission_rejects_plan_that_can_never_fit():
    BufferCatalog.reset(RapidsConf(
        {"spark.rapids.tpu.memory.hbm.budgetBytes": 1 << 20}))
    sched = QueryScheduler.reset(RapidsConf({}))
    with pytest.raises(ServeAdmissionRejected) as ei:
        sched.acquire("session-a", 0, 10 << 20, "d1")
    assert "exceeds the total HBM budget" in str(ei.value)
    assert sched.stats()["rejected"] == 1


def test_admission_reserves_and_queues_until_release():
    budget = 1 << 20
    BufferCatalog.reset(RapidsConf(
        {"spark.rapids.tpu.memory.hbm.budgetBytes": budget}))
    sched = QueryScheduler.reset(RapidsConf({}))
    t1 = sched.acquire("session-a", 0, 700_000, "d1")
    assert BufferCatalog.get().reserved_bytes == 700_000
    got = []

    def second():
        t2 = sched.acquire("session-b", 0, 700_000, "d2")
        got.append(t2)

    th = threading.Thread(target=second)
    th.start()
    time.sleep(0.2)
    assert not got  # 700k + 700k > 1M: queued, not admitted
    assert sched.stats()["waiting"] == 1
    sched.release(t1)
    th.join(5)
    assert got and got[0].verdict == "admit"
    assert BufferCatalog.get().reserved_bytes == 700_000
    sched.release(got[0])
    assert BufferCatalog.get().reserved_bytes == 0
    assert sched.stats()["peak_inflight_forecast"] <= budget


def test_bypass_admission_when_nothing_running():
    # residual device bytes above the budget must not wedge the queue:
    # with nothing active, the head admits anyway (spill enforces)
    BufferCatalog.reset(RapidsConf(
        {"spark.rapids.tpu.memory.hbm.budgetBytes": 1 << 20}))
    sched = QueryScheduler.reset(RapidsConf({}))
    cat = BufferCatalog.get()
    cat._device_bytes = 2 << 20  # simulate resident cache pressure
    t = sched.acquire("session-a", 0, 500_000, "d1")
    assert t.bypass and sched.stats()["bypass_admissions"] == 1
    sched.release(t)


def test_unbounded_plan_admits_with_zero_reservation():
    BufferCatalog.reset(RapidsConf(
        {"spark.rapids.tpu.memory.hbm.budgetBytes": 1 << 20}))
    sched = QueryScheduler.reset(RapidsConf({}))
    t = sched.acquire("session-a", 0, None, "d1")
    assert t.verdict == "admit"
    assert BufferCatalog.get().reserved_bytes == 0
    sched.release(t)


def test_max_queue_depth_rejects_with_named_error():
    BufferCatalog.reset(RapidsConf(
        {"spark.rapids.tpu.memory.hbm.budgetBytes": 1 << 20}))
    sched = QueryScheduler.reset(RapidsConf(
        {"spark.rapids.tpu.serve.maxQueueDepth": 1}))
    t1 = sched.acquire("session-a", 0, 900_000, "d1")
    waiter = threading.Thread(
        target=lambda: sched.release(
            sched.acquire("session-a", 0, 900_000, "d2")))
    waiter.start()
    time.sleep(0.2)  # d2 is now queued at depth 1
    with pytest.raises(ServeAdmissionRejected) as ei:
        sched.acquire("session-a", 0, 900_000, "d3")
    assert "maxQueueDepth" in str(ei.value)
    sched.release(t1)
    waiter.join(5)


def test_queue_timeout_raises_named_error():
    BufferCatalog.reset(RapidsConf(
        {"spark.rapids.tpu.memory.hbm.budgetBytes": 1 << 20}))
    sched = QueryScheduler.reset(RapidsConf(
        {"spark.rapids.tpu.serve.queueTimeoutMs": 200}))
    t1 = sched.acquire("session-a", 0, 900_000, "d1")
    with pytest.raises(ServeQueueTimeout) as ei:
        sched.acquire("session-b", 0, 900_000, "d2")
    assert "queueTimeoutMs" in str(ei.value)
    assert sched.stats()["timeouts"] == 1
    sched.release(t1)


def test_timeout_pumps_the_successor_head():
    # queue [big, small] in one session while another holds the budget:
    # big's timeout must PUMP the queue so small (which fits the live
    # headroom) admits immediately — not at the next unrelated release
    BufferCatalog.reset(RapidsConf(
        {"spark.rapids.tpu.memory.hbm.budgetBytes": 1 << 20}))
    sched = QueryScheduler.reset(RapidsConf({}))
    t1 = sched.acquire("sess-a", 0, 900_000, "hold")
    events = []

    def big():
        try:
            sched.acquire("sess-b", 0, 800_000, "big",
                          conf_=RapidsConf(
                              {"spark.rapids.tpu.serve.queueTimeoutMs":
                               300}))
        except ServeQueueTimeout:
            events.append("big-timeout")

    def small():
        t = sched.acquire("sess-b", 0, 50_000, "small")
        events.append("small-admitted")
        sched.release(t)

    tb = threading.Thread(target=big)
    tb.start()
    time.sleep(0.1)
    ts = threading.Thread(target=small)
    ts.start()
    tb.join(5)
    assert events and events[0] == "big-timeout"
    ts.join(2)  # must NOT need t1's release to proceed
    assert "small-admitted" in events
    sched.release(t1)


def test_large_head_is_not_starved_by_later_small_queries():
    # anti-starvation barrier: a later small query (same priority) must
    # not keep backfilling past a blocked large head — on release, the
    # large head admits FIRST
    BufferCatalog.reset(RapidsConf(
        {"spark.rapids.tpu.memory.hbm.budgetBytes": 1 << 20}))
    sched = QueryScheduler.reset(RapidsConf({}))
    t1 = sched.acquire("sess-a", 0, 900_000, "hold")
    tickets = {}
    lock = threading.Lock()

    def run(sess, forecast, tag):
        t = sched.acquire(sess, 0, forecast, tag)
        with lock:
            tickets[tag] = t
        time.sleep(0.01)
        sched.release(t)

    tb = threading.Thread(target=run, args=("sess-b", 800_000, "big"))
    tb.start()
    time.sleep(0.1)  # big is queued (free is only ~100k)
    tsm = threading.Thread(target=run, args=("sess-c", 50_000, "small"))
    tsm.start()
    time.sleep(0.3)
    # small FITS the live headroom but arrived after the starving head:
    # the barrier holds it back
    assert tickets == {}
    sched.release(t1)
    tb.join(5)
    tsm.join(5)
    assert set(tickets) == {"big", "small"}
    # big admitted FIRST (admit order, not thread-wakeup order: both
    # admit in one pump once the blocker releases)
    assert tickets["big"].admit_ns < tickets["small"].admit_ns


def test_rejected_query_closes_its_event_window():
    budget = 60_000  # smaller than _query_df's peak forecast at n=65536
    settings = {
        "spark.rapids.tpu.serve.enabled": True,
        "spark.rapids.tpu.memory.hbm.budgetBytes": budget,
        "spark.rapids.tpu.eventLog.enabled": True,
    }
    BufferCatalog.reset(RapidsConf(settings))
    QueryScheduler.reset(RapidsConf(settings))
    sess = TpuSession(settings)
    with pytest.raises(ServeAdmissionRejected):
        _query_df(sess, 2, n=1 << 16).collect()
    recs = sess.events.records()
    starts = [r for r in recs if r["event"] == "query_start"]
    ends = [r for r in recs if r["event"] == "query_end"]
    assert len(starts) == 1 and len(ends) == 1  # window closed
    assert ends[0]["error"] is True
    adm = [r for r in recs if r["event"] == "admission"]
    assert adm and adm[-1]["verdict"] == "reject"


# ---------------------------------------------------------------------------
# 3. fairness: round-robin across sessions, priority tiers
# ---------------------------------------------------------------------------
def _drain_order(sched, submits):
    """Submit (session, priority) tickets from threads while a blocker
    holds the whole budget; release the blocker and record admit order."""
    order = []
    order_lock = threading.Lock()
    threads = []
    started = []

    def run(sess, prio, tag):
        t = sched.acquire(sess, prio, 900_000, tag)
        with order_lock:
            order.append(tag)
        time.sleep(0.01)
        sched.release(t)

    blocker = sched.acquire("blocker", 0, 900_000, "b0")
    for sess, prio, tag in submits:
        th = threading.Thread(target=run, args=(sess, prio, tag))
        th.start()
        started.append(th)
        time.sleep(0.05)  # deterministic enqueue order
    sched.release(blocker)
    for th in started:
        th.join(10)
    return order


def test_round_robin_alternates_sessions():
    BufferCatalog.reset(RapidsConf(
        {"spark.rapids.tpu.memory.hbm.budgetBytes": 1 << 20}))
    sched = QueryScheduler.reset(RapidsConf({}))
    order = _drain_order(sched, [
        ("sess-a", 0, "a1"), ("sess-a", 0, "a2"),
        ("sess-b", 0, "b1"), ("sess-b", 0, "b2"),
    ])
    # per-session FIFO always holds...
    assert order.index("a1") < order.index("a2")
    assert order.index("b1") < order.index("b2")
    # ...and round-robin interleaves the sessions instead of draining
    # all of a's backlog first (a submitted its whole backlog first)
    assert order != ["a1", "a2", "b1", "b2"]


def test_priority_session_drains_first():
    BufferCatalog.reset(RapidsConf(
        {"spark.rapids.tpu.memory.hbm.budgetBytes": 1 << 20}))
    sched = QueryScheduler.reset(RapidsConf({}))
    order = _drain_order(sched, [
        ("sess-lo", 0, "lo1"), ("sess-lo", 0, "lo2"),
        ("sess-hi", 5, "hi1"), ("sess-hi", 5, "hi2"),
    ])
    # the high-priority session's queries all admit before the
    # low-priority backlog finishes
    assert max(order.index("hi1"), order.index("hi2")) \
        < order.index("lo2")


# ---------------------------------------------------------------------------
# 4. shared plan cache
# ---------------------------------------------------------------------------
def test_plan_cache_shares_analysis_across_sessions():
    SharedPlanCache.reset()
    settings = {"spark.rapids.tpu.serve.enabled": True}
    s1, s2 = TpuSession(settings), TpuSession(settings)
    r1 = _query_df(s1, 3).collect()
    r2 = _query_df(s2, 3).collect()
    assert r1 == r2
    st = SharedPlanCache.get().stats()
    assert st["misses"] == 1 and st["hits"] >= 1  # analyzed ONCE
    assert st["warm"] == 1  # first completion marked the digest warm


def test_plan_cache_keys_on_conf_fingerprint():
    SharedPlanCache.reset()
    s1 = TpuSession({"spark.rapids.tpu.serve.enabled": True})
    s2 = TpuSession({"spark.rapids.tpu.serve.enabled": True,
                     "spark.rapids.tpu.sql.shapeBucket.minRows": 256})
    _query_df(s1, 3).collect()
    _query_df(s2, 3).collect()
    # different layout-affecting settings -> different cache entries
    assert SharedPlanCache.get().stats()["misses"] == 2


def test_plan_cache_single_flight_under_race():
    SharedPlanCache.reset()
    cache = SharedPlanCache.get()
    computes = []

    def compute():
        computes.append(1)
        time.sleep(0.1)
        return "analysis"

    results = []
    ths = [threading.Thread(
        target=lambda: results.append(cache.analysis_for(("k",), compute)))
        for _ in range(8)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(10)
    assert len(computes) == 1  # one flight, seven waiters
    assert all(r[0] == "analysis" for r in results)
    assert sum(1 for r in results if not r[1]) == 1  # exactly one miss


# ---------------------------------------------------------------------------
# 5. the acceptance stress path: N threads x M queries, tiny budget
# ---------------------------------------------------------------------------
def test_stress_concurrent_sessions_tiny_budget(tmp_path):
    n_threads, n_queries = 4, 8
    forecast = _forecast_of()
    # room for ~2 admitted forecasts: real queueing under 4 threads, but
    # every single plan fits (no bypass, no rejects)
    budget = int(2.5 * forecast)
    settings = {
        "spark.rapids.tpu.serve.enabled": True,
        "spark.rapids.tpu.memory.hbm.budgetBytes": budget,
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
    }
    BufferCatalog.reset(RapidsConf(settings))
    QueryScheduler.reset(RapidsConf(settings))
    SharedPlanCache.reset()

    # single-threaded oracle, serve OFF (the plain collect path)
    oracle_sess = TpuSession({})
    oracle = {
        (ti, qi): _query_df(oracle_sess, 2 + (ti * n_queries + qi) % 5
                            ).collect()
        for ti in range(n_threads) for qi in range(n_queries)
    }

    results = {}
    errors = []
    lock = threading.Lock()

    def worker(ti):
        try:
            sess = TpuSession(settings)
            for qi in range(n_queries):
                rows = _query_df(sess, 2 + (ti * n_queries + qi) % 5
                                 ).collect()
                with lock:
                    results[(ti, qi)] = rows
        except Exception as e:  # pragma: no cover - the failure mode
            with lock:
                errors.append((ti, repr(e)))

    threads = [threading.Thread(target=worker, args=(ti,),
                                name=f"stress-{ti}")
               for ti in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    assert not errors, f"queries failed under concurrency: {errors}"
    assert len(results) == n_threads * n_queries  # all queries completed
    for key, rows in results.items():
        assert rows == oracle[key], f"result mismatch for {key}"

    sched = QueryScheduler.instance()
    st = sched.stats()
    assert st["admitted"] == n_threads * n_queries
    assert st["rejected"] == 0 and st["timeouts"] == 0
    assert st["active"] == 0 and st["waiting"] == 0  # fully drained
    # zero admission-forecast violations: with no bypass, the summed
    # admitted forecasts never exceeded the budget at any point
    assert st["bypass_admissions"] == 0
    assert st["peak_inflight_forecast"] <= budget
    # the tiny budget actually exercised the queue
    assert st["queued"] > 0

    # admission/queue events balance across the merged per-session logs
    events = tpu_profile.load_events([str(tmp_path)])
    adm = [r for r in events if r.get("event") == "admission"]
    # every query logs exactly one terminal "admit"; queued ones logged
    # a "queue" verdict first, none were rejected
    assert sum(1 for r in adm if r["verdict"] == "admit") \
        == n_threads * n_queries
    assert not any(r["verdict"] == "reject" for r in adm)
    enq = sum(1 for r in events if r.get("event") == "queue"
              and r["op"] == "enqueue")
    deq = sum(1 for r in events if r.get("event") == "queue"
              and r["op"] == "dequeue")
    assert enq == deq and enq == st["queued"]
    # the offline profiler agrees: zero violations (forecast bounds hold
    # per query under by-thread attribution, queue events balance)
    report, violations = tpu_profile.build_report(events)
    assert violations == 0, report
    assert "== serving ==" in report and "admit=" in report

    # queue-wait spans render on per-session serve lanes in Perfetto
    trace = EV.chrome_trace(events)
    tracks = {e["args"]["name"] for e in trace["traceEvents"]
              if e.get("ph") == "M"}
    assert any(t.startswith("serve session-") for t in tracks), tracks


def test_concurrent_execution_overlaps():
    """The pipelining claim, asserted structurally: with headroom for
    several forecasts, concurrent submits are simultaneously admitted
    (peak_active >= 2) and all results stay correct. The wall-clock
    queries/sec comparison lives in bench.py --serve, where the workload
    is sized to dominate scheduler overhead (a micro-workload on a
    shared 2-core CI box measures only noise)."""
    forecast = _forecast_of()
    settings = {
        "spark.rapids.tpu.serve.enabled": True,
        "spark.rapids.tpu.memory.hbm.budgetBytes": int(8 * forecast),
    }
    BufferCatalog.reset(RapidsConf(settings))
    QueryScheduler.reset(RapidsConf(settings))
    SharedPlanCache.reset()
    n_threads, n_queries = 4, 3
    errors = []

    def worker(ti):
        try:
            s = TpuSession(settings)
            for qi in range(n_queries):
                i = ti * n_queries + qi
                rows = _query_df(s, 2 + i % 5, n=4096).collect()
                assert rows[0][1] == 3996
        except Exception as e:  # pragma: no cover
            errors.append(repr(e))

    threads = [threading.Thread(target=worker, args=(ti,))
               for ti in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    assert not errors, errors
    st = QueryScheduler.instance().stats()
    assert st["admitted"] == n_threads * n_queries
    assert st["peak_active"] >= 2  # queries genuinely overlapped


# ---------------------------------------------------------------------------
# 6. /status + tpu_top surface the queue
# ---------------------------------------------------------------------------
def test_status_and_tpu_top_show_queue():
    from spark_rapids_tpu.obs.progress import ProgressTracker
    from spark_rapids_tpu.obs.registry import MetricsRegistry
    from spark_rapids_tpu.obs.server import build_status

    BufferCatalog.reset(RapidsConf(
        {"spark.rapids.tpu.memory.hbm.budgetBytes": 1 << 20}))
    sched = QueryScheduler.reset(RapidsConf({}))
    t1 = sched.acquire("session-9", 0, 900_000, "dead99beef99")
    waiter = threading.Thread(
        target=lambda: sched.release(
            sched.acquire("session-7", 1, 800_000, "feed77face77")))
    waiter.start()
    time.sleep(0.2)
    status = build_status(MetricsRegistry(), ProgressTracker(), None)
    json.dumps(status)  # /status must stay JSON-serializable
    serve = status["serve"]
    assert serve["stats"]["active"] == 1 and serve["stats"]["waiting"] == 1
    q = serve["queue"][0]
    assert q["session"] == "session-7" and q["position"] == 0
    assert "queued" in q["reason"]
    assert status["hbm"]["reserved_bytes"] == 900_000

    import importlib.util as iu

    spec = iu.spec_from_file_location(
        "tpu_top", os.path.join(REPO, "tools", "tpu_top.py"))
    tpu_top = iu.module_from_spec(spec)
    spec.loader.exec_module(tpu_top)
    frame = tpu_top.render_status(status)
    assert "session-7" in frame and "session-9" in frame
    assert "queued" in frame  # the admission verdict is visible
    sched.release(t1)
    waiter.join(5)


# ---------------------------------------------------------------------------
# 7. thread-safety regressions for the shared compile caches (satellite)
# ---------------------------------------------------------------------------
def test_cached_pipeline_compiles_once_under_race():
    from spark_rapids_tpu.exec import base as B

    cache = {}
    builds = []
    before = B.compile_miss_count()

    def build():
        builds.append(1)
        return lambda: "fn"

    barrier = threading.Barrier(8)

    def race():
        barrier.wait()
        B.cached_pipeline(cache, ("k",), "fused_chain", build)

    ths = [threading.Thread(target=race) for _ in range(8)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(10)
    assert len(builds) == 1  # one build...
    assert B.compile_miss_count() - before == 1  # ...one counted miss


def test_compile_counter_exact_under_concurrency():
    from spark_rapids_tpu.exec.base import CompileCounter

    c = CompileCounter()
    n_threads, n_each = 8, 500

    def bump():
        for _ in range(n_each):
            c.note("site-x")

    ths = [threading.Thread(target=bump) for _ in range(n_threads)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(10)
    total, by_site = c.snapshot()
    assert total == n_threads * n_each
    assert by_site["site-x"] == n_threads * n_each


def test_scanner_cache_single_instance_under_race(tmp_path):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.sql import session as S

    path = os.path.join(str(tmp_path), "t.parquet")
    pq.write_table(pa.table({"k": pa.array(
        np.arange(64, dtype="int64"))}), path)
    conf = RapidsConf({})
    S._SCANNER_CACHE.clear()
    got = []
    barrier = threading.Barrier(8)

    def race():
        barrier.wait()
        got.append(S._make_scanner(
            "parquet", path, (("columns", None),), conf))

    ths = [threading.Thread(target=race) for _ in range(8)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(10)
    assert len(got) == 8
    assert all(sc is got[0] for sc in got)  # ONE scanner, no duplicates


def test_scan_cache_accounting_consistent_under_race():
    from spark_rapids_tpu.io.scan_cache import DeviceScanCache

    cache = DeviceScanCache(max_bytes=10_000)
    barrier = threading.Barrier(8)

    def race(i):
        barrier.wait()
        for j in range(50):
            key = ("p", i, j % 7)
            cache.get(key)
            cache.put(key, object(), 100 * (1 + j % 3))

    ths = [threading.Thread(target=race, args=(i,)) for i in range(8)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(10)
    st = cache.stats()
    # byte accounting stayed single-entry: resident == sum over entries
    with cache._lock:
        real = sum(sz for (_, sz, _lid) in cache._entries.values())
    assert st["bytes"] == real
    assert st["bytes"] <= st["max_bytes"]


# ---------------------------------------------------------------------------
# 8. pipelined execution: host_prefetch overlaps the drain
# ---------------------------------------------------------------------------
def test_serve_parquet_prefetch_matches_oracle(tmp_path):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(7)
    n = 20_000
    pq.write_table(
        pa.table({
            "k": pa.array(rng.integers(0, 16, n).astype("int32")),
            "v": pa.array(rng.integers(0, 1000, n).astype("int64")),
        }),
        os.path.join(str(tmp_path), "t.parquet"), row_group_size=4096)
    plain = TpuSession({})
    oracle = sorted(
        plain.read.parquet(str(tmp_path)).group_by("k")
        .agg(A.agg(A.Sum(col("v")), "sv")).collect())
    served = TpuSession({"spark.rapids.tpu.serve.enabled": True})
    got = sorted(
        served.read.parquet(str(tmp_path)).group_by("k")
        .agg(A.agg(A.Sum(col("v")), "sv")).collect())
    assert got == oracle


def test_host_prefetch_runs_on_prefetch_pool(tmp_path):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.exec.scan import TpuFileSourceScanExec
    from spark_rapids_tpu.sql.session import _make_scanner

    path = os.path.join(str(tmp_path), "t.parquet")
    pq.write_table(pa.table({
        "v": pa.array(np.arange(4096, dtype="int64"))}), path,
        row_group_size=1024)
    conf = RapidsConf({})
    scan = TpuFileSourceScanExec(
        conf, _make_scanner("parquet", path, (("columns", None),), conf),
        "parquet")
    scan.host_prefetch()
    assert scan._prefetch_dev is not None or scan._prefetch is not None
    rows = sum(b.num_rows for b in scan.execute_columnar())
    assert rows == 4096
    # futures were consumed by the drain, not re-read
    table = scan._prefetch_dev or scan._prefetch
    assert all(f is None for f in table)
