"""Nondeterministic / metadata expression family (VERDICT r4 item #6).

Reference analog: GpuRandomExpressions.scala:31 (GpuRand),
GpuMonotonicallyIncreasingID.scala, GpuSparkPartitionID.scala,
GpuInputFileBlock.scala, HashFunctions.scala:43 (GpuMurmur3Hash).
The rand generator is counter-based (expr/nondet.py) and bit-identical
between the TPU kernel and the CPU oracle, so even rand() is
differentially testable.
"""
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.expressions import col
from spark_rapids_tpu.sql import TpuSession

from harness import assert_tpu_and_cpu_equal, compare_rows

SCHEMA = T.StructType([
    T.StructField("k", T.INT),
    T.StructField("v", T.LONG),
    T.StructField("s", T.STRING),
])


def _df(s, n=300, parts=3):
    return s.create_dataframe(
        {"k": [i % 7 for i in range(n)],
         "v": [None if i % 11 == 0 else i - 50 for i in range(n)],
         "s": [None if i % 13 == 0 else f"s{i % 5}" for i in range(n)]},
        SCHEMA, num_partitions=parts)


def test_spark_partition_id_and_monotonic_id_differential():
    def build(s):
        return _df(s).select(
            col("k"),
            E.Alias(E.SparkPartitionID(), "pid"),
            E.Alias(E.MonotonicallyIncreasingID(), "mid"),
        )

    rows = assert_tpu_and_cpu_equal(build)
    pids = {r[1] for r in rows}
    assert pids == {0, 1, 2}
    # ids unique and carrying the partition in the high bits
    mids = [r[2] for r in rows]
    assert len(set(mids)) == len(mids)
    assert {m >> 33 for m in mids} == {0, 1, 2}


def test_rand_differential_and_distribution():
    def build(s):
        return _df(s).select(
            col("k"), E.Alias(E.Rand(seed=7), "r"))

    rows = assert_tpu_and_cpu_equal(build)
    vals = [r[1] for r in rows]
    assert all(0.0 <= v < 1.0 for v in vals)
    assert len(set(vals)) > 290  # essentially all distinct
    assert abs(np.mean(vals) - 0.5) < 0.06
    # determinism per seed: a second run produces identical values
    s2 = TpuSession({})
    again = [r[1] for r in build(s2).collect()]
    assert again == vals


def test_rand_same_seed_same_stream_different_seed_differs():
    s = TpuSession({})
    df = _df(s).select(
        E.Alias(E.Rand(seed=7), "a"),
        E.Alias(E.Rand(seed=7), "b"),
        E.Alias(E.Rand(seed=8), "c"),
    )
    rows = df.collect()
    # Spark: two rand(7) instances seed identical generators -> equal
    assert all(a == b for a, b, _ in rows)
    assert any(a != c for a, _, c in rows)


def test_murmur3_hash_differential_fixed_and_string():
    def build(s):
        return _df(s).select(
            col("k"),
            E.Alias(E.Murmur3Hash((col("k"), col("v"))), "h1"),
            E.Alias(E.Murmur3Hash((col("s"),)), "h2"),
            E.Alias(E.Murmur3Hash((col("s"), col("v"))), "h3"),
        )

    assert_tpu_and_cpu_equal(build)


def test_input_file_name_from_parquet_scan(tmp_path):
    d = str(tmp_path)
    for i in range(2):
        pq.write_table(
            pa.table({"x": pa.array(np.arange(10) + i * 10,
                                    type=pa.int64())}),
            os.path.join(d, f"p{i}.parquet"))

    def build(s):
        return s.read.parquet(d).select(
            col("x"), E.Alias(E.InputFileName(), "f"))

    rows = assert_tpu_and_cpu_equal(build)
    files = {r[1] for r in rows}
    assert len(files) == 2
    assert all(f.endswith(".parquet") for f in files)
    # every row maps to the file that actually holds its value
    for x, f in rows:
        assert f.endswith(f"p{x // 10}.parquet")


def test_nondeterministic_project_does_not_fuse_but_chains():
    """A context project composes with downstream filter/aggregate."""
    def build(s):
        df = _df(s).select(
            col("k"), col("v"), E.Alias(E.Rand(seed=3), "r"))
        return df.where(E.LessThan(col("r"), E.lit(0.5))).group_by(
            "k").agg(A.agg(A.Count(None), "n"))

    rows = assert_tpu_and_cpu_equal(build)
    total = sum(r[1] for r in rows)
    assert 60 < total < 240  # ~half of 300 survive the rand filter
