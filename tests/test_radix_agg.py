"""Differential + tiling tests for the RADIX and PALLAS aggregation
lowerings (round 12: kill the 25x byte amplification).

Coverage, per the issue checklist:
  * the five-strategy differential matrix — MATMUL / SCATTER / SORT /
    RADIX (+ PALLAS via interpret mode off-TPU) — over the torture set:
    int64 wraparound, all-null columns, the float hi/lo + NORMAL/BIG
    stream splits (incl. inf/NaN/huge magnitudes), dead and negative
    segment ids;
  * radix tiling edge cases: empty batches, multi-tile + flush-tile
    paths on non-divisor tile sizes (FORCE_TILE_ROWS), and the hash-tier
    overflow escalation (cardinality past the first tier) retrying into
    the scatter-free fallback;
  * the recompile guard: forced RADIX/PALLAS plans compile ONCE across
    batches and a rerun compiles nothing (AUTO's guard lives in
    tests/test_metrics.py);
  * the Pallas hash-join probe kernel vs the binary-search baseline, at
    ops level and through the conf-gated exec path.

Integer sums and counts must be BIT-identical across every lowering
(limb/prefix accumulation wraps mod 2^64 like native adds). Float sums
are order-insensitive decompositions under MATMUL/PALLAS (f32 hi/lo)
and RADIX (f64 NORMAL/BIG streams): MATMUL/PALLAS compare at the
approx-float-agg tolerance, RADIX at f64 rounding tightness.
"""
import numpy as np
import pytest

import spark_rapids_tpu  # noqa: F401  (x64 enable)
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, schema_of
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.exec import (
    InMemoryScanExec,
    TpuHashAggregateExec,
    TpuProjectExec,
)
from spark_rapids_tpu.exec import base as exec_base
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.eval import ColV
from spark_rapids_tpu.expr.expressions import col, lit
from spark_rapids_tpu.ops import groupby as G
from spark_rapids_tpu.ops import radix_bin as RBX
from spark_rapids_tpu.sql import TpuSession

from harness import assert_tpu_and_cpu_equal

STRATEGIES = ("SCATTER", "MATMUL", "SORT", "RADIX", "PALLAS")
#: strategies whose float sums are exact f64 accumulations (vs the
#: order-insensitive f32 hi/lo decompositions of MATMUL/PALLAS)
_TIGHT_FLOAT = {"SCATTER", "SORT", "RADIX"}


# ---------------------------------------------------------------------------
# ops-level five-strategy matrix over groupby_agg
# ---------------------------------------------------------------------------
def _groups_of(keys, aggs, nseg):
    """{key tuple -> ((value, valid), ...)} over the live segments, so
    strategies with different output orders (hash-bucket compaction vs
    sorted-key order) compare directly."""
    n = int(nseg)
    kcols = [np.asarray(k.data)[:n] for k in keys]
    out = {}
    for i in range(n):
        key = tuple(c[i] for c in kcols)
        row = []
        for a in aggs:
            valid = bool(np.asarray(a.validity)[i])
            row.append((np.asarray(a.data)[i] if valid else None, valid))
        out[key] = tuple(row)
    return out


def _run_strategy(strategy, key_np, vals, num_rows, ops, dtypes=None):
    keys = [ColV(jnp.asarray(key_np), jnp.ones(key_np.shape[0], jnp.bool_))]
    cols = [None if v is None else ColV(jnp.asarray(v[0]), jnp.asarray(v[1]))
            for v in vals]
    return G.groupby_agg(keys, dtypes or [T.LONG], cols, list(ops),
                         num_rows, strategy=strategy)


def _assert_matrix_agrees(key_np, vals, num_rows, ops, float_ops=()):
    """Run every strategy over one torture input and diff against the
    SCATTER baseline: bit-identical on ints/counts/winner families,
    tolerance-matched on float sums per the strategy's decomposition."""
    base = _groups_of(*_run_strategy("SCATTER", key_np, vals, num_rows, ops))
    for strategy in STRATEGIES[1:]:
        got = _groups_of(*_run_strategy(strategy, key_np, vals, num_rows,
                                        ops))
        assert set(got) == set(base), (strategy, set(got) ^ set(base))
        for k in base:
            for ai, ((bv, bok), (gv, gok)) in enumerate(zip(base[k],
                                                            got[k])):
                assert bok == gok, (strategy, k, ai)
                if not bok:
                    continue
                if ai in float_ops:
                    bf, gf = float(bv), float(gv)
                    if np.isnan(bf) or np.isnan(gf):
                        assert np.isnan(bf) and np.isnan(gf), \
                            (strategy, k, ai, bf, gf)
                    elif strategy in _TIGHT_FLOAT:
                        np.testing.assert_allclose(gf, bf, rtol=1e-12,
                                                   atol=0.0,
                                                   err_msg=str((strategy,
                                                                k, ai)))
                    else:
                        np.testing.assert_allclose(gf, bf, rtol=1e-4,
                                                   atol=1e-6,
                                                   err_msg=str((strategy,
                                                                k, ai)))
                else:
                    assert bv == gv, (strategy, k, ai, bv, gv)


def test_matrix_int64_wraparound_and_counts():
    n, cap = 700, 1024
    rng = np.random.default_rng(5)
    key = np.zeros(cap, np.int64)
    key[:n] = rng.integers(0, 23, n)
    big = np.zeros(cap, np.int64)
    big[:n] = (1 << 62) + rng.integers(0, 1 << 40, n)  # wraps per group
    valid = np.zeros(cap, bool)
    valid[:n] = rng.random(n) > 0.15
    _assert_matrix_agrees(
        key, [(big, valid), (big, valid), None], n,
        ["sum", "count", "count_star"])


def test_matrix_all_null_and_minmax_first_last():
    n, cap = 500, 1024
    rng = np.random.default_rng(6)
    key = np.zeros(cap, np.int64)
    key[:n] = rng.integers(0, 11, n)
    data = np.zeros(cap, np.int64)
    data[:n] = rng.integers(-(2 ** 62), 2 ** 62, n)
    none = np.zeros(cap, bool)
    some = np.zeros(cap, bool)
    some[:n] = rng.random(n) > 0.5
    _assert_matrix_agrees(
        key,
        [(data, none), (data, some), (data, some), (data, some),
         (data, none)],
        n, ["sum", "min", "max", "first", "count"])


def test_matrix_float_streams_inf_nan_huge():
    """The float-sum decompositions (MATMUL/PALLAS f32 hi/lo + overflow
    correction, RADIX NORMAL/BIG/flags) must agree with the plain f64
    scatter sum on normals, huge magnitudes (>2^500), infinities of one
    sign, mixed infinities (-> NaN), and NaN poisoning."""
    cases = {
        0: [1.5, -2.25, 3e8],                      # plain normals
        1: [1e300, 1e300, -2.5e299],               # BIG stream only
        2: [np.inf, 1.0, 2.0],                     # +inf survives
        3: [-np.inf, -1.0],                        # -inf survives
        4: [np.inf, -np.inf, 5.0],                 # mixed -> NaN
        5: [np.nan, 1.0],                          # NaN poisons
        6: [1e308, 1e308],                         # overflow -> +inf
        7: [2.0 ** 501, -(2.0 ** 501), 7.0],       # BIG cancels to normal
    }
    rows = [(k, v) for k, vs in cases.items() for v in vs]
    n, cap = len(rows), 256
    key = np.zeros(cap, np.int64)
    fval = np.zeros(cap)
    key[:n] = [k for k, _ in rows]
    fval[:n] = [v for _, v in rows]
    valid = np.zeros(cap, bool)
    valid[:n] = True
    _assert_matrix_agrees(key, [(fval, valid), (fval, valid)], n,
                          ["sum", "count"], float_ops={0})


def test_matrix_float_magnitude_disparity_across_groups():
    """One group's 1e30 must not corrupt a NEIGHBOURING group's small
    sum: a tile-wide float prefix difference would cancel group 1's
    1+2+3 to 0.0 against group 0's 1e30 — the RADIX float family
    reduces by a segmented scan that resets at every boundary, so
    cross-group contamination is structurally impossible (regression
    for the round-12 review finding)."""
    cap = 256
    key = np.zeros(cap, np.int64)
    fval = np.zeros(cap)
    rows = [(0, 1e30), (1, 1.0), (1, 2.0), (1, 3.0), (2, -4.5),
            (0, 2.5e30), (3, 1e-20), (3, 2e-20)]
    n = len(rows)
    key[:n] = [k for k, _ in rows]
    fval[:n] = [v for _, v in rows]
    valid = np.zeros(cap, bool)
    valid[:n] = True
    _assert_matrix_agrees(key, [(fval, valid), (fval, valid)], n,
                          ["sum", "count"], float_ops={0})
    # and explicitly against the exact per-group answer
    keys, aggs, nseg = _run_strategy(
        "RADIX", key, [(fval, valid)], n, ["sum"])
    got = {int(np.asarray(keys[0].data)[i]):
           float(np.asarray(aggs[0].data)[i]) for i in range(int(nseg))}
    assert got[1] == 6.0 and got[2] == -4.5, got
    np.testing.assert_allclose(got[0], 3.5e30, rtol=1e-12)
    np.testing.assert_allclose(got[3], 3e-20, rtol=1e-12)


def test_matrix_dead_rows_never_contribute():
    """Rows past num_rows carry arbitrary garbage (incl. extreme values
    that would win any min/max) and must drop from every lowering."""
    n, cap = 100, 512
    rng = np.random.default_rng(8)
    key = rng.integers(0, 7, cap)  # garbage keys on dead rows too
    data = rng.integers(-(2 ** 62), 2 ** 62, cap)
    data[n:] = np.int64(-(2 ** 63))  # would win every min
    valid = np.ones(cap, bool)
    _assert_matrix_agrees(
        key, [(data, valid), (data, valid), (data, valid), None], n,
        ["sum", "min", "max", "count_star"])


def test_matrix_empty_batch():
    cap = 256
    key = np.zeros(cap, np.int64)
    data = np.zeros(cap, np.int64)
    valid = np.zeros(cap, bool)
    for strategy in STRATEGIES:
        keys, aggs, nseg = _run_strategy(
            strategy, key, [(data, valid), None], 0, ["sum", "count_star"])
        assert int(nseg) == 0, strategy


def test_matrix_tier_overflow_escalates_scatter_free():
    """Cardinality past the first hash tier (128 buckets) forces the
    tier-escalation retry; under RADIX/PALLAS the escalation (and the
    final sort fallback) must still produce the baseline's groups."""
    n, cap = 1500, 2048
    rng = np.random.default_rng(9)
    key = np.zeros(cap, np.int64)
    key[:n] = rng.integers(0, 600, n)  # > 128: first tier overflows
    data = np.zeros(cap, np.int64)
    data[:n] = rng.integers(-(2 ** 62), 2 ** 62, n)
    valid = np.zeros(cap, bool)
    valid[:n] = rng.random(n) > 0.1
    _assert_matrix_agrees(
        key, [(data, valid), (data, valid), None], n,
        ["sum", "max", "count_star"])


# ---------------------------------------------------------------------------
# radix tiling: multi-tile, flush tile, non-divisor tiles
# ---------------------------------------------------------------------------
@pytest.fixture
def force_tile():
    prev = RBX.FORCE_TILE_ROWS

    def set_tile(t):
        RBX.FORCE_TILE_ROWS = t

    try:
        yield set_tile
    finally:
        RBX.FORCE_TILE_ROWS = prev


@pytest.mark.parametrize("tile", [32, 48, 100])
def test_radix_tiling_multi_tile_and_flush(force_tile, tile):
    """Small forced tiles drive segments ACROSS tile boundaries (the
    open-segment carry) and the final flush trip; 48/100 do not divide
    the capacity, covering the ragged last tile. Results must match the
    untiled scatter baseline exactly."""
    n, cap = 900, 1024
    rng = np.random.default_rng(tile)
    key = np.zeros(cap, np.int64)
    key[:n] = np.sort(rng.integers(0, 9, n))  # few groups: long runs
    data = np.zeros(cap, np.int64)
    data[:n] = rng.integers(-(2 ** 62), 2 ** 62, n)
    fval = np.zeros(cap)
    fval[:n] = rng.normal(size=n) * 1e6
    valid = np.zeros(cap, bool)
    valid[:n] = rng.random(n) > 0.2
    base = _groups_of(*_run_strategy(
        "SCATTER", key,
        [(data, valid), (fval, valid), (data, valid), None], n,
        ["sum", "sum", "min", "count_star"]))
    force_tile(tile)
    got = _groups_of(*_run_strategy(
        "RADIX", key,
        [(data, valid), (fval, valid), (data, valid), None], n,
        ["sum", "sum", "min", "count_star"]))
    assert set(got) == set(base)
    for k in base:
        (bs, _), (bf, bfok), (bm, bmok), (bc, _) = base[k]
        (gs, _), (gf, gfok), (gm, gmok), (gc, _) = got[k]
        assert bs == gs and bc == gc and bmok == gmok
        if bmok:
            assert bm == gm
        if bfok:
            np.testing.assert_allclose(float(gf), float(bf), rtol=1e-12)


def test_radix_single_group_spanning_every_tile(force_tile):
    """One group across ALL tiles: the open-segment carry chains through
    every trip and only the flush tile finally writes it."""
    n, cap = 1000, 1024
    force_tile(64)
    key = np.zeros(cap, np.int64)
    data = np.ones(cap, np.int64)
    valid = np.zeros(cap, bool)
    valid[:n] = True
    keys, aggs, nseg = _run_strategy(
        "RADIX", key, [(data, valid), None], n, ["sum", "count_star"])
    assert int(nseg) == 1
    assert int(np.asarray(aggs[0].data)[0]) == n
    assert int(np.asarray(aggs[1].data)[0]) == n


# ---------------------------------------------------------------------------
# PALLAS bucket kernels vs the scatter baseline (negative/dead ids)
# ---------------------------------------------------------------------------
def test_pallas_bucket_reduce_negative_and_dead_ids():
    from spark_rapids_tpu.ops import bucket_reduce as BR
    from spark_rapids_tpu.ops.pallas_groupby import pallas_bucket_reduce

    n, B = 777, 48
    rng = np.random.default_rng(12)
    seg = rng.integers(-3, B + 4, n).astype(np.int32)  # both tails
    ival = rng.integers(-(2 ** 62), 2 ** 62, n)
    fval = rng.uniform(-1e6, 1e6, n)
    valid = rng.random(n) < 0.8
    args = (jnp.asarray(seg), B,
            [(jnp.asarray(ival), jnp.asarray(valid))],
            [jnp.asarray(valid)],
            [(jnp.asarray(fval), jnp.asarray(valid))])
    base = BR.bucket_reduce(*args, strategy="SCATTER")
    got = pallas_bucket_reduce(jnp.asarray(seg), B,
                               [(jnp.asarray(ival), jnp.asarray(valid))],
                               [jnp.asarray(valid)],
                               [(jnp.asarray(fval), jnp.asarray(valid))])
    np.testing.assert_array_equal(np.asarray(got[0][0]),
                                  np.asarray(base[0][0]))
    np.testing.assert_array_equal(np.asarray(got[1][0]),
                                  np.asarray(base[1][0]))
    np.testing.assert_allclose(np.asarray(got[2][0]),
                               np.asarray(base[2][0]),
                               rtol=1e-4, atol=1e-6)


def test_pallas_bucket_min_max_and_position():
    import jax

    from spark_rapids_tpu.ops.pallas_groupby import (
        pallas_bucket_min_max, pallas_bucket_position)

    n, B = 600, 32
    rng = np.random.default_rng(13)
    seg = jnp.asarray(rng.integers(0, B, n).astype(np.int32))
    consider = jnp.asarray(rng.random(n) < 0.7)
    for dt, fill in ((np.int64, (2 ** 63 - 1, -(2 ** 63))),
                     (np.float64, (np.inf, -np.inf))):
        data = (rng.integers(-(2 ** 62), 2 ** 62, n).astype(dt)
                if dt is np.int64 else
                (rng.normal(size=n) * 1e9).astype(dt))
        for op, ident in zip(("min", "max"), fill):
            masked = jnp.where(consider, jnp.asarray(data),
                               jnp.asarray(dt(ident)))
            fn = jax.ops.segment_min if op == "min" else jax.ops.segment_max
            want = np.asarray(fn(masked, seg, num_segments=B))
            got = np.asarray(pallas_bucket_min_max(
                seg, B, op, [masked])[0])
            have = np.asarray(jax.ops.segment_sum(
                consider.astype(jnp.int32), seg, num_segments=B)) > 0
            np.testing.assert_array_equal(got[have], want[have],
                                          err_msg=f"{dt} {op}")
    # first/last considered row per bucket
    idx = jnp.arange(n, dtype=jnp.int32)
    for op, red in (("min", jax.ops.segment_min),
                    ("max", jax.ops.segment_max)):
        fillv = n + 1 if op == "min" else -1
        want = np.asarray(red(jnp.where(consider, idx, jnp.int32(fillv)),
                              seg, num_segments=B))
        row, found = pallas_bucket_position(seg, B, op, consider)
        have = np.asarray(found)
        np.testing.assert_array_equal(np.asarray(row)[have],
                                      want[have], err_msg=op)


# ---------------------------------------------------------------------------
# exec-level: the conf-selected strategies against the CPU oracle
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("strategy", list(STRATEGIES))
def test_exec_strategy_matrix_vs_cpu_oracle(strategy):
    n = 160
    data = {
        "k": [i % 7 if i % 11 else None for i in range(n)],
        "a": [(i * 13) % 400 - 200 for i in range(n)],
        "b": [None if i % 9 == 0 else (i / 7.0 - 10.0) for i in range(n)],
    }
    schema = schema_of(k=T.INT, a=T.LONG, b=T.DOUBLE)

    # the f32 hi/lo decompositions (MATMUL/PALLAS) sit outside the
    # harness's 1e-9 oracle tolerance; their float-sum correctness is
    # pinned by the ops-level matrix at the documented 1e-4 tolerance
    fsum = ([A.agg(A.Sum(col("b")), "sb")]
            if strategy in _TIGHT_FLOAT else [])

    def build(s):
        return (s.create_dataframe(data, schema).group_by("k")
                .agg(A.agg(A.Sum(col("a")), "sa"),
                     *fsum,
                     A.agg(A.Min(col("a")), "mn"),
                     A.agg(A.Max(col("b")), "mx"),
                     A.agg(A.Count(col("b")), "cb"),
                     A.agg(A.Count(None), "cs")))

    assert_tpu_and_cpu_equal(
        build,
        conf={"spark.rapids.tpu.sql.agg.strategy": strategy,
              # float sums need the variableFloatAgg opt-in to replace;
              # the ops-level matrix above pins per-strategy tightness
              "spark.rapids.tpu.sql.variableFloatAgg.enabled": True},
        approx_float=True)


# ---------------------------------------------------------------------------
# recompile guard: forced RADIX / PALLAS compile once, rerun nothing
# ---------------------------------------------------------------------------
def _plan(conf, batches, schema):
    scan = InMemoryScanExec(conf, [batches], schema)
    proj = TpuProjectExec(
        conf, [col("k"), E.Alias(E.Multiply(col("a"), lit(3)), "a3")], scan)
    return TpuHashAggregateExec(
        conf, [col("k")],
        [A.agg(A.Sum(col("a3")), "s"), A.agg(A.Count(None), "c"),
         A.agg(A.Min(col("a3")), "mn")], proj)


@pytest.mark.parametrize("strategy", ["RADIX", "PALLAS"])
def test_forced_strategy_compiles_once(strategy):
    rng = np.random.default_rng(14)
    schema = schema_of(k=T.INT, a=T.LONG)
    nb, n = 3, 330 if strategy == "RADIX" else 350  # distinct cap buckets
    batches = [ColumnarBatch.from_pydict({
        "k": [int(x) for x in rng.integers(0, 6, n)],
        "a": [int(x) for x in rng.integers(-100, 100, n)],
    }, schema) for _ in range(nb)]
    conf = RapidsConf({"spark.rapids.tpu.sql.agg.fusedPlan": "ON",
                       "spark.rapids.tpu.sql.agg.strategy": strategy})
    agg = _plan(conf, batches, schema)
    before = exec_base.compile_miss_count()
    rows1 = agg.collect()
    assert exec_base.compile_miss_count() - before == 1, \
        exec_base.COMPILE_COUNTER.by_site
    again = _plan(conf, batches, schema)
    before2 = exec_base.compile_miss_count()
    rows2 = again.collect()
    assert exec_base.compile_miss_count() == before2
    assert sorted(rows1) == sorted(rows2)
    # and the baseline cross-check: same groups as the scatter program
    base = _plan(RapidsConf({
        "spark.rapids.tpu.sql.agg.fusedPlan": "ON",
        "spark.rapids.tpu.sql.agg.strategy": "SCATTER"}), batches, schema)
    assert sorted(base.collect()) == sorted(rows1)


# ---------------------------------------------------------------------------
# Pallas join probe kernel
# ---------------------------------------------------------------------------
def test_pallas_probe_ranges_matches_binary_search():
    from spark_rapids_tpu.ops import join as J

    rng = np.random.default_rng(15)
    nb, m = 300, 517
    build = np.sort(rng.integers(0, 90, nb).astype(np.uint32))
    bcount = 211  # rows past the count are non-joinable padding
    build[bcount:] = np.uint32(0xFFFFFFFF)
    probe = rng.integers(0, 120, m).astype(np.uint32)
    live = rng.random(m) < 0.85
    args = ([jnp.asarray(build)], jnp.int32(bcount),
            [jnp.asarray(probe)], jnp.asarray(live))
    lo0, hi0 = J.probe_ranges(*args, pallas=False)
    lo1, hi1 = J.probe_ranges(*args, pallas=True)
    np.testing.assert_array_equal(np.asarray(hi0 - lo0),
                                  np.asarray(hi1 - lo1))
    has = np.asarray(hi1 - lo1) > 0
    np.testing.assert_array_equal(np.asarray(lo0)[has],
                                  np.asarray(lo1)[has])


@pytest.mark.parametrize("how", ["inner", "left", "semi", "anti"])
def test_exec_join_with_pallas_probe(how):
    ln, rn = 90, 31
    ldata = {"k": [i % 9 if i % 11 else None for i in range(ln)],
             "a": [(i * 7) % 50 - 25 for i in range(ln)]}
    rdata = {"k2": [i % 12 if i % 7 else None for i in range(rn)],
             "b": [i / 3.0 for i in range(rn)]}
    lsch = schema_of(k=T.INT, a=T.LONG)
    rsch = schema_of(k2=T.INT, b=T.DOUBLE)

    def build(s):
        return s.create_dataframe(ldata, lsch).join(
            s.create_dataframe(rdata, rsch), on=[("k", "k2")], how=how)

    assert_tpu_and_cpu_equal(
        build,
        conf={"spark.rapids.tpu.sql.join.pallasProbe.enabled": True},
        approx_float=True)
