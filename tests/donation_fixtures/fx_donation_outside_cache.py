"""Must-catch fixture: donation invisible to the cache key (TPU203) —
the warm-process alias fork.

``cached_pipeline`` folds the donate mask into the structural key AND
the AOT program-cache entry identity; a ``donate_argnums`` declared
anywhere else forks donating and non-donating callers onto one cache
entry, so the warm process serves a donating program to a caller that
still owns its planes. tpu_donate must flag ``jit_donating_loose`` and
``pjit_donating_loose`` with TPU203, and must NOT flag
``jit_donating_routed`` (the builder hands the jit to a
``cached_pipeline`` call that carries ``donate=``) or ``jit_plain``
(no donation declared at all).
"""
import jax

from spark_rapids_tpu.exec.base import cached_pipeline

_CACHE = {}


def jit_donating_loose(fn):
    return jax.jit(fn, donate_argnums=(0,))


def pjit_donating_loose(pjit, fn):
    return pjit(fn, donate_argnums=(0,))


def jit_donating_routed(key, fn, mask):
    return cached_pipeline(
        _CACHE, key, "project",
        lambda: jax.jit(fn, donate_argnums=mask), donate=mask)


def jit_plain(fn):
    return jax.jit(fn)
