"""Must-catch fixture: batch read after its donating dispatch (TPU201)
— the use-after-free shape the guard exists to make impossible.

A batch dispatched under ``donation.guard(<certified site>, batch)``
has its planes DELETED by the donating program; any plane-reaching
read after the guarded block is a use-after-free the backend reports
as "Array has been deleted". tpu_donate must flag ``read_after_guard``
(a raw re-read) and ``rows_after_guard`` (plane-reaching method call)
with TPU201, and must NOT flag ``metadata_after_guard`` (safe
metadata attributes only) or ``else_arm_dispatch`` (the engine's
``if mask: with guard(...): ... else: ...`` idiom, where the else arm
is textually later but an execution ALTERNATIVE).
"""
from spark_rapids_tpu.plugin import donation


def read_after_guard(fn, batch, vals_of_batch):
    with donation.guard("project", batch, op="Project"):
        out = fn(vals_of_batch(batch))
    return out, vals_of_batch(batch)     # planes are gone


def rows_after_guard(fn, batch, vals_of_batch):
    with donation.guard("agg_update", batch, op="HashAggregate"):
        out = fn(vals_of_batch(batch))
    return out, batch.to_rows()          # plane-reaching method


def metadata_after_guard(fn, batch, vals_of_batch):
    with donation.guard("project", batch, op="Project"):
        out = fn(vals_of_batch(batch))
    return out, batch.num_rows, batch.schema   # metadata stays valid


def else_arm_dispatch(fn, batch, mask, vals_of_batch):
    if mask:
        with donation.guard("project", batch, op="Project"):
            out = fn(vals_of_batch(batch))
    else:
        out = fn(vals_of_batch(batch))   # alternative arm, not "later"
    return out
