"""Must-catch fixture: certified site dispatching with no donate= mask
(TPU202, warn-level) — the win left on the table.

``"project"`` is donation-certified in the DONATION_SPECS table; a
``cached_pipeline`` call naming it without plumbing ``donate=`` skips
the peak-temp win the certification proved safe. tpu_donate must warn
on ``build_without_mask`` with TPU202 (warning only — exit stays 0)
and must NOT warn on ``build_with_mask`` or ``build_uncertified``
(``"sort"`` is not certified, so there is no mask to plumb).
"""
from spark_rapids_tpu.exec.base import cached_pipeline

_CACHE = {}


def build_without_mask(key, build):
    return cached_pipeline(_CACHE, key, "project", build)


def build_with_mask(key, build, mask):
    return cached_pipeline(_CACHE, key, "project", build, donate=mask)


def build_uncertified(key, build):
    return cached_pipeline(_CACHE, key, "sort", build)
