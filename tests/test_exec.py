"""Exec-layer unit tests (reference tier-2 analog: operator suites)."""
import math

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import ColumnarBatch
from spark_rapids_tpu.columnar.batch import schema_of
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.exec import (
    InMemoryScanExec,
    TpuCoalesceBatchesExec,
    TpuExpandExec,
    TpuFilterExec,
    TpuHashAggregateExec,
    TpuLocalLimitExec,
    TpuProjectExec,
    TpuRangeExec,
    TpuUnionExec,
)
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.expressions import col, lit

CONF = RapidsConf()


def scan(data, schema, parts=1):
    return InMemoryScanExec.from_pydict(CONF, data, schema, parts)


class TestBasicExecs:
    def test_project(self):
        s = schema_of(a=T.INT, b=T.DOUBLE)
        src = scan({"a": [1, 2, None], "b": [1.5, None, 3.0]}, s)
        p = TpuProjectExec(CONF, [E.Alias(E.Add(col("a"), lit(10)), "a10"), col("b")], src)
        rows = p.collect()
        assert rows == [(11, 1.5), (12, None), (None, 3.0)]
        assert p.output_schema.names == ["a10", "b"]

    def test_filter(self):
        s = schema_of(a=T.INT)
        src = scan({"a": [1, 2, 3, None, 5, 6]}, s)
        f = TpuFilterExec(CONF, E.GreaterThan(col("a"), lit(2)), src)
        assert f.collect() == [(3,), (5,), (6,)]

    def test_filter_with_strings_passthrough(self):
        s = schema_of(a=T.INT, name=T.STRING)
        src = scan({"a": [1, 2, 3], "name": ["x", None, "zzz"]}, s)
        f = TpuFilterExec(CONF, E.LessThan(col("a"), lit(3)), src)
        assert f.collect() == [(1, "x"), (2, None)]

    def test_range(self):
        r = TpuRangeExec(CONF, 0, 10, 3)
        assert r.collect() == [(0,), (3,), (6,), (9,)]

    def test_range_partitions(self):
        r = TpuRangeExec(CONF, 0, 100, 1, num_slices=4)
        assert r.num_partitions == 4
        assert sorted(x[0] for x in r.collect()) == list(range(100))

    def test_union(self):
        s = schema_of(a=T.INT)
        u = TpuUnionExec(CONF, [scan({"a": [1, 2]}, s), scan({"a": [3]}, s)])
        assert u.collect() == [(1,), (2,), (3,)]
        assert u.num_partitions == 2

    def test_limit(self):
        s = schema_of(a=T.INT)
        src = scan({"a": list(range(10))}, s)
        l = TpuLocalLimitExec(CONF, 4, src)
        assert l.collect() == [(0,), (1,), (2,), (3,)]

    def test_limit_larger_than_input(self):
        s = schema_of(a=T.INT)
        src = scan({"a": [1, 2]}, s)
        assert TpuLocalLimitExec(CONF, 10, src).collect() == [(1,), (2,)]

    def test_expand(self):
        s = schema_of(a=T.INT)
        src = scan({"a": [1, 2]}, s)
        ex = TpuExpandExec(
            CONF,
            [[col("a"), lit(0)], [col("a"), lit(1)]],
            ["a", "tag"],
            src,
        )
        assert sorted(ex.collect()) == [(1, 0), (1, 1), (2, 0), (2, 1)]

    def test_coalesce_batches(self):
        s = schema_of(a=T.INT, w=T.STRING)
        b1 = ColumnarBatch.from_pydict({"a": [1, 2], "w": ["x", "yy"]}, s)
        b2 = ColumnarBatch.from_pydict({"a": [3], "w": [None]}, s)
        b3 = ColumnarBatch.from_pydict({"a": [4, 5], "w": ["zzz", ""]}, s)
        src = InMemoryScanExec(CONF, [[b1, b2, b3]], s)
        co = TpuCoalesceBatchesExec(CONF, src, target_rows=100)
        out = list(co.execute_columnar())
        assert len(out) == 1
        assert out[0].to_rows() == [
            (1, "x"), (2, "yy"), (3, None), (4, "zzz"), (5, ""),
        ]


class TestAggregate:
    def test_complete_grouped(self):
        s = schema_of(k=T.INT, v=T.LONG)
        src = scan({"k": [1, 2, 1, None, 2, 1], "v": [10, 20, 30, 40, None, 50]}, s)
        aggp = TpuHashAggregateExec(
            CONF, [col("k")],
            [A.agg(A.Sum(col("v")), "s"), A.agg(A.Count(col("v")), "c"),
             A.agg(A.Count(), "n"), A.agg(A.Average(col("v")), "m")],
            src,
        )
        rows = {r[0]: r[1:] for r in aggp.collect()}
        assert rows[1] == (90, 3, 3, 30.0)
        assert rows[2] == (20, 1, 2, 20.0)
        assert rows[None] == (40, 1, 1, 40.0)

    def test_complete_no_keys(self):
        s = schema_of(v=T.INT)
        src = scan({"v": [1, None, 3]}, s)
        aggp = TpuHashAggregateExec(
            CONF, [], [A.agg(A.Sum(col("v"))), A.agg(A.Count(col("v"))),
                       A.agg(A.Min(col("v"))), A.agg(A.Max(col("v")))], src,
        )
        assert aggp.collect() == [(4, 2, 1, 3)]

    def test_empty_input_no_keys(self):
        s = schema_of(v=T.INT)
        src = scan({"v": []}, s)
        aggp = TpuHashAggregateExec(
            CONF, [], [A.agg(A.Count(col("v"))), A.agg(A.Sum(col("v")))], src,
        )
        assert aggp.collect() == [(0, None)]

    def test_empty_input_grouped(self):
        s = schema_of(k=T.INT, v=T.INT)
        src = scan({"k": [], "v": []}, s)
        aggp = TpuHashAggregateExec(
            CONF, [col("k")], [A.agg(A.Sum(col("v")))], src)
        assert aggp.collect() == []

    def test_partial_final_roundtrip(self):
        s = schema_of(k=T.INT, v=T.INT)
        src = scan({"k": [1, 2, 1, 2, 1], "v": [1, 2, 3, 4, 5]}, s)
        partial = TpuHashAggregateExec(
            CONF, [col("k")],
            [A.agg(A.Average(col("v")), "m"), A.agg(A.Count(), "n")],
            src, mode=A.PARTIAL,
        )
        # partial emits buffer columns (sum, count, count_star)
        assert len(partial.output_schema.fields) == 4
        final = TpuHashAggregateExec(
            CONF, [col("k")],
            [A.agg(A.Average(col("v")), "m"), A.agg(A.Count(), "n")],
            partial, mode=A.FINAL,
        )
        rows = {r[0]: r[1:] for r in final.collect()}
        assert rows[1] == (3.0, 3)
        assert rows[2] == (3.0, 2)

    def test_multi_batch_merge(self):
        s = schema_of(k=T.INT, v=T.LONG)
        b1 = ColumnarBatch.from_pydict({"k": [1, 2], "v": [1, 2]}, s)
        b2 = ColumnarBatch.from_pydict({"k": [1, 3], "v": [10, 30]}, s)
        b3 = ColumnarBatch.from_pydict({"k": [2, 1], "v": [200, 100]}, s)
        src = InMemoryScanExec(CONF, [[b1, b2, b3]], s)
        aggp = TpuHashAggregateExec(
            CONF, [col("k")], [A.agg(A.Sum(col("v")), "s")], src)
        rows = dict(aggp.collect())
        assert rows == {1: 111, 2: 202, 3: 30}

    def test_string_keys_aggregate(self):
        s = schema_of(k=T.STRING, v=T.INT)
        src = scan({"k": ["a", "b", "a", None, "b"], "v": [1, 2, 3, 4, 5]}, s)
        aggp = TpuHashAggregateExec(
            CONF, [col("k")], [A.agg(A.Sum(col("v")), "s")], src)
        rows = dict(aggp.collect())
        assert rows == {"a": 4, "b": 7, None: 4}

    def test_first_last(self):
        s = schema_of(k=T.INT, v=T.INT)
        src = scan({"k": [1, 1, 1], "v": [None, 5, 7]}, s)
        aggp = TpuHashAggregateExec(
            CONF, [col("k")],
            [A.agg(A.First(col("v"), ignore_nulls=True), "f"),
             A.agg(A.Last(col("v")), "l")],
            src,
        )
        assert aggp.collect() == [(1, 5, 7)]

    def test_avg_all_null_group(self):
        s = schema_of(k=T.INT, v=T.INT)
        src = scan({"k": [1, 1], "v": [None, None]}, s)
        aggp = TpuHashAggregateExec(
            CONF, [col("k")], [A.agg(A.Average(col("v")), "m")], src)
        assert aggp.collect() == [(1, None)]


class TestPipeline:
    def test_scan_filter_project_aggregate(self):
        """The 'minimum end-to-end slice' shape from SURVEY.md §7 step 4."""
        s = schema_of(k=T.INT, a=T.LONG, b=T.DOUBLE)
        n = 1000
        data = {
            "k": [i % 7 for i in range(n)],
            "a": [i for i in range(n)],
            "b": [float(i) / 3 if i % 11 else None for i in range(n)],
        }
        src = scan(data, s, parts=2)
        f = TpuFilterExec(CONF, E.GreaterThanOrEqual(col("a"), lit(100)), src)
        p = TpuProjectExec(
            CONF, [col("k"), E.Alias(E.Multiply(col("a"), lit(2)), "a2"), col("b")], f)
        aggp = TpuHashAggregateExec(
            CONF, [col("k")],
            [A.agg(A.Sum(col("a2")), "s"), A.agg(A.Average(col("b")), "m"),
             A.agg(A.Count(), "n")],
            p,
        )
        merged = {}
        for row in aggp.collect():  # two partitions -> merge per-key
            k, sm, m, c = row
            if k in merged:
                os, om, oc = merged[k]
                merged[k] = (os + sm, None, oc + c)
            else:
                merged[k] = (sm, m, c)
        # oracle
        import collections

        osum = collections.Counter()
        ocnt = collections.Counter()
        for i in range(n):
            if i >= 100:
                osum[i % 7] += 2 * i
                ocnt[i % 7] += 1
        for k in osum:
            assert merged[k][0] == osum[k], k
            assert merged[k][2] == ocnt[k], k
