"""TPU-side parquet page decode vs the host arrow decoder.

Differential contract: for any file pyarrow can write, the device decode
path (io/parquet_device.py) must produce exactly what the host decode path
produces — same values, same nulls, same strings. Mirrors the reference's
parquet differential suites (parquet_test.py) for the decoder half."""
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.exec.scan import TpuFileSourceScanExec
from spark_rapids_tpu.io.parquet import ParquetScanner


def _collect(path, conf_dict):
    conf = RapidsConf(conf_dict)
    sc = ParquetScanner(path, conf)
    ex = TpuFileSourceScanExec(conf, sc, "parquet")
    rows = []
    for p in range(ex.num_partitions):
        for b in ex.execute_partition(p):
            rows.extend(b.to_rows())
    return rows


def _roundtrip(table, tmp_path, name="t.parquet", **write_kw):
    path = os.path.join(str(tmp_path), name)
    pq.write_table(table, path, **write_kw)
    on = _collect(path, {})
    off = _collect(
        path,
        {"spark.rapids.tpu.sql.format.parquet.deviceDecode.enabled": False},
    )
    assert on == off, (on[:5], off[:5])
    return on


def _used_device(path, conf_dict=None):
    conf = RapidsConf(conf_dict or {})
    sc = ParquetScanner(path, conf)
    dev, _ = sc.read_split_device(0)
    return dev is not None


def test_dictionary_int_columns(tmp_path):
    rng = np.random.default_rng(5)
    n = 50_000
    t = pa.table({
        "k32": pa.array(rng.integers(0, 50, n).astype(np.int32)),
        "k64": pa.array(rng.integers(0, 1000, n).astype(np.int64)),
    })
    path = os.path.join(str(tmp_path), "d.parquet")
    pq.write_table(t, path)
    assert _used_device(path)
    rows = _roundtrip(t, tmp_path)
    assert len(rows) == n
    assert rows[0] == (int(t["k32"][0]), int(t["k64"][0]))


def test_dictionary_double_and_float(tmp_path):
    rng = np.random.default_rng(6)
    n = 20_000
    vals = rng.choice(np.round(rng.normal(size=100), 3), n)
    t = pa.table({
        "d": pa.array(vals),
        "f": pa.array(vals.astype(np.float32)),
    })
    _roundtrip(t, tmp_path)


def test_nulls_dictionary(tmp_path):
    rng = np.random.default_rng(7)
    n = 30_000
    base = rng.integers(0, 20, n).astype(np.int64)
    mask = rng.random(n) < 0.3
    arr = pa.array(
        [None if m else int(v) for m, v in zip(mask, base)],
        type=pa.int64())
    t = pa.table({"x": arr})
    rows = _roundtrip(t, tmp_path)
    assert sum(1 for r in rows if r[0] is None) == int(mask.sum())


def test_plain_int_and_float(tmp_path):
    rng = np.random.default_rng(8)
    n = 20_000
    t = pa.table({
        "i32": pa.array(rng.integers(-(2**31), 2**31, n).astype(np.int32)),
        "i64": pa.array(rng.integers(-(2**62), 2**62, n)),
        "f32": pa.array(rng.normal(size=n).astype(np.float32)),
    })
    # near-unique values: pyarrow falls back to PLAIN after dict overflow
    path = os.path.join(str(tmp_path), "p.parquet")
    pq.write_table(t, path, use_dictionary=False)
    on = _collect(path, {})
    off = _collect(
        path,
        {"spark.rapids.tpu.sql.format.parquet.deviceDecode.enabled": False})
    assert on == off
    assert _used_device(path)


def test_plain_double_falls_back(tmp_path):
    rng = np.random.default_rng(9)
    t = pa.table({"d": pa.array(rng.normal(size=1000))})
    path = os.path.join(str(tmp_path), "pd.parquet")
    pq.write_table(t, path, use_dictionary=False)
    # f64 PLAIN can't bitcast on device: whole-split fallback, same rows
    assert not _used_device(path)
    _roundtrip(t, tmp_path, name="pd2.parquet", use_dictionary=False)


def test_string_dictionary(tmp_path):
    rng = np.random.default_rng(10)
    pool = ["alpha", "béta", "", "gamma-long-value", "δ"]
    n = 25_000
    vals = [pool[i] for i in rng.integers(0, len(pool), n)]
    mask = rng.random(n) < 0.1
    t = pa.table({
        "s": pa.array([None if m else v for m, v in zip(mask, vals)]),
        "v": pa.array(np.arange(n, dtype=np.int64) % 97),
    })
    path = os.path.join(str(tmp_path), "s.parquet")
    pq.write_table(t, path)
    assert _used_device(path)
    rows = _roundtrip(t, tmp_path, name="s2.parquet")
    assert rows[0][0] == (None if mask[0] else vals[0])


def test_multiple_row_groups_and_codecs(tmp_path):
    rng = np.random.default_rng(11)
    n = 40_000
    t = pa.table({
        "k": pa.array(rng.integers(0, 10, n).astype(np.int32)),
        "v": pa.array(rng.integers(0, 5, n).astype(np.int64)),
    })
    for codec in ("snappy", "zstd", "none"):
        _roundtrip(
            t, tmp_path, name=f"c_{codec}.parquet",
            compression=codec, row_group_size=7_000)


def test_data_page_v2(tmp_path):
    rng = np.random.default_rng(12)
    n = 15_000
    base = rng.integers(0, 30, n).astype(np.int64)
    mask = rng.random(n) < 0.2
    t = pa.table({
        "x": pa.array(
            [None if m else int(v) for m, v in zip(mask, base)],
            type=pa.int64()),
        "s": pa.array(
            [None if m else f"v{v % 7}" for m, v in zip(mask, base)]),
    })
    _roundtrip(t, tmp_path, name="v2.parquet", data_page_version="2.0")


def test_sorted_runs_rle_heavy(tmp_path):
    # sorted keys produce long RLE runs — exercises the RLE branch
    n = 30_000
    k = np.sort(np.random.default_rng(13).integers(0, 25, n)).astype(np.int32)
    t = pa.table({"k": pa.array(k)})
    _roundtrip(t, tmp_path, name="rle.parquet")


def test_all_null_column(tmp_path):
    t = pa.table({
        "x": pa.array([None] * 5000, type=pa.int32()),
        "y": pa.array(np.arange(5000, dtype=np.int32)),
    })
    rows = _roundtrip(t, tmp_path, name="an.parquet")
    assert all(r[0] is None for r in rows)


def test_through_session_aggregate(tmp_path):
    """End-to-end: session -> scan(device decode) -> filter -> aggregate,
    against the pandas oracle."""
    import pandas as pd

    from spark_rapids_tpu.expr import aggregates as A
    from spark_rapids_tpu.expr import expressions as E
    from spark_rapids_tpu.expr.expressions import col, lit
    from spark_rapids_tpu.sql import TpuSession

    rng = np.random.default_rng(14)
    n = 60_000
    t = pa.table({
        "k": pa.array(rng.integers(0, 12, n).astype(np.int32)),
        "a": pa.array(rng.integers(-50, 50, n).astype(np.int64)),
    })
    path = os.path.join(str(tmp_path), "q")
    os.makedirs(path)
    pq.write_table(t, os.path.join(path, "part0.parquet"),
                   row_group_size=16_000)
    sess = TpuSession({})
    res = (
        sess.read.parquet(path)
        .where(E.GreaterThanOrEqual(col("a"), lit(0)))
        .group_by("k")
        .agg(A.agg(A.Sum(col("a")), "s"), A.agg(A.Count(None), "c"))
        .collect())
    pdf = t.to_pandas()
    exp = pdf[pdf.a >= 0].groupby("k").agg(s=("a", "sum"), c=("a", "count"))
    got = {r[0]: (r[1], r[2]) for r in res}
    assert got == {k: (int(exp.loc[k, "s"]), int(exp.loc[k, "c"]))
                   for k in exp.index}


# ---------------------------------------------------------------------------
# round 14: streamed (tiled) fixed-width unpack + the unpack layout bound
# ---------------------------------------------------------------------------
def test_tiled_unpack_matches_flat_across_torture(tmp_path):
    """The tiled fori_loop unpack (bit-expand -> dictionary gather ->
    validity expand in one streamed program) must be bit-identical to
    the flat program over nullable/non-null, dict/plain, int32/int64
    chunks at several forced (non-divisor) tile sizes."""
    from spark_rapids_tpu.io import parquet_device as PD

    rng = np.random.default_rng(31)
    n = 3000
    table = pa.table({
        "di": pa.array(rng.integers(0, 40, n).astype(np.int32)),
        "dl": pa.array(rng.integers(0, 9, n).astype(np.int64)),
        "dn": pa.array([
            None if i % 7 == 0 else int(rng.integers(0, 12))
            for i in range(n)], type=pa.int32()),
        "pl": pa.array(rng.integers(-2 ** 62, 2 ** 62, n)),
    })
    path = os.path.join(str(tmp_path), "t.parquet")
    pq.write_table(table, path, use_dictionary=["di", "dl", "dn"])
    prev_tile, prev_on = PD.FORCE_UNPACK_TILE_ROWS, PD.TILED_UNPACK
    try:
        PD.TILED_UNPACK = False
        flat = _collect(path, {})
        PD.TILED_UNPACK = True
        for tile in (32, 96, 4096):
            PD.FORCE_UNPACK_TILE_ROWS = tile
            PD._DECODE_CACHE.clear()
            from spark_rapids_tpu.io.scan_cache import DeviceScanCache

            DeviceScanCache.get_instance(RapidsConf({})).invalidate_path(
                path)
            assert _collect(path, {}) == flat, tile
    finally:
        PD.FORCE_UNPACK_TILE_ROWS = prev_tile
        PD.TILED_UNPACK = prev_on
        PD._DECODE_CACHE.clear()


def test_tiled_unpack_program_classifies_radix_bin_not_scatter():
    """The streamed unpack writes its output through multi-element
    dynamic-update-slice tiles — the radix-bin idiom, zero scatters."""
    import jax
    from spark_rapids_tpu.hlo import summarize_hlo
    from spark_rapids_tpu.io import parquet_device as PD
    from spark_rapids_tpu.utils.bucketing import bucket_rows

    rng = np.random.default_rng(5)
    n = 200_000
    validity = rng.random(n) < 0.9
    plan = PD.ChunkPlan(phys="INT64", num_values=n, nullable=True)
    plan.validity = validity
    D = 64
    plan.dict_values = rng.integers(-10 ** 9, 10 ** 9, D).astype(np.int64)
    plan.codes = rng.integers(0, D, int(validity.sum())).astype(np.uint8)
    plan.n_present = int(validity.sum())
    cap = bucket_rows(n)
    args, key, run = PD.plan_decode(plan, T.LONG, cap)
    assert any(isinstance(k, tuple) and k and k[0] == "tile"
               for k in key), key
    dev = PD.stage_decode_args([args])[0]
    c = jax.jit(run).lower(dev).compile()
    s = summarize_hlo(c.as_text(), top_k=32)
    assert s["scatter_count"] == 0, s["top_fusions"]
    assert any(r["class"] == "radix-bin" for r in s["top_fusions"])


def test_parquet_scan_footprint_and_predict_exec_hbm(tmp_path):
    """The unpack site finally has a layout bound: predict_exec_hbm over
    a live parquet scan tree is non-null (uploaded payloads + decoded
    planes from the footers), so the bench parquet shape's
    byte_amplification stops being null and the --diff growth gate
    binds there."""
    from spark_rapids_tpu.plugin.plananalysis import (
        parquet_scan_footprint,
        predict_exec_hbm,
    )

    rng = np.random.default_rng(7)
    n = 4000
    table = pa.table({
        "k": pa.array(rng.integers(0, 16, n).astype(np.int32)),
        "v": pa.array(rng.integers(0, 999, n).astype(np.int64)),
    })
    path = os.path.join(str(tmp_path), "t.parquet")
    pq.write_table(table, path, row_group_size=1024)
    conf = RapidsConf({})
    sc = ParquetScanner(path, conf)
    ex = TpuFileSourceScanExec(conf, sc, "parquet")
    fp = parquet_scan_footprint(sc, ex.output_schema)
    assert fp is not None and fp["nrg"] == 4
    assert fp["decoded"] > 0 and fp["upload_total"] > 0
    bound = predict_exec_hbm(ex)
    assert bound is not None
    assert bound == 2 * (fp["decoded"] + fp["upload_total"])
    # and a non-parquet-boundable tree still degrades to None
    from spark_rapids_tpu.io.csv import CsvScanner

    csv_path = os.path.join(str(tmp_path), "t.csv")
    with open(csv_path, "w") as f:
        f.write("a,b\n1,2\n3,4\n")
    csv_ex = TpuFileSourceScanExec(
        conf, CsvScanner(csv_path, conf), "csv")
    assert predict_exec_hbm(csv_ex) is None
