"""Memory runtime tests: buffer catalog, tiered spill, spillable batches,
semaphore — the L1 subsystem (reference suites: RapidsBufferCatalogSuite,
RapidsDeviceMemoryStoreSuite, RapidsHostMemoryStoreSuite,
RapidsDiskStoreSuite, GpuSemaphoreSuite)."""
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.columnar import ColumnarBatch
from spark_rapids_tpu.columnar.batch import schema_of
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.expressions import col
from spark_rapids_tpu.memory import (
    BufferCatalog,
    SpillableColumnarBatch,
    SpillableHandle,
    TIER_DEVICE,
    TIER_DISK,
    TIER_HOST,
    TpuSemaphore,
)

from harness import assert_tpu_and_cpu_equal


@pytest.fixture(autouse=True)
def fresh_catalog():
    yield
    BufferCatalog.reset()
    TpuSemaphore.reset()


def _cat(budget=None, host_cap=None):
    conf = {}
    if budget is not None:
        conf["spark.rapids.tpu.memory.hbm.budgetBytes"] = budget
    if host_cap is not None:
        conf["spark.rapids.tpu.memory.host.spillStorageSize"] = host_cap
    return BufferCatalog.reset(RapidsConf(conf))


def _handle(cat, nbytes=1024, priority=0):
    return SpillableHandle(
        {"d": jnp.zeros(nbytes // 4, jnp.int32)}, priority, cat)


def test_catalog_accounting_and_unregister():
    cat = _cat(budget=1 << 30)
    h = _handle(cat, 4096)
    assert cat.device_bytes == 4096
    h.close()
    assert cat.device_bytes == 0


def test_spill_on_pressure_lowest_priority_first():
    cat = _cat(budget=10_000)
    low = _handle(cat, 4096, priority=-50)
    high = _handle(cat, 4096, priority=0)
    assert cat.device_bytes == 8192
    # next registration exceeds the budget: the low-priority buffer spills
    third = _handle(cat, 4096, priority=10)
    assert low.tier == TIER_HOST
    assert high.tier == TIER_DEVICE
    assert third.tier == TIER_DEVICE
    assert cat.metrics.device_to_host == 1
    assert cat.device_bytes <= 10_000


def test_host_overflow_goes_to_disk():
    cat = _cat(budget=5_000, host_cap=5_000)
    a = _handle(cat, 4096)
    b = _handle(cat, 4096)  # a spills to host
    c = _handle(cat, 4096)  # b spills to host; host over cap -> a to disk
    assert a.tier == TIER_DISK
    assert b.tier == TIER_HOST
    assert c.tier == TIER_DEVICE
    assert cat.metrics.host_to_disk == 1
    # disk round trip preserves data
    arrs = a.materialize()
    assert a.tier == TIER_DEVICE
    assert int(jnp.sum(arrs["d"])) == 0


def test_spillable_batch_round_trip_with_strings():
    cat = _cat(budget=1 << 30)
    schema = schema_of(s=T.STRING, v=T.LONG)
    batch = ColumnarBatch.from_pydict(
        {"s": ["a", None, "ccc", "ü"], "v": [1, 2, None, 4]}, schema)
    sb = SpillableColumnarBatch(batch, catalog=cat)
    assert sb._handle.spill_to_host() > 0
    assert sb.tier == TIER_HOST
    got = sb.get_batch()
    assert sb.tier == TIER_DEVICE
    assert got.to_rows() == [("a", 1), (None, 2), ("ccc", None), ("ü", 4)]
    sb.close()


def test_pinned_buffers_never_spill():
    cat = _cat(budget=5_000)
    a = _handle(cat, 4096)
    a.pinned = True
    _handle(cat, 4096)
    assert a.tier == TIER_DEVICE


def test_semaphore_caps_concurrency():
    sem = TpuSemaphore.reset(RapidsConf(
        {"spark.rapids.tpu.sql.concurrentTpuTasks": 1}))
    order = []

    def worker(tag):
        sem.acquire_if_necessary()
        try:
            order.append(("in", tag))
            time.sleep(0.05)
            order.append(("out", tag))
        finally:
            sem.release_if_necessary()

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # with one permit, enter/exit must strictly alternate
    for i in range(0, len(order), 2):
        assert order[i][0] == "in" and order[i + 1][0] == "out"
        assert order[i][1] == order[i + 1][1]


def test_semaphore_reentrant_per_thread():
    sem = TpuSemaphore.reset(RapidsConf(
        {"spark.rapids.tpu.sql.concurrentTpuTasks": 1}))
    sem.acquire_if_necessary()
    sem.acquire_if_necessary()  # nested exec: must not deadlock
    sem.release_if_necessary()
    sem.release_if_necessary()
    assert sem._sem.acquire(blocking=False)
    sem._sem.release()


def test_query_exceeding_budget_completes_by_spilling():
    """The VERDICT item-5 'done' bar: a query whose working set exceeds a
    configured budget completes by spilling shuffle pieces."""
    cat = _cat(budget=4 * 1024)  # tiny: the exchange pieces overflow it
    from spark_rapids_tpu.sql import TpuSession

    n = 4000
    schema = T.StructType([T.StructField("k", T.INT),
                           T.StructField("v", T.LONG)])
    data = {"k": [i % 37 for i in range(n)],
            "v": [i * 3 for i in range(n)]}
    sess = TpuSession({
        "spark.rapids.tpu.shuffle.mode": "host",
        "spark.rapids.tpu.sql.shuffle.partitions": 4,
    })
    df = sess.create_dataframe(data, schema, num_partitions=3)
    rows = sorted(df.group_by("k").agg(A.agg(A.Sum(col("v")), "sv")).collect())
    expect = {}
    for i in range(n):
        expect[i % 37] = expect.get(i % 37, 0) + i * 3
    assert rows == sorted(expect.items())
    assert cat.metrics.device_to_host > 0  # it really spilled
    # all shuffle pieces were released after the reduce side consumed them
    assert cat.device_bytes + getattr(cat, "_host_bytes") < 64 * 1024


def test_exchange_reexecution_after_release():
    """Review regression: releasing shuffle pieces after the last reduce
    partition must not make the exec one-shot."""
    from spark_rapids_tpu.sql import TpuSession

    schema = T.StructType([T.StructField("k", T.INT),
                           T.StructField("v", T.LONG)])
    sess = TpuSession({"spark.rapids.tpu.shuffle.mode": "host"})
    df = sess.create_dataframe(
        {"k": [i % 5 for i in range(100)], "v": list(range(100))},
        schema, num_partitions=3)
    q = df.group_by("k").agg(A.agg(A.Sum(col("v")), "sv"))
    first = sorted(q.collect())
    second = sorted(q.collect())
    assert first == second and len(first) == 5


def test_differential_with_spilling():
    cat = _cat(budget=4 * 1024)

    def build(s):
        schema = T.StructType([T.StructField("k", T.INT),
                               T.StructField("v", T.LONG)])
        data = {"k": [i % 11 for i in range(2000)],
                "v": [i for i in range(2000)]}
        return (s.create_dataframe(data, schema, num_partitions=4)
                .group_by("k").agg(A.agg(A.Count(None), "n"),
                                   A.agg(A.Sum(col("v")), "sv")))

    assert_tpu_and_cpu_equal(
        build, conf={"spark.rapids.tpu.shuffle.mode": "host"})
    assert cat.metrics.device_to_host > 0
