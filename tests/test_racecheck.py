"""Concurrency race analyzer (tools/tpu_racecheck.py), the declared lock
hierarchy (spark_rapids_tpu/utils/locks.py), and its runtime witness.

Four layers, mirroring the ISSUE 18 acceptance criteria:

  1. analyzer contract — the must-catch fixture corpus (each historical
     race shape in tests/racecheck_fixtures/ is flagged by its matching
     rule, the fixed variants are not), the repo itself is clean under
     --strict-allowlist, stale allowlist entries fail strict mode;
  2. witness semantics — edges recorded, inversions raised AND tallied,
     reentrancy, zero-overhead when off;
  3. regressions for the real races the analyzer surfaced on today's
     tree (watchdog start/stop churn, exchange consumed-set transition,
     catalog spill-dir creation, xla_cost lazy obs bind);
  4. the witness-on serve stress: zero inversions, and every observed
     acquisition pair is downward in LOCK_ORDER — the same partial
     order TPU101 enforces statically (the static graph from
     --dump-graph under-approximates dynamic dispatch, so the
     cross-check is order-consistency plus hot-edge overlap, not
     set equality).
"""
import os
import subprocess
import sys
import threading
import time
from concurrent.futures import Future

import pytest

from spark_rapids_tpu.utils import locks

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "tpu_racecheck.py")
FIXTURES = os.path.join(REPO, "tests", "racecheck_fixtures")


def _run_tool(*args):
    return subprocess.run([sys.executable, TOOL, *args],
                          capture_output=True, text=True, cwd=REPO)


def _findings(out: str):
    """(basename, rule, qualname) triples from analyzer stdout."""
    got = set()
    for line in out.splitlines():
        if ": TPU1" not in line:
            continue
        loc, rest = line.split(": TPU", 1)
        rule = "TPU" + rest.split(" ", 1)[0]
        qual = rest.split("[", 1)[1].split("]", 1)[0]
        got.add((os.path.basename(loc.rsplit(":", 1)[0]), rule, qual))
    return got


# ---------------------------------------------------------------------------
# 1. analyzer contract
# ---------------------------------------------------------------------------
def test_fixture_corpus_must_catch():
    """Every historical race shape is flagged by its matching rule."""
    r = _run_tool(FIXTURES, "--allowlist=/dev/null")
    assert r.returncode == 1, r.stdout + r.stderr
    got = _findings(r.stdout)
    must_catch = {
        # PR 9: get-then-build in a process-global pipeline cache
        ("fx_get_then_build.py", "TPU102", "pipeline_for"),
        # PR 10: probe-lock fallback transition
        ("fx_probe_transition.py", "TPU102", "LoadProbe.note_corruption"),
        # PR 15: mesh-aux unpickle outside the corruption guard
        ("fx_mesh_aux_unpickle.py", "TPU102", "aux_for"),
        # /status mid-scrape mutation from the refresher thread
        ("fx_status_scrape.py", "TPU103", "_refresh"),
        # declared-order inversion and raw AB/BA cycle
        ("fx_lock_order.py", "TPU101", "inverted"),
        ("fx_lock_cycle.py", "TPU101", "ab"),
        # manifest lock across a blocking boundary, direct + via call edge
        ("fx_blocking_hold.py", "TPU104", "wait_under_lock"),
        ("fx_blocking_hold.py", "TPU104", "sync_under_lock"),
    }
    missing = must_catch - got
    assert not missing, f"rules failed to catch: {missing}\n{r.stdout}"


def test_fixture_corpus_fixed_variants_not_flagged():
    """The corrected shapes sitting next to each race stay quiet."""
    r = _run_tool(FIXTURES, "--allowlist=/dev/null")
    quals = {q for (_, _, q) in _findings(r.stdout)}
    for clean in ("pipeline_for_fixed", "LoadProbe.note_corruption_fixed",
                  "forward", "wait_outside_lock"):
        assert clean not in quals, f"false positive on {clean}:\n{r.stdout}"


def test_repo_clean_under_strict_allowlist():
    """The acceptance gate: exit 0 on the engine tree, no stale entries."""
    r = _run_tool("--strict-allowlist")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "clean" in r.stdout


def test_stale_allowlist_entry_fails_strict(tmp_path):
    r = _run_tool(FIXTURES, "--allowlist=/dev/null")
    keys = [f"tests/racecheck_fixtures/{b}::{q}::{rule}"
            for (b, rule, q) in _findings(r.stdout)]
    allow = tmp_path / "allow.txt"
    allow.write_text("\n".join(keys) + "\nbogus.py::gone::TPU101  # stale\n")
    # non-strict: everything real is allowlisted, the stale line is ignored
    ok = _run_tool(FIXTURES, f"--allowlist={allow}")
    assert ok.returncode == 0, ok.stdout + ok.stderr
    # strict: the stale entry is itself a failure
    strict = _run_tool(FIXTURES, f"--allowlist={allow}",
                       "--strict-allowlist")
    assert strict.returncode == 1
    assert "stale allowlist entry" in strict.stderr


def test_dump_graph_prints_declared_downward_edges():
    r = _run_tool("--dump-graph")
    assert r.returncode == 0, r.stderr
    edges = set()
    for line in r.stdout.splitlines():
        head = line.split("#", 1)[0].strip()
        if " -> " in head:
            a, b = head.split(" -> ")
            edges.add((a.strip(), b.strip()))
    assert edges, "static manifest graph is empty"
    for a, b in edges:
        assert locks.rank_of(a) < locks.rank_of(b), (
            f"static edge {a} -> {b} is not downward — TPU101 should "
            "have failed the repo-clean gate")


# ---------------------------------------------------------------------------
# 2. witness semantics
# ---------------------------------------------------------------------------
@pytest.fixture
def witness():
    locks.uninstall_witness()
    w = locks.install_witness()
    yield w
    locks.uninstall_witness()


def test_witness_records_downward_edges(witness):
    outer = locks.ordered_lock("serve.scheduler")
    inner = locks.ordered_lock("memory.catalog", reentrant=True)
    with outer:
        with inner:
            pass
    assert locks.observed_edges() == {
        ("serve.scheduler", "memory.catalog"): 1}
    assert locks.observed_inversions() == []
    rep = locks.witness_report()
    assert rep["active"] and rep["inversions"] == []
    assert rep["edges"] == ["serve.scheduler -> memory.catalog"]


def test_witness_raises_named_inversion_and_tallies(witness):
    sched = locks.ordered_lock("serve.scheduler")
    plan = locks.ordered_lock("sql.plan")
    with sched:
        with pytest.raises(locks.LockOrderInversion) as ei:
            with plan:
                pass  # pragma: no cover - the acquire raises
    assert ei.value.held == "serve.scheduler"
    assert ei.value.acquiring == "sql.plan"
    assert "LOCK_ORDER" in str(ei.value)
    # the tally survives even when a stress harness swallows the raise
    assert ("serve.scheduler", "sql.plan",
            threading.current_thread().name) in locks.observed_inversions()
    # the colliding acquire never happened: sql.plan is free afterwards
    assert plan.acquire(blocking=False)
    plan.release()


def test_witness_reentrant_same_name_allowed(witness):
    lk = locks.ordered_lock("memory.spillable", reentrant=True)
    with lk:
        with lk:  # same-thread re-acquisition of the SAME name
            pass
    assert locks.observed_inversions() == []
    # a NON-reentrant same-name re-acquire is the self-deadlock shape
    a = locks.ordered_lock("obs.plane")
    b = locks.ordered_lock("obs.plane")
    with a:
        with pytest.raises(locks.LockOrderInversion):
            b.acquire()


def test_witness_zero_overhead_when_off():
    locks.uninstall_witness()
    assert not locks.witness_active()
    with locks.ordered_lock("sql.plan"):
        pass
    assert locks.observed_edges() == {}
    assert locks.observed_inversions() == []
    assert locks.witness_report() == {
        "active": False, "edges": [], "inversions": []}


def test_ordered_lock_rejects_undeclared_names():
    with pytest.raises(ValueError, match="LOCK_ORDER"):
        locks.ordered_lock("not.in.the.manifest")


# ---------------------------------------------------------------------------
# 3. regressions for the races the analyzer surfaced on today's tree
# ---------------------------------------------------------------------------
def _watchdog_threads():
    return [t for t in threading.enumerate()
            if t.name == "srtpu-watchdog" and t.is_alive()]


def test_watchdog_start_stop_churn_leaves_one_thread_at_most():
    """Pre-fix, unserialized start()/stop() could spawn two tick threads
    (both saw _thread None) or leak one past stop()."""
    from spark_rapids_tpu.obs.registry import MetricsRegistry
    from spark_rapids_tpu.obs.watchdog import Watchdog, WatchdogRules

    wd = Watchdog(MetricsRegistry(), WatchdogRules(), interval_s=0.01)
    base = len(_watchdog_threads())

    def churn():
        for _ in range(25):
            wd.start()
            wd.stop()

    ths = [threading.Thread(target=churn) for _ in range(6)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(30)
    assert wd._thread is None
    # double-start is idempotent: exactly one tick thread, stop reaps it
    wd.start()
    wd.start()
    assert len(_watchdog_threads()) == base + 1
    wd.stop()
    deadline = time.time() + 5
    while _watchdog_threads() and time.time() < deadline:
        time.sleep(0.01)
    assert len(_watchdog_threads()) == base
    assert wd._thread is None


def test_catalog_disk_dir_single_under_concurrency():
    """Pre-fix, concurrent host-overage drains could both see
    _spill_dir None and mkdtemp twice, scattering spill files."""
    import shutil

    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.memory.catalog import BufferCatalog

    BufferCatalog.reset(RapidsConf({}))
    cat = BufferCatalog.get()
    dirs, barrier = [], threading.Barrier(8)
    lock = threading.Lock()

    def probe():
        barrier.wait()
        d = cat._disk_dir()
        with lock:
            dirs.append(d)

    ths = [threading.Thread(target=probe) for _ in range(8)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(10)
    try:
        assert len(set(dirs)) == 1 and os.path.isdir(dirs[0])
    finally:
        shutil.rmtree(dirs[0], ignore_errors=True)
        BufferCatalog.reset(RapidsConf({}))


def test_xla_cost_obs_bind_thread_safe():
    """Pre-fix, the lazy _OBS_MOD bind was an unlocked check-then-act;
    now it double-checks under _LOCK and stays consistent under a
    thundering herd."""
    import spark_rapids_tpu.xla_cost as xc

    old = xc._OBS_MOD
    xc._OBS_MOD = None
    try:
        results, barrier = [], threading.Barrier(8)
        lock = threading.Lock()

        def probe():
            barrier.wait()
            v = xc.harvesting()
            with lock:
                results.append(v)

        ths = [threading.Thread(target=probe) for _ in range(8)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(10)
        assert len(results) == 8 and len(set(results)) == 1
        assert xc._OBS_MOD is not None
    finally:
        xc._OBS_MOD = old


def test_exchange_parallel_reduce_releases_transport_once():
    """Pre-fix, the consumed-set check-then-act let two reduce threads
    double-release the transport, or wedge the NEXT execution's release.
    Two back-to-back all-parallel executions must release exactly once
    each (the latch resets cleanly)."""
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar import ColumnarBatch, schema_of
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.exec import InMemoryScanExec
    from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.shuffle.partition import HashPartitioning

    conf = RapidsConf({"spark.rapids.tpu.shuffle.mode": "host"})
    schema = schema_of(k=T.INT, v=T.LONG)
    batch = ColumnarBatch.from_pydict(
        {"k": [i % 13 for i in range(512)],
         "v": list(range(512))}, schema)
    scan = InMemoryScanExec(conf, [[batch]], schema)
    ex = TpuShuffleExchangeExec(conf, scan, HashPartitioning([0], 8))

    releases = []
    real_release = ex.transport.release

    def counting_release(shuffle_id):
        releases.append(shuffle_id)
        return real_release(shuffle_id)

    ex.transport.release = counting_release

    for round_no in (1, 2):
        rows, errors = [], []
        lock = threading.Lock()

        def reduce_one(p):
            try:
                got = [r for b in ex.execute_partition(p)
                       for r in b.to_rows()]
                with lock:
                    rows.extend(got)
            except Exception as e:  # pragma: no cover - the failure mode
                with lock:
                    errors.append((p, repr(e)))

        ths = [threading.Thread(target=reduce_one, args=(p,))
               for p in range(ex.num_partitions)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(60)
        assert not errors, errors
        assert sorted(r[1] for r in rows) == list(range(512))
        assert len(releases) == round_no, (
            f"transport released {len(releases)}x after {round_no} full "
            "consumption round(s) — double-release or wedged latch")


# ---------------------------------------------------------------------------
# 3b. pq_decode packed-upload key determinism (the cold-start warm miss)
# ---------------------------------------------------------------------------
class _OrderedPool:
    """A decode pool that completes tasks one at a time in submission
    order or in REVERSE — the adversarial completion order that used to
    leak into the packed-upload layout key."""

    def __init__(self, reverse: bool):
        self.reverse = reverse
        self._q = []
        self._lock = threading.Lock()
        self._stop = False
        self._t = threading.Thread(target=self._drain, daemon=True)
        self._t.start()

    def submit(self, fn, *args, **kw):
        fut = Future()
        with self._lock:
            self._q.append((fut, fn, args, kw))
        return fut

    def _drain(self):
        while not self._stop:
            time.sleep(0.02)  # let a row group's whole batch accumulate
            with self._lock:
                batch, self._q = self._q, []
            if self.reverse:
                batch.reverse()
            for fut, fn, args, kw in batch:
                try:
                    fut.set_result(fn(*args, **kw))
                except BaseException as e:  # pragma: no cover
                    fut.set_exception(e)

    def stop(self):
        self._stop = True
        self._t.join(5)


def test_packed_upload_layout_is_completion_order_invariant(
        tmp_path, monkeypatch):
    """The staged-flush split must partition columns by DECLARED order,
    not decode completion order: forward and reverse completion must
    produce the identical packed layouts (= identical upload_unpack
    pipeline keys, = zero warm compile misses on the cold-start lane)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.exec.scan import TpuFileSourceScanExec
    from spark_rapids_tpu.io import arrow_convert, parquet_device
    from spark_rapids_tpu.io.parquet import ParquetScanner
    from spark_rapids_tpu.io.scan_cache import DeviceScanCache

    rng = np.random.default_rng(7)
    n = 8192
    t = pa.table({f"c{i}": pa.array(
        rng.integers(0, 50, n).astype(np.int32)) for i in range(5)})
    path = os.path.join(str(tmp_path), "d.parquet")
    pq.write_table(t, path, row_group_size=4096)

    real_upload = arrow_convert.packed_upload

    def scan_layouts(reverse: bool):
        DeviceScanCache.reset()
        layouts = []

        def spy(host_arrays):
            layouts.append(tuple(
                (a.shape, a.dtype.str) for a in host_arrays))
            return real_upload(host_arrays)

        pool = _OrderedPool(reverse)
        monkeypatch.setattr(arrow_convert, "packed_upload", spy)
        monkeypatch.setattr(parquet_device, "_decode_pool", lambda: pool)
        try:
            conf = RapidsConf(
                {"spark.rapids.tpu.scan.deviceCache.enabled": False})
            ex = TpuFileSourceScanExec(
                conf, ParquetScanner(path, conf), "parquet")
            rows = [r for p in range(ex.num_partitions)
                    for b in ex.execute_partition(p) for r in b.to_rows()]
        finally:
            pool.stop()
            monkeypatch.undo()
        return layouts, rows

    fwd_layouts, fwd_rows = scan_layouts(reverse=False)
    rev_layouts, rev_rows = scan_layouts(reverse=True)
    assert fwd_layouts, "device decode path did not stage any upload"
    assert sorted(rev_rows) == sorted(fwd_rows)
    assert sorted(fwd_layouts) == sorted(rev_layouts), (
        "packed-upload layout depends on decode completion order — the "
        "upload_unpack pipeline key is unstable across runs")


# ---------------------------------------------------------------------------
# 4. witness-on serve stress: the chaos cross-check
# ---------------------------------------------------------------------------
def test_witness_serve_stress_zero_inversions(tmp_path):
    """4 sessions x 4 queries with the witness armed via the conf entry:
    zero inversions, and every OBSERVED acquisition pair is downward in
    LOCK_ORDER — the runtime half of the TPU101 contract. The hot
    statically-predicted session edge must also actually be observed."""
    from spark_rapids_tpu import events as EV
    from spark_rapids_tpu import obs
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.expr import aggregates as A
    from spark_rapids_tpu.expr import expressions as E
    from spark_rapids_tpu.expr.expressions import col, lit
    from spark_rapids_tpu.memory.catalog import BufferCatalog
    from spark_rapids_tpu.serve import QueryScheduler, SharedPlanCache
    from spark_rapids_tpu.sql import TpuSession

    settings = {
        "spark.rapids.tpu.serve.enabled": True,
        "spark.rapids.tpu.tools.racecheck.witness.enabled": True,
    }
    locks.uninstall_witness()
    QueryScheduler.reset(RapidsConf(settings))
    SharedPlanCache.reset()
    BufferCatalog.reset(RapidsConf(settings))

    def q(sess, mult, n=2048):
        return (sess.range(0, n)
                .where(E.GreaterThanOrEqual(col("id"), lit(100)))
                .select(col("id"),
                        E.Alias(E.Multiply(col("id"), lit(mult)), "v"))
                .agg(A.agg(A.Sum(col("v")), "s"),
                     A.agg(A.Count(None), "c")))

    errors, lock = [], threading.Lock()

    def worker(ti):
        try:
            sess = TpuSession(settings)
            for qi in range(4):
                q(sess, 2 + (ti * 4 + qi) % 5).collect()
        except Exception as e:  # pragma: no cover - the failure mode
            with lock:
                errors.append((ti, repr(e)))

    try:
        ths = [threading.Thread(target=worker, args=(ti,),
                                name=f"witness-stress-{ti}")
               for ti in range(4)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(120)
        assert not errors, errors
        assert locks.witness_active(), (
            "the conf entry did not arm the witness")
        rep = locks.witness_report()
        assert rep["inversions"] == [], rep
        observed = locks.observed_edges()
        assert observed, "stress recorded no acquisition pairs"
        for a, b in observed:
            assert locks.rank_of(a) < locks.rank_of(b), (
                f"observed edge {a} -> {b} acquires upward — the static "
                "analyzer and the witness disagree")
        # cross-check against the static graph's hot session edge
        assert ("sql.plan", "serve.plan_cache") in observed
    finally:
        locks.uninstall_witness()
        QueryScheduler.reset()
        SharedPlanCache.reset()
        BufferCatalog.reset()
        EV.uninstall()
        obs.shutdown()
