"""Persistent AOT program cache (spark_rapids_tpu/serve/program_cache.py).

Pins the ISSUE 15 contracts:
  1. compile once, serve everywhere: a stored program deserializes on a
     later (cleared-cache / second-session / second-process) run with
     ZERO compile_miss events and row-exact results;
  2. cache-key correctness: flipping any identity component (format
     version, backend, device kind/count, jax version, conf
     fingerprint) misses; same-everything hits; a key whose repr is not
     process-stable never touches the directory;
  3. negative paths never fail a query: truncated/corrupt entries and
     version-mismatched headers are deleted and fall through to a plain
     compile; a deserialized program rejecting this call's signature
     falls back to the real build;
  4. the ``aotcache`` fault channel (read:<site>/write:<site>) drives
     both negative paths deterministically;
  5. size-capped LRU eviction keeps the directory bounded;
  6. the cost plane survives caching: warm runs re-emit the persisted
     program_cost/hlo_summary payloads flagged from_cache (saved_ms
     naming the avoided bill), feeding the roofline report, the
     '== program cache ==' profiler section, and the obs twins;
  7. zero overhead when off: conf off => no lookup, no store, no jax
     config change, cached_pipeline's fast path untouched;
  8. --diff: warm compile misses / a collapsed warm ratio / grown
     compile_s_warm flag regressions in the bench cold_start lane.
"""
import importlib.util
import json
import os
import struct
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

from spark_rapids_tpu import events as EV
from spark_rapids_tpu import faults as F
from spark_rapids_tpu import obs
from spark_rapids_tpu import xla_cost as XC
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.exec import base as B
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.expressions import col, lit
from spark_rapids_tpu.obs.registry import EVENT_BACKED_METRICS, METRICS, \
    MetricsRegistry
from spark_rapids_tpu.serve import program_cache as PC
from spark_rapids_tpu.sql import TpuSession

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "tpu_profile", os.path.join(REPO, "tools", "tpu_profile.py"))
tpu_profile = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(tpu_profile)


@pytest.fixture(autouse=True)
def clean_planes():
    """Every test starts and ends with events/obs/faults/program-cache
    uninstalled and the harvest hook off; uninstalling the cache also
    restores the suite's own jax compilation-cache settings."""
    EV.uninstall()
    obs.uninstall()
    F.uninstall()
    PC.uninstall()
    prev = XC.FORCE_HARVEST
    XC.FORCE_HARVEST = False
    yield
    XC.FORCE_HARVEST = prev
    EV.uninstall()
    obs.uninstall()
    F.uninstall()
    PC.uninstall()


def _query(sess, hi, mult):
    """The pipeline caches are PROCESS-global: each test uses a unique
    (hi, mult) pair, and BOTH ride in literals (literal values are part
    of the bound-expression cache keys) so its cold run actually
    compiles instead of inheriting another test's warm programs."""
    df = (sess.range(0, hi)
          .where(E.GreaterThanOrEqual(col("id"), lit(hi % 97)))
          .select(col("id"),
                  E.Alias(E.Multiply(col("id"), lit(mult)), "v"))
          .agg(A.agg(A.Sum(col("v")), "s"), A.agg(A.Count(None), "c")))
    return sorted(df.collect())


def _conf(tmp_path, **extra):
    return {"spark.rapids.tpu.aotCache.dir": str(tmp_path / "aot"),
            **extra}


def _entries(tmp_path):
    d = str(tmp_path / "aot")
    if not os.path.isdir(d):
        return []
    return sorted(f for f in os.listdir(d) if f.endswith(".aot"))


# ---------------------------------------------------------------------------
# 1. compile once, serve everywhere
# ---------------------------------------------------------------------------
def test_store_then_warm_hit_across_sessions(tmp_path):
    s1 = TpuSession(_conf(tmp_path))
    r1 = _query(s1, 1751, 3)
    st = PC.stats()
    assert st["puts"] >= 1 and st["hits"] == 0
    assert _entries(tmp_path)
    # a fresh process = empty in-memory pipeline caches; simulate with
    # the sanctioned sweep, then a SECOND session over the same dir
    B.clear_pipeline_caches()
    m0 = B.compile_miss_count()
    s2 = TpuSession(_conf(tmp_path))
    r2 = _query(s2, 1751, 3)
    st = PC.stats()
    assert B.compile_miss_count() == m0, "warm run must not compile"
    assert st["hits"] >= 1 and st["deserialized"] >= 1
    assert st["saved_ms"] > 0
    assert r1 == r2


def test_warm_rows_match_cache_off_oracle(tmp_path):
    s1 = TpuSession(_conf(tmp_path))
    _query(s1, 1753, 5)
    B.clear_pipeline_caches()
    warm = _query(TpuSession(_conf(tmp_path)), 1753, 5)
    assert PC.stats()["deserialized"] >= 1
    PC.uninstall()
    B.clear_pipeline_caches()
    oracle = _query(TpuSession({}), 1753, 5)
    assert warm == oracle


@pytest.mark.slow
def test_cross_process_second_run_compiles_nothing(tmp_path):
    """The ROADMAP 5(a) success metric, literally: a second process over
    a warm cache dir reports zero compile misses and serves every
    program from_cache."""
    script = tmp_path / "child.py"
    script.write_text(f"""
import sys, os, json
sys.path.insert(0, {REPO!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from spark_rapids_tpu.sql import TpuSession
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.expressions import col, lit
from spark_rapids_tpu.exec import base as B
from spark_rapids_tpu import xla_cost
xla_cost.FORCE_HARVEST = True
sess = TpuSession({{"spark.rapids.tpu.aotCache.dir": {str(tmp_path / 'aot')!r}}})
df = (sess.range(0, 1759)
      .where(E.GreaterThanOrEqual(col("id"), lit(7)))
      .select(col("id"), E.Alias(E.Multiply(col("id"), lit(3)), "v"))
      .agg(A.agg(A.Sum(col("v")), "s")))
rows = sorted(df.collect())
recs = xla_cost.records_since(0)
print(json.dumps({{
    "misses": B.compile_miss_count(),
    "rows": rows,
    "from_cache": sum(1 for r in recs if r.get("from_cache")),
    "compile_s": sum((r.get("trace_ms") or 0) + (r.get("compile_ms") or 0)
                     for r in recs) / 1e3,
}}))
""")

    def run():
        p = subprocess.run([sys.executable, str(script)],
                           capture_output=True, text=True, cwd=REPO)
        assert p.returncode == 0, p.stderr[-2000:]
        return json.loads(p.stdout.strip().splitlines()[-1])

    cold = run()
    warm = run()
    assert cold["misses"] > 0 and cold["from_cache"] == 0
    assert warm["misses"] == 0, "second process must compile nothing"
    assert warm["from_cache"] >= 1
    assert warm["rows"] == cold["rows"]
    assert warm["compile_s"] < cold["compile_s"]


# ---------------------------------------------------------------------------
# 2. cache-key correctness
# ---------------------------------------------------------------------------
def test_entry_name_flips_on_every_identity_component(tmp_path):
    conf = RapidsConf(_conf(tmp_path))
    base = PC.ProgramCache(conf)
    key = (("project", "p1"), ("bigint", 2048), 2048)
    name = base.entry_name("fused_chain", key)
    assert name is not None and name.endswith(".aot")
    # same everything -> same name (a second process recomputes it)
    assert PC.ProgramCache(conf).entry_name("fused_chain", key) == name
    # flip one component at a time -> different name
    for attr, val in (("backend", "tpu"), ("device_kind", "v5e"),
                      ("device_count", 1 + (base.device_count or 0)),
                      ("jax_version", "99.0"),
                      ("conf_fp", "deadbeef")):
        other = PC.ProgramCache(conf)
        setattr(other, attr, val)
        assert other.entry_name("fused_chain", key) != name, attr
    # different site / different pipeline key -> different name
    assert base.entry_name("agg_plan", key) != name
    assert base.entry_name("fused_chain", key + (1,)) != name


def test_unstable_key_repr_never_touches_disk(tmp_path):
    conf = RapidsConf(_conf(tmp_path))
    cache = PC.ProgramCache(conf)
    assert cache.entry_name("site", (object(),)) is None
    PC.install(RapidsConf(_conf(tmp_path)))
    store: dict = {}
    fn = B.cached_pipeline(store, (object(), 1), "unit_unstable",
                           lambda: jax.jit(lambda x: x + 1))
    assert fn(jnp.ones((4,), jnp.int32))[0] == 2
    assert _entries(tmp_path) == []


def test_conf_fingerprint_ignores_observability_confs(tmp_path):
    fp = PC.program_conf_fingerprint
    a = RapidsConf(_conf(tmp_path))
    b = RapidsConf(_conf(tmp_path,
                         **{"spark.rapids.tpu.eventLog.dir": "/tmp/x",
                            "spark.rapids.tpu.metrics.http.enabled": True}))
    assert fp(a) == fp(b), "observability confs must not shatter the key"
    c = RapidsConf(_conf(tmp_path,
                         **{"spark.rapids.tpu.sql.agg.strategy": "SORT"}))
    assert fp(a) != fp(c), "engine-shaping confs must key apart"


def test_conf_flip_misses_same_structural_key(tmp_path):
    s1 = TpuSession(_conf(tmp_path))
    _query(s1, 1761, 3)
    assert PC.stats()["puts"] >= 1
    B.clear_pipeline_caches()
    # join.strategy is irrelevant to this agg-only plan (identical
    # structural pipeline keys) but explicitly set -> new fingerprint
    s2 = TpuSession(_conf(
        tmp_path, **{"spark.rapids.tpu.sql.join.strategy": "DIRECT"}))
    _query(s2, 1761, 3)
    st = PC.stats()
    assert st["hits"] == 0 and st["misses"] >= 1


# ---------------------------------------------------------------------------
# 3. negative paths
# ---------------------------------------------------------------------------
def _corrupt_all(tmp_path, data=b"garbage"):
    for f in _entries(tmp_path):
        with open(os.path.join(str(tmp_path / "aot"), f), "wb") as fh:
            fh.write(data)


def test_corrupt_entry_deleted_and_query_succeeds(tmp_path):
    s1 = TpuSession(_conf(tmp_path))
    r1 = _query(s1, 1763, 3)
    _corrupt_all(tmp_path)
    B.clear_pipeline_caches()
    m0 = B.compile_miss_count()
    r2 = _query(TpuSession(_conf(tmp_path)), 1763, 3)
    st = PC.stats()
    assert r2 == r1
    assert st["corrupt"] >= 1
    assert B.compile_miss_count() > m0, "fell through to plain compiles"
    # poisoned entries were deleted, then re-stored by the fallback...
    # no: the fallback path is a plain compile+store-probe MISS path
    # only on the NEXT miss; the poisoned files themselves must be gone
    # or replaced by fresh valid entries (re-put on this run)
    for f in _entries(tmp_path):
        p = os.path.join(str(tmp_path / "aot"), f)
        assert os.path.getsize(p) > len(b"garbage")


def test_truncated_entry_is_poisoned(tmp_path):
    s1 = TpuSession(_conf(tmp_path))
    _query(s1, 1767, 3)
    _corrupt_all(tmp_path, b"\x00\x01")  # shorter than the length header
    B.clear_pipeline_caches()
    r = _query(TpuSession(_conf(tmp_path)), 1767, 3)
    assert r and PC.stats()["corrupt"] >= 1


def test_version_stamp_mismatch_invalidates(tmp_path):
    s1 = TpuSession(_conf(tmp_path))
    _query(s1, 1769, 3)
    d = str(tmp_path / "aot")
    names = _entries(tmp_path)
    assert names
    # rewrite each entry with a bumped format version but intact blob:
    # the explicit header check must reject it even at the same path
    for n in names:
        p = os.path.join(d, n)
        with open(p, "rb") as fh:
            raw = fh.read()
        (hlen,) = struct.unpack(">Q", raw[:8])
        header = json.loads(raw[8:8 + hlen].decode())
        header["version"] = PC.FORMAT_VERSION + 1
        hb = json.dumps(header, separators=(",", ":"),
                        sort_keys=True).encode()
        with open(p, "wb") as fh:
            fh.write(struct.pack(">Q", len(hb)) + hb + raw[8 + hlen:])
    B.clear_pipeline_caches()
    r = _query(TpuSession(_conf(tmp_path)), 1769, 3)
    st = PC.stats()
    assert r and st["corrupt"] >= len(names) and st["deserialized"] == 0


def test_signature_drift_falls_back_to_build(tmp_path):
    """A deserialized executable that rejects this call's arguments
    (the key under-captured the signature) must fall back to the real
    build and poison the entry."""
    from jax import export as _export

    PC.install(RapidsConf(_conf(tmp_path)))
    cache = PC.active()
    fn4 = jax.jit(lambda x: x * 2)
    exported = _export.export(fn4)(jnp.ones((4,), jnp.float32))
    path = os.path.join(cache.dir, "drift.aot")
    with open(path, "wb") as fh:
        fh.write(b"placeholder")  # only existence matters to _poison
    probe = PC._LoadProbe(
        cache, exported, {"cost": {}}, "unit_drift", ("k",), "d1", path,
        lambda: jax.jit(lambda x: x * 2), 0)
    out = probe(jnp.ones((8,), jnp.float32))  # wrong shape for the entry
    assert out.shape == (8,) and float(out[0]) == 2.0
    assert not os.path.exists(path), "drifted entry must be deleted"


# ---------------------------------------------------------------------------
# 4. fault injection (the aotcache channel)
# ---------------------------------------------------------------------------
def test_fault_read_channel_poisons_deterministically(tmp_path):
    s1 = TpuSession(_conf(tmp_path))
    r1 = _query(s1, 1771, 3)
    n_entries = len(_entries(tmp_path))
    assert n_entries >= 1
    B.clear_pipeline_caches()
    sess = TpuSession(_conf(
        tmp_path, **{"spark.rapids.tpu.test.faults.aotcache": "read:*"}))
    r2 = _query(sess, 1771, 3)
    st = PC.stats()
    assert r2 == r1, "an injected read fault must never fail a query"
    assert st["corrupt"] >= 1 and st["deserialized"] == 0
    assert any(ch == "aotcache" for ch, _, _ in F.active().fired())


def test_fault_write_channel_skips_store(tmp_path):
    sess = TpuSession(_conf(
        tmp_path, **{"spark.rapids.tpu.test.faults.aotcache": "write:*"}))
    r = _query(sess, 1773, 3)
    st = PC.stats()
    assert r, "an injected write fault must never fail a query"
    assert st["write_errors"] >= 1 and st["puts"] == 0
    assert _entries(tmp_path) == []


# ---------------------------------------------------------------------------
# 5. eviction
# ---------------------------------------------------------------------------
def test_lru_eviction_bounds_the_directory(tmp_path):
    sess = TpuSession(_conf(
        tmp_path, **{"spark.rapids.tpu.aotCache.maxBytes": 2000}))
    _query(sess, 1777, 3)
    st = PC.stats()
    assert st["evictions"] >= 1
    assert PC.active().resident_bytes() <= 2000


def test_lru_prefers_evicting_least_recently_used(tmp_path):
    PC.install(RapidsConf(_conf(tmp_path)))
    cache = PC.active()
    old = os.path.join(cache.dir, "a" * 40 + ".aot")
    new = os.path.join(cache.dir, "b" * 40 + ".aot")
    for p in (old, new):
        with open(p, "wb") as fh:
            fh.write(b"x" * 600)
    os.utime(old, times=(1, 1))  # least recently used
    cache.max_bytes = 1000
    cache._evict_if_needed()
    assert not os.path.exists(old) and os.path.exists(new)
    assert cache.stats.evictions == 1


# ---------------------------------------------------------------------------
# 6. the cost plane survives caching
# ---------------------------------------------------------------------------
def _run_logged(tmp_path, hi, log_sub):
    log_dir = tmp_path / log_sub
    sess = TpuSession(_conf(
        tmp_path, **{"spark.rapids.tpu.eventLog.dir": str(log_dir)}))
    _query(sess, hi, 3)
    sess.close()
    recs = []
    for f in os.listdir(log_dir):
        if f.endswith(".jsonl"):
            with open(log_dir / f) as fh:
                recs.extend(json.loads(ln) for ln in fh if ln.strip())
    return recs


def test_warm_run_reemits_cost_flagged_from_cache(tmp_path):
    cold = _run_logged(tmp_path, 1779, "log-cold")
    cold_costs = [r for r in cold if r["event"] == "program_cost"]
    assert cold_costs and not any(r.get("from_cache") for r in cold_costs)
    assert any(r["event"] == "program_cache" and r["op"] == "put"
               for r in cold)
    B.clear_pipeline_caches()
    warm = _run_logged(tmp_path, 1779, "log-warm")
    assert not any(r["event"] == "compile_miss" for r in warm)
    warm_costs = [r for r in warm if r["event"] == "program_cost"]
    assert warm_costs and all(r.get("from_cache") for r in warm_costs)
    for r in warm_costs:
        assert r.get("saved_ms", 0) > 0
        # near-zero warm bill: deserialize + cached compile, a fraction
        # of the persisted original
        assert (r["trace_ms"] + r["compile_ms"]) < r["saved_ms"]
    # persisted XLA byte figures re-emitted so the roofline stays fed
    cold_bytes = {r["digest"]: r.get("bytes_accessed")
                  for r in cold_costs}
    for r in warm_costs:
        if cold_bytes.get(r["digest"]) is not None:
            assert r.get("bytes_accessed") == cold_bytes[r["digest"]]
    # hlo payloads ride along when the original harvest parsed one
    if any(r["event"] == "hlo_summary" for r in cold):
        warm_hlo = [r for r in warm if r["event"] == "hlo_summary"]
        assert warm_hlo and all(r.get("from_cache") for r in warm_hlo)
    # schema: every program_cache event carries its required fields
    for r in warm + cold:
        if r["event"] == "program_cache":
            for field in EV.EVENT_TYPES["program_cache"]:
                assert field in r, (field, r)


def test_profile_section_reports_hits_and_avoided_seconds(tmp_path):
    _run_logged(tmp_path, 1783, "log-cold")
    B.clear_pipeline_caches()
    warm = _run_logged(tmp_path, 1783, "log-warm")
    report, violations = tpu_profile.build_report(warm)
    assert violations == 0
    assert "== program cache ==" in report
    sec = report.split("== program cache ==")[1].split("==")[0]
    assert "hit=" in sec and "deserialize=" in sec
    assert "avoided" in sec
    assert "served from the AOT cache" in report  # roofline annotation


def test_obs_twins_count_cache_ops(tmp_path):
    assert EVENT_BACKED_METRICS["program_cache"] == "tpu_program_cache"
    assert "tpu_program_cache" in METRICS
    reg = MetricsRegistry()
    obs.install(reg)
    sess = TpuSession(_conf(tmp_path))
    _query(sess, 1787, 3)
    assert reg.value("tpu_program_cache", op="put") >= 1
    B.clear_pipeline_caches()
    _query(TpuSession(_conf(tmp_path)), 1787, 3)
    assert reg.value("tpu_program_cache", op="hit") >= 1
    assert reg.value("tpu_program_cache", op="deserialize") >= 1
    assert reg.value("tpu_program_cache_saved_seconds") > 0


def test_status_and_top_render_cache_counters(tmp_path):
    from spark_rapids_tpu.obs.progress import ProgressTracker
    from spark_rapids_tpu.obs.server import build_status

    sess = TpuSession(_conf(tmp_path))
    _query(sess, 1789, 3)
    status = build_status(MetricsRegistry(), ProgressTracker(), None)
    assert status["program_cache"]["puts"] >= 1
    json.dumps(status)  # must stay plain-JSON
    _spec2 = importlib.util.spec_from_file_location(
        "tpu_top", os.path.join(REPO, "tools", "tpu_top.py"))
    tpu_top = importlib.util.module_from_spec(_spec2)
    _spec2.loader.exec_module(tpu_top)
    frame = tpu_top.render_status(status)
    assert "AOT cache:" in frame


# ---------------------------------------------------------------------------
# 7. zero overhead when off
# ---------------------------------------------------------------------------
def test_off_no_lookup_no_store_no_config_change(monkeypatch, tmp_path):
    def boom(*a, **k):
        raise AssertionError("program cache consulted while off")

    monkeypatch.setattr(PC.ProgramCache, "lookup", boom)
    monkeypatch.setattr(PC.ProgramCache, "wrap_store", boom)
    before = jax.config.jax_compilation_cache_dir
    assert not PC.enabled()
    sess = TpuSession({})  # cache conf off
    assert _query(sess, 1793, 3)
    assert jax.config.jax_compilation_cache_dir == before
    assert not os.path.exists(str(tmp_path / "aot"))
    assert PC.install(RapidsConf({})) is None


def test_uninstall_restores_jax_cache_config(tmp_path):
    before = jax.config.jax_compilation_cache_dir
    PC.install(RapidsConf(_conf(tmp_path)))
    assert jax.config.jax_compilation_cache_dir == os.path.join(
        str(tmp_path / "aot"), "xla")
    PC.uninstall()
    assert jax.config.jax_compilation_cache_dir == before


# ---------------------------------------------------------------------------
# 8. the mesh tuple path + single-flight + diff gates
# ---------------------------------------------------------------------------
def test_tuple_path_roundtrips_aux(tmp_path):
    PC.install(RapidsConf(_conf(tmp_path)))
    store: dict = {}
    key = ("unit_tuple", 4)

    def build():
        return jax.jit(lambda x: x + 1), ("layout", 4)

    fn, aux = B.cached_pipeline(store, key, "unit_tuple_site", build)
    assert aux == ("layout", 4)
    assert float(fn(jnp.ones((4,), jnp.float32))[0]) == 2.0
    assert _entries(tmp_path)
    store.clear()
    fn2, aux2 = B.cached_pipeline(
        store, key, "unit_tuple_site",
        lambda: (_ for _ in ()).throw(AssertionError("must not rebuild")))
    assert aux2 == ("layout", 4)
    assert float(fn2(jnp.ones((4,), jnp.float32))[0]) == 2.0
    assert PC.stats()["hits"] >= 1


def test_corrupt_aux_pickle_poisons_instead_of_raising(tmp_path):
    """A tuple-path entry whose aux payload is corrupt must be treated
    exactly like any other corruption: poisoned + plain compile, never
    an exception out of lookup()."""
    PC.install(RapidsConf(_conf(tmp_path)))
    store: dict = {}
    key = ("unit_badaux", 1)
    fn, aux = B.cached_pipeline(
        store, key, "unit_badaux_site",
        lambda: (jax.jit(lambda x: x + 5), ("aux",)))
    assert float(fn(jnp.ones((4,), jnp.float32))[0]) == 6.0
    names = _entries(tmp_path)
    assert names
    d = str(tmp_path / "aot")
    for n in names:
        p = os.path.join(d, n)
        with open(p, "rb") as fh:
            raw = fh.read()
        (hlen,) = struct.unpack(">Q", raw[:8])
        header = json.loads(raw[8:8 + hlen].decode())
        header["aux"] = "!!!not-base64-pickle!!!"
        hb = json.dumps(header, separators=(",", ":"),
                        sort_keys=True).encode()
        with open(p, "wb") as fh:
            fh.write(struct.pack(">Q", len(hb)) + hb + raw[8 + hlen:])
    store.clear()
    fn2, aux2 = B.cached_pipeline(
        store, key, "unit_badaux_site",
        lambda: (jax.jit(lambda x: x + 5), ("aux",)))
    assert float(fn2(jnp.ones((4,), jnp.float32))[0]) == 6.0
    assert aux2 == ("aux",)
    assert PC.stats()["corrupt"] >= 1


def test_unexportable_program_keeps_cost_plane(monkeypatch, tmp_path):
    """A program jax.export rejects must fall back to a PLAIN compile
    that still harvests its program_cost (one per miss) — losing the
    cache must not also lose the roofline."""
    from jax import export as jax_export

    PC.install(RapidsConf(_conf(tmp_path)))
    XC.FORCE_HARVEST = True

    def boom(fn, **kw):
        raise ValueError("synthetically unexportable")

    monkeypatch.setattr(jax_export, "export", boom)
    seq0 = XC.snapshot()
    store: dict = {}
    fn = B.cached_pipeline(store, ("unit_unexp", 1), "unit_unexp_site",
                           lambda: jax.jit(lambda x: x * 3))
    assert float(fn(jnp.ones((4,), jnp.float32))[0]) == 3.0
    recs = XC.records_since(seq0)
    assert any(r["site"] == "unit_unexp_site"
               and not r.get("from_cache") for r in recs)
    assert "unit_unexp_site" in PC.active()._unexportable
    assert _entries(tmp_path) == []
    # later misses at the marked site skip the export attempt entirely
    fn2 = B.cached_pipeline(store, ("unit_unexp", 2), "unit_unexp_site",
                            lambda: jax.jit(lambda x: x * 4))
    assert float(fn2(jnp.ones((4,), jnp.float32))[0]) == 4.0


@pytest.mark.slow
@pytest.mark.cpu_only
def test_mesh_shard_map_program_roundtrips(tmp_path):
    """The mesh ``_cached_program`` tuple path participates for real: a
    shard_map SPMD aggregate stores (aux layouts pickled into the
    header), deserializes on a cleared-cache rerun with zero compile
    misses, and stays row-exact. Sharded arguments carry the device
    context jax.export needs."""
    from spark_rapids_tpu import types as T

    conf = _conf(tmp_path, **{
        "spark.rapids.tpu.shuffle.mode": "ici",
        "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": -1})
    schema = T.StructType([T.StructField("k", T.INT),
                           T.StructField("v", T.LONG)])
    data = {"k": [i % 9 for i in range(700)],
            "v": [i * 5 - 701 for i in range(700)]}

    def run():
        s = TpuSession(conf)
        df = s.create_dataframe(data, schema, num_partitions=4)
        return sorted(df.group_by("k")
                      .agg(A.agg(A.Sum(col("v")), "sv"),
                           A.agg(A.Count(None), "n")).collect()), s

    r1, s1 = run()
    assert "Mesh" in s1.last_executed_plan.tree_string()
    assert PC.stats()["puts"] >= 1, "mesh program must store"
    B.clear_pipeline_caches()
    m0 = B.compile_miss_count()
    r2, _ = run()
    assert B.compile_miss_count() == m0
    assert PC.stats()["deserialized"] >= 1
    assert r1 == r2


def test_store_single_flight_lockfile(tmp_path):
    PC.install(RapidsConf(_conf(tmp_path)))
    cache = PC.active()
    path = os.path.join(cache.dir, "c" * 40 + ".aot")
    header = cache.header_identity("unit_sf")
    header["blob_len"] = 3
    # fresh lock held by "another process": the store is skipped
    with open(path + ".lock", "w"):
        pass
    cache.store("unit_sf", "d1", path, dict(header), b"abc")
    assert not os.path.exists(path)
    # stale lock (a crashed writer): reclaimed, store proceeds
    os.utime(path + ".lock", times=(1, 1))
    cache.store("unit_sf", "d1", path, dict(header), b"abc")
    assert os.path.exists(path)
    assert not os.path.exists(path + ".lock")


def _cold_row(**over):
    row = {"compile_s_cold": 4.0, "compile_s_warm": 0.3,
           "warm_ratio": 0.075, "compile_miss_cold": 3,
           "compile_miss_warm": 0, "from_cache_warm": 3}
    row.update(over)
    return row


def test_diff_gates_cold_start_lane():
    old = {"cold_start": {"agg": _cold_row()}}
    # clean new run: no regressions
    _, n = tpu_profile.diff_bench(
        old, {"cold_start": {"agg": _cold_row()}}, 0.25)
    assert n == 0
    # warm compile misses = the cache stopped hitting
    _, n = tpu_profile.diff_bench(
        old, {"cold_start": {"agg": _cold_row(compile_miss_warm=2)}}, 0.25)
    assert n >= 1
    # collapsed warm ratio
    _, n = tpu_profile.diff_bench(
        old, {"cold_start": {"agg": _cold_row(
            compile_s_warm=3.6, warm_ratio=0.9)}}, 0.25)
    assert n >= 1
    # grown warm compile seconds vs the old round
    _, n = tpu_profile.diff_bench(
        old, {"cold_start": {"agg": _cold_row(
            compile_s_warm=1.2, warm_ratio=0.3)}}, 0.25)
    assert n >= 1
    # a steady residual miss (timing-dependent keys, e.g. the parquet
    # packed upload) is NOT a regression: same count as the old round
    _, n = tpu_profile.diff_bench(
        {"cold_start": {"pq": _cold_row(compile_miss_warm=1)}},
        {"cold_start": {"pq": _cold_row(compile_miss_warm=1)}}, 0.25)
    assert n == 0
    # no baseline: misses flag only when the cache served NOTHING
    _, n = tpu_profile.diff_bench(
        {}, {"cold_start": {"agg": _cold_row(
            compile_miss_warm=1, from_cache_warm=2)}}, 0.25)
    assert n == 0
    _, n = tpu_profile.diff_bench(
        {}, {"cold_start": {"agg": _cold_row(
            compile_miss_warm=3, from_cache_warm=0,
            compile_s_warm=3.9, warm_ratio=0.975)}}, 0.25)
    assert n >= 1
