"""Dict-encoded string columns (late materialization): every string op
through BOTH the dict path and forced materialization, diffed against the
CPU oracle — plus the exec seams (group-by on codes, exchange, concat)
and the full session round trip.

The toggle is ``columnar.column.DICT_MATERIALIZE_EAGERLY`` (monkeypatched
per test): when set, dict columns expand to the plain Arrow layout before
entering any traced program, so the same query exercises the non-dict
lowering — results must be identical bit for bit.
"""
import random

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import ColumnarBatch
from spark_rapids_tpu.columnar import column as colmod
from spark_rapids_tpu.columnar.batch import schema_of
from spark_rapids_tpu.columnar.column import (
    column_from_pylist,
    dict_column_from_pylist,
)
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.cpu import eval_expression_rows
from spark_rapids_tpu.exec import (
    InMemoryScanExec,
    TpuFilterExec,
    TpuHashAggregateExec,
    TpuProjectExec,
)
from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import bind_references, evaluate_projection
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.expressions import col, lit
from spark_rapids_tpu.shuffle.partition import HashPartitioning

from data_gen import approx_equal

CONF = RapidsConf()
N = 96

# low-cardinality pool — the shape dictionary encoding exists for; mixes
# empties, case, pattern metacharacters, multibyte UTF-8, pads, numerics
POOL = [
    "alpha-001", "beta-smallX", "", "Gamma%_x", "delta verylong-value-42",
    "üñé-mixed", "a.b.c", "  pad  ", "X", "tail-9", "42", "-7",
]

SCHEMA = schema_of(s=T.STRING, t=T.STRING)


def make_rows(seed=0, n=N, null_prob=0.15):
    rng = random.Random(seed)
    gen = lambda: (None if rng.random() < null_prob else rng.choice(POOL))
    return [gen() for _ in range(n)], [gen() for _ in range(n)]


def make_dict_batch(seed=0, n=N, null_prob=0.15):
    """Batch with 's' DICT-encoded and 't' plain — the mixed layout every
    multi-input op must cope with."""
    s, t = make_rows(seed, n, null_prob)
    cols = [dict_column_from_pylist(s, T.STRING),
            column_from_pylist(t, T.STRING)]
    return ColumnarBatch(cols, SCHEMA, n), s, t


@pytest.fixture(params=["dict", "materialized"])
def dict_mode(request, monkeypatch):
    """Run the test body twice: once on the dict lowering, once with the
    forced-materialization toggle flipped (the fallback path)."""
    monkeypatch.setattr(colmod, "DICT_MATERIALIZE_EAGERLY",
                        request.param == "materialized")
    return request.param


def check_dict(expr, seed=0, null_prob=0.15):
    batch, s, t = make_dict_batch(seed, null_prob=null_prob)
    bound = bind_references(expr, SCHEMA)
    [tpu_col] = evaluate_projection([bound], batch)
    tpu_vals = tpu_col.to_pylist()
    rows = list(zip(s, t))
    cpu_vals = eval_expression_rows(bound, rows)
    assert len(tpu_vals) == len(cpu_vals)
    for i, (tv, cv) in enumerate(zip(tpu_vals, cpu_vals)):
        assert approx_equal(tv, cv), (
            f"row {i}: tpu={tv!r} cpu={cv!r} expr={expr} inputs={rows[i]!r}"
        )


# ---------------------------------------------------------------------------
# every string op, dict path vs forced materialization vs CPU oracle
# ---------------------------------------------------------------------------
STRING_OPS = [
    ("upper", lambda: E.Upper(col("s"))),
    ("lower", lambda: E.Lower(col("s"))),
    ("initcap", lambda: E.InitCap(col("s"))),
    ("length", lambda: E.Length(col("s"))),
    ("substring", lambda: E.Substring(col("s"), lit(2), lit(3))),
    ("substring_neg", lambda: E.Substring(col("s"), lit(-4), lit(3))),
    ("trim", lambda: E.StringTrim(col("s"))),
    ("ltrim", lambda: E.StringTrimLeft(col("s"))),
    ("rtrim", lambda: E.StringTrimRight(col("s"))),
    ("startswith", lambda: E.StartsWith(col("s"), lit("a"))),
    ("endswith", lambda: E.EndsWith(col("s"), lit("1"))),
    ("contains", lambda: E.Contains(col("s"), lit("X"))),
    ("like", lambda: E.Like(col("s"), lit("%a%1%"))),
    ("like_underscore", lambda: E.Like(col("s"), lit("_ail-_"))),
    ("like_exact", lambda: E.Like(col("s"), lit("X"))),
    ("rlike", lambda: E.RLike(col("s"), lit("a.b"))),
    ("regexp_replace", lambda: E.RegExpReplace(col("s"), lit("a"), lit("_Q_"))),
    ("replace", lambda: E.StringReplace(col("s"), lit("a"), lit("zzz"))),
    ("replace_empty", lambda: E.StringReplace(col("s"), lit(""), lit("zz"))),
    ("locate", lambda: E.StringLocate(lit("a"), col("s"), lit(1))),
    ("locate_null_start",
     lambda: E.StringLocate(lit("a"), col("s"), lit(None))),
    ("lpad", lambda: E.StringLPad(col("s"), lit(8), lit("*"))),
    ("rpad", lambda: E.StringRPad(col("s"), lit(8), lit("*"))),
    ("substring_index", lambda: E.SubstringIndex(col("s"), lit("-"), lit(1))),
    ("split_part", lambda: E.StringSplitPart(col("s"), lit("-"), lit(2))),
    ("eq_lit", lambda: E.EqualTo(col("s"), lit("alpha-001"))),
    ("eq_null_safe_lit", lambda: E.EqualNullSafe(col("s"), lit("X"))),
    ("eq_null_safe_null",
     lambda: E.EqualNullSafe(col("s"), E.Literal(None, T.STRING))),
    ("lt_lit", lambda: E.LessThan(col("s"), lit("delta"))),
    ("ge_lit_flipped", lambda: E.GreaterThanOrEqual(lit("delta"), col("s"))),
    ("cmp_dict_vs_plain", lambda: E.LessThanOrEqual(col("s"), col("t"))),
    ("in_list", lambda: E.In(col("s"), ("X", "üñé-mixed", "", "nope"))),
    ("in_list_null", lambda: E.In(col("s"), ("42", None))),
    ("cast_int", lambda: E.Cast(col("s"), T.INT)),
    ("cast_string_identity", lambda: E.Cast(col("s"), T.STRING)),
    ("concat_mixed", lambda: E.Concat((col("s"), lit("-"), col("t")))),
    ("concat_dict_dict", lambda: E.Concat((col("s"), col("s")))),
    ("if_mixed",
     lambda: E.If(E.Contains(col("s"), lit("a")), col("s"), col("t"))),
    ("coalesce", lambda: E.Coalesce((col("s"), col("t")))),
]


@pytest.mark.parametrize(
    "make", [m for _, m in STRING_OPS], ids=[k for k, _ in STRING_OPS])
def test_string_op_dict_vs_oracle(make, dict_mode):
    check_dict(make(), seed=7)


def test_all_null_dict_column(dict_mode):
    check_dict(E.Upper(col("s")), seed=11, null_prob=1.0)
    check_dict(E.EqualTo(col("s"), lit("X")), seed=12, null_prob=1.0)


# ---------------------------------------------------------------------------
# column layer: materialize() / host decode round trips
# ---------------------------------------------------------------------------
def test_dict_column_roundtrip_and_materialize():
    s, _ = make_rows(seed=3)
    dc = dict_column_from_pylist(s, T.STRING)
    assert dc.is_dict and dc.is_string
    assert dc.to_pylist() == s
    mat = dc.materialize()
    assert not mat.is_dict
    assert mat.to_pylist() == s
    # host_columns path on a dict batch (the collect fast path)
    batch = ColumnarBatch([dc], schema_of(s=T.STRING), len(s))
    assert [r[0] for r in batch.to_rows()] == s


def test_dict_device_memory_is_codes_not_chars():
    # 10k rows over a tiny pool: the dict layout must account ~4B/row,
    # not the expanded byte pool
    s = [POOL[i % 4] for i in range(10_000)]
    dc = dict_column_from_pylist(s, T.STRING)
    plain = dc.materialize()
    assert dc.device_memory_size() < plain.device_memory_size() / 2


# ---------------------------------------------------------------------------
# exec seams
# ---------------------------------------------------------------------------
def _groupby_oracle(keys, vals):
    out = {}
    for k, v in zip(keys, vals):
        c, s = out.get(k, (0, 0))
        out[k] = (c + 1, s + (v or 0))
    return sorted((k, c, s) for k, (c, s) in out.items())


def test_groupby_on_dict_key(dict_mode):
    n = 128
    rng = random.Random(21)
    keys = [rng.choice(POOL[:6]) for _ in range(n)]
    vals = list(range(n))
    kcol = dict_column_from_pylist(keys, T.STRING)
    vcol = column_from_pylist(vals, T.LONG)
    schema = schema_of(k=T.STRING, v=T.LONG)
    batch = ColumnarBatch([kcol, vcol], schema, n)
    agg = TpuHashAggregateExec(
        CONF, [col("k")],
        [A.agg(A.Count(col("v")), "c"), A.agg(A.Sum(col("v")), "sv")],
        InMemoryScanExec(CONF, [[batch]], schema))
    rows = sorted((k, c, s) for k, c, s in agg.collect())
    assert rows == _groupby_oracle(keys, vals)


def test_groupby_on_transformed_dict_key(dict_mode):
    # upper() clears the unique bit (entries can merge): grouping must
    # fall back to byte order and still agree with the oracle
    n = 96
    rng = random.Random(22)
    keys = [rng.choice(["ab", "AB", "aB", "c", ""]) for _ in range(n)]
    vals = [rng.randrange(100) for _ in range(n)]
    schema = schema_of(k=T.STRING, v=T.LONG)
    batch = ColumnarBatch(
        [dict_column_from_pylist(keys, T.STRING),
         column_from_pylist(vals, T.LONG)], schema, n)
    proj = TpuProjectExec(
        CONF, [E.Alias(E.Upper(col("k")), "k"), col("v")],
        InMemoryScanExec(CONF, [[batch]], schema))
    agg = TpuHashAggregateExec(
        CONF, [col("k")],
        [A.agg(A.Count(col("v")), "c"), A.agg(A.Sum(col("v")), "sv")], proj)
    rows = sorted(agg.collect())
    assert rows == _groupby_oracle([k.upper() for k in keys], vals)


def test_filter_project_keeps_dict_then_collects(dict_mode):
    batch, s, t = make_dict_batch(seed=31)
    filt = TpuFilterExec(
        CONF, E.Contains(col("s"), lit("a")),
        InMemoryScanExec(CONF, [[batch]], SCHEMA))
    proj = TpuProjectExec(
        CONF,
        [E.Alias(E.Substring(E.Upper(col("s")), lit(1), lit(6)), "u"),
         E.Alias(E.Length(col("s")), "ln")], filt)
    expect = [(sv.upper()[:6], len(sv)) for sv in s
              if sv is not None and "a" in sv]
    assert proj.collect() == expect


def test_dict_key_through_exchange(dict_mode):
    n = 120
    rng = random.Random(41)
    keys = [rng.choice(POOL[:5]) for _ in range(n)]
    vals = [rng.randrange(1000) for _ in range(n)]
    schema = schema_of(k=T.STRING, v=T.LONG)
    batch = ColumnarBatch(
        [dict_column_from_pylist(keys, T.STRING),
         column_from_pylist(vals, T.LONG)], schema, n)
    P = 4
    ex = TpuShuffleExchangeExec(
        CONF, InMemoryScanExec(CONF, [[batch]], schema),
        HashPartitioning([0], P))
    got = []
    seen_parts = 0
    for p in range(P):
        part_rows = [r for b in ex.execute_partition(p)
                     for r in b.to_rows()]
        # same key lands in ONE partition (grouping correctness)
        seen_parts += bool(part_rows)
        got.extend(part_rows)
    assert sorted(got) == sorted(zip(keys, vals))
    assert seen_parts >= 2  # the hash actually spread the 5 keys


def test_mixed_dict_plain_concat_exec(dict_mode):
    # two batches of the SAME column, one dict-encoded and one plain,
    # through a coalescing exec boundary (different dictionaries per
    # batch is the general case — plain is the extreme of it)
    s1, _ = make_rows(seed=51, n=40)
    s2, _ = make_rows(seed=52, n=24)
    schema = schema_of(s=T.STRING)
    b1 = ColumnarBatch([dict_column_from_pylist(s1, T.STRING)], schema, 40)
    b2 = ColumnarBatch([column_from_pylist(s2, T.STRING)], schema, 24)
    from spark_rapids_tpu.exec import TpuCoalesceBatchesExec

    co = TpuCoalesceBatchesExec(
        CONF, InMemoryScanExec(CONF, [[b1, b2]], schema), target_rows=1000)
    assert [r[0] for r in co.collect()] == s1 + s2


# ---------------------------------------------------------------------------
# session round trip: scan -> filter -> project -> groupby -> collect
# ---------------------------------------------------------------------------
def _session_query(tmp_path, dict_strings: bool):
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.io.scan_cache import DeviceScanCache
    from spark_rapids_tpu.sql import TpuSession

    DeviceScanCache.reset()
    rng = random.Random(61)
    n = 500
    cats = [rng.choice(POOL[:8]) for _ in range(n)]
    qty = [rng.randrange(1, 50) for _ in range(n)]
    path = str(tmp_path / "t.parquet")
    pq.write_table(
        pa.table({"cat": pa.array(cats), "qty": pa.array(qty, pa.int64())}),
        path, use_dictionary=True)
    sess = TpuSession({
        "spark.rapids.tpu.sql.format.parquet.dictStrings.enabled":
            dict_strings,
    })
    df = (
        sess.read.parquet(str(tmp_path))
        .where(E.Contains(col("cat"), lit("a")))
        .group_by("cat")
        .agg(A.agg(A.Sum(col("qty")), "s"), A.agg(A.Count(col("qty")), "c"))
    )
    rows = sorted(df.collect())
    oracle = {}
    for c, q in zip(cats, qty):
        if "a" in c:
            s_, n_ = oracle.get(c, (0, 0))
            oracle[c] = (s_ + q, n_ + 1)
    assert rows == sorted((k, s_, n_) for k, (s_, n_) in oracle.items())
    return rows


def test_session_roundtrip_dict_vs_plain(tmp_path):
    on = _session_query(tmp_path, True)
    off = _session_query(tmp_path, False)
    assert on == off
