"""Per-fusion HLO attribution plane (spark_rapids_tpu/hlo.py) + the
environment-provenance helper (envinfo.py) riding the same PR.

Pins the contracts ISSUE 11 introduced:
  1. golden HLO-text fixtures — a CPU-dialect module (scatter +
     transpose fusion), a TPU-dialect module (tiled layouts, one-hot
     expansion feeding a dot), and a malformed/unknown-op module — pin
     the parser's byte totals, idiom classifications, and the
     coverage-fraction degradation (never an exception);
  2. exactness anchor: a plain jitted dot's attribution equals the
     compiler's own ``cost_analysis()['bytes accessed']``;
  3. live harvest: a cold query emits exactly one ``hlo_summary`` per
     ``program_cost`` twin (same site+digest), with accounted_frac /
     coverage reported whenever the attribution explains less than the
     compiler's figure — the shortfall is named, never silent;
  4. zero overhead: with events AND obs off (FORCE_HARVEST unset) the
     HLO text is never fetched or parsed (spy on harvest_hlo — the only
     as_text caller — matching the xla_cost contract);
  5. obs twins: scatter-program counter + top-fusion-bytes gauge;
  6. tpu_profile: the '== hlo ==' section names the amplification
     culprit per site with its share of the site's XLA bytes, and
     --diff gates per-site fusion-byte growth / scatter appearance in
     both event-log and bench-JSON form (scatter gated only when the
     agg strategy did not change);
  7. env provenance: envinfo.environment_info shape, the
     environments_differ rule, its duplicated-by-design twin in the
     offline tool, and the loud ENVIRONMENTS DIFFER banner in --diff.
"""
import importlib.util
import json
import os

import pytest

from spark_rapids_tpu import envinfo
from spark_rapids_tpu import events as EV
from spark_rapids_tpu import hlo
from spark_rapids_tpu import obs
from spark_rapids_tpu import xla_cost as XC
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.expressions import col, lit
from spark_rapids_tpu.obs.registry import MetricsRegistry
from spark_rapids_tpu.sql import TpuSession

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "tpu_profile", os.path.join(REPO, "tools", "tpu_profile.py"))
tpu_profile = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(tpu_profile)


@pytest.fixture(autouse=True)
def clean_planes():
    EV.uninstall()
    obs.uninstall()
    prev = XC.FORCE_HARVEST
    XC.FORCE_HARVEST = False
    yield
    XC.FORCE_HARVEST = prev
    EV.uninstall()
    obs.uninstall()


# ---------------------------------------------------------------------------
# 1. golden fixtures
# ---------------------------------------------------------------------------
# CPU dialect: plain layouts, a kLoop transpose fusion, a real scatter
# with an add combiner. Hand-computed attribution (output + operand
# shape bytes; parameters/tuple cost zero):
#   fusion:  32768 out + 32768 operand           =  65536  transpose/copy
#   scatter: 32768 out + 32768 + 128 + 8192      =  73856  scatter-add
#   total                                        = 139392
CPU_HLO = """\
HloModule jit_step, entry_computation_layout={(f32[128,64]{1,0})->f32[64,128]{1,0}}

%add_comp (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %r = f32[] add(f32[] %a, f32[] %b)
}

%fused_computation (p0: f32[128,64]) -> f32[64,128] {
  %p0 = f32[128,64]{1,0} parameter(0)
  ROOT %t = f32[64,128]{1,0} transpose(f32[128,64]{1,0} %p0), dimensions={1,0}
}

ENTRY %main (x: f32[128,64], idx: s32[32,1], upd: f32[32,64]) -> (f32[64,128], f32[128,64]) {
  %x = f32[128,64]{1,0} parameter(0)
  %idx = s32[32,1]{1,0} parameter(1)
  %upd = f32[32,64]{1,0} parameter(2)
  %fusion = f32[64,128]{1,0} fusion(f32[128,64]{1,0} %x), kind=kLoop, calls=%fused_computation
  %scatter = f32[128,64]{1,0} scatter(f32[128,64]{1,0} %x, s32[32,1]{1,0} %idx, f32[32,64]{1,0} %upd), update_window_dims={1}, inserted_window_dims={0}, scatter_dims_to_operand_dims={0}, index_vector_dim=1, to_apply=%add_comp
  ROOT %out = (f32[64,128]{1,0}, f32[128,64]{1,0}) tuple(f32[64,128]{1,0} %fusion, f32[128,64]{1,0} %scatter)
}
"""

# TPU dialect: tiled layout suffixes {1,0:T(8,128)}, a one-hot
# expansion fusion (iota+broadcast+compare) feeding a dot — the
# bucket_reduce matmul signature. Attribution:
#   onehot fusion: 65536 out + 4096 operand           =  69632  one-hot expand
#   dot:           256 out + 65536 + 16384 operands   =  82176  one-hot dot
#   total                                             = 151808
TPU_HLO = """\
HloModule jit_agg, is_scheduled=true

%region_0.11 (Arg_0.12: f32[], Arg_1.13: f32[]) -> f32[] {
  %Arg_0.12 = f32[] parameter(0)
  %Arg_1.13 = f32[] parameter(1)
  ROOT %add.14 = f32[] add(f32[] %Arg_0.12, f32[] %Arg_1.13)
}

%fused_onehot (param_0.1: s32[1024]) -> f32[1024,16] {
  %param_0.1 = s32[1024]{0:T(1024)} parameter(0)
  %iota.3 = s32[1024,16]{1,0:T(8,128)} iota(), iota_dimension=1
  %broadcast.4 = s32[1024,16]{1,0:T(8,128)} broadcast(s32[1024]{0:T(1024)} %param_0.1), dimensions={0}
  %compare.5 = pred[1024,16]{1,0:T(8,128)(4,1)} compare(s32[1024,16]{1,0:T(8,128)} %broadcast.4, s32[1024,16]{1,0:T(8,128)} %iota.3), direction=EQ
  ROOT %convert.6 = f32[1024,16]{1,0:T(8,128)} convert(pred[1024,16]{1,0:T(8,128)(4,1)} %compare.5)
}

ENTRY %main.42 (p0: s32[1024], p1: f32[1024,4]) -> f32[16,4] {
  %p0 = s32[1024]{0:T(1024)} parameter(0)
  %p1 = f32[1024,4]{1,0:T(8,128)} parameter(1)
  %onehot = f32[1024,16]{1,0:T(8,128)} fusion(s32[1024]{0:T(1024)} %p0), kind=kLoop, calls=%fused_onehot
  ROOT %dot.9 = f32[16,4]{1,0:T(8,128)} dot(f32[1024,16]{1,0:T(8,128)} %onehot, f32[1024,4]{1,0:T(8,128)} %p1), lhs_contracting_dims={0}, rhs_contracting_dims={0}
}
"""

# malformed: an unknown dtype (q77), a line that is not an instruction,
# and a healthy ROOT — 2 of 4 entry lines fully parse -> coverage 0.5,
# and only the healthy add contributes bytes (32 out + 2x32 operands)
BAD_HLO = """\
HloModule weird

ENTRY %e (p: f32[8]) -> f32[8] {
  %p = f32[8]{0} parameter(0)
  %mys = q77[8] mystery-op(f32[8]{0} %p)
  this line is not an instruction at all
  ROOT %r = f32[8]{0} add(f32[8]{0} %p, f32[8]{0} %p)
}
"""


def test_cpu_dialect_golden_bytes_and_classes():
    s = hlo.summarize_hlo(CPU_HLO)
    assert s["coverage"] == 1.0
    assert s["instructions"] == 6
    assert s["total_bytes"] == 139392
    assert s["scatter_count"] == 1
    by_name = {r["name"]: r for r in s["top_fusions"]}
    assert by_name["scatter"]["class"] == "scatter-add"
    assert by_name["scatter"]["bytes"] == 73856
    assert by_name["fusion"]["class"] == "transpose/copy"
    assert by_name["fusion"]["bytes"] == 65536
    # ranked by attributed bytes: the scatter owns the module
    assert s["top_fusions"][0]["name"] == "scatter"
    assert s["largest_output"]["bytes"] == 32768


def test_tpu_dialect_tiled_layouts_and_one_hot():
    s = hlo.summarize_hlo(TPU_HLO)
    assert s["coverage"] == 1.0
    assert s["total_bytes"] == 151808
    assert s["scatter_count"] == 0
    by_name = {r["name"]: r for r in s["top_fusions"]}
    # the dot sees THROUGH its fusion operand to the broadcast-compare
    # expansion: classified as the one-hot dot idiom, not a plain dot
    assert by_name["dot.9"]["class"] == "one-hot dot"
    assert by_name["dot.9"]["bytes"] == 82176
    # the expansion itself is named even without an in-fusion dot
    assert by_name["onehot"]["class"] == "one-hot expand"
    assert by_name["onehot"]["bytes"] == 69632


def test_malformed_degrades_coverage_never_raises():
    s = hlo.summarize_hlo(BAD_HLO)
    assert s["coverage"] == 0.5
    assert s["total_bytes"] == 96
    assert s["scatter_count"] == 0
    # pure garbage and empty text both yield the zero summary
    for text in ("", "not hlo at all\n{}{}", "HloModule x\n"):
        z = hlo.summarize_hlo(text)
        assert z["coverage"] == 0.0 and z["total_bytes"] == 0


def test_dot_consuming_scatter_output_is_not_a_scatter():
    """The one-hot look-through must not leak producer opcodes into the
    idiom decision: a dot that merely CONSUMES a scatter's output stays
    a plain dot, and the module counts ONE scatter, not two (else any
    refactor fusing/unfusing a scatter's consumer flips scatter_count
    and fires the --diff appearance gate on a no-op change)."""
    text = """\
HloModule consume
%add_c (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %r = f32[] add(f32[] %a, f32[] %b)
}
ENTRY %e (x: f32[16,8], idx: s32[4,1], upd: f32[4,8], w: f32[8,4]) -> f32[16,4] {
  %x = f32[16,8]{1,0} parameter(0)
  %idx = s32[4,1]{1,0} parameter(1)
  %upd = f32[4,8]{1,0} parameter(2)
  %w = f32[8,4]{1,0} parameter(3)
  %sc = f32[16,8]{1,0} scatter(f32[16,8]{1,0} %x, s32[4,1]{1,0} %idx, f32[4,8]{1,0} %upd), update_window_dims={1}, inserted_window_dims={0}, scatter_dims_to_operand_dims={0}, index_vector_dim=1, to_apply=%add_c
  ROOT %d = f32[16,4]{1,0} dot(f32[16,8]{1,0} %sc, f32[8,4]{1,0} %w), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""
    s = hlo.summarize_hlo(text)
    assert s["coverage"] == 1.0
    assert s["scatter_count"] == 1, s["top_fusions"]
    by_name = {r["name"]: r for r in s["top_fusions"]}
    assert by_name["sc"]["class"] == "scatter-add"
    assert by_name["d"]["class"] == "dot"


def test_radix_bin_loop_not_misclassified_as_scatter():
    """The radix-bin lowering compiles to a while loop whose body writes
    MULTI-ELEMENT tiles through dynamic-update-slice (the sliding output
    window of ops/radix_bin.py). The classifier must read it as
    'radix-bin' — calling it scatter would trip the --diff
    scatter-appearance gate on the byte-amplification fix itself — while
    the CPU scatter emulation (one element updated per trip against a
    full-size accumulator) must STILL read as scatter-add."""
    text = """\
HloModule jit_radix

%tile_cond (cp: (s32[], f32[4096,2], f32[64,2])) -> pred[] {
  %cp = (s32[], f32[4096,2]{1,0}, f32[64,2]{1,0}) parameter(0)
  %ci = s32[] get-tuple-element((s32[], f32[4096,2]{1,0}, f32[64,2]{1,0}) %cp), index=0
  %cn = s32[] constant(64)
  ROOT %lt = pred[] compare(s32[] %ci, s32[] %cn), direction=LT
}

%tile_body (p: (s32[], f32[4096,2], f32[64,2])) -> (s32[], f32[4096,2], f32[64,2]) {
  %p = (s32[], f32[4096,2]{1,0}, f32[64,2]{1,0}) parameter(0)
  %i = s32[] get-tuple-element((s32[], f32[4096,2]{1,0}, f32[64,2]{1,0}) %p), index=0
  %buf = f32[4096,2]{1,0} get-tuple-element((s32[], f32[4096,2]{1,0}, f32[64,2]{1,0}) %p), index=1
  %tile = f32[64,2]{1,0} get-tuple-element((s32[], f32[4096,2]{1,0}, f32[64,2]{1,0}) %p), index=2
  %zero = s32[] constant(0)
  %win = f32[4096,2]{1,0} dynamic-update-slice(f32[4096,2]{1,0} %buf, f32[64,2]{1,0} %tile, s32[] %i, s32[] %zero)
  %one = s32[] constant(1)
  %ni = s32[] add(s32[] %i, s32[] %one)
  ROOT %t = (s32[], f32[4096,2]{1,0}, f32[64,2]{1,0}) tuple(s32[] %ni, f32[4096,2]{1,0} %win, f32[64,2]{1,0} %tile)
}

%em_cond (ep: (s32[], f32[4096], f32[1])) -> pred[] {
  %ep = (s32[], f32[4096]{0}, f32[1]{0}) parameter(0)
  %ei = s32[] get-tuple-element((s32[], f32[4096]{0}, f32[1]{0}) %ep), index=0
  %en = s32[] constant(4096)
  ROOT %elt = pred[] compare(s32[] %ei, s32[] %en), direction=LT
}

%em_body (q: (s32[], f32[4096], f32[1])) -> (s32[], f32[4096], f32[1]) {
  %q = (s32[], f32[4096]{0}, f32[1]{0}) parameter(0)
  %j = s32[] get-tuple-element((s32[], f32[4096]{0}, f32[1]{0}) %q), index=0
  %acc = f32[4096]{0} get-tuple-element((s32[], f32[4096]{0}, f32[1]{0}) %q), index=1
  %el = f32[1]{0} get-tuple-element((s32[], f32[4096]{0}, f32[1]{0}) %q), index=2
  %wr = f32[4096]{0} dynamic-update-slice(f32[4096]{0} %acc, f32[1]{0} %el, s32[] %j)
  %one2 = s32[] constant(1)
  %nj = s32[] add(s32[] %j, s32[] %one2)
  ROOT %t2 = (s32[], f32[4096]{0}, f32[1]{0}) tuple(s32[] %nj, f32[4096]{0} %wr, f32[1]{0} %el)
}

ENTRY %main (init: (s32[], f32[4096,2], f32[64,2]), einit: (s32[], f32[4096], f32[1])) -> f32[4096,2] {
  %init = (s32[], f32[4096,2]{1,0}, f32[64,2]{1,0}) parameter(0)
  %einit = (s32[], f32[4096]{0}, f32[1]{0}) parameter(1)
  %radix = (s32[], f32[4096,2]{1,0}, f32[64,2]{1,0}) while((s32[], f32[4096,2]{1,0}, f32[64,2]{1,0}) %init), condition=%tile_cond, body=%tile_body
  %emul = (s32[], f32[4096]{0}, f32[1]{0}) while((s32[], f32[4096]{0}, f32[1]{0}) %einit), condition=%em_cond, body=%em_body
  ROOT %out = f32[4096,2]{1,0} get-tuple-element((s32[], f32[4096,2]{1,0}, f32[64,2]{1,0}) %radix), index=1
}
"""
    s = hlo.summarize_hlo(text)
    assert s["coverage"] == 1.0
    by_name = {r["name"]: r for r in s["top_fusions"]}
    assert by_name["radix"]["class"] == "radix-bin", by_name
    assert by_name["emul"]["class"] == "scatter-add", by_name
    # only the per-element emulation counts against the scatter gate
    assert s["scatter_count"] == 1, s["top_fusions"]


def test_pallas_custom_call_classified_not_scatter():
    """A hand-written Pallas/Mosaic kernel surfaces as a custom-call
    whose target names the Mosaic pipeline; it owns its working set in
    VMEM and must classify as 'pallas', never as the scatter/one-hot it
    replaced (and never inflate scatter_count)."""
    text = """\
HloModule jit_pallas

ENTRY %main (p0: s32[1024], p1: f32[1024,16]) -> s32[256,17] {
  %p0 = s32[1024]{0} parameter(0)
  %p1 = f32[1024,16]{1,0} parameter(1)
  ROOT %cc = s32[256,17]{1,0} custom-call(s32[1024]{0} %p0, f32[1024,16]{1,0} %p1), custom_call_target="tpu_custom_call", api_version=API_VERSION_STATUS_RETURNING
}
"""
    s = hlo.summarize_hlo(text)
    assert s["coverage"] == 1.0
    assert s["scatter_count"] == 0
    by_name = {r["name"]: r for r in s["top_fusions"]}
    assert by_name["cc"]["class"] == "pallas"
    # bytes still attribute normally: output + operand shapes
    assert by_name["cc"]["bytes"] == 256 * 17 * 4 + 1024 * 4 + 1024 * 16 * 4


def test_compiled_radix_program_has_zero_scatter_classified():
    """End to end on the REAL compiled program: lower a RADIX-strategy
    groupby (sums, float sum, min, count, first — every reduction
    family), parse its optimized HLO, and require ZERO scatter-classified
    entry instructions with full parse coverage — the merge gate of the
    byte-amplification fix, pinned against compiler drift."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.expr.eval import ColV
    from spark_rapids_tpu.ops import groupby as G

    cap = 1 << 10
    rng = np.random.default_rng(0)
    keys = ColV(jnp.asarray(rng.integers(0, 50, cap).astype(np.int64)),
                jnp.ones(cap, jnp.bool_))
    vals = ColV(jnp.asarray(rng.integers(-100, 100, cap).astype(np.int64)),
                jnp.ones(cap, jnp.bool_))
    fvals = ColV(jnp.asarray(rng.normal(size=cap)),
                 jnp.ones(cap, jnp.bool_))

    def run(k, v, f, n):
        return G.groupby_agg(
            [k], [T.LONG], [v, f, v, None, v],
            ["sum", "sum", "min", "count_star", "first"],
            n, strategy="RADIX")

    txt = (jax.jit(run)
           .lower(keys, vals, fvals, jnp.int32(cap)).compile().as_text())
    s = hlo.summarize_hlo(txt, top_k=64)
    assert s["coverage"] == 1.0
    assert s["scatter_count"] == 0, [
        r for r in s["top_fusions"]
        if r["class"] in ("scatter", "scatter-add")]
    mod = hlo.parse_hlo_module(txt)
    classes = {hlo.classify(mod, ins) for ins in mod.instrs(mod.entry)}
    assert "radix-bin" in classes, classes
    assert not classes & {"scatter", "scatter-add"}, classes


def test_top_k_truncates_ranked_list():
    s = hlo.summarize_hlo(CPU_HLO, top_k=1)
    assert len(s["top_fusions"]) == 1
    assert s["top_fusions"][0]["name"] == "scatter"
    # truncation changes the reported list, not the totals
    assert s["total_bytes"] == 139392


def test_shape_parser_tuples_dynamic_dims_and_comments():
    # tuple with /*index=N*/ filler, bounded-dynamic dim, token
    b, e, _ = hlo._parse_shape(
        "(f32[2,3]{1,0}, /*index=1*/ s32[<=10]{0}, token[])", 0)
    assert b == 2 * 3 * 4 + 10 * 4  # token costs 0 bytes
    assert e == 6 + 10 + 1
    with pytest.raises(ValueError):
        hlo._parse_shape("f32[2,", 0)


# ---------------------------------------------------------------------------
# 2. exactness anchor vs the compiler's own figure
# ---------------------------------------------------------------------------
def test_plain_dot_matches_cost_analysis_exactly():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda a, b: a @ b)
    a = jnp.zeros((64, 64), jnp.float32)
    compiled = f.lower(a, a).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0] if ca else {}
    xla_bytes = ca.get("bytes accessed")
    s = hlo.summarize_hlo(compiled.as_text())
    assert s["coverage"] == 1.0
    if xla_bytes:  # backend reported one: the anchor must hold
        assert abs(s["total_bytes"] - xla_bytes) <= 0.1 * xla_bytes


# ---------------------------------------------------------------------------
# 3. live harvest: one hlo_summary per program_cost, shortfall named
# ---------------------------------------------------------------------------
def _query(sess, hi=4096, mult=301):
    """Cold compiles need a (hi, mult) pair no other suite has run —
    the pipeline caches are process-global (test_program_cost idiom)."""
    df = (sess.range(0, hi)
          .where(E.GreaterThanOrEqual(col("id"), lit(100)))
          .select(col("id"),
                  E.Alias(E.Multiply(col("id"), lit(mult)), "v"))
          .agg(A.agg(A.Sum(col("v")), "s"), A.agg(A.Count(None), "c")))
    return df.collect()


def test_live_harvest_one_summary_per_program(tmp_path):
    sess = TpuSession({"spark.rapids.tpu.eventLog.dir": str(tmp_path)})
    _query(sess, mult=301)
    with open(sess.events.path) as f:
        recs = [json.loads(line) for line in f]
    costs = [r for r in recs if r["event"] == "program_cost"]
    sums = [r for r in recs if r["event"] == "hlo_summary"]
    assert costs and sums
    # exactly one summary per harvested program, same (site, digest)
    assert ({(r["site"], r["digest"]) for r in costs}
            == {(r["site"], r["digest"]) for r in sums})
    for r in sums:
        for field in EV.EVENT_TYPES["hlo_summary"]:
            assert field in r, f"hlo_summary missing {field}: {r}"
        assert 0.0 <= r["coverage"] <= 1.0
        assert r["total_bytes"] >= 0
        # the acceptance contract: bytes within 10% of the compiler's
        # figure, OR the shortfall is REPORTED via accounted_frac +
        # coverage (XLA utilization-weights bytes inside fused loop
        # bodies; the ratio and coverage explain the divergence)
        af = r.get("accounted_frac")
        if af is not None and not (0.9 <= af <= 1.1):
            assert r["coverage"] is not None
    # warm rerun harvests nothing new (rides the xla_cost once-guard)
    n = len(sums)
    _query(sess, mult=301)
    with open(sess.events.path) as f:
        recs2 = [json.loads(line) for line in f]
    assert len([r for r in recs2 if r["event"] == "hlo_summary"]) == n


def test_agg_summaries_carry_scatter_attribution():
    """The headline shape: a grouped aggregate on the SCATTER strategy
    must name its scatter instructions (this is the instrument the
    item-1 kernel rewrite is judged by)."""
    sess = TpuSession({
        "spark.rapids.tpu.eventLog.enabled": True,
        "spark.rapids.tpu.sql.agg.strategy": "SCATTER",
    })
    df = (sess.range(0, 3000)
          .select(col("id"),
                  E.Alias(E.Multiply(col("id"), lit(302)), "v"))
          .group_by("v")
          .agg(A.agg(A.Sum(col("id")), "s")))
    df.collect()
    sums = [r for r in sess.events.records()
            if r["event"] == "hlo_summary"]
    assert sums
    assert any(r["scatter_count"] > 0 for r in sums), \
        "SCATTER-strategy agg harvested no scatter-classified fusions"
    clsset = {f["class"] for r in sums for f in r["top_fusions"]}
    assert clsset & {"scatter", "scatter-add"}, clsset


def test_harvest_hlo_tolerates_broken_compiled():
    class NoText:
        pass

    class RaisingText:
        def as_text(self):
            raise RuntimeError("backend refuses")

    class NotHlo:
        def as_text(self):
            return "definitely not an hlo dump"

    for compiled in (NoText(), RaisingText(), NotHlo()):
        assert hlo.harvest_hlo(compiled, "site", "d00d") is None


# ---------------------------------------------------------------------------
# 4. zero overhead when events + obs are both off
# ---------------------------------------------------------------------------
def test_zero_overhead_no_hlo_text_fetched_when_off(monkeypatch):
    fetched = []
    monkeypatch.setattr(
        hlo, "harvest_hlo",
        lambda *a, **k: fetched.append(a) or None)
    parsed = []
    monkeypatch.setattr(
        hlo, "summarize_hlo",
        lambda *a, **k: parsed.append(a) or {})
    sess = TpuSession({})  # defaults: everything off
    rows = _query(sess, hi=8192, mult=303)
    assert rows[0][1] == 8092
    assert fetched == [], "HLO text fetched while planes off"
    assert parsed == [], "HLO parsed while planes off"


# ---------------------------------------------------------------------------
# 5. obs twins
# ---------------------------------------------------------------------------
def test_hlo_summary_has_live_twin_declared():
    from spark_rapids_tpu.obs.registry import EVENT_BACKED_METRICS, METRICS

    fam = EVENT_BACKED_METRICS["hlo_summary"]
    assert fam in METRICS
    assert "tpu_hlo_top_fusion_bytes" in METRICS


def test_obs_twins_scatter_counter_and_fusion_gauge():
    reg = MetricsRegistry()
    obs.install(reg)
    try:
        obs.note_hlo_summary("agg_update", 3, 1 << 20)
        obs.note_hlo_summary("agg_update", 0, 1 << 10)  # smaller: no drop
        assert reg.value("tpu_hlo_scatter_programs",
                         site="agg_update") == 1
        assert reg.value("tpu_hlo_top_fusion_bytes",
                         site="agg_update") == 1 << 20
    finally:
        obs.uninstall()


def test_live_query_sets_obs_twins():
    reg = MetricsRegistry()
    obs.install(reg)
    try:
        sess = TpuSession({"spark.rapids.tpu.sql.agg.strategy": "SCATTER"})
        df = (sess.range(0, 2500)
              .select(col("id"),
                      E.Alias(E.Multiply(col("id"), lit(304)), "v"))
              .group_by("v")
              .agg(A.agg(A.Sum(col("id")), "s")))
        df.collect()
        snap = reg.snapshot()
        assert snap.get("tpu_hlo_scatter_programs"), snap.keys()
        assert snap.get("tpu_hlo_top_fusion_bytes"), snap.keys()
    finally:
        obs.uninstall()


# ---------------------------------------------------------------------------
# 6. tpu_profile: == hlo == section + --diff gates
# ---------------------------------------------------------------------------
def _sum_ev(site, digest, top, total, scatters=0, cls="scatter-add",
            ts=1):
    return {"ts": ts, "event": "hlo_summary", "site": site,
            "digest": digest, "backend": "cpu", "instructions": 10,
            "coverage": 1.0, "total_bytes": total,
            "scatter_count": scatters,
            "top_fusions": [{"name": "fusion.7", "op": "fusion",
                             "class": cls, "bytes": top,
                             "out_bytes": top // 2}],
            "largest_output": {"name": "fusion.7", "bytes": top // 2}}


def _cost_ev(site, digest, bytes_, ts=1):
    return {"ts": ts, "event": "program_cost", "site": site,
            "digest": digest, "backend": "cpu", "trace_ms": 1.0,
            "compile_ms": 1.0, "flops": 1.0, "bytes_accessed": bytes_,
            "temp_bytes": None, "argument_bytes": None,
            "output_bytes": None, "op": "TpuHashAggregateExec"}


def test_hlo_section_names_the_culprit():
    events = [
        _cost_ev("agg_update", "aaa", 19.4e9),
        _sum_ev("agg_update", "aaa", top=12_100_000_000,
                total=15_000_000_000, scatters=2),
    ]
    text = "\n".join(tpu_profile.hlo_section(events))
    assert "== hlo ==" in text
    assert "site=agg_update" in text and "scatters=2" in text
    # the culprit line joins the fusion to the compiler's own figure
    assert ("agg_update: fusion.7 [scatter-add] accounts for "
            "12100.00MB of 19400.00MB (62% of site XLA bytes)" in text)
    assert "largest single fusion" in text
    # no summaries: a placeholder, not an error
    assert "no hlo_summary events" in "\n".join(
        tpu_profile.hlo_section([]))


def test_report_includes_hlo_from_live_log():
    sess = TpuSession({"spark.rapids.tpu.eventLog.enabled": True})
    _query(sess, mult=305)
    text, violations = tpu_profile.build_report(sess.events.records())
    assert violations == 0
    assert "== hlo ==" in text
    sect = text.split("== hlo ==")[1].split("==")[0]
    assert "site=" in sect, "hlo section empty on a cold run:\n" + text


def test_diff_logs_gates_fusion_bytes_and_scatter_appearance():
    old = [_sum_ev("agg_update", "a", top=1 << 20, total=4 << 20)]
    # 10x growth in the top fusion: REGRESSION
    new = [_sum_ev("agg_update", "a", top=10 << 20, total=40 << 20)]
    text, n = tpu_profile.diff_logs(old, new, threshold=0.2)
    assert n >= 1 and "agg_update.top_fusion_bytes: REGRESSION" in text
    assert "agg_update.hlo_bytes: REGRESSION" in text
    # a scatter lowering APPEARING is structural, gated at any size
    news = [_sum_ev("agg_update", "a", top=1 << 20, total=4 << 20,
                    scatters=1)]
    text, n = tpu_profile.diff_logs(old, news, threshold=0.2)
    assert n == 1 and "agg_update.scatter_count: REGRESSION" in text
    # self-diff is clean
    text, n = tpu_profile.diff_logs(old, list(old), threshold=0.2)
    assert n == 0, text
    # the appearance gate covers a site the OLD log never harvested —
    # the rewrite-introduces-a-new-compile-site scenario must not evade
    # the structural gate via the site intersection
    newsite = [_sum_ev("pallas_update", "p", top=1 << 16, total=1 << 18,
                       scatters=1)]
    text, n = tpu_profile.diff_logs(old, old + newsite, threshold=0.2)
    assert n == 1 and "pallas_update.scatter_count: REGRESSION" in text
    # a scatter-free new site is not a regression
    clean = [_sum_ev("pallas_update", "p", top=1 << 16, total=1 << 18,
                     scatters=0, cls="dot")]
    text, n = tpu_profile.diff_logs(old, old + clean, threshold=0.2)
    assert n == 0, text


def test_diff_bench_gates_hlo_fields():
    def shape(top, scat, strategy="SCATTER"):
        return {"per_shape": {"agg": {
            "tpu_ms": 100.0, "agg_strategy": strategy,
            "hlo_top_fusion_bytes": top, "hlo_scatter_count": scat}}}

    text, n = tpu_profile.diff_bench(shape(1 << 20, 2),
                                     shape(10 << 20, 2), threshold=0.2)
    assert n == 1 and "agg.hlo_top_fusion_bytes: REGRESSION" in text
    # same strategy, scatter count rises: REGRESSION
    text, n = tpu_profile.diff_bench(shape(1 << 20, 2),
                                     shape(1 << 20, 3), threshold=0.2)
    assert n == 1 and "agg.hlo_scatter_count: REGRESSION" in text
    # a deliberate strategy flip owns its scatter delta: no gate
    text, n = tpu_profile.diff_bench(
        shape(1 << 20, 0, strategy="SORT"),
        shape(1 << 20, 3, strategy="SCATTER"), threshold=0.2)
    assert n == 0, text
    # ... and its fusion-map delta: the radix loop compiles as ONE big
    # fusion, so a flip's top-fusion growth is owned too (the committed
    # rounds' absolute amplification levels are pinned in CI instead)
    text, n = tpu_profile.diff_bench(
        shape(1 << 20, 2, strategy="SCATTER"),
        shape(10 << 20, 0, strategy="RADIX"), threshold=0.2)
    assert n == 0, text
    # absent fields (old rounds): no gate
    text, n = tpu_profile.diff_bench(
        {"per_shape": {"agg": {"tpu_ms": 100.0}}},
        shape(1 << 20, 2), threshold=0.2)
    assert n == 0, text


def test_diff_bench_gates_byte_amplification():
    def shape(**kw):
        return {"per_shape": {"agg": {"tpu_ms": 100.0, **kw}}}

    # first-class field, beyond-threshold growth: REGRESSION
    text, n = tpu_profile.diff_bench(
        shape(byte_amplification=2.5),
        shape(byte_amplification=25.0), threshold=0.2)
    assert n == 1 and "agg.byte_amplification: REGRESSION" in text
    # shrink (the round-12 fix direction): ok
    text, n = tpu_profile.diff_bench(
        shape(byte_amplification=25.0),
        shape(byte_amplification=2.5), threshold=0.2)
    assert n == 0 and "agg.byte_amplification: ok" in text
    # BACKFILL: an r09-era json carries only the two inputs — the ratio
    # is derived (19.4 GB / 772 MB ~ 25x) and still gates the new run
    old = shape(xla_bytes_accessed=int(19.4e9),
                predicted_hbm_bytes=int(772e6))
    text, n = tpu_profile.diff_bench(
        old, shape(byte_amplification=4.0), threshold=0.2)
    assert n == 0 and "25.13x -> 4.00x" in text, text
    text, n = tpu_profile.diff_bench(
        shape(byte_amplification=4.0), old, threshold=0.2)
    assert n == 1 and "REGRESSION" in text
    # one side missing both inputs: no gate
    text, n = tpu_profile.diff_bench(
        shape(), shape(byte_amplification=9.9), threshold=0.2)
    assert n == 0, text
    # a deliberate lowering flip (agg OR join strategy) owns its
    # amplification — AUTO resolves different tiers at different
    # scales, so a scale-mismatched smoke must not false-fire; the
    # committed absolute levels are pinned by the events CI job
    text, n = tpu_profile.diff_bench(
        shape(byte_amplification=9.8, agg_strategy="RADIX"),
        shape(byte_amplification=31.0, agg_strategy="SCATTER"),
        threshold=0.2)
    assert n == 0 and "agg.agg_strategy: RADIX -> SCATTER" in text, text
    text, n = tpu_profile.diff_bench(
        shape(byte_amplification=9.8, join_strategy="RADIX"),
        shape(byte_amplification=31.0, join_strategy="DIRECT"),
        threshold=0.2)
    assert n == 0 and "agg.join_strategy: RADIX -> DIRECT" in text, text
    # and bench.py's own helper is the same ratio (shared definition)
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(os.path.dirname(__file__), os.pardir,
                                  "bench.py"))
    bench_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_mod)
    assert bench_mod.byte_amplification(int(19.4e9), int(772e6)) == 25.13
    assert bench_mod.byte_amplification(None, 100) is None
    assert bench_mod.byte_amplification(100, 0) is None


# ---------------------------------------------------------------------------
# 7. environment provenance
# ---------------------------------------------------------------------------
def test_environment_info_shape_and_memoization():
    env = envinfo.environment_info()
    for key in ("backend", "device_kind", "device_count", "jax_version",
                "host_cores"):
        assert key in env, key
    assert env["device_count"] >= 1
    # memoized: same content, and the returned dict is a copy (a caller
    # mutating it cannot poison later events)
    env["backend"] = "poisoned"
    assert envinfo.environment_info()["backend"] != "poisoned"
    assert "backend=" in envinfo.describe(env)
    assert envinfo.describe(None) == "backend=?"


_ENV_CASES = [
    # (a, b, differ)
    ({"backend": "cpu", "device_kind": "cpu"},
     {"backend": "cpu", "device_kind": "cpu"}, False),
    ({"backend": "cpu", "device_kind": "cpu"},
     {"backend": "tpu", "device_kind": "TPU v5p"}, True),
    ({"backend": "tpu", "device_kind": "TPU v4"},
     {"backend": "tpu", "device_kind": "TPU v5p"}, True),
    # missing blocks (pre-provenance logs) never differ
    (None, {"backend": "tpu", "device_kind": "TPU v5p"}, False),
    ({"backend": "cpu", "device_kind": "cpu"}, None, False),
    (None, None, False),
]


def test_environments_differ_rule_and_profiler_twin_agree():
    for a, b, want in _ENV_CASES:
        assert envinfo.environments_differ(a, b) is want, (a, b)
        # the offline tool's duplicated-by-design copy must agree
        assert tpu_profile._envs_differ(a, b) is want, (a, b)


def test_diff_warns_loudly_on_environment_mismatch():
    cpu_env = {"backend": "cpu", "device_kind": "cpu",
               "device_count": 1, "jax_version": "0.4.37"}
    tpu_env = {"backend": "tpu", "device_kind": "TPU v5p",
               "device_count": 8, "jax_version": "0.4.37"}

    def qstart(env):
        return {"ts": 1, "event": "query_start", "query_id": 1,
                "plan_digest": "d", "sql_hash": "h", "env": env}

    text, n = tpu_profile.diff_logs([qstart(cpu_env)], [qstart(tpu_env)],
                                    threshold=0.2)
    assert "ENVIRONMENTS DIFFER" in text
    assert n == 0, "env mismatch is a warning, not a regression"
    # bench-JSON form: top-level env blocks
    text, n = tpu_profile.diff_bench(
        {"per_shape": {}, "env": cpu_env},
        {"per_shape": {}, "env": tpu_env}, threshold=0.2)
    assert "ENVIRONMENTS DIFFER" in text and n == 0
    # same env: silent
    text, _ = tpu_profile.diff_bench(
        {"per_shape": {}, "env": cpu_env},
        {"per_shape": {}, "env": dict(cpu_env)}, threshold=0.2)
    assert "ENVIRONMENTS DIFFER" not in text


def test_query_start_rides_env_and_status_serves_it(tmp_path):
    sess = TpuSession({
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.metrics.http.enabled": True,
    })
    try:
        _query(sess, mult=306)
        qs = [r for r in sess.events.records()
              if r["event"] == "query_start"]
        assert qs and qs[0].get("env"), "query_start lost its env block"
        assert qs[0]["env"]["backend"] == envinfo.environment_info()[
            "backend"]
        # /status serves the same block; tpu_top renders it
        import urllib.request

        st = json.loads(urllib.request.urlopen(
            sess.obs_address + "/status").read())
        assert st.get("env", {}).get("backend") == qs[0]["env"]["backend"]
        _tspec = importlib.util.spec_from_file_location(
            "tpu_top", os.path.join(REPO, "tools", "tpu_top.py"))
        tpu_top = importlib.util.module_from_spec(_tspec)
        _tspec.loader.exec_module(tpu_top)
        screen = tpu_top.render_status(st)
        assert "env  backend=" in screen
    finally:
        obs.shutdown()


# ---------------------------------------------------------------------------
# 8. conf-declared top-K reaches the harvest
# ---------------------------------------------------------------------------
def test_conf_top_k_controls_summary_width():
    sess = TpuSession({
        "spark.rapids.tpu.eventLog.enabled": True,
        "spark.rapids.tpu.hlo.topK": 1,
    })
    _query(sess, mult=307)
    sums = [r for r in sess.events.records()
            if r["event"] == "hlo_summary"]
    assert sums
    assert all(len(r["top_fusions"]) <= 1 for r in sums)
    hlo._TOP_K = None  # don't leak the narrowed width into later tests


# ---------------------------------------------------------------------------
# 9. direct-address join-table idiom (round 14): its own class
# ---------------------------------------------------------------------------
def test_join_table_build_classified_distinct_from_scatter():
    """The DIRECT join tier builds its (first, count) tables with a
    scatter-MIN of an IOTA (row indices) plus a scatter-ADD of ones over
    the same table shape. Both must classify 'join-table' — a
    deliberately chosen DIRECT join is not the scatter-add aggregation
    amplifier, and must contribute ZERO to scatter_count (the --diff
    appearance gate's subject)."""
    text = """\
HloModule jit_fastbuild

%min_s32 (a: s32[], b: s32[]) -> s32[] {
  %a = s32[] parameter(0)
  %b = s32[] parameter(1)
  ROOT %m = s32[] minimum(s32[] %a, s32[] %b)
}

%add_s32 (a2: s32[], b2: s32[]) -> s32[] {
  %a2 = s32[] parameter(0)
  %b2 = s32[] parameter(1)
  ROOT %s = s32[] add(s32[] %a2, s32[] %b2)
}

ENTRY %main (off: s64[4096,1], finit: s32[16384], cinit: s32[16384], ones: s32[4096]) -> (s32[16384], s32[16384]) {
  %off = s64[4096,1]{1,0} parameter(0)
  %finit = s32[16384]{0} parameter(1)
  %cinit = s32[16384]{0} parameter(2)
  %ones = s32[4096]{0} parameter(3)
  %bidx = s32[4096]{0} iota(), iota_dimension=0
  %first = s32[16384]{0} scatter(s32[16384]{0} %finit, s64[4096,1]{1,0} %off, s32[4096]{0} %bidx), update_window_dims={}, inserted_window_dims={0}, scatter_dims_to_operand_dims={0}, index_vector_dim=1, to_apply=%min_s32
  %cnt = s32[16384]{0} scatter(s32[16384]{0} %cinit, s64[4096,1]{1,0} %off, s32[4096]{0} %ones), update_window_dims={}, inserted_window_dims={0}, scatter_dims_to_operand_dims={0}, index_vector_dim=1, to_apply=%add_s32
  ROOT %out = (s32[16384]{0}, s32[16384]{0}) tuple(s32[16384]{0} %first, s32[16384]{0} %cnt)
}
"""
    s = hlo.summarize_hlo(text)
    assert s["coverage"] == 1.0
    by_name = {r["name"]: r for r in s["top_fusions"]}
    assert by_name["first"]["class"] == "join-table", by_name
    assert by_name["cnt"]["class"] == "join-table", by_name
    assert s["scatter_count"] == 0, s["top_fusions"]


def test_compiled_direct_join_build_classifies_join_table():
    """The REAL compiled direct-address build (this backend's dialect —
    on CPU a pair of while/DUS loops) must classify join-table end to
    end, and a min+count scatter AGGREGATION over data values must NOT
    (the iota update stream is the discriminator)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    def build_tables(key64, ok):
        nb = key64.shape[0]
        tbl = 4 * nb
        kmin = jnp.min(jnp.where(ok, key64, jnp.uint64(2 ** 64 - 1)))
        diffu = key64 - kmin
        off = jnp.where(ok & (diffu < jnp.uint64(tbl)), diffu,
                        jnp.uint64(tbl)).astype(jnp.int64)
        bidx = jnp.arange(nb, dtype=jnp.int32)
        first = jnp.full(tbl, nb, jnp.int32).at[off].min(bidx, mode="drop")
        cnt = jnp.zeros(tbl, jnp.int32).at[off].add(1, mode="drop")
        return first, cnt

    k = jnp.asarray(np.arange(2048, dtype=np.uint64))
    ok = jnp.ones(2048, bool)
    c = jax.jit(build_tables).lower(k, ok).compile()
    s = hlo.summarize_hlo(c.as_text(), top_k=16)
    assert s["scatter_count"] == 0, s["top_fusions"]
    assert any(r["class"] == "join-table" for r in s["top_fusions"])

    def agg_scatters(seg, vals):
        B = 128
        mn = jnp.full(B, 2 ** 31 - 1, jnp.int32).at[seg].min(
            vals, mode="drop")
        cnt = jnp.zeros(B, jnp.int32).at[seg].add(1, mode="drop")
        return mn, cnt

    seg = jnp.asarray((np.arange(2048) % 128).astype(np.int32))
    vals = jnp.asarray((np.arange(2048) * 7 % 999).astype(np.int32))
    c2 = jax.jit(agg_scatters).lower(seg, vals).compile()
    s2 = hlo.summarize_hlo(c2.as_text(), top_k=16)
    assert s2["scatter_count"] == 2, s2["top_fusions"]
    assert not any(r["class"] == "join-table" for r in s2["top_fusions"])
