"""CPU-vs-TPU differential test harness.

Reference analog: SparkQueryCompareTestSuite.testSparkResultsAreEqual
(tests/.../SparkQueryCompareTestSuite.scala:731) and the pytest
assert_gpu_and_cpu_are_equal_collect / assert_gpu_fallback_collect
(integration_tests asserts.py:330/:281): run the same query with the plugin
disabled and enabled, assert equal results; optionally assert that a named
operator fell back to CPU.
"""
import math
from typing import Callable, Dict, List, Optional, Sequence

from spark_rapids_tpu.sql import DataFrame, TpuSession


def _canon(v, approx: bool):
    """Total-order sort key: (null_rank, type_tag, (nan_rank, value))."""
    if v is None:
        return (0, "", (0, 0))
    if isinstance(v, bool):
        return (1, "b", (0, v))
    if isinstance(v, float):
        if math.isnan(v):
            return (1, "f", (1, 0.0))
        return (1, "f", (0, round(v, 9) if approx else v))
    if isinstance(v, int):
        return (1, "f", (0, v))
    if isinstance(v, bytes):
        return (1, "y", (0, v))
    return (1, "s", (0, str(v)))


def _sort_key(row, approx):
    return tuple(_canon(v, approx) for v in row)


def compare_rows(cpu_rows: List[tuple], tpu_rows: List[tuple],
                 ignore_order: bool = True, approx_float: bool = False) -> None:
    assert len(cpu_rows) == len(tpu_rows), (
        f"row count mismatch: cpu={len(cpu_rows)} tpu={len(tpu_rows)}\n"
        f"cpu={cpu_rows[:20]}\ntpu={tpu_rows[:20]}"
    )
    if ignore_order:
        cpu_rows = sorted(cpu_rows, key=lambda r: _sort_key(r, approx_float))
        tpu_rows = sorted(tpu_rows, key=lambda r: _sort_key(r, approx_float))
    for i, (cr, tr) in enumerate(zip(cpu_rows, tpu_rows)):
        assert len(cr) == len(tr), f"row {i} width mismatch: {cr} vs {tr}"
        for j, (cv, tv) in enumerate(zip(cr, tr)):
            if cv is None or tv is None:
                assert cv is None and tv is None, (
                    f"row {i} col {j}: cpu={cv!r} tpu={tv!r}")
                continue
            if isinstance(cv, float) and isinstance(tv, float):
                from data_gen import ON_TPU

                if math.isnan(cv) or math.isnan(tv):
                    assert math.isnan(cv) and math.isnan(tv), (
                        f"row {i} col {j}: cpu={cv!r} tpu={tv!r}")
                elif approx_float or ON_TPU:
                    # on the chip, f64 is pair-emulated: divisions and
                    # accumulations drift a few ulps from the CPU oracle
                    # (documented incompat, like the reference's
                    # approximate_float mark)
                    assert cv == tv or math.isclose(cv, tv, rel_tol=1e-9, abs_tol=1e-12), (
                        f"row {i} col {j}: cpu={cv!r} tpu={tv!r}")
                else:
                    assert cv == tv, f"row {i} col {j}: cpu={cv!r} tpu={tv!r}"
            else:
                assert cv == tv, f"row {i} col {j}: cpu={cv!r} tpu={tv!r}"


def assert_tpu_and_cpu_equal(
    build: Callable[[TpuSession], DataFrame],
    conf: Optional[Dict] = None,
    ignore_order: bool = True,
    approx_float: bool = False,
    allow_non_tpu: Sequence[str] = (),
):
    """Run the query twice (plugin off/on) and diff the results.

    Unless ``allow_non_tpu`` names CPU operators, the TPU run asserts that
    the WHOLE plan was replaced (reference: 'test.enabled' RapidsConf key).
    """
    conf = dict(conf or {})
    cpu_sess = TpuSession({**conf, "spark.rapids.tpu.sql.enabled": False})
    tpu_conf = {
        **conf,
        "spark.rapids.tpu.sql.enabled": True,
        "spark.rapids.tpu.sql.test.enabled": True,
        "spark.rapids.tpu.sql.test.allowedNonTpu": ",".join(allow_non_tpu),
        # every differential run also cross-checks the static type matrix
        # against the legacy lowering probe: a verdict disagreement on the
        # tested surface fails loudly below instead of drifting silently
        "spark.rapids.tpu.sql.matrix.probeCrossCheck.enabled": True,
    }
    tpu_sess = TpuSession(tpu_conf)
    from spark_rapids_tpu.plugin import typechecks as _TC

    before = len(_TC.cross_check_log())
    cpu_rows = build(cpu_sess).collect()
    tpu_rows = build(tpu_sess).collect()
    new = _TC.cross_check_log()[before:]
    assert not new, (
        "static matrix vs lowering-probe verdict disagreement:\n"
        + "\n".join(new)
    )
    compare_rows(cpu_rows, tpu_rows, ignore_order, approx_float)
    return cpu_rows


def assert_fallback(
    build: Callable[[TpuSession], DataFrame],
    fallback_class: str,
    conf: Optional[Dict] = None,
):
    """Assert results equal AND that ``fallback_class`` stayed on CPU
    (reference: assert_gpu_fallback_collect, asserts.py:281)."""
    conf = dict(conf or {})
    cpu_sess = TpuSession({**conf, "spark.rapids.tpu.sql.enabled": False})
    tpu_sess = TpuSession({**conf, "spark.rapids.tpu.sql.enabled": True})
    cpu_rows = build(cpu_sess).collect()
    tpu_rows = build(tpu_sess).collect()
    compare_rows(cpu_rows, tpu_rows)
    meta = tpu_sess.overrides.last_meta
    assert meta is not None, "no plan captured"
    fellback = meta.fallback_nodes()
    assert fallback_class in fellback, (
        f"expected {fallback_class} to fall back; fell back: {fellback}\n"
        + "\n".join(meta.explain_lines())
    )
