"""CPU-vs-TPU differential test harness.

Reference analog: SparkQueryCompareTestSuite.testSparkResultsAreEqual
(tests/.../SparkQueryCompareTestSuite.scala:731) and the pytest
assert_gpu_and_cpu_are_equal_collect / assert_gpu_fallback_collect
(integration_tests asserts.py:330/:281): run the same query with the plugin
disabled and enabled, assert equal results; optionally assert that a named
operator fell back to CPU.
"""
import math
from typing import Callable, Dict, List, Optional, Sequence

from spark_rapids_tpu.sql import DataFrame, TpuSession


def _canon(v, approx: bool):
    """Total-order sort key: (null_rank, type_tag, (nan_rank, value))."""
    if v is None:
        return (0, "", (0, 0))
    if isinstance(v, bool):
        return (1, "b", (0, v))
    if isinstance(v, float):
        if math.isnan(v):
            return (1, "f", (1, 0.0))
        return (1, "f", (0, round(v, 9) if approx else v))
    if isinstance(v, int):
        return (1, "f", (0, v))
    if isinstance(v, bytes):
        return (1, "y", (0, v))
    return (1, "s", (0, str(v)))


def _sort_key(row, approx):
    return tuple(_canon(v, approx) for v in row)


def compare_rows(cpu_rows: List[tuple], tpu_rows: List[tuple],
                 ignore_order: bool = True, approx_float: bool = False) -> None:
    assert len(cpu_rows) == len(tpu_rows), (
        f"row count mismatch: cpu={len(cpu_rows)} tpu={len(tpu_rows)}\n"
        f"cpu={cpu_rows[:20]}\ntpu={tpu_rows[:20]}"
    )
    if ignore_order:
        cpu_rows = sorted(cpu_rows, key=lambda r: _sort_key(r, approx_float))
        tpu_rows = sorted(tpu_rows, key=lambda r: _sort_key(r, approx_float))
    for i, (cr, tr) in enumerate(zip(cpu_rows, tpu_rows)):
        assert len(cr) == len(tr), f"row {i} width mismatch: {cr} vs {tr}"
        for j, (cv, tv) in enumerate(zip(cr, tr)):
            if cv is None or tv is None:
                assert cv is None and tv is None, (
                    f"row {i} col {j}: cpu={cv!r} tpu={tv!r}")
                continue
            if isinstance(cv, float) and isinstance(tv, float):
                from data_gen import ON_TPU

                if math.isnan(cv) or math.isnan(tv):
                    assert math.isnan(cv) and math.isnan(tv), (
                        f"row {i} col {j}: cpu={cv!r} tpu={tv!r}")
                elif approx_float or ON_TPU:
                    # on the chip, f64 is pair-emulated: divisions and
                    # accumulations drift a few ulps from the CPU oracle
                    # (documented incompat, like the reference's
                    # approximate_float mark)
                    assert cv == tv or math.isclose(cv, tv, rel_tol=1e-9, abs_tol=1e-12), (
                        f"row {i} col {j}: cpu={cv!r} tpu={tv!r}")
                else:
                    assert cv == tv, f"row {i} col {j}: cpu={cv!r} tpu={tv!r}"
            else:
                assert cv == tv, f"row {i} col {j}: cpu={cv!r} tpu={tv!r}"


def assert_tpu_and_cpu_equal(
    build: Callable[[TpuSession], DataFrame],
    conf: Optional[Dict] = None,
    ignore_order: bool = True,
    approx_float: bool = False,
    allow_non_tpu: Sequence[str] = (),
):
    """Run the query twice (plugin off/on) and diff the results.

    Unless ``allow_non_tpu`` names CPU operators, the TPU run asserts that
    the WHOLE plan was replaced (reference: 'test.enabled' RapidsConf key).
    """
    conf = dict(conf or {})
    cpu_sess = TpuSession({**conf, "spark.rapids.tpu.sql.enabled": False})
    tpu_conf = {
        **conf,
        "spark.rapids.tpu.sql.enabled": True,
        "spark.rapids.tpu.sql.test.enabled": True,
        "spark.rapids.tpu.sql.test.allowedNonTpu": ",".join(allow_non_tpu),
        # every differential run also cross-checks the static type matrix
        # against the legacy lowering probe: a verdict disagreement on the
        # tested surface fails loudly below instead of drifting silently
        "spark.rapids.tpu.sql.matrix.probeCrossCheck.enabled": True,
        # ... and the static plan analyzer (plugin/plananalysis.py): its
        # compile-signature forecast and byte bounds are asserted against
        # the measured run below
        "spark.rapids.tpu.sql.analysis.crossCheck.enabled": True,
    }
    tpu_sess = TpuSession(tpu_conf)
    from spark_rapids_tpu.exec.base import compile_snapshot
    from spark_rapids_tpu.plugin import typechecks as _TC

    before = len(_TC.cross_check_log())
    cpu_rows = build(cpu_sess).collect()
    snap = compile_snapshot()
    # harvest the compiled-program cost plane (xla_cost.py) during the
    # TPU run: every differential test exercises the CostProbe path and
    # the analyzer-bound vs XLA-bytes comparison below (the cost of a
    # probe is the same trace+compile jit would have done lazily)
    from spark_rapids_tpu import xla_cost as _XC

    cost_snap = _XC.snapshot()
    prev_harvest = _XC.FORCE_HARVEST
    _XC.FORCE_HARVEST = True
    try:
        tpu_rows = build(tpu_sess).collect()
    finally:
        _XC.FORCE_HARVEST = prev_harvest
    new = _TC.cross_check_log()[before:]
    assert not new, (
        "static matrix vs lowering-probe verdict disagreement:\n"
        + "\n".join(new)
    )
    compare_rows(cpu_rows, tpu_rows, ignore_order, approx_float)
    _assert_analysis_cross_check(tpu_sess, snap, build, tpu_conf, tpu_rows,
                                 cost_snap=cost_snap)
    return cpu_rows


def _assert_analysis_cross_check(tpu_sess, snap, build, tpu_conf, tpu_rows,
                                 cost_snap=None):
    """The static-plan-analyzer cross-check (plugin/plananalysis.py):

    1. for BOUNDED plans, the actual per-run compile cache-miss delta at
       every pipeline site is covered by the forecast (warm caches may
       miss less, never more — a miss above forecast means the analyzer
       mispredicted the plan's shapes or its fusion decisions);
    2. for BOUNDED plans, every operator's measured bytesTouched is
       covered by the analyzer's static byte bound;
    3. when the run elided validity planes, a rerun on the mask-carrying
       path (nullElision disabled) produces identical results;
    4. every program cost harvested during the run is well-formed
       (site/digest present, non-negative phase times), and the
       analyzer-bound vs XLA-bytes comparison is recorded on the session
       as ``last_xla_vs_analyzer`` — XLA ABOVE the bound is expected
       (temp-inflated kernels) and deliberately NOT asserted against:
       it is the roofline-push lead, not a bug.
    """
    if cost_snap is not None:
        from spark_rapids_tpu import xla_cost as _XC

        recs = _XC.records_since(cost_snap)
        for r in recs:
            assert r.get("site") and r.get("digest"), r
            assert (r.get("trace_ms") or 0) >= 0, r
            assert (r.get("compile_ms") or 0) >= 0, r
        an = tpu_sess.last_analysis
        bounds = an.bytes_by_op if an is not None else {}
        comparison = {}
        for r in recs:
            op = r.get("op")
            if op and r.get("bytes_accessed") is not None:
                xb, _ = comparison.get(op, (0.0, None))
                comparison[op] = (xb + r["bytes_accessed"],
                                  bounds.get(op))
        tpu_sess.last_xla_vs_analyzer = comparison

    analysis = tpu_sess.last_analysis
    if analysis is None:
        return
    from spark_rapids_tpu.exec.base import (
        BYTES_TOUCHED,
        COMPILE_COUNTER,
        TpuExec,
    )

    if analysis.bounded:
        base_total, base_sites = snap
        deltas = {
            k: v - base_sites.get(k, 0)
            for k, v in COMPILE_COUNTER.by_site.items()
            if v - base_sites.get(k, 0)
        }
        for site, actual in deltas.items():
            forecast = analysis.site_forecast.get(site, 0)
            assert actual <= forecast, (
                f"compile-signature forecast disagreement at site {site}: "
                f"actual misses {actual} > forecast {forecast} "
                f"(full forecast: {analysis.site_forecast})\n"
                + analysis.render()
            )

        plan = tpu_sess.last_executed_plan
        node = getattr(plan, "tpu_child", plan)
        if isinstance(node, TpuExec):
            measured: Dict[str, int] = {}

            def walk(n):
                m = n.metrics.get(BYTES_TOUCHED)
                if m is not None and m.value:
                    measured[n.node_name] = (
                        measured.get(n.node_name, 0) + m.value)
                for c in n.children:
                    walk(c)

            walk(node)
            for name, got in measured.items():
                bound = analysis.bytes_by_op.get(name)
                assert bound is not None and got <= bound, (
                    f"footprint disagreement at {name}: measured "
                    f"bytesTouched {got} > analyzer bound {bound} "
                    f"(bounds: {analysis.bytes_by_op})\n" + analysis.render()
                )

    if analysis.elided_columns:
        off_sess = TpuSession({
            **tpu_conf,
            "spark.rapids.tpu.sql.analysis.crossCheck.enabled": False,
            "spark.rapids.tpu.sql.analysis.nullElision.enabled": False,
        })
        rows_off = build(off_sess).collect()
        compare_rows(tpu_rows, rows_off, ignore_order=False,
                     approx_float=False)


def assert_fallback(
    build: Callable[[TpuSession], DataFrame],
    fallback_class: str,
    conf: Optional[Dict] = None,
):
    """Assert results equal AND that ``fallback_class`` stayed on CPU
    (reference: assert_gpu_fallback_collect, asserts.py:281)."""
    conf = dict(conf or {})
    cpu_sess = TpuSession({**conf, "spark.rapids.tpu.sql.enabled": False})
    tpu_sess = TpuSession({**conf, "spark.rapids.tpu.sql.enabled": True})
    cpu_rows = build(cpu_sess).collect()
    tpu_rows = build(tpu_sess).collect()
    compare_rows(cpu_rows, tpu_rows)
    meta = tpu_sess.overrides.last_meta
    assert meta is not None, "no plan captured"
    fellback = meta.fallback_nodes()
    assert fallback_class in fellback, (
        f"expected {fallback_class} to fall back; fell back: {fellback}\n"
        + "\n".join(meta.explain_lines())
    )
