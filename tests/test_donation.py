"""Donation-safety analyzer (tools/tpu_donate.py), the certification
table + batch-exclusivity protocol (plugin/donation.py), and the
runtime witness.

Five layers, mirroring the ISSUE 19 acceptance criteria:

  1. analyzer contract — the must-catch fixture corpus (each
     use-after-donation shape in tests/donation_fixtures/ is flagged by
     its matching rule, the safe variants are not), the repo itself is
     clean under --strict-allowlist, stale entries fail strict mode,
     TPU202 stays warn-level, and the manifest the tool reads from
     donation.py's AST matches the live DONATION_SPECS table;
  2. protocol semantics — mark_exclusive / claim / batch_donatable and
     every gate of dispatch_mask (conf off, uncertified site, shared
     batch, dict columns, snapshot-mode exclusion);
  3. guard semantics — deleted-plane accounting against a real donating
     dispatch (declined aliases count zero bytes, truthfully), plane
     restore on failure, and the witness's two typed violations
     (mask-with-no-effect, use-after-donation) plus the retry-layer
     re-typing;
  4. the differential matrix — donation on vs off bit-exact across the
     five agg strategies and five join tiers, under forced batch
     splits, with donated_bytes > 0 on every donating run and zero on
     every donation-off run;
  5. cache identity — the donate mask folds into the structural key AND
     the AOT program-cache entry: a warm same-mask run compiles nothing
     and still donates (the export probes re-declare donate_argnums),
     while flipping donation off recompiles instead of being served a
     donating executable.
"""
import importlib.util
import os
import shutil
import subprocess
import sys
import threading

import numpy as np
import pytest

import spark_rapids_tpu  # noqa: F401  (x64 enable)
import jax

from spark_rapids_tpu import events as EV
from spark_rapids_tpu import faults
from spark_rapids_tpu import obs
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import ColumnarBatch, schema_of
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.exec import base as B
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.expressions import col, lit
from spark_rapids_tpu.plugin import donation
from spark_rapids_tpu.serve import program_cache as PC
from spark_rapids_tpu.sql import TpuSession

from harness import compare_rows

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "tpu_donate.py")
FIXTURES = os.path.join(REPO, "tests", "donation_fixtures")

AGG_STRATEGIES = ("SCATTER", "MATMUL", "SORT", "RADIX", "PALLAS")
JOIN_STRATEGIES = ("AUTO", "SEARCH", "DIRECT", "RADIX", "PALLAS")

NO_BACKOFF = {"spark.rapids.tpu.memory.oomRetry.backoffMs": 0}


@pytest.fixture(autouse=True)
def clean_planes():
    """Every test starts and ends with events/obs/faults/program-cache
    uninstalled, the witness off, and the donated-bytes counters
    zeroed (they are process-global, like the pipeline caches)."""
    EV.uninstall()
    obs.uninstall()
    faults.uninstall()
    PC.uninstall()
    donation.uninstall_witness()
    donation.reset_counters()
    yield
    EV.uninstall()
    obs.uninstall()
    faults.uninstall()
    PC.uninstall()
    donation.uninstall_witness()
    donation.reset_counters()


def _run_tool(*args):
    return subprocess.run([sys.executable, TOOL, *args],
                          capture_output=True, text=True, cwd=REPO)


def _findings(out: str):
    """(basename, rule, qualname) triples from analyzer stdout —
    warnings (TPU202) carry a 'warning: ' prefix the parser strips."""
    got = set()
    for raw in out.splitlines():
        line = raw[len("warning: "):] if raw.startswith("warning: ") \
            else raw
        if ": TPU2" not in line:
            continue
        loc, rest = line.split(": TPU", 1)
        rule = "TPU" + rest.split(" ", 1)[0]
        qual = rest.split("[", 1)[1].split("]", 1)[0]
        got.add((os.path.basename(loc.rsplit(":", 1)[0]), rule, qual))
    return got


# ---------------------------------------------------------------------------
# 1. analyzer contract
# ---------------------------------------------------------------------------
def test_fixture_corpus_must_catch():
    """Every donation hazard shape is flagged by its matching rule."""
    r = _run_tool(FIXTURES, "--allowlist=/dev/null")
    assert r.returncode == 1, r.stdout + r.stderr
    got = _findings(r.stdout)
    must_catch = {
        ("fx_use_after_donation.py", "TPU201", "read_after_guard"),
        ("fx_use_after_donation.py", "TPU201", "rows_after_guard"),
        ("fx_certified_not_donating.py", "TPU202", "build_without_mask"),
        ("fx_donation_outside_cache.py", "TPU203", "jit_donating_loose"),
        ("fx_donation_outside_cache.py", "TPU203", "pjit_donating_loose"),
    }
    missing = must_catch - got
    assert not missing, f"rules failed to catch: {missing}\n{r.stdout}"


def test_fixture_corpus_safe_variants_not_flagged():
    """The safe shapes sitting next to each hazard stay quiet — in
    particular the engine's ``if mask: with guard: ... else: ...``
    idiom, whose else arm is textually after the with but an execution
    ALTERNATIVE."""
    r = _run_tool(FIXTURES, "--allowlist=/dev/null")
    quals = {q for (_, _, q) in _findings(r.stdout)}
    for clean in ("metadata_after_guard", "else_arm_dispatch",
                  "build_with_mask", "build_uncertified",
                  "jit_donating_routed", "jit_plain"):
        assert clean not in quals, f"false positive on {clean}:\n{r.stdout}"


def test_tpu202_is_warning_level(tmp_path):
    """A certified-but-not-donating site prints a warning and exits 0 —
    the omission must be visible but can never fail the build."""
    d = tmp_path / "only202"
    d.mkdir()
    shutil.copy(os.path.join(FIXTURES, "fx_certified_not_donating.py"),
                str(d))
    r = _run_tool(str(d), "--allowlist=/dev/null")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "warning:" in r.stdout and "TPU202" in r.stdout
    assert "clean with 1 warning(s)" in r.stdout


def test_repo_clean_under_strict_allowlist():
    """The acceptance gate: zero TPU201/TPU203 and zero TPU202 warnings
    on the engine tree, no stale allowlist entries."""
    r = _run_tool("--strict-allowlist")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "clean" in r.stdout
    assert "warning" not in r.stdout, r.stdout


def test_stale_allowlist_entry_fails_strict(tmp_path):
    r = _run_tool(FIXTURES, "--allowlist=/dev/null")
    keys = [f"tests/donation_fixtures/{b}::{q}::{rule}"
            for (b, rule, q) in _findings(r.stdout)]
    allow = tmp_path / "allow.txt"
    allow.write_text("\n".join(keys) + "\nbogus.py::gone::TPU201  # stale\n")
    ok = _run_tool(FIXTURES, f"--allowlist={allow}")
    assert ok.returncode == 0, ok.stdout + ok.stderr
    strict = _run_tool(FIXTURES, f"--allowlist={allow}",
                       "--strict-allowlist")
    assert strict.returncode == 1
    assert "stale allowlist entry" in strict.stderr


def _tool_module():
    spec = importlib.util.spec_from_file_location("tpu_donate", TOOL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_manifest_read_from_ast_matches_live_table():
    """The tool parses DONATION_SPECS out of donation.py's AST (it must
    run without jax); the parse must agree with the imported module on
    every site's argnums and retry contract."""
    rows = _tool_module().load_manifest()
    assert set(rows) == set(donation.DONATION_SPECS)
    for site, spec in donation.DONATION_SPECS.items():
        assert rows[site].argnums == spec.argnums, site
        assert rows[site].retry == spec.retry, site
        assert rows[site].certified == spec.certified, site
        assert spec.reason.startswith(rows[site].reason[:20]), site


def test_explain_prints_certification_table():
    r = _run_tool("--explain")
    assert r.returncode == 0, r.stderr
    for site, spec in donation.DONATION_SPECS.items():
        assert f"{site}: " in r.stdout
        verdict = "CERTIFIED" if spec.certified else "NOT CERTIFIED"
        line = next(ln for ln in r.stdout.splitlines()
                    if ln.startswith(f"{site}: "))
        assert verdict in line, line


# ---------------------------------------------------------------------------
# 2. the exclusivity protocol and dispatch_mask's gates
# ---------------------------------------------------------------------------
def _batch(n=256):
    schema = schema_of(k=T.INT, v=T.LONG)
    return ColumnarBatch.from_pydict(
        {"k": [i % 7 for i in range(n)],
         "v": [None if i % 11 == 0 else i for i in range(n)]}, schema)


def test_exclusivity_mark_claim_roundtrip():
    b = _batch()
    assert not donation.is_exclusive(b)
    assert not donation.batch_donatable(b)
    donation.mark_exclusive(b)
    assert donation.batch_donatable(b)
    donation.claim(b)  # a retainer takes shared ownership
    assert not donation.is_exclusive(b)
    assert not donation.batch_donatable(b)


def test_dict_columns_never_donatable():
    class _Col:
        is_dict = True

    class _B:
        exclusive = True
        columns = [_Col()]

    assert not donation.batch_donatable(_B())


def test_dispatch_mask_gates():
    b = donation.mark_exclusive(_batch())
    # the happy path: donation on (default), certified site, exclusive
    assert donation.dispatch_mask("project", b) == (0,)
    assert donation.dispatch_mask("fused_chain", [b]) == (0,)
    # uncertified / unknown sites never donate
    assert donation.dispatch_mask("sort", b) == ()
    assert donation.dispatch_mask("no_such_site", b) == ()
    # a shared batch poisons the whole dispatch
    assert donation.dispatch_mask("agg_plan", [b, _batch()]) == ()
    # empty batch list: nothing to donate
    assert donation.dispatch_mask("agg_plan", []) == ()
    # conf off: copy semantics everywhere
    off = RapidsConf({"spark.rapids.tpu.sql.donation.enabled": False})
    assert donation.dispatch_mask("project", b, off) == ()
    # snapshot-mode off excludes every retry-covered site (all the
    # certified sites declare retry="snapshot")
    nosnap = RapidsConf(
        {"spark.rapids.tpu.sql.donation.retrySnapshot.enabled": False})
    assert donation.dispatch_mask("project", b, nosnap) == ()


def test_session_conf_arms_witness():
    assert not donation.witness_enabled()
    TpuSession({"spark.rapids.tpu.tools.donation.witness.enabled": True})
    try:
        assert donation.witness_enabled()
    finally:
        donation.uninstall_witness()


# ---------------------------------------------------------------------------
# 3. guard semantics
# ---------------------------------------------------------------------------
def test_guard_accounts_only_deleted_planes():
    """A real donating dispatch on the CPU backend deletes the aliased
    data planes; the counters (and a per-op Metric handed in) must see
    exactly those bytes — declined aliases count zero."""
    b = donation.mark_exclusive(_batch(1024))
    planes = [c.data for c in b.columns]
    want = sum(int(a.nbytes) for a in planes)
    fn = jax.jit(lambda vals: [v + 1 for v in vals], donate_argnums=(0,))
    fn([p + 0 for p in planes])  # warm the cache outside the guard
    snap = donation.snapshot_counters()
    m = B.Metric("donatedBytes")
    with donation.guard("project", b, op="T", snapshot=False, metric=m):
        out = fn(planes)
    delta = donation.counters_since(snap)
    assert 0 < delta.get("project", 0) <= want
    assert m.value == delta["project"]
    assert m.kind == "bytes"
    # the outputs are real — donation reused the planes, not the values
    np.testing.assert_array_equal(
        np.asarray(out[0]), np.arange(1024) % 7 + 1)


def test_guard_restores_planes_on_failure():
    """The retry contract: on a failed donating dispatch the guard puts
    the snapshotted planes back so split-and-retry re-reads the input
    it is contractually owed."""
    b = donation.mark_exclusive(_batch(64))
    before = [np.asarray(c.data) for c in b.columns]
    fn = jax.jit(lambda v: v * 2, donate_argnums=(0,))
    with pytest.raises(RuntimeError, match="boom"):
        with donation.guard("project", b, snapshot=True):
            fn(b.columns[1].data)  # really donates the plane
            raise RuntimeError("boom")
    after = [np.asarray(c.data) for c in b.columns]
    for want, got in zip(before, after):
        np.testing.assert_array_equal(want, got)


def test_witness_flags_mask_with_no_effect():
    """A donate mask the program never aliased (zero planes deleted) is
    a certification bug; the witness turns it into a typed violation."""
    donation.install_witness()
    b = donation.mark_exclusive(_batch(64))
    with pytest.raises(donation.TpuDonationViolation,
                       match="no donated plane was deleted"):
        with donation.guard("project", b, op="BadMask", snapshot=False):
            pass  # the dispatch ignored the mask entirely
    # without the witness the same dispatch is only a zero in the
    # counters — never an error
    donation.uninstall_witness()
    snap = donation.snapshot_counters()
    with donation.guard("project", b, snapshot=False):
        pass
    assert donation.counters_since(snap) == {}


def test_witness_types_use_after_donation():
    donation.install_witness()
    b = donation.mark_exclusive(_batch(64))
    with pytest.raises(donation.TpuDonationViolation) as ei:
        with donation.guard("join", b, op="ProbeOp", snapshot=False):
            raise RuntimeError(
                "INTERNAL: Array has been deleted with shape=int64[64]")
    assert ei.value.site == "join" and ei.value.op == "ProbeOp"
    assert ei.value.__cause__ is not None
    # witness off: the raw backend error passes through untyped
    donation.uninstall_witness()
    with pytest.raises(RuntimeError) as raw:
        with donation.guard("join", b, snapshot=False):
            raise RuntimeError("Array has been deleted")
    assert not isinstance(raw.value, donation.TpuDonationViolation)


def test_retry_layer_retypes_use_after_donation():
    """memory/retry.py re-types a deleted-array error crossing the
    retry boundary, attributing the op — anything else re-raises
    untouched."""
    from spark_rapids_tpu.memory import retry as R

    with pytest.raises(donation.TpuDonationViolation,
                       match="retry attempt"):
        R._raise_if_donation_uaf(
            RuntimeError("Array has been deleted"), "TpuProjectExec")
    # non-donation errors and already-typed violations pass through
    assert R._raise_if_donation_uaf(ValueError("nope"), "Op") is None
    v = donation.TpuDonationViolation("join", "Op", "already typed")
    assert R._raise_if_donation_uaf(v, "Op") is None


def test_obs_rebase_gauge_clears_all_labeled_rows():
    """bench's per-shape memory snapshot rebases the program-temp
    high-water gauge; rebase_gauge must drop every labeled row of that
    gauge and nothing else."""
    from spark_rapids_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry()
    reg.set_gauge_max("tpu_program_temp_bytes", 100, site="a")
    reg.set_gauge_max("tpu_program_temp_bytes", 70, site="b")
    reg.inc("tpu_donated_bytes", 42, site="a")
    reg.rebase_gauge("tpu_program_temp_bytes")
    assert reg.value("tpu_program_temp_bytes", site="a") == 0
    assert reg.value("tpu_donated_bytes", site="a") == 42
    reg.set_gauge_max("tpu_program_temp_bytes", 9, site="a")
    assert reg.value("tpu_program_temp_bytes", site="a") == 9


# ---------------------------------------------------------------------------
# 4. the differential matrix: donation on == donation off, bit for bit
# ---------------------------------------------------------------------------
def _donating(extra=None):
    """Session settings for a donating run: host-resident scans make
    every upload exclusive, so certified downstream sites donate."""
    return {"spark.rapids.tpu.sql.inMemoryScan.hostResident": True,
            **(extra or {})}


def _copying(extra=None):
    return {"spark.rapids.tpu.sql.inMemoryScan.hostResident": True,
            "spark.rapids.tpu.sql.donation.enabled": False,
            **(extra or {})}


def _msort(rows):
    """Order-insensitive bit-exact comparison key (rows carry Nones)."""
    return sorted(rows, key=repr)


def _collect_with_counters(build, settings):
    sess = TpuSession(settings)
    snap = donation.snapshot_counters()
    rows = build(sess).collect()
    return rows, donation.counters_since(snap), sess


@pytest.mark.parametrize("strategy", AGG_STRATEGIES)
def test_agg_matrix_donation_differential(strategy):
    n = 900
    data = {"k": [i % 17 for i in range(n)],
            "a": [None if i % 13 == 0 else i * 3 for i in range(n)],
            "b": [i / 7.0 - 20.0 for i in range(n)]}
    schema = schema_of(k=T.INT, a=T.LONG, b=T.DOUBLE)

    def build(s):
        return (s.create_dataframe(data, schema)
                .where(E.GreaterThanOrEqual(col("a"), lit(0)))
                .group_by("k")
                .agg(A.agg(A.Sum(col("a")), "sa"),
                     A.agg(A.Min(col("a")), "mn"),
                     A.agg(A.Max(col("b")), "mx"),
                     A.agg(A.Count(col("b")), "cb")))

    st = {"spark.rapids.tpu.sql.agg.strategy": strategy}
    on_rows, on_don, _ = _collect_with_counters(build, _donating(st))
    off_rows, off_don, _ = _collect_with_counters(build, _copying(st))
    # bit-exact: identical program modulo aliasing, so == not approx
    assert _msort(on_rows) == _msort(off_rows), strategy
    assert sum(on_don.values()) > 0, (strategy, on_don)
    assert off_don == {}, (strategy, off_don)


@pytest.mark.parametrize("strategy", JOIN_STRATEGIES)
def test_join_matrix_donation_differential(strategy):
    n = 700
    ldata = {"k": [i % 29 for i in range(n)],
             "a": [None if i % 19 == 0 else i for i in range(n)]}
    rdata = {"k2": [i % 11 for i in range(29)],
             "b": [i * 10 for i in range(29)]}
    lsch = schema_of(k=T.INT, a=T.LONG)
    rsch = schema_of(k2=T.INT, b=T.LONG)

    def build(s):
        return s.create_dataframe(ldata, lsch).join(
            s.create_dataframe(rdata, rsch), on=[("k", "k2")],
            how="inner")

    st = {"spark.rapids.tpu.sql.join.strategy": strategy}
    on_rows, on_don, _ = _collect_with_counters(build, _donating(st))
    off_rows, off_don, _ = _collect_with_counters(build, _copying(st))
    assert _msort(on_rows) == _msort(off_rows), strategy
    assert sum(on_don.values()) > 0, (strategy, on_don)
    assert off_don == {}, (strategy, off_don)


def test_donation_under_forced_splits_agg():
    """Injected OOM forces split-and-retry through a donating dispatch:
    the guard's snapshot/restore must hand the retry bit-identical
    input planes (diffed against the CPU oracle)."""
    n = 1200
    data = {"k": [i % 13 for i in range(n)],
            "a": [None if i % 9 == 0 else i for i in range(n)]}
    schema = schema_of(k=T.INT, a=T.LONG)

    def build(s):
        return (s.create_dataframe(data, schema).group_by("k")
                .agg(A.agg(A.Sum(col("a")), "sa"),
                     A.agg(A.Count(None), "c")))

    want = build(
        TpuSession({"spark.rapids.tpu.sql.enabled": False})).collect()
    got, don, _ = _collect_with_counters(build, _donating({
        "spark.rapids.tpu.test.faults.oom": "TpuHashAggregateExec>256",
        **NO_BACKOFF}))
    compare_rows(want, got)
    inj = faults.active()
    assert inj is not None and inj.fired(), \
        "fault never fired — the split path was not exercised"
    assert sum(don.values()) > 0, don


def test_donation_under_forced_splits_join():
    n = 800
    ldata = {"k": [i % 23 for i in range(n)],
             "a": [None if i % 17 == 0 else i for i in range(n)]}
    rdata = {"k2": [i % 9 for i in range(23)],
             "b": [i * 10 for i in range(23)]}
    lsch = schema_of(k=T.INT, a=T.LONG)
    rsch = schema_of(k2=T.INT, b=T.LONG)

    def build(s):
        return s.create_dataframe(ldata, lsch).join(
            s.create_dataframe(rdata, rsch), on=[("k", "k2")],
            how="inner")

    want = build(
        TpuSession({"spark.rapids.tpu.sql.enabled": False})).collect()
    got, don, _ = _collect_with_counters(build, _donating({
        "spark.rapids.tpu.test.faults.oom":
            "TpuShuffledHashJoinExec*>256",
        **NO_BACKOFF}))
    compare_rows(want, got, ignore_order=True)
    inj = faults.active()
    assert inj is not None and inj.fired()
    assert sum(don.values()) > 0, don


def test_donation_surfaces_events_and_explain_metrics():
    """Every donating dispatch lands in the event log (site/op/bytes),
    the obs counter mapping, and the per-operator donatedBytes metric
    explain_metrics() renders."""
    n = 600
    data = {"k": [i % 7 for i in range(n)],
            "v": [i * 2 for i in range(n)]}
    schema = schema_of(k=T.INT, v=T.LONG)
    rows, don, sess = _collect_with_counters(
        lambda s: (s.create_dataframe(data, schema)
                   .where(E.GreaterThanOrEqual(col("v"), lit(10)))
                   .select(col("k"),
                           E.Alias(E.Multiply(col("v"), lit(3)), "w"))),
        _donating({"spark.rapids.tpu.eventLog.enabled": True}))
    assert len(rows) == n - 5
    assert sum(don.values()) > 0
    evs = [r for r in sess.events.records() if r["event"] == "donation"]
    assert evs, "donating dispatches emitted no donation events"
    assert sum(r["bytes"] for r in evs) == sum(don.values())
    assert all(r["site"] in donation.DONATION_SPECS for r in evs)
    assert all(r["op"] for r in evs)
    rep = sess.explain_metrics()
    assert "donatedBytes" in rep, rep


# ---------------------------------------------------------------------------
# 5. cache identity: the donate mask is part of the program's name
# ---------------------------------------------------------------------------
def _cache_conf(tmp_path, on=True, hi=2381, mult=5):
    base = {"spark.rapids.tpu.aotCache.dir": str(tmp_path / "aot"),
            "spark.rapids.tpu.sql.inMemoryScan.hostResident": True}
    if not on:
        base["spark.rapids.tpu.sql.donation.enabled"] = False
    return base


def _cache_query(sess, hi, mult):
    data = {"k": [i % 7 for i in range(hi)],
            "v": [i for i in range(hi)]}
    schema = schema_of(k=T.INT, v=T.LONG)
    df = (sess.create_dataframe(data, schema)
          .where(E.GreaterThanOrEqual(col("v"), lit(hi % 97)))
          .select(col("k"),
                  E.Alias(E.Multiply(col("v"), lit(mult)), "w"))
          .group_by("k").agg(A.agg(A.Sum(col("w")), "s")))
    return sorted(df.collect())


def test_warm_aot_zero_miss_and_donating_warm_hit(tmp_path):
    """Warm runs with the same donate mask compile nothing AND still
    donate — jax.export strips donate_argnums, so both AOT probes must
    re-declare the mask the entry key carries. A donation-off caller
    must recompile instead of being served the donating executable."""
    s1 = TpuSession(_cache_conf(tmp_path))
    r1 = _cache_query(s1, 2381, 5)
    st = PC.stats()
    assert st["puts"] >= 1, st
    # simulate the fresh process: empty in-memory pipeline caches
    B.clear_pipeline_caches()
    m0 = B.compile_miss_count()
    snap = donation.snapshot_counters()
    s2 = TpuSession(_cache_conf(tmp_path))
    r2 = _cache_query(s2, 2381, 5)
    assert r2 == r1
    assert B.compile_miss_count() == m0, \
        "warm same-mask run must not compile"
    assert sum(donation.counters_since(snap).values()) > 0, \
        "the deserialized program lost its donation mask"
    # a donation-off caller has a DIFFERENT key: never served the
    # donating entry, so it compiles (and still matches bit-for-bit)
    B.clear_pipeline_caches()
    m1 = B.compile_miss_count()
    s3 = TpuSession(_cache_conf(tmp_path, on=False))
    r3 = _cache_query(s3, 2381, 5)
    assert r3 == r1
    assert B.compile_miss_count() > m1, \
        "donation-off run was served a donating executable"


def test_warm_aot_cross_process_zero_miss(tmp_path):
    """The real cross-process acceptance run: a child process over the
    same AOT dir serves every donating program from disk — zero
    compile misses — and still reports donated bytes."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    prog = (
        "import json, sys\n"
        "sys.path.insert(0, %r)\n"
        "sys.path.insert(0, %r)\n"
        "import spark_rapids_tpu\n"
        "from spark_rapids_tpu.exec import base as B\n"
        "from spark_rapids_tpu.plugin import donation\n"
        "from test_donation import _cache_conf, _cache_query\n"
        "from spark_rapids_tpu.sql import TpuSession\n"
        "import pathlib\n"
        "tmp = pathlib.Path(%r)\n"
        "sess = TpuSession(_cache_conf(tmp))\n"
        "rows = _cache_query(sess, 2381, 5)\n"
        "print(json.dumps({'misses': B.compile_miss_count(),\n"
        "                  'donated': sum(donation"
        ".snapshot_counters().values()),\n"
        "                  'nrows': len(rows)}))\n"
    ) % (REPO, os.path.join(REPO, "tests"), str(tmp_path))
    # the parent seeds the cache dir
    s1 = TpuSession(_cache_conf(tmp_path))
    r1 = _cache_query(s1, 2381, 5)
    out = subprocess.run([sys.executable, "-c", prog], env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    import json
    got = json.loads(out.stdout.strip().splitlines()[-1])
    assert got["misses"] == 0, got
    assert got["donated"] > 0, got
    assert got["nrows"] == len(r1)


# ---------------------------------------------------------------------------
# 6. witness-on serve stress (the CI chaos gate rides this test)
# ---------------------------------------------------------------------------
def test_witness_serve_stress_zero_violations():
    """4 sessions x 4 donating queries with the runtime witness armed
    via the conf entry: every dispatch's donation really happened (the
    witness raises into the query otherwise) and results stay exact."""
    from spark_rapids_tpu.memory.catalog import BufferCatalog
    from spark_rapids_tpu.serve import QueryScheduler, SharedPlanCache

    settings = _donating({
        "spark.rapids.tpu.serve.enabled": True,
        "spark.rapids.tpu.tools.donation.witness.enabled": True,
    })
    QueryScheduler.reset(RapidsConf(settings))
    SharedPlanCache.reset()
    BufferCatalog.reset(RapidsConf(settings))

    n = 1024
    data = {"k": [i % 7 for i in range(n)],
            "v": [i for i in range(n)]}
    schema = schema_of(k=T.INT, v=T.LONG)

    def q(sess, mult):
        return (sess.create_dataframe(data, schema)
                .where(E.GreaterThanOrEqual(col("v"), lit(100)))
                .select(col("k"),
                        E.Alias(E.Multiply(col("v"), lit(mult)), "w"))
                .group_by("k").agg(A.agg(A.Sum(col("w")), "s")))

    want = {m: sorted(q(TpuSession(
        {"spark.rapids.tpu.sql.enabled": False}), m).collect())
        for m in range(2, 7)}
    errors, lock = [], threading.Lock()
    snap = donation.snapshot_counters()

    def worker(ti):
        try:
            sess = TpuSession(settings)
            for qi in range(4):
                m = 2 + (ti * 4 + qi) % 5
                got = sorted(q(sess, m).collect())
                assert got == want[m]
        except Exception as e:  # pragma: no cover - the failure mode
            with lock:
                errors.append((ti, repr(e)))

    try:
        ths = [threading.Thread(target=worker, args=(ti,),
                                name=f"donation-stress-{ti}")
               for ti in range(4)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(180)
        assert not errors, errors
        assert donation.witness_enabled(), \
            "the conf entry did not arm the witness"
        assert sum(donation.counters_since(snap).values()) > 0, \
            "stress never donated — the witness gate proved nothing"
    finally:
        donation.uninstall_witness()
        QueryScheduler.reset()
        SharedPlanCache.reset()
        BufferCatalog.reset()
        EV.uninstall()
        obs.shutdown()
