"""Shuffle layer tests: partitioners, serializer, exchange execs,
multi-partition plans through the planner.

Reference analog: GpuPartitioningSuite / GpuSinglePartitioningSuite,
GpuColumnarBatchSerializer round-trips, and the join/aggregate integration
tests that exercise GpuShuffleExchangeExec.
"""
import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.eval import ColV
from spark_rapids_tpu.ops import hashing
from spark_rapids_tpu.shuffle.partition import (
    HashPartitioning,
    RangePartitioning,
    RoundRobinPartitioning,
    SinglePartitioning,
    partition_cols,
)
from spark_rapids_tpu.shuffle.serializer import (
    deserialize_batch,
    serialize_batch,
)

from harness import assert_tpu_and_cpu_equal, compare_rows


# ---------------------------------------------------------------------------
# partition kernel
# ---------------------------------------------------------------------------
def test_partition_cols_offsets_and_stability():
    cap, n, P = 64, 50, 4
    rng = np.random.default_rng(0)
    pids = rng.integers(0, P, cap).astype(np.int32)
    data = np.arange(cap, dtype=np.int64)
    cols, offsets = partition_cols(
        [ColV(jnp.asarray(data), jnp.ones(cap, bool))],
        jnp.asarray(pids), n, P)
    offsets = np.asarray(offsets)
    out = np.asarray(cols[0].data)
    assert offsets[P] == n
    for j in range(P):
        rows = out[offsets[j]: offsets[j + 1]]
        want = [i for i in range(n) if pids[i] == j]
        assert list(rows) == want  # stable within partition


def test_hash_partitioning_matches_spark_pmod():
    # partition ids must be pmod(murmur3(key), n) — bit-exact vs the
    # hashing kernel (itself differentially tested against Spark vectors)
    cap = 32
    keys = np.array([0, 1, -5, 7, 42, 2**31 - 1, -(2**31), 13] * 4, np.int32)
    col = ColV(jnp.asarray(keys), jnp.ones(cap, bool))
    schema = T.StructType([T.StructField("k", T.INT)])
    part = HashPartitioning([0], 5)
    pids = np.asarray(part.partition_ids(
        [col], schema, jnp.ones(cap, bool), 0))
    h = np.asarray(hashing.murmur3([col], [T.INT]))
    want = ((h % 5) + 5) % 5
    assert (pids == want).all()


def test_round_robin_covers_all_partitions():
    schema = T.StructType([T.StructField("k", T.INT)])
    part = RoundRobinPartitioning(3)
    pids = np.asarray(part.partition_ids(
        [ColV(jnp.zeros(9, jnp.int32), jnp.ones(9, bool))],
        schema, jnp.ones(9, bool), map_index=1))
    assert sorted(set(pids.tolist())) == [0, 1, 2]
    assert (np.bincount(pids, minlength=3) == 3).all()


def test_range_partitioning_orders_partitions():
    from spark_rapids_tpu.ops.sort import SortOrder

    cap = 64
    keys = np.linspace(-100, 100, cap).astype(np.int64)
    rng = np.random.default_rng(1)
    rng.shuffle(keys)
    col = ColV(jnp.asarray(keys), jnp.ones(cap, bool))
    schema = T.StructType([T.StructField("k", T.LONG)])
    part = RangePartitioning([0], [SortOrder(True, None)], 4,
                             bounds=[[-50, 0, 50]])
    pids = np.asarray(part.partition_ids(
        [col], schema, jnp.ones(cap, bool), 0))
    for k, p in zip(keys, pids):
        want = 0 if k < -50 else 1 if k < 0 else 2 if k < 50 else 3
        assert p == want, (k, p, want)


def test_range_partitioning_null_bounds():
    from spark_rapids_tpu.ops.sort import SortOrder

    # nulls sort first (ASC): null bound separates nulls from values
    keys = np.array([5, -3, 0, 7], np.int64)
    valid = np.array([True, False, True, False])
    col = ColV(jnp.asarray(keys), jnp.asarray(valid))
    schema = T.StructType([T.StructField("k", T.LONG)])
    part = RangePartitioning([0], [SortOrder(True, None)], 2, bounds=[[None]])
    pids = np.asarray(part.partition_ids(
        [col], schema, jnp.ones(4, bool), 0))
    # nulls <= null bound -> partition 1? Spark: bound is inclusive-left;
    # null rows compare equal to the null bound -> partition 1; non-null
    # rows are greater than a null bound -> partition 1 too... except the
    # semantics we implement: pid = #bounds <= row; null == null -> 1,
    # values > null -> 1. Everything lands right of a null bound.
    assert (pids == 1).all()


# ---------------------------------------------------------------------------
# serializer
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("codec", ["none", "zstd"])
def test_serializer_round_trip(codec):
    schema = T.StructType([
        T.StructField("i", T.INT),
        T.StructField("l", T.LONG),
        T.StructField("d", T.DOUBLE),
        T.StructField("b", T.BOOLEAN),
        T.StructField("s", T.STRING),
    ])
    data = {
        "i": [1, None, -7, 2**31 - 1],
        "l": [None, 2**40, -1, 0],
        "d": [1.5, float("nan"), None, -0.0],
        "b": [True, False, None, True],
        "s": ["héllo", "", None, "x" * 300],
    }
    b = ColumnarBatch.from_pydict(data, schema)
    wire = serialize_batch(b, codec)
    back = deserialize_batch(wire)
    assert back.schema.names == schema.names
    got = back.to_rows()
    want = b.to_rows()
    compare_rows(want, got, ignore_order=False)


def test_serializer_empty_batch():
    schema = T.StructType([T.StructField("i", T.INT)])
    b = ColumnarBatch.from_pydict({"i": []}, schema)
    back = deserialize_batch(serialize_batch(b))
    assert back.num_rows == 0
    assert back.to_rows() == []


# ---------------------------------------------------------------------------
# exchange through the planner (differential, multi-partition inputs)
# ---------------------------------------------------------------------------
def _rand_kv(n, nkeys, seed, null_frac=0.1):
    rnd = random.Random(seed)
    return {
        "k": [
            rnd.randint(0, nkeys) if rnd.random() > null_frac else None
            for _ in range(n)
        ],
        "v": [
            rnd.randint(-1000, 1000) if rnd.random() > null_frac else None
            for _ in range(n)
        ],
    }


_KV_SCHEMA = T.StructType(
    [T.StructField("k", T.INT), T.StructField("v", T.LONG)])


@pytest.mark.parametrize("parts", [2, 4])
def test_partitioned_aggregate_through_exchange(parts):
    data = _rand_kv(800, 30, seed=parts)

    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(data, _KV_SCHEMA, num_partitions=parts)
        .group_by("k")
        .agg(A.agg(A.Sum(E.col("v")), "s"), A.agg(A.Count(E.col("v")), "c"),
             A.agg(A.Min(E.col("v")), "mn"), A.agg(A.Max(E.col("v")), "mx")),
    )


def test_partitioned_grand_aggregate_single_exchange():
    data = _rand_kv(500, 10, seed=7)
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(data, _KV_SCHEMA, num_partitions=3)
        .agg(A.agg(A.Sum(E.col("v")), "s"), A.agg(A.Count(E.col("v")), "c")),
    )


def test_partitioned_sort_through_range_exchange():
    data = _rand_kv(600, 200, seed=11)
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(data, _KV_SCHEMA, num_partitions=4)
        .order_by("k"),
        ignore_order=False,
    )


@pytest.mark.parametrize("how", ["inner", "left", "right", "full", "semi", "anti"])
def test_partitioned_join_through_exchange(how):
    left = _rand_kv(400, 40, seed=13)
    right_schema = T.StructType(
        [T.StructField("k", T.INT), T.StructField("w", T.LONG)])
    rnd = random.Random(17)
    right = {
        "k": [rnd.randint(0, 40) for _ in range(120)],
        "w": [rnd.randint(0, 9) for _ in range(120)],
    }

    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(left, _KV_SCHEMA, num_partitions=3)
        .join(s.create_dataframe(right, right_schema, num_partitions=2),
              on="k", how=how),
    )


def test_partitioned_string_groupby_through_exchange():
    words = ["alpha", "beta", "gamma", "", None, "δελτα", "w" * 80]
    rnd = random.Random(23)
    schema = T.StructType(
        [T.StructField("s", T.STRING), T.StructField("v", T.LONG)])
    data = {
        "s": [rnd.choice(words) for _ in range(500)],
        "v": [rnd.randint(0, 100) for _ in range(500)],
    }
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(data, schema, num_partitions=4)
        .group_by("s")
        .agg(A.agg(A.Count(E.col("v")), "c"), A.agg(A.Sum(E.col("v")), "sv")),
    )


def test_exchange_host_transport_and_codec():
    data = _rand_kv(400, 20, seed=29)
    conf = {
        "spark.rapids.tpu.shuffle.transport.class": "host",
        "spark.rapids.tpu.shuffle.compression.codec": "zstd",
    }
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(data, _KV_SCHEMA, num_partitions=3)
        .group_by("k")
        .agg(A.agg(A.Sum(E.col("v")), "s"), A.agg(A.Count(E.col("v")), "c")),
        conf=conf,
    )


def test_shuffle_partitions_conf_sets_reducer_count():
    from spark_rapids_tpu.sql.session import TpuSession

    data = _rand_kv(300, 15, seed=31)
    # reducer-count conf applies to the single-host exchange; the mesh path
    # derives its shard count from the device mesh instead
    s = TpuSession({"spark.rapids.tpu.sql.shuffle.partitions": 7,
                    "spark.rapids.tpu.shuffle.mode": "host"})
    df = s.create_dataframe(data, _KV_SCHEMA, num_partitions=2)
    out = df.group_by("k").agg(A.agg(A.Count(), "c")).collect()
    # find the exchange in the executed plan
    plan = s.last_executed_plan.tree_string()
    assert "n=7" in plan, plan
    s1 = TpuSession()
    out1 = (
        s1.create_dataframe(data, _KV_SCHEMA, num_partitions=1)
        .group_by("k").agg(A.agg(A.Count(), "c")).collect()
    )
    compare_rows(out1, out)
