"""Round-6 mesh SPMD tests: whole-plan absorption, the sharded scan, the
mesh window stage, per-shard plananalysis forecasts + cross-check, the
conf-validated mesh builder, and the MULTICHIP diff gate.

Everything differential: mesh outputs compare against the single-device /
python oracle, and the forecast cross-check must report ZERO violations on
every materialized stage (the same bar MULTICHIP_r06.json commits to).
"""
import json
import os
import sys
import tempfile

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.expressions import col, lit
from spark_rapids_tpu.plugin.plananalysis import (
    cross_check_mesh,
    forecast_mesh,
)
from spark_rapids_tpu.sql import TpuSession

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

ICI = {"spark.rapids.tpu.shuffle.mode": "ici",
       "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": -1}

N_DEV = 8


def _conf(extra=None):
    return RapidsConf({**ICI, **(extra or {})})


def _mesh_stages(root):
    from spark_rapids_tpu.plugin.plananalysis import _mesh_stages_of

    return _mesh_stages_of(root)


def _rows(root):
    out = []
    for p in range(root.num_partitions):
        for b in root.execute_partition(p):
            out.extend(b.to_rows())
    return out


# ---------------------------------------------------------------------------
# sharded scan + whole-plan absorption
# ---------------------------------------------------------------------------
def _agg_plan(conf, parts, schema):
    from spark_rapids_tpu.exec import TpuFilterExec, TpuProjectExec
    from spark_rapids_tpu.exec.mesh import TpuMeshAggregateExec
    from spark_rapids_tpu.exec.scan import MeshShardedScanExec

    scan = MeshShardedScanExec(conf, parts, schema)
    filt = TpuFilterExec(conf, E.GreaterThanOrEqual(col("a"), lit(0)), scan)
    proj = TpuProjectExec(
        conf,
        [col("k"), E.Alias(E.Multiply(col("a"), lit(2)), "a2")], filt)
    return TpuMeshAggregateExec(
        conf, [col("k")],
        [A.agg(A.Sum(col("a2")), "s"), A.agg(A.Count(None), "c")], proj)


def _agg_data(n=4000, n_parts=N_DEV, seed=0):
    from spark_rapids_tpu.columnar.batch import schema_of

    rng = np.random.default_rng(seed)
    k = rng.integers(0, 23, n).astype(np.int32)
    a = rng.integers(-100, 100, n).astype(np.int64)
    schema = schema_of(k=T.INT, a=T.LONG)
    per = (n + n_parts - 1) // n_parts
    parts = []
    for p in range(n_parts):
        lo, hi = p * per, min((p + 1) * per, n)
        parts.append((
            [(k[lo:hi], np.ones(hi - lo, bool)),
             (a[lo:hi], np.ones(hi - lo, bool))], hi - lo))
    return parts, schema, k, a


def _agg_oracle(k, a):
    want = {}
    for kk, aa in zip(k, a):
        if aa < 0:
            continue
        s, c = want.get(int(kk), (0, 0))
        want[int(kk)] = (s + 2 * int(aa), c + 1)
    return sorted((kk, s, c) for kk, (s, c) in want.items())


def test_sharded_scan_whole_plan_agg_differential():
    """scan -> filter -> project -> mesh aggregate as ONE SPMD program fed
    by the sharded scan: results match the python oracle, the chain was
    absorbed, the staging took the no-host-gather path, and the per-shard
    forecast cross-check holds exactly."""
    parts, schema, k, a = _agg_data()
    plan = _agg_plan(_conf(), parts, schema)
    got = sorted(tuple(r) for r in _rows(plan))
    assert got == _agg_oracle(k, a)
    (stage,) = _mesh_stages(plan)
    act = stage.mesh_actuals["staging"]
    assert act["source"] == "sharded_scan"
    fc = forecast_mesh(plan)
    st = fc["stages"][0]
    assert st["staging"]["absorbed_steps"] == [
        "TpuFilterExec", "TpuProjectExec"]
    assert st["staging"]["source"] == "sharded_scan"
    assert cross_check_mesh(plan) == []


def test_whole_plan_off_restores_host_staging():
    """wholePlan.enabled=false: the chain executes on the default device
    and staging gathers through the host — same results."""
    parts, schema, k, a = _agg_data(seed=3)
    conf = _conf(
        {"spark.rapids.tpu.shuffle.mesh.wholePlan.enabled": False})
    plan = _agg_plan(conf, parts, schema)
    got = sorted(tuple(r) for r in _rows(plan))
    assert got == _agg_oracle(k, a)
    (stage,) = _mesh_stages(plan)
    assert stage.mesh_actuals["staging"]["source"] == "host"
    assert cross_check_mesh(plan) == []  # forecast mirrors the host path


def test_agg_exchange_cap_retry_still_correct():
    """More groups per shard than the starting exchange capacity: the
    stage must retry with a doubled cap (observable as extra compiled
    programs within the forecast bound) and still produce exact results."""
    from spark_rapids_tpu.columnar.batch import schema_of

    n = 4096
    rng = np.random.default_rng(7)
    # ~600 distinct groups per shard > the 128-row starting cap
    k = rng.integers(0, 5000, n).astype(np.int32)
    a = rng.integers(0, 100, n).astype(np.int64)
    schema = schema_of(k=T.INT, a=T.LONG)
    per = n // N_DEV
    parts = [
        ([(k[p * per:(p + 1) * per], np.ones(per, bool)),
          (a[p * per:(p + 1) * per], np.ones(per, bool))], per)
        for p in range(N_DEV)
    ]
    conf = _conf(
        {"spark.rapids.tpu.shuffle.mesh.aggExchangeCapacity": 128})
    plan = _agg_plan(conf, parts, schema)
    got = sorted(tuple(r) for r in _rows(plan))
    assert got == _agg_oracle(k, a)
    (stage,) = _mesh_stages(plan)
    assert stage.mesh_actuals["programs"] >= 2  # at least one retry
    assert stage.mesh_actuals["exchange_cap"] > 128
    assert cross_check_mesh(plan) == []


def test_parquet_sharded_scan_through_session():
    """The full product path: a session-planned parquet scan -> filter ->
    grouped aggregate lowers to a mesh stage fed by the sharded parquet
    scan (row groups round-robined onto shards)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    n = 4000
    rng = np.random.default_rng(11)
    q = rng.integers(1, 11, n).astype(np.int32)
    c = rng.integers(0, 50, n).astype(np.int64)
    d = rng.integers(0, 100, n).astype(np.int32)
    tmpd = tempfile.mkdtemp(prefix="srtpu_meshpq_")
    t = pa.table({"q": pa.array(q), "c": pa.array(c), "d": pa.array(d)})
    pq.write_table(t, os.path.join(tmpd, "t.parquet"),
                   row_group_size=n // 16)
    # split per row group (the default 2GB coalescing target would pack
    # this small file into ONE split -> single partition -> no mesh)
    s = TpuSession({**ICI,
                    "spark.rapids.tpu.sql.reader.batchSizeBytes": 2048})
    df = (s.read.parquet(tmpd)
          .where(E.GreaterThanOrEqual(col("d"), lit(50)))
          .group_by("q")
          .agg(A.agg(A.Sum(col("c")), "s"), A.agg(A.Count(None), "n")))
    got = sorted(df.collect())
    want = {}
    for qq, cc, dd in zip(q, c, d):
        if dd < 50:
            continue
        sv, nv = want.get(int(qq), (0, 0))
        want[int(qq)] = (sv + int(cc), nv + 1)
    assert got == sorted((qq, sv, nv) for qq, (sv, nv) in want.items())
    plan = s.last_executed_plan.tree_string()
    assert "TpuMeshAggregateExec" in plan, plan
    root = s.last_executed_plan
    stages = _mesh_stages(root)
    assert stages, plan
    assert stages[0].mesh_actuals["staging"]["source"] == "sharded_scan"
    assert cross_check_mesh(root) == []


def test_mesh_window_differential():
    """The mesh window stage (hash exchange on the partition keys + the
    single-device window body per shard) matches the gather-everything
    single-partition path row for row."""
    from spark_rapids_tpu.expr import windows as W

    n = 1000
    rng = np.random.default_rng(13)
    data = {
        "k": [int(x) for x in rng.integers(0, 17, n)],
        "ts": [int(x) for x in rng.permutation(n)],
        "v": [int(x) for x in rng.integers(0, 50, n)],
    }
    schema = T.StructType([
        T.StructField("k", T.INT), T.StructField("ts", T.LONG),
        T.StructField("v", T.LONG)])

    def query(s):
        spec = W.WindowSpec(
            partition_by=(col("k"),), order_by=(col("ts"),),
            orders=((True, True),))
        return s.create_dataframe(
            data, schema, num_partitions=N_DEV).with_windows(
            W.WindowExpression(A.Sum(col("v")), spec, "rs"),
            W.WindowExpression(W.RowNumber(), spec, "rn"))

    s_mesh = TpuSession(ICI)
    got = sorted(query(s_mesh).collect())
    assert "TpuMeshWindowExec" in s_mesh.last_executed_plan.tree_string()
    s_host = TpuSession({"spark.rapids.tpu.shuffle.mode": "host"})
    want = sorted(query(s_host).collect())
    assert "TpuMeshWindowExec" not in s_host.last_executed_plan.tree_string()
    assert got == want
    assert cross_check_mesh(s_mesh.last_executed_plan) == []


def test_mesh_window_string_partition_falls_back():
    """String partition keys keep the single-partition gather path (the
    mesh window is gated to fixed-width direct references)."""
    from spark_rapids_tpu.expr import windows as W

    data = {"s": ["a", "b", "a", "c"] * 8, "v": list(range(32))}
    schema = T.StructType([
        T.StructField("s", T.STRING), T.StructField("v", T.LONG)])
    s = TpuSession(ICI)
    spec = W.WindowSpec(partition_by=(col("s"),), order_by=(col("v"),),
                        orders=((True, True),))
    df = s.create_dataframe(data, schema, num_partitions=4).with_windows(
        W.WindowExpression(A.Sum(col("v")), spec, "rs"))
    rows = df.collect()
    assert "TpuMeshWindowExec" not in s.last_executed_plan.tree_string()
    assert len(rows) == 32


# ---------------------------------------------------------------------------
# get_mesh conf (mesh.devices)
# ---------------------------------------------------------------------------
def test_get_mesh_conf_cap_and_memoization():
    from spark_rapids_tpu.parallel.mesh import get_mesh

    m2 = get_mesh(conf=RapidsConf({"spark.rapids.tpu.mesh.devices": 2}))
    assert int(m2.devices.size) == 2
    assert get_mesh(2) is m2  # memoized per (count, device identity)
    m_all = get_mesh(conf=RapidsConf({}))
    assert int(m_all.devices.size) == len(__import__("jax").devices())
    # legacy shuffle.meshSize still honored when mesh.devices unset
    m3 = get_mesh(conf=RapidsConf(
        {"spark.rapids.tpu.shuffle.meshSize": 3}))
    assert int(m3.devices.size) == 3
    # mesh.devices wins over meshSize
    m4 = get_mesh(conf=RapidsConf(
        {"spark.rapids.tpu.mesh.devices": 4,
         "spark.rapids.tpu.shuffle.meshSize": 2}))
    assert int(m4.devices.size) == 4


def test_get_mesh_too_many_devices_is_an_error():
    from spark_rapids_tpu.parallel.mesh import get_mesh

    with pytest.raises(ValueError, match="mesh.devices"):
        get_mesh(conf=RapidsConf(
            {"spark.rapids.tpu.mesh.devices": 4096}))


# ---------------------------------------------------------------------------
# per-shard observability: events + Perfetto tracks
# ---------------------------------------------------------------------------
def test_per_shard_spans_and_transfers_in_event_log():
    from spark_rapids_tpu import events as EV

    logger = EV.EventLogger(RapidsConf(
        {"spark.rapids.tpu.eventLog.enabled": True}))
    EV.install(logger)
    try:
        parts, schema, k, a = _agg_data(n=800, seed=21)
        plan = _agg_plan(_conf(), parts, schema)
        _rows(plan)
    finally:
        EV.uninstall()
    recs = logger.records()
    # every emitted field is declared: required by EVENT_TYPES, optional
    # by EVENT_OPTIONAL_FIELDS (the registry stays the source of truth)
    for r in recs:
        et = r.get("event")
        declared = set(EV.EVENT_TYPES[et]) | set(
            EV.EVENT_OPTIONAL_FIELDS.get(et, ())) | {"ts", "event", "tid"}
        assert set(r) <= declared, (et, sorted(set(r) - declared))
    spans = [r for r in recs if r.get("event") == "op_span"
             and r.get("shard") is not None]
    shards = sorted({r["shard"] for r in spans})
    assert shards == list(range(N_DEV))
    xfers = [r for r in recs if r.get("event") == "transfer"
             and r.get("shard") is not None]
    assert sorted({r["shard"] for r in xfers}) == list(range(N_DEV))
    trace = EV.chrome_trace(recs)
    names = {e["args"]["name"] for e in trace["traceEvents"]
             if e.get("ph") == "M"}
    for sh in range(N_DEV):
        assert any(f"[chip {sh}]" in n for n in names), names


# ---------------------------------------------------------------------------
# MULTICHIP diff gate (tools/tpu_profile.py)
# ---------------------------------------------------------------------------
def _multichip_payload(eff=0.6, lowered=True, sharded=True, viol=()):
    return {
        "metric": "mesh_scaling", "n_devices": 8, "scale": 0.25,
        "host_parallelism": 2,
        "per_shape": {
            "agg": {"tpu_ms": 100.0, "device_ms": 80.0,
                    "scaling_efficiency": eff, "mesh_lowered": lowered,
                    "sharded_scan": sharded},
        },
        "forecast_violations": list(viol),
        "ok": not viol,
    }


def test_multichip_diff_flags_efficiency_drop(tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import tpu_profile as TP

    text, bad = TP.diff_multichip(
        _multichip_payload(eff=0.6), _multichip_payload(eff=0.3), 0.2)
    assert bad == 1 and "scaling_efficiency: REGRESSION" in text
    text, bad = TP.diff_multichip(
        _multichip_payload(eff=0.6), _multichip_payload(eff=0.55), 0.2)
    assert bad == 0
    # mesh lowering lost -> structural regression even across scales
    new = _multichip_payload(eff=0.6, lowered=False)
    new["scale"] = 0.01
    text, bad = TP.diff_multichip(_multichip_payload(), new, 0.2)
    assert bad == 1 and "no longer lowers" in text
    # forecast violations in the new run always gate
    text, bad = TP.diff_multichip(
        _multichip_payload(), _multichip_payload(viol=["x"]), 0.2)
    assert bad >= 1 and "forecast violation" in text
    # legacy dry-run old format: structural only, no crash
    text, bad = TP.diff_multichip(
        {"n_devices": 8, "ok": True}, _multichip_payload(), 0.2)
    assert bad == 0


def test_multichip_diff_file_dispatch(tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import tpu_profile as TP

    old = tmp_path / "MULTICHIP_old.json"
    new = tmp_path / "MULTICHIP_new.json"
    old.write_text(json.dumps(_multichip_payload()))
    new.write_text(json.dumps(_multichip_payload()))
    text, bad = TP.run_diff(str(old), str(new), 0.2)
    assert "diff (multichip)" in text
    assert bad == 0
