"""Compiled-program cost plane (spark_rapids_tpu/xla_cost.py) + the
roofline observability riding on it.

Pins the contracts ISSUE 10 introduced:
  1. ``program_cost`` round-trips the JSONL sink with its full schema
     and is emitted EXACTLY ONCE per compile miss — a warm rerun
     (recompile-guard style) emits nothing;
  2. missing-cost-key tolerance: a backend reporting no cost/memory
     analysis degrades every consumer (event, roofline report,
     explain_metrics, bench block) to partial rows, never an error;
  3. the tpu_profile '== roofline ==' section renders achieved GB/s /
     FLOP/s vs peaks, a limiter classification, the
     furthest-below-roofline program, and the analyzer-vs-XLA byte
     delta;
  4. the analyzer-bound vs XLA-bytes cross-check runs on a bounded plan
     (harness records it; XLA above the bound is a lead, not a failure);
  5. zero overhead: with events AND obs off (and FORCE_HARVEST unset)
     cost_analysis is never called and nothing is wrapped;
  6. obs twins: compile-seconds-by-site counter + largest-temp gauge;
  7. Perfetto: program_cost renders as a real duration span on the
     compile track plus a cumulative compile-seconds counter;
  8. --diff: grown XLA bytes / peak temp flag a regression, compile-time
     jitter below the 1ms floor never does, and bench JSONs compare
     hbm_frac_xla only when both runs carry it.
"""
import importlib.util
import json
import os

import pytest

from spark_rapids_tpu import events as EV
from spark_rapids_tpu import obs
from spark_rapids_tpu import xla_cost as XC
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.expressions import col, lit
from spark_rapids_tpu.obs.registry import MetricsRegistry
from spark_rapids_tpu.sql import TpuSession

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "tpu_profile", os.path.join(REPO, "tools", "tpu_profile.py"))
tpu_profile = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(tpu_profile)


@pytest.fixture(autouse=True)
def clean_planes():
    """Every test starts and ends with events/obs uninstalled and the
    harvest hook off (other suites set FORCE_HARVEST via the harness)."""
    EV.uninstall()
    obs.uninstall()
    prev = XC.FORCE_HARVEST
    XC.FORCE_HARVEST = False
    yield
    XC.FORCE_HARVEST = prev
    EV.uninstall()
    obs.uninstall()


def _query(sess, hi=2048, mult=2):
    """The pipeline caches are PROCESS-global: a test that needs a cold
    compile must use a (hi, mult) pair no other test (or suite) has run,
    or it inherits warm programs and harvests nothing."""
    df = (sess.range(0, hi)
          .where(E.GreaterThanOrEqual(col("id"), lit(100)))
          .select(col("id"),
                  E.Alias(E.Multiply(col("id"), lit(mult)), "v"))
          .agg(A.agg(A.Sum(col("v")), "s"), A.agg(A.Count(None), "c")))
    return df.collect()


# ---------------------------------------------------------------------------
# 1. schema + exactly-one-per-miss
# ---------------------------------------------------------------------------
def test_program_cost_schema_roundtrip(tmp_path):
    sess = TpuSession({
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.metrics.deviceSync.enabled": True,
    })
    _query(sess, mult=101)
    with open(sess.events.path) as f:
        recs = [json.loads(line) for line in f]
    costs = [r for r in recs if r["event"] == "program_cost"]
    assert costs, "no program_cost events from a cold session"
    for r in costs:
        # every REQUIRED field present (None allowed — backends differ)
        for field in EV.EVENT_TYPES["program_cost"]:
            assert field in r, f"program_cost missing {field}: {r}"
        assert r["site"] and r["digest"]
        assert r["trace_ms"] >= 0 and r["compile_ms"] >= 0
        # the CPU backend DOES report these two; assert one real harvest
    assert any(r.get("bytes_accessed") for r in costs)
    assert any(r.get("op") for r in costs), "no op attribution"


def test_exactly_one_cost_event_per_compile_miss():
    sess = TpuSession({"spark.rapids.tpu.eventLog.enabled": True})
    _query(sess, mult=102)
    recs = sess.events.records()
    costs = [r for r in recs if r["event"] == "program_cost"]
    misses = [r for r in recs if r["event"] == "compile_miss"]
    assert costs
    # at most one cost event per miss, and no two costs share a digest
    assert len(costs) <= len(misses)
    digests = [r["digest"] for r in costs]
    assert len(digests) == len(set(digests))
    # recompile-guard style: the warm rerun emits NOTHING new
    n = len(costs)
    _query(sess, mult=102)
    costs2 = [r for r in sess.events.records()
              if r["event"] == "program_cost"]
    assert len(costs2) == n, "warm rerun harvested again"


# ---------------------------------------------------------------------------
# 2. missing-key tolerance (the CPU-fallback / exotic-backend contract)
# ---------------------------------------------------------------------------
class _NoCostCompiled:
    def cost_analysis(self):
        raise NotImplementedError("backend reports no cost analysis")

    def memory_analysis(self):
        return None


class _WeirdListCompiled:
    def cost_analysis(self):
        return []  # empty list: some backends return one dict per module

    def memory_analysis(self):
        raise RuntimeError("unsupported")


def test_harvest_tolerates_missing_cost_keys():
    for compiled in (_NoCostCompiled(), _WeirdListCompiled()):
        cost = XC.harvest_compiled(compiled)
        for field in XC.COST_FIELDS:
            assert cost[field] is None
    # a record built from the degraded harvest still emits + reports
    logger = EV.EventLogger(ring_size=64, path=None)
    logger.enabled = True
    EV.install(logger)
    XC.note_program_cost("degraded_site", "d00d", 1_000_000, 2_000_000,
                         XC.harvest_compiled(_NoCostCompiled()), op="OpX")
    (rec,) = [r for r in logger.records() if r["event"] == "program_cost"]
    assert rec["bytes_accessed"] is None and rec["temp_bytes"] is None
    # the roofline section degrades to a partial row, not an error
    lines = tpu_profile.roofline_section([rec], [])
    text = "\n".join(lines)
    assert "degraded_site" in text
    assert "no byte/flop cost keys" in text


# ---------------------------------------------------------------------------
# 3. roofline golden render
# ---------------------------------------------------------------------------
def _mk(event, **kw):
    kw.setdefault("ts", _mk.ts)
    _mk.ts += 1000
    kw["event"] = event
    return kw


_mk.ts = 1_000_000


def test_roofline_section_golden():
    events = [
        _mk("program_cost", site="fused_chain", digest="aaa", backend="cpu",
            trace_ms=10.0, compile_ms=20.0, flops=4.0e6,
            bytes_accessed=8.0e6, temp_bytes=1 << 20,
            argument_bytes=1 << 10, output_bytes=1 << 10,
            op="TpuProjectExec"),
        _mk("program_cost", site="agg_plan", digest="bbb", backend="cpu",
            trace_ms=5.0, compile_ms=15.0, flops=2.0e9,
            bytes_accessed=1.0e6, temp_bytes=2 << 20,
            argument_bytes=1 << 10, output_bytes=1 << 10,
            op="TpuHashAggregateExec"),
        # device lanes: project 8ms, aggregate 2ms
        _mk("op_span", op="TpuProjectExec", section="", start=0,
            dur=8_000_000, lane="device"),
        _mk("op_span", op="TpuHashAggregateExec", section="", start=0,
            dur=2_000_000, lane="device"),
    ]
    queries = [{"analysis": {"bytes_by_op": {"TpuProjectExec": 4_000_000}},
                "events": events, "query_id": 1}]
    lines = tpu_profile.roofline_section(
        events, queries, peak_gbps=100.0, peak_tflops=1.0)
    text = "\n".join(lines)
    assert "== roofline ==" in text
    # project: 8e6 bytes / 8e6 ns = 1 GB/s = 1% of 100 GB/s peak;
    # flops 4e6/8e6ns = 0.5 GFLOP/s = 0.05% of 1 TFLOP/s -> bandwidth
    assert ("site=fused_chain op=TpuProjectExec programs=1 "
            "compile=30.0ms" in text)
    assert "achieved[device]=1.000GB/s (1.00% of peak)" in text
    assert "-> bandwidth-limited" in text
    # aggregate: 2e9 flops / 2e6 ns = 1000 GFLOP/s = 100% of 1 TFLOP/s;
    # bytes 1e6/2e6ns = 0.5GB/s = 0.5% -> compute
    assert "-> compute-limited" in text
    # analyzer delta: XLA 8MB > bound 4MB names the lead
    assert ("TpuProjectExec: XLA touches 8.00MB > analyzer bound 4.00MB"
            in text)
    assert "materialized intermediates" in text
    # project is furthest below roofline (1% < 100%)
    assert "furthest below roofline: fused_chain at 1.00% of peak" in text


def test_roofline_peaks_stay_in_sync_with_engine():
    # the offline tool duplicates BACKEND_PEAKS to avoid importing jax;
    # the engine's table is the source of truth
    assert tpu_profile.BACKEND_PEAKS == XC.BACKEND_PEAKS


def test_report_includes_roofline_from_live_log():
    sess = TpuSession({
        "spark.rapids.tpu.eventLog.enabled": True,
        "spark.rapids.tpu.metrics.deviceSync.enabled": True,
    })
    _query(sess, mult=103)
    text, violations = tpu_profile.build_report(sess.events.records())
    assert violations == 0
    assert "== roofline ==" in text
    assert "site=" in text.split("== roofline ==")[1].split("==")[0], (
        "roofline section empty on a cold run:\n" + text)


# ---------------------------------------------------------------------------
# 4. analyzer-bound vs XLA-bytes cross-check on a bounded plan
# ---------------------------------------------------------------------------
def test_bounded_plan_cross_check_records_xla_vs_analyzer():
    from tests.harness import assert_tpu_and_cpu_equal

    captured = []

    def build(sess):
        captured.append(sess)
        return (sess.range(0, 777)
                .select(col("id"),
                        E.Alias(E.Multiply(col("id"), lit(37)), "w")))

    assert_tpu_and_cpu_equal(build)
    # build runs for the CPU session, THE TPU SESSION, and possibly an
    # elision-off differential session — the cross-check lands on #2
    sess = captured[1]
    comp = getattr(sess, "last_xla_vs_analyzer", None)
    assert comp, "harness did not record the XLA-vs-analyzer comparison"
    for op, (xla_bytes, bound) in comp.items():
        assert xla_bytes > 0
        # bounds exist for the fully-modeled ops of this bounded plan
        if bound is not None:
            assert bound > 0


# ---------------------------------------------------------------------------
# 5. zero overhead when events + obs are both off
# ---------------------------------------------------------------------------
def test_zero_overhead_no_cost_analysis_when_off(monkeypatch):
    calls = []

    def spy(compiled):
        calls.append(compiled)
        return {k: None for k in XC.COST_FIELDS}

    monkeypatch.setattr(XC, "harvest_compiled", spy)
    wrapped = []
    orig_wrap = XC.wrap

    def wrap_spy(built, site, key):
        out = orig_wrap(built, site, key)
        if out is not built:
            wrapped.append(site)
        return out

    monkeypatch.setattr(XC, "wrap", wrap_spy)
    sess = TpuSession({})  # defaults: everything off
    rows = _query(sess, hi=4096, mult=104)
    assert rows[0][1] == 3996
    assert calls == [], "cost_analysis harvested while planes off"
    assert wrapped == [], f"CostProbe wrapped while planes off: {wrapped}"


# ---------------------------------------------------------------------------
# 6. obs twins
# ---------------------------------------------------------------------------
def test_obs_twins_compile_seconds_and_temp_gauge():
    reg = MetricsRegistry()
    obs.install(reg)
    try:
        sess = TpuSession({})
        _query(sess, hi=8192, mult=105)
        sites = [k for k in reg.snapshot().get("tpu_compile_seconds", {})]
        assert any("phase=trace" in s for s in sites), sites
        assert any("phase=compile" in s for s in sites), sites
        temps = reg.snapshot().get("tpu_program_temp_bytes", {})
        assert temps, "largest-temp gauge never set"
        # high-water semantics: a smaller write never lowers the gauge
        site = next(iter(temps))
        label = site.split("=", 1)[1]
        before = temps[site]
        reg.set_gauge_max("tpu_program_temp_bytes", before - 1, site=label)
        assert reg.value("tpu_program_temp_bytes", site=label) == before
    finally:
        obs.uninstall()


def test_program_cost_has_live_twin_declared():
    from spark_rapids_tpu.obs.registry import EVENT_BACKED_METRICS, METRICS

    fam = EVENT_BACKED_METRICS["program_cost"]
    assert fam in METRICS


# ---------------------------------------------------------------------------
# 7. Perfetto: compile spans + cumulative compile-seconds counter
# ---------------------------------------------------------------------------
def test_perfetto_compile_track_and_counter():
    sess = TpuSession({"spark.rapids.tpu.eventLog.enabled": True})
    _query(sess, mult=106)
    trace = EV.chrome_trace(sess.events.records())
    spans = [e for e in trace["traceEvents"]
             if e.get("ph") == "X"
             and str(e.get("name", "")).startswith("compile:")]
    assert spans, "compile misses still invisible in the trace"
    for s in spans:
        assert s["dur"] > 0
        assert s["args"]["trace_ms"] is not None
    counters = [e for e in trace["traceEvents"]
                if e.get("ph") == "C" and e["name"] == "compile_seconds"]
    assert len(counters) == len(spans)
    secs = [c["args"]["seconds"] for c in counters]
    assert secs == sorted(secs) and secs[-1] > 0  # cumulative


# ---------------------------------------------------------------------------
# 8. --diff gates
# ---------------------------------------------------------------------------
def _cost_ev(site, bytes_, temp, compile_ms=10.0, ts=1):
    return {"ts": ts, "event": "program_cost", "site": site, "digest": "d",
            "backend": "cpu", "trace_ms": 1.0, "compile_ms": compile_ms,
            "flops": 1.0, "bytes_accessed": bytes_, "temp_bytes": temp,
            "argument_bytes": 0, "output_bytes": 0}


def test_diff_flags_grown_xla_bytes_and_temp():
    old = [_cost_ev("agg_plan", 1.0e6, 1 << 20)]
    new = [_cost_ev("agg_plan", 2.0e6, 1 << 20)]
    text, n = tpu_profile.diff_logs(old, new, threshold=0.2)
    assert n == 1 and "agg_plan.xla_bytes: REGRESSION" in text
    new_temp = [_cost_ev("agg_plan", 1.0e6, 4 << 20)]
    text, n = tpu_profile.diff_logs(old, new_temp, threshold=0.2)
    assert n == 1 and "agg_plan.peak_temp: REGRESSION" in text


def test_diff_ignores_compile_jitter_below_noise_floor():
    # 0.4ms -> 0.9ms is >2x but under the 1ms floor: jitter, not a
    # regression; bytes/temp identical
    old = [_cost_ev("sort", 1.0e6, 1 << 20, compile_ms=0.4)]
    new = [_cost_ev("sort", 1.0e6, 1 << 20, compile_ms=0.9)]
    text, n = tpu_profile.diff_logs(old, new, threshold=0.2)
    assert n == 0, text
    # but a REAL compile blowup (10ms -> 100ms) flags
    big = [_cost_ev("sort", 1.0e6, 1 << 20, compile_ms=100.0)]
    old10 = [_cost_ev("sort", 1.0e6, 1 << 20, compile_ms=10.0)]
    text, n = tpu_profile.diff_logs(old10, big, threshold=0.2)
    assert n == 1 and "sort.compile: REGRESSION" in text


def test_diff_bench_compares_hbm_frac_xla_when_present():
    old = {"per_shape": {"agg": {"tpu_ms": 100.0, "hbm_frac_xla": 0.10}}}
    new = {"per_shape": {"agg": {"tpu_ms": 100.0, "hbm_frac_xla": 0.02}}}
    text, n = tpu_profile.diff_bench(old, new, threshold=0.2)
    assert n == 1 and "agg.hbm_frac_xla: REGRESSION" in text
    # a full collapse must fire at CI's --threshold 2.0 too: the gate is
    # ratio-form like the ms gates (a drop-fraction saturates at 1.0 and
    # could never clear 2.0), and small committed fracs (~0.004 on the
    # CPU fallback) sit ABOVE the noise floor
    collapsed = {"per_shape": {"agg": {"tpu_ms": 100.0,
                                       "hbm_frac_xla": 0.0001}}}
    small = {"per_shape": {"agg": {"tpu_ms": 100.0,
                                   "hbm_frac_xla": 0.0038}}}
    text, n = tpu_profile.diff_bench(old, collapsed, threshold=2.0)
    assert n == 1 and "agg.hbm_frac_xla: REGRESSION" in text
    text, n = tpu_profile.diff_bench(small, collapsed, threshold=2.0)
    assert n == 1, text
    # zero new-run frac (device fully idle) is the worst case, not a div0
    zero = {"per_shape": {"agg": {"tpu_ms": 100.0, "hbm_frac_xla": 0.0}}}
    text, n = tpu_profile.diff_bench(old, zero, threshold=2.0)
    assert n == 1, text
    # absent on either side: no gate (the runs aren't comparable)
    new_absent = {"per_shape": {"agg": {"tpu_ms": 100.0}}}
    text, n = tpu_profile.diff_bench(old, new_absent, threshold=0.2)
    assert n == 0, text


# ---------------------------------------------------------------------------
# 9. explain_metrics lane labeling (the satellite fix) + xla columns
# ---------------------------------------------------------------------------
def test_explain_metrics_labels_bandwidth_lane():
    # deviceSync ON: the device lane feeds the column and says so
    sess = TpuSession({
        "spark.rapids.tpu.eventLog.enabled": True,
        "spark.rapids.tpu.metrics.deviceSync.enabled": True,
    })
    _query(sess, mult=107)
    text = sess.explain_metrics()
    assert "hbm_gbps[device]=" in text
    assert "hbm_gbps[host]=" not in text.split("\n")[0]
    # cost plane was on (events): the xla columns and harvest footer ride
    assert "xla_bytes=" in text
    assert "programs harvested:" in text
    # deviceSync OFF: the host lane feeds it and the label SAYS host —
    # an unlabeled figure here silently overstated bandwidth (async
    # dispatch makes host time << device work)
    sess2 = TpuSession({})
    _query(sess2, hi=8192, mult=108)
    text2 = sess2.explain_metrics()
    assert "hbm_gbps[host]=" in text2
    assert "hbm_gbps[device]=" not in text2


def test_format_metrics_prefers_device_lane():
    from spark_rapids_tpu.exec.base import (
        BYTES_TOUCHED,
        OP_TIME_DEVICE,
        TOTAL_TIME,
        TpuExec,
    )
    from spark_rapids_tpu.conf import RapidsConf

    class Dummy(TpuExec):
        @property
        def output_schema(self):
            return None

    node = Dummy(RapidsConf({}))
    node.metric(BYTES_TOUCHED, "bytes").add(10_000_000)
    node.metric(TOTAL_TIME, "ns").add(1_000_000)       # 10 GB/s via host
    from spark_rapids_tpu.exec.base import format_metrics

    text = format_metrics(node)
    assert "hbm_gbps[host]=10.00" in text
    node.metric(OP_TIME_DEVICE, "ns").add(10_000_000)  # 1 GB/s via device
    text = format_metrics(node)
    assert "hbm_gbps[device]=1.00" in text
    assert "hbm_gbps[host]" not in text


# ---------------------------------------------------------------------------
# 10. review fixes: conf peaks reach the offline tool; per-query bounds
# ---------------------------------------------------------------------------
def test_conf_declared_peaks_ride_events_into_roofline():
    # the offline profiler has no RapidsConf — the only channel for the
    # roofline.* confs is the harvested event itself
    sess = TpuSession({
        "spark.rapids.tpu.eventLog.enabled": True,
        "spark.rapids.tpu.roofline.peakHbmGBps": 200.0,
        "spark.rapids.tpu.roofline.peakTflops": 2.0,
    })
    _query(sess, mult=211)
    costs = [r for r in sess.events.records()
             if r["event"] == "program_cost"]
    assert costs and all(r.get("peak_hbm_gbps") == 200.0
                         and r.get("peak_tflops") == 2.0 for r in costs)
    text, _ = tpu_profile.build_report(sess.events.records())
    assert "peaks: 200 GB/s, 2.0 TFLOP/s" in text
    # CLI flags still override the logged peaks
    text, _ = tpu_profile.build_report(sess.events.records(),
                                       peak_gbps=50.0)
    assert "peaks: 50 GB/s" in text
    import spark_rapids_tpu.xla_cost as XC2

    XC2._CONF_PEAKS = None  # don't leak conf peaks into later tests


def test_roofline_analyzer_delta_is_per_query():
    # ten queries, each compiling a 100MB program against a 150MB bound:
    # the old log-wide sum printed 1000MB > 150MB ("+850MB materialized
    # intermediates") for a kernel that materializes nothing
    queries = []
    events = []
    for qid in range(10):
        ev = _mk("program_cost", site="fused_chain", digest=f"q{qid}",
                 backend="cpu", trace_ms=1.0, compile_ms=1.0, flops=1.0,
                 bytes_accessed=100e6, temp_bytes=None,
                 argument_bytes=None, output_bytes=None,
                 op="TpuProjectExec")
        events.append(ev)
        queries.append({"query_id": qid, "events": [ev],
                        "analysis": {"bytes_by_op":
                                     {"TpuProjectExec": 150_000_000}}})
    lines = tpu_profile.roofline_section(events, queries,
                                         peak_gbps=100.0, peak_tflops=1.0)
    text = "\n".join(lines)
    assert "XLA touches 100.00MB <= analyzer bound 150.00MB" in text
    assert "materialized intermediates" not in text


def test_format_metrics_same_class_nodes_print_cost_once():
    import spark_rapids_tpu.xla_cost as XC2
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.exec.base import (
        OP_TIME_DEVICE,
        TpuExec,
        format_metrics,
    )

    class Dummy(TpuExec):
        @property
        def output_schema(self):
            return None

    seq = XC2.snapshot()
    XC2.note_program_cost("fused_chain", "d1", 1000, 1000,
                          {"bytes_accessed": 8.0e6, "flops": 1.0},
                          op="Dummy")
    parent = Dummy(RapidsConf({}))
    child = Dummy(RapidsConf({}))
    parent.children = [child]
    parent.metric(OP_TIME_DEVICE, "ns").add(1_000_000)
    child.metric(OP_TIME_DEVICE, "ns").add(1_000_000)
    text = format_metrics(parent, cost_since=seq)
    # the class-wide harvest prints on ONE line, and with two Dummy
    # nodes no single device lane is the right denominator for it
    assert text.count("xla_bytes=8.0MB") == 1, text
    assert "xla_gbps" not in text, text


def test_roofline_shared_op_sites_get_one_combined_line():
    # agg_update and agg_plan both attribute to TpuHashAggregateExec:
    # each site dividing its bytes by the op's WHOLE device lane would
    # double-count time and understate both rows — the group gets ONE
    # combined achieved line over the summed bytes instead
    events = [
        _mk("program_cost", site="agg_update", digest="u", backend="cpu",
            trace_ms=1.0, compile_ms=1.0, flops=1.0e6,
            bytes_accessed=6.0e6, temp_bytes=None, argument_bytes=None,
            output_bytes=None, op="TpuHashAggregateExec"),
        _mk("program_cost", site="agg_plan", digest="p", backend="cpu",
            trace_ms=1.0, compile_ms=1.0, flops=1.0e6,
            bytes_accessed=2.0e6, temp_bytes=None, argument_bytes=None,
            output_bytes=None, op="TpuHashAggregateExec"),
        _mk("op_span", op="TpuHashAggregateExec", section="", start=0,
            dur=4_000_000, lane="device"),
    ]
    lines = tpu_profile.roofline_section(events, [], peak_gbps=100.0,
                                         peak_tflops=1.0)
    text = "\n".join(lines)
    # no per-site achieved figures for the shared op ...
    for line in text.splitlines():
        if line.strip().startswith("site="):
            assert "achieved" not in line, line
    # ... one combined line: (6e6+2e6) bytes / 4e6 ns = 2 GB/s
    assert ("op=TpuHashAggregateExec sites=agg_plan+agg_update "
            "achieved[device]=2.000GB/s (2.00% of peak)" in text), text
    assert ("furthest below roofline: TpuHashAggregateExec "
            "(agg_plan+agg_update)" in text), text
