"""Must-catch fixture: manifest lock held across a blocking boundary
(TPU104) — the teardown/mid-scrape stall shape.

Waiting on a future (or a host sync) while holding a hierarchy lock
stalls every other acquirer behind the wait. tpu_racecheck must flag
``wait_under_lock`` (direct ``.result()``) and ``sync_under_lock``
(host_pull reached through a call edge) with TPU104, and must NOT flag
``wait_outside_lock``.
"""
from spark_rapids_tpu.utils.locks import ordered_lock

_CACHE_LOCK = ordered_lock("serve.plan_cache")


def wait_under_lock(fut):
    with _CACHE_LOCK:
        return fut.result()          # every other acquirer stalls here


def _drain(dev):
    from spark_rapids_tpu.runtime import host_pull

    return host_pull(dev)


def sync_under_lock(dev):
    with _CACHE_LOCK:
        return _drain(dev)           # blocking through the call edge


def wait_outside_lock(fut):
    out = fut.result()
    with _CACHE_LOCK:
        return out
