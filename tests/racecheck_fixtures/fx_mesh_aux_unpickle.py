"""Must-catch fixture: the PR 15 mesh-aux unpickle outside the
corruption guard.

The AOT store's mesh-aux sidecar was probed with ``.get`` and, on miss,
deserialized and inserted into the shared table outside the guard that
serializes corruption recovery — two loaders could interleave and one
would publish a half-validated aux. tpu_racecheck must flag
``aux_for`` with TPU102.
"""
import pickle
from concurrent.futures import ThreadPoolExecutor  # noqa: F401 — pool users

_MESH_AUX: dict = {}


def aux_for(key, blob):
    entry = _MESH_AUX.get(key)       # check: no guard held
    if entry is None:
        entry = pickle.loads(blob)
        _MESH_AUX[key] = entry       # act: publishes unvalidated aux
    return entry
