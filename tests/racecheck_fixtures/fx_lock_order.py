"""Must-catch fixture: manifest lock-order inversion (TPU101).

LOCK_ORDER only permits acquiring DOWNWARD (outermost rank 0 first).
``inverted`` takes the scheduler lock while already holding the
lower-ranked plan-cache lock — an upward acquisition that deadlocks
against any downward path. tpu_racecheck must flag ``inverted`` with
TPU101 and must NOT flag ``forward`` (a distinct, downward pair, so no
cycle forms between the two functions either).
"""
from spark_rapids_tpu.utils.locks import ordered_lock

_PLAN = ordered_lock("sql.plan")
_SCHED = ordered_lock("serve.scheduler")
_CACHE = ordered_lock("serve.plan_cache")


def forward():
    with _PLAN:
        with _CACHE:     # downward: rank(sql.plan) < rank(serve.plan_cache)
            pass


def inverted():
    with _CACHE:
        with _SCHED:     # upward: scheduler outranks the plan cache
            pass
