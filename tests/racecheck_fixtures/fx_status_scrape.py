"""Must-catch fixture: the /status mid-scrape mutation.

The status endpoint's refresher thread rewrote the shared snapshot dict
in place while the HTTP handler iterated it — a RuntimeError (dict
changed size during iteration) under load. tpu_racecheck must flag
``_refresh`` with TPU103 (module-global mutation from a thread-run
function with no lock held).
"""
import threading

_SNAPSHOT: dict = {}


def _refresh():
    _SNAPSHOT["queued"] = 0          # unlocked write from the thread
    _SNAPSHOT.update(scrape())


def scrape():
    return {"running": 1}


def start_refresher():
    t = threading.Thread(target=_refresh, daemon=True)
    t.start()
    return t
