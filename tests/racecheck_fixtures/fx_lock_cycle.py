"""Must-catch fixture: raw-lock cycle (TPU101).

Two undeclared ``threading`` locks acquired in opposite orders by two
functions — the classic AB/BA deadlock. Neither lock is in the
manifest, so rank checks can't see it; the cycle detector on the full
static acquire graph must.
"""
import threading

_A = threading.Lock()
_B = threading.Lock()


def ab():
    with _A:
        with _B:
            pass


def ba():
    with _B:
        with _A:
            pass
