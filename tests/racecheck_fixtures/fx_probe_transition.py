"""Must-catch fixture: the PR 10 probe-lock fallback transition race.

The AOT-cache load probe flipped ``self._fallback`` after observing it
clear WITHOUT holding the probe lock, so a concurrent prober could
re-enter the transition and double-drain the in-flight table.
tpu_racecheck must flag ``note_corruption`` with TPU102 (the class owns
a lock, so unlocked attr check-then-act is in scope) and must NOT flag
``note_corruption_fixed``.
"""
import threading


class LoadProbe:
    def __init__(self):
        self._lock = threading.Lock()
        self._fallback = False
        self._inflight: dict = {}

    def note_corruption(self, key):
        if not self._fallback:        # check: probe lock not held
            self._fallback = True     # act: racing transition
            self._inflight.clear()

    def note_corruption_fixed(self, key):
        with self._lock:
            if not self._fallback:
                self._fallback = True
                self._inflight.clear()
