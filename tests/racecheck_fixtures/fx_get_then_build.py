"""Must-catch fixture: the PR 9 get-then-build pipeline-cache race.

Every process-global pipeline cache in the audit had this exact shape:
check the dict, miss, build, insert — with no lock, so two threads both
miss and both compile. tpu_racecheck must flag ``pipeline_for`` with
TPU102 and must NOT flag ``pipeline_for_fixed`` (double-checked under
the module lock — the cached_pipeline shape).
"""
import threading

_PIPELINES: dict = {}
_LOCK = threading.Lock()


def pipeline_for(key, build):
    if key not in _PIPELINES:        # check: no lock held
        _PIPELINES[key] = build()    # act: a second thread raced us here
    return _PIPELINES[key]


def pipeline_for_fixed(key, build):
    with _LOCK:
        if key not in _PIPELINES:
            _PIPELINES[key] = build()
        return _PIPELINES[key]
