"""Round-3 operator gap tests: CollectLimit, CartesianProduct, Generate,
bounded ROWS window frames, size-thresholded broadcast hash join
(reference: limit.scala:126, GpuCartesianProductExec.scala:304,
GpuGenerateExec, GpuWindowExpression.scala:451, shim GpuBroadcastHashJoinExec).
"""
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr import windows as W
from spark_rapids_tpu.expr.expressions import col, lit
from spark_rapids_tpu.sql import TpuSession

from harness import assert_tpu_and_cpu_equal, compare_rows

SCHEMA = T.StructType([
    T.StructField("k", T.INT), T.StructField("a", T.LONG),
    T.StructField("b", T.DOUBLE),
])


def _data(n=300):
    return {
        "k": [i % 7 if i % 13 else None for i in range(n)],
        "a": [i * 3 - n if i % 11 else None for i in range(n)],
        "b": [i / 7.0 if i % 5 else None for i in range(n)],
    }


def make_df(s, n=300, parts=3):
    return s.create_dataframe(_data(n), SCHEMA, num_partitions=parts)


# ---------------------------------------------------------------------------
# CollectLimit
# ---------------------------------------------------------------------------
def test_collect_limit_is_global():
    sess = TpuSession()
    rows = make_df(sess, 300, 4).limit(50).collect()
    assert len(rows) == 50
    assert "TpuCollectLimitExec" in sess.last_executed_plan.tree_string()


def test_collect_limit_differential():
    assert_tpu_and_cpu_equal(
        lambda s: make_df(s, 120, 3).limit(40), ignore_order=False)
    assert_tpu_and_cpu_equal(lambda s: make_df(s, 30, 2).limit(100))


def test_local_limit_still_available():
    sess = TpuSession()
    rows = make_df(sess, 300, 3).local_limit(10).collect()
    assert len(rows) == 30  # 10 per partition


# ---------------------------------------------------------------------------
# CartesianProduct / cross join
# ---------------------------------------------------------------------------
def test_cartesian_product_differential():
    def build(s):
        left = s.create_dataframe(
            {"x": [1, 2, 3, None]}, T.StructType([T.StructField("x", T.INT)]),
            num_partitions=2)
        right = s.create_dataframe(
            {"y": [10, 20, 30]}, T.StructType([T.StructField("y", T.INT)]))
        return left.cross_join(right)

    rows = assert_tpu_and_cpu_equal(build)
    assert len(rows) == 12


def test_cartesian_plan_name():
    sess = TpuSession()
    l = sess.create_dataframe({"x": [1, 2]},
                              T.StructType([T.StructField("x", T.INT)]))
    r = sess.create_dataframe({"y": [3]},
                              T.StructType([T.StructField("y", T.INT)]))
    l.cross_join(r).collect()
    assert "TpuCartesianProductExec" in sess.last_executed_plan.tree_string()


def test_cross_join_with_condition():
    def build(s):
        l = s.create_dataframe({"x": list(range(20))},
                               T.StructType([T.StructField("x", T.INT)]))
        r = s.create_dataframe({"y": list(range(10))},
                               T.StructType([T.StructField("y", T.INT)]))
        return l.cross_join(r, condition=E.GreaterThan(col("x"), col("y")))

    assert_tpu_and_cpu_equal(build)


# ---------------------------------------------------------------------------
# Generate / explode
# ---------------------------------------------------------------------------
def test_explode_values_differential():
    def build(s):
        return make_df(s, 100, 2).explode(
            [col("a"), E.Multiply(col("a"), lit(2)), lit(7)], name="v")

    assert_tpu_and_cpu_equal(build)


def test_posexplode_differential():
    def build(s):
        return make_df(s, 60, 2).explode(
            [col("a"), col("k")], name="v", pos=True)

    rows = assert_tpu_and_cpu_equal(build)
    assert {r[3] for r in rows} == {0, 1}  # pos column


def test_generate_output_schema():
    sess = TpuSession()
    df = make_df(sess, 20, 1).explode([col("a"), lit(1)], name="v", pos=True)
    assert [f.name for f in df.schema.fields] == ["k", "a", "b", "pos", "v"]
    assert len(df.collect()) == 40


# ---------------------------------------------------------------------------
# bounded ROWS window frames
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("lo,hi", [(-2, 0), (-1, 1), (0, 2), (-3, -1), (1, 3)])
def test_bounded_rows_frames(lo, hi):
    frame = W.WindowFrame(W.ROWS, lo, hi)
    spec = W.WindowSpec(
        partition_by=(col("k"),), order_by=(col("a"),),
        orders=((True, True),), frame=frame)

    def build(s):
        return make_df(s, 200, 1).with_windows(
            W.WindowExpression(A.Sum(col("a")), spec, "rs"),
            W.WindowExpression(A.Min(col("a")), spec, "mn"),
            W.WindowExpression(A.Max(col("a")), spec, "mx"),
            W.WindowExpression(A.Count(col("a")), spec, "cn"),
        )

    assert_tpu_and_cpu_equal(build)


def test_bounded_rows_average():
    frame = W.WindowFrame(W.ROWS, -3, 3)
    spec = W.WindowSpec(partition_by=(col("k"),), order_by=(col("a"),),
                        orders=((True, True),), frame=frame)

    def build(s):
        return make_df(s, 150, 1).with_windows(
            W.WindowExpression(A.Average(col("b")), spec, "av"))

    assert_tpu_and_cpu_equal(
        build, approx_float=True,
        conf={"spark.rapids.tpu.sql.variableFloatAgg.enabled": True})


def test_bounded_rows_current_row_sentinels():
    # ROWS BETWEEN 2 PRECEDING AND CURRENT ROW via the sentinel
    frame = W.WindowFrame(W.ROWS, -2, W.CURRENT_ROW)
    spec = W.WindowSpec(partition_by=(col("k"),), order_by=(col("a"),),
                        orders=((True, True),), frame=frame)

    def build(s):
        return make_df(s, 120, 1).with_windows(
            W.WindowExpression(A.Sum(col("a")), spec, "rs"))

    assert_tpu_and_cpu_equal(build)


# ---------------------------------------------------------------------------
# size-thresholded broadcast hash join
# ---------------------------------------------------------------------------
def test_small_side_broadcasts():
    sess = TpuSession()
    big = make_df(sess, 400, 4)
    dim = sess.create_dataframe(
        {"k2": list(range(7)), "w": [i * 10 for i in range(7)]},
        T.StructType([T.StructField("k2", T.INT), T.StructField("w", T.LONG)]),
        num_partitions=2)
    big.join(dim, on=[("k", "k2")]).collect()
    plan = sess.last_executed_plan.tree_string()
    assert "TpuBroadcastExchangeExec" in plan
    assert "TpuShuffleExchangeExec" not in plan
    assert "TpuMeshAggregateExec" not in plan


@pytest.mark.parametrize("how", ["inner", "left", "semi", "anti", "right"])
def test_broadcast_join_differential(how):
    def build(s):
        big = make_df(s, 300, 3)
        dim = s.create_dataframe(
            {"k2": [0, 1, 2, 3, None], "w": [0, 10, 20, 30, 40]},
            T.StructType([T.StructField("k2", T.INT),
                          T.StructField("w", T.LONG)]),
            num_partitions=2)
        if how == "right":
            return dim.join(big, on=[("k2", "k")], how="right")
        return big.join(dim, on=[("k", "k2")], how=how)

    assert_tpu_and_cpu_equal(build)


def test_threshold_disable_keeps_exchanges():
    sess = TpuSession({"spark.rapids.tpu.sql.autoBroadcastJoinThreshold": -1,
                       "spark.rapids.tpu.shuffle.mode": "host"})
    big = make_df(sess, 200, 3)
    dim = sess.create_dataframe(
        {"k2": [1, 2], "w": [1, 2]},
        T.StructType([T.StructField("k2", T.INT), T.StructField("w", T.LONG)]),
        num_partitions=2)
    big.join(dim, on=[("k", "k2")]).collect()
    assert "TpuShuffleExchangeExec" in sess.last_executed_plan.tree_string()
