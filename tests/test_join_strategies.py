"""Tiered join lowerings (round 14: kill the 29.8x join byte
amplification).

Coverage, per the issue checklist:
  * the five-tier differential matrix — AUTO / SEARCH / DIRECT / RADIX
    (+ PALLAS via interpret mode off-TPU) — over every join type and the
    torture inputs: all-null keys, NaN keys (NaN==NaN, -0.0==0.0),
    duplicate-heavy builds (the RADIX fused fast path must decline its
    uniqueness precondition and fall to the general co-sort), empty
    build/probe sides, and non-pow2 radix-agg tiles over a join output
    (FORCE_TILE_ROWS);
  * ops-level bit-identity: radix_probe_ranges' [lo, hi) — including
    insertion points for unmatched rows — and the matched-build mask
    equal the binary-search baseline everywhere, and
    radix_expansion_plan's pair list equals the repeat-based plan on
    every live slot;
  * ZERO scatter instructions in every RADIX-tier program (the compiled
    probe, the matched variant, the fused lo/matched variant, and the
    expansion), pinned through the hlo.py classifier;
  * forced-strategy recompile guards: a rerun of a RADIX join compiles
    nothing;
  * splits under fault injection (faults.py oom channel) for the new
    tiers, row-exact vs the CPU oracle;
  * the chooser: forced values, the CPU AUTO flip at build cap 2^16,
    the accelerator cost model against conf-declared roofline peaks,
    the legacy pallasProbe toggle, and the 'join_strategy' event +
    describe() visibility.
"""
import numpy as np
import pytest

import spark_rapids_tpu  # noqa: F401  (x64 enable)
import jax
import jax.numpy as jnp

from spark_rapids_tpu import faults
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import schema_of
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.exec import base as exec_base
from spark_rapids_tpu.exec.join import (
    TpuShuffledHashJoinExec,
    choose_join_strategy,
)
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.expressions import col, lit
from spark_rapids_tpu.hlo import summarize_hlo
from spark_rapids_tpu.ops import join as J
from spark_rapids_tpu.ops import radix_bin as RBX
from spark_rapids_tpu.sql import TpuSession

from harness import compare_rows

STRATEGIES = ("AUTO", "SEARCH", "DIRECT", "RADIX", "PALLAS")
JOIN_TYPES = ("inner", "left", "right", "full", "semi", "anti")


# ---------------------------------------------------------------------------
# ops-level bit-identity: co-sorted merge vs binary search
# ---------------------------------------------------------------------------
def _sorted_build(rng, nb, bcount, nwords, lo_card=50):
    """Build words with a lexicographically sorted joinable prefix and
    garbage beyond it (the exec sorts exactly like this)."""
    ws = [rng.integers(0, lo_card, nb).astype(np.uint32)]
    for _ in range(nwords - 1):
        ws.append(rng.integers(0, 3, nb).astype(np.uint32))
    order = np.lexsort(tuple(w[:bcount] for w in reversed(ws)))
    for w in ws:
        w[:bcount] = w[:bcount][order]
    return ws


def test_ops_radix_ranges_bitidentical_vs_search():
    rng = np.random.default_rng(3)
    for trial in range(8):
        nb = int(rng.integers(1, 400))
        m = int(rng.integers(1, 600))
        bcount = int(rng.integers(0, nb + 1))
        nwords = 1 + trial % 3
        bws = _sorted_build(rng, nb, bcount, nwords)
        pws = [rng.integers(0, 70, m).astype(np.uint32)] + [
            rng.integers(0, 3, m).astype(np.uint32)
            for _ in range(nwords - 1)
        ]
        live = rng.random(m) < 0.8
        args = ([jnp.asarray(w) for w in bws], jnp.int32(bcount),
                [jnp.asarray(w) for w in pws], jnp.asarray(live))
        lo0, hi0 = J._probe_binary_search(*args)
        lo1, hi1, matched = J.radix_probe_ranges(*args, want_matched=True)
        np.testing.assert_array_equal(np.asarray(lo0), np.asarray(lo1),
                                      err_msg=f"trial {trial} lo")
        np.testing.assert_array_equal(np.asarray(hi0), np.asarray(hi1),
                                      err_msg=f"trial {trial} hi")
        want_m = np.asarray(J.matched_build_mask(
            lo0, hi0, jnp.asarray(live), nb))
        np.testing.assert_array_equal(want_m, np.asarray(matched),
                                      err_msg=f"trial {trial} matched")
        # the fused lo/matched variant: same lo, matched == (hi > lo)
        lo2, hi2, _ = J.radix_probe_ranges(*args, lo_matched_only=True)
        has = np.asarray(hi0 > lo0)
        np.testing.assert_array_equal(np.asarray(lo2)[has],
                                      np.asarray(lo0)[has])
        np.testing.assert_array_equal(np.asarray(hi2 > lo2), has)


def test_ops_radix_ranges_dead_probe_and_empty_sides():
    one = jnp.asarray(np.array([7], np.uint32))
    # empty joinable build: every probe reports [0, 0)
    lo, hi, m = J.radix_probe_ranges(
        [one], jnp.int32(0), [jnp.asarray(np.array([7, 9], np.uint32))],
        jnp.asarray(np.array([True, True])), want_matched=True)
    assert np.asarray(lo).tolist() == [0, 0]
    assert np.asarray(hi).tolist() == [0, 0]
    assert not np.asarray(m).any()
    # dead probe rows always report [0, 0), whatever their words
    lo, hi, _ = J.radix_probe_ranges(
        [one], jnp.int32(1), [one], jnp.asarray(np.array([False])))
    assert np.asarray(lo).tolist() == [0] and np.asarray(hi).tolist() == [0]


def test_ops_radix_expansion_identical_on_live_slots():
    rng = np.random.default_rng(9)
    counts = jnp.asarray(rng.integers(0, 4, 300).astype(np.int32))
    lo = jnp.asarray(np.cumsum(rng.integers(0, 3, 300)).astype(np.int32))
    out_cap = 1024
    p0, b0, s0 = J.expansion_plan(counts, lo, out_cap)
    p1, b1, s1 = J.radix_expansion_plan(counts, lo, out_cap)
    live = np.asarray(s0)
    np.testing.assert_array_equal(live, np.asarray(s1))
    np.testing.assert_array_equal(np.asarray(p0)[live], np.asarray(p1)[live])
    np.testing.assert_array_equal(np.asarray(b0)[live], np.asarray(b1)[live])


def test_ops_radix_programs_have_zero_scatters():
    rng = np.random.default_rng(1)
    nb, m = 256, 512
    bws = [jnp.asarray(np.sort(rng.integers(0, 99, nb).astype(np.uint32)))]
    pws = [jnp.asarray(rng.integers(0, 99, m).astype(np.uint32))]
    live = jnp.ones(m, bool)
    variants = {
        "ranges": lambda: jax.jit(
            lambda *a: J.radix_probe_ranges(*a)).lower(
                bws, jnp.int32(nb), pws, live).compile(),
        "matched": lambda: jax.jit(
            lambda *a: J.radix_probe_ranges(*a, want_matched=True)).lower(
                bws, jnp.int32(nb), pws, live).compile(),
        "fused": lambda: jax.jit(
            lambda *a: J.radix_probe_ranges(
                *a, lo_matched_only=True)).lower(
                bws, jnp.int32(nb), pws, live).compile(),
        "expansion": lambda: jax.jit(
            lambda c, l: J.radix_expansion_plan(c, l, 1024)).lower(
                jnp.zeros(m, jnp.int32), jnp.zeros(m, jnp.int32)).compile(),
    }
    for name, build in variants.items():
        s = summarize_hlo(build().as_text(), top_k=64)
        assert s["scatter_count"] == 0, (name, s["top_fusions"])


# ---------------------------------------------------------------------------
# exec-level five-tier matrix vs the CPU oracle
# ---------------------------------------------------------------------------
def _torture_datasets():
    """(name, left data+schema, right data+schema) torture inputs. Small
    on purpose: the CPU oracle join is O(n^2)."""
    ln, rn = 72, 29
    lsch = schema_of(k=T.INT, a=T.LONG)
    rsch = schema_of(k2=T.INT, b=T.LONG)
    fsch_l = schema_of(k=T.DOUBLE, a=T.LONG)
    fsch_r = schema_of(k2=T.DOUBLE, b=T.LONG)
    unique = ({"k": [i % 40 if i % 11 else None for i in range(ln)],
               "a": [(i * 7) % 50 - 25 for i in range(ln)]}, lsch,
              {"k2": [i if i % 7 else None for i in range(rn)],
               "b": [i * 3 for i in range(rn)]}, rsch)
    dup = (unique[0], lsch,
           {"k2": [i % 5 if i % 7 else None for i in range(rn)],
            "b": [i * 3 for i in range(rn)]}, rsch)
    allnull = (unique[0], lsch,
               {"k2": [None] * rn, "b": [i for i in range(rn)]}, rsch)
    nan = ({"k": [float("nan") if i % 5 == 0 else
                  (-0.0 if i % 5 == 1 else float(i % 9))
                  for i in range(ln)],
            "a": [i for i in range(ln)]}, fsch_l,
           {"k2": [float("nan") if i % 4 == 0 else
                   (0.0 if i % 4 == 1 else float(i % 12))
                   for i in range(rn)],
            "b": [i * 3 for i in range(rn)]}, fsch_r)
    empty_build = (unique[0], lsch, {"k2": [], "b": []}, rsch)
    empty_probe = ({"k": [], "a": []}, lsch, unique[2], rsch)
    return [("unique", *unique), ("dup", *dup), ("allnull", *allnull),
            ("nan", *nan), ("empty_build", *empty_build),
            ("empty_probe", *empty_probe)]


@pytest.mark.parametrize("strategy", [
    # RADIX (the new tier) and DIRECT (the fused incumbent) run in the
    # budgeted tier-1 sweep; the rest ride the CI pallas job, which runs
    # this file unfiltered
    "RADIX", "DIRECT",
    pytest.param("AUTO", marks=pytest.mark.slow),
    pytest.param("SEARCH", marks=pytest.mark.slow),
    pytest.param("PALLAS", marks=pytest.mark.slow),
])
def test_exec_join_matrix_vs_cpu_oracle(strategy):
    datasets = _torture_datasets()
    cpu_sess = TpuSession({"spark.rapids.tpu.sql.enabled": False})
    tpu_sess = TpuSession(
        {"spark.rapids.tpu.sql.join.strategy": strategy})

    def build(s, ds, how):
        _, ld, lsch, rd, rsch = ds
        return s.create_dataframe(ld, lsch).join(
            s.create_dataframe(rd, rsch), on=[("k", "k2")], how=how)

    for ds in datasets:
        for how in JOIN_TYPES:
            want = build(cpu_sess, ds, how).collect()
            got = build(tpu_sess, ds, how).collect()
            compare_rows(want, got, ignore_order=True,
                         approx_float=True)


def test_join_feeding_radix_agg_non_pow2_tiles():
    """Join output through a forced-RADIX aggregate on non-divisor tile
    sizes (FORCE_TILE_ROWS): the radix-binned agg must reduce the join's
    masked/fused output exactly, multi-tile + flush paths included."""
    n, d = 700, 37
    rng = np.random.default_rng(21)
    ldata = {"k": [int(x) for x in rng.integers(0, d, n)],
             "v": [int(x) for x in rng.integers(-100, 100, n)]}
    rdata = {"k2": list(range(d)),
             "g": [i % 6 for i in range(d)]}
    lsch = schema_of(k=T.INT, v=T.LONG)
    rsch = schema_of(k2=T.INT, g=T.INT)
    from spark_rapids_tpu.expr import aggregates as A

    def build(s):
        j = s.create_dataframe(ldata, lsch).join(
            s.create_dataframe(rdata, rsch), on=[("k", "k2")], how="inner")
        return j.group_by("g").agg(A.agg(A.Sum(col("v")), "sv"),
                                   A.agg(A.Count(None), "c"))

    want = build(TpuSession({"spark.rapids.tpu.sql.enabled": False})).collect()
    prev = RBX.FORCE_TILE_ROWS
    try:
        for tile in (96, 160):
            RBX.FORCE_TILE_ROWS = tile
            got = build(TpuSession({
                "spark.rapids.tpu.sql.join.strategy": "RADIX",
                "spark.rapids.tpu.sql.agg.strategy": "RADIX"})).collect()
            compare_rows(want, got, ignore_order=True)
    finally:
        RBX.FORCE_TILE_ROWS = prev


# ---------------------------------------------------------------------------
# fused fast path + recompile guards
# ---------------------------------------------------------------------------
def _exec_join(conf_dict, ldata, lsch, rdata, rsch, how="inner"):
    from spark_rapids_tpu.columnar import ColumnarBatch
    from spark_rapids_tpu.exec import InMemoryScanExec

    conf = RapidsConf(conf_dict)
    lb = ColumnarBatch.from_pydict(ldata, lsch)
    rb = ColumnarBatch.from_pydict(rdata, rsch)
    return TpuShuffledHashJoinExec(
        conf, InMemoryScanExec(conf, [[lb]], lsch),
        InMemoryScanExec(conf, [[rb]], rsch),
        [col("k")], [col("k2")], how)


_L = {"k": [i % 29 for i in range(120)], "a": list(range(120))}
_LS = schema_of(k=T.INT, a=T.LONG)
_RU = {"k2": list(range(29)), "b": [i * 2 for i in range(29)]}
_RD = {"k2": [i % 4 for i in range(29)], "b": [i * 2 for i in range(29)]}
_RS = schema_of(k2=T.INT, b=T.LONG)


def test_radix_unique_build_takes_fused_fast_path():
    j = _exec_join({"spark.rapids.tpu.sql.join.strategy": "RADIX"},
                   _L, _LS, _RU, _RS)
    rows = j.collect()
    st = j._fast_built
    assert isinstance(st, dict) and st["kind"] == "radix", st
    assert j._join_strategy_choice[0] == "RADIX"
    assert "strategy=RADIX" in j.describe()
    assert len(rows) == 120  # every probe row matches its unique key


def test_radix_duplicate_build_declines_fusion_general_path():
    j = _exec_join({"spark.rapids.tpu.sql.join.strategy": "RADIX"},
                   _L, _LS, _RD, _RS)
    rows = j.collect()
    assert j._fast_built is False  # uniqueness sync said no
    # 120 probe rows x 29/4-ish dup matches, vs the oracle
    o = _exec_join({"spark.rapids.tpu.sql.join.strategy": "SEARCH"},
                   _L, _LS, _RD, _RS)
    compare_rows(o.collect(), rows, ignore_order=True)


def test_forced_radix_join_compiles_once():
    j = _exec_join({"spark.rapids.tpu.sql.join.strategy": "RADIX"},
                   _L, _LS, _RU, _RS)
    rows1 = sorted(j.collect())
    before = exec_base.compile_miss_count()
    rows2 = sorted(j.collect())  # same exec, same shapes: zero compiles
    assert exec_base.compile_miss_count() == before, \
        exec_base.COMPILE_COUNTER.by_site
    assert rows1 == rows2
    # and the memoized choice never flips mid-plan
    assert j._strategy_by_cap == {32: "RADIX"} or len(
        j._strategy_by_cap) == 1


def test_fused_radix_probe_program_has_zero_scatters():
    """Harvest the compiled programs of a RADIX join feeding a RADIX
    aggregate (the bench join-shape topology) and pin ZERO
    scatter-classified instructions across all of them — the acceptance
    criterion of the rewrite."""
    from spark_rapids_tpu import hlo, xla_cost
    from spark_rapids_tpu.exec import TpuHashAggregateExec
    from spark_rapids_tpu.expr import aggregates as A

    prev = xla_cost.FORCE_HARVEST
    xla_cost.FORCE_HARVEST = True
    try:
        seq = hlo.snapshot()
        j = _exec_join({"spark.rapids.tpu.sql.join.strategy": "RADIX",
                        "spark.rapids.tpu.sql.agg.strategy": "RADIX"},
                       {"k": [i % 13 for i in range(500)],
                        "a": list(range(500))}, _LS,
                       {"k2": list(range(13)),
                        "b": [i * 7 for i in range(13)]}, _RS)
        agg = TpuHashAggregateExec(
            j.conf, [col("b")],
            [A.agg(A.Sum(col("a")), "s"), A.agg(A.Count(None), "c")], j)
        agg.collect()
        recs = hlo.records_since(seq)
        assert recs, "no programs harvested"
        assert sum(r.get("scatter_count") or 0 for r in recs) == 0, [
            (r["digest"], r["top_fusions"]) for r in recs
            if r.get("scatter_count")]
    finally:
        xla_cost.FORCE_HARVEST = prev


# ---------------------------------------------------------------------------
# splits under fault injection for the new tiers
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("strategy", ["RADIX", "PALLAS"])
def test_split_and_retry_under_injected_oom(strategy):
    n = 600
    ldata = {"k": [i % 23 for i in range(n)],
             "a": [None if i % 17 == 0 else i for i in range(n)]}
    rdata = {"k2": [i % 9 for i in range(23)],
             "b": [i * 10 for i in range(23)]}
    lsch = schema_of(k=T.INT, a=T.LONG)
    rsch = schema_of(k2=T.INT, b=T.LONG)

    def build(s):
        return s.create_dataframe(ldata, lsch).join(
            s.create_dataframe(rdata, rsch), on=[("k", "k2")], how="inner")

    want = build(TpuSession({"spark.rapids.tpu.sql.enabled": False})).collect()
    sess = TpuSession({
        "spark.rapids.tpu.sql.join.strategy": strategy,
        "spark.rapids.tpu.test.faults.oom": "TpuShuffledHashJoinExec*>256",
        "spark.rapids.tpu.memory.oomRetry.backoffMs": 0,
    })
    try:
        got = build(sess).collect()
        compare_rows(want, got, ignore_order=True)
        inj = faults.active()
        assert inj is not None and inj.fired(), strategy
    finally:
        faults.uninstall()


# ---------------------------------------------------------------------------
# the chooser + visibility surfaces
# ---------------------------------------------------------------------------
def test_chooser_forced_and_auto_branches():
    keys = (T.LONG,)
    forced = RapidsConf({"spark.rapids.tpu.sql.join.strategy": "SEARCH"})
    s, why = choose_join_strategy(forced, 1 << 17, keys, "inner")
    assert s == "SEARCH" and "forced" in why
    auto = RapidsConf({})
    # CPU AUTO: small single-key build -> DIRECT (fusable table), big
    # build -> RADIX (the scatter dialect's charged-byte amplification)
    s, why = choose_join_strategy(auto, 1 << 12, keys, "inner",
                                  backend="cpu")
    assert s == "DIRECT", why
    s, why = choose_join_strategy(auto, 1 << 17, keys, "inner",
                                  backend="cpu")
    assert s == "RADIX" and "29.8x" in why
    # multi-word keys have no direct-address table at any size
    s, _ = choose_join_strategy(auto, 1 << 12, (T.LONG, T.LONG), "inner",
                                backend="cpu")
    assert s == "RADIX"
    # accelerator AUTO: single-key builds keep the fusable direct
    # table; multi-word keys are costed against the conf-declared
    # roofline peaks, with the search's gather chain priced at the
    # chip's near-serial random-access rate
    s, why = choose_join_strategy(auto, 1 << 17, keys, "inner",
                                  backend="tpu")
    assert s == "DIRECT", why
    s_wide, why_wide = choose_join_strategy(
        auto, 1 << 22, (T.LONG, T.LONG, T.LONG), "inner", backend="tpu")
    assert s_wide == "RADIX", why_wide
    assert "est radix" in why_wide and "GB/s" in why_wide
    # a tiny declared HBM peak makes the sort passes expensive enough
    # that the gather chain wins the same shape
    slow_hbm = RapidsConf(
        {"spark.rapids.tpu.roofline.peakHbmGBps": 0.05})
    s_slow, why_slow = choose_join_strategy(
        slow_hbm, 1 << 22, (T.LONG, T.LONG, T.LONG), "inner",
        backend="tpu")
    assert s_slow == "SEARCH", why_slow
    # legacy toggle: pallasProbe forces the PALLAS tier under AUTO
    legacy = RapidsConf(
        {"spark.rapids.tpu.sql.join.pallasProbe.enabled": True})
    s, why = choose_join_strategy(legacy, 1 << 12, keys, "inner")
    assert s == "PALLAS" and "legacy" in why


def test_strategy_visible_in_events_and_explain():
    sess = TpuSession({"spark.rapids.tpu.eventLog.enabled": True,
                       "spark.rapids.tpu.sql.join.strategy": "RADIX"})
    ldf = sess.create_dataframe(_L, _LS)
    rdf = sess.create_dataframe(_RU, _RS)
    rows = ldf.join(rdf, on=[("k", "k2")], how="inner").collect()
    assert len(rows) == 120
    evs = [r for r in sess.events.records()
           if r.get("event") == "join_strategy"]
    assert evs, "join_strategy event not emitted"
    assert evs[0]["strategy"] == "RADIX"
    assert evs[0]["build_cap"] >= 29
    assert "forced" in evs[0]["reason"]


def test_plananalysis_forecasts_join_strategy():
    sess = TpuSession({"spark.rapids.tpu.sql.join.strategy": "RADIX"})
    ldf = sess.create_dataframe(_L, _LS)
    rdf = sess.create_dataframe(_RU, _RS)
    text = ldf.join(rdf, on=[("k", "k2")], how="inner").explain()
    assert "join strategy: RADIX" in text, text


def test_profiler_join_strategy_section():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "tpu_profile", os.path.join(
            os.path.dirname(__file__), "..", "tools", "tpu_profile.py"))
    tp = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tp)
    events = [
        {"event": "join_strategy", "ts": 0, "op": "TpuShuffledHashJoinExec",
         "strategy": "RADIX", "reason": "forced by conf",
         "build_cap": 1 << 15},
    ]
    text = tp.build_report(events)
    if isinstance(text, tuple):  # (report, violation count)
        text = text[0]
    assert "== join strategy ==" in text
    assert "TpuShuffledHashJoinExec[build_cap=32768]: RADIX" in text


def test_string_key_join_mismatched_length_buckets():
    """String join keys derive their chunk-word counts from EACH side's
    own max-length bucket; pad_key_words zero-extends the shorter side
    (exact — beyond-bucket chunks are all zero), so a probe key equal
    to a build key's PREFIX must not match it. CPU AUTO routes string
    keys to RADIX, which crashed (or truncation-matched) before the
    round-14 review fix; SEARCH silently compared only the common
    prefix."""
    ldata = {"k": ["abcd", "abcdXYZw", "ab", None, "abcd"],
             "a": [1, 2, 3, 4, 5]}
    rdata = {"k2": ["abcd", "abcdXYZwLONGTAIL", "zz", None],
             "b": [10, 20, 30, 40]}
    lsch = schema_of(k=T.STRING, a=T.LONG)
    rsch = schema_of(k2=T.STRING, b=T.LONG)

    def build(s):
        return s.create_dataframe(ldata, lsch).join(
            s.create_dataframe(rdata, rsch), on=[("k", "k2")], how="left")

    want = build(TpuSession({"spark.rapids.tpu.sql.enabled": False})).collect()
    for strategy in ("AUTO", "SEARCH", "RADIX"):
        got = build(TpuSession({
            "spark.rapids.tpu.sql.join.strategy": strategy})).collect()
        compare_rows(want, got, ignore_order=True)
    # ops-level: the padded word lists reconstruct the longer encoding
    from spark_rapids_tpu.ops.join import pad_key_words

    bw = [jnp.zeros(8, jnp.uint32)] * 3
    pw = [jnp.ones(4, jnp.uint32)]
    b2, p2 = pad_key_words(bw, pw)
    assert len(b2) == len(p2) == 3
    assert p2[1].shape == (4,) and not np.asarray(p2[1]).any()


def test_legacy_pallas_toggle_keeps_direct_fused_fast_path():
    """sql.join.pallasProbe.enabled predates the strategy conf and only
    ever governed the GENERAL probe path — the DIRECT fused fast path
    pre-empted it. The AUTO resolution must preserve that (the conf's
    keep-their-behavior contract), while a FORCED strategy=PALLAS does
    disable the fast path."""
    legacy = {"spark.rapids.tpu.sql.join.pallasProbe.enabled": True}
    j = _exec_join(legacy, _L, _LS, _RU, _RS)
    rows = j.collect()
    assert isinstance(j._fast_built, dict) and \
        j._fast_built["kind"] == "direct", j._fast_built
    o = _exec_join({"spark.rapids.tpu.sql.join.strategy": "SEARCH"},
                   _L, _LS, _RU, _RS)
    compare_rows(o.collect(), rows, ignore_order=True)
    forced = {"spark.rapids.tpu.sql.join.strategy": "PALLAS"}
    j2 = _exec_join(forced, _L, _LS, _RU, _RS)
    rows2 = j2.collect()
    assert j2._fast_built is False
    compare_rows(rows, rows2, ignore_order=True)
