"""HBM ledger: per-buffer lifecycle attribution + leak sentinel.

Pins this PR's acceptance contracts:
  1. zero-overhead-off: with events+obs off and no force arm, a full
     register/spill/unspill/close lifecycle builds NO ledger record and
     touches NO registry method (the PR 5/6 contract, mirrored);
  2. lifecycle round-trip: every registered buffer emits buffer_alloc
     with its owner tag (op, query id, creation site, origin digest),
     bid-stamped spill/unspill hops, and buffer_free with a reason; the
     query-end sweep emits heap_snapshot — and tools/tpu_heap.py
     reconstructs the same peak/churn/ownership story from the log;
  3. the leak sentinel flags a deliberately-pinned buffer at query end
     (ledger, watchdog alert, live counter) and stays quiet for clean
     queries, declared plan state, scan-cache entries, reservations;
  4. close is idempotent and a spilled buffer's free reconciles (no
     double-free, no phantom device-live bytes);
  5. attribution holds under concurrent sessions: records carry the
     owning thread's (tid, query_id);
  6. the admission feed (ROADMAP 5a): swept per-query peaks fold into
     the per-digest history the serve scheduler consumes, and admission
     events carry forecast_source.
"""
import importlib.util
import json
import os
import threading

import jax.numpy as jnp
import pytest

from spark_rapids_tpu import events as EV
from spark_rapids_tpu import obs
from spark_rapids_tpu import xla_cost
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.memory import SpillableHandle, TIER_HOST
from spark_rapids_tpu.memory import ledger as L
from spark_rapids_tpu.memory.catalog import BufferCatalog
from spark_rapids_tpu.memory.ledger import Ledger, query_scope
from spark_rapids_tpu.memory.spillable import SpillableVals
from spark_rapids_tpu.obs.registry import MetricsRegistry
from spark_rapids_tpu.obs.server import build_status
from spark_rapids_tpu.obs.watchdog import (
    Watchdog,
    WatchdogRules,
    replay_alerts,
)
from spark_rapids_tpu.serve import QueryScheduler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


tpu_heap = _load_tool("tpu_heap")
tpu_top = _load_tool("tpu_top")


@pytest.fixture(autouse=True)
def clean_state():
    """Both planes down, no force arm, fresh catalog on both sides."""
    obs.shutdown()
    obs.uninstall()
    EV.uninstall()
    L.force_arm(False)
    BufferCatalog.reset()
    QueryScheduler.reset()
    yield
    obs.shutdown()
    obs.uninstall()
    EV.uninstall()
    L.force_arm(False)
    BufferCatalog.reset()
    QueryScheduler.reset()


def _cat(budget=None):
    conf = {}
    if budget is not None:
        conf["spark.rapids.tpu.memory.hbm.budgetBytes"] = budget
    return BufferCatalog.reset(RapidsConf(conf))


def _handle(cat, nbytes=4096, priority=0, **kw):
    return SpillableHandle(
        {"d": jnp.zeros(nbytes // 4, jnp.int32)}, priority, cat, **kw)


def _logger(tmp_path):
    logger = EV.EventLogger(RapidsConf(
        {"spark.rapids.tpu.eventLog.dir": str(tmp_path)}))
    EV.install(logger)
    return logger


# ---------------------------------------------------------------------------
# 1. zero-overhead-off
# ---------------------------------------------------------------------------
def test_zero_overhead_when_both_planes_off(monkeypatch):
    """The spy: with events+obs off and no force arm, a full lifecycle
    (register -> pressure spill -> unspill -> close) must not build one
    ledger record, emit one event, or touch one registry method."""
    def _boom(name):
        def fail(*a, **k):
            raise AssertionError(f"{name} touched while planes off")
        return fail

    monkeypatch.setattr(Ledger, "note_alloc", _boom("Ledger.note_alloc"))
    monkeypatch.setattr(EV.EventLogger, "emit", _boom("EventLogger.emit"))
    for m in ("inc", "set_gauge", "set_gauge_max", "observe",
              "span_open", "note_compile_miss"):
        monkeypatch.setattr(MetricsRegistry, m, _boom(f"registry.{m}"))

    cat = _cat(budget=10_000)
    assert not cat.ledger.armed()
    low = _handle(cat, 4096, priority=-50)
    high = _handle(cat, 4096)
    third = _handle(cat, 4096, priority=10)  # forces low to spill
    assert low.tier == TIER_HOST
    low.materialize()                        # unspill hop
    for h in (low, high, third):
        h.close()
    st = cat.ledger.stats()
    assert st == {"allocs": 0, "frees": 0, "tracked": 0,
                  "live_bytes": 0, "leaked_live": 0, "leaked_total": 0}
    assert low._lid is None and high._lid is None


# ---------------------------------------------------------------------------
# 2. lifecycle round-trip: events, owner tags, and the offline profiler
# ---------------------------------------------------------------------------
def test_lifecycle_events_round_trip(tmp_path):
    logger = _logger(tmp_path)
    cat = _cat(budget=10_000)
    with query_scope("q1"), xla_cost.op_scope("TpuSortExec"):
        low = _handle(cat, 4096, priority=-50)
        high = _handle(cat, 4096)
        third = _handle(cat, 4096, priority=10)  # low spills to host
        assert low.tier == TIER_HOST
        low.materialize()                        # unspill; high spills
        for h in (low, high, third):
            h.close()
    leaks = cat.ledger.sweep_query("q1", digest="dg-rt")
    assert leaks == []

    recs = logger.records()
    allocs = [r for r in recs if r["event"] == "buffer_alloc"]
    frees = [r for r in recs if r["event"] == "buffer_free"]
    spills = [r for r in recs if r["event"] == "spill"]
    snaps = [r for r in recs if r["event"] == "heap_snapshot"]

    assert len(allocs) == 3 and len(frees) == 3
    for r in allocs:
        assert r["kind"] == "spillable" and r["bytes"] == 4096
        assert r["op"] == "TpuSortExec" and r["query_id"] == "q1"
        assert "test_ledger.py:" in r["site"]
        assert len(r["origin"]) == 12
    # every free names a reason and pairs a recorded alloc by bid
    assert {r["reason"] for r in frees} == {"close"}
    assert {r["bid"] for r in frees} == {r["bid"] for r in allocs}
    # spill hops are bid-stamped: low out, low back in, high out
    assert [(r["kind"], r["bid"] is not None) for r in spills] == [
        ("device_to_host", True), ("unspill", True),
        ("device_to_host", True)]
    assert spills[0]["bid"] == spills[1]["bid"]
    # the sweep's snapshot closes the story: empty heap, nothing leaked
    assert len(snaps) == 1
    assert snaps[0]["query_id"] == "q1" and snaps[0]["leaked"] == 0
    assert snaps[0]["live_bytes"] == 0

    st = cat.ledger.stats()
    assert st["allocs"] == 3 and st["frees"] == 3
    assert st["tracked"] == 0 and st["live_bytes"] == 0

    # the offline profiler reconstructs the same story from the log
    t = tpu_heap.build_timeline(recs)
    assert t.peak_bytes == 12288
    assert t.peak_by_op == {"TpuSortExec": 12288}
    assert t.unattributed_fraction() == 0.0
    assert t.churn_by_op == {"TpuSortExec": 8192}
    assert t.free_reasons == {"close": 3}
    assert t.end_leaks() == [] and t.sentinel_leaks == 0
    report = tpu_heap.build_report(t)
    assert "top owners at peak: TpuSortExec" in report
    assert "no leaks" in report

    # and the watchdog replay twin names the owner when the spill
    # watermark crosses the pressure line (budget 9000 -> limit 7650)
    alerts = replay_alerts(recs, WatchdogRules(), budget=9_000)
    pressure = [a for a in alerts if a.kind == "hbm_pressure"]
    assert len(pressure) == 1  # one episode, not one per spill event
    assert "top owners: TpuSortExec" in pressure[0].detail
    assert not [a for a in alerts if a.kind == "buffer_leak"]


def test_live_gauge_and_leak_counter_twins():
    reg = MetricsRegistry()
    obs.install(reg)
    cat = _cat()
    with query_scope("qg"), xla_cost.op_scope("TpuHashJoinExec"):
        h = _handle(cat, 8192)
        assert reg.value("tpu_hbm_bytes", op="TpuHashJoinExec") == 8192
        h.close()
        assert reg.value("tpu_hbm_bytes", op="TpuHashJoinExec") == 0
        pinned = _handle(cat, 4096)
    assert cat.ledger.sweep_query("qg")  # pinned outlived the query
    assert reg.value("tpu_hbm_leaked_buffers") == 1
    pinned.close()
    assert cat.ledger.stats()["leaked_live"] == 0


# ---------------------------------------------------------------------------
# 3. leak sentinel
# ---------------------------------------------------------------------------
def test_leak_sentinel_flags_pinned_buffer_and_watchdog_alerts():
    L.force_arm(True)
    cat = _cat()
    with query_scope("qA"), xla_cost.op_scope("TpuSortExec"):
        pinned = _handle(cat, 8192)
        closed = _handle(cat, 4096)
        closed.close()
    leaks = cat.ledger.sweep_query("qA")
    assert len(leaks) == 1
    assert leaks[0]["query_id"] == "qA" and leaks[0]["bytes"] == 8192
    assert leaks[0]["op"] == "TpuSortExec"
    assert "test_ledger.py:" in leaks[0]["site"]
    assert cat.ledger.stats()["leaked_live"] == 1
    assert cat.ledger.live_leaks()[0]["lid"] == leaks[0]["lid"]
    # re-sweeping the same query does not double-flag
    assert cat.ledger.sweep_query("qA") == []
    assert cat.ledger.stats()["leaked_total"] == 1

    # the live watchdog surfaces it, naming op/bytes/query
    wd = Watchdog(MetricsRegistry(), WatchdogRules())
    alerts = [a for a in wd.check_now() if a.kind == "buffer_leak"]
    assert len(alerts) == 1 and alerts[0].value == 1
    assert "TpuSortExec" in alerts[0].detail
    assert "qA" in alerts[0].detail
    assert "outlived the owning query" in alerts[0].describe()
    # the alert stays active (not re-raised) while the leak lives...
    assert not wd.check_now()
    # ...and clears when the buffer is actually freed
    pinned.close()
    assert cat.ledger.stats()["leaked_live"] == 0
    wd2 = Watchdog(MetricsRegistry(), WatchdogRules())
    assert not [a for a in wd2.check_now() if a.kind == "buffer_leak"]


def test_sentinel_exempts_declared_plan_state_cache_and_reservations():
    from spark_rapids_tpu.expr.values import ColV

    L.force_arm(True)
    cat = _cat()
    with query_scope("qB"):
        build = _handle(cat, 4096, ledger_kind="plan_state")
        sv = SpillableVals(
            [ColV(jnp.zeros(64, jnp.int64), jnp.ones(64, jnp.bool_))],
            catalog=cat, ledger_kind="plan_state")
        rid = cat.reserve(2048, label="admission")
        cache_lid = cat.ledger.note_alloc(1024, kind=L.KIND_SCAN_CACHE)
    assert cat.ledger.sweep_query("qB") == []
    assert cat.ledger.stats()["leaked_live"] == 0
    # reservations are bookkeeping, not device residency
    assert cat.ledger.snapshot()["live_bytes"] == \
        cat.ledger.stats()["live_bytes"]
    build.close()
    sv.close()
    cat.release_reservation(rid)
    cat.ledger.note_free(cache_lid, reason="evict")
    assert cat.ledger.stats()["tracked"] == 0
    assert cat.ledger.stats()["live_bytes"] == 0


def test_harness_guard_catches_deliberate_leak():
    """The conftest teardown twin: prove it actually trips (then reset
    the catalog ourselves, exactly as a deliberately-leaking test
    must)."""
    L.force_arm(True)
    cat = _cat()
    with query_scope("qX"):
        _handle(cat, 4096)
    cat.ledger.sweep_query("qX")
    assert cat.ledger.stats()["leaked_live"] == 1
    BufferCatalog.reset()  # what the guard demands of a leaking test


# ---------------------------------------------------------------------------
# 4. reconciliation: idempotent close, spilled free, no phantom bytes
# ---------------------------------------------------------------------------
def test_double_close_and_spilled_close_reconcile():
    L.force_arm(True)
    cat = _cat(budget=10_000)
    with query_scope("qC"):
        low = _handle(cat, 4096, priority=-50)
        high = _handle(cat, 4096)
        third = _handle(cat, 4096, priority=10)
        assert low.tier == TIER_HOST  # spilled: off-device in the ledger
        low.close(reason="split")     # freeing a HOST buffer...
        # ...must not deduct device-live bytes it no longer holds
        assert cat.ledger.stats()["live_bytes"] == 8192
        low.close(reason="split")     # idempotent: one free, not two
        assert cat.ledger.stats()["frees"] == 1
        high.close()
        third.close()
    assert cat.ledger.sweep_query("qC") == []
    st = cat.ledger.stats()
    assert st["allocs"] == 3 and st["frees"] == 3
    assert st["live_bytes"] == 0 and st["tracked"] == 0
    assert cat.ledger.snapshot()["by_op"] == {}


def test_concurrent_queries_attribute_by_tid_and_query_id():
    L.force_arm(True)
    cat = _cat()
    handles, tids = {}, {}
    barrier = threading.Barrier(2)

    def run(qid):
        barrier.wait()
        with query_scope(qid):
            handles[qid] = _handle(cat, 4096)
            tids[qid] = threading.get_ident()

    threads = [threading.Thread(target=run, args=(f"q{i}",))
               for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    for qid in ("q0", "q1"):
        leaks = cat.ledger.sweep_query(qid)
        assert len(leaks) == 1, f"{qid} swept {len(leaks)} records"
        assert leaks[0]["query_id"] == qid
        assert leaks[0]["tid"] == tids[qid]
    for h in handles.values():
        h.close()
    assert cat.ledger.stats()["leaked_live"] == 0


# ---------------------------------------------------------------------------
# 5. admission feed: observed peaks + forecast_source
# ---------------------------------------------------------------------------
def test_sweep_folds_query_peak_into_digest_history():
    L.force_arm(True)
    cat = _cat()
    with query_scope("qq"):
        a = _handle(cat, 8192)
        b = _handle(cat, 4096)
        b.close()
        a.close()
    assert cat.ledger.sweep_query("qq", digest="dg") == []
    assert cat.ledger.observed_peak("dg") == 12288
    assert cat.ledger.query_peak("qq") == 12288  # survives the sweep
    assert cat.observed_query_peak("qq") == 12288
    # a smaller later run never lowers the digest's observed peak
    with query_scope("qr"):
        c = _handle(cat, 4096)
        c.close()
    cat.ledger.sweep_query("qr", digest="dg")
    assert cat.ledger.observed_peak("dg") == 12288
    assert cat.ledger.observed_peak(None) is None


def test_admission_events_carry_forecast_source(tmp_path):
    logger = _logger(tmp_path)
    _cat(budget=1 << 20)
    sched = QueryScheduler.reset(RapidsConf({}))
    t = sched.acquire("sess-a", 0, 500_000, "d1",
                      forecast_source="ledger")
    assert t.forecast_source == "ledger"
    sched.release(t)
    sched.note_oom_requeue("sess-a", "d1", 600_000)
    adm = [r for r in logger.records() if r["event"] == "admission"]
    assert [r["forecast_source"] for r in adm] == ["ledger", "watermark"]
    assert adm[1]["verdict"] == "requeue"


# ---------------------------------------------------------------------------
# 6. surfaces: /status block, tpu_top panel, explain footer, op peaks
# ---------------------------------------------------------------------------
def test_status_heap_block_and_surfaces():
    from spark_rapids_tpu.exec.base import memory_footer
    from spark_rapids_tpu.obs.progress import ProgressTracker

    L.force_arm(True)
    cat = _cat()
    with query_scope("qs"), xla_cost.op_scope("TpuSortExec"):
        h = _handle(cat, 8192)
    st = build_status(MetricsRegistry(), ProgressTracker(), None)
    heap = st["heap"]
    json.dumps(st)  # the whole payload must stay JSON-serializable
    assert heap["live_bytes"] == 8192
    assert heap["by_op"] == {"TpuSortExec": 8192}
    assert heap["top"] == [["TpuSortExec", 8192]]
    assert heap["leaked"] == 0 and heap["tracked"] == 1
    assert heap["allocs"] == 1 and heap["frees"] == 0

    # tpu_top renders the block (and the leak line when flagged)
    cat.ledger.sweep_query("qs")
    status = {"hbm": {}, "heap": cat.ledger.status_block(),
              "alerts": [], "metrics": {}}
    text = tpu_top.render_status(status, clock="12:00:00")
    assert "heap 0.0MB attributed — top: TpuSortExec 0.0MB" in text
    assert "heap LEAKS: 1 live (1 total flagged)" in text

    # explain_metrics' memory footer decomposes the peak by op
    footer = memory_footer()
    assert "memory by op (peak): TpuSortExec 0.0MB" in footer
    assert "LEAKED 1 buffer(s)" in footer

    h.close()
    assert cat.ledger.stats()["leaked_live"] == 0
    # rebase (the bench per-shape window) drops the freed peak
    cat.ledger.rebase_peaks()
    assert cat.ledger.op_peaks() == {}
    assert "memory by op" not in memory_footer()


def test_event_schema_and_metric_twins_pinned():
    assert EV.EVENT_TYPES["buffer_alloc"] == (
        "bid", "kind", "bytes", "op", "query_id", "site", "origin")
    assert EV.EVENT_TYPES["buffer_free"] == (
        "bid", "kind", "bytes", "reason", "op", "query_id")
    assert EV.EVENT_TYPES["heap_snapshot"] == (
        "query_id", "live_bytes", "by_op", "top", "leaked")
    assert "forecast_source" in EV.EVENT_OPTIONAL_FIELDS["admission"]
    assert "bid" in EV.EVENT_OPTIONAL_FIELDS["spill"]
    assert obs.EVENT_BACKED_METRICS["buffer_alloc"] == "tpu_hbm_bytes"
    assert obs.EVENT_BACKED_METRICS["buffer_free"] == "tpu_hbm_bytes"
    assert obs.EVENT_BACKED_METRICS["heap_snapshot"] == \
        "tpu_hbm_leaked_buffers"
    # the exempt-kind lists cannot drift between the ledger and the tool
    assert set(tpu_heap.LEAK_EXEMPT_KINDS) == set(L.SWEEP_EXEMPT_KINDS)


# ---------------------------------------------------------------------------
# 7. offline tools: tpu_heap snapshot/diff/gates, replay leak episodes
# ---------------------------------------------------------------------------
def _synth_events():
    MB = 1 << 20
    return [
        {"event": "buffer_alloc", "ts": 100, "bid": 1, "kind": "spillable",
         "bytes": 6 * MB, "op": "TpuSortExec", "site": "exec/sort.py:10",
         "query_id": "s1"},
        {"event": "buffer_alloc", "ts": 200, "bid": 2, "kind": "spillable",
         "bytes": 5 * MB, "op": "TpuHashJoinExec",
         "site": "exec/join.py:20", "query_id": "s1"},
        {"event": "buffer_alloc", "ts": 250, "bid": 3,
         "kind": "reservation", "bytes": 99 * MB, "op": None,
         "site": "serve/scheduler.py:1", "query_id": None},
        {"event": "spill", "ts": 300, "kind": "device_to_host",
         "bytes": 5 * MB, "device_bytes": 6 * MB, "bid": 2},
        {"event": "buffer_free", "ts": 400, "bid": 2, "kind": "spillable",
         "bytes": 5 * MB, "reason": "close", "op": "TpuHashJoinExec",
         "query_id": "s1"},
        {"event": "buffer_free", "ts": 500, "bid": 1, "kind": "spillable",
         "bytes": 6 * MB, "reason": "close", "op": "TpuSortExec",
         "query_id": "s1"},
        {"event": "heap_snapshot", "ts": 600, "query_id": "s1",
         "live_bytes": 0, "by_op": {}, "top": [], "leaked": 0},
    ]


def test_tpu_heap_timeline_snapshot_and_cli(tmp_path, capsys):
    MB = 1 << 20
    events = _synth_events()
    t = tpu_heap.build_timeline(events)
    assert t.peak_bytes == 11 * MB  # the reservation never counts
    assert t.peak_by_op == {"TpuSortExec": 6 * MB,
                            "TpuHashJoinExec": 5 * MB}
    assert t.churn_by_op == {"TpuHashJoinExec": 5 * MB}
    assert t.end_leaks() == [] and t.sentinel_leaks == 0

    # --at: bid 2 is off-device at ts 350, so only the sort owns bytes
    mid = tpu_heap.snapshot_at(events, 350)
    assert mid._by_op() == {"TpuSortExec": 6 * MB}
    assert "1 spilled" in tpu_heap.build_snapshot_report(mid, 350)

    p = str(tmp_path / "log.jsonl")
    with open(p, "w") as f:
        for r in events:
            f.write(json.dumps(r) + "\n")
    rc = tpu_heap.main([p, "--fail-on-leaks", "--max-unattributed",
                        "0.01"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "top owners at peak: TpuSortExec 6.29MB" in out
    assert "unattributed at peak: 0.00%" in out

    # a log whose query never swept a live buffer fails the leak gate
    leaky = events[:4]  # bid 1 still live, bid 2 spilled but live
    p2 = str(tmp_path / "leaky.jsonl")
    with open(p2, "w") as f:
        for r in leaky:
            f.write(json.dumps(r) + "\n")
    assert tpu_heap.main([p2]) == 0              # report-only: no gate
    assert tpu_heap.main([p2, "--fail-on-leaks"]) == 1
    capsys.readouterr()


def test_tpu_heap_diff_gates_per_op_growth_with_noise_floor():
    MB = 1 << 20

    def tl(op_peaks):
        t = tpu_heap.HeapTimeline()
        t.op_peak = dict(op_peaks)
        t.peak_bytes = sum(op_peaks.values())
        return t

    # +3MB on a 6MB op (>20% and >1MB): regression
    text, bad = tpu_heap.diff_heap(
        tl({"TpuSortExec": 6 * MB}), tl({"TpuSortExec": 9 * MB}), 0.2)
    assert bad == 1 and "REGRESSION TpuSortExec" in text
    # +0.5MB: above 20% relative but under the absolute jitter floor
    _, bad = tpu_heap.diff_heap(
        tl({"TpuSortExec": 2 * MB}),
        tl({"TpuSortExec": 2 * MB + MB // 2}), 0.2)
    assert bad == 0
    # +100MB on a 1GB op: huge absolute, under the relative threshold
    _, bad = tpu_heap.diff_heap(
        tl({"TpuSortExec": 1024 * MB}), tl({"TpuSortExec": 1124 * MB}),
        0.2)
    assert bad == 0
    # a brand-new op needs only the absolute floor
    text, bad = tpu_heap.diff_heap(
        tl({}), tl({"TpuExpandExec": 2 * MB}), 0.2)
    assert bad == 1 and "(new op)" in text
    # an end-of-log leak count regression gates regardless of peaks
    new = tl({})
    new.live[7] = {"op": "TpuSortExec", "site": "s", "bytes": MB,
                   "kind": "spillable", "query_id": "q", "ts": 0}
    text, bad = tpu_heap.diff_heap(tl({}), new, 0.2)
    assert bad == 1 and "REGRESSION leaks: 0 -> 1" in text


def test_replay_leak_alert_episode_semantics():
    mk = lambda ts, leaked: {
        "event": "heap_snapshot", "ts": ts, "query_id": f"q{ts}",
        "live_bytes": 0, "by_op": {}, "top": [], "leaked": leaked}
    alerts = replay_alerts(
        [mk(1, 2), mk(2, 2), mk(3, 0), mk(4, 1)], WatchdogRules())
    leaks = [a for a in alerts if a.kind == "buffer_leak"]
    # one per episode: 2-leak episode, cleared, then a fresh 1-leak one
    assert [(a.value, a.ts) for a in leaks] == [(2, 1), (1, 4)]
