"""Cross-process shuffle: one query executed across TWO python processes.

The mapper PROCESS partitions a seeded dataset, computes partial
aggregates, and pushes serialized pieces to this process's shuffle server
over TCP; the reducer (this process) fetches every reduce partition
through the same SPI and finalizes the aggregate. Result must match the
single-process CPU oracle — the reference tests its UCX machinery with
mocked connections (RapidsShuffleTestHelper.scala:56-131); a real
localhost socket pair is strictly stronger.
"""
import os
import subprocess
import sys
import textwrap

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import ColumnarBatch, schema_of
from spark_rapids_tpu.shuffle.network import (
    BounceBuffers,
    NetworkShuffleTransport,
    ShuffleClient,
    ShuffleServer,
)
from spark_rapids_tpu.shuffle.serializer import (
    deserialize_batch,
    serialize_batch,
)

pytestmark = pytest.mark.cpu_only  # subprocess pins the CPU backend


def test_server_roundtrip_single_process():
    srv = ShuffleServer(window_bytes=256, window_count=2)
    try:
        schema = schema_of(k=T.INT, v=T.LONG, s=T.STRING)
        batch = ColumnarBatch.from_pydict(
            {"k": [1, 2, None], "v": [10, 20, 30],
             "s": ["a", None, "x" * 500]}, schema)
        data = serialize_batch(batch, "none")
        cli = ShuffleClient(srv.address)
        cli.push_serialized(7, 0, 3, data)
        cli.push_serialized(7, 1, 3, data)
        got = cli.fetch_serialized(7, 3)
        assert [m for m, _ in got] == [0, 1]
        rb = deserialize_batch(got[0][1])
        assert rb.to_rows() == batch.to_rows()
        assert cli.fetch_serialized(7, 99) == []
        cli.close()
    finally:
        srv.close()


def test_windowed_send_smaller_than_piece():
    """Pieces far larger than one bounce buffer stream through the window."""
    srv = ShuffleServer(window_bytes=128, window_count=2)
    try:
        payload = os.urandom(10_000)
        cli = ShuffleClient(srv.address)
        cli.push_serialized(1, 0, 0, payload)
        [(mid, got)] = cli.fetch_serialized(1, 0)
        assert mid == 0 and got == payload
        cli.close()
    finally:
        srv.close()


def test_bounce_pool_blocks_at_capacity():
    pool = BounceBuffers(count=2, size=64)
    a, b = pool.acquire(), pool.acquire()
    acquired = []

    import threading

    def third():
        acquired.append(pool.acquire())

    t = threading.Thread(target=third)
    t.start()
    t.join(0.2)
    assert t.is_alive() and not acquired  # window is closed
    pool.release(a)
    t.join(5)
    assert acquired
    pool.release(acquired[0])
    pool.release(b)


_MAPPER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar import ColumnarBatch, schema_of
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.exec import InMemoryScanExec, TpuHashAggregateExec
    from spark_rapids_tpu.exec.base import vals_of_batch
    from spark_rapids_tpu.expr import aggregates as A
    from spark_rapids_tpu.expr.expressions import col
    from spark_rapids_tpu.shuffle.network import NetworkShuffleTransport
    from spark_rapids_tpu.shuffle.transport import ShufflePiece

    host, port, nparts = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    rng = np.random.default_rng(99)
    n = 5000
    schema = schema_of(k=T.INT, v=T.LONG)
    batch = ColumnarBatch.from_pydict(
        {{"k": [int(x) for x in rng.integers(0, 37, n)],
          "v": [int(x) for x in rng.integers(-100, 100, n)]}}, schema)
    conf = RapidsConf({{}})
    # map-side PARTIAL aggregate (Spark's update half)
    part = TpuHashAggregateExec(
        conf, [col("k")],
        [A.agg(A.Sum(col("v")), "s"), A.agg(A.Count(None), "c")],
        InMemoryScanExec(conf, [[batch]], schema), mode=A.PARTIAL)
    [pbatch] = list(part.execute_columnar())
    pschema = part.output_schema
    tr = NetworkShuffleTransport(push_to=(host, port), codec="lz4")
    # split the partial rows by key % nparts (partitioner correctness is
    # covered by test_shuffle.py; the unit under test is the TCP wire)
    rows = pbatch.to_rows()
    for rid in range(nparts):
        sub = [r for r in rows if (r[0] or 0) % nparts == rid]
        if not sub:
            continue
        sb = ColumnarBatch.from_pydict(
            {{f.name: [r[i] for r in sub]
              for i, f in enumerate(pschema.fields)}}, pschema)
        piece = ShufflePiece(vals_of_batch(sb), sb.num_rows, ())
        tr.write(1, 0, rid, piece, pschema)
    tr.close()
    print("MAPPER_DONE")
""")


def test_query_across_two_processes():
    import numpy as np

    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.exec import InMemoryScanExec, TpuHashAggregateExec
    from spark_rapids_tpu.exec.base import batch_from_vals
    from spark_rapids_tpu.expr import aggregates as A
    from spark_rapids_tpu.expr.expressions import col

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    nparts = 4
    srv = ShuffleServer()
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run(
            [sys.executable, "-c", _MAPPER.format(repo=repo),
             srv.address[0], str(srv.address[1]), str(nparts)],
            capture_output=True, text=True, timeout=300, env=env)
        assert "MAPPER_DONE" in proc.stdout, proc.stderr[-2000:]

        # reduce side: fetch each partition, FINAL-aggregate the partials
        conf = RapidsConf({})
        tr = NetworkShuffleTransport(server=srv)
        rows = []
        # the partial layout is [k, sum_buf, count_buf]
        pschema = schema_of(k=T.INT, s=T.LONG, c=T.LONG)
        for rid in range(nparts):
            pieces = tr.fetch(1, rid)
            if not pieces:
                continue
            batches = [
                batch_from_vals(p.vals, pschema, p.n) for p in pieces
            ]
            fin = TpuHashAggregateExec(
                conf, [col("k")],
                [A.agg(A.Sum(col("v")), "s"), A.agg(A.Count(None), "c")],
                InMemoryScanExec(conf, [batches], pschema), mode=A.FINAL)
            for b in fin.execute_columnar():
                rows.extend(b.to_rows())

        rng = np.random.default_rng(99)
        n = 5000
        k = rng.integers(0, 37, n)
        v = rng.integers(-100, 100, n)
        import pandas as pd

        exp = pd.DataFrame({"k": k, "v": v}).groupby("k").agg(
            s=("v", "sum"), c=("v", "count"))
        got = {r[0]: (r[1], r[2]) for r in rows}
        assert len(got) == len(exp)
        for kk in exp.index:
            assert got[kk] == (exp.loc[kk, "s"], exp.loc[kk, "c"])
    finally:
        srv.close()
