"""Cross-process shuffle: one query executed across TWO python processes.

The mapper PROCESS partitions a seeded dataset, computes partial
aggregates, and pushes serialized pieces to this process's shuffle server
over TCP; the reducer (this process) fetches every reduce partition
through the same SPI and finalizes the aggregate. Result must match the
single-process CPU oracle — the reference tests its UCX machinery with
mocked connections (RapidsShuffleTestHelper.scala:56-131); a real
localhost socket pair is strictly stronger.
"""
import os
import subprocess
import sys
import textwrap

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import ColumnarBatch, schema_of
from spark_rapids_tpu.shuffle.network import (
    BounceBuffers,
    NetworkShuffleTransport,
    ShuffleClient,
    ShuffleServer,
)
from spark_rapids_tpu.shuffle.serializer import (
    deserialize_batch,
    serialize_batch,
)

pytestmark = pytest.mark.cpu_only  # subprocess pins the CPU backend


def test_server_roundtrip_single_process():
    srv = ShuffleServer(window_bytes=256, window_count=2)
    try:
        schema = schema_of(k=T.INT, v=T.LONG, s=T.STRING)
        batch = ColumnarBatch.from_pydict(
            {"k": [1, 2, None], "v": [10, 20, 30],
             "s": ["a", None, "x" * 500]}, schema)
        data = serialize_batch(batch, "none")
        cli = ShuffleClient(srv.address)
        cli.push_serialized(7, 0, 3, data)
        cli.push_serialized(7, 1, 3, data)
        got = cli.fetch_serialized(7, 3)
        assert [m for m, _ in got] == [0, 1]
        rb = deserialize_batch(got[0][1])
        assert rb.to_rows() == batch.to_rows()
        assert cli.fetch_serialized(7, 99) == []
        cli.close()
    finally:
        srv.close()


def test_windowed_send_smaller_than_piece():
    """Pieces far larger than one bounce buffer stream through the window."""
    srv = ShuffleServer(window_bytes=128, window_count=2)
    try:
        payload = os.urandom(10_000)
        cli = ShuffleClient(srv.address)
        cli.push_serialized(1, 0, 0, payload)
        [(mid, got)] = cli.fetch_serialized(1, 0)
        assert mid == 0 and got == payload
        cli.close()
    finally:
        srv.close()


def test_bounce_pool_blocks_at_capacity():
    pool = BounceBuffers(count=2, size=64)
    a, b = pool.acquire(), pool.acquire()
    acquired = []

    import threading

    def third():
        acquired.append(pool.acquire())

    t = threading.Thread(target=third)
    t.start()
    t.join(0.2)
    assert t.is_alive() and not acquired  # window is closed
    pool.release(a)
    t.join(5)
    assert acquired
    pool.release(acquired[0])
    pool.release(b)


_MAPPER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar import ColumnarBatch, schema_of
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.exec import InMemoryScanExec, TpuHashAggregateExec
    from spark_rapids_tpu.exec.base import vals_of_batch
    from spark_rapids_tpu.expr import aggregates as A
    from spark_rapids_tpu.expr.expressions import col
    from spark_rapids_tpu.shuffle.network import NetworkShuffleTransport
    from spark_rapids_tpu.shuffle.transport import ShufflePiece

    host, port, nparts = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    rng = np.random.default_rng(99)
    n = 5000
    schema = schema_of(k=T.INT, v=T.LONG)
    batch = ColumnarBatch.from_pydict(
        {{"k": [int(x) for x in rng.integers(0, 37, n)],
          "v": [int(x) for x in rng.integers(-100, 100, n)]}}, schema)
    conf = RapidsConf({{}})
    # map-side PARTIAL aggregate (Spark's update half)
    part = TpuHashAggregateExec(
        conf, [col("k")],
        [A.agg(A.Sum(col("v")), "s"), A.agg(A.Count(None), "c")],
        InMemoryScanExec(conf, [[batch]], schema), mode=A.PARTIAL)
    [pbatch] = list(part.execute_columnar())
    pschema = part.output_schema
    tr = NetworkShuffleTransport(push_to=(host, port), codec="lz4")
    # split the partial rows by key % nparts (partitioner correctness is
    # covered by test_shuffle.py; the unit under test is the TCP wire)
    rows = pbatch.to_rows()
    for rid in range(nparts):
        sub = [r for r in rows if (r[0] or 0) % nparts == rid]
        if not sub:
            continue
        sb = ColumnarBatch.from_pydict(
            {{f.name: [r[i] for r in sub]
              for i, f in enumerate(pschema.fields)}}, pschema)
        piece = ShufflePiece(vals_of_batch(sb), sb.num_rows, ())
        tr.write(1, 0, rid, piece, pschema)
    tr.close()
    print("MAPPER_DONE")
""")


def test_query_across_two_processes():
    import numpy as np

    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.exec import InMemoryScanExec, TpuHashAggregateExec
    from spark_rapids_tpu.exec.base import batch_from_vals
    from spark_rapids_tpu.expr import aggregates as A
    from spark_rapids_tpu.expr.expressions import col

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    nparts = 4
    srv = ShuffleServer()
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run(
            [sys.executable, "-c", _MAPPER.format(repo=repo),
             srv.address[0], str(srv.address[1]), str(nparts)],
            capture_output=True, text=True, timeout=300, env=env)
        assert "MAPPER_DONE" in proc.stdout, proc.stderr[-2000:]

        # reduce side: fetch each partition, FINAL-aggregate the partials
        conf = RapidsConf({})
        tr = NetworkShuffleTransport(server=srv)
        rows = []
        # the partial layout is [k, sum_buf, count_buf]
        pschema = schema_of(k=T.INT, s=T.LONG, c=T.LONG)
        for rid in range(nparts):
            pieces = tr.fetch(1, rid)
            if not pieces:
                continue
            batches = [
                batch_from_vals(p.vals, pschema, p.n) for p in pieces
            ]
            fin = TpuHashAggregateExec(
                conf, [col("k")],
                [A.agg(A.Sum(col("v")), "s"), A.agg(A.Count(None), "c")],
                InMemoryScanExec(conf, [batches], pschema), mode=A.FINAL)
            for b in fin.execute_columnar():
                rows.extend(b.to_rows())

        rng = np.random.default_rng(99)
        n = 5000
        k = rng.integers(0, 37, n)
        v = rng.integers(-100, 100, n)
        import pandas as pd

        exp = pd.DataFrame({"k": k, "v": v}).groupby("k").agg(
            s=("v", "sum"), c=("v", "count"))
        got = {r[0]: (r[1], r[2]) for r in rows}
        assert len(got) == len(exp)
        for kk in exp.index:
            assert got[kk] == (exp.loc[kk, "s"], exp.loc[kk, "c"])
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# round 5: conf-selected network transport (VERDICT r4 item #4)
# ---------------------------------------------------------------------------
def _net_session(extra=None):
    from spark_rapids_tpu.sql import TpuSession

    conf = {
        "spark.rapids.tpu.shuffle.mode": "host",  # exchanges, not SPMD
        "spark.rapids.tpu.shuffle.transport.class": "network",
        "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": -1,
        "spark.rapids.tpu.sql.test.enabled": True,
    }
    conf.update(extra or {})
    return TpuSession(conf)


def _mk_df(s, n=600, parts=4):
    from harness import compare_rows  # noqa: F401

    return s.create_dataframe(
        {"k": [i % 9 if i % 13 else None for i in range(n)],
         "v": [None if i % 17 == 0 else i * 3 - n for i in range(n)],
         "s": [f"s{i % 5}-{'x' * (i % 3)}" for i in range(n)]},
        T.StructType([
            T.StructField("k", T.INT), T.StructField("v", T.LONG),
            T.StructField("s", T.STRING)]),
        num_partitions=parts)


def test_conf_selected_network_aggregate_differential():
    """spark.rapids.tpu.shuffle.transport.class=network routes the
    exchange over real sockets; results match the CPU oracle
    (reference: transport selection by conf, RapidsConf.scala:696)."""
    from harness import assert_tpu_and_cpu_equal
    from spark_rapids_tpu.expr import aggregates as A
    from spark_rapids_tpu.expr.expressions import col

    def build(s):
        return _mk_df(s).group_by("k").agg(
            A.agg(A.Sum(col("v")), "sv"), A.agg(A.Count(None), "n"))

    assert_tpu_and_cpu_equal(
        build,
        conf={"spark.rapids.tpu.shuffle.mode": "host",
              "spark.rapids.tpu.shuffle.transport.class": "network"})
    s = _net_session()
    _mk_df(s).group_by("k").agg(A.agg(A.Count(None), "n")).collect()
    plan = s.last_executed_plan.tree_string()
    assert "TpuShuffleExchangeExec" in plan
    def find_transport(node):
        tr = getattr(node, "transport", None)
        if tr is not None:
            return tr
        kids = list(getattr(node, "children", ()))
        tc = getattr(node, "tpu_child", None)  # ColumnarToRow boundary
        if tc is not None:
            kids.append(tc)
        for c in kids:
            r = find_transport(c)
            if r is not None:
                return r
        return None

    tr = find_transport(s.last_executed_plan)
    assert tr is not None and type(tr).__name__ == "NetworkShuffleTransport"


def test_conf_selected_network_join_and_aqe_differential():
    """A join and an AQE-coalesced aggregate both run over the socket
    transport (the map-stats path has now seen the network)."""
    from harness import assert_tpu_and_cpu_equal
    from spark_rapids_tpu.expr import aggregates as A
    from spark_rapids_tpu.expr.expressions import col

    def build_join(s):
        left = _mk_df(s, n=300, parts=3)
        right = s.create_dataframe(
            {"k2": list(range(9)), "w": [i * 10 for i in range(9)]},
            T.StructType([T.StructField("k2", T.INT),
                          T.StructField("w", T.LONG)]), num_partitions=2)
        return left.join(right, on=[("k", "k2")])

    net = {"spark.rapids.tpu.shuffle.mode": "host",
           "spark.rapids.tpu.shuffle.transport.class": "network",
           "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": -1}
    assert_tpu_and_cpu_equal(build_join, conf=net)

    def build_agg(s):
        return _mk_df(s, n=900, parts=6).group_by("s").agg(
            A.agg(A.Sum(col("v")), "sv"))

    assert_tpu_and_cpu_equal(
        build_agg, conf={**net, "spark.rapids.tpu.sql.adaptive.enabled": True})


def test_fetch_failure_is_clean_and_retries_recover():
    """Kill the server mid-stream: the client must fail with
    FetchFailedError after bounded retries, not hang; a live server after
    transient drops must recover (reference: the mocked error-path state
    machine tests, RapidsShuffleTestHelper.scala:56-131)."""
    import threading
    import time

    from spark_rapids_tpu.shuffle.network import (
        FetchFailedError,
        ShuffleClient,
        ShuffleServer,
    )

    srv = ShuffleServer(window_bytes=128, window_count=2)
    payload = os.urandom(50_000)
    cli = ShuffleClient(srv.address, retries=3, retry_wait_s=0.05)
    cli.push_serialized(5, 0, 0, payload)

    # hard-kill the server shortly after fetching starts: in-flight
    # connections are severed AND the port stops accepting
    killer = threading.Timer(0.01, lambda: srv.close(force=True))
    killer.start()
    try:
        t0 = time.monotonic()
        with pytest.raises(FetchFailedError):
            for _ in range(2000):  # keep fetching until the kill lands
                got = cli.fetch_serialized(5, 0)
                assert got and got[0][1] == payload
        assert time.monotonic() - t0 < 30  # bounded, no hang
    finally:
        killer.cancel()
        cli.close()
        srv.close(force=True)

    # transient failure then recovery: new server at a fresh port
    srv2 = ShuffleServer()
    cli2 = ShuffleClient(srv2.address, retries=3, retry_wait_s=0.05)
    cli2.push_serialized(6, 0, 0, payload)
    # break the socket under the client; the retry path must reconnect
    cli2._sock.close()
    got = cli2.fetch_serialized(6, 0)
    assert got[0][1] == payload
    cli2.close()
    srv2.close()


@pytest.mark.parametrize("bad", ["hostonly", "host:", ":9000", "host:port"])
def test_invalid_peer_entry_raises_conf_error(bad):
    """A malformed peers entry must fail with an error naming the conf key
    and the offending entry, not a bare int() ValueError at transport
    construction."""
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.exec.exchange import make_transport

    conf = RapidsConf({
        "spark.rapids.tpu.shuffle.transport.class": "network",
        "spark.rapids.tpu.shuffle.network.peers": f"ok-host:9000,{bad}",
    })
    with pytest.raises(ValueError) as ei:
        make_transport(conf)
    msg = str(ei.value)
    assert "spark.rapids.tpu.shuffle.network.peers" in msg
    assert repr(bad) in msg
