"""Differential string-expression tests: TPU lowering vs CPU interpreter.

Mirrors the reference's string coverage (stringFunctions.scala via
integration_tests string_test.py + CastOpSuite string rows), applied through
the same two-engine diff used by test_expressions.py.
"""
import random
import zlib

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import ColumnarBatch, schema_of
from spark_rapids_tpu.cpu import eval_expression_rows
from spark_rapids_tpu.expr import bind_references, col, evaluate_projection, lit
from spark_rapids_tpu.expr import expressions as E
from spark_rapids_tpu.expr.eval import tpu_supports

from data_gen import approx_equal

N = 96

# alphabet keeps case-mapped chars inside the TPU's U+0250 mapped range and
# avoids length-changing mappings (ß -> SS), the documented incompat
_ALPHA = "abcdefgXYZ 019.,%_üÜéÉñÑÿŸ\t-"


def gen_strings(n, rng, null_prob=0.15):
    specials = ["", "a", "X", "NULL", "  pad  ", "aXbXc", "üñé", "x" * 40,
                "a.b.c", "%lit%", "1", "-42", " 7 ", "3.5", "true", "no"]
    out = []
    for _ in range(n):
        r = rng.random()
        if r < null_prob:
            out.append(None)
        elif r < null_prob + 0.25:
            out.append(rng.choice(specials))
        else:
            k = rng.randint(0, 14)
            out.append("".join(rng.choice(_ALPHA) for _ in range(k)))
    return out


STR_SCHEMA = schema_of(s=T.STRING, t=T.STRING)


def make_batch(seed, null_prob=0.15):
    rng = random.Random(seed)
    data = {
        "s": gen_strings(N, rng, null_prob),
        "t": gen_strings(N, rng, null_prob),
    }
    return ColumnarBatch.from_pydict(data, STR_SCHEMA), data


def check(expr, seed=0, null_prob=0.15):
    batch, data = make_batch(seed, null_prob)
    bound = bind_references(expr, STR_SCHEMA)
    [tpu_col] = evaluate_projection([bound], batch)
    tpu_vals = tpu_col.to_pylist()
    rows = list(zip(data["s"], data["t"]))
    cpu_vals = eval_expression_rows(bound, rows)
    assert len(tpu_vals) == len(cpu_vals)
    for i, (tv, cv) in enumerate(zip(tpu_vals, cpu_vals)):
        assert approx_equal(tv, cv), (
            f"row {i}: tpu={tv!r} cpu={cv!r} expr={expr} inputs={rows[i]!r}"
        )


# ---------------------------------------------------------------------------
# comparisons / membership / conditionals
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("op", [
    E.EqualTo, E.EqualNullSafe, E.LessThan, E.LessThanOrEqual,
    E.GreaterThan, E.GreaterThanOrEqual,
])
def test_string_comparisons(op):
    check(op(col("s"), col("t")), seed=101)
    check(op(col("s"), lit("aXbXc")), seed=102)


def test_string_in():
    check(E.In(col("s"), ("a", "X", "üñé", "")), seed=103)
    check(E.In(col("s"), ("a", None, "x" * 40)), seed=104)


def test_string_conditionals():
    p = E.GreaterThan(E.Length(col("s")), lit(3))
    check(E.If(p, col("s"), col("t")), seed=105)
    check(E.If(p, col("s"), lit(None)), seed=106)
    check(E.Coalesce((col("s"), col("t"), lit("zz"))), seed=107, null_prob=0.5)
    check(
        E.CaseWhen(
            ((p, col("t")), (E.EqualTo(col("s"), lit("a")), lit("ONE"))),
            else_value=lit("other"),
        ),
        seed=108,
    )
    check(E.CaseWhen(((p, col("t")),)), seed=109)


# ---------------------------------------------------------------------------
# case / length / substring / concat / trim
# ---------------------------------------------------------------------------
def test_upper_lower_initcap():
    check(E.Upper(col("s")), seed=110)
    check(E.Lower(col("s")), seed=111)
    check(E.InitCap(col("s")), seed=112)


def test_length():
    check(E.Length(col("s")), seed=113)


@pytest.mark.parametrize("pos,ln", [
    (1, 3), (2, 100), (0, 2), (-3, 2), (-100, 3), (5, -1), (3, 0),
    (-1, 5), (2, 2**31 - 1),
])
def test_substring(pos, ln):
    check(E.Substring(col("s"), lit(pos), lit(ln)), seed=hash((pos, ln)) & 0xFFF)


def test_substring_null_args():
    check(E.Substring(col("s"), lit(None), lit(2)), seed=114)


def test_concat():
    check(E.Concat((col("s"), col("t"))), seed=115)
    check(E.Concat((col("s"), lit("-"), col("t"), lit("!"))), seed=116)
    check(E.Concat((col("s"), lit(None))), seed=117)


def test_trim_family():
    check(E.StringTrim(col("s")), seed=118)
    check(E.StringTrimLeft(col("s")), seed=119)
    check(E.StringTrimRight(col("s")), seed=120)
    check(E.StringTrim(col("s"), "ab "), seed=121)
    check(E.StringTrimLeft(col("s"), "aX"), seed=122)
    check(E.StringTrimRight(col("s"), "c."), seed=123)


# ---------------------------------------------------------------------------
# predicates / like / locate
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("pat", ["a", "X", "", "aX", "üñ", "  ", "x" * 40])
def test_starts_ends_contains(pat):
    sd = hash(pat) & 0xFFF
    check(E.StartsWith(col("s"), lit(pat)), seed=sd)
    check(E.EndsWith(col("s"), lit(pat)), seed=sd + 1)
    check(E.Contains(col("s"), lit(pat)), seed=sd + 2)


def test_predicate_null_pattern():
    check(E.StartsWith(col("s"), lit(None)), seed=124)


@pytest.mark.parametrize("pat", [
    "%X%", "a%", "%c", "a%c", "a%b%c", "aXbXc", "", "%", "%%", "_", "a_",
    "a_c", "___", "%üñ%", "100\\%", "a\\_c",
])
def test_like(pat):
    check(E.Like(col("s"), lit(pat)), seed=zlib.crc32(pat.encode()) & 0xFFF)


def test_like_null_pattern():
    check(E.Like(col("s"), lit(None)), seed=125)


@pytest.mark.parametrize("sub,start", [
    ("X", 1), ("a", 2), ("üñ", 1), ("", 1), ("X", 0), ("b", 3), ("x" * 40, 1),
])
def test_locate(sub, start):
    check(E.StringLocate(lit(sub), col("s"), lit(start)),
          seed=hash((sub, start)) & 0xFFF)


def test_locate_nulls():
    check(E.StringLocate(lit(None), col("s"), lit(1)), seed=126)
    check(E.StringLocate(lit("a"), col("s"), lit(None)), seed=127)


# ---------------------------------------------------------------------------
# replace / pad / substring_index / split
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("search,repl", [
    ("X", "-"), ("a", ""), ("aX", "=="), ("b", "bbb"), ("üñ", "u"),
])
def test_replace(search, repl):
    check(E.StringReplace(col("s"), lit(search), lit(repl)),
          seed=hash((search, repl)) & 0xFFF)


def test_replace_empty_search_is_identity():
    check(E.StringReplace(col("s"), lit(""), lit("zz")), seed=128)


def test_replace_self_overlapping_falls_back():
    ok, why = tpu_supports(
        E.StringReplace(col("s"), lit("aa"), lit("b")), STR_SCHEMA)
    assert not ok and "self-overlapping" in why


@pytest.mark.parametrize("ln,pad", [
    (7, "*"), (3, "xy"), (0, "*"), (10, ""), (6, "üñ"), (12, "ab"),
])
def test_pads(ln, pad):
    sd = hash((ln, pad)) & 0xFFF
    check(E.StringLPad(col("s"), lit(ln), lit(pad)), seed=sd)
    check(E.StringRPad(col("s"), lit(ln), lit(pad)), seed=sd + 1)


def test_huge_count_literals_stay_bounded():
    """Review regression: count/idx far beyond any possible occurrence
    count must not size the occurrence matrix (4TB allocation)."""
    check(E.SubstringIndex(col("s"), lit("."), lit(10**6)), seed=130)
    check(E.SubstringIndex(col("s"), lit("."), lit(-(10**6))), seed=131)
    check(E.StringSplitPart(col("s"), lit("."), lit(10**6)), seed=132)


@pytest.mark.parametrize("count", [1, 2, 0, -1, -2])
def test_substring_index(count):
    check(E.SubstringIndex(col("s"), lit("."), lit(count)),
          seed=hash(count) & 0xFFF)
    check(E.SubstringIndex(col("s"), lit("X"), lit(count)),
          seed=(hash(count) + 7) & 0xFFF)


@pytest.mark.parametrize("idx", [0, 1, 2, 5])
def test_split_part(idx):
    check(E.StringSplitPart(col("s"), lit("X"), lit(idx)),
          seed=hash(idx) & 0xFFF)
    check(E.StringSplitPart(col("s"), lit("."), lit(idx)),
          seed=(hash(idx) + 3) & 0xFFF)


# ---------------------------------------------------------------------------
# casts
# ---------------------------------------------------------------------------
def _check_cast_from_strings(values, to):
    schema = schema_of(s=T.STRING)
    batch = ColumnarBatch.from_pydict({"s": values}, schema)
    bound = bind_references(E.Cast(col("s"), to), schema)
    [r] = evaluate_projection([bound], batch)
    cpu = eval_expression_rows(bound, [(v,) for v in values])
    for i, (tv, cv) in enumerate(zip(r.to_pylist(), cpu)):
        assert approx_equal(tv, cv), f"cast {values[i]!r}: tpu={tv!r} cpu={cv!r}"


def test_cast_string_to_int():
    vals = ["42", "-7", "+13", "  99 ", "", "abc", "3.5", "12x", None,
            "2147483647", "2147483648", "-2147483648", "-2147483649",
            "0", "-0", "00123", "+", "-", "128", "-129", " \t10\n"]
    _check_cast_from_strings(vals, T.INT)
    _check_cast_from_strings(vals, T.LONG)
    _check_cast_from_strings(vals, T.BYTE)
    _check_cast_from_strings(
        ["9223372036854775807", "9223372036854775808",
         "-9223372036854775808", "-9223372036854775809"], T.LONG)


def test_cast_string_to_bool():
    vals = ["true", "TRUE", "t", "y", "yes", "1", "false", "F", "n", "NO",
            "0", " true ", "tr", "2", "", None]
    _check_cast_from_strings(vals, T.BOOLEAN)


def test_cast_string_to_float():
    vals = ["1.5", "-2.25", "3", ".5", "5.", "1e3", "2.5e-2", "1E2",
            "-0.125", " 7.5 ", "inf", "-Infinity", "NaN", "abc", "1.2.3",
            "1e", "", None, "+4.5", "1e+2"]
    _check_cast_from_strings(vals, T.DOUBLE)
    _check_cast_from_strings(vals, T.FLOAT)


def test_cast_int_to_string():
    schema = schema_of(a=T.LONG, b=T.INT, c=T.BYTE)
    vals = {
        "a": [0, 1, -1, 2**63 - 1, -(2**63), 42, None, 1000000],
        "b": [0, -2147483648, 2147483647, 7, None, -99, 10, 100],
        "c": [0, -128, 127, None, 5, -5, 99, -100],
    }
    batch = ColumnarBatch.from_pydict(vals, schema)
    for name in ("a", "b", "c"):
        bound = bind_references(E.Cast(col(name), T.STRING), schema)
        [r] = evaluate_projection([bound], batch)
        expect = [None if v is None else str(v) for v in vals[name]]
        assert r.to_pylist() == expect


def test_cast_bool_to_string():
    schema = schema_of(p=T.BOOLEAN)
    batch = ColumnarBatch.from_pydict({"p": [True, False, None]}, schema)
    bound = bind_references(E.Cast(col("p"), T.STRING), schema)
    [r] = evaluate_projection([bound], batch)
    assert r.to_pylist() == ["true", "false", None]


def test_cast_gates_in_planner():
    """String->numeric casts are conf-gated off by default, like the
    reference (RapidsConf.scala:487-533)."""
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.plugin.overrides import check_expression

    schema = schema_of(s=T.STRING)
    conf = RapidsConf({})
    r = check_expression(E.Cast(col("s"), T.INT), schema, conf)
    assert r and "castStringToInteger" in r[0]
    r = check_expression(E.Cast(col("s"), T.DOUBLE), schema, conf)
    assert r and "castStringToFloat" in r[0]
    on = RapidsConf({
        "spark.rapids.tpu.sql.castStringToInteger.enabled": True})
    assert check_expression(E.Cast(col("s"), T.INT), schema, on) == []
    # always-on direction
    assert check_expression(
        E.Cast(E.Length(col("s")), T.STRING), schema, conf) == []


def test_cast_string_long_digit_runs():
    """Leading zeros don't count toward the 19-digit bound; >17-digit
    mantissas keep their magnitude."""
    _check_cast_from_strings(
        ["00000000000000000000123", "0000000000000000000000"], T.INT)
    _check_cast_from_strings(
        ["12345678901234567890123", "0.000000000000000000005",
         "00000000000000000001.5"], T.DOUBLE)


def test_trim_empty_trimstr_is_noop():
    check(E.StringTrim(col("s"), ""), seed=129)


def test_java_float_repr():
    """CPU fallback float->string matches Java Double/Float.toString."""
    from spark_rapids_tpu.cpu.interpreter import _java_double_str

    assert _java_double_str(12345678.9, False) == "1.23456789E7"
    assert _java_double_str(1.23456789e-4, False) == "1.23456789E-4"
    assert _java_double_str(5.0, False) == "5.0"
    assert _java_double_str(-0.0, False) == "-0.0"
    assert _java_double_str(1e7, False) == "1.0E7"
    assert _java_double_str(0.001, False) == "0.001"
    assert _java_double_str(float("inf"), False) == "Infinity"
    import struct

    f11 = struct.unpack("f", struct.pack("f", 1.1))[0]
    assert _java_double_str(f11, True) == "1.1"


def test_fused_string_pipeline():
    """Strings fuse with arithmetic in one projection (the TPU-first win)."""
    e = E.If(
        E.And(E.StartsWith(col("s"), lit("a")),
              E.GreaterThan(E.Length(col("t")), lit(2))),
        E.Upper(E.Concat((col("s"), lit("-"), col("t")))),
        E.StringRPad(E.StringTrim(col("s")), lit(8), lit(".")),
    )
    check(e, seed=200)


# ---------------------------------------------------------------------------
# regex family (RLike via byte DFA; RegExpReplace via the literal guard)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("pat", [
    "X", "abc", "a.c", "^a", "c$", "^aXbXc$", "a|b|üñ", "[abc]", "[^abc]",
    "[a-fX-Z]", r"\d+", r"\d\d", r"[0-9]{2}", "a.*c", "X+", " *", "a?b",
    r"\.", r"\s", r"\w+$", "(ab|cd)e?", "^$", "", "x{2,4}", r"\d{1,3}",
    ".", "[%]lit[%]",
])
def test_rlike(pat):
    check(E.RLike(col("s"), lit(pat)), seed=zlib.crc32(pat.encode()) & 0xFFF)


def test_rlike_null_pattern():
    check(E.RLike(col("s"), lit(None)), seed=321)


@pytest.mark.parametrize("pat", [
    "(a", "a**", "a(?=b)", "(a)\\1", "a*?", "[z-a]",
    "..",  # UTF-8 codepoint expansion blows the 16-state DFA cap
])
def test_rlike_unsupported_falls_back(pat):
    from spark_rapids_tpu.expr.eval import tpu_supports as probe

    ok, why = probe(E.RLike(col("s"), lit(pat)), STR_SCHEMA)
    assert not ok, pat


def test_rlike_too_many_states_falls_back():
    # distinct-literal alternation forces a wide DFA
    pat = "|".join(f"w{i}xyz{i}" for i in range(20))
    from spark_rapids_tpu.expr.eval import tpu_supports as probe

    ok, why = probe(E.RLike(col("s"), lit(pat)), STR_SCHEMA)
    assert not ok


@pytest.mark.parametrize("pat,repl", [
    ("X", "_"), (r"\.", ";"), ("aXb", ""), ("üñ", "u"), (r"100\%", "c"),
])
def test_regexp_replace_literal_guard(pat, repl):
    check(E.RegExpReplace(col("s"), lit(pat), lit(repl)),
          seed=zlib.crc32((pat + repl).encode()) & 0xFFF)


def test_regexp_replace_nonliteral_falls_back():
    from spark_rapids_tpu.expr.eval import tpu_supports as probe

    for pat in (r"\d+", "a.c", "x|y"):
        ok, why = probe(
            E.RegExpReplace(col("s"), lit(pat), lit("_")), STR_SCHEMA)
        assert not ok, pat
    # group references in the replacement are also guarded
    ok, why = probe(
        E.RegExpReplace(col("s"), lit("X"), lit("$1")), STR_SCHEMA)
    assert not ok


def test_regexp_replace_nulls():
    check(E.RegExpReplace(col("s"), lit(None), lit("_")), seed=322)
    check(E.RegExpReplace(col("s"), lit("X"), lit(None)), seed=323)
