#!/usr/bin/env python3
"""Offline HBM heap profiler over the per-buffer ledger's event stream.

Companion to tools/tpu_profile.py (op spans, rooflines) — this tool
answers the MEMORY questions a recorded run leaves behind: who held the
bytes at the watermark, which call sites allocate, what churned through
the spiller, what donation gave back, and whether anything leaked. It
consumes the ``buffer_alloc``/``buffer_free``/``heap_snapshot`` events
the HBM ledger (spark_rapids_tpu/memory/ledger.py) emits, plus the
bid-stamped ``spill`` events that move ledger buffers across tiers and
the ``donation`` events from the donation plane.

Modes::

    tpu_heap.py LOG...                  # full heap report
    tpu_heap.py LOG --at NS             # live-heap snapshot at timestamp
    tpu_heap.py --diff OLD NEW          # per-op peak growth gate

CI gates (used by the ``heap`` workflow job)::

    --fail-on-leaks        nonzero exit if the sentinel flagged buffers
                           (heap_snapshot leaked>0) or non-exempt
                           buffers are still live at end of log
    --max-unattributed F   nonzero exit if more than fraction F of the
                           peak's live bytes carry no owning op

No spark_rapids_tpu imports: like the other tools/ scripts this runs
standalone on any machine holding a log (tests load it via importlib).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

#: per-op peak growth below this many bytes is allocator jitter, not a
#: regression (mirrors tpu_profile's DIFF_MIN_* noise-floor convention)
DIFF_MIN_BYTES = 1 << 20

#: ledger record kinds that never count as device residency or leaks
#: (must mirror memory/ledger.py: reservations are bookkeeping, not
#: buffers; scan-cache entries outlive queries by design)
NON_DEVICE_KINDS = ("reservation",)
LEAK_EXEMPT_KINDS = ("reservation", "scan_cache", "plan_state")


# ---------------------------------------------------------------------------
# loading (same shape as tpu_profile.load_events — duplicated so the
# tool stays standalone)
# ---------------------------------------------------------------------------
def load_events(paths: List[str]) -> List[dict]:
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if f.endswith(".jsonl")))
        else:
            files.append(p)
    out: List[dict] = []
    for f in files:
        with open(f) as fh:
            for i, line in enumerate(fh):
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError as e:
                    raise SystemExit(
                        f"{f}:{i + 1}: not a JSONL event log ({e})")
    out.sort(key=lambda r: r.get("ts", 0))
    return out


def _mb(b: Optional[float]) -> str:
    return "-" if b is None else f"{b / 1e6:.2f}MB"


# ---------------------------------------------------------------------------
# timeline reconstruction
# ---------------------------------------------------------------------------
class HeapTimeline:
    """The whole heap story of one log, replayed buffer by buffer.

    ``live`` tracks device-resident ledger buffers (bid -> record);
    spilled-to-host buffers stay tracked but leave the device tally
    until their unspill. The peak is the device-byte watermark of the
    ATTRIBUTED heap — by construction every byte in it has a record, so
    "unattributed" means owned by no op (op absent at alloc), not
    invisible to the ledger.
    """

    def __init__(self) -> None:
        self.live: Dict[object, dict] = {}       # bid -> record
        self.off_device: set = set()             # spilled bids
        self.live_bytes = 0
        self.peak_bytes = 0
        self.peak_ts = 0
        self.peak_by_op: Dict[str, int] = {}
        self.op_peak: Dict[str, int] = {}        # per-op own watermark
        self.alloc_by_op: Dict[str, int] = {}    # cumulative alloc bytes
        self.alloc_count_by_op: Dict[str, int] = {}
        self.site_bytes: Dict[str, int] = {}     # cumulative alloc bytes
        self.site_count: Dict[str, int] = {}
        self.churn_by_op: Dict[str, int] = {}    # spilled-off bytes
        self.donated_by_site: Dict[str, int] = {}
        self.free_reasons: Dict[str, int] = {}
        self.snapshots: List[dict] = []          # heap_snapshot events
        self.sentinel_leaks = 0                  # sum of snapshot leaked

    def _by_op(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for bid, r in self.live.items():
            if bid in self.off_device:
                continue
            out[r["op"]] = out.get(r["op"], 0) + r["bytes"]
        return out

    def _bump(self, op: str, delta: int, ts: int) -> None:
        self.live_bytes += delta
        if delta > 0:
            cur = self._by_op().get(op, 0)
            if cur > self.op_peak.get(op, 0):
                self.op_peak[op] = cur
            if self.live_bytes > self.peak_bytes:
                self.peak_bytes = self.live_bytes
                self.peak_ts = ts
                self.peak_by_op = self._by_op()

    def feed(self, r: dict) -> None:
        ev = r.get("event")
        ts = r.get("ts", 0)
        if ev == "buffer_alloc":
            if r.get("kind") in NON_DEVICE_KINDS:
                return
            op = r.get("op") or "(unattributed)"
            site = r.get("site") or "?"
            nbytes = int(r.get("bytes") or 0)
            self.live[r.get("bid")] = {
                "op": op, "site": site, "bytes": nbytes,
                "kind": r.get("kind"), "query_id": r.get("query_id"),
                "ts": ts}
            self.alloc_by_op[op] = self.alloc_by_op.get(op, 0) + nbytes
            self.alloc_count_by_op[op] = \
                self.alloc_count_by_op.get(op, 0) + 1
            self.site_bytes[site] = self.site_bytes.get(site, 0) + nbytes
            self.site_count[site] = self.site_count.get(site, 0) + 1
            self._bump(op, nbytes, ts)
        elif ev == "buffer_free":
            rec = self.live.pop(r.get("bid"), None)
            reason = r.get("reason") or "?"
            self.free_reasons[reason] = self.free_reasons.get(reason, 0) + 1
            if rec is None:
                return
            if r.get("bid") in self.off_device:
                self.off_device.discard(r.get("bid"))
            else:
                self._bump(rec["op"], -rec["bytes"], ts)
        elif ev == "spill":
            bid = r.get("bid")
            rec = self.live.get(bid) if bid is not None else None
            if rec is None:
                return
            if r.get("kind") == "device_to_host" \
                    and bid not in self.off_device:
                self.off_device.add(bid)
                self._bump(rec["op"], -rec["bytes"], ts)
                self.churn_by_op[rec["op"]] = \
                    self.churn_by_op.get(rec["op"], 0) + rec["bytes"]
            elif r.get("kind") == "unspill" and bid in self.off_device:
                self.off_device.discard(bid)
                self._bump(rec["op"], rec["bytes"], ts)
        elif ev == "donation":
            site = r.get("site") or "?"
            self.donated_by_site[site] = \
                self.donated_by_site.get(site, 0) + int(r.get("bytes") or 0)
        elif ev == "heap_snapshot":
            self.snapshots.append(r)
            self.sentinel_leaks += int(r.get("leaked") or 0)

    # -- derived views ------------------------------------------------------
    def end_leaks(self) -> List[dict]:
        """Non-exempt buffers still live when the log ends — the offline
        twin of the sentinel (catches buffers whose query never swept)."""
        return [dict(r, bid=bid) for bid, r in self.live.items()
                if r.get("kind") not in LEAK_EXEMPT_KINDS]

    def unattributed_fraction(self) -> float:
        """Share of the peak's live bytes owned by no op."""
        if not self.peak_bytes:
            return 0.0
        return self.peak_by_op.get("(unattributed)", 0) / self.peak_bytes


def build_timeline(events: List[dict]) -> HeapTimeline:
    t = HeapTimeline()
    for r in events:
        t.feed(r)
    return t


def snapshot_at(events: List[dict], at_ns: int) -> HeapTimeline:
    """The heap as it stood at ``at_ns`` (feed stops at the timestamp)."""
    t = HeapTimeline()
    for r in events:
        if r.get("ts", 0) > at_ns:
            break
        t.feed(r)
    return t


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------
def _table(rows: List[Tuple[str, ...]], header: Tuple[str, ...]
           ) -> List[str]:
    widths = [len(h) for h in header]
    for row in rows:
        for i, c in enumerate(row):
            widths[i] = max(widths[i], len(c))
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    out = [fmt.format(*header), fmt.format(*("-" * w for w in widths))]
    out.extend(fmt.format(*row) for row in rows)
    return out


def build_report(t: HeapTimeline, top_n: int = 10) -> str:
    lines: List[str] = ["== HBM heap report =="]
    base = t.peak_ts
    lines.append(
        f"peak device-live (attributed): {_mb(t.peak_bytes)}"
        + (f" at ts {base}" if base else ""))
    top = sorted(t.peak_by_op.items(), key=lambda kv: -kv[1])[:3]
    if top:
        lines.append("top owners at peak: " + ", ".join(
            f"{op} {_mb(b)}" for op, b in top))
    unatt = t.unattributed_fraction()
    lines.append(f"unattributed at peak: {unatt * 100:.2f}%")
    lines.append(f"live at end of log: {_mb(t.live_bytes)} "
                 f"({len(t.live)} buffer(s))")

    if t.op_peak:
        lines.append("")
        lines.append("-- per-op attribution --")
        rows = [(op,
                 _mb(t.op_peak.get(op, 0)),
                 _mb(t.alloc_by_op.get(op, 0)),
                 str(t.alloc_count_by_op.get(op, 0)),
                 _mb(t.churn_by_op.get(op, 0)) if op in t.churn_by_op
                 else "-")
                for op, _ in sorted(t.op_peak.items(),
                                    key=lambda kv: -kv[1])[:top_n]]
        lines.extend(_table(
            rows, ("op", "peak", "allocated", "allocs", "spill churn")))

    if t.site_bytes:
        lines.append("")
        lines.append("-- per-site allocation --")
        rows = [(site, _mb(b), str(t.site_count.get(site, 0)))
                for site, b in sorted(t.site_bytes.items(),
                                      key=lambda kv: -kv[1])[:top_n]]
        lines.extend(_table(rows, ("site", "allocated", "allocs")))

    churn = sum(t.churn_by_op.values())
    if churn:
        lines.append("")
        lines.append(f"spill churn: {_mb(churn)} left the device "
                     "(re-upload paid on each unspill)")
    if t.donated_by_site:
        total = sum(t.donated_by_site.values())
        lines.append("")
        lines.append(f"donation savings: {_mb(total)} of output aliased "
                     "over donated inputs")
        for site, b in sorted(t.donated_by_site.items(),
                              key=lambda kv: -kv[1])[:top_n]:
            lines.append(f"  {site}: {_mb(b)}")
    if t.free_reasons:
        lines.append("")
        lines.append("free reasons: " + ", ".join(
            f"{k}={v}" for k, v in sorted(t.free_reasons.items())))

    leaks = t.end_leaks()
    lines.append("")
    if t.sentinel_leaks or leaks:
        lines.append(f"LEAKS: sentinel flagged {t.sentinel_leaks}, "
                     f"{len(leaks)} non-exempt buffer(s) live at end")
        for r in leaks[:top_n]:
            lines.append(
                f"  bid={r['bid']} {r['op']} {_mb(r['bytes'])} "
                f"site={r['site']} query={r.get('query_id')}")
    else:
        lines.append("no leaks: sentinel clean, nothing non-exempt "
                     "live at end of log")
    return "\n".join(lines)


def build_snapshot_report(t: HeapTimeline, at_ns: int) -> str:
    lines = [f"== heap at ts {at_ns} =="]
    lines.append(f"device-live: {_mb(t.live_bytes)} "
                 f"({len(t.live) - len(t.off_device)} buffer(s) on "
                 f"device, {len(t.off_device)} spilled)")
    for op, b in sorted(t._by_op().items(), key=lambda kv: -kv[1]):
        lines.append(f"  {op}: {_mb(b)}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# diff: per-op peak growth gate
# ---------------------------------------------------------------------------
def diff_heap(old: HeapTimeline, new: HeapTimeline, threshold: float
              ) -> Tuple[str, int]:
    """Per-op peak growth between two logs. A regression is an op whose
    peak grew more than ``threshold`` relative AND more than
    DIFF_MIN_BYTES absolute (allocator jitter floor); brand-new ops
    count from zero but still need the absolute floor."""
    lines: List[str] = ["== heap diff (per-op peak) =="]
    regressions = 0
    ops = sorted(set(old.op_peak) | set(new.op_peak))
    for op in ops:
        o, n = old.op_peak.get(op, 0), new.op_peak.get(op, 0)
        if n - o <= DIFF_MIN_BYTES:
            continue
        if o and (n - o) / o <= threshold:
            continue
        regressions += 1
        lines.append(
            f"REGRESSION {op}: peak {_mb(o)} -> {_mb(n)} "
            + (f"({(n - o) / o * 100:+.0f}%)" if o else "(new op)"))
    dp, dn = old.peak_bytes, new.peak_bytes
    lines.append(f"total peak: {_mb(dp)} -> {_mb(dn)}")
    lo, ln = len(old.end_leaks()), len(new.end_leaks())
    if ln > lo:
        regressions += 1
        lines.append(f"REGRESSION leaks: {lo} -> {ln} non-exempt "
                     "buffer(s) live at end")
    if regressions == 0:
        lines.append("no per-op peak regressions")
    return "\n".join(lines), regressions


# ---------------------------------------------------------------------------
def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Offline HBM heap profiler over ledger event logs "
                    "(see module docstring)")
    ap.add_argument("paths", nargs="+",
                    help="event-log files/dirs; with --diff, exactly two "
                         "(old new)")
    ap.add_argument("--top", type=int, default=10,
                    help="rows per attribution table")
    ap.add_argument("--at", type=int, default=None,
                    help="render the live heap at this ts (ns) instead "
                         "of the full report")
    ap.add_argument("--diff", action="store_true",
                    help="compare two logs; nonzero exit on per-op peak "
                         "growth beyond --threshold")
    ap.add_argument("--threshold", type=float, default=0.2,
                    help="relative per-op peak growth threshold for "
                         "--diff (0.2 = 20%%)")
    ap.add_argument("--fail-on-leaks", action="store_true",
                    help="nonzero exit if the sentinel flagged leaks or "
                         "non-exempt buffers are live at end of log")
    ap.add_argument("--max-unattributed", type=float, default=None,
                    help="nonzero exit if more than this fraction of "
                         "peak bytes carries no owning op (CI: 0.01)")
    args = ap.parse_args(argv)

    if args.diff:
        if len(args.paths) != 2:
            ap.error("--diff takes exactly two paths (old new)")
        old = build_timeline(load_events([args.paths[0]]))
        new = build_timeline(load_events([args.paths[1]]))
        text, bad = diff_heap(old, new, args.threshold)
        print(text)
        return 1 if bad else 0

    events = load_events(args.paths)
    if not events:
        print("no events found", file=sys.stderr)
        return 1

    if args.at is not None:
        print(build_snapshot_report(snapshot_at(events, args.at), args.at))
        return 0

    t = build_timeline(events)
    print(build_report(t, args.top))
    rc = 0
    if args.fail_on_leaks and (t.sentinel_leaks or t.end_leaks()):
        print("FAIL: leaked buffers (see report)", file=sys.stderr)
        rc = 1
    if args.max_unattributed is not None:
        frac = t.unattributed_fraction()
        if frac > args.max_unattributed:
            print(f"FAIL: {frac * 100:.2f}% of peak bytes unattributed "
                  f"(limit {args.max_unattributed * 100:.2f}%)",
                  file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
