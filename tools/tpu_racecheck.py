#!/usr/bin/env python3
"""tpu_racecheck — repo-directed AST analysis for concurrency hazards.

The engine is deeply concurrent (serve scheduler, obs registry +
watchdog threads, prefetch/decode pools, cross-process AOT cache) and
its dominant residual bug class is lock misuse: PR 9's thread-safety
audit found get-then-build races in every process-global pipeline
cache, and the PR 10/15 post-review passes each hand-caught more
(probe-lock transitions, mid-scrape dict mutation, plane-lock teardown
races). This tool turns that review lore into CI failures, checked
against the DECLARED lock hierarchy in
``spark_rapids_tpu/utils/locks.py`` (``LOCK_ORDER`` + ``LEAF_SINKS``).

Rules
-----
TPU101  lock-order inversion: the static acquire graph (``with`` sites
        across call edges, transitively) contains an edge that violates
        the declared partial order — a manifest lock acquired while
        holding an equal-or-lower-ranked manifest lock, ANY acquisition
        while holding a leaf sink, an undeclared (raw ``threading``)
        lock held across a structural manifest-lock acquisition, or a
        cycle anywhere in the full graph (declared or not).
TPU102  check-then-act on shared mutable state: a module-global dict/
        list/set (or a lock-owning class's attribute) conditionally
        read and later written in the same function with NEITHER access
        under a lock — the get-then-build shape. The sanctioned helper
        ``exec/base.cached_pipeline`` (which double-checks under the
        pipeline lock) is the fix; double-checked sites (write under a
        lock) are not flagged. Only modules that import ``threading`` /
        ``concurrent.futures`` are in scope.
TPU103  unlocked mutation from a thread: a function reachable from a
        ``threading.Thread(target=...)`` / ``Timer`` / pool
        ``.submit(...)`` entry writes module-global mutable state with
        no lock held — the /status mid-scrape-mutation shape.
TPU104  manifest lock held across a blocking boundary: a ``with`` body
        on a declared lock reaches ``host_pull``/``host_fence``/
        ``device_get``/``block_until_ready``/``.item()``, a future
        ``.result()``, an event/queue wait, a no-arg ``.join()``,
        ``time.sleep``, or ``subprocess.*`` — directly or through
        resolvable calls. Holding a hierarchy lock through a host sync
        or a thread join is how the teardown/scrape stalls happened.

The static graph is cross-checked at runtime: the conf-gated witness
(``spark.rapids.tpu.tools.racecheck.witness.enabled``) records actual
acquisition pairs through ``ordered_lock`` and the chaos suite asserts
every observed pair acquires DOWNWARD in LOCK_ORDER — the same partial
order TPU101 enforces statically (``--dump-graph`` prints the static
manifest edges; the static set under-approximates dynamic dispatch, so
it is compared for consistency, not equality).

Allowlist: ``tools/tpu_racecheck_allow.txt`` (conf entry
``spark.rapids.tpu.tools.racecheck.allowlistPath``), one
``relpath::qualname::RULE  # why`` per line; ``--strict-allowlist``
fails on stale entries. Exit 0 clean, 1 findings, 2 usage error.
"""
from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Optional, Set, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from lint_common import (  # noqa: E402 — path bootstrap above
    Finding,
    REPO_ROOT,
    attr_chain,
    default_allowlist_path,
    enclosing_function,
    function_defs,
    iter_py_files,
    parents_map,
    run_tool,
)

DEFAULT_TARGET = os.path.join(REPO_ROOT, "spark_rapids_tpu")
MANIFEST_PATH = os.path.join(
    REPO_ROOT, "spark_rapids_tpu", "utils", "locks.py")

#: attribute mutators that count as a WRITE to the object they're
#: called on (dict/list/set/deque surface the engine actually uses)
MUTATING_METHODS = frozenset({
    "append", "add", "update", "setdefault", "pop", "popleft", "remove",
    "discard", "clear", "insert", "extend", "appendleft", "__setitem__",
})

#: call names that block the calling thread (TPU104 boundaries)
BLOCKING_CALL_NAMES = frozenset({
    "host_pull", "host_fence", "device_get", "block_until_ready",
})


def _default_allowlist_path() -> str:
    return default_allowlist_path(
        "RACECHECK_ALLOWLIST_PATH",
        os.path.join("tools", "tpu_racecheck_allow.txt"))


# ---------------------------------------------------------------------------
# The declared hierarchy, read straight from the manifest module's AST
# (no engine import — the tool must run without jax installed).
# ---------------------------------------------------------------------------
def load_manifest(path: str = MANIFEST_PATH) -> Tuple[Dict[str, int],
                                                      Set[str]]:
    """(name -> rank, leaf sink names) from LOCK_ORDER / LEAF_SINKS."""
    with open(path, "rb") as f:
        tree = ast.parse(f.read(), filename=path)
    order: List[str] = []
    sinks: Set[str] = set()
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if "LOCK_ORDER" in names and isinstance(node.value, ast.Tuple):
            order = [e.value for e in node.value.elts
                     if isinstance(e, ast.Constant)]
        if "LEAF_SINKS" in names:
            sinks = {n.value for n in ast.walk(node.value)
                     if isinstance(n, ast.Constant)
                     and isinstance(n.value, str)}
    return {n: i for i, n in enumerate(order)}, sinks


# ---------------------------------------------------------------------------
# Per-module scan: lock definitions, function bodies (acquire sites with
# the held-lock stack, calls, blocking boundaries, global/attr accesses)
# ---------------------------------------------------------------------------
class LockDef:
    __slots__ = ("lid", "manifest_name", "reentrant", "relpath", "line")

    def __init__(self, lid, manifest_name, reentrant, relpath, line):
        self.lid = lid                    # graph node id
        self.manifest_name = manifest_name  # None for undeclared locks
        self.reentrant = reentrant
        self.relpath = relpath
        self.line = line

    @property
    def label(self) -> str:
        return self.manifest_name or f"<undeclared {self.lid}>"


class FuncScan:
    __slots__ = ("qualname", "module", "node",
                 "acquire_events", "call_events", "blocking_events",
                 "global_checks", "global_writes",
                 "attr_checks", "attr_writes", "class_qual")

    def __init__(self, qualname, module, node, class_qual):
        self.qualname = qualname
        self.module = module
        self.node = node
        self.class_qual = class_qual
        self.acquire_events: List[tuple] = []  # (lid, line, held[lid])
        self.call_events: List[tuple] = []     # (desc, line, held[lid])
        self.blocking_events: List[tuple] = []  # (line, label, held[lid])
        self.global_checks: Dict[str, List[tuple]] = {}  # g -> (ln, locked)
        self.global_writes: Dict[str, List[tuple]] = {}
        self.attr_checks: Dict[str, List[tuple]] = {}    # attr -> (ln, lk)
        self.attr_writes: Dict[str, List[tuple]] = {}


class ModuleScan:
    def __init__(self, relpath: str):
        self.relpath = relpath
        # "spark_rapids_tpu/serve/scheduler.py" -> dotted module name
        self.dotted = relpath[:-3].replace(os.sep, ".")
        self.import_aliases: Dict[str, str] = {}   # alias -> dotted target
        self.module_locks: Dict[str, LockDef] = {}  # module-level var
        self.class_locks: Dict[Tuple[str, str], LockDef] = {}
        self.lock_classes: Set[str] = set()  # class quals owning a lock
        self.funcs: Dict[str, FuncScan] = {}
        self.top_funcs: Dict[str, str] = {}  # bare name -> qualname
        self.methods: Dict[Tuple[str, str], str] = {}  # (cls, m) -> qual
        self.mutable_globals: Set[str] = set()
        self.uses_threading = False
        self.thread_entry_descs: List[tuple] = []


def _is_threading_lock_ctor(call: ast.Call, mod: ModuleScan) -> Optional[bool]:
    """None if not a raw lock ctor, else reentrant flag."""
    chain = attr_chain(call.func)
    if not chain:
        return None
    parts = chain.split(".")
    if parts[-1] not in ("Lock", "RLock"):
        return None
    root = parts[0]
    if len(parts) == 1:  # bare Lock() via from-import
        tgt = mod.import_aliases.get(root, "")
        if not tgt.startswith("threading"):
            return None
    elif mod.import_aliases.get(root, root) not in (
            "threading", "_threading"):
        return None
    return parts[-1] == "RLock"


def _is_ordered_lock_ctor(call: ast.Call) -> bool:
    chain = attr_chain(call.func)
    return bool(chain) and chain.split(".")[-1] in (
        "ordered_lock", "_ordered_lock")


def _mutable_literal(value: ast.AST) -> bool:
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
        return True
    if isinstance(value, ast.Constant) and value.value is None:
        return True
    if isinstance(value, ast.Call):
        chain = attr_chain(value.func) or ""
        return chain.split(".")[-1] in (
            "dict", "list", "set", "deque", "defaultdict", "OrderedDict")
    return False


def _call_desc(call: ast.Call, mod: ModuleScan,
               class_qual: Optional[str]) -> Optional[tuple]:
    """A resolvable-call descriptor, or None."""
    f = call.func
    if isinstance(f, ast.Name):
        return ("local", f.id)
    if isinstance(f, ast.Attribute):
        if isinstance(f.value, ast.Name) and f.value.id in ("self", "cls") \
                and class_qual is not None:
            return ("self", class_qual, f.attr)
        if isinstance(f.value, ast.Name):
            tgt = mod.import_aliases.get(f.value.id)
            if tgt is not None:
                return ("module", tgt, f.attr)
        return ("attr", f.attr)
    return None


def _blocking_label(call: ast.Call, mod: ModuleScan) -> Optional[str]:
    """Label if this call blocks the calling thread, else None."""
    chain = attr_chain(call.func) or ""
    parts = chain.split(".")
    last = parts[-1] if parts else ""
    if last in BLOCKING_CALL_NAMES:
        return f"{last}() host sync"
    if isinstance(call.func, ast.Attribute):
        if last == "item" and not call.args:
            return ".item() host sync"
        if last == "result":
            return ".result() future wait"
        if last == "wait":
            return ".wait() event/condition wait"
        if last == "join" and not call.args:
            # thread/queue join; str.join/os.path.join take a positional
            return ".join() thread/queue wait"
        if last == "get" and any(kw.arg in ("block", "timeout")
                                 for kw in call.keywords):
            return ".get(block/timeout) queue wait"
    root = mod.import_aliases.get(parts[0], parts[0]) if parts else ""
    if root == "time" and last == "sleep":
        return "time.sleep()"
    if root == "subprocess":
        return f"subprocess.{last}()"
    return None


def scan_module(path: str, relpath: str) -> Optional[ModuleScan]:
    with open(path, "rb") as f:
        try:
            tree = ast.parse(f.read(), filename=path)
        except SyntaxError:
            return None
    mod = ModuleScan(relpath)
    parents = parents_map(tree)
    qualnames = function_defs(tree)

    # imports ---------------------------------------------------------------
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                mod.import_aliases[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            for a in node.names:
                tgt = f"{base}.{a.name}" if base else a.name
                mod.import_aliases[a.asname or a.name] = tgt
    mod.uses_threading = any(
        v.startswith(("threading", "concurrent.futures"))
        for v in mod.import_aliases.values())

    def enclosing_class(node) -> Optional[str]:
        cur, names = parents.get(node), []
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                names.append(cur.name)
            cur = parents.get(cur)
        return ".".join(reversed(names)) if names else None

    # lock + mutable-global + function indexes ------------------------------
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cq = enclosing_class(node)
            qn = qualnames[node]
            if cq is None and enclosing_function(node, parents) is None:
                mod.top_funcs[node.name] = qn
            if cq is not None and qn == f"{cq}.{node.name}":
                mod.methods[(cq, node.name)] = qn
        # normalize plain and annotated assignments (`_C = {}` and
        # `_C: dict = {}` declare the same mutable global)
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        if not isinstance(value, ast.Call):
            if enclosing_function(node, parents) is None \
                    and enclosing_class(node) is None:
                for t in targets:
                    if isinstance(t, ast.Name) \
                            and _mutable_literal(value):
                        mod.mutable_globals.add(t.id)
            continue
        call = value
        is_ordered = _is_ordered_lock_ctor(call)
        raw_reentrant = _is_threading_lock_ctor(call, mod)
        if not is_ordered and raw_reentrant is None:
            continue
        if is_ordered:
            name = (call.args[0].value
                    if call.args and isinstance(call.args[0], ast.Constant)
                    else None)
            reentrant = any(
                kw.arg == "reentrant" and isinstance(kw.value, ast.Constant)
                and bool(kw.value.value) for kw in call.keywords)
        else:
            name, reentrant = None, raw_reentrant
        for t in targets:
            cq = enclosing_class(node)
            if isinstance(t, ast.Name) and cq is None \
                    and enclosing_function(node, parents) is None:
                lid = name or f"~{relpath}::{t.id}"
                mod.module_locks[t.id] = LockDef(
                    lid, name, reentrant, relpath, node.lineno)
            elif isinstance(t, ast.Name) and cq is not None:
                # class-level attr (e.g. _instance_lock)
                lid = name or f"~{relpath}::{cq}.{t.id}"
                mod.class_locks[(cq, t.id)] = LockDef(
                    lid, name, reentrant, relpath, node.lineno)
                mod.lock_classes.add(cq)
            elif isinstance(t, ast.Attribute) and isinstance(
                    t.value, ast.Name) and t.value.id in ("self", "cls") \
                    and cq is not None:
                lid = name or f"~{relpath}::{cq}.{t.attr}"
                mod.class_locks[(cq, t.attr)] = LockDef(
                    lid, name, reentrant, relpath, node.lineno)
                mod.lock_classes.add(cq)

    lock_attr_names = {a for (_, a) in mod.class_locks}

    def resolve_lock(expr, class_qual) -> Optional[LockDef]:
        if isinstance(expr, ast.Name):
            return mod.module_locks.get(expr.id)
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name):
            if expr.value.id in ("self", "cls") and class_qual:
                return mod.class_locks.get((class_qual, expr.attr))
            return mod.class_locks.get((expr.value.id, expr.attr))
        return None

    # per-function body walk ------------------------------------------------
    def scan_function(fn_node, qn, class_qual) -> FuncScan:
        fs = FuncScan(qn, mod, fn_node, class_qual)
        held: List[LockDef] = []

        def note_check(g_or_attr, store, line):
            store.setdefault(g_or_attr, []).append((line, bool(held)))

        def global_name_refs(expr) -> Set[str]:
            return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)
                    and n.id in mod.mutable_globals}

        def self_attr_refs(expr) -> Set[str]:
            out = set()
            for n in ast.walk(expr):
                if isinstance(n, ast.Attribute) and isinstance(
                        n.value, ast.Name) and n.value.id == "self" \
                        and n.attr not in lock_attr_names:
                    out.add(n.attr)
            return out

        def visit(node):
            if node is not fn_node and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda)):
                return  # nested defs are scanned as their own functions
            if isinstance(node, ast.With):
                pushed = []
                for item in node.items:
                    ld = resolve_lock(item.context_expr, class_qual)
                    if ld is not None:
                        fs.acquire_events.append(
                            (ld, node.lineno, [h.lid for h in held]))
                        held.append(ld)
                        pushed.append(ld)
                for item in node.items:
                    visit(item.context_expr)
                for child in node.body:
                    visit(child)
                for _ in pushed:
                    held.pop()
                return
            if isinstance(node, (ast.If, ast.While)):
                for g in global_name_refs(node.test):
                    note_check(g, fs.global_checks, node.lineno)
                for a in self_attr_refs(node.test):
                    note_check(a, fs.attr_checks, node.lineno)
            if isinstance(node, ast.Compare):
                for g in global_name_refs(node):
                    note_check(g, fs.global_checks, node.lineno)
                for a in self_attr_refs(node):
                    note_check(a, fs.attr_checks, node.lineno)
            if isinstance(node, ast.Call):
                desc = _call_desc(node, mod, class_qual)
                if desc is not None:
                    fs.call_events.append(
                        (desc, node.lineno, [h.lid for h in held]))
                label = _blocking_label(node, mod)
                if label is not None:
                    fs.blocking_events.append(
                        (node.lineno, label, [h.lid for h in held]))
                f = node.func
                if isinstance(f, ast.Attribute):
                    if f.attr == "get" and isinstance(f.value, ast.Name) \
                            and f.value.id in mod.mutable_globals:
                        note_check(f.value.id, fs.global_checks, node.lineno)
                    if f.attr in MUTATING_METHODS:
                        if isinstance(f.value, ast.Name) \
                                and f.value.id in mod.mutable_globals:
                            note_check(f.value.id, fs.global_writes,
                                       node.lineno)
                        if isinstance(f.value, ast.Attribute) and isinstance(
                                f.value.value, ast.Name) \
                                and f.value.value.id == "self" \
                                and f.value.attr not in lock_attr_names:
                            note_check(f.value.attr, fs.attr_writes,
                                       node.lineno)
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        if isinstance(t.value, ast.Name) \
                                and t.value.id in mod.mutable_globals:
                            note_check(t.value.id, fs.global_writes,
                                       node.lineno)
                        if isinstance(t.value, ast.Attribute) \
                                and isinstance(t.value.value, ast.Name) \
                                and t.value.value.id == "self" \
                                and t.value.attr not in lock_attr_names:
                            note_check(t.value.attr, fs.attr_writes,
                                       node.lineno)
                    elif isinstance(t, ast.Name) \
                            and t.id in declared_globals:
                        note_check(t.id, fs.global_writes, node.lineno)
                    elif isinstance(t, ast.Attribute) and isinstance(
                            t.value, ast.Name) and t.value.id == "self" \
                            and t.attr not in lock_attr_names:
                        note_check(t.attr, fs.attr_writes, node.lineno)
            for child in ast.iter_child_nodes(node):
                visit(child)

        declared_globals: Set[str] = set()
        for n in ast.walk(fn_node):
            if isinstance(n, ast.Global):
                declared_globals.update(
                    g for g in n.names if g in mod.mutable_globals
                    or g in mod.module_locks)
                mod.mutable_globals.update(
                    g for g in n.names if g not in mod.module_locks)
        visit(fn_node)
        return fs

    for node, qn in qualnames.items():
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mod.funcs[qn] = scan_function(node, qn, enclosing_class(node))

    # thread entry points ---------------------------------------------------
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func) or ""
        last = chain.split(".")[-1]
        target_expr = None
        if last in ("Thread", "Timer"):
            for kw in node.keywords:
                if kw.arg == "target":
                    target_expr = kw.value
            if last == "Timer" and len(node.args) >= 2:
                target_expr = node.args[1]
        elif last == "submit" and isinstance(node.func, ast.Attribute) \
                and node.args:
            target_expr = node.args[0]
        if target_expr is None:
            continue
        cq = enclosing_class(node)
        desc = _call_desc(ast.Call(func=target_expr, args=[], keywords=[]),
                          mod, cq) if isinstance(
            target_expr, (ast.Name, ast.Attribute)) else None
        if desc is not None:
            mod.thread_entry_descs.append(desc)
    return mod


# ---------------------------------------------------------------------------
# Whole-program resolution: call graph, transitive acquires, may-block
# ---------------------------------------------------------------------------
class Program:
    def __init__(self, modules: List[ModuleScan],
                 ranks: Dict[str, int], sinks: Set[str]):
        self.modules = modules
        self.ranks = ranks
        self.sinks = sinks
        self.funcs: Dict[str, FuncScan] = {}
        self.methods_by_name: Dict[str, List[FuncScan]] = {}
        self.lock_defs: Dict[str, LockDef] = {}
        for m in modules:
            for qn, fs in m.funcs.items():
                self.funcs[f"{m.dotted}:{qn}"] = fs
            for (cq, meth), qn in m.methods.items():
                self.methods_by_name.setdefault(meth, []).append(
                    m.funcs[qn])
            for ld in list(m.module_locks.values()) \
                    + list(m.class_locks.values()):
                self.lock_defs.setdefault(ld.lid, ld)
        self._acq: Dict[int, Set[str]] = {}
        self._blk: Dict[int, Optional[str]] = {}

    def _module_by_suffix(self, dotted: str) -> Optional[ModuleScan]:
        for m in self.modules:
            if m.dotted == dotted or m.dotted.endswith("." + dotted) \
                    or m.dotted.split(".")[-1] == dotted.split(".")[-1]:
                return m
        return None

    def resolve(self, desc: tuple, mod: ModuleScan) -> Optional[FuncScan]:
        kind = desc[0]
        if kind == "local":
            qn = mod.top_funcs.get(desc[1])
            if qn is not None:
                return mod.funcs[qn]
            tgt = mod.import_aliases.get(desc[1])
            if tgt and "." in tgt:
                owner, fname = tgt.rsplit(".", 1)
                m2 = self._module_by_suffix(owner)
                if m2 is not None and fname in m2.top_funcs:
                    return m2.funcs[m2.top_funcs[fname]]
            return None
        if kind == "self":
            qn = mod.methods.get((desc[1], desc[2]))
            return mod.funcs[qn] if qn is not None else None
        if kind == "module":
            m2 = self._module_by_suffix(desc[1])
            if m2 is not None and desc[2] in m2.top_funcs:
                return m2.funcs[m2.top_funcs[desc[2]]]
            return None
        if kind == "attr":
            cands = self.methods_by_name.get(desc[1], [])
            return cands[0] if len(cands) == 1 else None
        return None

    # transitive locks a call of fs may acquire ----------------------------
    def acquired(self, fs: FuncScan, _seen=None) -> Set[str]:
        key = id(fs)
        if key in self._acq:
            return self._acq[key]
        _seen = _seen or set()
        if key in _seen:
            return set()
        _seen.add(key)
        out = {ld.lid for ld, _, _ in fs.acquire_events}
        for desc, _, _ in fs.call_events:
            g = self.resolve(desc, fs.module)
            if g is not None:
                out |= self.acquired(g, _seen)
        self._acq[key] = out
        return out

    # may a call of fs block? (label of the first boundary, or None) -------
    def may_block(self, fs: FuncScan, _seen=None) -> Optional[str]:
        key = id(fs)
        if key in self._blk:
            return self._blk[key]
        _seen = _seen or set()
        if key in _seen:
            return None
        _seen.add(key)
        out: Optional[str] = None
        if fs.blocking_events:
            out = fs.blocking_events[0][1]
        else:
            for desc, _, _ in fs.call_events:
                if desc[0] == "attr":
                    # all same-name candidates must block (conservative
                    # fallback where unique resolution fails)
                    cands = self.methods_by_name.get(desc[1], [])
                    if cands and len(cands) > 1 and all(
                            self.may_block(c, _seen) for c in cands):
                        out = (f"call to .{desc[1]}() "
                               f"(every known implementation blocks)")
                        break
                g = self.resolve(desc, fs.module)
                if g is not None:
                    lbl = self.may_block(g, _seen)
                    if lbl is not None:
                        out = f"call into {g.qualname} -> {lbl}"
                        break
        self._blk[key] = out
        return out


def build_edges(prog: Program):
    """(outer lid, inner lid) -> (relpath, line, qualname, why)."""
    edges: Dict[Tuple[str, str], tuple] = {}

    def add(outer, inner, fs, line, why):
        k = (outer, inner)
        if k not in edges:
            edges[k] = (fs.module.relpath, line, fs.qualname, why)

    for fs in prog.funcs.values():
        for ld, line, held in fs.acquire_events:
            for h in held:
                add(h, ld.lid, fs, line, f"acquires {ld.label!r} directly")
        for desc, line, held in fs.call_events:
            if not held:
                continue
            g = prog.resolve(desc, fs.module)
            if g is None:
                continue
            for inner in prog.acquired(g):
                for h in held:
                    add(h, inner, fs, line,
                        f"call into {g.qualname} acquires "
                        f"{prog.lock_defs[inner].label!r}")
    return edges


def find_cycles(edges) -> List[List[str]]:
    adj: Dict[str, List[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, []).append(b)
    seen_cycles: Set[frozenset] = set()
    cycles: List[List[str]] = []
    color: Dict[str, int] = {}
    stack: List[str] = []

    def dfs(v):
        color[v] = 1
        stack.append(v)
        for w in adj.get(v, ()):
            if color.get(w, 0) == 0:
                dfs(w)
            elif color.get(w) == 1:
                cyc = stack[stack.index(w):] + [w]
                key = frozenset(cyc)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    cycles.append(cyc)
        stack.pop()
        color[v] = 2

    for v in list(adj):
        if color.get(v, 0) == 0:
            dfs(v)
    return cycles


# ---------------------------------------------------------------------------
# Findings
# ---------------------------------------------------------------------------
def analyze(target: str) -> Dict[str, List[Finding]]:
    ranks, sinks = load_manifest()
    modules = []
    for path in iter_py_files(target):
        rel = os.path.relpath(path, REPO_ROOT)
        m = scan_module(path, rel)
        if m is not None:
            modules.append(m)
    prog = Program(modules, ranks, sinks)
    edges = build_edges(prog)
    by_path: Dict[str, List[Finding]] = {}

    def emit(path, line, rule, qual, msg):
        by_path.setdefault(path, []).append(
            Finding(path, line, rule, qual, msg))

    # --- TPU101: order violations on the static acquire graph -------------
    for (outer, inner), (path, line, qual, why) in sorted(edges.items()):
        od = prog.lock_defs.get(outer)
        idf = prog.lock_defs.get(inner)
        o_name = od.manifest_name if od else None
        i_name = idf.manifest_name if idf else None
        if o_name is not None and o_name in sinks:
            emit(path, line, "TPU101", qual,
                 f"leaf-sink lock {o_name!r} held while {why} — leaf "
                 "sinks must never call out (locks.py LEAF_SINKS)")
            continue
        if o_name is not None and i_name is not None:
            if o_name == i_name:
                if od is not None and not od.reentrant:
                    emit(path, line, "TPU101", qual,
                         f"non-reentrant lock {o_name!r} re-acquired "
                         f"while already held ({why}) — self-deadlock")
                continue
            if ranks.get(o_name, -1) >= ranks.get(i_name, 10 ** 9):
                emit(path, line, "TPU101", qual,
                     f"lock-order inversion: {why} while holding "
                     f"{o_name!r} (rank {ranks[o_name]} >= rank "
                     f"{ranks[i_name]}) — LOCK_ORDER only permits "
                     "acquiring downward")
            continue
        if o_name is None and i_name is not None and i_name not in sinks:
            emit(path, line, "TPU101", qual,
                 f"undeclared lock {outer!r} held while {why} — raw "
                 "threading locks must not sit above the declared "
                 "hierarchy; migrate it onto ordered_lock() or "
                 "restructure")
    for cyc in find_cycles(edges):
        first = edges[(cyc[0], cyc[1])]
        labels = [prog.lock_defs[lid].label if lid in prog.lock_defs
                  else lid for lid in cyc]
        emit(first[0], first[1], "TPU101", first[2],
             "cycle in the static acquire graph: "
             + " -> ".join(labels) + " — deadlock possible")

    # --- TPU102: check-then-act on shared mutable state --------------------
    for m in modules:
        if not m.uses_threading:
            continue
        for fs in m.funcs.values():
            fname = fs.qualname.rsplit(".", 1)[-1]
            for g, checks in fs.global_checks.items():
                writes = fs.global_writes.get(g, [])
                bad_c = [ln for ln, lk in checks if not lk]
                bad_w = [ln for ln, lk in writes if not lk]
                if bad_c and bad_w and min(bad_c) <= max(bad_w):
                    emit(m.relpath, min(bad_c), "TPU102", fs.qualname,
                         f"check-then-act on module global {g!r}: read at "
                         f"line {min(bad_c)} and write at line "
                         f"{max(bad_w)} with no lock held — two threads "
                         "can interleave; double-check under a lock "
                         "(exec/base.cached_pipeline is the sanctioned "
                         "helper for caches)")
            if fs.class_qual is None or fname == "__init__" \
                    or fs.class_qual not in m.lock_classes:
                continue
            for a, checks in fs.attr_checks.items():
                writes = fs.attr_writes.get(a, [])
                bad_c = [ln for ln, lk in checks if not lk]
                bad_w = [ln for ln, lk in writes if not lk]
                if bad_c and bad_w and min(bad_c) <= max(bad_w):
                    emit(m.relpath, min(bad_c), "TPU102", fs.qualname,
                         f"check-then-act on self.{a} in lock-owning "
                         f"class {fs.class_qual}: read at line "
                         f"{min(bad_c)} and write at line {max(bad_w)} "
                         "with the class's lock not held — hold the lock "
                         "for the transition or double-check under it")

    # --- TPU103: unlocked global mutation from a thread --------------------
    prog_funcs = list(prog.funcs.values())
    thread_run: Set[int] = set()
    work: List[FuncScan] = []
    for m in modules:
        for desc in m.thread_entry_descs:
            g = prog.resolve(desc, m)
            if g is not None and id(g) not in thread_run:
                thread_run.add(id(g))
                work.append(g)
    while work:
        fs = work.pop()
        for desc, _, _ in fs.call_events:
            g = prog.resolve(desc, fs.module)
            if g is not None and id(g) not in thread_run:
                thread_run.add(id(g))
                work.append(g)
    for fs in prog_funcs:
        if id(fs) not in thread_run:
            continue
        for g, writes in fs.global_writes.items():
            bad = [ln for ln, lk in writes if not lk]
            if bad:
                emit(fs.module.relpath, min(bad), "TPU103", fs.qualname,
                     f"module global {g!r} mutated from a thread-run "
                     "function with no lock held — racing the main "
                     "thread's readers; guard it or hand the data over "
                     "via a queue/immutable snapshot")

    # --- TPU104: manifest lock held across a blocking boundary -------------
    seen104: Set[tuple] = set()
    for fs in prog.funcs.values():
        for line, label, held in fs.blocking_events:
            for h in held:
                hd = prog.lock_defs.get(h)
                if hd is None or hd.manifest_name is None:
                    continue
                k = (fs.module.relpath, fs.qualname, h)
                if k not in seen104:
                    seen104.add(k)
                    emit(fs.module.relpath, line, "TPU104", fs.qualname,
                         f"manifest lock {hd.manifest_name!r} held "
                         f"across a blocking boundary: {label} — every "
                         "other acquirer stalls behind the block")
        for desc, line, held in fs.call_events:
            man = [h for h in held
                   if prog.lock_defs.get(h) is not None
                   and prog.lock_defs[h].manifest_name is not None]
            if not man:
                continue
            g = prog.resolve(desc, fs.module)
            lbl = prog.may_block(g) if g is not None else None
            if lbl is None:
                continue
            for h in man:
                k = (fs.module.relpath, fs.qualname, h)
                if k not in seen104:
                    seen104.add(k)
                    emit(fs.module.relpath, line, "TPU104", fs.qualname,
                         f"manifest lock "
                         f"{prog.lock_defs[h].manifest_name!r} held "
                         f"across a blocking boundary: {lbl}")
    return by_path


def dump_graph(target: str) -> int:
    """Print the static manifest-edge set. The chaos suite cross-checks
    the witness's observed edges against these: both must be downward in
    LOCK_ORDER, and the hot statically-predicted edges must actually be
    observed (the static set under-approximates dynamic dispatch, so
    observed ⊆ static does not hold exactly)."""
    ranks, sinks = load_manifest()
    modules = [m for m in (
        scan_module(p, os.path.relpath(p, REPO_ROOT))
        for p in iter_py_files(target)) if m is not None]
    prog = Program(modules, ranks, sinks)
    for (outer, inner), (path, line, qual, _why) in sorted(
            build_edges(prog).items()):
        od, idf = prog.lock_defs.get(outer), prog.lock_defs.get(inner)
        if od is None or idf is None:
            continue
        if od.manifest_name and idf.manifest_name:
            print(f"{od.manifest_name} -> {idf.manifest_name}"
                  f"  # {path}:{line} {qual}")
    return 0


def main(argv: List[str]) -> int:
    args = [a for a in argv if not a.startswith("--")]
    target = os.path.abspath(args[0]) if args else DEFAULT_TARGET
    if not os.path.exists(target):
        print(f"tpu_racecheck: no such target {target}", file=sys.stderr)
        return 2
    if "--dump-graph" in argv:
        return dump_graph(target)
    by_path = analyze(target)

    def check_file(path: str, relpath: str) -> List[Finding]:
        return by_path.get(relpath, [])

    return run_tool("tpu_racecheck", argv, target,
                    _default_allowlist_path(), check_file)


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
