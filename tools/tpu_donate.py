#!/usr/bin/env python3
"""tpu_donate — the donation-safety analyzer (static side).

Buffer donation (``jax.jit(..., donate_argnums=...)``) reuses an input
plane's HBM for a program's outputs and temps — the engine's biggest
peak-temp lever — but a donated plane is DELETED after dispatch, so a
caller that reads it afterwards has a use-after-free the backend
reports as an inscrutable "Array has been deleted". The engine's proof
obligation lives in the DECLARED certification table
(``spark_rapids_tpu/plugin/donation.py`` ``DONATION_SPECS``: per
compile site, the argnums proven dead after dispatch plus the
split-and-retry reconciliation, or the reason donation is forbidden).
This tool cross-checks that table against the AST of the pipeline
builders and their call sites — the same declared-manifest pattern as
``tools/tpu_racecheck.py`` over ``utils/locks.LOCK_ORDER``; the
conf-gated runtime witness (``tools.donation.witness.enabled``) is the
dynamic cross-check.

Rules
-----
TPU201  use-after-donation: a batch variable dispatched under
        ``donation.guard(<site>, <batch>)`` is read again AFTER the
        guarded block in the same function, through anything other
        than the safe metadata attributes (num_rows / num_rows_lazy /
        capacity / schema / exclusive) — its planes are deleted by the
        donating dispatch, so any plane-reaching use is a
        use-after-free the guard cannot restore.
TPU202  (warning) certified site not donating: a
        ``cached_pipeline(...)`` call naming a site the table
        certifies, with NO ``donate=`` mask plumbed — the donation win
        the certification proved safe is being left on the table.
        Warn-level: it cannot make the build fail, but it prints so
        the omission is a decision, not an accident.
TPU203  donation invisible to the cache key: a ``jax.jit``/``pjit``
        call declaring ``donate_argnums``/``donate_argnames`` outside
        a builder whose ``cached_pipeline``/``_cached_program`` call
        carries a ``donate=`` kwarg. ``cached_pipeline`` folds the
        mask into the structural key AND the AOT program-cache entry
        identity; a mask declared anywhere else forks donating and
        non-donating callers onto one cache entry — the warm process
        would serve a donating program to a caller that still owns its
        planes (or vice versa).

Allowlist: ``tools/tpu_donate_allow.txt`` (conf entry
``spark.rapids.tpu.tools.donate.allowlistPath``), one
``relpath::qualname::RULE  # why`` per line; ``--strict-allowlist``
fails on stale entries. ``--explain`` prints the certification table
with each site's safety argument verbatim. Exit 0 clean (TPU202
warnings do not fail), 1 findings/stale, 2 usage error.
"""
from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Optional, Set, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from lint_common import (  # noqa: E402 — path bootstrap above
    Finding,
    REPO_ROOT,
    attr_chain,
    default_allowlist_path,
    enclosing_function,
    iter_py_files,
    load_allowlist,
    parents_map,
    qualname_resolver,
)

DEFAULT_TARGET = os.path.join(REPO_ROOT, "spark_rapids_tpu")
MANIFEST_PATH = os.path.join(
    REPO_ROOT, "spark_rapids_tpu", "plugin", "donation.py")

#: batch attributes that stay valid after the planes donate (python
#: object metadata, not device planes — donation deletes buffers, not
#: the ColumnarBatch)
SAFE_ATTRS = frozenset({
    "num_rows", "num_rows_lazy", "capacity", "schema", "exclusive",
})

JAX_ALIASES = frozenset({"jax", "_jax", "_jx"})
CACHED_BUILDERS = frozenset({"cached_pipeline", "_cached_program"})


def _default_allowlist_path() -> str:
    return default_allowlist_path(
        "DONATE_ALLOWLIST_PATH",
        os.path.join("tools", "tpu_donate_allow.txt"))


# ---------------------------------------------------------------------------
# The declared manifest, read straight from donation.py's AST (no engine
# import — the tool must run without jax installed).
# ---------------------------------------------------------------------------
class SpecRow:
    __slots__ = ("site", "argnums", "retry", "reason", "line")

    def __init__(self, site, argnums, retry, reason, line):
        self.site = site
        self.argnums = argnums
        self.retry = retry
        self.reason = reason
        self.line = line

    @property
    def certified(self) -> bool:
        return bool(self.argnums)


def load_manifest(path: str = MANIFEST_PATH) -> Dict[str, SpecRow]:
    """site -> SpecRow from the DONATION_SPECS literal."""
    with open(path, "rb") as f:
        tree = ast.parse(f.read(), filename=path)
    rows: Dict[str, SpecRow] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and attr_chain(node.func) == "DonationSpec"
                and len(node.args) >= 4):
            continue
        site_a, argnums_a, retry_a, reason_a = node.args[:4]
        if not isinstance(site_a, ast.Constant):
            continue
        argnums = tuple(
            e.value for e in ast.walk(argnums_a)
            if isinstance(e, ast.Constant) and isinstance(e.value, int))
        retry = retry_a.value if isinstance(retry_a, ast.Constant) else None
        # reason is usually an implicit concat of string constants
        reason = "".join(
            c.value for c in ast.walk(reason_a)
            if isinstance(c, ast.Constant) and isinstance(c.value, str))
        rows[site_a.value] = SpecRow(
            site_a.value, argnums, retry, reason, node.lineno)
    return rows


# ---------------------------------------------------------------------------
# Per-file checks
# ---------------------------------------------------------------------------
def _is_guard_call(call: ast.Call) -> bool:
    chain = attr_chain(call.func)
    return bool(chain) and chain.split(".")[-1] == "guard"


def _is_jit_like(call: ast.Call) -> bool:
    chain = attr_chain(call.func)
    if chain is None:
        return False
    last = chain.split(".")[-1]
    if last == "pjit":
        return True
    return chain.split(".")[0] in JAX_ALIASES and last == "jit"


def _is_cached_builder_call(call: ast.Call) -> bool:
    chain = attr_chain(call.func)
    return bool(chain) and chain.split(".")[-1] in CACHED_BUILDERS


def _donating_kw(call: ast.Call) -> bool:
    return any(kw.arg in ("donate_argnums", "donate_argnames")
               for kw in call.keywords)


def _guarded_vars(call: ast.Call) -> Set[str]:
    """Names of the batch variable(s) a guard() call donates."""
    if len(call.args) < 2:
        return set()
    b = call.args[1]
    if isinstance(b, ast.Name):
        return {b.id}
    if isinstance(b, (ast.List, ast.Tuple)):
        return {e.id for e in b.elts if isinstance(e, ast.Name)}
    return set()


def _site_of_cached_call(call: ast.Call) -> Optional[str]:
    """The site string of a cached_pipeline/_cached_program call (3rd
    positional for cached_pipeline, site= keyword for either)."""
    for kw in call.keywords:
        if kw.arg == "site" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    if len(call.args) >= 3 and isinstance(call.args[2], ast.Constant) \
            and isinstance(call.args[2].value, str):
        return call.args[2].value
    return None


def _alternative_nodes(with_node: ast.With, parents) -> Set[int]:
    """ids of nodes in branches that are execution ALTERNATIVES to the
    guarded block: the engine's donating dispatches are written
    ``if mask: with guard(...): ... else: <non-donating dispatch>``,
    and the else arm sits textually after the with but never runs after
    a donation — a line-number "later read" check must skip it."""
    out: Set[int] = set()
    cur: ast.AST = with_node
    parent = parents.get(cur)
    while parent is not None and not isinstance(
            parent, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        if isinstance(parent, ast.If):
            in_body = any(cur is s or id(cur) in
                          {id(n) for n in ast.walk(s)}
                          for s in parent.body)
            alt = parent.orelse if in_body else parent.body
            for s in alt:
                out.update(id(n) for n in ast.walk(s))
        cur, parent = parent, parents.get(parent)
    return out


def check_file(path: str, relpath: str,
               manifest: Dict[str, SpecRow]) -> List[Finding]:
    with open(path, "rb") as f:
        try:
            tree = ast.parse(f.read(), filename=path)
        except SyntaxError as e:
            return [Finding(relpath, e.lineno or 0, "TPU200", "<module>",
                            f"syntax error: {e.msg}")]
    parents = parents_map(tree)
    qual_of = qualname_resolver(tree, parents)
    findings: List[Finding] = []

    # functions whose body contains a cached-builder call with donate=
    # (the TPU203 sanctioned regions: a donating jit must sit under one)
    donate_routed: Set[ast.AST] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_cached_builder_call(node) \
                and any(kw.arg == "donate" for kw in node.keywords):
            fn = enclosing_function(node, parents)
            if fn is not None:
                donate_routed.add(fn)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue

        # --- TPU201: batch read after its guarded donating dispatch ---
        if _is_guard_call(node) and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and node.args[0].value in manifest \
                and manifest[node.args[0].value].certified:
            with_node = parents.get(node)
            # guard() must be a `with` item's context expression
            while with_node is not None \
                    and not isinstance(with_node, ast.With):
                with_node = parents.get(with_node)
            if with_node is None:
                continue
            names = _guarded_vars(node)
            if not names:
                continue
            fn = enclosing_function(with_node, parents)
            if fn is None:
                continue
            end = with_node.end_lineno or with_node.lineno
            skip = _alternative_nodes(with_node, parents)
            for n in ast.walk(fn):
                if not (isinstance(n, ast.Name) and n.id in names
                        and isinstance(n.ctx, ast.Load)
                        and (n.lineno or 0) > end
                        and id(n) not in skip):
                    continue
                par = parents.get(n)
                if isinstance(par, ast.Attribute) \
                        and par.attr in SAFE_ATTRS:
                    continue
                findings.append(Finding(
                    relpath, n.lineno, "TPU201", qual_of(n),
                    f"batch {n.id!r} read after its planes donated "
                    f"under guard({node.args[0].value!r}, ...) at line "
                    f"{with_node.lineno} — donated planes are DELETED "
                    "at dispatch; restructure so the guarded dispatch "
                    "is the last plane-reaching use"))

        # --- TPU202 (warn): certified site dispatching with no mask ---
        if _is_cached_builder_call(node):
            site = _site_of_cached_call(node)
            if site is not None and site in manifest \
                    and manifest[site].certified \
                    and not any(kw.arg == "donate" for kw in node.keywords):
                findings.append(Finding(
                    relpath, node.lineno, "TPU202", qual_of(node),
                    f"site {site!r} is donation-certified "
                    f"(donation.py:{manifest[site].line}) but this "
                    "cached_pipeline call plumbs no donate= mask — the "
                    "certified peak-temp win is not being taken"))

        # --- TPU203: donation declared outside cached_pipeline --------
        if _is_jit_like(node) and _donating_kw(node):
            fn = enclosing_function(node, parents)
            routed = False
            while fn is not None:
                if fn in donate_routed:
                    routed = True
                    break
                fn = enclosing_function(fn, parents)
            if not routed:
                findings.append(Finding(
                    relpath, node.lineno, "TPU203", qual_of(node),
                    "donate_argnums declared outside a cached_pipeline "
                    "builder carrying donate= — the mask must fold into "
                    "the structural key and the AOT entry identity, or "
                    "donating and non-donating callers share one cache "
                    "entry"))
    return findings


# ---------------------------------------------------------------------------
# CLI (run_tool semantics, with TPU202 degraded to a warning that never
# affects the exit status)
# ---------------------------------------------------------------------------
def explain(manifest: Dict[str, SpecRow]) -> int:
    for s in manifest.values():
        verdict = (f"CERTIFIED argnums={s.argnums} retry={s.retry}"
                   if s.certified else "NOT CERTIFIED")
        print(f"{s.site}: {verdict}")
        print(f"    {s.reason}")
    return 0


def main(argv: List[str]) -> int:
    manifest = load_manifest()
    if "--explain" in argv:
        return explain(manifest)
    args = [a for a in argv if not a.startswith("--")]
    target = os.path.abspath(args[0]) if args else DEFAULT_TARGET
    allow_path = _default_allowlist_path()
    for a in argv:
        if a.startswith("--allowlist="):
            allow_path = a.split("=", 1)[1]
    if not os.path.exists(target):
        print(f"tpu_donate: no such target {target}", file=sys.stderr)
        return 2
    allowed = load_allowlist(allow_path)
    errors: List[Finding] = []
    warnings_: List[Finding] = []
    used: Set[str] = set()
    for path in iter_py_files(target):
        rel = os.path.relpath(path, REPO_ROOT)
        for f in check_file(path, rel, manifest):
            if f.key() in allowed:
                used.add(f.key())
                continue
            (warnings_ if f.rule == "TPU202" else errors).append(f)
    for f in errors:
        print(str(f))
    for f in warnings_:
        print(f"warning: {f}")
    stale = allowed - used
    if stale and "--strict-allowlist" in argv:
        for s in sorted(stale):
            print(f"tpu_donate: stale allowlist entry: {s}",
                  file=sys.stderr)
        return 1
    if errors:
        print(f"tpu_donate: {len(errors)} finding(s), "
              f"{len(warnings_)} warning(s) ({len(used)} allowlisted)",
              file=sys.stderr)
        return 1
    if warnings_:
        print(f"tpu_donate: clean with {len(warnings_)} warning(s) "
              f"({len(used)} allowlisted site(s))")
        return 0
    print(f"tpu_donate: clean ({len(used)} allowlisted site(s))")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
