"""Shared AST-walk + allowlist machinery for the repo-directed analysis
tools (tools/tpu_lint.py — tracing hazards TPU001–004 — and
tools/tpu_racecheck.py — concurrency hazards TPU101–104).

Both tools have the same skeleton: walk a target tree of .py files,
parse each with ``ast`` (no imports, so they run without jax), produce
``Finding``s keyed ``relpath::qualname::RULE``, filter them through a
conf-named allowlist file, and exit 0 clean / 1 findings / 2 usage
error — with ``--strict-allowlist`` turning stale allowlist entries
into failures. This module is that skeleton; the rule logic stays in
the tools.
"""
from __future__ import annotations

import ast
import os
import sys
from typing import Callable, Dict, List, Optional, Set

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class Finding:
    __slots__ = ("path", "line", "rule", "qualname", "message")

    def __init__(self, path, line, rule, qualname, message):
        self.path = path
        self.line = line
        self.rule = rule
        self.qualname = qualname
        self.message = message

    def key(self) -> str:
        return f"{self.path}::{self.qualname}::{self.rule}"

    def __str__(self):
        return (f"{self.path}:{self.line}: {self.rule} [{self.qualname}] "
                f"{self.message}")


def load_allowlist(path: str) -> Set[str]:
    allowed: Set[str] = set()
    if not os.path.exists(path):
        return allowed
    with open(path) as f:
        for raw in f:
            line = raw.split("#", 1)[0].strip()
            if line:
                allowed.add(line)
    return allowed


def default_allowlist_path(conf_attr: str, fallback: str) -> str:
    """Resolve the tool's allowlist path from its conf entry (so the
    location is documented in docs/configs.md), falling back to the
    literal when the engine can't import (the tools must run bare)."""
    try:
        sys.path.insert(0, REPO_ROOT)
        import spark_rapids_tpu.conf as _conf

        entry = getattr(_conf, conf_attr)
        return os.path.join(REPO_ROOT, entry.default)
    except Exception:  # noqa: BLE001 — tools must run without deps
        return os.path.join(REPO_ROOT, fallback)


def attr_chain(node: ast.AST) -> Optional[str]:
    """'jax.device_get' for Attribute(Name('jax'), 'device_get'), else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def function_defs(tree: ast.AST) -> Dict[ast.AST, str]:
    """Every function/lambda node -> qualname."""
    out: Dict[ast.AST, str] = {}

    def walk(node, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out[child] = ".".join(stack + [child.name])
                walk(child, stack + [child.name])
            elif isinstance(child, ast.Lambda):
                out[child] = ".".join(stack + ["<lambda>"])
                walk(child, stack)
            elif isinstance(child, ast.ClassDef):
                walk(child, stack + [child.name])
            else:
                walk(child, stack)

    walk(tree, [])
    return out


def parents_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    par: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            par[child] = node
    return par


def enclosing_function(node, parents):
    cur = parents.get(node)
    while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        cur = parents.get(cur)
    return cur


def qualname_resolver(tree: ast.AST, parents) -> Callable[[ast.AST], str]:
    """node -> qualname of its nearest enclosing function (or <module>)."""
    qualnames = function_defs(tree)

    def qual_of(node) -> str:
        fn = node if node in qualnames else enclosing_function(node, parents)
        while fn is not None and fn not in qualnames:
            fn = enclosing_function(fn, parents)
        return qualnames.get(fn, "<module>")

    return qual_of


def iter_py_files(target: str):
    for dirpath, dirnames, filenames in os.walk(target):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def run_tool(tool: str, argv: List[str], default_target: str,
             default_allow_path: str,
             check_file: Callable[[str, str], List[Finding]]) -> int:
    """The shared CLI driver: positional target dir, --allowlist=PATH,
    --strict-allowlist. Exit 0 clean, 1 findings/stale, 2 usage error.
    ``check_file(abspath, relpath)`` supplies the tool's rules."""
    args = [a for a in argv if not a.startswith("--")]
    target = os.path.abspath(args[0]) if args else default_target
    allow_path = default_allow_path
    for a in argv:
        if a.startswith("--allowlist="):
            allow_path = a.split("=", 1)[1]
    if not os.path.exists(target):
        print(f"{tool}: no such target {target}", file=sys.stderr)
        return 2
    allowed = load_allowlist(allow_path)
    findings: List[Finding] = []
    used: Set[str] = set()
    for path in iter_py_files(target):
        rel = os.path.relpath(path, REPO_ROOT)
        for f in check_file(path, rel):
            if f.key() in allowed:
                used.add(f.key())
                continue
            findings.append(f)
    for f in findings:
        print(str(f))
    stale = allowed - used
    if stale and "--strict-allowlist" in argv:
        for s in sorted(stale):
            print(f"{tool}: stale allowlist entry: {s}", file=sys.stderr)
        return 1
    if findings:
        print(f"{tool}: {len(findings)} finding(s) "
              f"({len(used)} allowlisted)", file=sys.stderr)
        return 1
    print(f"{tool}: clean ({len(used)} allowlisted site(s))")
    return 0
