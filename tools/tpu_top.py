#!/usr/bin/env python
"""The engine's htop: a refresh-loop terminal view over /status.

Point it at a session started with
``spark.rapids.tpu.metrics.http.enabled`` (the session prints its
address via ``TpuSession.obs_address``) and it renders, once per
interval:

  * live + recent queries with per-op progress bars — numerators from
    record_batch, denominators from the static plan analyzer's row/batch
    forecasts (an unbounded op shows its counts without a bar);
  * the HBM watermark vs the derived budget (the same derive_hbm_budget
    the spiller and the plan analyzer use) and the spill story;
  * the HBM ledger's heap panel: live bytes by owning op and the leak
    sentinel's tally (present when the ledger is armed);
  * watchdog alerts (stall / hbm_pressure / recompile_storm /
    retry_storm / buffer_leak);
  * a counter footer: compile misses, shuffle traffic, scan-cache hit
    rate, host-link transfers.

Usage:
  python tools/tpu_top.py --url http://127.0.0.1:PORT [--interval 2]
  python tools/tpu_top.py --url ... --once          # one frame, no clear
"""
from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import List, Optional

BAR_WIDTH = 24


def fetch_status(url: str, timeout: float = 5.0) -> dict:
    with urllib.request.urlopen(url.rstrip("/") + "/status",
                                timeout=timeout) as r:
        return json.loads(r.read().decode())


def _bar(frac: Optional[float], width: int = BAR_WIDTH) -> str:
    if frac is None:
        return "·" * width + "   n/a"
    frac = max(0.0, min(1.0, frac))
    fill = int(round(frac * width))
    return "#" * fill + "-" * (width - fill) + f" {frac * 100:5.1f}%"


def _mb(v: Optional[float]) -> str:
    return "-" if v is None else f"{v / 1e6:.1f}MB"


def _metric_total(metrics: dict, name: str, match: str = "") -> float:
    """Sum one family's series, optionally filtered on a label substring."""
    return sum(v for k, v in (metrics.get(name) or {}).items()
               if match in k)


def render_status(status: dict, clock: str = "") -> str:
    """One frame of the display (pure function — tests feed canned
    payloads; the loop only fetches and clears the screen)."""
    lines: List[str] = []
    live = status.get("queries_live", 0)
    lines.append(f"tpu_top {clock}  queries live={live}")

    # environment provenance (envinfo via /status): whether the numbers
    # on screen are device-backed or the CPU fallback's, at a glance
    env = status.get("env")
    if env:
        lines.append(
            f"env  backend={env.get('backend')} "
            f"device={env.get('device_kind')} x{env.get('device_count')} "
            f"jax={env.get('jax_version')}")

    hbm = status.get("hbm") or {}
    budget = hbm.get("budget_bytes")
    dev = hbm.get("device_bytes", 0)
    frac = (dev / budget) if budget else None
    lines.append(
        f"HBM  [{_bar(frac)}]  {_mb(dev)} of "
        f"{_mb(budget) if budget else 'unlimited'} "
        f"(peak {_mb(hbm.get('peak_device_bytes', 0))}, "
        f"spilled {_mb(hbm.get('spilled_bytes', 0))})")

    # per-buffer heap panel (the HBM ledger's /status block): who owns
    # the live bytes, and whether the leak sentinel has flagged anything
    heap = status.get("heap") or {}
    if heap.get("live_bytes") or heap.get("leaked") \
            or heap.get("leaked_total"):
        owners = ", ".join(f"{op} {_mb(b)}"
                           for op, b in (heap.get("top") or [])) or "none"
        lines.append(
            f"heap {_mb(heap.get('live_bytes', 0))} attributed — "
            f"top: {owners}")
        leaked = heap.get("leaked", 0)
        if leaked or heap.get("leaked_total"):
            lines.append(
                f"heap LEAKS: {leaked} live "
                f"({heap.get('leaked_total', 0)} total flagged)")

    alerts = status.get("alerts") or []
    for a in alerts[-5:]:
        lines.append(f"ALERT [{a.get('kind')}] {a.get('detail')} "
                     f"value={a.get('value'):g} "
                     f"threshold={a.get('threshold'):g}")

    serve = status.get("serve")
    if serve:
        st = serve.get("stats") or {}
        lines.append(
            f"serve: {st.get('active', 0)} running, "
            f"{st.get('waiting', 0)} queued  "
            f"(admitted={st.get('admitted', 0)} "
            f"queued={st.get('queued', 0)} "
            f"rejected={st.get('rejected', 0)} "
            f"timeouts={st.get('timeouts', 0)})")
        for a in serve.get("active") or []:
            rm = a.get("running_ms")
            lines.append(
                f"  > {a.get('session')} plan={a.get('digest')} "
                f"forecast={_mb(a.get('forecast_bytes'))}"
                + (f" running {rm:.0f}ms" if rm is not None else "")
                + (" [bypass]" if a.get("bypass") else ""))
        for q in serve.get("queue") or []:
            lines.append(
                f"  #{q.get('position')} {q.get('session')} "
                f"plan={q.get('digest')} "
                f"waited {q.get('waited_ms', 0):.0f}ms — "
                f"{q.get('reason')}")

    pc = status.get("program_cache")
    if pc:
        lines.append(
            f"AOT cache: {pc.get('hits', 0)} hit / "
            f"{pc.get('misses', 0)} miss / {pc.get('puts', 0)} put"
            + (f" / {pc.get('evictions', 0)} evict"
               if pc.get("evictions") else "")
            + (f" / {pc.get('corrupt', 0)} corrupt"
               if pc.get("corrupt") else "")
            + (f"  saved ~{pc.get('saved_ms', 0) / 1e3:.1f}s compile "
               f"(paid {pc.get('warm_ms', 0) / 1e3:.2f}s warm)"
               if pc.get("hits") else ""))

    lines.append("")
    queries = status.get("queries") or []
    if not queries:
        lines.append("no queries yet")
    for q in queries:
        state = q.get("state", "?")
        mark = {"running": ">", "finished": " ", "failed": "!"}.get(
            state, "?")
        lines.append(
            f"{mark} query {q.get('query_id')} [{state}] "
            f"plan={q.get('plan_digest')} "
            f"elapsed={q.get('elapsed_ms', 0):.0f}ms"
            + (f" rows={q['rows_out']}"
               if q.get("rows_out") is not None else ""))
        for op in q.get("ops") or []:
            rf = op.get("rows_forecast")
            bf = op.get("batches_forecast")
            # same fallback order as the progress fraction: a lazy row
            # count (still a device scalar) shows its batch denominator
            if rf and op.get("rows"):
                detail = f"rows {op.get('rows', 0)}/{rf}"
            elif bf:
                detail = f"batches {op.get('batches', 0)}/{bf}"
            else:
                detail = (f"rows {op.get('rows', 0)} "
                          f"batches {op.get('batches', 0)} (unbounded)")
            lines.append(f"    {op.get('op', '?'):<24} "
                         f"[{_bar(op.get('progress'))}] {detail}")

    m = status.get("metrics") or {}
    hits = _metric_total(m, "tpu_scan_cache_ops", "op=hit")
    misses = _metric_total(m, "tpu_scan_cache_ops", "op=miss")
    seen = hits + misses
    lines.append("")
    lines.append(
        "compile misses: "
        f"{_metric_total(m, 'tpu_compile_misses'):g}   "
        "shuffle: "
        f"{_mb(_metric_total(m, 'tpu_shuffle_bytes', 'direction=write'))} w"
        f" / {_mb(_metric_total(m, 'tpu_shuffle_bytes', 'direction=fetch'))}"
        " f   scan cache: "
        + (f"{hits / seen * 100:.0f}% hit" if seen else "no activity")
        + "   transfers: "
        f"{_mb(_metric_total(m, 'tpu_transfer_bytes'))}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="live terminal view over a spark_rapids_tpu /status "
                    "endpoint (see module docstring)")
    ap.add_argument("--url", required=True,
                    help="exporter base URL (TpuSession.obs_address), "
                         "e.g. http://127.0.0.1:9090")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period in seconds")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (no screen clearing)")
    args = ap.parse_args(argv)

    while True:
        try:
            status = fetch_status(args.url)
        except (urllib.error.URLError, OSError) as e:
            print(f"cannot reach {args.url}: {e}", file=sys.stderr)
            return 1
        frame = render_status(status, clock=time.strftime("%H:%M:%S"))
        if args.once:
            print(frame)
            return 0
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        time.sleep(args.interval)


if __name__ == "__main__":
    raise SystemExit(main())
