#!/usr/bin/env python
"""Offline query profiler over structured event logs.

The rapids-4-spark profiling-tool analog: consume one or more JSONL event
logs produced by ``spark.rapids.tpu.eventLog.dir`` (spark_rapids_tpu/
events.py) and answer "where did this query's time and memory actually go,
and did it regress since last run?" without re-running anything.

Report sections:
  * queries           — per-query duration, rows, plan digest, fallbacks
  * top ops           — top-N operators by device time (host time when no
                        deviceSync lane was recorded), batches/rows/bytes
  * compile misses    — per-site counts, storm flag at/over the threshold
  * roofline          — per compile site: harvested XLA cost
                        (program_cost events) joined against the op_span
                        device lane into achieved GB/s and FLOP/s versus
                        the backend's declared peaks, a bandwidth- vs
                        compute-limited classification, the program
                        furthest below roofline, and the analyzer-bound
                        vs XLA-bytes delta (XLA above the bound means the
                        kernel materializes intermediates the layout
                        model doesn't know about — the roofline-push
                        lead, not a violation)
  * hlo               — per-fusion byte attribution of each harvested
                        program (hlo_summary events): per compile site,
                        the top-bytes fusion with its idiom
                        classification (scatter-add / one-hot dot /
                        gather / transpose-copy / collective) and its
                        share of the site's XLA bytes-accessed — the
                        instruction-level culprit behind a byte
                        amplification, plus parse coverage
  * transfers         — host-link bytes each way + sync-point count
  * shuffle           — pieces/bytes/rows each way, per codec
  * spill timeline    — every spill/unspill with the live device-byte
                        watermark, plus the peak
  * resilience        — OOM recovery actions (oom_retry events by
                        op/kind: retry, split, requeue, fused-plan
                        fallback) and split-and-retry halvings
                        (batch_split events with max depth) — how often
                        forecasts were wrong and what recovery cost;
                        plus the shuffle section's fetch-retry line
  * scan cache        — hit/miss/evict counts and bytes
  * forecast vs actual— the static plan analyzer's bounds (plan_analysis
                        events) diffed against measured compile misses and
                        per-op bytes; any measured value above its bound is
                        a VIOLATION (the offline twin of the test
                        harness's analysis cross-check) and makes the exit
                        code nonzero so CI catches emitter/analyzer drift

Diff mode (``--diff A B``): compare two event logs (per-op host/device
time and bytes, per-site XLA bytes/temp, per-site top-fusion bytes and
scatter counts from hlo_summary events) or two bench JSON result files
(``BENCH_*.json`` — the ``per_shape`` block's tpu_ms/device_ms plus the
hlo_top_fusion_bytes/hlo_scatter_count gates). Regressions beyond
``--threshold`` (default 20%) are flagged and make the exit code
nonzero. When the two runs' ``env`` provenance blocks name different
hardware (backend/device kind), a loud ENVIRONMENTS DIFFER banner
prints first — structural gates stay meaningful, time ratios do not.

Alert replay (``--alerts``): run the LIVE watchdog's rules
(obs/watchdog.py — stall, hbm_pressure, recompile_storm) over a recorded
log, so thresholds are tuned against production recordings instead of
guesses: lower ``--stall-ms`` until the known-slow op fires, check the
pressure fraction against a run that actually spilled. The HBM budget
comes from the log's plan_analysis events unless ``--budget`` overrides.

Usage:
  python tools/tpu_profile.py LOG.jsonl [LOG2.jsonl ...] [--top N]
  python tools/tpu_profile.py --diff OLD NEW [--threshold 0.2]
  python tools/tpu_profile.py LOG.jsonl --alerts [--stall-ms 30000]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

DEFAULT_STORM_THRESHOLD = 8
#: time deltas under this (ns) are measurement noise, never a regression
#: (also applied to harvested compile-time deltas in --diff: trace/
#: compile jitter below the floor is never flagged)
DIFF_MIN_NS = 1_000_000
#: same floor for bench-JSON ms fields (0.1ms of scheduler jitter on a
#: 0.3ms shape is a 1.33x "ratio", not a regression)
DIFF_MIN_MS = 1.0
#: hbm_frac_* gates only fire when the OLD run's fraction was above this
#: floor — below it the figure is quantization noise and any ratio is
#: meaningless (must sit under the committed BENCH shape values, which
#: run ~2e-4..6e-3 on the CPU fallback, or the gate is dead exactly
#: where CI runs it)
DIFF_MIN_FRAC = 1e-4
#: per-op HBM peak growth below this many bytes is allocator jitter
#: (padding, pool rounding), not an operator holding more memory
DIFF_MIN_HBM_BYTES = 1 << 20

#: per-backend (peak HBM GB/s, peak TFLOP/s) used when --peak-hbm-gbps /
#: --peak-tflops are not given; MUST mirror
#: spark_rapids_tpu.xla_cost.BACKEND_PEAKS (tests/test_program_cost.py
#: pins the two in sync — duplicated here so the offline tool never
#: needs to import jax just to read a constant)
BACKEND_PEAKS = {
    "tpu": (819.0, 197.0),
    "gpu": (900.0, 19.5),
    "cpu": (100.0, 1.0),
}


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------
def load_events(paths: List[str]) -> List[dict]:
    """Events from JSONL files (directories expand to their *.jsonl),
    merged and sorted by timestamp."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if f.endswith(".jsonl")))
        else:
            files.append(p)
    out: List[dict] = []
    for f in files:
        with open(f) as fh:
            for i, line in enumerate(fh):
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError as e:
                    raise SystemExit(
                        f"{f}:{i + 1}: not a JSONL event log ({e})")
    out.sort(key=lambda r: r.get("ts", 0))
    return out


def _is_bench_json(path: str) -> bool:
    try:
        with open(path) as f:
            head = f.read(1 << 20)
        return (("per_shape" in head or "cold_start" in head)
                and path.endswith(".json"))
    except OSError:
        return False


def _is_multichip_json(path: str) -> bool:
    """A MULTICHIP_*.json (bench.py --mesh payload): mesh_scaling metric,
    or the legacy dry-run {n_devices, ok} format."""
    try:
        with open(path) as f:
            head = f.read(1 << 20)
    except OSError:
        return False
    if not path.endswith(".json"):
        return False
    return "mesh_scaling" in head or (
        "n_devices" in head and "per_shape" not in head)


def _ms(ns: Optional[float]) -> str:
    return "-" if ns is None else f"{ns / 1e6:.1f}ms"


def _mb(b: Optional[float]) -> str:
    return "-" if b is None else f"{b / 1e6:.2f}MB"


# ---------------------------------------------------------------------------
# environment provenance (envinfo.environment_info blocks riding on
# query_start events and BENCH json top levels)
# ---------------------------------------------------------------------------
def _env_of(events: List[dict]) -> Optional[dict]:
    """The first query_start env block in a log (None for pre-provenance
    logs — the session stamps every query_start, so one is enough)."""
    for r in events:
        if r.get("event") == "query_start" and r.get("env"):
            return r["env"]
    return None


def _env_str(env: Optional[dict]) -> str:
    if not env:
        return "backend=?"
    return (f"backend={env.get('backend')} "
            f"device={env.get('device_kind')} "
            f"x{env.get('device_count')} "
            f"jax={env.get('jax_version')}")


def _envs_differ(a: Optional[dict], b: Optional[dict]) -> bool:
    """Same rule as spark_rapids_tpu.envinfo.environments_differ (kept
    local so the offline tool stays import-free; tests/test_hlo.py pins
    the two in agreement): different backend or device kind means
    absolute times and HBM fractions are NOT comparable. Missing blocks
    (pre-provenance logs) never differ — no evidence, no warning."""
    if not a or not b:
        return False
    return (a.get("backend") != b.get("backend")
            or a.get("device_kind") != b.get("device_kind"))


def _env_warning(old_env: Optional[dict], new_env: Optional[dict]
                 ) -> List[str]:
    """Loud comparability banner for --diff when the two runs name
    different hardware (the recurring CPU-fallback-vs-device confusion:
    a 10x 'regression' between a device round and a tunnel-down fallback
    round is an environment change, not a kernel change)."""
    if not _envs_differ(old_env, new_env):
        return []
    return [
        "  !!! ENVIRONMENTS DIFFER — timings are NOT comparable !!!",
        f"  !!! old: {_env_str(old_env)}",
        f"  !!! new: {_env_str(new_env)}",
        "  !!! trust structural gates only (strategy/lowering/scatter "
        "counts), not time or HBM-fraction ratios",
    ]


# ---------------------------------------------------------------------------
# aggregation
# ---------------------------------------------------------------------------
class OpStats:
    __slots__ = ("host_ns", "device_ns", "batches", "rows", "bytes")

    def __init__(self):
        self.host_ns = 0
        self.device_ns = 0
        self.batches = 0
        self.rows = 0
        self.bytes = 0


def aggregate_ops(events: List[dict]) -> Dict[str, OpStats]:
    ops: Dict[str, OpStats] = defaultdict(OpStats)
    for r in events:
        ev = r.get("event")
        if ev == "op_span":
            s = ops[r["op"]]
            if r.get("lane") == "device":
                s.device_ns += r["dur"]
            else:
                s.host_ns += r["dur"]
        elif ev == "op_batch":
            s = ops[r["op"]]
            s.batches += 1
            s.rows += r.get("rows") or 0
            s.bytes += r.get("bytes") or 0
    return dict(ops)


def _query_windows(events: List[dict]) -> List[dict]:
    """One record per query: start/end ts, duration, rows, tagging and
    analysis payloads, and the events inside its window.

    Concurrency-aware: under the serving scheduler, sessions interleave,
    so (a) queries are keyed by (emitting thread, query_id) — per-session
    query counters collide across sessions in a merged log — and (b)
    when a query's ts window overlaps another's, its events are filtered
    to the records its own drain thread emitted (every record carries
    ``tid``; the same by-thread attribution the live progress tracker
    uses). Serial single-session logs behave exactly as before."""
    queries: Dict[object, dict] = {}
    order: List[dict] = []

    def qkey(r: dict) -> tuple:
        return (r.get("tid"), r.get("query_id"))

    def _fallback(r: dict) -> Optional[dict]:
        """query_end drained on a different thread than planning (the
        writer path): match the open query with this query_id."""
        for q in order:
            if q["query_id"] == r.get("query_id") and q["end"] is None:
                return q
        return None

    for r in events:
        ev = r.get("event")
        if ev == "query_start":
            q = {"query_id": r.get("query_id"), "start": r["ts"],
                 "end": None, "dur": None, "rows": None,
                 "tid": r.get("tid"),
                 "plan_digest": r.get("plan_digest"),
                 "tagged": None, "analysis": None}
            queries[qkey(r)] = q
            order.append(q)
        elif ev == "plan_tagged":
            q = queries.get(qkey(r)) or _fallback(r)
            if q is not None:
                q["tagged"] = r
        elif ev == "plan_analysis":
            q = queries.get(qkey(r)) or _fallback(r)
            if q is not None:
                q["analysis"] = r
        elif ev == "query_end":
            q = queries.get(qkey(r))
            if q is None or q["end"] is not None:
                q = _fallback(r)
            if q is not None:
                q["end"] = r["ts"]
                q["dur"] = r.get("dur")
                q["rows"] = r.get("rows")
    for q in order:
        lo, hi = q["start"], q["end"] if q["end"] is not None else float("inf")
        overlaps = any(
            o is not q and q["start"] <= (o["end"] or float("inf"))
            and o["start"] <= hi for o in order)
        q["events"] = [
            r for r in events
            if lo <= r.get("ts", 0) <= hi
            and (not overlaps or q["tid"] is None
                 or r.get("tid") in (None, q["tid"]))
        ]
    return order


def roofline_section(events: List[dict], queries: List[dict],
                     peak_gbps: Optional[float] = None,
                     peak_tflops: Optional[float] = None,
                     ops: Optional[Dict[str, "OpStats"]] = None
                     ) -> List[str]:
    """Join each compile site's harvested XLA cost (program_cost events)
    against its op's measured device lane: achieved GB/s and FLOP/s vs
    the declared peaks, limiter classification, the program furthest
    below roofline, and the analyzer-bound vs XLA-bytes delta.

    Honest accounting: ``bytes_accessed``/``flops`` are PER-INVOCATION
    figures of each distinct compiled program, summed once each — so the
    achieved numbers are lower bounds that are exact for a cold
    single-dispatch run (the bench/CI case) and conservative when
    programs re-dispatched. An op's measured lane is ONE denominator:
    sites sharing an op (the aggregate compiles at agg_update AND
    agg_plan inside the same op_timed scope) get one combined
    ``op=...`` achieved line over the group's summed bytes instead of
    each dividing by the op's whole lane (which would double-count time
    and understate every row). Sites whose backend reported partial
    cost keys (the CPU fallback) degrade to partial rows, never
    errors."""
    costs = [r for r in events if r.get("event") == "program_cost"]
    lines = ["== roofline =="]
    if not costs:
        lines.append("  no program_cost events (cost plane saw no compile"
                     " misses — warm caches, or the log predates it)")
        return lines
    backend = next((r.get("backend") for r in costs if r.get("backend")),
                   None)
    dg, dt = BACKEND_PEAKS.get(backend or "", BACKEND_PEAKS["cpu"])
    # peak resolution: CLI flag > conf-declared peaks riding in the
    # events (spark.rapids.tpu.roofline.* at harvest time — the only
    # channel a session conf has to this offline tool) > backend default
    logged_g = next((r.get("peak_hbm_gbps") for r in costs
                     if r.get("peak_hbm_gbps")), None)
    logged_t = next((r.get("peak_tflops") for r in costs
                     if r.get("peak_tflops")), None)
    peak_gbps = peak_gbps or logged_g or dg
    peak_tflops = peak_tflops or logged_t or dt
    if ops is None:
        ops = aggregate_ops(events)
    # analyzer comparison is PER QUERY: each query's own (site, op) XLA
    # traffic against ITS analyzer bound — a merged multi-query log must
    # not sum ten queries' bytes against one query's bound, and an op
    # must not be charged a site-mate's bytes
    per_q: Dict[Tuple[str, str], List[Tuple[float, int]]] = defaultdict(list)
    for q in queries:
        qb = (q.get("analysis") or {}).get("bytes_by_op") or {}
        acc: Dict[Tuple[str, str], float] = defaultdict(float)
        for r in q.get("events", []):
            if (r.get("event") == "program_cost" and r.get("op")
                    and r.get("bytes_accessed") is not None):
                acc[(r.get("site"), r["op"])] += r["bytes_accessed"]
        for (site, op), xb in acc.items():
            if qb.get(op) is not None:
                per_q[(site, op)].append((xb, qb[op]))
    sites: Dict[str, dict] = {}
    for r in costs:
        s = sites.setdefault(r.get("site"), {
            "programs": 0, "bytes": 0.0, "flops": 0.0, "temp": 0,
            "compile_ms": 0.0, "ops": set(), "partial": False,
            "by_op": {}})
        s["programs"] += 1
        s["compile_ms"] += (r.get("trace_ms") or 0) + (r.get("compile_ms")
                                                       or 0)
        if r.get("bytes_accessed") is None:
            s["partial"] = True
        else:
            s["bytes"] += r["bytes_accessed"]
        if r.get("flops") is not None:
            s["flops"] += r["flops"]
        if r.get("temp_bytes") is not None:
            s["temp"] = max(s["temp"], r["temp_bytes"])
        if r.get("op"):
            s["ops"].add(r["op"])
            d = s["by_op"].setdefault(r["op"], {"bytes": 0.0, "flops": 0.0})
            d["bytes"] += r.get("bytes_accessed") or 0
            d["flops"] += r.get("flops") or 0
    lines.append(f"  peaks: {peak_gbps:.0f} GB/s, {peak_tflops:.1f} "
                 f"TFLOP/s (backend {backend or '?'}; override with "
                 "spark.rapids.tpu.roofline.peakHbmGBps/.peakTflops or "
                 "--peak-hbm-gbps/--peak-tflops)")
    cached_n = sum(1 for r in costs if r.get("from_cache"))
    if cached_n:
        # AOT program cache (serve/program_cache.py): these programs'
        # bytes/flops are the ORIGINAL harvest re-emitted on a
        # deserialize hit; their compile_ms is this process's near-zero
        # warm cost, so per-site compile seconds read honestly
        lines.append(f"  {cached_n}/{len(costs)} program(s) served "
                     "from the AOT cache (bytes/flops persisted at "
                     "original compile; compile ms = warm deserialize "
                     "cost)")
    # which sites claim each op: ops claimed by >1 site get ONE combined
    # achieved line (the op's lane is one denominator, not one per site)
    op_claims: Dict[str, set] = {}
    for site, s in sites.items():
        for o in s["ops"]:
            op_claims.setdefault(o, set()).add(site)
    shared_ops = {o for o, cl in op_claims.items() if len(cl) > 1}
    by_shared_op: Dict[str, dict] = {}
    for r in costs:
        o = r.get("op")
        if o in shared_ops:
            d = by_shared_op.setdefault(o, {"bytes": 0.0, "flops": 0.0})
            d["bytes"] += r.get("bytes_accessed") or 0
            d["flops"] += r.get("flops") or 0

    def achieved(t_ns: float, lane: str, nbytes: float, nflops: float
                 ) -> Tuple[str, float, str]:
        gbps = nbytes / t_ns          # bytes/ns == GB/s
        tflops = nflops / t_ns / 1e3  # flops/ns == GFLOP/s
        bw_frac = gbps / peak_gbps if peak_gbps else 0.0
        fl_frac = tflops / peak_tflops if peak_tflops else 0.0
        limiter = ("bandwidth-limited" if bw_frac >= fl_frac
                   else "compute-limited")
        return (f"achieved[{lane}]={gbps:.3f}GB/s "
                f"({bw_frac * 100:.2f}% of peak) "
                f"{tflops * 1e3:.3f}GFLOP/s "
                f"({fl_frac * 100:.2f}%) -> {limiter}",
                max(bw_frac, fl_frac), limiter)

    worst: Optional[Tuple[float, str, str]] = None
    for site, s in sorted(sites.items()):
        opl = ",".join(sorted(s["ops"])) or "?"
        row = (f"  site={site} op={opl} programs={s['programs']} "
               f"compile={s['compile_ms']:.1f}ms "
               f"xla_bytes={_mb(s['bytes']) if s['bytes'] else '-'}")
        if s["temp"]:
            row += f" peak_temp={_mb(s['temp'])}"
        # a site's own achieved figure covers only the ops it owns
        # EXCLUSIVELY (shared ops render on the combined lines below);
        # a mixed site still gets a row for its exclusive share
        excl = [o for o in s["ops"] if o not in shared_ops]
        ex_bytes = s["bytes"] - sum(s["by_op"][o]["bytes"]
                                    for o in s["ops"] if o in shared_ops)
        ex_flops = s["flops"] - sum(s["by_op"][o]["flops"]
                                    for o in s["ops"] if o in shared_ops)
        dev_ns = sum(ops[o].device_ns for o in excl if o in ops)
        host_ns = sum(ops[o].host_ns for o in excl if o in ops)
        t_ns, lane = (dev_ns, "device") if dev_ns else (host_ns, "host")
        if t_ns and (ex_bytes or ex_flops):
            txt, score, limiter = achieved(t_ns, lane, ex_bytes, ex_flops)
            row += " " + txt
            if worst is None or score < worst[0]:
                worst = (score, site, limiter)
        elif s["partial"] and not s["bytes"]:
            row += " (backend reported no byte/flop cost keys)"
        lines.append(row)
        for o in sorted(s["ops"]):
            pairs = per_q.get((site, o))
            if not pairs:
                continue
            # show the worst single query (largest overshoot)
            xb, b = max(pairs, key=lambda t: t[0] - t[1])
            if xb > b:
                lines.append(
                    f"    {o}: XLA touches {_mb(xb)} > analyzer "
                    f"bound {_mb(b)} (+{_mb(xb - b)} materialized "
                    "intermediates — roofline-push lead)")
            else:
                lines.append(
                    f"    {o}: XLA touches {_mb(xb)} <= analyzer "
                    f"bound {_mb(b)}")
    for o in sorted(shared_ops):
        st = ops.get(o)
        d = by_shared_op.get(o, {})
        if st is None or not (d.get("bytes") or d.get("flops")):
            continue
        t_ns, lane = ((st.device_ns, "device") if st.device_ns
                      else (st.host_ns, "host"))
        if not t_ns:
            continue
        group = "+".join(sorted(op_claims[o]))
        txt, score, limiter = achieved(t_ns, lane, d["bytes"], d["flops"])
        lines.append(f"  op={o} sites={group} {txt}")
        if worst is None or score < worst[0]:
            worst = (score, f"{o} ({group})", limiter)
    if worst is not None:
        lines.append(f"  furthest below roofline: {worst[1]} at "
                     f"{worst[0] * 100:.2f}% of peak ({worst[2]})")
    return lines


def hlo_section(events: List[dict]) -> List[str]:
    """``== hlo ==``: per-fusion byte attribution joined to its compile
    site (hlo_summary events, emitted beside each program_cost twin by
    spark_rapids_tpu/hlo.py). Per site: programs parsed, the summed
    shape-level byte attribution, worst parse coverage, module scatter
    count, and the AMPLIFICATION CULPRIT — the single top-bytes fusion
    with its idiom classification and its share of the site's XLA
    bytes-accessed ("agg_update: fusion.7 [scatter-add] accounts for
    12.1MB of 19.4MB"). Coverage < 1 or a low accounted fraction means
    the text parse explains only part of the compiler's figure (XLA
    utilization-weights bytes inside fusions/loop bodies) — reported,
    never an error."""
    sums = [r for r in events if r.get("event") == "hlo_summary"]
    lines = ["== hlo =="]
    if not sums:
        lines.append("  no hlo_summary events (cost plane saw no compile"
                     " misses, or the log predates per-fusion attribution)")
        return lines
    # the program_cost twin's compiler-reported bytes, by (site, digest)
    xla: Dict[Tuple[str, str], float] = defaultdict(float)
    for r in events:
        if (r.get("event") == "program_cost"
                and r.get("bytes_accessed") is not None):
            xla[(r.get("site"), r.get("digest"))] += r["bytes_accessed"]
    sites: Dict[str, dict] = {}
    for r in sums:
        s = sites.setdefault(r.get("site"), {
            "programs": 0, "bytes": 0, "xla": 0.0, "cov": 1.0,
            "scatters": 0, "ops": set(), "top": None})
        s["programs"] += 1
        s["bytes"] += r.get("total_bytes") or 0
        s["xla"] += xla.get((r.get("site"), r.get("digest")), 0.0)
        if r.get("coverage") is not None:
            s["cov"] = min(s["cov"], r["coverage"])
        s["scatters"] += r.get("scatter_count") or 0
        if r.get("op"):
            s["ops"].add(r["op"])
        for f in r.get("top_fusions") or []:
            if s["top"] is None or (f.get("bytes") or 0) > s["top"]["bytes"]:
                s["top"] = {"name": f.get("name"), "class": f.get("class"),
                            "bytes": f.get("bytes") or 0}
    worst: Optional[Tuple[float, str]] = None
    for site, s in sorted(sites.items()):
        opl = ",".join(sorted(s["ops"]))
        lines.append(
            f"  site={site}" + (f" op={opl}" if opl else "")
            + f" programs={s['programs']} attributed={_mb(s['bytes'])}"
            + f" coverage={s['cov']:.2f}"
            + (f" scatters={s['scatters']}" if s["scatters"] else ""))
        top = s["top"]
        if top is None:
            continue
        # the culprit line: the fusion the bytes live in, named against
        # the compiler's own figure for the site when it reported one
        denom = s["xla"] or s["bytes"]
        denom_kind = "XLA bytes" if s["xla"] else "attributed bytes"
        share = (f" ({top['bytes'] / denom * 100:.0f}% of site "
                 f"{denom_kind})") if denom else ""
        lines.append(
            f"    {site}: {top['name']} [{top['class']}] accounts for "
            f"{_mb(top['bytes'])} of {_mb(denom)}{share}")
        if worst is None or top["bytes"] > worst[0]:
            worst = (top["bytes"],
                     f"{site}: {top['name']} [{top['class']}] "
                     f"{_mb(top['bytes'])}")
    if worst is not None:
        lines.append(f"  largest single fusion: {worst[1]}")
    return lines


def forecast_vs_actual(queries: List[dict]) -> Tuple[List[str], int]:
    """Per bounded query: measured compile misses per site vs the
    analyzer's forecast, and measured per-op bytes vs the byte bound.
    Mirrors tests/harness.py::_assert_analysis_cross_check semantics —
    warm caches may miss LESS than forecast, never more."""
    lines: List[str] = []
    violations = 0
    for q in queries:
        an = q.get("analysis")
        if an is None:
            continue
        qid = q["query_id"]
        if not an.get("bounded"):
            lines.append(f"  query {qid}: not statically bounded "
                         "(layouts reported, forecasts omitted)")
            continue
        actual_sites: Dict[str, int] = defaultdict(int)
        actual_bytes: Dict[str, int] = defaultdict(int)
        recovery = 0
        for r in q["events"]:
            if r.get("event") == "compile_miss":
                actual_sites[r["site"]] += 1
            elif r.get("event") == "op_batch":
                actual_bytes[r["op"]] += r.get("bytes") or 0
            elif r.get("event") in ("oom_retry", "batch_split"):
                recovery += 1
        forecast = an.get("site_forecast") or {}
        bounds = an.get("bytes_by_op") or {}
        if recovery:
            # OOM recovery degraded this query to half-capacity (or
            # fallback-path) programs the STATIC plan never forecast:
            # the compile bound is honestly waived — that's degradation
            # doing its job, not emitter/analyzer drift (the resilience
            # section reports the actions themselves)
            lines.append(
                f"  query {qid}: compile forecast waived — {recovery} "
                "OOM recovery action(s) compiled degraded-capacity "
                "programs (see == resilience ==)")
        for site in sorted(set(actual_sites) | set(forecast)):
            got, exp = actual_sites.get(site, 0), forecast.get(site, 0)
            bad = got > exp and not recovery
            violations += bad
            if recovery and got > exp:
                lines.append(
                    f"  query {qid} compile[{site}]: actual {got} > "
                    f"forecast {exp} (waived: OOM recovery)")
                continue
            lines.append(
                f"  query {qid} compile[{site}]: actual {got} <= "
                f"forecast {exp}" if not bad else
                f"  query {qid} compile[{site}]: VIOLATION actual {got} > "
                f"forecast {exp}")
        for op in sorted(actual_bytes):
            got = actual_bytes[op]
            bound = bounds.get(op)
            bad = bound is None or got > bound
            violations += bad
            if bound is None:
                lines.append(f"  query {qid} bytes[{op}]: VIOLATION "
                             f"measured {_mb(got)} has no analyzer bound")
            elif bad:
                lines.append(f"  query {qid} bytes[{op}]: VIOLATION "
                             f"measured {_mb(got)} > bound {_mb(bound)}")
            else:
                lines.append(f"  query {qid} bytes[{op}]: measured "
                             f"{_mb(got)} <= bound {_mb(bound)}")
        # analyzer bound vs XLA's compiler-reported bytes: the layout
        # model bounds what rows REQUIRE; XLA reports what the compiled
        # kernel TOUCHES (temp-inflated). XLA above the bound is the
        # interesting signal — the kernel materializes intermediates the
        # layout model doesn't know about — and a lead, NOT a violation.
        xla_by_op: Dict[str, float] = defaultdict(float)
        for r in q["events"]:
            if (r.get("event") == "program_cost" and r.get("op")
                    and r.get("bytes_accessed") is not None):
                xla_by_op[r["op"]] += r["bytes_accessed"]
        for op in sorted(xla_by_op):
            bound = bounds.get(op)
            if bound is None:
                continue
            got = xla_by_op[op]
            if got > bound:
                lines.append(
                    f"  query {qid} xla[{op}]: XLA bytes {_mb(got)} "
                    f"exceed analyzer bound {_mb(bound)} "
                    f"(+{_mb(got - bound)} materialized intermediates — "
                    "roofline-push lead, not a violation)")
            else:
                lines.append(
                    f"  query {qid} xla[{op}]: XLA bytes {_mb(got)} "
                    f"within analyzer bound {_mb(bound)}")
    if not lines:
        lines.append("  no plan_analysis events in log (enable "
                     "sql.analysis.enabled with the event log on)")
    lines.append(f"  {violations} violation(s)")
    return lines, violations


# ---------------------------------------------------------------------------
# the report
# ---------------------------------------------------------------------------
def build_report(events: List[dict], top_n: int = 10,
                 storm_threshold: int = DEFAULT_STORM_THRESHOLD,
                 peak_gbps: Optional[float] = None,
                 peak_tflops: Optional[float] = None) -> Tuple[str, int]:
    """(report text, violation count) for one merged event stream."""
    lines: List[str] = []
    queries = _query_windows(events)

    lines.append("== queries ==")
    env = _env_of(events)
    if env:
        lines.append("  env: " + _env_str(env))
    if not queries:
        lines.append("  none recorded")
    for q in queries:
        fb = q.get("tagged") or {}
        nfb = len(fb.get("fallbacks") or [])
        lines.append(
            f"  query {q['query_id']} plan={q.get('plan_digest')} "
            f"dur={_ms(q['dur'])} rows={q['rows']}"
            + (f" fallbacks={nfb}" if nfb else ""))
        for f in (fb.get("fallbacks") or []):
            lines.append(f"    !{f['op']}: {'; '.join(f['reasons'])}")

    ops = aggregate_ops(events)
    have_device = any(s.device_ns for s in ops.values())
    lane = "device" if have_device else "host"
    lines.append(f"== top ops by {lane} time ==")
    ranked = sorted(
        ops.items(),
        key=lambda kv: (kv[1].device_ns if have_device else kv[1].host_ns),
        reverse=True)[:top_n]
    if not ranked:
        lines.append("  no op spans recorded")
    for name, s in ranked:
        gbps = (s.bytes / s.device_ns if s.device_ns else None)
        lines.append(
            f"  {name}: device={_ms(s.device_ns) if s.device_ns else '-'} "
            f"host={_ms(s.host_ns)} batches={s.batches} rows={s.rows} "
            f"bytes={_mb(s.bytes)}"
            + (f" hbm_gbps={gbps:.2f}" if gbps else ""))
    if not have_device and ranked:
        lines.append("  (no device lane: run with "
                     "spark.rapids.tpu.metrics.deviceSync.enabled for "
                     "device-accurate ranking)")

    sites: Dict[str, int] = defaultdict(int)
    for r in events:
        if r.get("event") == "compile_miss":
            sites[r["site"]] += 1
    lines.append("== compile cache misses ==")
    if not sites:
        lines.append("  none (steady state)")
    for site, n in sorted(sites.items(), key=lambda kv: -kv[1]):
        storm = " <-- COMPILE STORM" if n >= storm_threshold else ""
        lines.append(f"  {site}: {n}{storm}")

    lines.extend(roofline_section(events, queries, peak_gbps, peak_tflops,
                                  ops=ops))

    lines.extend(hlo_section(events))

    xfer: Dict[str, List[int]] = defaultdict(lambda: [0, 0])
    for r in events:
        if r.get("event") == "transfer":
            t = xfer[r["direction"]]
            t[0] += 1
            t[1] += r.get("bytes") or 0
    lines.append("== transfers ==")
    if not xfer:
        lines.append("  none recorded")
    for d, (n, b) in sorted(xfer.items()):
        lines.append(f"  {d}: {n} transfer(s), {_mb(b)}")

    sh: Dict[Tuple[str, str], List[int]] = defaultdict(lambda: [0, 0, 0])
    for r in events:
        if r.get("event") in ("shuffle_write", "shuffle_fetch"):
            t = sh[(r["event"], r.get("codec", "none"))]
            t[0] += 1
            t[1] += r.get("bytes") or 0
            t[2] += r.get("rows") or 0
    lines.append("== shuffle ==")
    if not sh:
        lines.append("  none recorded")
    for (ev, codec), (n, b, rows) in sorted(sh.items()):
        lines.append(f"  {ev}[{codec}]: {n} piece(s), {_mb(b)}, "
                     f"{rows} row(s)")
    fetch_retries = sum(
        r.get("retries") or 0 for r in events
        if r.get("event") == "shuffle_fetch")
    if fetch_retries:
        lines.append(f"  fetch retries: {fetch_retries} transient "
                     "failure(s) recovered by backoff "
                     "(shuffle/network.py)")

    spills = [r for r in events if r.get("event") == "spill"]
    lines.append("== spill timeline ==")
    if not spills:
        lines.append("  none (working set fit the budget)")
    else:
        base = events[0]["ts"]
        peak = 0
        for r in spills:
            peak = max(peak, r["device_bytes"])
            lines.append(
                f"  +{(r['ts'] - base) / 1e6:.1f}ms {r['kind']} "
                f"{_mb(r['bytes'])} (device watermark "
                f"{_mb(r['device_bytes'])})")
        lines.append(f"  peak device watermark: {_mb(peak)}")

    # OOM recovery plane (memory/retry.py): how often forecasts were
    # wrong and what the recovery cost — retries (spill + backoff),
    # split-and-retry halvings (half-capacity recompiles, see the
    # resilience markers beside the compile track in Perfetto), and
    # serve requeues. A nonzero steady-state rate here means the HBM
    # budget or the analyzer's forecasts need attention (the live twin
    # is the watchdog's retry_storm alert).
    lines.append("== resilience ==")
    retries_by: Dict[Tuple[str, str], int] = defaultdict(int)
    for r in events:
        if r.get("event") == "oom_retry":
            retries_by[(r.get("op", "?"), r.get("kind", "retry"))] += 1
    splits_by: Dict[str, List[int]] = defaultdict(lambda: [0, 0])
    for r in events:
        if r.get("event") == "batch_split":
            t = splits_by[r.get("op", "?")]
            t[0] += 1
            t[1] = max(t[1], r.get("depth") or 0)
    if not retries_by and not splits_by:
        lines.append("  none (no OOM recovery activity)")
    for (op, kind), n in sorted(retries_by.items()):
        lines.append(f"  {op}: {n} {kind} action(s)")
    for op, (n, maxd) in sorted(splits_by.items()):
        lines.append(f"  {op}: {n} batch split(s), max depth {maxd} "
                     f"(completed at 1/{1 << maxd} capacity)")

    sc: Dict[str, List[int]] = defaultdict(lambda: [0, 0])
    for r in events:
        if r.get("event") == "scan_cache":
            t = sc[r["op"]]
            t[0] += 1
            t[1] += r.get("bytes") or 0
    lines.append("== scan cache ==")
    if not sc:
        lines.append("  no activity")
    for op, (n, b) in sorted(sc.items()):
        lines.append(f"  {op}: {n} ({_mb(b)})")

    # persistent AOT program cache (serve/program_cache.py): lifecycle
    # counts per op, warm compile cost actually paid, and the
    # compile-seconds-avoided estimate from the persisted cost payloads
    # riding the from_cache program_cost events. A warm serving process
    # should read hits ~= deserializes, zero compile misses above, and
    # avoided >> paid.
    pc_ops: Dict[str, List[float]] = defaultdict(lambda: [0, 0.0])
    for r in events:
        if r.get("event") == "program_cache":
            t = pc_ops[r["op"]]
            t[0] += 1
            t[1] += r.get("bytes") or 0
    warm_paid_ms = 0.0
    saved_ms = 0.0
    from_cache_n = 0
    for r in events:
        if r.get("event") == "program_cost" and r.get("from_cache"):
            from_cache_n += 1
            warm_paid_ms += ((r.get("trace_ms") or 0)
                             + (r.get("compile_ms") or 0))
            saved_ms += r.get("saved_ms") or 0
    lines.append("== program cache ==")
    if not pc_ops:
        lines.append("  no activity (spark.rapids.tpu.aotCache off)")
    else:
        lines.append("  " + ", ".join(
            f"{op}={int(n)}" for op, (n, _) in sorted(pc_ops.items())))
        for op in ("hit", "put"):
            if op in pc_ops and pc_ops[op][1]:
                lines.append(f"  {op} bytes: {_mb(pc_ops[op][1])}")
        if from_cache_n:
            lines.append(
                f"  {from_cache_n} program(s) served from cache: paid "
                f"{warm_paid_ms / 1e3:.2f}s (deserialize + cached "
                f"compile), avoided ~{saved_ms / 1e3:.2f}s of original "
                "trace+compile (persisted payload estimate)")
        corrupt = int(pc_ops.get("corrupt", [0, 0])[0])
        if corrupt:
            lines.append(f"  NOTE: {corrupt} poisoned entr"
                         f"{'y' if corrupt == 1 else 'ies'} deleted "
                         "(fell through to plain compiles)")

    # aggregation strategy choices (one 'agg_strategy' event per exec per
    # capacity): the chooser on the record — compare against the top-ops
    # table above to see whether the pick was right
    strat: Dict[Tuple[str, str, int], Tuple[int, str]] = {}
    for r in events:
        if r.get("event") == "agg_strategy":
            k = (r.get("op"), r.get("strategy"), r.get("cap"))
            n, _ = strat.get(k, (0, ""))
            strat[k] = (n + 1, r.get("reason", ""))
    lines.append("== agg strategy ==")
    if not strat:
        lines.append("  none recorded (no grouped aggregates ran)")
    for (op, s, cap), (n, reason) in sorted(strat.items()):
        times = f" x{n}" if n > 1 else ""
        lines.append(f"  {op}[cap={cap}]: {s}{times} — {reason}")

    # join strategy choices (one 'join_strategy' event per exec per
    # BUILD capacity): the probe-lowering twin of the section above
    jstrat: Dict[Tuple[str, str, int], Tuple[int, str]] = {}
    for r in events:
        if r.get("event") == "join_strategy":
            k = (r.get("op"), r.get("strategy"), r.get("build_cap"))
            n, _ = jstrat.get(k, (0, ""))
            jstrat[k] = (n + 1, r.get("reason", ""))
    lines.append("== join strategy ==")
    if not jstrat:
        lines.append("  none recorded (no equi-joins ran)")
    for (op, s, cap), (n, reason) in sorted(jstrat.items()):
        times = f" x{n}" if n > 1 else ""
        lines.append(f"  {op}[build_cap={cap}]: {s}{times} — {reason}")

    # pipelined parquet decode stages: per-stage totals; overlapping
    # decode/upload spans are visible in the Perfetto export
    pipe: Dict[str, List[int]] = defaultdict(lambda: [0, 0, 0])
    for r in events:
        if r.get("event") == "pq_pipeline":
            t = pipe[r["stage"]]
            t[0] += 1
            t[1] += r.get("bytes") or 0
            t[2] += r.get("dur") or 0
    lines.append("== parquet pipeline ==")
    if not pipe:
        lines.append("  no activity")
    for stage, (n, b, dur) in sorted(pipe.items()):
        lines.append(f"  {stage}: {n} ({_mb(b)}, {_ms(dur)} host)")

    # serving layer: admission verdicts, queue balance + wait quantiles
    # (serve/scheduler.py events; absent in non-serving logs)
    adm: Dict[str, int] = defaultdict(int)
    for r in events:
        if r.get("event") == "admission":
            adm[r["verdict"]] += 1
    qops: Dict[str, int] = defaultdict(int)
    waits: List[int] = []
    max_depth = 0
    for r in events:
        if r.get("event") == "queue":
            qops[r["op"]] += 1
            max_depth = max(max_depth, r.get("depth") or 0)
            if r["op"] == "dequeue":
                waits.append(r.get("wait_ns") or 0)
    serving_violations = 0
    lines.append("== serving ==")
    if not adm and not qops:
        lines.append("  no serving activity "
                     "(spark.rapids.tpu.serve.enabled off)")
    else:
        lines.append("  admissions: " + ", ".join(
            f"{v}={n}" for v, n in sorted(adm.items())))
        if qops:
            waits.sort()

            def pct(p: float) -> str:
                return _ms(waits[min(len(waits) - 1,
                                     int(p * len(waits)))]) if waits else "-"
            lines.append(
                f"  queue: {qops.get('enqueue', 0)} enqueued, "
                f"{qops.get('dequeue', 0)} dequeued, "
                f"{qops.get('timeout', 0)} timed out, "
                f"max depth {max_depth}, wait p50={pct(0.5)} "
                f"p95={pct(0.95)}")
            if qops.get("enqueue", 0) != (qops.get("dequeue", 0)
                                          + qops.get("timeout", 0)):
                serving_violations += 1
                lines.append(
                    "  VIOLATION: queue events unbalanced — "
                    f"{qops.get('enqueue', 0)} enqueue(s) vs "
                    f"{qops.get('dequeue', 0)} dequeue(s) + "
                    f"{qops.get('timeout', 0)} timeout(s) (a query "
                    "entered the queue and never left)")

    lines.append("== forecast vs actual ==")
    fa_lines, violations = forecast_vs_actual(queries)
    lines.extend(fa_lines)
    return "\n".join(lines), violations + serving_violations


# ---------------------------------------------------------------------------
# alert replay (--alerts): the live watchdog's rules over a recorded log
# ---------------------------------------------------------------------------
def run_alerts(events: List[dict], stall_ms: int, pressure_fraction: float,
               storm_threshold: int, storm_window_ms: int,
               budget: Optional[int]) -> Tuple[str, int]:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from spark_rapids_tpu.obs.watchdog import WatchdogRules, replay_alerts

    rules = WatchdogRules(
        stall_ns=stall_ms * 1_000_000,
        pressure_fraction=pressure_fraction,
        storm_threshold=storm_threshold,
        storm_window_ns=storm_window_ms * 1_000_000,
    )
    alerts = replay_alerts(events, rules, budget=budget)
    base = events[0].get("ts", 0) if events else 0
    lines = ["== watchdog alert replay =="]
    lines.append(
        f"  rules: stall>={stall_ms}ms, "
        f"pressure>={pressure_fraction:.2f}x budget, "
        f"storm>={storm_threshold} misses/{storm_window_ms}ms")
    if not alerts:
        lines.append("  no alerts at these thresholds")
    for a in alerts:
        lines.append(f"  +{(a.ts - base) / 1e6:.1f}ms {a.describe()}")
    lines.append(f"  {len(alerts)} alert(s)")
    return "\n".join(lines), len(alerts)


# ---------------------------------------------------------------------------
# diff mode
# ---------------------------------------------------------------------------
def _byte_amp(shape_row: dict) -> Optional[float]:
    """Per-shape byte amplification (XLA bytes-accessed / analyzer
    layout bound). Newer BENCH jsons carry it first-class
    (bench.byte_amplification); older rounds that recorded both inputs
    are BACKFILLED here so the r09-era baselines still gate the fix."""
    amp = shape_row.get("byte_amplification")
    if amp is not None:
        return amp
    xb = shape_row.get("xla_bytes_accessed")
    lb = shape_row.get("predicted_hbm_bytes")
    if xb and lb:
        return round(xb / lb, 2)
    return None


def diff_bench(old: dict, new: dict, threshold: float
               ) -> Tuple[str, int]:
    # driver-captured BENCH_*.json files wrap the bench line in a
    # {"parsed": {...}} envelope; unwrap so rounds diff either layout
    old = old.get("parsed", old) if "per_shape" not in old else old
    new = new.get("parsed", new) if "per_shape" not in new else new
    lines: List[str] = []
    regressions = 0
    # top-level env blocks (bench.py stamps envinfo.environment_info):
    # different hardware -> loud warning, time gates stay advisory
    lines.extend(_env_warning(old.get("env"), new.get("env")))
    shapes = sorted(set(old.get("per_shape") or {})
                    | set(new.get("per_shape") or {}))
    for shape in shapes:
        a = (old.get("per_shape") or {}).get(shape)
        b = (new.get("per_shape") or {}).get(shape)
        if a is None or b is None:
            lines.append(f"  {shape}: only in "
                         f"{'new' if a is None else 'old'} run")
            continue
        if not isinstance(a, dict) or not isinstance(b, dict):
            # pre-round-6 layout: bare speedup floats — no timed fields
            lines.append(f"  {shape}: no comparable timing fields "
                         "(legacy bench layout)")
            continue
        sa, sb = a.get("agg_strategy"), b.get("agg_strategy")
        if sa != sb and (sa or sb):
            lines.append(f"  {shape}.agg_strategy: {sa} -> {sb} "
                         "(lowering changed — compare device_ms)")
        ja, jb = a.get("join_strategy"), b.get("join_strategy")
        if ja != jb and (ja or jb):
            lines.append(f"  {shape}.join_strategy: {ja} -> {jb} "
                         "(join lowering changed — compare device_ms)")
        # the same-lowering waiver below covers BOTH strategy fields: a
        # deliberate agg OR join flip redraws the compiled-byte profile
        # (incl. total bytes — AUTO legitimately resolves different
        # tiers at different scales), so every byte gate binds only
        # when neither changed; the flip itself is flagged above, and
        # CI pins the committed rounds' ABSOLUTE amplification levels
        # (events job: agg <= r09/5, join <= r10/3) so a flip that
        # blows up bytes still cannot land
        same_lowering = sa == sb and ja == jb
        for field in ("tpu_ms", "device_ms"):
            va, vb = a.get(field), b.get(field)
            if va is None or vb is None or va <= 0:
                continue
            ratio = vb / va
            if ratio > 1.0 + threshold and vb - va > DIFF_MIN_MS:
                regressions += 1
                lines.append(
                    f"  {shape}.{field}: REGRESSION {va:.1f} -> {vb:.1f} "
                    f"({ratio:.2f}x, threshold {1 + threshold:.2f}x)")
            else:
                lines.append(
                    f"  {shape}.{field}: ok {va:.1f} -> {vb:.1f} "
                    f"({ratio:.2f}x)")
        # compiler-reported HBM utilization: compared only when BOTH
        # runs harvested it (hbm_frac_xla = XLA bytes / device time /
        # peak); a relative drop beyond the threshold means the device
        # got less busy for the same compiled work
        # ... unless the agg lowering deliberately changed (flagged
        # above): a strategy flip rewrites what "the same compiled work"
        # even is — e.g. the radix rewrite shrinks XLA bytes ~25x, which
        # reads as a frac drop while being the fix itself
        fa, fb = a.get("hbm_frac_xla"), b.get("hbm_frac_xla")
        if fa is not None and fb is not None and fa > DIFF_MIN_FRAC \
                and same_lowering:
            # same unbounded ratio form as the tpu_ms/device_ms gates: a
            # drop-fraction ((fa-fb)/fa) saturates at 1.0 and can never
            # clear CI's threshold 2.0, so a full collapse would pass
            ratio = fa / fb if fb > 0 else float("inf")
            if ratio > 1.0 + threshold:
                regressions += 1
                lines.append(f"  {shape}.hbm_frac_xla: REGRESSION "
                             f"{fa:.4f} -> {fb:.4f} ({ratio:.2f}x drop, "
                             f"threshold {1 + threshold:.2f}x)")
            else:
                lines.append(f"  {shape}.hbm_frac_xla: ok {fa:.4f} -> "
                             f"{fb:.4f}")
        # per-fusion attribution gates, the bench twin of diff_logs'
        # _site_hlo checks: the largest single-fusion byte figure must
        # not grow beyond the threshold, and the scatter count must not
        # rise (both shape-derived — meaningful across environments)
        ta, tb = a.get("hlo_top_fusion_bytes"), b.get("hlo_top_fusion_bytes")
        if ta and tb and same_lowering:
            # a deliberate lowering flip redraws the fusion map (the
            # radix loop IS one big fusion); its TOTAL bytes are gated
            # by byte_amplification above, so the per-fusion gate only
            # binds same-strategy runs
            if tb > ta * (1.0 + threshold):
                regressions += 1
                lines.append(f"  {shape}.hlo_top_fusion_bytes: REGRESSION "
                             f"{ta} -> {tb} (one fusion owns more traffic)")
            else:
                lines.append(f"  {shape}.hlo_top_fusion_bytes: ok "
                             f"{ta} -> {tb}")
        # byte amplification (XLA bytes / layout bound): the trended
        # number of the round-12 kernel rewrite. Growth beyond the
        # threshold means the compiled programs started touching bytes
        # the layout never demanded — a regression even when wall clock
        # on a noisy shared box hides it (backfilled for older jsons).
        # Same-lowering only: AUTO resolves different tiers at
        # different scales (a scale-0.1 smoke legitimately runs the
        # SCATTER agg the committed scale-0.25 round replaced), and a
        # deliberate flip owns its amplification — the committed-round
        # ABSOLUTE levels are pinned by the events job instead
        aa, ab = _byte_amp(a), _byte_amp(b)
        if aa and ab and same_lowering:
            if ab > aa * (1.0 + threshold):
                regressions += 1
                lines.append(f"  {shape}.byte_amplification: REGRESSION "
                             f"{aa:.2f}x -> {ab:.2f}x of the layout "
                             f"bound (threshold {1 + threshold:.2f}x "
                             "growth)")
            else:
                lines.append(f"  {shape}.byte_amplification: ok "
                             f"{aa:.2f}x -> {ab:.2f}x")
        # peak temp (largest per-program temp allocation): growth beyond
        # the threshold under the SAME lowering means a program started
        # materializing bigger intermediates; a strategy flip owns its
        # temp profile (flagged above)
        pa, pb = a.get("xla_peak_temp_bytes"), b.get("xla_peak_temp_bytes")
        if pa and pb and same_lowering:
            if pb > pa * (1.0 + threshold):
                regressions += 1
                lines.append(f"  {shape}.xla_peak_temp_bytes: REGRESSION "
                             f"{pa} -> {pb} (bigger materialized "
                             "intermediates)")
            else:
                lines.append(f"  {shape}.xla_peak_temp_bytes: ok "
                             f"{pa} -> {pb}")
        # per-op HBM peak (the ledger's per-shape attribution,
        # bench._mem_stats hbm_peak_by_op): any single op's peak growing
        # beyond the threshold AND the 1MiB jitter floor means that
        # operator started holding more device memory at once — gated
        # same-lowering only (a strategy flip redraws who holds what)
        ha, hb = a.get("hbm_peak_by_op"), b.get("hbm_peak_by_op")
        if isinstance(ha, dict) and isinstance(hb, dict) and same_lowering:
            for op in sorted(set(ha) | set(hb)):
                oa, ob = ha.get(op) or 0, hb.get(op) or 0
                if ob - oa <= DIFF_MIN_HBM_BYTES:
                    continue
                if oa and ob / oa <= 1.0 + threshold:
                    continue
                regressions += 1
                lines.append(
                    f"  {shape}.hbm_peak_by_op[{op}]: REGRESSION "
                    f"{oa} -> {ob} bytes"
                    + (f" ({ob / oa:.2f}x)" if oa else " (new op)"))
        # leaked buffers are an absolute gate, not a diff: any nonzero
        # count in the NEW run fails regardless of the old run
        leaked_new = b.get("leaked_buffers")
        if leaked_new:
            regressions += 1
            lines.append(f"  {shape}.leaked_buffers: REGRESSION "
                         f"{leaked_new} buffer(s) outlived the query "
                         "(must be 0)")
        ka, kb = a.get("hlo_scatter_count"), b.get("hlo_scatter_count")
        if ka is not None and kb is not None:
            # growth is gated only when NEITHER lowering changed (agg
            # and join strategy alike): a deliberate flip (already
            # flagged above) owns its scatter-count delta, a
            # same-strategy rise is a regression
            if kb > ka and same_lowering:
                regressions += 1
                lines.append(f"  {shape}.hlo_scatter_count: REGRESSION "
                             f"{ka} -> {kb} (a scatter lowering appeared)")
            elif ka or kb:
                lines.append(f"  {shape}.hlo_scatter_count: ok {ka} -> "
                             f"{kb}")
    # cold-start lane (bench.py --cold-start): the warm-cache compile
    # seconds are the serving-restart bill, and they must stay ~zero.
    # Structural gates on the new run alone (meaningful across
    # environments): a warm run that counted compile misses means the
    # AOT cache stopped hitting, and a warm/cold ratio above 0.5 means
    # deserialize+cached-compile stopped being cheap. Relative gate vs
    # the old round: compile_s_warm growth beyond the threshold.
    ca, cb = old.get("cold_start"), new.get("cold_start")
    if cb:
        for shape, row in sorted(cb.items()):
            if not isinstance(row, dict):
                continue
            misses = row.get("compile_miss_warm") or 0
            old_row = (ca or {}).get(shape)
            old_row = old_row if isinstance(old_row, dict) else None
            # a site with timing-dependent keys (the parquet packed
            # upload) legitimately carries a residual warm miss every
            # round — gate on GROWTH vs the old round, or (with no
            # baseline) on the cache having served nothing at all
            if old_row is not None:
                miss_bad = misses > (old_row.get("compile_miss_warm")
                                     or 0)
            else:
                miss_bad = misses and not row.get("from_cache_warm")
            if miss_bad:
                regressions += 1
                lines.append(
                    f"  cold_start.{shape}: REGRESSION {misses} warm "
                    "compile miss(es) — the AOT cache stopped hitting")
            ratio = row.get("warm_ratio")
            if ratio is not None and ratio > 0.5:
                regressions += 1
                lines.append(
                    f"  cold_start.{shape}: REGRESSION warm/cold "
                    f"compile ratio {ratio:.2f} > 0.5 (deserialize no "
                    "longer avoids the compile bill)")
            wa = ((ca or {}).get(shape) or {}).get("compile_s_warm") \
                if isinstance((ca or {}).get(shape), dict) else None
            wb = row.get("compile_s_warm")
            if wa and wb is not None:
                if wb > wa * (1.0 + threshold) \
                        and (wb - wa) * 1e3 > DIFF_MIN_MS:
                    regressions += 1
                    lines.append(
                        f"  cold_start.{shape}.compile_s_warm: "
                        f"REGRESSION {wa:.2f}s -> {wb:.2f}s")
                else:
                    lines.append(
                        f"  cold_start.{shape}.compile_s_warm: ok "
                        f"{wa:.2f}s -> {wb:.2f}s")
            elif wb is not None and not misses and (
                    ratio is None or ratio <= 0.5):
                lines.append(
                    f"  cold_start.{shape}: ok warm {wb:.2f}s"
                    + (f" ({ratio:.2f}x of cold)"
                       if ratio is not None else ""))
    elif ca:
        lines.append("  cold_start: lane missing from new run (run "
                     "bench.py --cold-start to compare)")
    # serving lane (bench.py --serve): structural gates always — the new
    # run must be internally clean (ok flag: no errors/rejects/bypass,
    # summed forecasts within budget) and must still beat serialized
    # submission; qps is noise-compared only when the runs match shape
    sa, sb = old.get("serve"), new.get("serve")
    if sa and sb:
        if not sb.get("ok"):
            regressions += 1
            lines.append("  serve: REGRESSION new run not ok "
                         f"(errors={sb.get('errors')}, "
                         f"rejected={sb.get('rejected')}, "
                         f"bypass={sb.get('bypass_admissions')})")
        sp = sb.get("speedup_vs_serialized")
        if sp is not None and sp <= 1.0:
            regressions += 1
            lines.append(f"  serve: REGRESSION concurrent qps no longer "
                         f"beats serialized ({sp:.3f}x)")
        elif sp is not None:
            lines.append(f"  serve: ok {sp:.3f}x vs serialized "
                         f"(qps {sb.get('qps')}, p95 {sb.get('p95_ms')}ms)")
        comparable = (sa.get("scale") == sb.get("scale")
                      and sa.get("threads") == sb.get("threads")
                      and sa.get("queries_per_thread")
                      == sb.get("queries_per_thread"))
        va, vb = sa.get("qps"), sb.get("qps")
        if comparable and va and vb and va / vb > 1.0 + threshold:
            regressions += 1
            lines.append(f"  serve.qps: REGRESSION {va} -> {vb}")
    elif sa and not sb:
        lines.append("  serve: lane missing from new run (run bench.py "
                     "--serve to compare)")
    lines.append(f"  {regressions} regression(s)")
    return "\n".join(lines), regressions


#: absolute scaling-efficiency drop per shape that flags a regression in
#: the MULTICHIP diff (efficiency is already a 0..1 normalized quantity,
#: so a relative threshold would over-trigger near zero)
MULTICHIP_EFF_DROP = 0.1


def diff_multichip(old: dict, new: dict, threshold: float,
                   eff_drop: float = MULTICHIP_EFF_DROP
                   ) -> Tuple[str, int]:
    """Diff two MULTICHIP json payloads (bench.py --mesh). Structural
    gates always apply: every old shape present, mesh-lowered shapes stay
    mesh-lowered, zero forecast violations in the new run. Per-shape
    scaling-efficiency regression (absolute drop > ``eff_drop``) and
    device_ms regressions (relative ``threshold``) are compared only when
    both runs measured the same scale AND device count — a reduced-scale
    smoke against a committed full-scale round checks structure, not
    noise."""
    old = old.get("parsed", old) if "per_shape" not in old else old
    new = new.get("parsed", new) if "per_shape" not in new else new
    lines: List[str] = []
    regressions = 0
    if "per_shape" not in old:
        # legacy dry-run format: only the ok flag existed
        lines.append("  old run is the legacy dry-run format; structural "
                     "gate on the new run only")
        old = {"per_shape": {}}
    if new.get("forecast_violations"):
        regressions += 1
        lines.append(
            f"  REGRESSION: {len(new['forecast_violations'])} per-shard "
            "forecast violation(s) in new run")
    comparable = (
        old.get("scale") == new.get("scale")
        and old.get("n_devices") == new.get("n_devices")
        and old.get("host_parallelism") == new.get("host_parallelism"))
    if not comparable and old.get("per_shape"):
        lines.append(
            f"  scale/devices differ (old scale={old.get('scale')} "
            f"n={old.get('n_devices')}, new scale={new.get('scale')} "
            f"n={new.get('n_devices')}): structural checks only")
    shapes = sorted(set(old.get("per_shape") or {})
                    | set(new.get("per_shape") or {}))
    for shape in shapes:
        a = (old.get("per_shape") or {}).get(shape)
        b = (new.get("per_shape") or {}).get(shape)
        if b is None:
            regressions += 1
            lines.append(f"  {shape}: REGRESSION shape missing from new "
                         "run")
            continue
        if a is None:
            lines.append(f"  {shape}: new shape (no baseline)")
            continue
        if a.get("mesh_lowered") and not b.get("mesh_lowered"):
            regressions += 1
            lines.append(f"  {shape}: REGRESSION no longer lowers to the "
                         "mesh")
        if a.get("sharded_scan") and not b.get("sharded_scan"):
            regressions += 1
            lines.append(f"  {shape}: REGRESSION sharded scan fell back "
                         "to host staging")
        if not comparable:
            continue
        ea, eb = a.get("scaling_efficiency"), b.get("scaling_efficiency")
        if ea is not None and eb is not None:
            if ea - eb > eff_drop:
                regressions += 1
                lines.append(
                    f"  {shape}.scaling_efficiency: REGRESSION "
                    f"{ea:.3f} -> {eb:.3f} (drop > {eff_drop})")
            else:
                lines.append(f"  {shape}.scaling_efficiency: ok "
                             f"{ea:.3f} -> {eb:.3f}")
        for field in ("tpu_ms", "device_ms"):
            va, vb = a.get(field), b.get(field)
            if va is None or vb is None or va <= 0:
                continue
            ratio = vb / va
            if ratio > 1.0 + threshold and vb - va > DIFF_MIN_MS:
                regressions += 1
                lines.append(
                    f"  {shape}.{field}: REGRESSION {va:.1f} -> {vb:.1f} "
                    f"({ratio:.2f}x)")
            else:
                lines.append(f"  {shape}.{field}: ok {va:.1f} -> "
                             f"{vb:.1f} ({ratio:.2f}x)")
    lines.append(f"  {regressions} regression(s)")
    return "\n".join(lines), regressions


def diff_logs(old_events: List[dict], new_events: List[dict],
              threshold: float) -> Tuple[str, int]:
    lines: List[str] = []
    regressions = 0
    # environment provenance first: when the two logs name different
    # hardware, every time/byte ratio below is apples-to-oranges — warn
    # loudly (warning, not regression: CI diffs a fresh CPU smoke against
    # committed device rounds on purpose, gating structure only)
    lines.extend(_env_warning(_env_of(old_events), _env_of(new_events)))
    a, b = aggregate_ops(old_events), aggregate_ops(new_events)
    for op in sorted(set(a) | set(b)):
        sa, sb = a.get(op), b.get(op)
        if sa is None or sb is None:
            lines.append(f"  {op}: only in {'new' if sa is None else 'old'} "
                         "log")
            continue
        for field in ("device_ns", "host_ns"):
            va, vb = getattr(sa, field), getattr(sb, field)
            if va <= 0 or vb <= 0:
                continue
            ratio = vb / va
            # ignore sub-millisecond deltas — host scheduling noise
            if ratio > 1.0 + threshold and vb - va > DIFF_MIN_NS:
                regressions += 1
                lines.append(
                    f"  {op}.{field[:-3]}: REGRESSION {_ms(va)} -> "
                    f"{_ms(vb)} ({ratio:.2f}x)")
            else:
                lines.append(f"  {op}.{field[:-3]}: ok {_ms(va)} -> "
                             f"{_ms(vb)}")
        if sb.bytes > sa.bytes * (1.0 + threshold) and sa.bytes > 0:
            regressions += 1
            lines.append(f"  {op}.bytes: REGRESSION {_mb(sa.bytes)} -> "
                         f"{_mb(sb.bytes)}")
    # roofline gates over harvested program costs: a site whose XLA
    # bytes_accessed or peak temp allocation GREW beyond the threshold is
    # a silent intermediate-materialization regression — exactly what the
    # cost plane exists to catch. Compile-TIME deltas stay subject to the
    # 1ms noise floor (trace/compile jitter is never a regression).
    ca, cb = _site_costs(old_events), _site_costs(new_events)
    for site in sorted(set(ca) & set(cb)):
        a_c, b_c = ca[site], cb[site]
        for field, label in (("bytes", "xla_bytes"), ("temp", "peak_temp")):
            va, vb = a_c[field], b_c[field]
            if va <= 0 or vb <= va * (1.0 + threshold):
                if va > 0 and vb > 0:
                    lines.append(f"  {site}.{label}: ok {_mb(va)} -> "
                                 f"{_mb(vb)}")
                continue
            regressions += 1
            lines.append(f"  {site}.{label}: REGRESSION {_mb(va)} -> "
                         f"{_mb(vb)} (intermediate materialization?)")
        va, vb = a_c["compile_ns"], b_c["compile_ns"]
        if (va > 0 and vb > va * (1.0 + threshold)
                and vb - va > DIFF_MIN_NS):
            regressions += 1
            lines.append(f"  {site}.compile: REGRESSION {_ms(va)} -> "
                         f"{_ms(vb)}")
    # per-fusion HLO gates (hlo_summary events): a site whose largest
    # single-fusion byte attribution grew beyond the threshold, or that
    # gained scatter-classified programs, regressed STRUCTURALLY — this
    # is the gate the item-1 kernel rewrite is judged by (bytes per
    # fusion must shrink; a new scatter lowering must not sneak in), and
    # it holds even across environments (shape-derived, not timed)
    # union of sites, not intersection: the appears-at-any-size scatter
    # gate must fire even when the new run compiled the scatter at a
    # compile site the old log never harvested (exactly the rewrite-
    # introduces-a-new-site scenario); byte-growth gates still need a
    # nonzero old-side figure to compute growth against
    ha, hb = _site_hlo(old_events), _site_hlo(new_events)
    empty = {"bytes": 0, "top": 0, "scatters": 0}
    for site in sorted(set(ha) | set(hb)):
        a_h, b_h = ha.get(site, empty), hb.get(site, empty)
        for field, label in (("top", "top_fusion_bytes"),
                             ("bytes", "hlo_bytes")):
            va, vb = a_h[field], b_h[field]
            if va > 0 and vb > va * (1.0 + threshold):
                regressions += 1
                note = (" (one fusion owns more traffic?)"
                        if field == "top" else "")
                lines.append(f"  {site}.{label}: REGRESSION {_mb(va)} -> "
                             f"{_mb(vb)}{note}")
            elif va > 0 and vb > 0:
                lines.append(f"  {site}.{label}: ok {_mb(va)} -> "
                             f"{_mb(vb)}")
        if b_h["scatters"] > a_h["scatters"]:
            regressions += 1
            lines.append(
                f"  {site}.scatter_count: REGRESSION {a_h['scatters']} -> "
                f"{b_h['scatters']} (a scatter lowering appeared)")
        elif a_h["scatters"] or b_h["scatters"]:
            lines.append(f"  {site}.scatter_count: ok {a_h['scatters']} "
                         f"-> {b_h['scatters']}")
    lines.append(f"  {regressions} regression(s)")
    return "\n".join(lines), regressions


def _site_hlo(events: List[dict]) -> Dict[str, dict]:
    """Per-site hlo_summary aggregates for --diff: summed shape-level
    byte attribution, the largest single-fusion byte figure, and the
    summed scatter count across the site's harvested programs."""
    per: Dict[str, dict] = {}
    for r in events:
        if r.get("event") != "hlo_summary":
            continue
        d = per.setdefault(r.get("site"),
                           {"bytes": 0, "top": 0, "scatters": 0})
        d["bytes"] += r.get("total_bytes") or 0
        d["scatters"] += r.get("scatter_count") or 0
        for f in r.get("top_fusions") or []:
            d["top"] = max(d["top"], f.get("bytes") or 0)
    return per


def _site_costs(events: List[dict]) -> Dict[str, dict]:
    """Per-site program_cost aggregates for --diff: summed bytes, peak
    temp, summed trace+compile ns (fields the backend omitted count 0)."""
    per: Dict[str, dict] = {}
    for r in events:
        if r.get("event") != "program_cost":
            continue
        d = per.setdefault(r.get("site"),
                           {"bytes": 0.0, "temp": 0, "compile_ns": 0})
        d["bytes"] += r.get("bytes_accessed") or 0
        d["temp"] = max(d["temp"], r.get("temp_bytes") or 0)
        d["compile_ns"] += int(((r.get("trace_ms") or 0)
                                + (r.get("compile_ms") or 0)) * 1e6)
    return per


def run_diff(old_path: str, new_path: str, threshold: float
             ) -> Tuple[str, int]:
    if _is_multichip_json(old_path) or _is_multichip_json(new_path):
        with open(old_path) as f:
            old = json.load(f)
        with open(new_path) as f:
            new = json.load(f)
        head = [f"== diff (multichip) {old_path} -> {new_path} =="]
        body, n = diff_multichip(old, new, threshold)
    elif _is_bench_json(old_path) or _is_bench_json(new_path):
        with open(old_path) as f:
            old = json.load(f)
        with open(new_path) as f:
            new = json.load(f)
        head = [f"== diff (bench) {old_path} -> {new_path} =="]
        body, n = diff_bench(old, new, threshold)
    else:
        head = [f"== diff (event logs) {old_path} -> {new_path} =="]
        body, n = diff_logs(load_events([old_path]),
                            load_events([new_path]), threshold)
    return "\n".join(head + [body]), n


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Offline profiler for spark_rapids_tpu event logs "
                    "(see module docstring)")
    ap.add_argument("paths", nargs="+",
                    help="event-log files/dirs; with --diff, exactly two "
                         "logs or bench JSON files (old new)")
    ap.add_argument("--top", type=int, default=10,
                    help="operators to show in the top-ops table")
    ap.add_argument("--diff", action="store_true",
                    help="compare two logs / bench JSONs; nonzero exit on "
                         "regressions beyond --threshold")
    ap.add_argument("--threshold", type=float, default=0.2,
                    help="relative regression threshold for --diff "
                         "(0.2 = 20%%)")
    ap.add_argument("--storm-threshold", type=int,
                    default=DEFAULT_STORM_THRESHOLD,
                    help="compile misses per site that flag a storm")
    ap.add_argument("--peak-hbm-gbps", type=float, default=None,
                    help="roofline peak HBM bandwidth (GB/s); default: "
                         "per-backend from the log's program_cost events")
    ap.add_argument("--peak-tflops", type=float, default=None,
                    help="roofline peak compute (TFLOP/s); default: "
                         "per-backend from the log's program_cost events")
    ap.add_argument("--alerts", action="store_true",
                    help="replay the live watchdog rules over the log(s) "
                         "to tune thresholds offline (obs/watchdog.py)")
    ap.add_argument("--stall-ms", type=int, default=30000,
                    help="--alerts: op span duration that counts as a "
                         "stall")
    ap.add_argument("--pressure-fraction", type=float, default=0.85,
                    help="--alerts: HBM watermark fraction of the budget "
                         "that counts as pressure")
    ap.add_argument("--storm-window-ms", type=int, default=10000,
                    help="--alerts: sliding window for the per-site "
                         "compile-miss storm (count: --storm-threshold)")
    ap.add_argument("--budget", type=int, default=None,
                    help="--alerts: HBM budget bytes override (default: "
                         "the log's plan_analysis budget)")
    args = ap.parse_args(argv)

    if args.alerts:
        events = load_events(args.paths)
        if not events:
            print("no events found", file=sys.stderr)
            return 1
        text, _n = run_alerts(
            events, args.stall_ms, args.pressure_fraction,
            args.storm_threshold, args.storm_window_ms, args.budget)
        print(text)
        # a threshold-tuning tool, not a gate: alerts are the point, so
        # finding some is success (exit 0)
        return 0

    if args.diff:
        if len(args.paths) != 2:
            ap.error("--diff takes exactly two paths (old new)")
        text, bad = run_diff(args.paths[0], args.paths[1], args.threshold)
        print(text)
        return 1 if bad else 0

    events = load_events(args.paths)
    if not events:
        print("no events found", file=sys.stderr)
        return 1
    text, violations = build_report(events, args.top, args.storm_threshold,
                                    peak_gbps=args.peak_hbm_gbps,
                                    peak_tflops=args.peak_tflops)
    print(text)
    # forecast violations mean the analyzer's bounds or the emitters
    # drifted — CI runs this on a fresh log so the drift can't land
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
