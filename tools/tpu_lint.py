#!/usr/bin/env python3
"""tpu_lint — repo-directed AST lint for TPU tracing hazards.

The engine's hot path is XLA-traced JAX: the classic regressions are a
host sync smuggled into a per-batch loop (``.item()``, a stray
``jax.device_get``), python control flow on a traced value inside a
jitted function (silent recompiles or trace errors), and jit cache keys
that churn (a fresh lambda per call compiles every batch). They all
look innocent in review — this lint makes them CI failures instead.

Rules
-----
TPU001  device→host pull outside the sanctioned sync helpers
        (exec/base.py host_pull/host_fence): ``jax.device_get``,
        ``jax.block_until_ready``, or ``<expr>.item()`` anywhere in
        ``spark_rapids_tpu/{exec,ops,expr}/``. One batched pull through
        the helper costs one tunnel RTT and is auditable; scattered raw
        pulls are how per-batch RTTs regress.
TPU002  unstable jit cache key: ``jax.jit(lambda ...)`` (a fresh lambda
        can never hit the executable cache), ``jax.jit`` called inside a
        function without storing the result in a cache (subscript
        assignment or an lru_cache'd enclosing function), or ``id(...)``
        inside a cache-key tuple (ids are reused after GC).
TPU003  traced-value hazard inside a jit region: within a function
        passed to ``jax.jit`` (and its nested defs) — ``float()`` /
        ``int()`` / ``bool()`` / ``np.asarray()`` applied to a traced
        parameter, ``.item()``, or an ``if``/``while`` whose test reads
        a traced parameter (python control flow cannot branch on traced
        values).
TPU005  raw ``jax.jit`` / ``pjit`` outside the guarded pipeline-cache
        layer: every engine executable must be built inside a builder
        handed to ``exec/base.cached_pipeline`` (or
        ``exec/mesh._cached_program``) so the program participates in
        the AOT program cache, the compile-cost harvest, and the
        donation-mask key fold — a raw jit is invisible to all three.
        ``exec/base.py`` is exempt (it IS the layer); the two AOT
        export-probe compiles in serve/program_cache.py are the
        documented allowlisted exceptions.
TPU004  capacity decision outside the sanctioned layer: a direct
        ``bucket_rows``/``round_up_pow2`` call, or hand-rolled
        power-of-two arithmetic (``1 << (...).bit_length()``), anywhere
        in ``spark_rapids_tpu/`` outside ``columnar/``,
        ``utils/bucketing.py``, and the static plan analyzer
        (``plugin/plananalysis.py``). Batch/byte-pool capacities must
        route through ``columnar.column.choose_capacity`` so the
        analyzer can reproduce the exact buckets the runtime will
        allocate — a hand-rolled bucket is invisible to the plan-time
        layout/footprint/signature forecast.

Allowlist
---------
``tools/tpu_lint_allow.txt`` (path configurable via the
``spark.rapids.tpu.tools.lint.allowlistPath`` conf entry): one
``relpath::qualname::RULE`` per line for the documented legitimate
sites; ``#`` comments. The sanctioned helpers themselves (exec/base.py)
are exempt from TPU001 by construction.

Exit status: 0 clean, 1 findings, 2 usage/IO error.
"""
from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Optional, Set, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from lint_common import (  # noqa: E402 — path bootstrap above
    Finding,
    REPO_ROOT,
    attr_chain as _attr_chain,
    default_allowlist_path,
    enclosing_function as _enclosing_function,
    function_defs as _function_defs,
    iter_py_files,
    load_allowlist,
    parents_map as _parents,
    run_tool,
)

DEFAULT_TARGET = os.path.join(REPO_ROOT, "spark_rapids_tpu")
#: dirs where ANY raw host-sync primitive is a finding (TPU001); the rest
#: of the package is host-boundary code where pulls are the point
SYNC_STRICT_DIRS = ("exec", "ops", "expr")
SANCTIONED_FILES = (os.path.join("exec", "base.py"),)

JAX_MODULE_ALIASES = {"jax", "_jax", "_jx"}
NUMPY_ALIASES = {"np", "numpy"}

#: dirs/files where raw bucket arithmetic is the implementation itself
#: (TPU004 exempt): the columnar layer OWNS choose_capacity, bucketing.py
#: defines the primitive, and the plan analyzer mirrors the rules
CAPACITY_SANCTIONED = (
    os.path.join("spark_rapids_tpu", "columnar") + os.sep,
    os.path.join("spark_rapids_tpu", "utils", "bucketing.py"),
    os.path.join("spark_rapids_tpu", "utils", "__init__.py"),
    os.path.join("spark_rapids_tpu", "plugin", "plananalysis.py"),
)


def _default_allowlist_path() -> str:
    return default_allowlist_path(
        "LINT_ALLOWLIST_PATH", os.path.join("tools", "tpu_lint_allow.txt"))


def _is_jit_call(call: ast.Call) -> bool:
    chain = _attr_chain(call.func)
    return chain is not None and chain.split(".")[0] in JAX_MODULE_ALIASES \
        and chain.endswith(".jit")


def _is_jit_like(call: ast.Call) -> bool:
    """jax.jit OR pjit under any import spelling (TPU005 scope)."""
    chain = _attr_chain(call.func)
    if chain is None:
        return False
    if chain.split(".")[-1] == "pjit":
        return True
    return _is_jit_call(call)


def _jit_regions(tree: ast.AST, parents) -> Set[ast.AST]:
    """Function defs passed to jax.jit — resolved by NAME within the
    jit call's enclosing function (then module) scope."""
    regions: Set[ast.AST] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _is_jit_call(node)):
            continue
        if not node.args:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Lambda):
            regions.add(arg)
            continue
        if not isinstance(arg, ast.Name):
            continue
        scope = _enclosing_function(node, parents)
        while True:
            # a Lambda scope has an expression body, never statement
            # defs — look straight through it to the outer function
            # (e.g. ``cached_pipeline(..., lambda: jax.jit(run))``)
            if scope is None:
                body = tree.body
            elif isinstance(scope, ast.Lambda):
                body = []
            else:
                body = scope.body
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and stmt.name == arg.id:
                    regions.add(stmt)
                    break
            else:
                if scope is None:
                    break
                scope = _enclosing_function(scope, parents)
                continue
            break
    return regions


def _region_nodes(region: ast.AST):
    """All nodes inside a jit region, including nested defs."""
    yield from ast.walk(region)


def _traced_params(region: ast.AST) -> Set[str]:
    """Parameter names of the jit entry and every nested def (all are
    trace-time values when the region runs under jax.jit)."""
    names: Set[str] = set()
    for node in ast.walk(region):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            a = node.args
            for p in (list(a.posonlyargs) + list(a.args)
                      + list(a.kwonlyargs)):
                names.add(p.arg)
            if a.vararg:
                names.add(a.vararg.arg)
            if a.kwarg:
                names.add(a.kwarg.arg)
    names.discard("self")
    return names


def _refs_any(node: ast.AST, names: Set[str]) -> bool:
    return any(
        isinstance(n, ast.Name) and n.id in names for n in ast.walk(node))


#: the sanctioned guarded-cache helpers (exec/base.cached_pipeline and
#: exec/mesh._cached_program): a builder function handed to one of these
#: has its jit result stored in the keyed cache BY the helper, under the
#: pipeline-cache lock — that IS the cache store
_CACHED_BUILDER_FUNCS = ("cached_pipeline", "_cached_program")


def _is_cached_builder_call(call: ast.Call) -> bool:
    chain = _attr_chain(call.func)
    return chain is not None \
        and chain.split(".")[-1] in _CACHED_BUILDER_FUNCS


def _passed_to_cached_builder(name: str, tree: ast.AST) -> bool:
    """Is a def of this name used as an argument to cached_pipeline /
    _cached_program anywhere in the module?"""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_cached_builder_call(node):
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(a, ast.Name) and a.id == name:
                    return True
    return False


def _routes_through_cached_builder(call: ast.Call, parents,
                                   tree: ast.AST) -> bool:
    """Does this jit/pjit call's result reach the guarded cache layer —
    i.e. is it (part of) the return value of a builder handed to
    cached_pipeline/_cached_program, or inside a lambda passed to one
    directly? (Tuple wrapping — ``return jax.jit(fn), aux`` — is the
    mesh builders' shape and counts.)"""
    cur = call
    while True:
        parent = parents.get(cur)
        if parent is None:
            return False
        if isinstance(parent, ast.Lambda):
            outer = parents.get(parent)
            return isinstance(outer, ast.Call) \
                and _is_cached_builder_call(outer)
        if isinstance(parent, ast.Return):
            fn = _enclosing_function(parent, parents)
            return isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and _passed_to_cached_builder(fn.name, tree)
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Module)):
            return False
        cur = parent


def _in_cache_store(call: ast.Call, parents, tree: ast.AST) -> bool:
    """jax.jit(...) whose result lands in a subscript store
    (``_CACHE[key] = jax.jit(run)``), is returned from an
    lru_cache-decorated function, or is returned from / wrapped in a
    builder handed to the guarded cache helpers (cached_pipeline)."""
    cur = call
    while True:
        parent = parents.get(cur)
        if parent is None:
            return False
        if isinstance(parent, ast.Assign):
            return any(isinstance(t, ast.Subscript) for t in parent.targets)
        if isinstance(parent, ast.Lambda):
            # ``cached_pipeline(..., lambda: jax.jit(run))``
            outer = parents.get(parent)
            return isinstance(outer, ast.Call) \
                and _is_cached_builder_call(outer)
        if isinstance(parent, ast.Return):
            fn = _enclosing_function(parent, parents)
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in fn.decorator_list:
                    chain = _attr_chain(dec) or (
                        _attr_chain(dec.func)
                        if isinstance(dec, ast.Call) else None)
                    if chain and ("lru_cache" in chain or chain.endswith(
                            ".cache") or chain == "cache"):
                        return True
                if _passed_to_cached_builder(fn.name, tree):
                    return True
            return False
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Module)):
            return False
        cur = parent


def lint_file(path: str, relpath: str) -> List[Finding]:
    with open(path, "rb") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding(relpath, e.lineno or 0, "TPU000", "<module>",
                        f"syntax error: {e.msg}")]
    parents = _parents(tree)
    qualnames = _function_defs(tree)
    regions = _jit_regions(tree, parents)
    region_node_sets = {r: set(ast.walk(r)) for r in regions}

    def qual_of(node) -> str:
        fn = node if node in qualnames else _enclosing_function(node, parents)
        while fn is not None and fn not in qualnames:
            fn = _enclosing_function(fn, parents)
        return qualnames.get(fn, "<module>")

    findings: List[Finding] = []
    strict_sync = (
        any(f"spark_rapids_tpu{os.sep}{d}{os.sep}" in relpath
            for d in SYNC_STRICT_DIRS)
        and not any(relpath.endswith(s) for s in SANCTIONED_FILES)
    )
    jit_strict = (
        f"spark_rapids_tpu{os.sep}" in relpath
        and not any(relpath.endswith(s) for s in SANCTIONED_FILES)
    )
    capacity_strict = (
        f"spark_rapids_tpu{os.sep}" in relpath
        and not any(s in relpath for s in CAPACITY_SANCTIONED)
    )

    in_any_region = set()
    for s in region_node_sets.values():
        in_any_region |= s

    for node in ast.walk(tree):
        if (capacity_strict and isinstance(node, ast.BinOp)
                and isinstance(node.op, ast.LShift)):
            # hand-rolled power-of-two bucket: 1 << (...).bit_length()
            if any(isinstance(n, ast.Call)
                   and isinstance(n.func, ast.Attribute)
                   and n.func.attr == "bit_length"
                   for n in ast.walk(node)):
                findings.append(Finding(
                    relpath, node.lineno, "TPU004", qual_of(node),
                    "hand-rolled power-of-two capacity arithmetic — use "
                    "columnar.column.choose_capacity"))
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        root = chain.split(".")[0] if chain else None

        # --- TPU001: raw host syncs in the strict dirs -------------------
        if strict_sync:
            if chain and root in JAX_MODULE_ALIASES and chain.endswith(
                    (".device_get", ".block_until_ready")):
                findings.append(Finding(
                    relpath, node.lineno, "TPU001", qual_of(node),
                    f"raw {chain.split('.', 1)[1]} — batch it through "
                    "exec/base.py host_pull()/host_fence()"))
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item" and not node.args):
                findings.append(Finding(
                    relpath, node.lineno, "TPU001", qual_of(node),
                    ".item() is a per-value device sync — pull once via "
                    "exec/base.py host_pull()"))

        # --- TPU002: unstable jit cache keys -----------------------------
        if _is_jit_call(node):
            if node.args and isinstance(node.args[0], ast.Lambda):
                findings.append(Finding(
                    relpath, node.lineno, "TPU002", qual_of(node),
                    "jax.jit(lambda ...): a fresh lambda never hits the "
                    "executable cache — jit a module-level def"))
            elif _enclosing_function(node, parents) is not None \
                    and not _in_cache_store(node, parents, tree):
                findings.append(Finding(
                    relpath, node.lineno, "TPU002", qual_of(node),
                    "jax.jit(...) inside a function without a cache "
                    "store — every call retraces; keep compiled fns in "
                    "a keyed cache or an lru_cache'd builder"))
        # --- TPU005: raw jit/pjit outside the guarded cache layer --------
        if jit_strict and _is_jit_like(node) \
                and not _routes_through_cached_builder(node, parents, tree):
            findings.append(Finding(
                relpath, node.lineno, "TPU005", qual_of(node),
                "raw jax.jit/pjit outside exec/base.cached_pipeline — "
                "build programs inside a builder handed to the guarded "
                "cache so they join the AOT program cache, the cost "
                "harvest, and the donation-mask key fold"))
        # --- TPU004: capacity decisions outside the sanctioned layer -----
        if capacity_strict:
            callee = (node.func.id if isinstance(node.func, ast.Name)
                      else (chain.rsplit(".", 1)[-1] if chain else None))
            if callee in ("bucket_rows", "round_up_pow2"):
                findings.append(Finding(
                    relpath, node.lineno, "TPU004", qual_of(node),
                    f"direct {callee}() — capacity decisions must go "
                    "through columnar.column.choose_capacity so the plan "
                    "analyzer can reproduce the bucket"))

        if (isinstance(node.func, ast.Name) and node.func.id == "id"
                and node.args):
            parent = parents.get(node)
            if isinstance(parent, ast.Tuple):
                holder = parents.get(parent)
                tgt = getattr(holder, "targets", None)
                names = [t.id for t in (tgt or [])
                         if isinstance(t, ast.Name)]
                if any("key" in n.lower() for n in names):
                    findings.append(Finding(
                        relpath, node.lineno, "TPU002", qual_of(node),
                        "id(...) in a cache key: ids are reused after GC "
                        "and silently alias entries — key on values"))

    # --- TPU003: traced-value hazards inside jit regions -----------------
    for region in regions:
        traced = _traced_params(region)
        qn = qualnames.get(region, "<lambda>")
        for node in region_node_sets[region]:
            if isinstance(node, (ast.If, ast.While)):
                if _refs_any(node.test, traced):
                    findings.append(Finding(
                        relpath, node.lineno, "TPU003", qn,
                        "python if/while on a traced value inside a jit "
                        "region — use jnp.where/lax.cond"))
            elif isinstance(node, ast.Call):
                chain = _attr_chain(node.func)
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item" and not node.args):
                    findings.append(Finding(
                        relpath, node.lineno, "TPU003", qn,
                        ".item() inside a jit region is a trace error / "
                        "hidden sync"))
                elif (isinstance(node.func, ast.Name)
                      and node.func.id in ("float", "int", "bool")
                      and node.args and _refs_any(node.args[0], traced)):
                    findings.append(Finding(
                        relpath, node.lineno, "TPU003", qn,
                        f"{node.func.id}() on a traced value inside a jit "
                        "region — trace error; use astype/jnp casts"))
                elif (chain and chain.split(".")[0] in NUMPY_ALIASES
                      and chain.endswith(".asarray") and node.args
                      and _refs_any(node.args[0], traced)):
                    findings.append(Finding(
                        relpath, node.lineno, "TPU003", qn,
                        "np.asarray(traced value) pulls to host inside a "
                        "jit region — use jnp.asarray"))
    return findings


def main(argv: List[str]) -> int:
    return run_tool("tpu_lint", argv, DEFAULT_TARGET,
                    _default_allowlist_path(), lint_file)


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
