// LZ4 block-format codec (compress + decompress), C++17, no dependencies.
//
// Reference analog: the nvcomp LZ4 batched codec behind the reference's
// TableCompressionCodec SPI (NvcompLZ4CompressionCodec.scala:25-159,
// SURVEY.md §2.12 item 4). On TPU hosts there is no device codec; this is
// the native host-side implementation the shuffle serializer loads through
// ctypes (spark_rapids_tpu/native.py). Standard LZ4 block format:
//   token: high nibble = literal run length, low nibble = match length - 4
//   (15 => 255-terminated extension bytes), literals, then a 2-byte LE
//   match offset. The final sequence is literals-only; the last match must
//   start >= 12 bytes from the end and leave >= 5 literal bytes.
#include <cstdint>
#include <cstring>

namespace {

constexpr int MINMATCH = 4;
constexpr int HASH_LOG = 16;
constexpr int HASH_SIZE = 1 << HASH_LOG;

inline uint32_t read32(const uint8_t* p) {
    uint32_t v;
    std::memcpy(&v, p, 4);
    return v;
}

inline uint32_t hash4(uint32_t v) {
    return (v * 2654435761u) >> (32 - HASH_LOG);
}

inline uint8_t* write_length(uint8_t* op, int len) {
    while (len >= 255) {
        *op++ = 255;
        len -= 255;
    }
    *op++ = static_cast<uint8_t>(len);
    return op;
}

}  // namespace

extern "C" {

// worst-case compressed size for n input bytes (LZ4_compressBound)
int srtpu_lz4_bound(int n) {
    return n + n / 255 + 16;
}

// returns compressed size, or 0 on failure / insufficient dst capacity
int srtpu_lz4_compress(const uint8_t* src, int n, uint8_t* dst, int dcap) {
    if (n < 0 || dcap < srtpu_lz4_bound(n)) return 0;
    if (n == 0) return 0;
    const uint8_t* ip = src;
    const uint8_t* const iend = src + n;
    // matches may not start within the last 12 bytes (format rule)
    const uint8_t* const mflimit = (n >= 13) ? iend - 12 : src;
    const uint8_t* anchor = src;
    uint8_t* op = dst;

    int32_t table[HASH_SIZE];
    std::memset(table, -1, sizeof(table));

    while (ip < mflimit) {
        uint32_t h = hash4(read32(ip));
        int32_t cand = table[h];
        table[h] = static_cast<int32_t>(ip - src);
        const uint8_t* match = src + cand;
        if (cand < 0 || ip - match > 65535 || read32(match) != read32(ip)) {
            ++ip;
            continue;
        }
        // extend the match forward (stay clear of the 5-byte tail rule)
        const uint8_t* const matchlimit = iend - 5;
        const uint8_t* mp = match + MINMATCH;
        const uint8_t* cp = ip + MINMATCH;
        while (cp < matchlimit && *cp == *mp) {
            ++cp;
            ++mp;
        }
        int mlen = static_cast<int>(cp - ip);
        int litlen = static_cast<int>(ip - anchor);

        // token
        uint8_t* token = op++;
        int lit_nib = litlen >= 15 ? 15 : litlen;
        int mat_nib = (mlen - MINMATCH) >= 15 ? 15 : (mlen - MINMATCH);
        *token = static_cast<uint8_t>((lit_nib << 4) | mat_nib);
        if (litlen >= 15) op = write_length(op, litlen - 15);
        std::memcpy(op, anchor, litlen);
        op += litlen;
        uint16_t off = static_cast<uint16_t>(ip - match);
        *op++ = static_cast<uint8_t>(off & 0xFF);
        *op++ = static_cast<uint8_t>(off >> 8);
        if (mlen - MINMATCH >= 15) op = write_length(op, mlen - MINMATCH - 15);

        ip = cp;
        anchor = ip;
        // NOTE: no table insert here — the loop top inserts for this ip;
        // inserting now would make the next lookup find ip itself
        // (offset 0, malformed stream)
    }

    // final literals-only sequence
    int litlen = static_cast<int>(iend - anchor);
    uint8_t* token = op++;
    *token = static_cast<uint8_t>((litlen >= 15 ? 15 : litlen) << 4);
    if (litlen >= 15) op = write_length(op, litlen - 15);
    std::memcpy(op, anchor, litlen);
    op += litlen;
    return static_cast<int>(op - dst);
}

// returns decompressed size, or -1 on malformed input / capacity overflow
int srtpu_lz4_decompress(const uint8_t* src, int n, uint8_t* dst, int dcap) {
    const uint8_t* ip = src;
    const uint8_t* const iend = src + n;
    uint8_t* op = dst;
    uint8_t* const oend = dst + dcap;

    while (ip < iend) {
        uint8_t token = *ip++;
        int litlen = token >> 4;
        if (litlen == 15) {
            uint8_t b;
            do {
                if (ip >= iend) return -1;
                b = *ip++;
                litlen += b;
            } while (b == 255);
        }
        if (ip + litlen > iend || op + litlen > oend) return -1;
        std::memcpy(op, ip, litlen);
        ip += litlen;
        op += litlen;
        if (ip >= iend) break;  // final sequence has no match part

        if (ip + 2 > iend) return -1;
        int off = ip[0] | (ip[1] << 8);
        ip += 2;
        if (off == 0 || op - dst < off) return -1;
        int mlen = (token & 0x0F);
        if (mlen == 15) {
            uint8_t b;
            do {
                if (ip >= iend) return -1;
                b = *ip++;
                mlen += b;
            } while (b == 255);
        }
        mlen += MINMATCH;
        if (op + mlen > oend) return -1;
        const uint8_t* match = op - off;
        // when the match overlaps the output (off < mlen) the bytes being
        // read are being produced by this same copy: byte-forward copy IS
        // the semantics (repeating pattern); memcpy only when disjoint
        if (off >= mlen) {
            std::memcpy(op, match, mlen);
        } else {
            for (int i = 0; i < mlen; ++i) op[i] = match[i];
        }
        op += mlen;
    }
    return static_cast<int>(op - dst);
}

}  // extern "C"
