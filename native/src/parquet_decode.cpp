// Parquet RLE/bit-packed hybrid stream decoder, C++17, no dependencies.
//
// Reference analog: the native half of the reference's parquet decode —
// cudf's gpuDecodePages kernels behind GpuParquetScan.scala:1157. On TPU
// the dictionary-code EXPANSION happens on-device (XLA gathers,
// io/parquet_device.py); this native routine covers the host half that was
// previously vectorized-numpy: expanding the RLE/bit-packed hybrid streams
// (dictionary indices and definition levels) into narrow integer arrays.
// Called per page through ctypes; releases the GIL, so the per-column
// planning thread pool gets real parallelism.
#include <algorithm>
#include <cstdint>
#include <cstring>

namespace {

// Decode one hybrid stream of n values (bit width bw) into out[0..n).
// Returns the byte position just after the stream, or -1 on malformed /
// short input. T is the output element (u8/u16/i32 picked by caller).
template <typename T>
int64_t decode_hybrid(const uint8_t* data, int64_t pos, int64_t end, int bw,
                      int64_t n, T* out) {
    if (bw == 0) {
        std::memset(out, 0, sizeof(T) * static_cast<size_t>(n));
        return pos;
    }
    if (bw < 0 || bw > 24) return -1;
    const int byte_w = (bw + 7) / 8;
    const uint32_t mask = (1u << bw) - 1;
    constexpr int64_t kMaxRuns = int64_t{1} << 20;  // adversarial-file guard
    int64_t got = 0;
    int64_t runs = 0;
    while (got < n && pos < end) {
        if (++runs > kMaxRuns) return -1;
        uint64_t header = 0;
        int shift = 0;
        while (true) {
            if (pos >= end || shift > 56) return -1;
            uint8_t b = data[pos++];
            header |= static_cast<uint64_t>(b & 0x7F) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
        if (header & 1) {  // bit-packed run: (header>>1) groups of 8
            const int64_t groups = static_cast<int64_t>(header >> 1);
            // bound BEFORE multiplying: a huge varint must not wrap the
            // products negative and slip past the range checks below
            if (groups < 0 || groups > (end - pos) / bw + 8) return -1;
            const int64_t count = groups * 8;
            const int64_t nbytes = groups * bw;
            if (pos + nbytes > end) return -1;
            const int64_t take = std::min(count, n - got);
            const uint8_t* p = data + pos;
            uint64_t buf = 0;
            int bits = 0;
            int64_t bi = 0;
            for (int64_t i = 0; i < take; ++i) {
                while (bits < bw) {
                    buf |= static_cast<uint64_t>(p[bi++]) << bits;
                    bits += 8;
                }
                out[got + i] = static_cast<T>(buf & mask);
                buf >>= bw;
                bits -= bw;
            }
            pos += nbytes;
            got += count;  // trailing pad values advance the logical count
        } else {  // RLE run
            const int64_t count = static_cast<int64_t>(header >> 1);
            if (pos + byte_w > end) return -1;
            uint32_t v = 0;
            for (int i = 0; i < byte_w; ++i)
                v |= static_cast<uint32_t>(data[pos + i]) << (8 * i);
            pos += byte_w;
            const int64_t take = std::min(count, n - got);
            std::fill(out + got, out + got + take, static_cast<T>(v));
            got += count;
        }
    }
    return got < n ? -1 : pos;
}

}  // namespace

extern "C" {

// out_width selects the output element size: 1 (u8), 2 (u16), 4 (i32).
// Returns the byte position after the stream, or -1 on error.
int64_t srtpu_pq_hybrid_decode(const uint8_t* data, int64_t pos, int64_t end,
                               int32_t bw, int64_t n, int32_t out_width,
                               void* out) {
    switch (out_width) {
        case 1:
            return decode_hybrid(data, pos, end, bw, n,
                                 static_cast<uint8_t*>(out));
        case 2:
            return decode_hybrid(data, pos, end, bw, n,
                                 static_cast<uint16_t*>(out));
        case 4:
            return decode_hybrid(data, pos, end, bw, n,
                                 static_cast<int32_t*>(out));
        default:
            return -1;
    }
}

// Parse a BYTE_ARRAY PLAIN dictionary page: count (u32-len, bytes) entries.
// Writes count+1 int32 offsets and the concatenated chars; returns total
// char bytes, or -1 if the payload is malformed / chars overflow char_cap.
int64_t srtpu_pq_binary_dict(const uint8_t* raw, int64_t len, int64_t count,
                             int32_t* offsets, uint8_t* chars,
                             int64_t char_cap) {
    int64_t p = 0;
    int64_t total = 0;
    offsets[0] = 0;
    for (int64_t i = 0; i < count; ++i) {
        if (p + 4 > len) return -1;
        uint32_t ln;
        std::memcpy(&ln, raw + p, 4);
        p += 4;
        if (p + ln > len || total + ln > char_cap) return -1;
        std::memcpy(chars + total, raw + p, ln);
        p += ln;
        total += ln;
        offsets[i + 1] = static_cast<int32_t>(total);
    }
    return total;
}

}  // extern "C"
