"""Build the native runtime library (g++ -O3 -shared).

Reference analog: the in-tree native build (udf-examples CMakeLists /
the cudf native jar) — here a single g++ invocation; callers fall back to
pure python when the toolchain is unavailable.
"""
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
SOURCES = [os.path.join(HERE, "src", "lz4.cpp"),
           os.path.join(HERE, "src", "parquet_decode.cpp")]
OUT = os.path.join(HERE, "libsrtpu.so")


def build(force: bool = False) -> str:
    if not force and os.path.exists(OUT) and all(
        os.path.getmtime(OUT) >= os.path.getmtime(s) for s in SOURCES
    ):
        return OUT
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", OUT, *SOURCES]
    subprocess.run(cmd, check=True, capture_output=True)
    return OUT


if __name__ == "__main__":
    print(build(force="--force" in sys.argv))
