"""Generated reference docs.

Reference analog: RapidsConf.help (RapidsConf.scala:838) -> docs/configs.md
and TypeChecks.help (TypeChecks.scala:1005) -> docs/supported_ops.md — both
documentation artifacts generated from the live registries so they can
never drift from the code.

docs/supported_ops.md is generated ENTIRELY from the static type matrices
in plugin/typechecks.py — the same tables that drive plan tagging — so a
cell in the doc IS the tagging behavior. ``python -m
spark_rapids_tpu.plugin.docgen`` regenerates; ``--check`` (wired into CI)
fails when a matrix cell was edited without regenerating.
"""
from __future__ import annotations

import sys
from typing import Dict, List, Tuple

from ..conf import _REGISTRY


def _import_conf_modules() -> None:
    """Some conf entries register on first import of their module
    (memory/catalog.py, ml/columnar_rdd.py). The generated doc must not
    depend on what happens to be imported, so pull them all in first."""
    import importlib

    for mod in ("spark_rapids_tpu.events",
                "spark_rapids_tpu.hlo",
                "spark_rapids_tpu.memory.catalog",
                "spark_rapids_tpu.ml.columnar_rdd",
                "spark_rapids_tpu.serve.program_cache",
                "spark_rapids_tpu.serve.scheduler",
                "spark_rapids_tpu.xla_cost"):
        try:
            importlib.import_module(mod)
        except ImportError:
            pass


def configs_md() -> str:
    _import_conf_modules()
    lines = [
        "# Configuration",
        "",
        "Generated from the conf registry (do not edit by hand; "
        "`python -m spark_rapids_tpu.plugin.docgen`).",
        "",
        "| Key | Default | Description |",
        "|---|---|---|",
    ]
    for key in sorted(_REGISTRY):
        e = _REGISTRY[key]
        if e.internal:
            continue
        lines.append(f"| {e.key} | {e.default!r} | {e.doc} |")
    return "\n".join(lines) + "\n"


def _param_rows(name: str, desc: str, ctx_label: str, cc) -> List[str]:
    """One table row per parameter plus the result row of one context.
    Cells are conf-INDEPENDENT by design: a conf-gated tag renders as PS
    with the gate named in Notes, never as a flipped cell."""
    from . import typechecks as TC

    rows = []
    entries: List[Tuple[str, object]] = [
        (pc.name, pc) for pc in cc.params
    ]
    if cc.repeat is not None:
        entries.append((f"{cc.repeat.name}...", cc.repeat))
    entries.append(("result", None))
    first = True
    for pname, pc in entries:
        sig = cc.output if pc is None else pc.sig
        cells = []
        notes = []
        for tag in TC.TYPE_TAGS:
            cells.append(sig.cell(tag))
            n = sig.cell_note(tag)
            if n:
                notes.append(f"{tag}: {n}")
        if pc is not None and pc.lit_required:
            notes.insert(0, "must be a literal")
        rows.append(
            "| " + " | ".join(
                [name if first else "", desc if first else "", ctx_label,
                 pname] + cells + ["; ".join(notes)]
            ) + " |"
        )
        first = False
    return rows


def supported_ops_md() -> str:
    """The supported-ops matrix doc: every expression rule's per-context,
    per-parameter type cells, the cast grid, and the exec rules — all
    read straight from typechecks.CHECKS / CAST_CHECKS."""
    from . import typechecks as TC
    from .overrides import EXEC_RULES, EXPRESSION_RULES

    head = " | ".join(TC.TYPE_TAGS)
    lines: List[str] = [
        "# Supported operators and expressions",
        "",
        "Generated ENTIRELY from the static type matrices in "
        "`plugin/typechecks.py` — the same tables the plan tagger uses — "
        "so this document cannot drift from behavior (reference: the "
        "TypeChecks-generated docs/supported_ops.md). Regenerate with "
        "`python -m spark_rapids_tpu.plugin.docgen`; CI runs `--check`.",
        "",
        "Cells: `S` = supported; `PS` = partial support (see the Notes "
        "column: a conf gate, a literal-only parameter, or a documented "
        "restriction); blank = the plan falls back to CPU with a reason "
        "naming the rule, parameter, and type (read it from "
        "`TpuSession.explain()`, see docs/compatibility.md).",
        "",
        "## Expressions",
        "",
        f"| Expression | Description | Context | Param | {head} | Notes |",
        "|---" * (4 + len(TC.TYPE_TAGS) + 1) + "|",
    ]
    for cls in sorted(EXPRESSION_RULES, key=lambda c: EXPRESSION_RULES[c].name):
        r = EXPRESSION_RULES[cls]
        checks = TC.CHECKS.get(cls)
        if checks is None:
            lines.append(
                "| " + " | ".join(
                    [r.name, r.description, "-", "-"]
                    + [""] * len(TC.TYPE_TAGS)
                    + ["no matrix declared"]) + " |")
            continue
        # collapse contexts that share one ContextCheck (structural nodes)
        by_cc: Dict[int, List[str]] = {}
        cc_of: Dict[int, object] = {}
        for ctx in TC.CONTEXTS:
            cc = checks.contexts.get(ctx)
            if cc is None:
                continue
            by_cc.setdefault(id(cc), []).append(ctx)
            cc_of[id(cc)] = cc
        first = True
        for cid, ctxs in by_cc.items():
            label = "all" if len(ctxs) == len(checks.contexts) > 1 \
                else "/".join(ctxs)
            lines.extend(_param_rows(
                r.name if first else "", r.description if first else "",
                label, cc_of[cid]))
            first = False
    lines += [
        "",
        "## Casts",
        "",
        "The `Cast` from-type x to-type grid (`CastChecks`). `PS` cells "
        "are conf-gated or noted below.",
        "",
        f"| From \\ To | {head} |",
        "|---" * (1 + len(TC.TYPE_TAGS)) + "|",
    ]
    cast_notes: List[str] = []
    for frm in TC.TYPE_TAGS:
        sig = TC.CAST_CHECKS.matrix.get(frm, TC.none)
        cells = []
        for to in TC.TYPE_TAGS:
            cells.append(sig.cell(to))
            n = sig.cell_note(to)
            if n:
                cast_notes.append(f"* {frm} -> {to}: {n}")
        lines.append(f"| {frm} | " + " | ".join(cells) + " |")
    if cast_notes:
        lines += [""] + cast_notes
    lines += [
        "",
        "## Execs",
        "",
        "Exec rules tag their output schemas against the same engine type "
        "set (array/struct columns always fall back; decimal obeys "
        "spark.rapids.tpu.sql.decimalType.enabled and the DECIMAL64 cap).",
        "",
        "| Exec | Description |",
        "|---|---|",
    ]
    for cls in sorted(EXEC_RULES, key=lambda c: EXEC_RULES[c].name):
        r = EXEC_RULES[cls]
        lines.append(f"| {r.name} | {r.description} |")
    return "\n".join(lines) + "\n"


_DOCS = {
    "configs.md": configs_md,
    "supported_ops.md": supported_ops_md,
}


def write_docs(outdir: str = "docs") -> None:
    import os

    os.makedirs(outdir, exist_ok=True)
    for fname, gen in _DOCS.items():
        with open(os.path.join(outdir, fname), "w") as f:
            f.write(gen())


def check_docs(outdir: str = "docs") -> List[str]:
    """Names of generated docs that are out of sync with the registries
    (empty = clean). The CI `docgen --check` gate."""
    import os

    stale = []
    for fname, gen in _DOCS.items():
        path = os.path.join(outdir, fname)
        try:
            with open(path) as f:
                on_disk = f.read()
        except OSError:
            stale.append(fname)
            continue
        if on_disk != gen():
            stale.append(fname)
    return stale


def main(argv: List[str]) -> int:
    outdir = "docs"
    if "--outdir" in argv:
        outdir = argv[argv.index("--outdir") + 1]
    if "--check" in argv:
        stale = check_docs(outdir)
        if stale:
            print(
                "docs out of sync with the type matrix / conf registry: "
                + ", ".join(stale)
                + "\nregenerate with: python -m spark_rapids_tpu.plugin.docgen",
                file=sys.stderr,
            )
            return 1
        print("generated docs are in sync")
        return 0
    write_docs(outdir)
    print(f"wrote {', '.join(_DOCS)} to {outdir}/")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
