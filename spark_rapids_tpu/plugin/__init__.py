"""Planning/override layer: wraps a CPU physical plan, tags what can run on
TPU, converts convertible subtrees, and reports fallbacks.

Reference analog: GpuOverrides.scala + RapidsMeta.scala + TypeChecks.scala
(SURVEY.md §2.2) — carried over conceptually intact because this layer never
knew about CUDA in the reference either.
"""
from .overrides import TpuOverrides, PlanMeta, explain_plan  # noqa: F401
