"""Static plan analyzer: batch layouts, nullability, HBM footprint, and
compile-signature forecasting — all derived from the bound plan WITHOUT
lowering or executing anything.

PR 3 (plugin/typechecks.py) made *fallback* verdicts statically decidable;
this module closes the remaining plan-time blind spots, which are physical:

  * **layouts** — every operator's static output batch layout (capacity
    bucket, per-column storage dtype, string byte-pool bounds, dict
    metadata), derived with the SAME bucket rules the runtime uses
    (columnar/column.py ``choose_capacity``), so ``explain()`` shows the
    shapes a plan will materialize before anything runs;
  * **nullability** — a three-point lattice (NON_NULL / MAYBE_NULL /
    ALL_NULL) propagated through every registered expression rule.
    ``exec/base.py``'s fused chains and ``expr/eval.py``'s projection
    pipelines consume it (via :func:`entry_nonnull_flags` +
    ``ops/filter_gather.elide_validity``) to elide validity-plane HBM
    reads on provably non-null columns — sound because a NON_NULL
    column's validity at a batch boundary is exactly the liveness mask
    (padding slots are always invalid, live rows always valid);
  * **footprint** — a peak-HBM estimate per pipeline stage, checked
    against the memory/catalog.py budget so ``explain()`` can warn
    "this plan will spill/OOM at capacity N" before any device
    allocation happens;
  * **signatures** — a forecast of the distinct compile-cache keys the
    plan will request per pipeline cache site (fused_chain / project /
    agg_update / agg_plan / sort / ...), so a shape-polymorphic plan is
    flagged as a recompile storm at plan time, and the fusion decisions
    (sql.stageFusion / sql.agg.fusedPlan AUTO) are derived by calling
    the RUNTIME's own eligibility methods — the forecast then verifies
    them empirically: a wrong fusion prediction shows up as a
    forecast-vs-actual cache-miss disagreement in the cross-check.

Cross-check mode (spark.rapids.tpu.sql.analysis.crossCheck.enabled, the
same pattern as the typechecks probe cross-check) runs under the test
harness and asserts three invariants per query:

  1. zero disagreements between forecast compile signatures and the
     actual per-run cache-miss deltas (actual misses at every site must
     be covered by the forecast; warmed caches may miss less, never
     more);
  2. the analyzer's per-operator byte bound covers the profiler's
     measured ``bytesTouched`` on every operator;
  3. nullability-elided execution is differentially identical to the
     mask-carrying path (a second run with elision disabled).

A plan is ``bounded`` (invariants 1-2 assertable) only when EVERY
operator is exactly modeled: in-memory/range sources flowing through
project / filter / expand / union / limit / single-partition aggregate
and sort, with no CPU fallbacks. Anything else (file scans, exchanges,
joins, windows, AQE) still gets a structural report — layouts and
nullability — but its shapes are data-dependent, so the analyzer says
so instead of guessing.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .. import types as T
from ..conf import (
    AGG_FUSED_PLAN,
    ANALYSIS_ENABLED,
    ANALYSIS_NULL_ELISION,
    ANALYSIS_STORM_THRESHOLD,
    MAX_READER_BATCH_SIZE_ROWS,
    RapidsConf,
)
from ..cpu import plan as C
from ..expr import aggregates as A
from ..expr import expressions as E
from ..types import StructType

# ---------------------------------------------------------------------------
# The nullability lattice
# ---------------------------------------------------------------------------
NON_NULL = "NON_NULL"
MAYBE_NULL = "MAYBE_NULL"
ALL_NULL = "ALL_NULL"


def join_null(a: str, b: str) -> str:
    """Lattice join of two states flowing into one slot (e.g. union)."""
    if a == b:
        return a
    return MAYBE_NULL


def _meet_children(states: Sequence[str]) -> str:
    """Result state of an operator that is null iff ANY input is null
    (the standard strict-function rule: valid = AND of validities)."""
    if any(s == ALL_NULL for s in states):
        return ALL_NULL
    if all(s == NON_NULL for s in states):
        return NON_NULL
    return MAYBE_NULL


_CHILD_PASSTHROUGH = (
    E.UnaryMinus, E.UnaryPositive, E.Abs, E.BitwiseNot, E.Not,
    E.Floor, E.Ceil, E.Round, E.Rint, E.Signum,
    E.Sqrt, E.Exp, E.Sin, E.Cos, E.Tan, E.Asin, E.Acos, E.Atan,
    E.Sinh, E.Cosh, E.Tanh, E.Cbrt, E.Expm1, E.ToDegrees, E.ToRadians,
    E.Year, E.Quarter, E.Month, E.DayOfMonth, E.DayOfYear, E.DayOfWeek,
    E.WeekDay, E.Hour, E.Minute, E.Second, E.LastDay, E.UnixTimestamp,
    E.ToUnixTimestamp, E.TimeAdd,
    E.Upper, E.Lower, E.InitCap, E.Length,
    E.StringTrim, E.StringTrimLeft, E.StringTrimRight,
)

_STRICT_BINARY = (
    E.Pow, E.Atan2, E.BitwiseAnd, E.BitwiseOr, E.BitwiseXor,
    E.ShiftLeft, E.ShiftRight, E.ShiftRightUnsigned,
    E.DateAdd, E.DateSub, E.DateDiff, E.NaNvl,
    E.StartsWith, E.EndsWith, E.Contains,
)


def expr_nullability(e: E.Expression, inputs: Sequence[str]) -> str:
    """Output nullability of one BOUND expression given per-ordinal input
    column states. Unknown rules degrade to MAYBE_NULL — always sound."""
    ev = lambda c: expr_nullability(c, inputs)  # noqa: E731

    if isinstance(e, E.Alias):
        return ev(e.child)
    if isinstance(e, E.Literal):
        return ALL_NULL if e.value is None else NON_NULL
    if isinstance(e, E.BoundReference):
        return inputs[e.ordinal] if e.ordinal < len(inputs) else MAYBE_NULL
    if isinstance(e, (E.IsNull, E.IsNotNull, E.IsNan, E.EqualNullSafe,
                      E.Murmur3Hash, E.Rand, E.MonotonicallyIncreasingID,
                      E.SparkPartitionID, E.InputFileName)):
        return NON_NULL
    if isinstance(e, E.Coalesce):
        states = [ev(c) for c in e.exprs]
        if any(s == NON_NULL for s in states):
            return NON_NULL
        if all(s == ALL_NULL for s in states):
            return ALL_NULL
        return MAYBE_NULL
    if isinstance(e, (E.And, E.Or)):
        # 3-valued: two non-null operands give a non-null verdict; a null
        # operand can still be dominated (F AND NULL = F), so never ALL_NULL
        l, r = ev(e.left), ev(e.right)
        return NON_NULL if l == r == NON_NULL else MAYBE_NULL
    if isinstance(e, E.If):
        t, f = ev(e.true_value), ev(e.false_value)
        if t == f and t in (NON_NULL, ALL_NULL):
            return t
        return MAYBE_NULL
    if isinstance(e, E.CaseWhen):
        vals = [ev(v) for _, v in e.branches]
        vals.append(ev(e.else_value) if e.else_value is not None else ALL_NULL)
        if all(v == NON_NULL for v in vals):
            return NON_NULL
        if all(v == ALL_NULL for v in vals):
            return ALL_NULL
        return MAYBE_NULL
    if isinstance(e, E.In):
        has_null = any(v is None for v in e.values)
        c = ev(e.child)
        if c == ALL_NULL:
            return ALL_NULL
        return c if not has_null else MAYBE_NULL
    if isinstance(e, (E.Divide, E.IntegralDivide, E.Remainder, E.Pmod)):
        if isinstance(e.dtype, T.DecimalType):
            return MAYBE_NULL  # overflow nulls the row
        states = [ev(e.left), ev(e.right)]
        # a zero divisor nulls the row for non-float results; a literal
        # non-zero divisor cannot
        floats = e.dtype.is_floating and not isinstance(e, E.IntegralDivide)
        if isinstance(e, E.Divide):
            floats = False  # divide nulls on zero divisor even for floats
        lit_nonzero = (isinstance(e.right, E.Literal)
                       and e.right.value not in (None, 0, 0.0))
        if floats or lit_nonzero:
            return _meet_children(states)
        if any(s == ALL_NULL for s in states):
            return ALL_NULL
        return MAYBE_NULL
    if isinstance(e, (E.Add, E.Subtract, E.Multiply)):
        if isinstance(e.dtype, T.DecimalType):
            return MAYBE_NULL  # overflow nulls the row
        return _meet_children([ev(e.left), ev(e.right)])
    if isinstance(e, (E.Log, E.Log10, E.Log2, E.Log1p)):
        return MAYBE_NULL  # x <= 0 nulls the row
    if isinstance(e, E.Cast):
        frm, to = e.child.dtype, e.to
        risky = (
            isinstance(frm, (T.StringType, T.DecimalType))
            or isinstance(to, T.DecimalType)
            or (frm.is_floating and isinstance(to, T.TimestampType))
        )
        return MAYBE_NULL if risky else ev(e.child)
    if isinstance(e, _CHILD_PASSTHROUGH):
        kids = e.children
        return _meet_children([ev(c) for c in kids]) if kids else MAYBE_NULL
    if isinstance(e, (E._BinaryComparison,)):
        return _meet_children([ev(e.left), ev(e.right)])
    if isinstance(e, _STRICT_BINARY) or isinstance(e, E.Concat):
        kids = e.children
        return _meet_children([ev(c) for c in kids]) if kids else MAYBE_NULL
    return MAYBE_NULL


def agg_nullability(func: A.AggregateFunction, input_state: str,
                    grouped: bool) -> str:
    """Result nullability of one aggregate function. Groups are non-empty
    by construction, so grouped count is NON_NULL and grouped min/max/
    sum over a NON_NULL input stay NON_NULL; a grand aggregate over an
    empty (or all-null) input yields NULL for everything but count."""
    if isinstance(func, A.Count):
        return NON_NULL
    if grouped and input_state == NON_NULL and isinstance(
            func, (A.Sum, A.Min, A.Max, A.Average, A.First, A.Last)):
        return NON_NULL
    return MAYBE_NULL


def schema_nullability(schema: StructType) -> List[str]:
    return [NON_NULL if not f.nullable else MAYBE_NULL
            for f in schema.fields]


def narrow_by_predicate(states: List[str], bound: E.Expression) -> List[str]:
    """Post-filter narrowing: conjuncts that can never hold for a NULL in
    a direct column reference prove that column NON_NULL downstream
    (IsNotNull(c), and col-vs-non-null-literal comparisons, whose 3VL
    result is NULL — filtered — when the column is null)."""
    out = list(states)

    def mark(ref):
        if isinstance(ref, E.BoundReference) and ref.ordinal < len(out):
            out[ref.ordinal] = NON_NULL

    def visit(e):
        if isinstance(e, E.And):
            visit(e.left)
            visit(e.right)
            return
        if isinstance(e, E.IsNotNull):
            mark(e.child)
        elif isinstance(e, E._BinaryComparison) and not isinstance(
                e, E.EqualNullSafe):
            l, r = e.left, e.right
            if isinstance(l, E.BoundReference) and isinstance(r, E.Literal) \
                    and r.value is not None:
                mark(l)
            if isinstance(r, E.BoundReference) and isinstance(l, E.Literal) \
                    and l.value is not None:
                mark(r)

    visit(bound)
    return out


# ---------------------------------------------------------------------------
# Runtime consumption hook: which chain-entry columns may elide their
# validity plane. Sound because of the batch invariant (columnar/column.py):
# padding slots always hold validity=False and a declared-non-null column's
# live rows are all valid — validity IS the liveness mask, bit for bit.
# ---------------------------------------------------------------------------
def entry_nonnull_flags(schema: StructType, conf: RapidsConf) -> tuple:
    """Per-column elision flags for a batch of ``schema`` entering a fused
    pipeline; () when elision is disabled (the mask-carrying path)."""
    if not conf.get(ANALYSIS_NULL_ELISION):
        return ()
    flags = tuple(not f.nullable for f in schema.fields)
    return flags if any(flags) else ()


# ---------------------------------------------------------------------------
# Layout model
# ---------------------------------------------------------------------------
def _storage_bytes(dt: T.DataType) -> int:
    import numpy as np

    if isinstance(dt, T.NullType):
        return 1
    return int(np.dtype(dt.to_numpy()).itemsize)


@dataclasses.dataclass
class ColState:
    """Static layout + nullability of one column inside one batch."""

    name: str
    dtype: T.DataType
    null: str
    char_cap: Optional[int] = None   # strings: byte-pool array length
    max_len: Optional[int] = None    # strings: max single-row byte length

    @property
    def is_string(self) -> bool:
        return isinstance(self.dtype, (T.StringType, T.BinaryType))

    def bytes_at(self, cap: int) -> Optional[int]:
        """Upper bound of this column's contribution to batch_bytes()
        (exec/base.py) at capacity — covers both the rows-known and the
        capacity-fallback accounting the profiler uses."""
        if self.is_string:
            if self.char_cap is None:
                return None
            return cap * 5 + self.char_cap
        return cap * (_storage_bytes(self.dtype) + 1)

    def describe(self) -> str:
        t = self.dtype.simpleString
        if self.is_string and self.char_cap is not None:
            t += f"(chars<={self.char_cap})"
        return f"{self.name}: {t} {self.null}"


@dataclasses.dataclass
class BatchState:
    rows: Optional[int]  # exact logical rows when statically known
    cap: int
    cols: List[ColState]

    def sig(self) -> Optional[tuple]:
        """Static stand-in for exec/base.py batch_signature + capacity:
        two batches compile the same pipeline iff their sigs are equal.
        None when a string byte-pool bound is unknown."""
        parts: List[tuple] = [("cap", self.cap)]
        for c in self.cols:
            if c.is_string:
                if c.char_cap is None:
                    return None
                parts.append(("s", c.dtype.simpleString, c.char_cap,
                              c.max_len))
            else:
                parts.append(("f", c.dtype.simpleString))
        return tuple(parts)

    def bytes_bound(self) -> Optional[int]:
        total = 0
        for c in self.cols:
            b = c.bytes_at(self.cap)
            if b is None:
                return None
            total += b
        return total


@dataclasses.dataclass
class OpReport:
    name: str          # the TPU exec class name this node converts to
    detail: str
    layout: List[ColState]
    out_bytes: Optional[int]      # bound on this op's total bytesTouched
    sites: Dict[str, int]         # forecast compile signatures by site
    exact: bool
    notes: List[str]
    children: List["OpReport"]
    # the live-progress denominators (obs/progress.py): forecast output
    # rows / batch count when statically known, set centrally by
    # _Analyzer.analyze from the handler's batch states
    out_rows: Optional[int] = None
    out_batches: Optional[int] = None

    def lines(self, indent: int = 0) -> List[str]:
        pad = "  " * indent
        head = f"{pad}@{self.name}"
        if self.detail:
            head += f" {self.detail}"
        if self.out_bytes is not None:
            head += f" bytes<={_pretty_bytes(self.out_bytes)}"
        if self.sites:
            head += " compiles[" + ", ".join(
                f"{k}={v}" for k, v in sorted(self.sites.items())) + "]"
        if not self.exact:
            head += " (shapes not statically bounded)"
        out = [head]
        if self.layout:
            out.append(pad + "    " + "; ".join(
                c.describe() for c in self.layout))
        for n in self.notes:
            out.append(pad + "    note: " + n)
        for c in self.children:
            out.extend(c.lines(indent + 1))
        return out


def _pretty_bytes(n: int) -> str:
    if n >= 1 << 20:
        return f"{n / (1 << 20):.1f}MB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f}KB"
    return f"{n}B"


@dataclasses.dataclass
class PlanAnalysis:
    root: OpReport
    bounded: bool
    site_forecast: Dict[str, int]
    bytes_by_op: Dict[str, int]      # exec name -> summed byte bound
    peak_hbm: Optional[int]
    budget: Optional[int]
    warnings: List[str]
    elided_columns: int
    # forecast output rows / batch counts per exec name where statically
    # known — the denominators the live progress plane (/status) divides
    # record_batch's numerators into
    rows_by_op: Dict[str, int] = dataclasses.field(default_factory=dict)
    batches_by_op: Dict[str, int] = dataclasses.field(default_factory=dict)

    def render_lines(self) -> List[str]:
        lines = ["== Static Plan Analysis =="]
        lines.extend(self.root.lines())
        if self.bounded:
            total = sum(self.site_forecast.values())
            sites = ", ".join(f"{k}={v}" for k, v in
                              sorted(self.site_forecast.items()))
            lines.append(
                f"forecast compile signatures: {total}"
                + (f" ({sites})" if sites else ""))
        else:
            lines.append(
                "forecast compile signatures: not statically bounded "
                "(plan has data-dependent shapes or CPU fallbacks)")
        if self.elided_columns:
            lines.append(
                f"nullability elision: {self.elided_columns} validity "
                "plane(s) elided at pipeline entries")
        if self.peak_hbm is not None:
            b = ("unlimited" if self.budget is None
                 else _pretty_bytes(self.budget))
            lines.append(
                f"predicted peak HBM: {_pretty_bytes(self.peak_hbm)} "
                f"(budget: {b})")
        for w in self.warnings:
            lines.append("warning: " + w)
        return lines

    def render(self) -> str:
        return "\n".join(self.render_lines())

    def event_fields(self) -> Dict[str, object]:
        """The JSON-safe forecast payload for the ``plan_analysis``
        event-log record — tools/tpu_profile.py diffs these bounds against
        the measured compile_miss / op_batch events of the same query (the
        offline twin of the test harness's analysis cross-check)."""
        return {"bounded": self.bounded,
                "site_forecast": dict(self.site_forecast),
                "bytes_by_op": dict(self.bytes_by_op),
                "rows_by_op": dict(self.rows_by_op),
                "batches_by_op": dict(self.batches_by_op),
                "peak_hbm": self.peak_hbm, "budget": self.budget,
                "warnings": list(self.warnings)}


# ---------------------------------------------------------------------------
# The analyzer walk
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class _Result:
    parts: Optional[List[List[BatchState]]]  # None = shapes unknown
    layout: List[ColState]                   # merged per-column summary
    report: OpReport
    exact: bool
    # a fusable chain below (and including) this node that has not yet
    # been attributed to a consumer: (chain-top report, source sig set)
    pending_chain: Optional[Tuple[OpReport, Optional[Set[tuple]]]] = None
    # the source feeding the pending chain (for aggregates absorbing it)
    chain_source: Optional["_Result"] = None
    chain_len: int = 0


class _Analyzer:
    def __init__(self, conf: RapidsConf):
        self.conf = conf
        from ..utils.bucketing import bucket_rows

        self._bucket = bucket_rows
        self.elided = 0
        self.scan_resident = 0
        self.max_working = 0
        self.max_cap = 0  # largest batch capacity seen (OOM diagnostics)
        self.exact_all = True

    # -- shared helpers ----------------------------------------------------
    def _note_working(self, *bounds: Optional[int]) -> None:
        known = [b for b in bounds if b is not None]
        if known:
            self.max_working = max(self.max_working, sum(known))

    def _count_elision(self, schema: StructType) -> None:
        flags = entry_nonnull_flags(schema, self.conf)
        self.elided += sum(1 for f in flags if f)

    def _sigs(self, parts: Optional[List[List[BatchState]]]
              ) -> Optional[Set[tuple]]:
        if parts is None:
            return None
        sigs: Set[tuple] = set()
        for p in parts:
            for b in p:
                s = b.sig()
                if s is None:
                    return None
                sigs.add(s)
        return sigs

    def _total_bytes(self, parts: Optional[List[List[BatchState]]]
                     ) -> Optional[int]:
        if parts is None:
            return None
        total = 0
        for p in parts:
            for b in p:
                self.max_cap = max(self.max_cap, b.cap)
                bb = b.bytes_bound()
                if bb is None:
                    return None
                total += bb
        return total

    def _finalize_chain(self, r: _Result) -> None:
        """The chain top runs run_fused_chain (one 'fused_chain' compile
        per distinct source signature) because no consumer absorbed it."""
        if r.pending_chain is None:
            return
        top_report, source_sigs = r.pending_chain
        if source_sigs is not None:
            top_report.sites["fused_chain"] = (
                top_report.sites.get("fused_chain", 0) + len(source_sigs))
        else:
            top_report.exact = False
        if r.chain_source is not None and r.chain_source.layout:
            self._count_elision(StructType(tuple(
                T.StructField(c.name, c.dtype, c.null != NON_NULL)
                for c in r.chain_source.layout)))
        r.pending_chain = None
        r.chain_source = None

    def _merge_layout(self, parts: Optional[List[List[BatchState]]],
                      schema: StructType) -> List[ColState]:
        """Per-column summary across batches (max char caps, joined
        nullability); falls back to schema-derived states."""
        if parts is None or not any(parts):
            return [
                ColState(f.name, f.dataType,
                         NON_NULL if not f.nullable else MAYBE_NULL)
                for f in schema.fields
            ]
        merged: List[ColState] = []
        batches = [b for p in parts for b in p]
        for i, f in enumerate(schema.fields):
            cols = [b.cols[i] for b in batches]
            null = cols[0].null
            for c in cols[1:]:
                null = join_null(null, c.null)
            ccs = [c.char_cap for c in cols]
            mls = [c.max_len for c in cols]
            merged.append(ColState(
                f.name, f.dataType, null,
                char_cap=(None if any(c is None for c in ccs) or not ccs
                          else max(ccs)) if cols[0].is_string else None,
                max_len=(None if any(m is None for m in mls) or not mls
                         else max(mls)) if cols[0].is_string else None,
            ))
        return merged

    # -- node dispatch -----------------------------------------------------
    def analyze(self, node: C.CpuExec) -> _Result:
        handlers = {
            C.CpuScanExec: self._scan,
            C.CpuFileScanExec: self._file_scan,
            C.CpuRangeExec: self._range,
            C.CpuProjectExec: self._project,
            C.CpuFilterExec: self._filter,
            C.CpuHashAggregateExec: self._aggregate,
            C.CpuSortExec: self._sort,
            C.CpuLocalLimitExec: self._limit,
            C.CpuCollectLimitExec: self._limit,
            C.CpuUnionExec: self._union,
            C.CpuGenerateExec: self._expand,   # subclass before base
            C.CpuExpandExec: self._expand,
        }
        h = handlers.get(type(node))
        r = self._structural(node) if h is None else h(node)
        if not r.exact:
            self.exact_all = False
        if r.parts is not None:
            # progress denominators: batch count is known whenever the
            # shapes are; rows only when every batch's logical count is
            # (a filter's post-predicate rows are not)
            batches = [b for p in r.parts for b in p]
            r.report.out_batches = len(batches)
            if all(b.rows is not None for b in batches):
                r.report.out_rows = sum(b.rows for b in batches)
        return r

    def _structural(self, node: C.CpuExec) -> _Result:
        """Layout/nullability-only report for shapes the analyzer does not
        bound statically (file scans, joins, windows)."""
        kids = [self.analyze(c) for c in node.children]
        for k in kids:
            self._finalize_chain(k)
        schema = node.output_schema
        layout = [
            ColState(f.name, f.dataType,
                     NON_NULL if not f.nullable else MAYBE_NULL)
            for f in schema.fields
        ]
        notes = []
        if isinstance(node, C.CpuJoinExec):
            layout = self._join_layout(node, kids)
            notes.append(
                f"{node.join_type} join: output shapes depend on match "
                "counts (not statically bounded)")
            self._note_join_strategy(node, kids, notes)
        self.exact_all = False
        return _Result(
            parts=None, layout=layout,
            report=OpReport(node.node_name, "", layout, None, {}, False,
                            notes, [k.report for k in kids]),
            exact=False)

    def _note_join_strategy(self, node: C.CpuJoinExec,
                            kids: List["_Result"],
                            notes: List[str]) -> None:
        """Forecast the join probe lowering by calling the RUNTIME's own
        chooser (exec/join.choose_join_strategy) over the statically
        known build capacity — the agg-strategy-note contract: a wrong
        forecast surfaces as a mismatch against the 'join_strategy'
        event, never as silent drift. AUTO with no static build shape
        (file scans, exchanges below the build side) must not guess."""
        from ..conf import JOIN_STRATEGY
        from ..exec.join import choose_join_strategy

        swap = node.join_type == "right"
        build_kid = kids[0] if swap else kids[1]
        build_keys = node._bl if swap else node._br
        jt = "left" if swap else node.join_type
        build_cap = None
        if build_kid.parts is not None:
            rows = sum(b.rows or 0 for p in build_kid.parts for b in p)
            build_cap = self._bucket(max(1, rows))
        if build_cap is None and self.conf.get(JOIN_STRATEGY) == "AUTO":
            notes.append(
                "join strategy: AUTO — resolved per build capacity at "
                "run time (build side not statically bounded); see the "
                "'join_strategy' event for the actual choice")
            return
        strat, reason = choose_join_strategy(
            self.conf, build_cap if build_cap is not None else 128,
            [k.dtype for k in build_keys], jt)
        notes.append(f"join strategy: {strat} — {reason}")

    def _join_layout(self, node: C.CpuJoinExec,
                     kids: List[_Result]) -> List[ColState]:
        """Join output nullability: an outer join reintroduces NULLs on
        the non-preserved side regardless of input nullability."""
        schema = node.output_schema
        nl = len(node.children[0].output_schema.fields)
        base: List[str] = []
        for side, kid in ((0, kids[0]), (1, kids[1])):
            states = [c.null for c in kid.layout]
            base.extend(states)
        out: List[ColState] = []
        how = node.join_type
        for i, f in enumerate(schema.fields):
            if i < len(base):
                s = base[i]
            else:
                s = MAYBE_NULL
            from_right = i >= nl
            if how == "full":
                s = MAYBE_NULL
            elif how == "left" and from_right:
                s = MAYBE_NULL
            elif how == "right" and not from_right:
                s = MAYBE_NULL
            out.append(ColState(f.name, f.dataType, s))
        return out

    # -- sources -----------------------------------------------------------
    def _scan(self, node: C.CpuScanExec) -> _Result:
        schema = node.output_schema
        base_null = schema_nullability(schema)
        parts: List[List[BatchState]] = []
        exact = True
        total_rows = sum(len(p) for p in node._partitions)
        inspect_bytes = total_rows <= 1_000_000
        for prt in node._partitions:
            n = len(prt)
            if n == 0:
                parts.append([])  # _convert_scan emits no batch
                continue
            cap = self._bucket(n)  # batch_from_rows capacity rule
            cols: List[ColState] = []
            for i, f in enumerate(schema.fields):
                cs = ColState(f.name, f.dataType, base_null[i])
                if cs.is_string:
                    if inspect_bytes:
                        total = 0
                        mx = 0
                        for row in prt:
                            v = row[i]
                            if v is None:
                                continue
                            b = v if isinstance(v, bytes) else str(v).encode(
                                "utf-8")
                            total += len(b)
                            mx = max(mx, len(b))
                        cs.char_cap = self._bucket(max(total, 1), 128)
                        cs.max_len = mx
                    else:
                        exact = False
                cols.append(cs)
            parts.append([BatchState(n, cap, cols)])
        out_bytes = self._total_bytes(parts)
        if out_bytes is not None:
            self.scan_resident += out_bytes  # batches live for the plan
        layout = self._merge_layout(parts, schema)
        nparts = len(node._partitions)
        return _Result(
            parts, layout,
            OpReport("InMemoryScanExec",
                     f"[{nparts} partition(s), rows={total_rows}]",
                     layout, out_bytes, {}, exact, [], []),
            exact)

    def _file_scan(self, node: C.CpuFileScanExec) -> _Result:
        """File scans stay structurally unbounded (row counts and string
        pools are data, not schema) — but their HBM FOOTPRINT is readable
        from the file footers alone, and round 6's forecast ignored it
        entirely (file-scan plans reported no peak at all, so the
        plan-time "will spill" warning could never fire for exactly the
        scans most likely to spill). Parquet footers give per-row-group
        row counts and chunk byte sizes, so the analyzer now charges:

          * decoded batches — every selected row group's capacity bucket
            x schema row width (+ string chunk pools at their
            uncompressed size) stays RESIDENT for the plan (the scan
            cache pins it, exactly like in-memory scan batches);
          * the pipelined reader's device window — TWO staged uploads in
            flight (double-buffered staging), each bounded by the largest
            row group's selected-chunk uncompressed bytes;
          * host staging — maxInFlight row groups of decoded payloads
            (reported in the notes; host memory is not HBM, so it rides
            outside the peak figure).
        """
        schema = node.output_schema
        layout = [
            ColState(f.name, f.dataType,
                     NON_NULL if not f.nullable else MAYBE_NULL)
            for f in schema.fields
        ]
        notes = ["file scan batch shapes come from file metadata"]
        if getattr(node, "fmt", None) == "parquet":
            try:
                self._model_parquet_scan(node, schema, notes)
            except Exception:  # missing files, exotic footers: stay quiet
                pass
        self.exact_all = False
        return _Result(
            parts=None, layout=layout,
            report=OpReport(node.node_name, "", layout, None, {}, False,
                            notes, []),
            exact=False)

    def _model_parquet_scan(self, node, schema: StructType,
                            notes: List[str]) -> None:
        from ..conf import PARQUET_PIPELINE_MAX_IN_FLIGHT

        fp = parquet_scan_footprint(node.scanner, schema)
        if fp is None:
            return
        for cap in fp["caps"]:
            self.max_cap = max(self.max_cap, cap)
        decoded, max_upload = fp["decoded"], fp["max_upload"]
        window = 2 * max_upload  # double-buffered staged transfers
        mif = self.conf.get(PARQUET_PIPELINE_MAX_IN_FLIGHT)
        self.scan_resident += decoded
        self._note_working(window)
        notes.append(
            f"pipelined device decode: {fp['nrg']} row group(s), decoded "
            f"batches ~{_pretty_bytes(decoded)} resident (scan cache), "
            f"double-buffered upload window <= {_pretty_bytes(window)} "
            f"device, host staging <= "
            f"{_pretty_bytes(mif * max_upload)} (maxInFlight={mif})")
        notes.append(
            "unpack layout bound: uploaded payloads "
            f"<= {_pretty_bytes(fp['upload_total'])} + decoded planes "
            f"{_pretty_bytes(decoded)} — the denominator of the parquet "
            "shape's byte_amplification (bench.py)")

    def _range(self, node: C.CpuRangeExec) -> _Result:
        schema = node.output_schema
        max_rows = self.conf.get(MAX_READER_BATCH_SIZE_ROWS)
        total = max(0, -(-(node.end - node.start) // node.step))
        slices = node.num_slices
        per = (total + slices - 1) // slices if total else 0
        parts: List[List[BatchState]] = []
        name = schema.fields[0].name
        for idx in range(slices):
            lo, hi = idx * per, min(total, (idx + 1) * per)
            batches: List[BatchState] = []
            pos = lo
            while pos < hi:
                n = min(max_rows, hi - pos)
                cap = self._bucket(n, self.conf.shape_bucket_min)
                batches.append(BatchState(
                    n, cap, [ColState(name, T.LONG, NON_NULL)]))
                pos += n
            parts.append(batches)
        out_bytes = self._total_bytes(parts)
        layout = self._merge_layout(parts, schema)
        return _Result(
            parts, layout,
            OpReport("TpuRangeExec", f"[rows={total}]", layout, out_bytes,
                     {}, True, [], []),
            True)

    # -- fusable row ops ---------------------------------------------------
    def _expr_col_state(self, bound: E.Expression, name: str,
                        in_cols: List[ColState], cap: int) -> ColState:
        dt = bound.dtype
        null = expr_nullability(
            bound, [c.null for c in in_cols])
        cs = ColState(name, dt, null)
        if not cs.is_string:
            return cs
        ref = bound
        while isinstance(ref, E.Alias):
            ref = ref.child
        if isinstance(ref, E.BoundReference) and ref.ordinal < len(in_cols):
            src = in_cols[ref.ordinal]
            cs.char_cap, cs.max_len = src.char_cap, src.max_len
        elif isinstance(ref, E.Literal):
            raw = (ref.value.encode("utf-8")
                   if isinstance(ref.value, str) else (ref.value or b""))
            cs.char_cap = max(cap * len(raw), 1)
            cs.max_len = len(raw)
        # other string-producing expressions: byte pool is kernel-specific
        # (char_cap stays None -> downstream shapes not bounded)
        return cs

    def _output_names(self, exprs, schema: StructType) -> List[str]:
        names = []
        for i, e in enumerate(exprs):
            if isinstance(e, (E.Alias, E.UnresolvedAttribute)):
                names.append(e.name)
            else:
                names.append(f"col{i}")
        return names

    def _project(self, node: C.CpuProjectExec) -> _Result:
        from .overrides import _has_string_hash

        kid = self.analyze(node.children[0])
        child_schema = node.children[0].output_schema
        fusable = not any(
            E.has_context_expr(e) or _has_string_hash(e, child_schema)
            for e in node.exprs
        )
        bound = [E.bind_references(e, child_schema) for e in node.exprs]
        names = self._output_names(node.exprs, child_schema)
        exact = kid.exact

        parts: Optional[List[List[BatchState]]] = None
        if kid.parts is not None:
            parts = []
            for p in kid.parts:
                nb = []
                for b in p:
                    cols = [
                        self._expr_col_state(be, nm, b.cols, b.cap)
                        for be, nm in zip(bound, names)
                    ]
                    nb.append(BatchState(b.rows, b.cap, cols))
                parts.append(nb)
        layout = self._merge_layout(parts, node.output_schema)
        report = OpReport("TpuProjectExec",
                          "" if fusable else "(context exprs)",
                          layout, self._total_bytes(parts), {}, exact,
                          [], [kid.report])
        self._note_working(self._total_bytes(kid.parts),
                           self._total_bytes(parts))
        if not fusable:
            # context projects run standalone: one 'project' compile per
            # distinct extended input signature. rand/id/partition-id
            # columns are cap-shaped (deterministic per input signature);
            # input_file_name and hash()-over-strings size their byte
            # pools from run-time values, so those stay unbounded.
            self._finalize_chain(kid)

            def _shape_dependent(e):
                if isinstance(e, (E.InputFileName, E.Murmur3Hash)):
                    return True
                return any(_shape_dependent(c) for c in e.children)

            sigs = self._sigs(kid.parts)
            if sigs is not None and not any(
                    _shape_dependent(b) for b in bound):
                report.sites["project"] = len(sigs)
            else:
                exact = False
                report.exact = False
            return _Result(parts, layout, report, exact)
        # fusable: extend (or start) the pending chain
        if kid.pending_chain is not None:
            source_sigs = kid.pending_chain[1]
            source = kid.chain_source
            kid.pending_chain = None
        else:
            source_sigs = self._sigs(kid.parts)
            source = kid
        return _Result(parts, layout, report, exact,
                       pending_chain=(report, source_sigs),
                       chain_source=source,
                       chain_len=kid.chain_len + 1)

    def _filter(self, node: C.CpuFilterExec) -> _Result:
        kid = self.analyze(node.children[0])
        child_schema = node.children[0].output_schema
        bound = E.bind_references(node.condition, child_schema)
        exact = kid.exact
        parts: Optional[List[List[BatchState]]] = None
        if kid.parts is not None:
            parts = []
            for p in kid.parts:
                nb = []
                for b in p:
                    states = narrow_by_predicate(
                        [c.null for c in b.cols], bound)
                    cols = [dataclasses.replace(c, null=s)
                            for c, s in zip(b.cols, states)]
                    nb.append(BatchState(None, b.cap, cols))  # rows unknown
                parts.append(nb)
        layout = self._merge_layout(parts, node.output_schema)
        report = OpReport("TpuFilterExec", "", layout,
                          self._total_bytes(parts), {}, exact, [],
                          [kid.report])
        self._note_working(self._total_bytes(kid.parts),
                           self._total_bytes(parts))
        if kid.pending_chain is not None:
            source_sigs = kid.pending_chain[1]
            source = kid.chain_source
            kid.pending_chain = None
        else:
            source_sigs = self._sigs(kid.parts)
            source = kid
        return _Result(parts, layout, report, exact,
                       pending_chain=(report, source_sigs),
                       chain_source=source,
                       chain_len=kid.chain_len + 1)

    # -- aggregate ---------------------------------------------------------
    def _aggregate(self, node: C.CpuHashAggregateExec) -> _Result:
        kid = self.analyze(node.children[0])
        child_schema = node.children[0].output_schema
        if node.children[0].num_partitions != 1:
            # partial -> exchange -> final (or mesh): shapes cross an
            # exchange whose batch sizes are data-dependent
            self._finalize_chain(kid)
            self._count_elision(child_schema)
            layout = self._agg_result_layout(node, kid, None)
            self.exact_all = False
            return _Result(
                None, layout,
                OpReport("TpuHashAggregateExec", "(partial+exchange+final)",
                         layout, None, {}, False,
                         ["multi-partition aggregate: exchange batch "
                          "shapes are data-dependent"], [kid.report]),
                False)

        from ..exec import aggregate as XA

        agg = XA.TpuHashAggregateExec(
            self.conf, node.group_exprs, node.agg_exprs,
            _SchemaOnlyExec(self.conf, child_schema), A.COMPLETE)

        report = OpReport("TpuHashAggregateExec", "", [], None, {},
                          kid.exact, [], [kid.report])

        # chain absorption mirrors execute_partition: fusable children fold
        # into the update program UNLESS a string min/max value needs an
        # exact byte bound measured on the aggregate's direct input
        string_minmax = any(
            op in ("min", "max") and e is not None
            and isinstance(e.dtype, (T.StringType, T.BinaryType))
            for op, e in zip(agg._update_ops, agg._update_exprs)
        )
        absorbed = kid.pending_chain is not None and not string_minmax
        if absorbed:
            source = kid.chain_source
            source_sigs = kid.pending_chain[1]
            kid.pending_chain = None
            in_parts = source.parts if source is not None else None
            in_sigs = source_sigs
            if source is not None:
                self._count_elision(StructType(tuple(
                    T.StructField(c.name, c.dtype, c.null != NON_NULL)
                    for c in source.layout)))
        else:
            self._finalize_chain(kid)
            in_parts = kid.parts
            in_sigs = self._sigs(kid.parts)
            self._count_elision(child_schema)  # per-batch update entries

        exact = kid.exact and in_sigs is not None
        if string_minmax:
            exact = False
            report.notes.append(
                "string min/max byte bounds are measured at run time")

        grouped = bool(node.group_exprs)
        string_buffers = any(
            isinstance(f.dataType, (T.StringType, T.BinaryType))
            for f in agg._buffer_schema.fields
        )
        sites: Dict[str, int] = {}
        in_batches = ([b for p in in_parts for b in p]
                      if in_parts is not None else None)
        nbatches = len(in_batches) if in_batches is not None else None
        can_fuse = (self.conf.get(AGG_FUSED_PLAN) != "OFF"
                    and agg._can_fuse_plan())
        cap_sum = (sum(max(1, b.cap) for b in in_batches)
                   if in_batches else 0)
        byte_sum = self._total_bytes(in_parts) or 0
        fused = (can_fuse and nbatches is not None and 0 < nbatches
                 and nbatches <= agg._FUSED_PLAN_MAX_BATCHES
                 and cap_sum <= agg._FUSED_PLAN_MAX_ROWS
                 and byte_sum <= agg._FUSED_PLAN_MAX_BYTES
                 and agg._fused_plan_on(nbatches))
        report.notes.append(
            "fusedPlan: " + ("ON (one agg_plan program)" if fused else
                             "per-batch updates"
                             + ("" if can_fuse else
                                " (string keys/buffers are ineligible)")))
        # stage fusion (scan→agg as one program) needs a device-decoded
        # file scan source; the statically-bounded paths are in-memory,
        # so the verified expectation here is always "no stage fusion" —
        # a wrong expectation would surface as an unforecast agg_stage
        # cache miss in the cross-check
        if agg._can_fuse_stage() and agg._stage_fusion_on():
            report.notes.append(
                "stageFusion: eligible but source is not a device-decoded "
                "file scan — not applied")
        if nbatches is None:
            exact = False
        elif nbatches == 0:
            if not grouped:
                # grand aggregate over empty input: one zero-row update
                # batch + the result projection
                sites["agg_update"] = 1
                sites["project"] = 1
        elif fused:
            sites["agg_plan"] = 1
            if nbatches > 1:
                # the in-trace padded merge concatenates partials; its
                # output capacity is modeled only for the 1-batch case
                exact = False
        else:
            if in_sigs is not None:
                # one update program per distinct input signature
                sites["agg_update"] = len(in_sigs)
            if nbatches > 1:
                # the merge re-aggregates a concatenated batch whose
                # capacity depends on runtime group counts
                exact = False
                report.notes.append(
                    "multi-batch merge shapes depend on group counts")
            else:
                sites["project"] = sites.get("project", 0) + 1  # _evaluate

        # output layout
        in_cols = (in_batches[0].cols if in_batches else
                   [ColState(f.name, f.dataType,
                             NON_NULL if not f.nullable else MAYBE_NULL)
                    for f in child_schema.fields])
        in_cap = in_batches[0].cap if in_batches else 128
        if node.group_exprs:
            # strategy forecast: call the RUNTIME's own chooser over the
            # statically-known capacity — the same "derive the decision
            # from the engine's own eligibility code" rule the fusion
            # notes follow, so a wrong forecast surfaces as a strategy
            # mismatch between this note and the 'agg_strategy' event.
            # AUTO's cost model is capacity-dependent, so with NO static
            # capacity (file scans, exchanges) the note must not guess
            # from the placeholder cap — that would manufacture exactly
            # the spurious mismatch the note exists to expose. A forced
            # conf value is capacity-independent and always forecastable.
            from ..conf import AGG_STRATEGY
            from ..exec.aggregate import choose_agg_strategy

            if in_batches or self.conf.get(AGG_STRATEGY) != "AUTO":
                cap_for_choice = (max(b.cap for b in in_batches)
                                  if in_batches else in_cap)
                strat, sreason = choose_agg_strategy(
                    self.conf, cap_for_choice, agg._update_ops,
                    agg._update_exprs, agg._key_dtypes())
                report.notes.append(f"agg strategy: {strat} — {sreason}")
            else:
                report.notes.append(
                    "agg strategy: AUTO — resolved per batch capacity at "
                    "run time (input shapes not statically bounded); see "
                    "the 'agg_strategy' event for the actual choice")
        layout = self._agg_result_layout(node, kid, in_cols)
        out_cap = in_cap if grouped else 1
        out_parts: Optional[List[List[BatchState]]] = None
        if exact:
            if nbatches == 0 and grouped:
                out_parts = [[]]
            else:
                out_cols = [
                    dataclasses.replace(cs, name=f.name)
                    for f, cs in zip(node.output_schema.fields, layout)
                ]
                if any(c.is_string and c.char_cap is None
                       for c in out_cols):
                    exact = False
                else:
                    out_parts = [[BatchState(
                        None if grouped else 1, out_cap, out_cols)]]
        report.layout = layout
        report.sites = sites
        report.exact = exact
        report.out_bytes = self._total_bytes(out_parts)
        report.detail = f"(mode=COMPLETE, keys={len(node.group_exprs)})"
        self._note_working(self._total_bytes(in_parts),
                           self._total_bytes(out_parts))
        return _Result(out_parts, layout, report, exact)

    def _agg_result_layout(self, node: C.CpuHashAggregateExec,
                           kid: _Result,
                           in_cols: Optional[List[ColState]]
                           ) -> List[ColState]:
        child_schema = node.children[0].output_schema
        if in_cols is None:
            in_cols = kid.layout
        states = [c.null for c in in_cols]
        grouped = bool(node.group_exprs)
        out: List[ColState] = []
        schema = node.output_schema
        i = 0
        for g in node.group_exprs:
            f = schema.fields[i]
            try:
                b = E.bind_references(g, child_schema)
                cs = self._expr_col_state(b, f.name, in_cols, 0)
                cs.null = expr_nullability(b, states)
            except (ValueError, KeyError):
                cs = ColState(f.name, f.dataType, MAYBE_NULL)
            out.append(cs)
            i += 1
        for ae in node.agg_exprs:
            f = schema.fields[i]
            func = ae.func
            in_state = MAYBE_NULL
            if func.input is not None:
                try:
                    bf = E.bind_references(func.child, child_schema)
                    in_state = expr_nullability(bf, states)
                except (ValueError, KeyError):
                    in_state = MAYBE_NULL
            out.append(ColState(
                f.name, f.dataType,
                agg_nullability(func, in_state, grouped)))
            i += 1
        return out

    # -- sort / limit / union / expand -------------------------------------
    def _sort(self, node: C.CpuSortExec) -> _Result:
        kid = self.analyze(node.children[0])
        self._finalize_chain(kid)
        schema = node.output_schema
        exact = kid.exact
        parts = None
        sites: Dict[str, int] = {}
        notes: List[str] = []
        if node.children[0].num_partitions != 1:
            exact = False
            notes.append("partitioned sort exchanges by range first")
        elif kid.parts is not None:
            batches = [b for p in kid.parts for b in p]
            if len(batches) == 1:
                b = batches[0]
                # string sort keys need the run-time max row length;
                # statically known only when the scan measured it
                ok = True
                try:
                    bound = [E.bind_references(e, schema)
                             for e in node.sort_exprs]
                except (ValueError, KeyError):
                    bound = []
                    ok = False
                for be in bound:
                    if isinstance(be.dtype, (T.StringType, T.BinaryType)):
                        if not (isinstance(be, E.BoundReference)
                                and b.cols[be.ordinal].max_len is not None):
                            ok = False
                if ok and b.sig() is not None:
                    sites["sort"] = 1
                    parts = [[BatchState(b.rows, b.cap, list(b.cols))]]
                else:
                    exact = False
            elif len(batches) == 0:
                parts = [[]]
            else:
                exact = False
                notes.append("multi-batch sort concatenates first")
        layout = self._merge_layout(parts, schema)
        report = OpReport("TpuSortExec", "", layout,
                          self._total_bytes(parts), sites, exact, notes,
                          [kid.report])
        self._note_working(self._total_bytes(kid.parts),
                           self._total_bytes(parts))
        return _Result(parts, layout, report, exact)

    def _limit(self, node) -> _Result:
        kid = self.analyze(node.children[0])
        self._finalize_chain(kid)
        limit = node.limit
        exact = kid.exact
        parts: Optional[List[List[BatchState]]] = None
        is_collect = isinstance(node, C.CpuCollectLimitExec)
        if kid.parts is not None:
            remaining = limit
            out_parts: List[List[BatchState]] = []
            flat = ([b for p in kid.parts for b in p]
                    if is_collect else None)
            groups = [flat] if is_collect else kid.parts
            for p in groups:
                remaining_p = remaining if is_collect else limit
                nb: List[BatchState] = []
                for b in p:
                    if remaining_p <= 0:
                        break
                    if b.rows is None:
                        exact = False
                        break
                    if b.rows <= remaining_p:
                        nb.append(b)
                        remaining_p -= b.rows
                    else:
                        cap = self._bucket(
                            remaining_p, self.conf.shape_bucket_min)
                        nb.append(BatchState(remaining_p, cap,
                                             list(b.cols)))
                        remaining_p = 0
                out_parts.append(nb)
                if is_collect:
                    remaining = remaining_p
            if exact:
                parts = out_parts
        name = ("TpuCollectLimitExec" if is_collect else "TpuLocalLimitExec")
        layout = self._merge_layout(parts, node.output_schema)
        report = OpReport(name, f"[limit={limit}]", layout,
                          self._total_bytes(parts), {}, exact, [],
                          [kid.report])
        return _Result(parts, layout, report, exact)

    def _union(self, node: C.CpuUnionExec) -> _Result:
        kids = [self.analyze(c) for c in node.children]
        for k in kids:
            self._finalize_chain(k)
        exact = all(k.exact for k in kids)
        parts: Optional[List[List[BatchState]]] = []
        for k in kids:
            if k.parts is None:
                parts = None
                exact = False
                break
            parts.extend(k.parts)
        layout = self._merge_layout(parts, node.output_schema)
        report = OpReport("TpuUnionExec", "", layout,
                          self._total_bytes(parts), {}, exact, [],
                          [k.report for k in kids])
        return _Result(parts, layout, report, exact)

    def _expand(self, node: C.CpuExpandExec) -> _Result:
        kid = self.analyze(node.children[0])
        self._finalize_chain(kid)
        child_schema = node.children[0].output_schema
        nproj = len(node.projections)
        exact = kid.exact
        sites: Dict[str, int] = {}
        parts: Optional[List[List[BatchState]]] = None
        names = [f.name for f in node.output_schema.fields]
        try:
            bounds = [
                [E.bind_references(e, child_schema) for e in p]
                for p in node.projections
            ]
        except (ValueError, KeyError):
            bounds = None
            exact = False
        if kid.parts is not None and bounds is not None:
            sigs = self._sigs(kid.parts)
            if sigs is not None:
                sites["project"] = nproj * len(sigs)
            else:
                exact = False
            parts = []
            for p in kid.parts:
                nb = []
                for b in p:
                    for pb in bounds:
                        cols = [
                            self._expr_col_state(be, nm, b.cols, b.cap)
                            for be, nm in zip(pb, names)
                        ]
                        nb.append(BatchState(b.rows, b.cap, cols))
                parts.append(nb)
            self._count_elision(child_schema)
        else:
            exact = False
        layout = self._merge_layout(parts, node.output_schema)
        report = OpReport("TpuExpandExec", f"[{nproj} projections]", layout,
                          self._total_bytes(parts), sites, exact, [],
                          [kid.report])
        return _Result(parts, layout, report, exact)


class _SchemaOnlyExec:
    """Planning stand-in handed to runtime exec constructors so the
    analyzer resolves buffer schemas and fusion eligibility through the
    EXACT code paths the execution engine uses (nothing is executed —
    constructors only bind expressions)."""

    fusable = False

    def __init__(self, conf: RapidsConf, schema: StructType):
        self.conf = conf
        self._schema = schema
        self.children: List = []
        self.metrics: Dict = {}

    @property
    def output_schema(self) -> StructType:
        return self._schema

    @property
    def num_partitions(self) -> int:
        return 1


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------
def analyze_plan(cpu_plan: C.CpuExec, conf: RapidsConf,
                 meta=None) -> PlanAnalysis:
    """Analyze a bound CPU physical plan WITHOUT lowering or executing
    anything: tag it (typechecks fallbacks make the plan unbounded), then
    derive layouts, nullability, footprint, and the compile-signature
    forecast. ``meta``: an already-tagged PlanMeta for this plan, when the
    caller ran the tagging pass itself (explain) — saves a second full
    matrix walk."""
    if meta is None:
        from .overrides import PlanMeta

        meta = PlanMeta(cpu_plan, conf)
        meta.tag_for_tpu()
    fallbacks = meta.fallback_nodes()

    an = _Analyzer(conf)
    root = an.analyze(cpu_plan)
    an._finalize_chain(root)

    bounded = an.exact_all and root.exact and not fallbacks
    warnings: List[str] = []
    if fallbacks:
        warnings.append(
            "plan has CPU fallbacks (%s): analysis is structural only"
            % ", ".join(sorted(set(fallbacks))))

        def clear_sites(r: OpReport):
            # fallen-back subtrees never reach the TPU pipeline caches;
            # rendering their would-be compile counts would be fiction
            r.sites = {}
            for c in r.children:
                clear_sites(c)

        clear_sites(root.report)

    # aggregate per-site and per-exec-name forecasts over the report tree
    site_forecast: Dict[str, int] = {}
    bytes_by_op: Dict[str, int] = {}
    rows_by_op: Dict[str, int] = {}
    batches_by_op: Dict[str, int] = {}

    def walk(r: OpReport):
        for k, v in r.sites.items():
            site_forecast[k] = site_forecast.get(k, 0) + v
        if r.out_bytes is not None:
            bytes_by_op[r.name] = bytes_by_op.get(r.name, 0) + r.out_bytes
        if r.out_rows is not None:
            rows_by_op[r.name] = rows_by_op.get(r.name, 0) + r.out_rows
        if r.out_batches is not None:
            batches_by_op[r.name] = (
                batches_by_op.get(r.name, 0) + r.out_batches)
        for c in r.children:
            walk(c)

    walk(root.report)

    threshold = conf.get(ANALYSIS_STORM_THRESHOLD)
    if bounded:
        for site, count in sorted(site_forecast.items()):
            if count >= threshold:
                warnings.append(
                    f"recompile storm: site {site} expects {count} distinct "
                    f"compile signatures (threshold {threshold}) — the plan "
                    "is shape-polymorphic; align batch capacities or raise "
                    "spark.rapids.tpu.sql.analysis.recompileStorm.threshold")

    peak = None
    if an.scan_resident or an.max_working:
        peak = an.scan_resident + an.max_working
    from ..memory.catalog import derive_hbm_budget

    budget = derive_hbm_budget(conf)
    if peak is not None and budget is not None and peak > budget:
        # name the LARGEST capacity in the plan — that is what the peak
        # is made of, not the root's (often tiny) output batch
        cap = an.max_cap
        warnings.append(
            f"predicted peak HBM {_pretty_bytes(peak)} exceeds the "
            f"device budget {_pretty_bytes(budget)} — this plan will "
            f"spill/OOM at capacity {cap}; reduce batch sizes "
            "(sql.reader.batchSizeRows) or raise the budget")

    return PlanAnalysis(
        root=root.report,
        bounded=bounded,
        site_forecast=site_forecast if bounded else {},
        bytes_by_op=bytes_by_op,
        peak_hbm=peak,
        budget=budget,
        warnings=warnings,
        elided_columns=an.elided,
        rows_by_op=rows_by_op,
        batches_by_op=batches_by_op,
    )


def analysis_enabled(conf: RapidsConf) -> bool:
    return conf.get(ANALYSIS_ENABLED)


def parquet_scan_footprint(scanner, schema: StructType) -> Optional[dict]:
    """Footer-derived layout bound of a parquet scan's device-decode
    (unpack) site, shared by the analyzer's ``_model_parquet_scan`` and
    :func:`predict_exec_hbm` (one implementation, so the explain() note
    and the bench denominator can never drift):

      * ``decoded``      — every selected row group's capacity bucket x
        schema row width (+ string chunk pools at uncompressed size),
        the planes the unpack programs must WRITE (and the scan cache
        pins resident);
      * ``upload_total`` — the encoded payloads the unpack programs must
        READ (sum of selected chunks' uncompressed bytes);
      * ``max_upload``/``nrg``/``caps`` — the pipelined reader's
        double-buffer sizing inputs.

    Returns None when the footers are unreadable (missing files, exotic
    formats) — consumers degrade to "no bound" rather than fake one; a
    genuine programming error still raises (the analyzer's call site
    keeps its own never-fail-a-query blanket, bench's does not)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from ..utils.bucketing import bucket_rows

    try:
        file_cols = set(getattr(scanner, "columns", ()) or ())
        pcols = set(getattr(scanner, "partition_cols", ()) or ())
        wanted = file_cols - pcols
        fixed_row = 0
        has_strings = False
        for f in schema.fields:
            if f.name in pcols or (wanted and f.name not in wanted):
                continue
            if isinstance(f.dataType, (T.StringType, T.BinaryType)):
                fixed_row += 5  # offsets+validity; chars pool added below
                has_strings = True
            else:
                fixed_row += _storage_bytes(f.dataType) + 1
        decoded = 0
        upload_total = 0
        max_upload = 0
        nrg = 0
        caps: List[int] = []
        pfs: Dict[str, object] = {}
        for s in scanner.splits():
            pf = pfs.get(s.path)
            if pf is None:
                pf = pfs[s.path] = pq.ParquetFile(s.path)
            md = pf.metadata
            for rg in s.row_groups:
                rgmd = md.row_group(rg)
                nrg += 1
                upload = 0
                chars = 0
                for ci in range(rgmd.num_columns):
                    col = rgmd.column(ci)
                    if wanted and col.path_in_schema not in wanted:
                        continue
                    upload += int(col.total_uncompressed_size)
                    if has_strings and col.physical_type == "BYTE_ARRAY":
                        chars += int(col.total_uncompressed_size)
                cap = bucket_rows(max(1, rgmd.num_rows))
                caps.append(cap)
                decoded += cap * fixed_row + chars
                upload_total += upload
                max_upload = max(max_upload, upload)
    except (OSError, ValueError, KeyError, pa.lib.ArrowException):
        return None  # missing files, exotic footers: no bound
    if not nrg:
        return None
    return {"decoded": decoded, "upload_total": upload_total,
            "max_upload": max_upload, "nrg": nrg, "caps": caps}


def predict_exec_hbm(exec_) -> Optional[int]:
    """Forecast the HBM bytes a LIVE TpuExec tree will touch: resident
    source batches plus each operator's output-layout bound. Used by
    bench.py to emit predicted_hbm_bytes next to the measured roofline
    (BENCH tracks forecast accuracy across rounds).

    Parquet file scans bound through :func:`parquet_scan_footprint`
    (uploaded payloads + decoded planes — the unpack site's layout
    bound), so the parquet shape's byte_amplification is no longer null
    and the --diff amplification-growth gate actually binds there."""
    from ..exec.base import TpuExec, batch_bytes
    from ..exec.scan import TpuFileSourceScanExec

    if not isinstance(exec_, TpuExec):
        return None
    total = 0

    def walk(node) -> bool:
        nonlocal total
        parts = getattr(node, "_partitions", None)
        if parts is not None:  # in-memory source: batches are resident
            for p in parts:
                for b in p:
                    total += batch_bytes(b)
            return True
        if isinstance(node, TpuFileSourceScanExec):
            if getattr(node, "fmt", None) != "parquet":
                return False
            fp = parquet_scan_footprint(node.scanner, node.output_schema)
            if fp is None:
                return False
            total += fp["upload_total"] + fp["decoded"]
            return True
        ok = True
        for c in node.children:
            ok = walk(c) and ok
        # each operator streams roughly its input once more as output;
        # without static layouts here, reuse the child bound
        return ok

    ok = walk(exec_)
    return total * 2 if ok and total else None


# ---------------------------------------------------------------------------
# Per-shard mesh forecasts (round 6): what a mesh SPMD stage will stage
# and compile, per shard, BEFORE it runs — derived by calling the runtime
# exec's OWN sizing helpers (exec/mesh.forecast_mesh_staging wraps
# io/mesh_stage.mesh_shard_cap / shard_plane_bytes, the exact code the
# staging paths execute), so forecast and actual share one implementation
# and the cross-check below can demand EQUALITY, not just bounds.
# ---------------------------------------------------------------------------
def _mesh_stages_of(exec_) -> List:
    """Mesh stages in a live plan, traversing both TpuExec ``children``
    and the row-boundary ``tpu_child`` link (session roots are
    ColumnarToRowExec)."""
    from ..exec.mesh import _MeshStage

    stages: List = []

    def walk(node) -> None:
        if isinstance(node, _MeshStage):
            stages.append(node)
        tc = getattr(node, "tpu_child", None)
        if tc is not None:
            walk(tc)
        for c in getattr(node, "children", ()):
            walk(c)

    walk(exec_)
    return stages


def forecast_mesh(exec_) -> Optional[dict]:
    """Per-shard forecast for every mesh SPMD stage in a LIVE TpuExec
    tree: staging layout (common per-shard capacity, per-shard rows after
    the round-robin placement, staged plane bytes), the compile site and
    an upper bound on programs (1 + capacity-overflow retries), and a
    static per-shard HBM lower bound (staged planes + output surface).
    None when the plan has no mesh stages. Sources whose row counts are
    not statically known (csv scans) yield ``staging: None`` — reported,
    not cross-checked."""
    stages = _mesh_stages_of(exec_)
    if not stages:
        return None
    out = []
    for st in stages:
        entry: Dict[str, Any] = {
            "op": st.node_name,
            "site": st.mesh_site,
            "n_shards": st.n_shards,
        }
        caps = []
        if len(st.children) == 1:
            s = st.forecast_mesh_staging(st.children[0])
            entry["staging"] = s
            if s:
                caps.append(s["cap"])
        else:
            for which, child in zip(("left", "right"), st.children):
                s = st.forecast_mesh_staging(child)
                entry[f"staging_{which}"] = s
                if s:
                    caps.append(s["cap"])
        entry["programs_bound"] = (
            st.mesh_program_bound(max(caps)) if caps else None)
        # static per-shard HBM lower bound: the staged input planes must
        # be resident while the program runs; outputs add one more
        # surface of the same shape (XLA temporaries are the compiler's
        # business and not bounded here)
        staged = [
            v for k, v in entry.items()
            if k.startswith("staging") and v and v.get("staged_bytes")
        ]
        if staged:
            entry["peak_hbm_per_shard_lower"] = sum(
                s["staged_bytes"][0] for s in staged) * 2
        out.append(entry)
    return {"n_stages": len(out), "stages": out}


def cross_check_mesh(exec_) -> List[str]:
    """Diff every mesh stage's recorded actuals (exec/mesh
    ``mesh_actuals``: staging cap/rows/bytes/source, compiled program
    count) against :func:`forecast_mesh`. Returns violation strings —
    empty means the per-shard forecast held exactly. Staging entries the
    forecast could not bound (``staging: None``) are skipped; a stage
    that never materialized has no actuals and is skipped too."""
    fc = forecast_mesh(exec_)
    if fc is None:
        return []
    stages = _mesh_stages_of(exec_)
    bad: List[str] = []
    for st, entry in zip(stages, fc["stages"]):
        actual = st.mesh_actuals
        if not actual:
            continue
        pairs = []
        if "staging" in entry:
            pairs.append((entry["staging"], actual.get("staging"), ""))
        else:
            pairs.append((entry.get("staging_left"),
                          actual.get("staging_left"), "left"))
            pairs.append((entry.get("staging_right"),
                          actual.get("staging_right"), "right"))
        name = entry["op"]
        for fcast, act, which in pairs:
            if fcast is None or act is None:
                continue
            tag = f"{name}{('.' + which) if which else ''}"
            if fcast["cap"] != act["cap"]:
                bad.append(f"{tag}: staged cap {act['cap']} != "
                           f"forecast {fcast['cap']}")
            if list(fcast["per_shard_rows"]) != list(act["per_shard_rows"]):
                bad.append(f"{tag}: per-shard rows {act['per_shard_rows']}"
                           f" != forecast {fcast['per_shard_rows']}")
            if fcast.get("staged_bytes") is not None and \
                    list(fcast["staged_bytes"]) != list(act["staged_bytes"]):
                bad.append(f"{tag}: staged bytes {act['staged_bytes']} != "
                           f"forecast {fcast['staged_bytes']}")
            if fcast["source"] != act.get("source"):
                bad.append(f"{tag}: staging source {act.get('source')} != "
                           f"forecast {fcast['source']}")
        bound = entry.get("programs_bound")
        progs = actual.get("programs", 0)
        if bound is not None and progs > bound:
            bad.append(f"{name}: {progs} compiled program(s) > "
                       f"forecast bound {bound}")
    return bad
