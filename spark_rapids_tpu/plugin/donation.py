"""Buffer-donation safety: the declared certification table, the
batch-exclusivity protocol, and the runtime witness.

Donation (``jax.jit(..., donate_argnums=...)``) lets XLA reuse an input
plane's HBM for the program's outputs and temps — the single biggest
peak-temp lever the engine has — but it is UNSOUND unless the caller
provably drops every reference to the donated plane after dispatch. The
reference plugin inherits that proof from RMM's ownership discipline
(cuDF buffers are moved, not aliased); this engine builds it in three
layers:

1. **The certification table** (``DONATION_SPECS``, below): for every
   compile site the engine owns, either the argnums proven dead after
   dispatch plus how the site squares with split-and-retry, or the
   reason donation is forbidden. ``tools/tpu_donate.py`` cross-checks
   this table against the AST of the builders and their call sites
   (TPU201: a certified argnum the caller later reads; TPU202: a
   certified site not donating; TPU203: donation invisible to
   ``cached_pipeline``'s key), the same declared-manifest pattern as
   ``tools/tpu_racecheck.py`` over ``utils/locks.LOCK_ORDER``.

2. **The exclusivity protocol** (``mark_exclusive`` / ``claim``): the
   static pass proves the *site* safe; whether a particular batch's
   planes are unshared is a runtime fact. Only batches explicitly
   marked exclusive by their producer (fresh host→device uploads,
   fused-chain outputs, join outputs) ever donate, and any consumer
   that RETAINS a batch beyond its own dispatch (scan cache, exchange
   buffering, concat) must ``claim()`` it first, clearing the mark.
   Dictionary-encoded columns never donate — their dictionary pools
   are shared across every batch of the column.

3. **The retry contract** (``guard``): ``memory/retry.py``'s
   split-and-retry re-dispatches the *input* batch, so a donating
   dispatch under ``with_oom_retry`` must snapshot donated planes to
   host first and restore them on failure
   (``donation.retrySnapshot.enabled``), or simply not donate retried
   args when snapshots are disabled. The conf-gated witness
   (``tools.donation.witness.enabled``) asserts post-dispatch that
   donated buffers really were deleted and converts any
   use-after-donation error into a typed, op-attributed
   ``TpuDonationViolation``.

This module is importable without jax (the tool layer runs on bare
CPython); jax is imported lazily inside the functions that dispatch.
"""
from __future__ import annotations

import os
import threading
import warnings
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import conf as _conf
from .. import events as _events
from .. import obs as _obs

# XLA legitimately declines individual aliases (a bool validity plane
# rarely matches any output buffer); the guard accounts the decline
# truthfully in the donated-bytes counters, so the per-compile warning
# is noise the engine already measures
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")

__all__ = [
    "DonationSpec", "DONATION_SPECS", "certified_sites",
    "TpuDonationViolation", "mark_exclusive", "is_exclusive", "claim",
    "batch_donatable", "dispatch_mask", "guard", "snapshot_counters",
    "counters_since", "witness_enabled", "enabled",
]


class DonationSpec:
    """One compile site's donation certification (or refusal).

    ``argnums`` — jitted-function argument indices proven dead after
    dispatch (empty tuple: site not certified). ``retry`` — how the
    site reconciles donation with split-and-retry: ``"snapshot"``
    (planes snapshotted to host before dispatch, restored on failure)
    or ``None`` for uncertified sites. ``reason`` — the safety
    argument, quoted verbatim by the tool's ``--explain`` output."""

    __slots__ = ("site", "argnums", "retry", "reason")

    def __init__(self, site: str, argnums: Tuple[int, ...],
                 retry: Optional[str], reason: str):
        self.site = site
        self.argnums = argnums
        self.retry = retry
        self.reason = reason

    @property
    def certified(self) -> bool:
        return bool(self.argnums)


# The engine-wide lifetime analysis, one verdict per compile site. The
# argnum refers to the jitted builder's parameter position (argnum 0 is
# the per-batch column-plane pytree at every certified site). Sites
# listed with argnums=() are PROVEN UNSAFE (or not worth it) for the
# stated reason; tools/tpu_donate.py TPU202 only fires on certified
# sites, and TPU201 validates the certified ones against the callers.
DONATION_SPECS: Dict[str, DonationSpec] = {s.site: s for s in [
    DonationSpec(
        "fused_chain", (0,), "snapshot",
        "run_fused_chain's attempt reads vals_of_batch(b) exactly once "
        "(the dispatch); the output batch is rebuilt from the program's "
        "return via batch_from_vals, and the input batch object is "
        "dropped when the retry scope exits. Split-and-retry re-reads "
        "the input planes, hence snapshot mode."),
    DonationSpec(
        "project", (0,), "snapshot",
        "Same per-batch shape as fused_chain: the standalone projection "
        "pipeline reads the input planes once at dispatch and rebuilds "
        "the output batch from the return value."),
    DonationSpec(
        "agg_update", (0,), "snapshot",
        "The streaming per-batch partial-aggregate update reads the "
        "probe batch's planes once; partial state lives in the "
        "program's RETURN, never in the input planes. Dispatched under "
        "with_oom_retry, hence snapshot mode."),
    DonationSpec(
        "agg_plan", (0,), "snapshot",
        "The fused whole-partition plan takes every buffered batch's "
        "planes as argnum 0 and reduces them to partials in one "
        "program; the device-OOM fallback (flush_buffered) re-reads "
        "the buffered batches, hence snapshot mode."),
    DonationSpec(
        "agg_stage", (), None,
        "Stage programs run inside the fused-plan fallback ladder and "
        "their inputs are the retained `batches` buffer the ladder may "
        "re-read at ANY later rung — no single dispatch is the last "
        "use, so no argnum is provably dead."),
    DonationSpec(
        "agg_merge", (), None,
        "with_oom_retry_nosplit re-dispatches the SAME partials list on "
        "retry, and merge partials feed multiple merge rounds — the "
        "caller provably retains every input."),
    DonationSpec(
        "join", (0,), "snapshot",
        "Only the probe-side expand program donates: expand_phase's "
        "argnum 0 (the probe plane pytree) is the LAST read of the "
        "probe batch — count_phase reads the same planes FIRST, so the "
        "count dispatch must not donate, and build-side planes (argnum "
        "1) are retained across every probe batch and must never "
        "donate. Probe dispatch runs under with_oom_retry, hence "
        "snapshot mode. String/dict probes use eager gathers and do "
        "not qualify."),
    DonationSpec(
        "sort", (), None,
        "Sort buffers every input batch until partition end and the "
        "gather program reads the buffered planes after the key "
        "program already read them — multi-dispatch liveness, no dead "
        "argnum."),
    DonationSpec(
        "window", (), None,
        "Window frames re-read the partition's planes once per "
        "function; the partition buffer outlives each dispatch."),
    DonationSpec(
        "exchange", (), None,
        "Exchange retains batches in partition buffers across the "
        "shuffle boundary (and may serve them to a remote reader "
        "twice under retry) — retention is the operator's purpose."),
    DonationSpec(
        "pq_unpack", (), None,
        "The streamed parquet unpack dispatches over mmap-backed scan "
        "planes owned by the scan cache; residency is the point of "
        "the cache, so the caller never drops its reference."),
]}


def certified_sites() -> Tuple[str, ...]:
    return tuple(s.site for s in DONATION_SPECS.values() if s.certified)


class TpuDonationViolation(RuntimeError):
    """A donated buffer was observed live after dispatch, or a deleted
    (donated) buffer was used afterwards — the static certification and
    runtime reality disagree. Carries the site/op attribution the
    offline log needs; raised only under the donation witness."""

    def __init__(self, site: str, op: Optional[str], detail: str):
        self.site = site
        self.op = op
        super().__init__(
            f"donation violation at site={site!r}"
            + (f" op={op!r}" if op else "") + f": {detail}")


# ---------------------------------------------------------------------------
# Exclusivity protocol
# ---------------------------------------------------------------------------
def mark_exclusive(batch):
    """Producer-side: declare this batch's planes referenced by nobody
    but the consumer it is being yielded to. Only four producers
    qualify (fresh host→device scan uploads, fused-chain outputs, join
    outputs, split-and-retry halves); marking anything else is a
    soundness bug the witness will catch. Returns the batch for
    chaining."""
    try:
        batch.exclusive = True
    except AttributeError:
        pass  # host-side / foreign batch types don't carry the flag
    return batch


def is_exclusive(batch) -> bool:
    return bool(getattr(batch, "exclusive", False))


def claim(batch):
    """Consumer-side: take shared ownership of a batch this operator
    RETAINS beyond its own dispatch (scan-cache insert, exchange
    buffering, concat inputs, spill). Clears the exclusivity mark so
    no later dispatch donates planes this retainer still holds.
    Returns the batch for chaining."""
    if getattr(batch, "exclusive", False):
        batch.exclusive = False
    return batch


def _has_dict_columns(batch) -> bool:
    for c in getattr(batch, "columns", ()):
        if getattr(c, "is_dict", False):
            return True
    return False


def batch_donatable(batch) -> bool:
    """A batch's planes may donate iff its producer marked it exclusive
    and no column is dictionary-encoded (dictionary pools are shared
    across every batch of the column — never donatable)."""
    return is_exclusive(batch) and not _has_dict_columns(batch)


def _get(conf, entry):
    """Session-scoped conf read with a no-session fallback to the
    entry's default (the engine's standard RapidsConf.get pattern —
    every exec call site passes its own conf handle)."""
    return entry.default if conf is None else conf.get(entry)


def enabled(conf=None) -> bool:
    return bool(_get(conf, _conf.DONATION_ENABLED))


def snapshot_mode(conf=None) -> bool:
    return bool(_get(conf, _conf.DONATION_RETRY_SNAPSHOT))


_WITNESS_ENV = os.environ.get("SRTPU_DONATION_WITNESS", "") == "1"
_WITNESS_SESSION = False


def install_witness() -> None:
    """Turn the runtime donation witness on (process-global, idempotent;
    wired from TpuSession under tools.donation.witness.enabled and the
    SRTPU_DONATION_WITNESS=1 environment hook, the locks.py pattern)."""
    global _WITNESS_SESSION
    _WITNESS_SESSION = True


def uninstall_witness() -> None:
    global _WITNESS_SESSION
    _WITNESS_SESSION = False


def witness_enabled() -> bool:
    return _WITNESS_ENV or _WITNESS_SESSION


def dispatch_mask(site: str, batches, conf=None) -> Tuple[int, ...]:
    """The donate_argnums for ONE dispatch at ``site`` over ``batches``
    (a batch or a sequence of batches bound to the certified argnum).
    Empty tuple unless donation is on, the site is certified, and
    EVERY batch bound to the donated argnum is provably unshared
    (exclusive, dict-free). Deterministic given batch provenance, so
    masks never fork the compile cache between identical runs."""
    if not enabled(conf):
        return ()
    spec = DONATION_SPECS.get(site)
    if spec is None or not spec.certified:
        return ()
    if spec.retry == "snapshot" and not snapshot_mode(conf):
        # exclusion mode: the site dispatches under split-and-retry and
        # snapshots are off, so retried args must not donate
        return ()
    if not isinstance(batches, (list, tuple)):
        batches = (batches,)
    if not batches:
        return ()
    for b in batches:
        if not batch_donatable(b):
            return ()
    return spec.argnums


# ---------------------------------------------------------------------------
# Donated-bytes accounting (events / obs / bench counters)
# ---------------------------------------------------------------------------
_COUNTER_LOCK = threading.Lock()
_DONATED_BYTES: Dict[str, int] = {}
_DONATED_PLANES: Dict[str, int] = {}


def _note_donation(site: str, op: Optional[str], nbytes: int,
                   planes: int) -> None:
    with _COUNTER_LOCK:
        _DONATED_BYTES[site] = _DONATED_BYTES.get(site, 0) + nbytes
        _DONATED_PLANES[site] = _DONATED_PLANES.get(site, 0) + planes
    if _events.enabled():
        _events.emit("donation", site=site, op=op or "", bytes=nbytes,
                     planes=planes)
    if _obs.enabled():
        _obs.inc("tpu_donated_bytes", nbytes, site=site)


def snapshot_counters() -> Dict[str, int]:
    """Cumulative donated bytes per site (bench snapshots/diffs this
    around each shape, the xla_cost.snapshot()/records_since pattern)."""
    with _COUNTER_LOCK:
        return dict(_DONATED_BYTES)


def counters_since(snap: Dict[str, int]) -> Dict[str, int]:
    with _COUNTER_LOCK:
        return {k: v - snap.get(k, 0)
                for k, v in _DONATED_BYTES.items() if v - snap.get(k, 0)}


def reset_counters() -> None:
    with _COUNTER_LOCK:
        _DONATED_BYTES.clear()
        _DONATED_PLANES.clear()


# ---------------------------------------------------------------------------
# The dispatch guard
# ---------------------------------------------------------------------------
def _plane_arrays(batch) -> List[Tuple[Any, str, Any]]:
    """(column, slot, array) for every donatable device plane of a
    batch — the restore handle set. String offsets/chars planes are
    included (a donating program's argnum-0 pytree donates EVERY leaf);
    dict planes never appear (dict batches are not donatable)."""
    out = []
    for c in getattr(batch, "columns", ()):
        for slot in ("data", "validity", "offsets", "chars"):
            a = getattr(c, slot, None)
            if a is not None and hasattr(a, "nbytes"):
                out.append((c, slot, a))
    return out


def _use_after_donation(exc: BaseException) -> bool:
    return "deleted" in str(exc).lower() and "rray" in str(exc)


def _snapshot_planes(arrays) -> List[Any]:
    """True host COPIES of device planes for the guard's restore leg.

    This deliberately does NOT route through the sanctioned
    ``host_pull`` (``jax.device_get``): on the CPU backend device_get
    returns a zero-copy VIEW of the device buffer and pins it with an
    external reference, after which XLA silently refuses to delete the
    donated buffer — the snapshot leg would defeat the exact donation
    it exists to protect. ``np.array(a, copy=True)`` reads the same
    bytes without retaining a view, so the buffer stays deletable. The
    d2h still lands in the transfer accounting like any host_pull."""
    import numpy as np
    out = [np.array(a, copy=True) for a in arrays]
    if _events.enabled() or _obs.enabled():
        nb = sum(int(a.nbytes) for a in out)
        _events.emit("transfer", direction="d2h", bytes=nb,
                     site="donation_snapshot")
        if _obs.enabled():
            _obs.inc("tpu_transfers", 1, direction="d2h")
            _obs.inc("tpu_transfer_bytes", nb, direction="d2h")
    return out


@contextmanager
def guard(site: str, batches, op: Optional[str] = None,
          snapshot: Optional[bool] = None, conf=None, metric=None):
    """Wrap ONE donating dispatch at a retry-covered site.

    Entry: snapshots every donated plane to host as TRUE COPIES
    (``_snapshot_planes`` — device_get's zero-copy view would pin the
    buffer and silently block the donation; the d2h still shows up in
    the transfer accounting like any other pull). Exit on success: bumps the
    donated-bytes counters — and ``metric``, an exec-owned Metric when
    the call site has one, so explain_metrics() attributes donation per
    operator — and, under the witness, asserts jax really
    deleted the donated buffers. Exit on failure: restores the planes
    into the batch's (mutable) DeviceColumn slots so split-and-retry /
    the agg fallback ladder can re-read the input it is contractually
    owed, then re-raises — translating any use-after-donation error
    into a typed TpuDonationViolation first."""
    if not isinstance(batches, (list, tuple)):
        batches = (batches,)
    handles = [h for b in batches for h in _plane_arrays(b)]
    nbytes = sum(int(h[2].nbytes) for h in handles)
    snaps = None
    want_snapshot = (snapshot if snapshot is not None
                     else snapshot_mode(conf))
    if want_snapshot:
        snaps = _snapshot_planes([h[2] for h in handles])
    try:
        yield
    except Exception as e:
        if snaps is not None:
            import jax.numpy as jnp
            for (c, slot, _), host in zip(handles, snaps):
                setattr(c, slot, jnp.asarray(host))
        if witness_enabled() and _use_after_donation(e):
            raise TpuDonationViolation(site, op, str(e)) from e
        raise
    # count only planes XLA actually deleted: the backend may DECLINE an
    # individual alias (shape/dtype matches no output — typical for bool
    # validity planes), in which case the input stays live and donated no
    # bytes. Declined aliases are a missed optimization, not a soundness
    # bug; the violation is a mask that had NO effect at all (the argnum
    # named a parameter the program never received as a buffer).
    deleted_bytes = 0
    deleted_planes = 0
    for _, slot, a in handles:
        is_del = getattr(a, "is_deleted", None)
        if is_del is not None and is_del():
            deleted_bytes += int(a.nbytes)
            deleted_planes += 1
    _note_donation(site, op, deleted_bytes, deleted_planes)
    if metric is not None:
        metric.add(deleted_bytes)
    if witness_enabled() and handles and deleted_planes == 0:
        raise TpuDonationViolation(
            site, op,
            f"no donated plane was deleted after dispatch ({nbytes} "
            f"bytes across {len(handles)} planes still live) — the "
            "donate mask named an argnum the program does not alias")
